#!/usr/bin/env bash
# One-command real-cluster smoke test for the dlrover-tpu operator:
# applies deploy/ to whatever cluster kubectl currently points at,
# submits the golden ElasticJob, and waits for its phase to reach
# Running (ref analogue: the Go operator's controller e2e,
# go/operator/pkg/controllers/elasticjob_controller.go:85).
#
# Usage: deploy/smoke.sh [--image <registry>/dlrover-tpu/operator:tag]
#                        [--timeout 300]
#
# This is the validation step tests/test_operator_deploy.py CANNOT
# perform (no kind/minikube in the CI image — it e2e-tests against a
# simulated apiserver speaking the real HTTP API instead). Run this
# whenever a real cluster is available.
set -euo pipefail

IMAGE=""
TIMEOUT=300
while [[ $# -gt 0 ]]; do
  case "$1" in
    --image) IMAGE="$2"; shift 2 ;;
    --timeout) TIMEOUT="$2"; shift 2 ;;
    *) echo "unknown arg: $1" >&2; exit 2 ;;
  esac
done

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
cd "$ROOT"

echo "== context: $(kubectl config current-context)"

if [[ -n "$IMAGE" ]]; then
  echo "== building + pushing $IMAGE"
  docker build -f deploy/Dockerfile -t "$IMAGE" .
  docker push "$IMAGE"
fi

echo "== applying CRDs, RBAC, operator"
kubectl apply -f deploy/crd-elasticjob.yaml
kubectl apply -f deploy/crd-scaleplan.yaml
kubectl apply -f deploy/rbac.yaml
if [[ -n "$IMAGE" ]]; then
  sed "s#image: .*dlrover-tpu/operator.*#image: $IMAGE#" \
    deploy/operator.yaml | kubectl apply -f -
else
  kubectl apply -f deploy/operator.yaml
fi

echo "== waiting for the operator deployment"
kubectl -n dlrover-tpu rollout status deploy/dlrover-tpu-operator \
  --timeout="${TIMEOUT}s"

echo "== submitting the golden ElasticJob"
kubectl apply -f tests/golden/elasticjob.yaml

echo "== waiting for phase Running (timeout ${TIMEOUT}s)"
deadline=$((SECONDS + TIMEOUT))
phase=""
while (( SECONDS < deadline )); do
  phase=$(kubectl get elasticjob ctr-train -n default \
    -o jsonpath='{.status.phase}' 2>/dev/null || true)
  echo "   phase: ${phase:-<none>}"
  case "$phase" in
    Running|Succeeded) break ;;
    Failed)
      echo "SMOKE FAIL: job phase Failed" >&2
      kubectl describe elasticjob ctr-train -n default >&2 || true
      exit 1 ;;
  esac
  sleep 5
done

if [[ "$phase" != "Running" && "$phase" != "Succeeded" ]]; then
  echo "SMOKE FAIL: job never reached Running within ${TIMEOUT}s" >&2
  kubectl get pods -n default -l dlrover-job=ctr-train >&2 || true
  kubectl logs -n dlrover-tpu deploy/dlrover-tpu-operator \
    --tail=50 >&2 || true
  exit 1
fi

echo "SMOKE OK: ElasticJob ctr-train is $phase"
kubectl get pods -n default -l dlrover-job=ctr-train
