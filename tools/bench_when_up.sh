#!/usr/bin/env bash
# The moment the TPU tunnel answers: land the round's verified perf
# number (VERDICT r4 item 1). Runs the health-gated bench, the
# backward-block autotune + fused-norm A/B, pins winners via env, and
# re-runs the bench; every successful measurement also lands in
# builder-side PERF_r05.json so a later capture-window outage cannot
# erase the story.
#
# Usage: tools/bench_when_up.sh  (run from the repo root)
set -uo pipefail
cd "$(dirname "$0")/.."

STAMP() { date +%H:%M:%S; }

echo "[$(STAMP)] step 1: baseline bench (shipped config)"
python bench.py | tee /tmp/bench_1.json
grep -q '"error"' /tmp/bench_1.json && {
  echo "bench still failing; aborting"; exit 1; }
python - <<'EOF'
import json, time
line = open("/tmp/bench_1.json").read().strip().splitlines()[-1]
rec = json.loads(line)
rec.update(stage="baseline", config="shipped defaults",
           ts=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
hist = []
try: hist = json.load(open("PERF_r05.json"))
except Exception: pass
hist.append(rec)
json.dump(hist, open("PERF_r05.json", "w"), indent=1)
print("PERF_r05.json <-", rec)
EOF

echo "[$(STAMP)] step 2: backward-block autotune + fused-norm A/B"
python tools/autotune_bwd_blocks.py --quick | tee /tmp/autotune.txt

echo "[$(STAMP)] step 3: re-bench with the autotune winner pinned"
echo "  (read the winner line from /tmp/autotune.txt, export the"
echo "   BENCH_* env it names, rerun: python bench.py, and append to"
echo "   PERF_r05.json as stage=tuned)"
