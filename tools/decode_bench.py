"""Decode/serving-path benchmark -> DECODE_r05.json (VERDICT r4
missing: the decode surface was unmeasured code).

Measures on the live chip (gated like the other round tools — a
CPU run writes only a labeled side file):

* prefill tok/s, monolithic (one batched forward filling the cache)
  for the GPT-2-shaped bench model;
* prefill tok/s, chunked (bounded-memory llama_prefill_chunked) for a
  windowed Mistral-tiny config, vs its monolithic prefill — the
  O(chunk*window) claim in wall-clock;
* steady-state decode tok/s (KV-cached lax.scan loop) for both.

The reference has no decode surface (training-only framework) — these
numbers are where "beat the reference" is strict superset capability;
cited in models/generate.py.

Run:  python -u tools/decode_bench.py
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def timed(fn, *args, repeats=3, **kw):
    """Best-of wall clock with block_until_ready, after one warmup
    (compile) call."""
    best, _, out = timed_samples(fn, *args, repeats=repeats, **kw)
    return best, out


def timed_samples(fn, *args, repeats=3, **kw):
    """``(best, samples, out)`` — every repeat's wall clock, for the
    latency percentiles (p50/p99 need the distribution, not just the
    floor)."""
    import jax

    out = fn(*args, **kw)
    jax.block_until_ready(out)
    samples = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn(*args, **kw)
        jax.block_until_ready(out)
        samples.append(time.perf_counter() - t0)
    return min(samples), samples, out


class VirtualClock:
    """Replica-local time for the disaggregation A/B: each fleet
    member's clock advances only by the measured wall cost of ITS OWN
    scheduler steps — the single-machine-honest model of dedicated
    per-role hardware (this container has one core, so concurrent
    subprocess replicas would just re-serialize on the OS scheduler;
    same fake-clock discipline as the PR-10 overlap acceptance)."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def run_disagg_ab(
    seed: int = 11,
    streams: int = 4,
    storms: int = 48,
    stream_max_new: int = 96,
    storm_prompt_len: int = 56,
    storm_max_new: int = 4,
    lanes: int = 6,
    max_len: int = 128,
    verify_outputs: bool = True,
) -> dict:
    """Prefill/decode disaggregation interference A/B (the hermetic
    half of the ISSUE-15 acceptance): the SAME long-prompt storm
    beside the SAME streaming decodes runs through

    * a COLOCATED mixed scheduler — prompt chunks and decode share
      one iteration loop, so every storm-laden step charges its
      prefill budget's wall time to the streams' inter-token gap;
    * a DISAGGREGATED pair — a prefill-role scheduler exports KV
      handoffs a decode-role scheduler imports, each on its own
      virtual clock, so decode ticks are charged ONLY their own
      compute (install + ragged step), never a prompt chunk.

    Step costs are REAL measured wall times of the jitted programs;
    only the concurrency is simulated (virtual per-replica clocks).
    Greedy outputs are verified bitwise against ``generate.generate``
    through the handoff. Returns the per-lane TPOT percentiles of
    the streaming requests (from the schedulers' own TPOT samples —
    the same values the dlrover_serve_tpot_seconds histograms and
    TTFT phase decomposition export)."""
    import numpy as np

    import jax.numpy as jnp

    from dlrover_tpu.models import generate
    from dlrover_tpu.obs.timeseries import _percentile
    from dlrover_tpu.serving.replica import build_tiny_model
    from dlrover_tpu.serving.scheduler import (
        ContinuousBatchingScheduler,
        ServeRequest,
    )

    params, cfg = build_tiny_model(seed, block_size=max_len)
    rng = np.random.default_rng(seed)
    stream_prompts = [
        rng.integers(0, cfg.vocab_size, size=6).tolist()
        for _ in range(streams)
    ]
    storm_prompts = [
        rng.integers(0, cfg.vocab_size, size=storm_prompt_len).tolist()
        for _ in range(storms)
    ]
    warm_stream = rng.integers(0, cfg.vocab_size, size=6).tolist()
    warm_storm = rng.integers(
        0, cfg.vocab_size, size=storm_prompt_len
    ).tolist()

    def make(role, clock, n_lanes):
        return ContinuousBatchingScheduler(
            params, cfg, lanes=n_lanes, block_size=8,
            prefill_chunk=16, prefill_budget=64, max_len=max_len,
            role=role, clock=clock,
        )

    def stepped(sched, clock):
        """One scheduler step, charging its wall cost to the
        scheduler's OWN clock (frozen during the step, so all the
        step's events stamp at step start — per-lane TPOT becomes
        the sum of this loop's own step costs per token)."""
        t0 = time.perf_counter()
        out = sched.step()
        clock.advance(time.perf_counter() - t0)
        return out

    def submit(sched, tag, prompt, max_new):
        assert sched.submit(
            ServeRequest(
                request_id=tag, prompt=list(prompt),
                max_new_tokens=max_new,
            )
        )

    # ---- colocated leg ---------------------------------------------------
    vc = VirtualClock()
    mixed = make("mixed", vc, lanes)
    done: dict = {}

    def drain_mixed(until, budget=20000):
        for _ in range(budget):
            for c in stepped(mixed, vc):
                done[c.request_id] = c
            if until():
                return
        raise RuntimeError("colocated leg did not converge")

    submit(mixed, "warm-s", warm_stream, 3)
    submit(mixed, "warm-l", warm_storm, 2)
    drain_mixed(lambda: "warm-s" in done and "warm-l" in done)
    for i, p in enumerate(stream_prompts):
        submit(mixed, f"stream-{i}", p, stream_max_new)
    drain_mixed(
        lambda: all(
            s.phase == "decode" for s in mixed._by_lane.values()
        ) and mixed.active() == streams
    )
    for i, p in enumerate(storm_prompts):
        submit(mixed, f"storm-{i}", p, storm_max_new)
    drain_mixed(lambda: len(done) == 2 + streams + storms)
    coloc_done = dict(done)
    coloc_tpots = sorted(
        coloc_done[f"stream-{i}"].tpot_s for i in range(streams)
    )

    # ---- disaggregated leg ----------------------------------------------
    vpre, vdec = VirtualClock(), VirtualClock()
    pre = make("prefill", vpre, lanes)
    dec = make("decode", vdec, lanes)
    done = {}

    def pump(until, budget=40000):
        """Alternate the two replicas' loops; handoffs flow prefill
        -> decode; each loop's cost lands on its own clock."""
        for _ in range(budget):
            for c in stepped(pre, vpre):
                if c.finish_reason == "handoff":
                    assert dec.submit_handoff(c.handoff)
                else:
                    done[c.request_id] = c
            for c in stepped(dec, vdec):
                done[c.request_id] = c
            if until():
                return
        raise RuntimeError("disaggregated leg did not converge")

    submit(pre, "warm-s", warm_stream, 3)
    submit(pre, "warm-l", warm_storm, 2)
    pump(lambda: "warm-s" in done and "warm-l" in done)
    for i, p in enumerate(stream_prompts):
        submit(pre, f"stream-{i}", p, stream_max_new)
    pump(lambda: dec.active() == streams)
    for i, p in enumerate(storm_prompts):
        submit(pre, f"storm-{i}", p, storm_max_new)
    pump(lambda: len(done) == 2 + streams + storms)
    disagg_done = dict(done)
    disagg_tpots = sorted(
        disagg_done[f"stream-{i}"].tpot_s for i in range(streams)
    )

    # ---- bitwise parity through the handoff ------------------------------
    # Every stream + a storm sample: each reference generate.generate
    # call re-traces (distinct shapes), so verifying all 48 storms
    # would cost more wall time than the A/B itself — the failover
    # drill leg verifies EVERY request end-to-end over RPC.
    mismatched = []
    cases = {}
    if verify_outputs:
        for i, p in enumerate(stream_prompts):
            cases[f"stream-{i}"] = (p, stream_max_new)
        for i, p in enumerate(storm_prompts[:8]):
            cases[f"storm-{i}"] = (p, storm_max_new)
        for rid, (prompt, max_new) in cases.items():
            want = np.asarray(
                generate.generate(
                    params, cfg, jnp.asarray([prompt], jnp.int32),
                    max_new_tokens=max_new, temperature=0.0,
                )
            )[0, len(prompt):].tolist()
            for leg, results in (
                ("colocated", coloc_done),
                ("disagg", disagg_done),
            ):
                if results[rid].tokens != want:
                    mismatched.append((leg, rid))
    if mismatched:
        raise AssertionError(
            "greedy outputs diverged from generate.generate: "
            f"{mismatched[:4]}"
        )

    coloc_p99 = _percentile(coloc_tpots, 99.0)
    disagg_p99 = _percentile(disagg_tpots, 99.0)
    return {
        "seed": seed,
        "streams": streams,
        "storms": storms,
        "stream_max_new": stream_max_new,
        "storm_prompt_len": storm_prompt_len,
        "storm_max_new": storm_max_new,
        "lanes": lanes,
        "prefill_chunk": 16,
        "prefill_budget": 64,
        "coloc_p50_tpot_s": round(_percentile(coloc_tpots, 50.0), 6),
        "coloc_p99_tpot_s": round(coloc_p99, 6),
        "disagg_p50_tpot_s": round(
            _percentile(disagg_tpots, 50.0), 6
        ),
        "disagg_p99_tpot_s": round(disagg_p99, 6),
        "tpot_p99_ratio": round(
            disagg_p99 / max(coloc_p99, 1e-12), 4
        ),
        "handoffs": pre.stats()["handoffs_exported"],
        # Verified in BOTH legs (every stream + a storm sample; the
        # failover drill leg verifies every request over RPC).
        "outputs_verified": 2 * len(cases),
    }


def disagg_mode() -> int:
    """``--disagg``: record the colocated/disaggregated kind-decode
    ledger pair and verify the regression gate reads it
    lower-is-better (the ISSUE-15 bench satellite). DECODE_LEDGER=0
    routes the records to a throwaway ledger so CI never pollutes
    the history (the gate is still exercised end to end)."""
    import tempfile

    from bench_ledger import append_record, compare

    rec = run_disagg_ab()
    ok = rec["disagg_p99_tpot_s"] < rec["coloc_p99_tpot_s"]
    print(
        f"[disagg] stream p99 TPOT: colocated "
        f"{rec['coloc_p99_tpot_s']}s vs disaggregated "
        f"{rec['disagg_p99_tpot_s']}s "
        f"(x{rec['tpot_p99_ratio']}, {rec['handoffs']} handoffs, "
        f"{rec['outputs_verified']} outputs bitwise-verified)",
        flush=True,
    )
    path = None
    if os.environ.get("DECODE_LEDGER", "1") == "0":
        path = tempfile.mktemp(suffix=".jsonl")
    roles = {
        "colocated": {"mixed": 1},
        "disagg": {"prefill": 1, "decode": 1},
    }
    for label, value in (
        ("colocated", rec["coloc_p99_tpot_s"]),
        ("disagg", rec["disagg_p99_tpot_s"]),
    ):
        stored = append_record(
            {
                "kind": "decode",
                "metric": "decode_p99_tpot_seconds",
                "value": value,
                "unit": "s",
                "label": label,
                # The role config is part of the record's pins: a
                # compare across fleet shapes must SAY it compared
                # fleet shapes.
                "pins": {
                    "roles": roles[label],
                    "lanes": rec["lanes"],
                    "prefill_chunk": rec["prefill_chunk"],
                    "prefill_budget": rec["prefill_budget"],
                    "storms": rec["storms"],
                    "storm_prompt_len": rec["storm_prompt_len"],
                },
            },
            path=path,
        )
        print(
            f"[disagg] ledger += decode_p99_tpot_seconds "
            f"{stored.get('value')} s ({label})",
            flush=True,
        )
    code, report = compare(
        baseline="colocated",
        head="disagg",
        metric="decode_p99_tpot_seconds",
        path=path,
    )
    print(report, flush=True)
    if "(lower is better)" not in report:
        print(
            "[disagg] FAIL: compare did not gate "
            "decode_p99_tpot_seconds lower-is-better",
            file=sys.stderr,
        )
        return 1
    if code != 0 or not ok:
        print(
            "[disagg] FAIL: disaggregated p99 TPOT did not beat "
            "colocated",
            file=sys.stderr,
        )
        return 1
    return 0


def main() -> int:
    if "--disagg" in sys.argv[1:]:
        return disagg_mode()
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from dlrover_tpu.models import generate, llama

    on_tpu = jax.default_backend() in ("tpu", "axon")
    small = os.environ.get("DECODE_SMALL") == "1" or not on_tpu
    rec: dict = {
        "backend": jax.default_backend(),
        "full_scale": not small,
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }

    def ckpt():
        """Measured-so-far checkpoint: a tunnel death mid-section
        must not erase completed sections (the r5 longctx lesson)."""
        json.dump(rec, open("/tmp/decode_partial.json", "w"), indent=1)

    # --- GPT-2-shaped Llama-family config (the bench model's shape) --
    if small:
        cfg = llama.LlamaConfig.tiny()
        cfg = dataclasses.replace(cfg, block_size=128)
        b, t_prompt, new = 2, 64, 32
        mcfg = dataclasses.replace(
            llama.LlamaConfig.tiny(), sliding_window=16, block_size=128
        )
        m_prompt, chunk = 96, 32
    else:
        cfg = llama.LlamaConfig(
            vocab_size=50304, block_size=2048, n_layer=12, n_head=12,
            n_kv_head=12, n_embd=768, intermediate=3072,
            dtype=jnp.bfloat16,
        )
        b, t_prompt, new = 8, 1024, 512
        # Mistral-tiny: the 4096-token band binding inside an 8k
        # prompt, GQA 4:1 — the sliding-window serving regime.
        mcfg = llama.LlamaConfig(
            vocab_size=32000, block_size=16384, n_layer=8, n_head=16,
            n_kv_head=4, n_embd=1024, intermediate=3584,
            dtype=jnp.bfloat16, sliding_window=4096,
        )
        m_prompt, chunk = 8192, 1024

    key = jax.random.PRNGKey(0)
    params = llama.init_params(key, cfg)
    prompt = jax.random.randint(
        jax.random.fold_in(key, 1), (b, t_prompt), 0, cfg.vocab_size
    )

    # Monolithic prefill tok/s.
    total = t_prompt + new
    cache = generate._cache_for(cfg, b, total, cfg.n_kv_head)
    pre_fn = jax.jit(
        lambda p, c, tok: generate.llama_prefill(p, c, tok, cfg)
    )
    dt, (logits, filled) = timed(pre_fn, params, cache, prompt)
    rec["gpt2_prefill_ms"] = round(dt * 1e3, 2)
    rec["gpt2_prefill_tok_s"] = round(b * t_prompt / dt, 1)
    print(f"[decode] gpt2-shape prefill: {dt*1e3:.1f} ms "
          f"({rec['gpt2_prefill_tok_s']} tok/s)", flush=True)
    ckpt()

    # Steady-state decode tok/s: difference two generate lengths so
    # prefill and fixed overheads cancel exactly (subtracting a
    # separately-jitted prefill underflows when the two programs
    # optimize differently).
    def gen_at(n_new, samples=False):
        fn = jax.jit(
            lambda p, pr, k: generate.generate(
                p, cfg, pr, max_new_tokens=n_new, temperature=0.0,
                key=k,
            )
        )
        d, walls, _ = timed_samples(
            fn, params, prompt, jax.random.PRNGKey(2), repeats=5
        )
        return (d, walls) if samples else d

    half = max(new // 2, 1)
    (dt_full, full_walls), dt_half = (
        gen_at(new, samples=True), gen_at(new - half)
    )
    decode_s = max(dt_full - dt_half, 1e-9)
    rec["gpt2_generate_ms"] = round(dt_full * 1e3, 2)
    rec["gpt2_decode_tok_s"] = round(b * half / decode_s, 1)
    rec["gpt2_decode_ms_per_tok"] = round(decode_s / half * 1e3, 3)
    print(f"[decode] gpt2-shape decode: {rec['gpt2_decode_tok_s']} "
          f"tok/s ({rec['gpt2_decode_ms_per_tok']} ms/tok, "
          f"batch {b})", flush=True)
    ckpt()

    # Latency distributions (the serving SLO pair): TTFT = a 1-token
    # generate (prefill + first token), sampled per repeat; TPOT =
    # per-repeat (full_wall - best_half_wall) / half. p50/p99 use the
    # one shared nearest-rank formula so the bench's gates measure
    # the same quantity as the router's exported gauges.
    from dlrover_tpu.obs.timeseries import _percentile

    _, ttft_walls = gen_at(1, samples=True)
    tpot_samples = sorted(
        max(w - dt_half, 1e-9) / half for w in full_walls
    )
    ttft_samples = sorted(ttft_walls)
    rec["ttft_p50_s"] = round(_percentile(ttft_samples, 50.0), 4)
    rec["ttft_p99_s"] = round(_percentile(ttft_samples, 99.0), 4)
    rec["tpot_p50_s"] = round(_percentile(tpot_samples, 50.0), 5)
    rec["tpot_p99_s"] = round(_percentile(tpot_samples, 99.0), 5)
    print(f"[decode] gpt2-shape latency: ttft p50/p99 "
          f"{rec['ttft_p50_s']}/{rec['ttft_p99_s']}s, tpot p50/p99 "
          f"{rec['tpot_p50_s']}/{rec['tpot_p99_s']}s", flush=True)
    ckpt()

    # --- windowed Mistral-tiny: chunked vs monolithic prefill --------
    mparams = llama.init_params(jax.random.fold_in(key, 3), mcfg)
    mprompt = jax.random.randint(
        jax.random.fold_in(key, 4), (1, m_prompt), 0, mcfg.vocab_size
    )
    mcache = generate._cache_for(mcfg, 1, m_prompt + 8, mcfg.n_kv_head)
    mono_fn = jax.jit(
        lambda p, c, tok: generate.llama_prefill(p, c, tok, mcfg)
    )
    dt_mono, _ = timed(mono_fn, mparams, mcache, mprompt)
    rec["mistral_prefill_mono_ms"] = round(dt_mono * 1e3, 2)

    # jit the whole chunk loop (it unrolls at trace time) so both
    # prefill paths compare as compiled programs — unjitted, the
    # chunked path would pay per-op dispatch the monolithic one
    # doesn't.
    chunked = jax.jit(
        lambda p, c, tok: generate.llama_prefill_chunked(
            p, c, tok, mcfg, chunk_size=chunk
        )
    )

    dt_chunk, _ = timed(chunked, mparams, mcache, mprompt)
    rec["mistral_prefill_chunked_ms"] = round(dt_chunk * 1e3, 2)
    rec["mistral_prompt"] = m_prompt
    rec["mistral_window"] = mcfg.sliding_window
    rec["mistral_chunk"] = chunk
    rec["chunked_over_mono"] = round(dt_chunk / dt_mono, 2)
    print(f"[decode] mistral prefill {m_prompt} tokens: "
          f"mono {dt_mono*1e3:.1f} ms vs chunked {dt_chunk*1e3:.1f} ms",
          flush=True)
    ckpt()

    # Windowed decode tok/s — same two-length differencing.
    m_new = 8 if small else 128

    def mgen_at(n_new):
        fn = jax.jit(
            lambda p, pr, k: generate.generate(
                p, mcfg, pr, max_new_tokens=n_new, temperature=0.0,
                key=k,
            )
        )
        d, _ = timed(fn, mparams, mprompt, jax.random.PRNGKey(5))
        return d

    m_half = max(m_new // 2, 1)
    dt_mfull, dt_mhalf = mgen_at(m_new), mgen_at(m_new - m_half)
    mdecode_s = max(dt_mfull - dt_mhalf, 1e-9)
    rec["mistral_decode_tok_s"] = round(m_half / mdecode_s, 1)
    rec["mistral_decode_ms_per_tok"] = round(
        mdecode_s / m_half * 1e3, 3
    )
    print(f"[decode] mistral decode: {rec['mistral_decode_tok_s']} "
          f"tok/s at context {m_prompt}", flush=True)
    ckpt()

    # Artifact convention (tools/README.md): only full-size hardware
    # runs write the repo-root round record; smoke runs go to /tmp.
    out = (
        os.path.join(REPO, "DECODE_r05.json")
        if (on_tpu and not small)
        else "/tmp/decode_bench_smoke.json"
    )
    json.dump(rec, open(out, "w"), indent=1)
    print(f"[decode] wrote {out}", flush=True)

    # Regression gate: decode throughput rides the same fingerprinted
    # append-only ledger as training (BENCH_LEDGER.jsonl; record kind
    # "decode"), so a serving-path regression trips
    # `bench_ledger compare --metric decode_tokens_per_sec` exactly
    # like a train-step one. DECODE_LEDGER=0 skips (sweeps that
    # should not pollute the history).
    if os.environ.get("DECODE_LEDGER", "1") != "0":
        from bench_ledger import append_record

        for metric, value, unit, extra in (
            (
                "decode_tokens_per_sec",
                rec["gpt2_decode_tok_s"],
                "tok/s",
                {
                    "prefill_tok_s": rec["gpt2_prefill_tok_s"],
                    "ms_per_tok": rec["gpt2_decode_ms_per_tok"],
                    "batch": b,
                },
            ),
            (
                "decode_windowed_tokens_per_sec",
                rec["mistral_decode_tok_s"],
                "tok/s",
                {
                    "context": m_prompt,
                    "window": mcfg.sliding_window,
                    "chunked_over_mono": rec["chunked_over_mono"],
                },
            ),
            # Latency gates: `bench_ledger compare --metric
            # decode_ttft_p99_s` (or decode_tpot_p99_s) trips on a
            # latency regression, not just a throughput one.
            (
                "decode_ttft_p99_s",
                rec["ttft_p99_s"],
                "s",
                {"p50": rec["ttft_p50_s"], "batch": b},
            ),
            (
                "decode_tpot_p99_s",
                rec["tpot_p99_s"],
                "s",
                {"p50": rec["tpot_p50_s"], "batch": b},
            ),
        ):
            stored = append_record(
                {
                    "kind": "decode",
                    "metric": metric,
                    "value": value,
                    "unit": unit,
                    "backend": rec["backend"],
                    "full_scale": rec["full_scale"],
                    **extra,
                },
                backend=rec["backend"],
            )
            print(
                f"[decode] ledger += {metric} "
                f"{stored.get('value')} {unit} "
                f"(rev {str(stored.get('git_rev', ''))[:12]})",
                flush=True,
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
