"""Make the repo root importable from tools/ scripts.

PYTHONPATH=. breaks the axon TPU plugin's jax_plugins namespace
discovery, so tools extend sys.path here instead of via env var:

    import _repo_path  # noqa: F401  (must precede `import jax`)
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
