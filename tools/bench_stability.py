"""Multi-run bench stability record (VERDICT r4 weak #1).

Runs ``bench.py`` N times (default 3) back-to-back on the live chip and
writes STABILITY_r05.json with every run's record plus mean / stddev /
spread of tokens-per-second, so single-run sweep deltas (e.g. 0.902 vs
0.924 in PERF_r04.json) can be judged against measured run-to-run noise.

Artifact is written ONLY if >= ``--min-runs`` runs succeed, so a tunnel
drop mid-way leaves no misleading single-run "stability" file and the
unattended chain retries on its next probe.

Run:  python -u tools/bench_stability.py
"""

from __future__ import annotations

import argparse
import json
import math
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def one_run(timeout_s: float) -> dict | None:
    try:
        p = subprocess.run(
            [sys.executable, "bench.py"],
            env={**os.environ, "BENCH_MAX_WAIT_S": "600",
                 "BENCH_PROBE_TIMEOUT": "90"},
            capture_output=True, text=True, cwd=REPO, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        print(f"[stability] run timed out after {timeout_s:.0f}s", flush=True)
        return None
    for line in p.stdout.splitlines():
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                return None
            if not rec.get("error"):
                return rec
    print(f"[stability] rc={p.returncode} stderr tail: "
          f"{(p.stderr or '')[-300:]}", flush=True)
    return None


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--min-runs", type=int, default=3)
    ap.add_argument("--timeout", type=float, default=1200)
    args = ap.parse_args()

    runs = []
    for i in range(args.runs):
        print(f"[stability] run {i + 1}/{args.runs}", flush=True)
        rec = one_run(args.timeout)
        if rec:
            rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
            runs.append(rec)
            print(f"[stability] -> {rec['value']} {rec.get('unit')}",
                  flush=True)
        time.sleep(5)

    if len(runs) < args.min_runs:
        print(f"[stability] only {len(runs)}/{args.min_runs} runs landed; "
              "not writing artifact", flush=True)
        return 1

    vals = [r["value"] for r in runs]
    mean = sum(vals) / len(vals)
    var = (
        sum((v - mean) ** 2 for v in vals) / (len(vals) - 1)
        if len(vals) > 1 else 0.0
    )
    stats = {
        "n": len(vals),
        "mean": round(mean, 1),
        "stddev": round(math.sqrt(var), 1),
        "spread_pct": round(100 * (max(vals) - min(vals)) / mean, 3),
    }
    out = {
        "runs": runs,
        **stats,
        "unit": runs[0].get("unit"),
        "vs_baseline_mean": round(
            sum(r.get("vs_baseline", 0) for r in runs) / len(runs), 4),
    }
    # Provenance stamp + ledger record: the multi-run stats are the
    # strongest comparison endpoint the regression gate can use
    # (tools/bench_ledger.py prefers stats.mean over single values).
    try:
        import _repo_path  # noqa: F401
        from dlrover_tpu.common.runmeta import run_metadata

        out["meta"] = run_metadata(
            backend=runs[0].get("backend")
        )
        import bench_ledger

        bench_ledger.append_record(
            {
                "metric": runs[0].get("metric"),
                "value": stats["mean"],
                "unit": runs[0].get("unit"),
                "vs_baseline": out["vs_baseline_mean"],
                "stats": stats,
                "stage": "stability",
                "meta": out["meta"],
            }
        )
    except Exception as exc:  # noqa: BLE001 — bookkeeping must not
        # discard three successful chip runs
        print(f"[stability] ledger/meta stamp failed: {exc!r}",
              flush=True)
    path = os.path.join(REPO, "STABILITY_r05.json")
    json.dump(out, open(path, "w"), indent=1)
    print(f"[stability] wrote {path}: mean={out['mean']} "
          f"stddev={out['stddev']} spread={out['spread_pct']}%", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
