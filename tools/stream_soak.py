"""Streaming exactly-once soak: PS kill + master kill + rebalance.

The drill proves the stream-barrier / replay-fence contract end to
end with REAL process boundaries: a master subprocess (``--state_dir``
journal), PS subprocesses (``dlrover_tpu.sparse.ps_main``), and an
in-process fenced ``SparseTrainer`` streaming a seeded record ledger
through them. Mid-stream it

* SIGKILLs one PS between barriers (un-flushed applies die with it;
  the liveness monitor fails it over, survivors restore the barrier
  cut, the trainer replays its post-barrier window through the fence),
* SIGKILLs the master right after a durable barrier and restarts it
  on the same state_dir (warm restart must restore the shard ledger,
  the stream watermarks AND the PS partition map),
* registers a fresh PS mid-stream (live rebalance: partitions move
  PS-to-PS with their fence state; the map bump triggers a replay the
  survivors must dedup).

Afterwards it audits *every* record id for exactly-once application
using per-row arithmetic: the trainer applies all-ones gradients via
fused sparse SGD at lr=1.0, so a row's update count is
``round(-mean(row))`` (init noise is ±0.05 « 0.5). Zero lost records,
zero double-applies, or :class:`DrillError`.

Usage::

    python tools/stream_soak.py --selftest        # seeded, CI-sized
    python tools/stream_soak.py --records 512 --rounds 3 --seed 7
    python tools/stream_soak.py --json out.json --ledger

The full soak appends a kind="soak" record to BENCH_LEDGER.jsonl so
the exactly-once audit leaves the same durable evidence trail as the
perf benches.
"""

import _repo_path  # noqa: F401  (sys.path, must precede dlrover_tpu)

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

from chaos_drill import DrillError, start_master
from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import RpcClient, find_free_port
from dlrover_tpu.common.config import ensure_framework_on_pythonpath
from dlrover_tpu.obs.timeline import load_events

TABLE = "emb"
DIM = 4


def start_ps(
    node_id: int,
    master_addr: str,
    checkpoint_dir: str,
    stats_interval: float = 0.3,
) -> subprocess.Popen:
    """Spawn one real PS node process; it registers itself with the
    master (which assigns partitions and publishes the map)."""
    env = ensure_framework_on_pythonpath(dict(os.environ))
    env.update({
        "JAX_PLATFORMS": "cpu",
        # PS processes must not inherit the drill's client-side chaos.
        "DLROVER_TPU_CHAOS": "0",
    })
    return subprocess.Popen(
        [
            sys.executable,
            "-m", "dlrover_tpu.sparse.ps_main",
            "--node-id", str(node_id),
            "--master", master_addr,
            "--checkpoint-dir", checkpoint_dir,
            "--tables", f"{TABLE}:{DIM}",
            "--stats-interval", str(stats_interval),
        ],
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )


def wait_for_map(master_client, want_ps, timeout: float = 30.0):
    """Block until the published PartitionMap covers ``want_ps`` (and
    only them) with a non-empty assignment."""
    want = set(want_ps)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        pmap = master_client.get_partition_map()
        if set(pmap.ps_addrs) == want and pmap.assignment:
            return pmap
        time.sleep(0.1)
    raise DrillError(
        f"partition map never converged to PS set {sorted(want)} "
        f"within {timeout}s"
    )


def wait_for_map_version(master_client, min_version: int,
                         timeout: float = 30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        pmap = master_client.get_partition_map()
        if pmap.version >= min_version:
            return pmap
        time.sleep(0.1)
    raise DrillError(
        f"partition map never reached version {min_version} "
        f"within {timeout}s"
    )


def wait_for_quiesced_ledger(
    state_dir: str, dataset: str, timeout: float = 15.0
) -> dict:
    """Block until the newest valid master snapshot shows no in-flight
    (doing) shard for ``dataset``. The master-kill leg is the clean
    one — barrier durable, completions acked — so the drill must not
    race the completion journal (the doing-shard kill is
    chaos_drill's contract, not this one's)."""
    from dlrover_tpu.master.state_store import MasterStateStore

    store = MasterStateStore(state_dir)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        doc = store.load_latest()
        if doc is not None:
            ds = (
                doc["state"]
                .get("task_manager", {})
                .get("datasets", {})
                .get(dataset)
            )
            if ds is not None and not ds.get("state", {}).get("doing"):
                return doc
        time.sleep(0.05)
    raise DrillError(
        f"ledger snapshot still shows in-flight shards for {dataset} "
        f"after {timeout}s"
    )


def audit_exactly_once(master_client, total_records: int) -> dict:
    """Export every row from every PS and verify each record id was
    applied exactly once (count = round(-mean(row)) under all-ones
    SGD at lr=1.0)."""
    pmap = master_client.get_partition_map()
    counts = {}
    for ps_id in pmap.ps_ids():
        addr = pmap.ps_addrs.get(ps_id)
        if addr is None:
            continue
        client = RpcClient(addr, timeout=10.0)
        try:
            dump = client.get(msg.PsExportRequest(
                table=TABLE,
                partitions=pmap.partitions_of(ps_id),
                since_version=0,
                include_slots=False,
            ))
        finally:
            client.close()
        if dump.keys is None:
            continue
        keys = dump.keys.to_numpy()
        values = dump.values.to_numpy().reshape(keys.size, DIM)
        for k, row in zip(keys.tolist(), values):
            n = int(round(-float(row.mean())))
            # Partitions are disjoint across PS, but a just-moved
            # partition could briefly exist on two nodes; identical
            # counts for the same key are the same row, not a double.
            counts[k] = max(counts.get(k, 0), n)
    doubles = sorted(k for k, n in counts.items() if n > 1)
    missing = sorted(
        k for k in range(total_records) if counts.get(k, 0) != 1
    )
    extras = sorted(k for k in counts if not 0 <= k < total_records)
    if doubles:
        raise DrillError(
            f"records applied more than once: {doubles[:10]} "
            f"({len(doubles)} total) — replay fence failed"
        )
    if missing:
        raise DrillError(
            f"records lost or never applied: {missing[:10]} "
            f"({len(missing)} total) — barrier restore/replay failed"
        )
    if extras:
        raise DrillError(f"rows outside the record space: {extras[:10]}")
    return {"rows_audited": len(counts)}


def run_soak(
    seed: int = 0,
    total_records: int = 48,
    batch_size: int = 4,
    stream_partitions: int = 2,
    barrier_every: int = 3,
    kill_ps_after_task: int = 4,
    kill_master_after_barrier: int = 2,
    rebalance_after_task: int = 9,
    keep_dir: bool = False,
    ledger: bool = False,
) -> dict:
    """One full kill cycle: PS SIGKILL -> master SIGKILL+warm restart
    -> live PS rebalance, then the exactly-once audit. Returns a
    JSON-able report; raises :class:`DrillError` on any violation."""
    # Late imports: the trainer pulls jax/optax.
    import jax.numpy as jnp
    import optax

    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.agent.sharding_client import ShardingClient
    from dlrover_tpu.sparse.ps_client import DistributedKvClient
    from dlrover_tpu.trainer.sparse_trainer import (
        SparseTrainer,
        make_ctr_loss_and_grads,
    )

    tmpdir = tempfile.mkdtemp(prefix="stream_soak_")
    state_dir = os.path.join(tmpdir, "state")
    ps_ckpt = os.path.join(tmpdir, "ps_ckpt")
    trace_file = os.path.join(tmpdir, "trace.jsonl")
    port = find_free_port()
    master_addr = f"127.0.0.1:{port}"
    # Shrink PS death detection to ~1.5 s worst case so whole kill
    # cycles fit in a CI selftest (defaults: ~10 s).
    master_env = {
        "DLROVER_TPU_PS_LIVENESS_INTERVAL": "0.3",
        "DLROVER_TPU_PS_LIVENESS_TIMEOUT": "1.0",
    }
    t0 = time.monotonic()
    master = start_master(port, state_dir, trace_file,
                          extra_env=master_env)
    ps_procs = {}
    mc = None
    kv = None
    try:
        ps_procs[0] = start_ps(0, master_addr, ps_ckpt)
        ps_procs[1] = start_ps(1, master_addr, ps_ckpt)
        mc = MasterClient(master_addr, node_id=0)
        mc.supervisor.outage_budget = 60.0
        mc.supervisor.backoff_base = 0.1
        mc.register_node()
        wait_for_map(mc, {0, 1})

        sharding = ShardingClient("stream", client=mc)
        sharding.create_dataset(
            dataset_size=total_records,
            batch_size=batch_size,
            num_minibatches_per_shard=1,
            storage_type="streaming",
            num_stream_partitions=stream_partitions,
        )
        kv = DistributedKvClient(
            mc.get_partition_map, {TABLE: DIM}, client_id=0
        )

        def loss_fn(dense, emb):
            # All-ones embedding grads: fused SGD at lr=1.0 turns a
            # row into a unit-decrement apply counter for the audit.
            return jnp.sum(emb) + 0.0 * dense["w"][0]

        trainer = SparseTrainer(
            client=kv,
            loss_and_grads=make_ctr_loss_and_grads(loss_fn),
            dense_optimizer=optax.sgd(0.0),
            dense_params={"w": jnp.zeros((1,))},
            table=TABLE,
            embedding_dim=DIM,
            sparse_optimizer="sgd",
            sparse_lr=1.0,
            barrier_client=sharding,
            barrier_every=barrier_every,
        )

        replayed = {"n": 0}
        orig_replay = trainer.maybe_replay

        def counted_replay():
            n = orig_replay()
            replayed["n"] += n
            return n

        trainer.maybe_replay = counted_replay

        ps_killed = master_killed = rebalanced = False
        restart_done = {}
        tasks_done = 0
        streamed = set()
        while True:
            task = sharding.get_task(timeout=120)
            if task is None:
                break
            ids = np.asarray(task.shard.record_indices, np.int64)
            if not len(ids):
                sharding.report_task_done(task.task_id)
                continue
            dup = streamed.intersection(ids.tolist())
            if dup:
                raise DrillError(
                    f"ledger re-dispatched records {sorted(dup)[:10]}"
                )
            streamed.update(ids.tolist())
            trainer.train_step(ids)
            sharding.report_task_done(task.task_id)
            tasks_done += 1

            if not ps_killed and tasks_done >= kill_ps_after_task:
                # Between steps, mid-barrier-window: every apply since
                # the last barrier is un-flushed and dies with the PS.
                # The survivor restores the barrier cut; the trainer's
                # replay window must refill the gap exactly once.
                ps_procs[0].kill()
                ps_procs[0].wait()
                ps_killed = True
            if (
                not master_killed
                and trainer.last_barrier is not None
                and trainer.last_barrier.epoch
                >= kill_master_after_barrier
            ):
                if not trainer.last_barrier.durable:
                    raise DrillError(
                        "stream barrier acked without a durable "
                        "journal record"
                    )
                wait_for_quiesced_ledger(state_dir, "stream")
                master.kill()  # SIGKILL: no goodbye snapshot
                master.wait()

                def restart_later():
                    restart_done["proc"] = start_master(
                        port, state_dir, trace_file,
                        extra_env=master_env,
                    )

                threading.Thread(
                    target=restart_later, daemon=True
                ).start()
                master_killed = True
            if not rebalanced and tasks_done >= rebalance_after_task:
                if master_killed and "proc" not in restart_done:
                    # Registration needs a live master; the supervisor
                    # below would ride it out, but a deterministic
                    # drill orders the legs explicitly.
                    deadline = time.monotonic() + 60
                    while (
                        "proc" not in restart_done
                        and time.monotonic() < deadline
                    ):
                        time.sleep(0.1)
                old_version = mc.get_partition_map().version
                ps_procs[2] = start_ps(2, master_addr, ps_ckpt)
                wait_for_map_version(mc, old_version + 1)
                rebalanced = True
        if "proc" in restart_done:
            master = restart_done["proc"]
        for leg, done in (
            ("ps kill", ps_killed),
            ("master kill", master_killed),
            ("rebalance", rebalanced),
        ):
            if not done:
                raise DrillError(
                    f"stream ended before the {leg} leg — dataset too "
                    "small for the configured kill points"
                )
        if streamed != set(range(total_records)):
            raise DrillError(
                f"ledger never dispatched "
                f"{sorted(set(range(total_records)) - streamed)[:10]}"
            )
        # A rebalance on the final task can leave the replay window
        # pending (it normally drains at the next step's replay check)
        # — drain it, then cut the final barrier so the audit sees a
        # quiesced, fully-fenced store.
        trainer.maybe_replay()
        final = trainer.commit_barrier()
        if not final.durable:
            raise DrillError("final stream barrier not durable")
        audit = audit_exactly_once(mc, total_records)

        events = load_events(trace_file)
        names = [e.get("name") for e in events]
        if "stream.barrier" not in names:
            raise DrillError("no stream.barrier span in the trace")
        warm = [e for e in events if e.get("name") == "master.warm_restart"]
        if not warm:
            raise DrillError(
                "no master.warm_restart event — the replacement "
                "master cold-started"
            )
        report = {
            "seed": seed,
            "total_records": total_records,
            "tasks": tasks_done,
            "stream_partitions": stream_partitions,
            "barriers": final.epoch,
            "final_flush_gen": final.flush_gen,
            "replayed_applies": replayed["n"],
            "rows_audited": audit["rows_audited"],
            "warm_restart_events": len(warm),
            "stream_barrier_spans": names.count("stream.barrier"),
            "wall_s": round(time.monotonic() - t0, 3),
            "dir": tmpdir if keep_dir else None,
        }
        if ledger:
            from bench_ledger import append_record

            append_record({
                "kind": "soak",
                "metric": "stream_soak_records_exactly_once",
                "value": float(audit["rows_audited"]),
                "unit": "records",
                "detail": (
                    "PS SIGKILL + master SIGKILL + rebalance; "
                    f"{replayed['n']} fenced replays, "
                    f"{final.epoch} barriers"
                ),
                "soak": {k: v for k, v in report.items() if k != "dir"},
            }, backend="cpu")
        return report
    finally:
        if kv is not None:
            kv.close()
        if mc is not None:
            mc.close()
        for proc in ps_procs.values():
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        if master.poll() is None:
            master.send_signal(signal.SIGTERM)
            try:
                master.wait(timeout=10)
            except subprocess.TimeoutExpired:
                master.kill()
        if not keep_dir:
            shutil.rmtree(tmpdir, ignore_errors=True)


def selftest() -> int:
    """Seeded, hermetic CI smoke: one full kill cycle at drill scale
    (48 records, 2 stream partitions, all three fault legs)."""
    t0 = time.monotonic()
    report = run_soak(seed=7)
    print(
        f"soak ok: {report['rows_audited']} records exactly-once "
        f"through ps-kill+master-kill+rebalance "
        f"({report['replayed_applies']} fenced replays, "
        f"{report['barriers']} barriers, {report['wall_s']}s)"
    )
    print(f"stream soak selftest ok ({time.monotonic() - t0:.1f}s)")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("stream_soak")
    parser.add_argument("--selftest", action="store_true",
                        help="seeded quick mode for CI")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--rounds", type=int, default=1)
    parser.add_argument("--records", type=int, default=96)
    parser.add_argument("--batch", type=int, default=4)
    parser.add_argument("--stream_partitions", type=int, default=2)
    parser.add_argument("--barrier_every", type=int, default=3)
    parser.add_argument("--json", type=str, default="",
                        help="write the soak report to this path")
    parser.add_argument("--ledger", action="store_true",
                        help="append a kind=soak BENCH_LEDGER record")
    parser.add_argument("--keep_dir", action="store_true")
    args = parser.parse_args(argv)
    if args.selftest:
        return selftest()
    reports = []
    failures = 0
    for i in range(args.rounds):
        seed = args.seed + i
        try:
            rep = run_soak(
                seed=seed,
                total_records=args.records,
                batch_size=args.batch,
                stream_partitions=args.stream_partitions,
                barrier_every=args.barrier_every,
                keep_dir=args.keep_dir,
                ledger=args.ledger,
            )
            rep["ok"] = True
        except DrillError as e:
            failures += 1
            rep = {"seed": seed, "ok": False, "error": str(e)}
        print(json.dumps(rep))
        reports.append(rep)
    summary = {
        "rounds": args.rounds,
        "failures": failures,
        "reports": reports,
    }
    if args.json:
        with open(args.json, "w") as f:
            json.dump(summary, f, indent=2)
    print(
        f"stream soak: {args.rounds - failures}/{args.rounds} rounds ok"
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
