"""16-device pipe x fsdp x seq x tensor composition dryrun (VERDICT r4
weak #5 / next #5): the 7B/v5p-32 CI search emits mixed-parallel plans
(tests/test_accelerate.py::test_llama2_7b_plan_for_v5p32_in_ci), but
no mesh with ALL of pipe/fsdp/seq/tensor > 1 had ever been executed,
even virtually. This runs exactly that composition — the v5p-32 plan
family's mesh shape halved onto 16 virtual CPU devices (2x2x2x2),
scaled-down GPT dims — through TWO full 1F1B training steps:

* ``pipe``    — the 1F1B block-stack schedule (parallel/pipeline.py);
* ``fsdp``    — microbatch rows sharded, loss/grads pmean'd across it;
* ``seq``     — the TOKEN dimension sharded inside the schedule
                (models/pipeline_lm.py seq_axis) with ring attention
                called directly in the already-manual stage body;
* ``tensor``  — attention heads split per shard inside the stage
                (each tensor shard computes its head slice of the
                ring, all_gather'd back).

Ref analogue: atorch's mixed_parallel as an executable (not just
plannable) method, atorch/auto/opt_lib/optimization_library.py:38-56.

Run (fresh process — the device-count flag binds at first jax init):
    python -u tools/dryrun_7b_composition.py
Invoked as a subprocess by __graft_entry__.dryrun_multichip so the
driver's MULTICHIP artifact carries this phase's result line.
"""

from __future__ import annotations

import os
import sys

# 16 default (2x2x2x2); DRYRUN_DEVICES=32 doubles the fsdp axis for
# the full v5p-32 shape when wall-clock allows. Only these two shapes
# are derivable (fsdp = N/8, batch = 4*fsdp) — fail fast on others.
N_DEVICES = int(os.environ.get("DRYRUN_DEVICES", "16"))
if N_DEVICES not in (16, 32):
    raise SystemExit(
        f"DRYRUN_DEVICES must be 16 or 32, got {N_DEVICES}"
    )

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={N_DEVICES}"
    ).strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) < N_DEVICES:
        raise RuntimeError(
            f"need {N_DEVICES} virtual devices, got "
            f"{len(jax.devices())} — run in a fresh process"
        )
    import jax.numpy as jnp
    import optax

    from dlrover_tpu.models import gpt
    from dlrover_tpu.models.gpt_pipeline import (
        make_gpt_pipeline_step,
        shard_params_for_pipeline,
    )
    from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
    from dlrover_tpu.parallel.ring_attention import ring_attention

    fsdp = N_DEVICES // 8  # 2 at 16 devices, 4 at 32
    mesh = build_mesh(
        MeshConfig(pipe=2, fsdp=fsdp, seq=2, tensor=2),
        devices=jax.devices()[:N_DEVICES],
    )
    assert all(
        mesh.shape[a] > 1 for a in ("pipe", "fsdp", "seq", "tensor")
    ), dict(mesh.shape)

    # GPT-2 scaled down ~1000x: 4 layers over pipe=2 (v_chunks=1),
    # 8 heads split over tensor=2, 64-token blocks split over seq=2.
    cfg = gpt.GPTConfig(
        vocab_size=256,
        block_size=64,
        n_layer=4,
        n_head=8,
        n_embd=64,
        dtype=jnp.float32,
        remat=True,
    )

    def attn_fn(q, k, v):
        """Collective attention inside the pipeline's manual region:
        heads split over ``tensor`` (each shard runs its slice of the
        seq ring, outputs all_gather'd back — the stage weights are
        replicated over tensor, so only attention compute shards),
        sequence blocks over the ``seq`` ring."""
        tp = jax.lax.psum(1, "tensor")  # static axis size
        tidx = jax.lax.axis_index("tensor")
        hp = q.shape[2] // tp

        def sl(x):
            return jax.lax.dynamic_slice_in_dim(
                x, tidx * hp, hp, axis=2
            )

        o = ring_attention(
            sl(q), sl(k), sl(v), axis_name="seq", causal=True
        )
        return jax.lax.all_gather(o, "tensor", axis=2, tiled=True)

    optimizer = optax.adamw(1e-3)
    step = make_gpt_pipeline_step(
        mesh, cfg, optimizer, n_micro=4, attn_fn=attn_fn,
        batch_axes=("data", "fsdp"), seq_axis="seq",
    )
    params = shard_params_for_pipeline(
        mesh, gpt.init_params(jax.random.PRNGKey(0), cfg)
    )
    opt_state = optimizer.init(params)
    # Microbatch rows must split over data x fsdp: with n_micro=4,
    # batch = 4*fsdp gives mb = fsdp rows per microbatch.
    batch = 4 * fsdp
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, cfg.block_size), 0,
        cfg.vocab_size,
    )
    targets = jnp.roll(tokens, -1, axis=1)

    # Parity oracle: the same model/init/batch through the dense
    # single-program loss — the 4-axis composition must compute the
    # SAME objective, not merely a finite one.
    dense_params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    want = float(gpt.loss_fn(dense_params, tokens, targets, cfg=cfg))

    params, opt_state, metrics = step(params, opt_state, tokens, targets)
    loss1 = float(metrics["loss"])
    assert loss1 == loss1, "7B-composition loss is NaN"
    assert abs(loss1 - want) < 5e-3, (
        f"composition loss {loss1:.5f} != dense oracle {want:.5f}"
    )
    # Second step proves the updated (still-sharded) params re-enter
    # the compiled step — the full train-loop contract, not a one-off.
    params, opt_state, metrics = step(params, opt_state, tokens, targets)
    loss2 = float(metrics["loss"])
    assert loss2 == loss2 and loss2 < loss1, (loss1, loss2)
    print(
        f"dryrun 7b-composition ok: mesh={dict(mesh.shape)} "
        f"devices={N_DEVICES} loss={loss1:.4f}->{loss2:.4f} "
        f"(dense oracle {want:.4f})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
