"""Render a recovery timeline + metrics table from an obs JSONL trace.

Reads the event file the tracer exports (DLROVER_TPU_TRACE_FILE) —
e.g. the per-host traces the chaos drills leave behind — and prints:

* the reconstructed recovery timeline (obs/timeline.py), when the
  canonical trainer marks are present;
* an input-pipeline summary (data-wait vs staging vs train wall
  time) when trainer.prefetch_* events are present — the quick "is
  the prefetch pipeline hiding input staging" check
  (docs/PERFORMANCE.md);
* a per-event-name table: count, and for span events total/mean
  duration, sorted by total time.

Usage:
    python tools/obs_report.py TRACE.jsonl [--failure-ts T] [--top N]
    python tools/obs_report.py --selftest

``--selftest`` runs the reconstruction pipeline on a synthetic event
log and exits nonzero on any inconsistency — a fast CI smoke with no
inputs (invoked by tests/test_obs.py).
"""

from __future__ import annotations

import argparse
import sys

import _repo_path  # noqa: F401

from dlrover_tpu.obs.timeline import (
    REQUIRED_PHASES,
    load_events,
    reconstruct_recovery_timeline,
    render_timeline,
)


def metrics_table(events, top: int = 15) -> str:
    stats = {}
    for ev in events:
        name = ev.get("name", "?")
        count, total = stats.get(name, (0, 0.0))
        stats[name] = (count + 1, total + float(ev.get("dur_s", 0.0)))
    rows = sorted(
        stats.items(), key=lambda kv: (-kv[1][1], -kv[1][0])
    )[:top]
    lines = [
        f"top {len(rows)} event names (of {len(stats)}):",
        f"  {'event':<32} {'count':>7} {'total_s':>9} {'mean_s':>9}",
    ]
    for name, (count, total) in rows:
        mean = total / count if count else 0.0
        lines.append(
            f"  {name:<32} {count:>7} {total:>9.3f} {mean:>9.4f}"
        )
    return "\n".join(lines)


def input_pipeline_summary(events) -> str:
    """Data-wait vs step time from the trainer.prefetch_* events.

    ``trainer.prefetch_wait`` carries how long the train loop blocked
    on the input queue per batch; ``trainer.prefetch_stage`` carries
    the worker-side collate+H2D staging cost the pipeline is hiding.
    With ``trainer.step`` events present, the wait is also put in
    proportion to the training wall time. Returns "" when the trace
    has no prefetch events (prefetch off or pre-pipeline trace).
    """
    waits = [
        float(e.get("dur_s", 0.0))
        for e in events
        if e.get("name") == "trainer.prefetch_wait"
    ]
    stages = [
        float(e.get("dur_s", 0.0))
        for e in events
        if e.get("name") == "trainer.prefetch_stage"
    ]
    if not waits and not stages:
        return ""
    lines = ["input pipeline (trainer.prefetch_*):"]
    wait_total = sum(waits)
    stage_total = sum(stages)
    if waits:
        lines.append(
            f"  data-wait : {wait_total:9.3f}s total over "
            f"{len(waits)} batches (mean {wait_total / len(waits):.4f}s)"
        )
    if stages:
        lines.append(
            f"  staging   : {stage_total:9.3f}s total over "
            f"{len(stages)} batches (mean "
            f"{stage_total / len(stages):.4f}s, overlapped with compute)"
        )
    if waits and stages:
        lines.append(
            f"  hidden    : {max(stage_total - wait_total, 0.0):9.3f}s "
            "of staging overlapped behind compute"
        )
    step_ts = sorted(
        e["ts"]
        for e in events
        if e.get("name") == "trainer.step" and "ts" in e
    )
    if waits and len(step_ts) >= 2:
        span = step_ts[-1] - step_ts[0]
        if span > 0:
            lines.append(
                f"  train span: {span:9.3f}s across "
                f"{len(step_ts)} steps -> data-wait is "
                f"{100.0 * wait_total / span:.1f}% of wall time"
            )
    return "\n".join(lines)


def report(path: str, failure_ts=None, top: int = 15) -> int:
    events = [e for e in load_events(path) if "ts" in e]
    if not events:
        print(f"no events in {path}")
        return 1
    t0 = min(e["ts"] for e in events)
    t1 = max(e["ts"] for e in events)
    print(
        f"{len(events)} events over {t1 - t0:.1f}s from {path}"
    )
    tl = reconstruct_recovery_timeline(events, t_failure=failure_ts)
    if tl is not None:
        print()
        print(render_timeline(tl))
    pipeline = input_pipeline_summary(events)
    if pipeline:
        print()
        print(pipeline)
    print()
    print(metrics_table(events, top=top))
    return 0


def selftest() -> int:
    """Hermetic check of the reconstruction pipeline on synthetic
    events shaped like a real drill trace."""
    t = 1000.0
    events = [
        {"name": "node.heartbeat_timeout", "ts": t, "node_id": 1},
        {"name": "trainer.proc_start", "ts": t + 4.0},
        {"name": "trainer.dist_ready", "ts": t + 10.0},
        {"name": "trainer.built", "ts": t + 25.0},
        {"name": "trainer.restore_done", "ts": t + 27.5},
        {"name": "trainer.first_step_done", "ts": t + 40.0},
        {"name": "trainer.step", "ts": t + 41.0, "step": 11},
        {"name": "trainer.throughput_recovered", "ts": t + 45.0},
        # input pipeline shaped like a healthy prefetch: staging cost
        # per batch is high, consumer wait is near zero
        {"name": "trainer.prefetch_start", "ts": t + 40.0, "depth": 2},
        {"name": "trainer.prefetch_stage", "ts": t + 40.1,
         "dur_s": 0.5},
        {"name": "trainer.prefetch_stage", "ts": t + 40.7,
         "dur_s": 0.5},
        {"name": "trainer.prefetch_wait", "ts": t + 41.0,
         "dur_s": 0.01},
        {"name": "trainer.prefetch_wait", "ts": t + 42.0,
         "dur_s": 0.03},
        {"name": "trainer.step", "ts": t + 43.0, "step": 12},
        {"name": "trainer.prefetch_stop", "ts": t + 45.0,
         "delivered": 2, "dropped": 0},
    ]
    tl = reconstruct_recovery_timeline(events)
    errors = []
    if tl is None:
        errors.append("reconstruction returned None")
    else:
        if not tl.complete:
            errors.append(f"timeline incomplete: {tl.phases}")
        for name in REQUIRED_PHASES:
            dur = tl.phases.get(name)
            if dur is None or dur <= 0:
                errors.append(f"phase {name} not positive: {dur}")
        expect = {
            "failure-detect": 4.0,
            "rendezvous": 6.0,
            "build": 15.0,
            "restore": 2.5,
            "first-step": 12.5,
            "throughput-90": 5.0,
        }
        for name, want in expect.items():
            got = tl.phases.get(name)
            if got is None or abs(got - want) > 1e-6:
                errors.append(f"phase {name}: want {want}, got {got}")
        if abs(tl.total_s - 45.0) > 1e-6:
            errors.append(f"total_s: want 45.0, got {tl.total_s}")
        render_timeline(tl)  # must not raise
        metrics_table(events)
        pipeline = input_pipeline_summary(events)
        if "data-wait" not in pipeline:
            errors.append(f"no data-wait line in: {pipeline!r}")
        if "0.040s total over 2 batches" not in pipeline:
            errors.append(f"wrong wait total in: {pipeline!r}")
        if "1.000s total over 2 batches" not in pipeline:
            errors.append(f"wrong stage total in: {pipeline!r}")
        if "0.960s" not in pipeline:  # hidden = stage - wait
            errors.append(f"wrong hidden time in: {pipeline!r}")
        if "data-wait is 2.0% of wall time" not in pipeline:
            errors.append(f"wrong wall fraction in: {pipeline!r}")
        if input_pipeline_summary(
            [e for e in events if "prefetch" not in e["name"]]
        ):
            errors.append("pipeline summary not empty without events")
    if errors:
        print("obs selftest FAILED:")
        for e in errors:
            print(f"  - {e}")
        return 1
    print("obs selftest ok")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser("obs_report")
    p.add_argument("event_file", nargs="?", default="")
    p.add_argument(
        "--failure-ts", type=float, default=None,
        help="failure instant (unix time); derived from master-side "
        "node.fail/node.gone events when omitted",
    )
    p.add_argument("--top", type=int, default=15)
    p.add_argument(
        "--selftest", action="store_true",
        help="run the reconstruction pipeline on synthetic events",
    )
    args = p.parse_args(argv)
    if args.selftest:
        return selftest()
    if not args.event_file:
        p.error("event_file is required (or pass --selftest)")
    return report(args.event_file, args.failure_ts, args.top)


if __name__ == "__main__":
    sys.exit(main())
