"""Render a recovery timeline + metrics table from an obs JSONL trace.

Reads the event file the tracer exports (DLROVER_TPU_TRACE_FILE) —
e.g. the per-host traces the chaos drills leave behind — and prints:

* the reconstructed recovery timeline (obs/timeline.py), when the
  canonical trainer marks are present;
* an input-pipeline summary (data-wait vs staging vs train wall
  time) when trainer.prefetch_* events are present — the quick "is
  the prefetch pipeline hiding input staging" check
  (docs/PERFORMANCE.md);
* with ``--goodput``: the exhaustive wall-time attribution
  (productive / compile / data_wait / checkpoint / recovery /
  idle_unknown, obs/goodput.py) over the trace window;
* a per-event-name table: count, and for span events total/mean
  duration, sorted by total time.

``--health TARGET`` renders the master's fleet-health plane (score,
active verdicts with their evidence windows, transition history —
obs/health.py) from either a live master (``host:port``, via the
``HealthQueryRequest`` RPC) or a JSON snapshot file
(``HealthMonitor.snapshot()`` shaped). Exits 1 when a critical
verdict is active, so scripts can gate on it like the /healthz
probe.

``--capacity TARGET`` renders the pool's capacity accounting plane
(per-tenant chip-second ledger, goodput-per-chip, preemption /
restore overhead, SLO error budgets with burn-rate alerts —
obs/capacity.py + the slo_burn detector in obs/health.py) from a
live pool master (``host:port``, via the ``CapacityQueryRequest``
RPC) or a JSON snapshot file (``CapacityLedger.snapshot()`` shaped,
optionally with an attached ``slo`` block). Exits 1 while any
tenant's error budget is burning.

``--postmortem DIR`` instead renders a forensics dir (the flight
recorder's ``bundle_*.json`` black-box bundles + ``stacks_*.txt``
faulthandler dumps + any ``*.jsonl`` traces, obs/postmortem.py) into
the "last 60 seconds before failure" report: failure instant,
windowed event tail, recovery timeline + goodput over the window,
then every bundle's per-thread Python stacks and each stacks file's
final dump.

Exit codes — every probe section follows the same contract so
scripts and cron gates can treat any section uniformly:

    rc  meaning                 examples
    --  ----------------------  ------------------------------------
    0   probe passed            healthy fleet; no unhealthy replica;
                                no failed pool job; budgets intact
    1   probe FAILED            --health: critical / failing-
                                probation / unknown-remediation
                                verdict active; --serving: unhealthy
                                replica; --pool: failed job;
                                --capacity: SLO budget burning;
                                --trace: key not found
    2   target unreachable      snapshot file missing, RPC refused /
                                timed out — the probe itself could
                                not run (distinct from "ran and
                                failed" so alerting can separate
                                outage-of-signal from bad signal)

Usage:
    python tools/obs_report.py TRACE.jsonl [--failure-ts T] [--top N]
    python tools/obs_report.py TRACE.jsonl --goodput
    python tools/obs_report.py --health 127.0.0.1:8001
    python tools/obs_report.py --health health_snapshot.json
    python tools/obs_report.py --capacity 127.0.0.1:8001
    python tools/obs_report.py --postmortem /tmp/dlrover_tpu_forensics_job
    python tools/obs_report.py --selftest

``--selftest`` runs the reconstruction + goodput + fleet-aggregation
+ postmortem pipelines on synthetic events/snapshots/bundles and
exits nonzero on any inconsistency — a fast CI smoke with no inputs
(invoked by tests/test_obs.py).
"""

from __future__ import annotations

import argparse
import sys

import _repo_path  # noqa: F401

from dlrover_tpu.obs.goodput import (
    attribute_goodput,
    render_goodput,
)
from dlrover_tpu.obs.timeline import (
    REQUIRED_PHASES,
    load_events,
    reconstruct_recovery_timeline,
    render_timeline,
)


def metrics_table(events, top: int = 15) -> str:
    stats = {}
    for ev in events:
        name = ev.get("name", "?")
        count, total = stats.get(name, (0, 0.0))
        stats[name] = (count + 1, total + float(ev.get("dur_s", 0.0)))
    rows = sorted(
        stats.items(), key=lambda kv: (-kv[1][1], -kv[1][0])
    )[:top]
    lines = [
        f"top {len(rows)} event names (of {len(stats)}):",
        f"  {'event':<32} {'count':>7} {'total_s':>9} {'mean_s':>9}",
    ]
    for name, (count, total) in rows:
        mean = total / count if count else 0.0
        lines.append(
            f"  {name:<32} {count:>7} {total:>9.3f} {mean:>9.4f}"
        )
    return "\n".join(lines)


def input_pipeline_summary(events) -> str:
    """Data-wait vs step time from the trainer.prefetch_* events.

    ``trainer.prefetch_wait`` carries how long the train loop blocked
    on the input queue per batch; ``trainer.prefetch_stage`` carries
    the worker-side collate+H2D staging cost the pipeline is hiding.
    With ``trainer.step`` events present, the wait is also put in
    proportion to the training wall time. Returns "" when the trace
    has no prefetch events (prefetch off or pre-pipeline trace).
    """
    wait_events = [
        e for e in events if e.get("name") == "trainer.prefetch_wait"
    ]
    waits = [float(e.get("dur_s", 0.0)) for e in wait_events]
    stages = [
        float(e.get("dur_s", 0.0))
        for e in events
        if e.get("name") == "trainer.prefetch_stage"
    ]
    h2ds = [
        float(e.get("dur_s", 0.0))
        for e in events
        if e.get("name") == "trainer.prefetch_h2d"
    ]
    if not waits and not stages and not h2ds:
        return ""
    lines = ["input pipeline (trainer.prefetch_*):"]
    wait_total = sum(waits)
    stage_total = sum(stages)
    h2d_total = sum(h2ds)
    if waits:
        lines.append(
            f"  data-wait : {wait_total:9.3f}s total over "
            f"{len(waits)} batches (mean {wait_total / len(waits):.4f}s)"
        )
        # Host-wait vs H2D-stage split (carried per wait event since
        # the device-resident pipeline): where the blocked time went.
        host_w = sum(float(e.get("host_s", 0.0)) for e in wait_events)
        h2d_w = sum(float(e.get("h2d_s", 0.0)) for e in wait_events)
        if host_w or h2d_w:
            lines.append(
                f"  wait split: host {host_w:.3f}s / "
                f"h2d {h2d_w:.3f}s"
            )
    if stages:
        lines.append(
            f"  staging   : {stage_total:9.3f}s total over "
            f"{len(stages)} batches (mean "
            f"{stage_total / len(stages):.4f}s, overlapped with compute)"
        )
    if h2ds:
        lines.append(
            f"  h2d stage : {h2d_total:9.3f}s total over "
            f"{len(h2ds)} batches (mean "
            f"{h2d_total / len(h2ds):.4f}s)"
        )
    if waits and (stages or h2ds):
        lines.append(
            f"  hidden    : "
            f"{max(stage_total + h2d_total - wait_total, 0.0):9.3f}s "
            "of staging overlapped behind compute"
        )
    step_ts = sorted(
        e["ts"]
        for e in events
        if e.get("name") == "trainer.step" and "ts" in e
    )
    if waits and len(step_ts) >= 2:
        span = step_ts[-1] - step_ts[0]
        if span > 0:
            lines.append(
                f"  train span: {span:9.3f}s across "
                f"{len(step_ts)} steps -> data-wait is "
                f"{100.0 * wait_total / span:.1f}% of wall time"
            )
    return "\n".join(lines)


def perf_summary(events) -> str:
    """Step-phase / compile / MFU summary from the profiler's trace
    events (``trainer.step_phases`` per step, ``trainer.compile`` per
    detected (re)compile, ``trainer.profile_done`` per on-demand
    capture). Returns "" when the trace carries none — a pre-profiler
    trace renders exactly as before."""
    rows = [e for e in events if e.get("name") == "trainer.step_phases"]
    compiles = [e for e in events if e.get("name") == "trainer.compile"]
    captures = [
        e for e in events if e.get("name") == "trainer.profile_done"
    ]
    if not rows and not compiles:
        return ""
    lines = ["step phases (trainer.step_phases):"]
    if rows:
        wall = sum(float(e.get("wall_s", 0.0)) for e in rows)
        lines.append(
            f"  {len(rows)} steps, {wall:.3f}s wall"
            + (
                f", mean step {wall / len(rows):.4f}s"
                if rows
                else ""
            )
        )
        lines.append(
            f"  {'phase':<16} {'total_s':>9} {'mean_s':>9} {'% wall':>7}"
        )
        for phase, key in (
            ("data_wait", "data_wait_s"),
            ("h2d_stage", "h2d_s"),
            ("compile", "compile_s"),
            ("dispatch", "dispatch_s"),
            ("device_execute", "device_s"),
        ):
            total = sum(float(e.get(key, 0.0)) for e in rows)
            pct = 100.0 * total / wall if wall > 0 else 0.0
            lines.append(
                f"  {phase:<16} {total:>9.3f} "
                f"{total / len(rows):>9.4f} {pct:>6.1f}%"
            )
        mfus = [
            float(e["mfu"]) for e in rows if e.get("mfu") is not None
        ]
        if mfus:
            lines.append(
                f"  mfu: last {mfus[-1]:.4f} over {len(mfus)} samples"
            )
    if compiles:
        by_fn = {}
        for e in compiles:
            fn = str(e.get("fn", "?"))
            count, total = by_fn.get(fn, (0, 0.0))
            by_fn[fn] = (count + 1, total + float(e.get("dur_s", 0.0)))
        parts = ", ".join(
            f"{fn} x{c} ({t:.2f}s)"
            for fn, (c, t) in sorted(by_fn.items())
        )
        lines.append(f"  compiles: {parts}")
    for e in captures:
        lines.append(
            f"  profile capture: {e.get('steps')} steps"
            f" (request {e.get('request_id') or '-'}"
            + (
                f", mfu {e['mfu']}"
                if e.get("mfu") is not None
                else ""
            )
            + ")"
        )
    return "\n".join(lines)


def report(
    path: str, failure_ts=None, top: int = 15, goodput: bool = False,
    perf: bool = False,
) -> int:
    events = [e for e in load_events(path) if "ts" in e]
    if not events:
        print(f"no events in {path}")
        return 1
    t0 = min(e["ts"] for e in events)
    t1 = max(e["ts"] for e in events)
    print(
        f"{len(events)} events over {t1 - t0:.1f}s from {path}"
    )
    tl = reconstruct_recovery_timeline(events, t_failure=failure_ts)
    if tl is not None:
        print()
        print(render_timeline(tl))
    pipeline = input_pipeline_summary(events)
    if pipeline:
        print()
        print(pipeline)
    if perf:
        summary = perf_summary(events)
        print()
        print(summary or "no perf events (trainer.step_phases) in trace")
    if goodput:
        gp = attribute_goodput(events)
        if gp is not None:
            print()
            print(render_goodput(gp))
    print()
    print(metrics_table(events, top=top))
    return 0


def health_report(target: str) -> int:
    """Render the fleet-health plane (and the remediation engine's
    decision history) from a live master (host:port,
    HealthQueryRequest + RemediationQueryRequest RPCs) or a JSON
    snapshot file (optionally carrying a ``remediation`` key).
    Returns 1 when a critical verdict is active OR a remediation
    probation window is currently failing (probe semantics), else
    0."""
    import dataclasses
    import json
    import os

    from dlrover_tpu.master.remediation import render_remediation
    from dlrover_tpu.obs.health import SEVERITY_CRITICAL, render_health

    remediation_unknown = False
    if os.path.isfile(target):
        with open(target) as f:
            payload = json.load(f)
    elif (
        target.endswith(".json")
        or os.sep in target
        or ":" not in target
    ):
        # Looks like a snapshot path, not host:port — a typo'd file
        # name must fail fast, not hang in gRPC connect retries
        # against a nonsense address.
        print(f"health snapshot not found: {target}", file=sys.stderr)
        return 2
    else:
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient(target, node_id=-1)
        try:
            # Probe semantics: a down master must fail fast, not
            # ride out the supervisor's full reconnect budget.
            resp = client.query_health(
                include_history=True, max_wait=15.0
            )
        except Exception as exc:  # noqa: BLE001
            print(
                f"health query to {target} failed: {exc}",
                file=sys.stderr,
            )
            return 2
        payload = {
            "score": resp.score,
            "active": [
                dataclasses.asdict(v) for v in resp.verdicts
            ],
            "history": [
                dataclasses.asdict(v) for v in resp.history
            ],
        }
        try:
            rem = client.query_remediation(max_wait=15.0)
            payload["remediation"] = {
                "enabled": rem.enabled,
                "dry_run": rem.dry_run,
                "cordoned": list(rem.cordoned),
                "probation_failing": rem.probation_failing,
                "decisions": [
                    dataclasses.asdict(d) for d in rem.decisions
                ],
            }
        except Exception as e:  # noqa: BLE001
            if (
                "no get handler" in str(e)
                or "unknown message" in str(e)
            ):
                # Genuinely pre-remediation master (older wire
                # schema): its health plane still renders and the
                # probe follows the health verdicts alone.
                print(
                    "warning: master predates the remediation "
                    f"RPC: {e}",
                    file=sys.stderr,
                )
            else:
                # A remediation-CAPABLE master failed the query
                # (timeout, transient RPC error): the probe must NOT
                # read healthy — a failing probation could be hiding
                # behind the failure, and the documented exit-1
                # contract would be silently broken.
                print(
                    f"error: remediation query failed: {e}",
                    file=sys.stderr,
                )
                remediation_unknown = True
    print(render_health(payload))
    remediation = payload.get("remediation")
    if remediation is not None:
        print()
        print(render_remediation(remediation))
    critical = sum(
        1
        for v in payload.get("active", [])
        if v.get("severity") == SEVERITY_CRITICAL
    )
    probation_failing = bool(
        (remediation or {}).get("probation_failing")
    )
    return (
        1 if critical or probation_failing or remediation_unknown
        else 0
    )


def serving_report(target: str) -> int:
    """Render the serving plane (router request counters, per-replica
    TTFT/TPOT/queue/KV stats, unhealthy replicas) from a live master
    (host:port, ``ServeQueryRequest`` RPC) or a JSON snapshot file
    (``ServingRouter.snapshot()`` shaped). Exits 1 when any replica is
    currently unhealthy (probe semantics, like ``--health``)."""
    import json
    import os

    from dlrover_tpu.serving.router import render_serving

    if os.path.isfile(target):
        with open(target) as f:
            payload = json.load(f)
    elif (
        target.endswith(".json")
        or os.sep in target
        or ":" not in target
    ):
        print(
            f"serving snapshot not found: {target}", file=sys.stderr
        )
        return 2
    else:
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient(target, node_id=-1)
        try:
            resp = client.query_serving(max_wait=15.0)
        except Exception as exc:  # noqa: BLE001
            print(
                f"serving query to {target} failed: {exc}",
                file=sys.stderr,
            )
            return 2
        finally:
            client.close()
        if not resp.enabled:
            print("serving plane disabled on this master")
            return 0
        payload = resp.snapshot
    print(render_serving(payload))
    return 1 if payload.get("unhealthy") else 0


def pool_report(target: str) -> int:
    """Render the multi-job pool plane (queue depth per priority
    band, per-tenant quota usage, slice utilization, preemption
    counts, wait-time percentiles) from a live pool master
    (host:port, ``PoolQueryRequest`` RPC) or a JSON snapshot file
    (``PoolScheduler.snapshot()`` shaped)."""
    import json
    import os

    from dlrover_tpu.pool.scheduler import render_pool

    if os.path.isfile(target):
        with open(target) as f:
            payload = json.load(f)
    elif (
        target.endswith(".json")
        or os.sep in target
        or ":" not in target
    ):
        print(
            f"pool snapshot not found: {target}", file=sys.stderr
        )
        return 2
    else:
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient(target, node_id=-1)
        try:
            resp = client.query_pool(max_wait=15.0)
        except Exception as exc:  # noqa: BLE001
            print(
                f"pool query to {target} failed: {exc}",
                file=sys.stderr,
            )
            return 2
        finally:
            client.close()
        if not resp.enabled:
            print("pool plane disabled on this master")
            return 0
        payload = resp.snapshot
    print(render_pool(payload))
    failed = [
        jid
        for jid, j in (payload.get("jobs") or {}).items()
        if j.get("state") == "failed"
    ]
    return 1 if failed else 0


def capacity_report(target: str) -> int:
    """Render the pool capacity plane (per-tenant chip-second
    ledger, goodput-per-chip, overhead, SLO error budgets) from a
    live pool master (host:port, ``CapacityQueryRequest`` RPC) or a
    JSON snapshot file (``CapacityLedger.snapshot()`` shaped).
    Exits 1 while any tenant's error budget is burning."""
    import json
    import os

    from dlrover_tpu.obs.capacity import render_capacity

    if os.path.isfile(target):
        with open(target) as f:
            payload = json.load(f)
    elif (
        target.endswith(".json")
        or os.sep in target
        or ":" not in target
    ):
        print(
            f"capacity snapshot not found: {target}", file=sys.stderr
        )
        return 2
    else:
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient(target, node_id=-1)
        try:
            resp = client.query_capacity(max_wait=15.0)
        except Exception as exc:  # noqa: BLE001
            print(
                f"capacity query to {target} failed: {exc}",
                file=sys.stderr,
            )
            return 2
        finally:
            client.close()
        if not resp.enabled:
            print("capacity plane disabled on this master")
            return 0
        payload = resp.snapshot
    print(render_capacity(payload))
    budgets = (payload.get("slo") or {}).get("budgets") or []
    burning = [b for b in budgets if b.get("burning")]
    return 1 if burning else 0


def stall_report(target: str) -> int:
    """Render the stall-localization plane (per-host progress-beacon
    table, open/recent ``collective_stall`` incidents with culprit,
    trace id, and coordinated-capture bundle paths) from a live
    master (host:port, ``StallQueryRequest`` RPC) or a JSON snapshot
    file (``StallCorrelator.snapshot()`` shaped). Exits 1 while an
    incident is open — a paging surface, like --capacity's burning
    budgets."""
    import json
    import os

    from dlrover_tpu.obs.stall import render_stall

    if os.path.isfile(target):
        with open(target) as f:
            payload = json.load(f)
    elif (
        target.endswith(".json")
        or os.sep in target
        or ":" not in target
    ):
        print(
            f"stall snapshot not found: {target}", file=sys.stderr
        )
        return 2
    else:
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient(target, node_id=-1)
        try:
            resp = client.query_stall(max_wait=15.0)
        except Exception as exc:  # noqa: BLE001
            print(
                f"stall query to {target} failed: {exc}",
                file=sys.stderr,
            )
            return 2
        finally:
            client.close()
        if not resp.enabled:
            print("stall plane disabled on this master")
            return 0
        payload = resp.snapshot
    print(render_stall(payload))
    return 1 if payload.get("incident") else 0


def trace_report(key: str, target: str) -> int:
    """Render causal trace timelines for ``key`` — a trace id, a
    serving request id, or a node subject (``node:<id>`` or a bare
    node id) — from a live master (host:port, ``TraceQueryRequest``
    RPC) or a JSON file of trace-store timelines. The span tree is
    indented by causality with per-span durations; requeue hops and
    remediation rungs are summarized per trace."""
    import json
    import os

    from dlrover_tpu.obs.trace_store import render_trace

    def _matches(tl: dict) -> bool:
        subjects = set(tl.get("subjects", ()))
        return (
            tl.get("trace_id") == key
            or key in subjects
            or f"node:{key}" in subjects
        )

    if os.path.isfile(target):
        with open(target) as f:
            doc = json.load(f)
        timelines = doc.get("traces", doc) if isinstance(
            doc, dict
        ) else doc
        timelines = [tl for tl in timelines if _matches(tl)]
    elif (
        target.endswith(".json")
        or os.sep in target
        or ":" not in target
    ):
        print(f"trace snapshot not found: {target}", file=sys.stderr)
        return 2
    else:
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient(target, node_id=-1)
        try:
            resp = client.query_traces(trace_id=key, max_wait=15.0)
            if resp.enabled and not resp.traces:
                # Not a trace id: try it as a subject (request id /
                # node) — bare node ids get the node: prefix form too.
                resp = client.query_traces(subject=key, max_wait=15.0)
                if not resp.traces and key.isdigit():
                    resp = client.query_traces(
                        subject=f"node:{key}", max_wait=15.0
                    )
        except Exception as exc:  # noqa: BLE001
            print(
                f"trace query to {target} failed: {exc}",
                file=sys.stderr,
            )
            return 2
        finally:
            client.close()
        if not resp.enabled:
            print("trace store disabled on this master")
            return 0
        timelines = list(resp.traces)
    if not timelines:
        print(f"no trace found for {key!r}")
        return 1
    for tl in timelines:
        print(render_trace(tl))
        names = [s.get("name", "") for s in tl.get("spans", ())]
        hops = sum(1 for n in names if n == "serve.hop")
        requeues = sum(1 for n in names if n == "serve.requeue")
        rungs = sorted(
            {
                n.split(".", 1)[1]
                for n in names
                if n.startswith("remediation.")
                and n not in (
                    "remediation.decision", "remediation.verdict",
                    "remediation.governors",
                )
            }
        )
        summary = []
        if hops:
            summary.append(
                f"{hops} replica hop(s), {requeues} requeue(s)"
            )
        if rungs:
            summary.append(f"remediation: {' -> '.join(rungs)}")
        if summary:
            print("  -- " + "; ".join(summary))
    return 0


def _selftest_trace() -> list:
    """Trace assembly hermetically: a synthetic serving-request
    timeline (two hops, phase spans) plus a remediation decision
    trace must render as an indented causal tree through the same
    path ``--trace`` uses."""
    import json as _json
    import tempfile

    from dlrover_tpu.obs.trace_store import (
        TraceStore,
        render_trace,
        span_tree,
    )

    errors = []
    store = TraceStore()
    t = 2000.0
    tid, root = "t" * 32, "r" * 16
    store.add_span(
        tid, "serve.request", t, 3.0, span_id=root,
        request_id="req-1", requeues=1, outcome="done",
    )
    store.add_span(
        tid, "serve.queue", t, 0.1, parent_span_id=root,
        request_id="req-1", hop=0,
    )
    store.add_span(
        tid, "serve.hop", t + 0.1, 1.0, span_id="h" * 16,
        parent_span_id=root, request_id="req-1",
        replica_id=4000000, end="requeue",
    )
    store.add_span(
        tid, "serve.hop", t + 1.3, 1.7, span_id="g" * 16,
        parent_span_id=root, request_id="req-1",
        replica_id=4000001, end="done",
    )
    for i, (name, dur) in enumerate(
        (
            ("serve.dispatch", 0.1),
            ("serve.prefill", 0.5),
            ("serve.first_token", 0.05),
            ("serve.decode", 1.0),
        )
    ):
        store.add_span(
            tid, name, t + 1.35 + 0.4 * i, dur,
            parent_span_id="g" * 16, request_id="req-1",
        )
    dec_tid = "d" * 32
    store.add_span(
        dec_tid, "remediation.decision", t + 1.0,
        span_id="q" * 16, node_id=4000000, decision_id=1,
    )
    store.add_span(
        dec_tid, "remediation.verdict", t + 1.0,
        parent_span_id="q" * 16, node_id=4000000,
        detector="replica_unhealthy",
    )
    store.add_span(
        dec_tid, "remediation.drain_replica", t + 1.1,
        parent_span_id="q" * 16, node_id=4000000,
    )
    store.add_span(
        dec_tid, "serve.requeue", t + 1.1,
        parent_span_id="q" * 16, request_id="req-1",
        link_trace_id=tid,
    )
    tl = store.get(tid)
    if tl is None:
        return ["trace store lost the request trace"]
    tree = span_tree(tl)
    if tree[0]["name"] != "serve.request" or tree[0]["depth"] != 0:
        errors.append(f"tree root wrong: {tree[0]}")
    depths = {s["name"]: s["depth"] for s in tree}
    if depths.get("serve.hop") != 1 or depths.get(
        "serve.prefill"
    ) != 2:
        errors.append(f"tree depths wrong: {depths}")
    rendered = render_trace(tl)
    for needle in ("serve.request", "serve.hop", "req-1"):
        if needle not in rendered:
            errors.append(
                f"trace render missing {needle!r}: {rendered!r}"
            )
    # query surfaces: by subject (request id and node form)
    if not store.query(subject="req-1"):
        errors.append("subject query by request id found nothing")
    if [
        x["trace_id"] for x in store.query(subject="node:4000000")
    ] != [tid, dec_tid]:
        errors.append("subject query by node wrong")
    # file path end to end (the --trace target contract)
    with tempfile.NamedTemporaryFile(
        "w", suffix=".json", delete=False
    ) as f:
        _json.dump({"traces": store.query()}, f)
        path = f.name
    try:
        if trace_report(tid, path) != 0:
            errors.append("trace_report rc != 0 on the request trace")
        if trace_report("node:4000000", path) != 0:
            errors.append("trace_report rc != 0 on the node subject")
        if trace_report("missing", path) != 1:
            errors.append("trace_report rc != 1 on an unknown key")
    finally:
        import os as _os

        _os.unlink(path)
    # bounded retention: the store must evict oldest-first
    small = TraceStore(max_traces=3)
    for i in range(10):
        small.add_span(f"trace-{i}", "serve.request", float(i), 1.0)
    if len(small) != 3 or small.get("trace-0") is not None:
        errors.append("trace retention not bounded")
    return errors


def _selftest_serving() -> list:
    """Serving plane hermetically: a fake-clock router over two
    replicas — one serving, one stalling mid-flight — must requeue
    the stalled replica's work on drain, flag it unhealthy, and
    render counters/percentiles via the same path ``--serving``
    uses."""
    import json as _json
    import tempfile

    from dlrover_tpu.serving.router import ServingRouter, render_serving

    errors = []
    clk = [1000.0]
    router = ServingRouter(
        clock=lambda: clk[0],
        config={"progress_timeout_s": 5.0, "latency_window": 64},
    )
    router.register_replica(100, addr="rep-a")
    router.register_replica(101, addr="rep-b")
    rids = [
        router.submit([1, 2, 3], max_new_tokens=4) for _ in range(4)
    ]
    if any(r is None for r in rids):
        errors.append(f"submit rejected: {rids}")
    a = router.pull(100, max_items=2)
    b = router.pull(101, max_items=2)
    if len(a) != 2 or len(b) != 2:
        errors.append(f"pull sizes wrong: {len(a)}, {len(b)}")
    clk[0] += 1.0
    for req in a:
        router.complete(
            100, req.request_id, [7, 8, 9, 10],
            ttft_s=0.2, tpot_s=0.01, finish_reason="length",
        )
    # rep-b stalls holding 2 requests past the progress timeout.
    clk[0] += 6.0
    unhealthy = router.unhealthy_replicas()
    if [u["replica_id"] for u in unhealthy] != [101]:
        errors.append(f"unhealthy detection wrong: {unhealthy}")
    requeued = router.drain_replica(101, reason="selftest")
    if requeued != 2:
        errors.append(f"drain requeued {requeued}, want 2")
    redispatch = router.pull(100, max_items=4)
    if len(redispatch) != 2:
        errors.append(
            f"survivor re-pulled {len(redispatch)}, want 2"
        )
    for req in redispatch:
        router.complete(
            100, req.request_id, [1, 1, 1, 1],
            ttft_s=0.3, tpot_s=0.02, finish_reason="length",
            phases={
                "dispatch": 0.05, "prefill": 0.25,
                "first_decode": 0.05, "decode": 0.4,
            },
        )
    counters = router.counters()
    if counters["done"] != 4 or counters["requeued_total"] != 2:
        errors.append(f"counters wrong: {counters}")
    for rid in rids:
        rec = router.result(rid)
        if rec is None or rec["state"] != "done":
            errors.append(f"request {rid} not done: {rec}")
    # Late duplicate from the drained replica: dropped, first wins.
    if router.complete(101, rids[-1], [9, 9, 9, 9]):
        errors.append("late duplicate completion was accepted")
    router.report_stats(
        100,
        {
            "queue_depth": 1, "active": 2, "tokens_generated": 16,
            "ttft_p99_s": 0.25, "tpot_p50_s": 0.015,
            "kv": {"utilization": 0.5},
        },
    )
    snapshot = router.snapshot()
    rendered = render_serving(snapshot)
    for needle in (
        "4 done",
        "2 requeue(s)",
        "replica 100",
        "replica 101",
        "[UNHEALTHY]",
        "kv 50%",
        "UNHEALTHY replicas: [101]",
        # The worst-trace TTFT breakdown: queue covers the 7s the
        # requeued requests waited across the stall + drain.
        "worst TTFT",
        "dispatch 0.050s",
        "prefill 0.250s",
        "1 requeue(s)",
    ):
        if needle not in rendered:
            errors.append(
                f"serving render missing {needle!r}: {rendered!r}"
            )
    # The --serving file path end to end: snapshot -> JSON -> report,
    # rc 1 while the drained replica is still unhealthy.
    with tempfile.NamedTemporaryFile(
        "w", suffix=".json", delete=False
    ) as f:
        _json.dump(snapshot, f)
        path = f.name
    try:
        if serving_report(path) != 1:
            errors.append(
                "serving_report rc != 1 with an unhealthy replica"
            )
        # The replica re-registers (fresh process): healthy again.
        router.register_replica(101, addr="rep-b")
        with open(path, "w") as f:
            _json.dump(router.snapshot(), f)
        if serving_report(path) != 0:
            errors.append(
                "serving_report rc != 0 after replica recovery"
            )
    finally:
        import os as _os

        _os.unlink(path)
    errors.extend(_selftest_serving_disagg())
    return errors


def _selftest_serving_disagg() -> list:
    """Disaggregated snapshot hermetically: a prefill + decode fleet
    moves one request through prefilling -> handoff -> decoding ->
    done, and the ``--serving`` renderer shows the per-role rows,
    the staged-handoff queue, and per-role KV utilization."""
    import numpy as np

    from dlrover_tpu.serving import handoff as hmod
    from dlrover_tpu.serving.router import (
        ServingRouter,
        render_serving,
    )

    errors = []
    clk = [2000.0]
    router = ServingRouter(
        clock=lambda: clk[0],
        config={"progress_timeout_s": 5.0},
    )
    router.register_replica(200, addr="pre-a", role="prefill")
    router.register_replica(201, addr="dec-a", role="decode")
    rid = router.submit([1, 2, 3], max_new_tokens=4)
    if router.pull(201, max_items=1) != []:
        errors.append("decode replica was fed a raw prompt")
    items = router.pull(200, max_items=1)
    if not items or router.result(rid)["state"] != "prefilling":
        errors.append(
            f"prefill dispatch wrong: {router.result(rid)}"
        )
    zeros = np.zeros((2, 8, 2, 4), np.float32)
    wire = hmod.pack(
        hmod.HandoffPayload(
            rid, [1, 2, 3], 4, 0.0, 9, zeros, zeros,
            ttft_s=0.1,
            phases={
                "dispatch": 0.0, "prefill": 0.08,
                "first_decode": 0.02,
            },
        )
    )
    router.complete(200, rid, [], handoff=wire)
    if router.result(rid)["state"] != "handoff":
        errors.append(
            f"handoff staging wrong: {router.result(rid)}"
        )
    router.report_stats(
        201,
        {
            "role": "decode", "queue_depth": 0, "active": 1,
            "tokens_generated": 1, "kv": {"utilization": 0.25},
        },
    )
    snapshot = router.snapshot()
    rendered = render_serving(snapshot)
    for needle in (
        "role prefill",
        "role decode",
        "handoff queue 1 staged",
        "kv 25%",
    ):
        if needle not in rendered:
            errors.append(
                f"disagg serving render missing {needle!r}: "
                f"{rendered!r}"
            )
    out = router.pull(201, max_items=1)
    if not out or not out[0].handoff:
        errors.append("decode pull did not carry the KV payload")
    clk[0] += 0.5
    router.complete(
        201, rid, [9, 8, 7, 6], ttft_s=0.1, tpot_s=0.01,
        finish_reason="length",
        phases={
            "dispatch": 0.0, "prefill": 0.08, "first_decode": 0.02,
            "handoff": 0.01, "decode": 0.03,
        },
    )
    rec = router.result(rid)
    if rec["state"] != "done" or "handoff" not in rec["phases"]:
        errors.append(f"disagg completion wrong: {rec}")
    if router.snapshot()["handoff_queue_depth"] != 0:
        errors.append("handoff queue not drained after dispatch")
    return errors


def _selftest_health() -> list:
    """Health plane hermetically: a fake-clock monitor over a ramping
    slow host + a healthy control host must convict exactly the slow
    one, queue a PROFILE action, and render score + evidence via the
    same path ``--health`` uses."""
    import json as _json
    import tempfile

    from dlrover_tpu.obs.health import (
        SEVERITY_CRITICAL,
        HealthMonitor,
        render_health,
    )
    from dlrover_tpu.obs.timeseries import TimeSeriesStore

    errors = []
    clk = [0.0]
    store = TimeSeriesStore(clock=lambda: clk[0])
    actions = []
    monitor = HealthMonitor(
        store,
        action_sink=lambda node, action: actions.append((node, action)),
        clock=lambda: clk[0],
        config={"window_s": 60.0, "min_points": 3.0},
    )
    monitor.fleet = type(
        "F",
        (),
        {
            "node_for_host": staticmethod(
                lambda host: {"slow": 3, "ok": 4}.get(host)
            ),
            "aggregates": staticmethod(dict),
        },
    )()
    for i in range(40):
        t = 900.0 + i * 5
        slow = 0.1 if t < 1000 else 0.1 * (1 + (t - 1000) / 30.0)
        store.record("host.step_time", slow, ts=t, host="slow")
        store.record("host.step_time", 0.1, ts=t, host="ok")
    clk[0] = 1095.0
    verdicts = monitor.evaluate_once()
    convicted = {(v.detector, v.host, v.severity) for v in verdicts}
    if ("throughput_degradation", "slow", SEVERITY_CRITICAL) not in convicted:
        errors.append(f"slow host not convicted: {convicted}")
    if any(v.host == "ok" for v in verdicts):
        errors.append(f"healthy control host convicted: {convicted}")
    if actions != [(3, "profile")]:
        errors.append(f"PROFILE not queued for node 3: {actions}")
    if monitor.health_score() >= 1.0:
        errors.append(f"score did not drop: {monitor.health_score()}")
    payload = monitor.healthz_payload()
    if payload["ok"] or payload["critical_verdicts"] != 1:
        errors.append(f"healthz payload wrong: {payload}")
    rendered = render_health(monitor.snapshot())
    for needle in (
        "job health score 0.70",
        "throughput_degradation",
        "action: profile",
        "evidence",
    ):
        if needle not in rendered:
            errors.append(f"health render missing {needle!r}")
    # The --health file path end to end: snapshot -> JSON -> report,
    # rc 1 because a critical verdict is active.
    with tempfile.NamedTemporaryFile(
        "w", suffix=".json", delete=False
    ) as f:
        _json.dump(monitor.snapshot(), f)
        path = f.name
    try:
        if health_report(path) != 1:
            errors.append("health_report rc != 1 with critical verdict")
    finally:
        import os as _os

        _os.unlink(path)
    # Recovery: the slow host heals, the verdict resolves, rc goes 0.
    for i in range(40):
        t = 1100.0 + i * 5
        store.record("host.step_time", 0.1, ts=t, host="slow")
        store.record("host.step_time", 0.1, ts=t, host="ok")
    clk[0] = 1295.0
    if monitor.evaluate_once():
        errors.append("verdict did not resolve after recovery")
    if monitor.health_score() != 1.0:
        errors.append(
            f"score did not recover: {monitor.health_score()}"
        )
    history = monitor.history()
    if not any(v.resolved for v in history):
        errors.append("no resolution transition in history")
    return errors


def _selftest_remediation() -> list:
    """Remediation rendering + probe semantics: the --health body
    must carry the decision history with its governor audit trail,
    and a currently-failing probation window must exit 1 even with no
    critical verdict active (the verdict resolved, but remediation
    demonstrably did not restore health)."""
    import json as _json
    import tempfile

    from dlrover_tpu.master.remediation import render_remediation

    errors = []
    payload = {
        "enabled": True,
        "dry_run": False,
        "cordoned": [1],
        "probation_failing": True,
        "decisions": [
            {
                "decision_id": 1,
                "detector": "throughput_degradation",
                "node_id": 1,
                "host": "h1",
                "action": "cordon_replace",
                "outcome": "acted",
                "dry_run": False,
                "governors": {
                    "hysteresis": "ok", "cooldown": "ok",
                    "blast_radius": "ok", "min_nodes": "ok",
                },
            },
            {
                "decision_id": 2,
                "detector": "data_starvation",
                "node_id": 2,
                "host": "h2",
                "action": "restart_training",
                "outcome": "blocked",
                "dry_run": False,
                "governors": {
                    "hysteresis": "ok",
                    "blast_radius": (
                        "blocked: 1 action(s) in the last 600s "
                        "window (cap 1)"
                    ),
                    "cooldown": "ok",
                },
            },
        ],
    }
    rendered = render_remediation(payload)
    for needle in (
        "remediation (active)",
        "cordoned [1]",
        "PROBATION FAILING",
        "cordon_replace",
        "governors ok:",
        "governor blast_radius: blocked:",
    ):
        if needle not in rendered:
            errors.append(
                f"remediation render missing {needle!r}: {rendered!r}"
            )
    # Probe semantics end to end through the --health file path: no
    # critical verdict, but a failing probation -> rc 1.
    snapshot = {
        "score": 1.0,
        "active": [],
        "history": [],
        "remediation": payload,
    }
    with tempfile.NamedTemporaryFile(
        "w", suffix=".json", delete=False
    ) as f:
        _json.dump(snapshot, f)
        path = f.name
    try:
        if health_report(path) != 1:
            errors.append(
                "health_report rc != 1 with a failing probation"
            )
        snapshot["remediation"]["probation_failing"] = False
        with open(path, "w") as f:
            _json.dump(snapshot, f)
        if health_report(path) != 0:
            errors.append(
                "health_report rc != 0 with healthy remediation"
            )
    finally:
        import os as _os

        _os.unlink(path)
    return errors


def selftest() -> int:
    """Hermetic check of the reconstruction pipeline on synthetic
    events shaped like a real drill trace."""
    t = 1000.0
    events = [
        {"name": "node.heartbeat_timeout", "ts": t, "node_id": 1},
        {"name": "trainer.proc_start", "ts": t + 4.0},
        {"name": "trainer.dist_ready", "ts": t + 10.0},
        {"name": "trainer.built", "ts": t + 25.0},
        {"name": "trainer.restore_done", "ts": t + 27.5},
        {"name": "trainer.first_step_done", "ts": t + 40.0},
        {"name": "trainer.step", "ts": t + 41.0, "step": 11},
        {"name": "trainer.throughput_recovered", "ts": t + 45.0},
        # input pipeline shaped like a healthy prefetch: staging cost
        # per batch is high, consumer wait is near zero
        {"name": "trainer.prefetch_start", "ts": t + 40.0, "depth": 2},
        {"name": "trainer.prefetch_stage", "ts": t + 40.1,
         "dur_s": 0.5},
        {"name": "trainer.prefetch_stage", "ts": t + 40.7,
         "dur_s": 0.5},
        {"name": "trainer.prefetch_h2d", "ts": t + 40.6,
         "dur_s": 0.2},
        {"name": "trainer.prefetch_h2d", "ts": t + 41.2,
         "dur_s": 0.2},
        {"name": "trainer.prefetch_wait", "ts": t + 41.0,
         "dur_s": 0.01, "host_s": 0.008, "h2d_s": 0.002},
        {"name": "trainer.prefetch_wait", "ts": t + 42.0,
         "dur_s": 0.03, "host_s": 0.02, "h2d_s": 0.01},
        {"name": "trainer.step", "ts": t + 43.0, "step": 12},
        {"name": "trainer.prefetch_stop", "ts": t + 45.0,
         "delivered": 2, "dropped": 0},
    ]
    tl = reconstruct_recovery_timeline(events)
    errors = []
    if tl is None:
        errors.append("reconstruction returned None")
    else:
        if not tl.complete:
            errors.append(f"timeline incomplete: {tl.phases}")
        for name in REQUIRED_PHASES:
            dur = tl.phases.get(name)
            if dur is None or dur <= 0:
                errors.append(f"phase {name} not positive: {dur}")
        expect = {
            "failure-detect": 4.0,
            "rendezvous": 6.0,
            "build": 15.0,
            "restore": 2.5,
            "first-step": 12.5,
            "throughput-90": 5.0,
        }
        for name, want in expect.items():
            got = tl.phases.get(name)
            if got is None or abs(got - want) > 1e-6:
                errors.append(f"phase {name}: want {want}, got {got}")
        if abs(tl.total_s - 45.0) > 1e-6:
            errors.append(f"total_s: want 45.0, got {tl.total_s}")
        render_timeline(tl)  # must not raise
        metrics_table(events)
        pipeline = input_pipeline_summary(events)
        if "data-wait" not in pipeline:
            errors.append(f"no data-wait line in: {pipeline!r}")
        if "0.040s total over 2 batches" not in pipeline:
            errors.append(f"wrong wait total in: {pipeline!r}")
        if "1.000s total over 2 batches" not in pipeline:
            errors.append(f"wrong stage total in: {pipeline!r}")
        if "0.400s total over 2 batches" not in pipeline:
            errors.append(f"wrong h2d stage total in: {pipeline!r}")
        if "wait split: host 0.028s / h2d 0.012s" not in pipeline:
            errors.append(f"wrong wait split in: {pipeline!r}")
        if "1.360s" not in pipeline:  # hidden = stage + h2d - wait
            errors.append(f"wrong hidden time in: {pipeline!r}")
        if "data-wait is 2.0% of wall time" not in pipeline:
            errors.append(f"wrong wall fraction in: {pipeline!r}")
        if input_pipeline_summary(
            [e for e in events if "prefetch" not in e["name"]]
        ):
            errors.append("pipeline summary not empty without events")
        errors.extend(_selftest_goodput(events))
    errors.extend(_selftest_fleet())
    errors.extend(_selftest_postmortem())
    errors.extend(_selftest_perf())
    errors.extend(_selftest_health())
    errors.extend(_selftest_remediation())
    errors.extend(_selftest_serving())
    errors.extend(_selftest_trace())
    errors.extend(_selftest_pool())
    errors.extend(_selftest_capacity())
    errors.extend(_selftest_stall())
    if errors:
        print("obs selftest FAILED:")
        for e in errors:
            print(f"  - {e}")
        return 1
    print("obs selftest ok")
    return 0


def _selftest_pool() -> list:
    """The --pool path end to end: a real PoolScheduler plays a
    priority-preemption + quota story with fake runtimes, its
    snapshot round-trips through JSON, and the renderer surfaces
    queue depth per band, tenant quotas, slice utilization,
    preemptions, and wait percentiles."""
    import json
    import os
    import tempfile

    from dlrover_tpu.pool import (
        JobRuntime,
        PoolJobSpec,
        PoolScheduler,
        SlicePool,
    )
    from dlrover_tpu.pool.scheduler import render_pool

    errors = []

    class FakeRT(JobRuntime):
        def place(self, slices, resume):
            pass

        def park(self, on_parked):
            on_parked({"staged": True, "path": "/ck", "step": 3})

        def stop(self):
            pass

    pool = SlicePool(4, tenant_quotas={"research": 2})
    sched = PoolScheduler(pool, park_timeout_s=5.0)
    sched.submit(
        PoolJobSpec(job_id="low", tenant="research", priority=1,
                    n_slices=2, min_slices=1),
        FakeRT(),
    )
    sched.submit(
        PoolJobSpec(job_id="high", tenant="prod", priority=5,
                    n_slices=4),
        FakeRT(),
    )
    # low was preempted for high; a second research job is now
    # quota-feasible but capacity-queued.
    sched.submit(
        PoolJobSpec(job_id="more", tenant="research", priority=1,
                    n_slices=1),
        FakeRT(),
    )
    info = sched.job_info("low")
    if info["state"] != "preempted" or info["preemptions"] != 1:
        errors.append(f"pool selftest: low not preempted: {info}")
    if sched.job_info("high")["slices"] != [0, 1, 2, 3]:
        errors.append("pool selftest: high gang not whole")
    snap = sched.snapshot()
    if snap["counters"]["preemptions"].get("priority") != 1:
        errors.append(
            f"pool selftest: counters {snap['counters']}"
        )
    if snap["queue_depth"].get("1") != 2:
        errors.append(
            f"pool selftest: band-1 depth {snap['queue_depth']}"
        )
    rendered = render_pool(snap)
    for needle in (
        "utilization 100%",
        "queue depth: 2",
        "research:",
        "priority=1",
        "wait band",
    ):
        if needle not in rendered:
            errors.append(
                f"pool selftest: {needle!r} missing from:\n"
                f"{rendered}"
            )
    # File-target path end to end (the --pool target contract).
    with tempfile.NamedTemporaryFile(
        "w", suffix="_pool.json", delete=False
    ) as f:
        json.dump(snap, f)
        path = f.name
    try:
        if pool_report(path) != 0:
            errors.append("pool selftest: pool_report(file) != 0")
    finally:
        os.unlink(path)
    return errors


def _selftest_capacity() -> list:
    """The --capacity path end to end: a real CapacityLedger plays a
    two-tenant pool story under a fake clock — idle gap, allocation,
    goodput accrual, a preemption + restore — then the snapshot must
    hold the partition invariant exactly, the renderer must surface
    the per-tenant table and SLO budget alerts, and the file-target
    rc contract must distinguish burning (1) / healthy (0) /
    missing (2)."""
    import json
    import os
    import tempfile

    from dlrover_tpu.obs.capacity import (
        CapacityLedger,
        render_capacity,
    )
    from dlrover_tpu.pool.slice_pool import SliceSpec

    errors = []
    t0 = 1000.0
    specs = [SliceSpec(slice_id=0), SliceSpec(slice_id=1)]
    led = CapacityLedger(specs, clock=lambda: t0)  # 2 x 4 chips
    # tenant a trains on both slices after a 10s idle gap, gets
    # preempted at t+40, resumes on slice 1 at t+70.
    led.on_allocate("ja", "a", [0, 1], ts=t0 + 10)
    led.observe_goodput("ja", 0.9, ts=t0 + 40)
    led.mark_preempting("ja", ts=t0 + 40)
    led.on_release("ja", [0, 1], ts=t0 + 50)
    # tenant b serves on slice 0 from t+60.
    led.on_allocate("jb", "b", [0], ts=t0 + 60)
    led.on_allocate("ja", "a", [1], ts=t0 + 70)
    led.mark_restoring("ja", ts=t0 + 70)
    led.job_ready("ja", ts=t0 + 80)
    snap = led.snapshot(ts=t0 + 100)
    if not snap["partition_ok"]:
        errors.append(
            f"capacity selftest: partition broken: "
            f"{snap['chip_seconds']}"
        )
    if abs(snap["chip_seconds"]["capacity"] - 800.0) > 1e-6:
        errors.append(
            f"capacity selftest: capacity "
            f"{snap['chip_seconds']['capacity']} != 800"
        )
    by_state = snap["chip_seconds"]["by_state"]
    if abs(by_state.get("idle", 0.0) - 200.0) > 1e-6:
        errors.append(
            f"capacity selftest: idle cs {by_state.get('idle')}"
            " != 200"
        )
    a = snap["tenants"].get("a", {})
    if abs(a.get("overhead_chip_seconds", 0.0) - 120.0) > 1e-6:
        errors.append(
            f"capacity selftest: tenant-a overhead {a} != 120"
        )
    # A ratio observation applies forward: 0.9 lands at t+40 just
    # as the preemption stops accrual, so only the 20s x 4 chips
    # after the restore completes counts — overhead accrues none.
    if abs(a.get("productive_chip_seconds", 0.0) - 72.0) > 1e-6:
        errors.append(
            f"capacity selftest: tenant-a productive {a} != 72"
        )
    if abs(snap.get("utilization", 0.0) - 0.75) > 1e-6:
        errors.append(
            f"capacity selftest: utilization {snap['utilization']}"
        )
    snap["slo"] = {
        "budgets": [
            {
                "tenant": "b", "slo": "ttft",
                "series": "tenant.ttft_p99_s",
                "objective": 0.5, "direction": "max",
                "budget_remaining": 0.2, "burning": True,
                "severity": "critical",
                "burn": {"fast": 15.4, "slow": 2.0},
            },
        ]
    }
    rendered = render_capacity(snap)
    for needle in (
        "2 slice(s) / 8 chip(s)",
        "utilization 75%",
        "preempting 80.0",
        "restoring 40.0",
        "b/ttft: budget remaining 20%",
        "BURNING [critical] fast 15.4x slow 2.0x",
    ):
        if needle not in rendered:
            errors.append(
                f"capacity selftest: {needle!r} missing from:\n"
                f"{rendered}"
            )
    # File-target rc contract: burning -> 1, healthy -> 0,
    # missing -> 2.
    with tempfile.NamedTemporaryFile(
        "w", suffix="_capacity.json", delete=False
    ) as f:
        json.dump(snap, f)
        path = f.name
    try:
        if capacity_report(path) != 1:
            errors.append(
                "capacity selftest: burning snapshot rc != 1"
            )
        snap["slo"]["budgets"][0]["burning"] = False
        with open(path, "w") as f:
            json.dump(snap, f)
        if capacity_report(path) != 0:
            errors.append(
                "capacity selftest: healthy snapshot rc != 0"
            )
    finally:
        os.unlink(path)
    if capacity_report(path) != 2:
        errors.append("capacity selftest: missing target rc != 2")
    return errors


def _selftest_goodput(events) -> list:
    """Goodput attribution on the same synthetic trace: buckets must
    be exhaustive (sum == window) with the hand-computed values."""
    errors = []
    gp = attribute_goodput(events)
    if gp is None:
        return ["goodput attribution returned None"]
    want = {
        "recovery": 40.0,       # failure t .. first_step_done t+40
        "data_wait": 0.04,      # two prefetch_wait events
        "productive": 1.97,     # steps t+41..t+43 minus data-wait
        "compile": 0.0,
        "checkpoint": 0.0,
        "idle_unknown": 2.99,   # the remainder
    }
    for cat, val in want.items():
        got = gp.seconds.get(cat, 0.0)
        if abs(got - val) > 1e-6:
            errors.append(f"goodput[{cat}]: want {val}, got {got}")
    if abs(sum(gp.seconds.values()) - gp.total_s) > 1e-6:
        errors.append(
            f"goodput buckets sum {sum(gp.seconds.values())} != "
            f"window {gp.total_s}"
        )
    rendered = render_goodput(gp)
    if "goodput" not in rendered or "recovery" not in rendered:
        errors.append(f"goodput render incomplete: {rendered!r}")
    return errors


def _selftest_fleet() -> list:
    """Fleet aggregation on two synthetic host snapshots: host-labeled
    series, cross-host aggregates, and age-out on removal."""
    from types import SimpleNamespace

    from dlrover_tpu.obs.fleet import FleetAggregator
    from dlrover_tpu.obs.metrics import MetricsRegistry

    errors = []
    reg = MetricsRegistry()
    fleet = FleetAggregator(registry=reg, ttl=3600.0)

    def snap(node_id, host, step_time, syncs):
        return SimpleNamespace(
            node_id=node_id,
            host=host,
            timestamp=1000.0,
            registry={
                "dlrover_train_steps_total": {
                    "type": "counter", "help": "steps",
                    "labelnames": [], "series": [[[], 10 + node_id]],
                },
                "dlrover_train_host_syncs_total": {
                    "type": "counter", "help": "syncs",
                    "labelnames": ["reason"],
                    "series": [[["log"], syncs]],
                },
            },
            resource={"tokens_per_s": 1000.0 * (node_id + 1)},
            step_times=[step_time] * 3,
            events=[],
        )

    fleet.ingest(snap(0, "w0", 0.10, 5))
    fleet.ingest(snap(1, "w1", 0.30, 7))
    body = reg.render()
    for needle in (
        'dlrover_train_steps_total{host="w0"} 10',
        'dlrover_train_steps_total{host="w1"} 11',
        'dlrover_train_host_syncs_total{reason="log",host="w1"} 7',
        "dlrover_fleet_hosts 2",
        'dlrover_fleet_series{series="host_syncs_total",stat="sum"} 12',
        'dlrover_fleet_series{series="step_time_s",stat="max"} 0.3',
        'dlrover_fleet_series{series="tokens_per_s",stat="min"} 1000',
    ):
        if needle not in body:
            errors.append(f"fleet render missing {needle!r}")
    fleet.remove_node(1)
    body = reg.render()
    if 'host="w1"' in body:
        errors.append("departed host w1 still rendered after removal")
    if "dlrover_fleet_hosts 1" not in body:
        errors.append("fleet host count did not drop to 1")
    fleet.close()
    if "dlrover_fleet_hosts" in reg.render():
        errors.append("fleet collector still rendering after close()")
    return errors


def _selftest_postmortem() -> list:
    """Postmortem rendering over a synthetic forensics dir: one hang
    bundle with a wedged thread, one faulthandler stacks file, one
    trace — the report must carry the failure instant, the hung
    thread's stack, the fault dump, and the goodput attribution."""
    import json as _json
    import tempfile

    from dlrover_tpu.obs.postmortem import (
        collect_events,
        failure_instant,
        last_fault_dump,
        load_bundles,
        render_postmortem,
    )

    errors = []
    t = 2000.0
    with tempfile.TemporaryDirectory() as dir_:
        bundle = {
            "schema": 1,
            "kind": "hang",
            "reason": "no step progress for 62.0s",
            "ts": t + 62.0,
            "role": "agent",
            "rank": 0,
            "pid": 111,
            "proc": {"python": "3.11.0", "jax_platform": "cpu"},
            "env": {},
            "notes": {"step": 41, "loss": 2.5},
            "logs": [
                {"ts": t + 61.0, "level": "WARNING",
                 "logger": "agent", "msg": "no step progress"},
            ],
            "events": [
                {"name": "trainer.step", "ts": t, "step": 40,
                 "pid": 222},
                {"name": "trainer.step", "ts": t + 1.0, "step": 41,
                 "pid": 222},
                {"name": "stall.incident", "ts": t + 60.0,
                 "pid": 111, "incident": "stall-2060-1",
                 "kind": "laggard", "culprit": "host-c", "hosts": 3},
                {"name": "agent.hang_detected", "ts": t + 62.0,
                 "pid": 111},
            ],
            "metrics": {},
            "stacks": [
                {"thread": "MainThread", "ident": 1, "daemon": False,
                 "current": True,
                 "frames": ["agent.py:500 in _invoke_run"]},
            ],
            "stacks_file": f"{dir_}/stacks_111.txt",
        }
        with open(f"{dir_}/bundle_agent_r0_111_001_hang.json", "w") as f:
            _json.dump(bundle, f)
        with open(f"{dir_}/stacks_222.txt", "w") as f:
            f.write(
                "# flight recorder role=trainer rank=0 pid=222\n"
                "Current thread 0x00007f01 (most recent call first):\n"
                '  File "train.py", line 12 in stuck_collective\n'
                '  File "train.py", line 30 in main\n'
            )
        bundles = load_bundles(dir_)
        if len(bundles) != 1:
            errors.append(f"expected 1 bundle, loaded {len(bundles)}")
        events = collect_events(dir_, bundles)
        if len(events) != 4:
            errors.append(f"expected 4 events, got {len(events)}")
        t_fail, source = failure_instant(events, bundles)
        if t_fail != t + 62.0 or source != "agent.hang_detected":
            errors.append(
                f"failure instant wrong: {t_fail} from {source!r}"
            )
        dump = last_fault_dump(
            open(f"{dir_}/stacks_222.txt").read()
        )
        if not dump.startswith("Current thread"):
            errors.append(f"last_fault_dump wrong: {dump!r}")
        report = render_postmortem(dir_, window=90.0)
        for needle in (
            "failure instant: 2062.000 (from agent.hang_detected)",
            "bundle_agent_r0_111_001_hang.json",
            "notes: loss=2.5, step=41",
            "thread MainThread (current):",
            "agent.py:500 in _invoke_run",
            "stack dump stacks_222.txt (pid 222):",
            "stuck_collective",
            "goodput",  # attribution over the window
            "agent.hang_detected",
            "stall incidents in window:",
            "stall-2060-1 opened at 2060.000: laggard, "
            "culprit host-c, 3 host(s) parked",
        ):
            if needle not in report:
                errors.append(f"postmortem missing {needle!r}")
        empty = render_postmortem(f"{dir_}/nope")
        if "no forensics artifacts" not in empty:
            errors.append(f"empty-dir message wrong: {empty!r}")
    return errors


def _selftest_perf() -> list:
    """--perf section on synthetic profiler events: phase totals,
    wall percentages, compile rollup, and capture line must all be
    hand-verifiable."""
    errors = []
    t = 3000.0
    events = [
        {"name": "trainer.compile", "ts": t, "fn": "train_step",
         "dur_s": 2.0, "total": 1},
        {"name": "trainer.step_phases", "ts": t + 2.0, "step": 1,
         "wall_s": 2.5, "data_wait_s": 0.25, "h2d_s": 0.0,
         "compile_s": 2.0, "dispatch_s": 0.05, "device_s": 0.2},
        {"name": "trainer.step_phases", "ts": t + 3.0, "step": 2,
         "wall_s": 0.5, "data_wait_s": 0.05, "h2d_s": 0.1,
         "compile_s": 0.0, "dispatch_s": 0.05, "device_s": 0.3,
         "mfu": 0.41},
        {"name": "trainer.step_phases", "ts": t + 4.0, "step": 3,
         "wall_s": 1.0, "data_wait_s": 0.2, "h2d_s": 0.1,
         "compile_s": 0.0, "dispatch_s": 0.1, "device_s": 0.6,
         "mfu": 0.43},
        {"name": "trainer.profile_done", "ts": t + 4.0, "steps": 3,
         "request_id": "r1", "mfu": 0.43},
    ]
    summary = perf_summary(events)
    for needle in (
        "3 steps, 4.000s wall",
        "data_wait            0.500",  # 0.25+0.05+0.2
        "h2d_stage            0.200",  # the split's H2D slice
        "compile              2.000",
        "device_execute       1.100",
        "50.0%",   # compile = 2.0 / 4.0 wall
        "mfu: last 0.4300 over 2 samples",
        "compiles: train_step x1 (2.00s)",
        "profile capture: 3 steps (request r1, mfu 0.43)",
    ):
        if needle not in summary:
            errors.append(f"perf summary missing {needle!r}: {summary!r}")
    if perf_summary(
        [e for e in events if "step_phases" not in e["name"]
         and "compile" not in e["name"]]
    ):
        errors.append("perf summary not empty without profiler events")
    return errors


def _selftest_stall() -> list:
    """The --stall path end to end: a real StallCorrelator over a
    fake three-host fleet with an injected clock. One host stops
    stamping -> after the tick streak exactly that host is convicted
    (collective_stall), the coordinated capture reaches all three
    nodes, the incident trace carries per-host progress spans, the
    snapshot round-trips through JSON into stall_report's rc=1 /
    rc=0 contract, and a flapping beacon never convicts."""
    import json
    import os
    import tempfile
    import types

    from dlrover_tpu.obs.stall import StallCorrelator, render_stall

    errors = []
    t = [5000.0]

    class FakeFleet:
        def __init__(self):
            self.snaps = {}

        def set(self, host, node_id, step, phase, mb, age_s):
            self.snaps[host] = types.SimpleNamespace(
                host=host, node_id=node_id, wall_ts=t[0],
                beacon={"step": step, "phase": phase,
                        "microbatch": mb, "age_s": age_s},
            )

        def live_snapshots(self):
            return list(self.snaps.values())

    fleet = FakeFleet()
    pushes = []

    def capture(node_id, action, dedupe_key=None):
        pushes.append((node_id, action, dedupe_key))
        return True

    from dlrover_tpu.obs.trace_store import TraceStore

    traces = TraceStore(clock=lambda: t[0])
    corr = StallCorrelator(
        fleet=fleet, traces=traces, capture=capture,
        clock=lambda: t[0],
        config={"stall_after_s": 60.0, "stall_ticks": 2.0,
                "capture_cooldown_s": 0.0},
    )
    # Healthy fleet: everyone stamping, no verdicts.
    for h, n in (("host-a", 0), ("host-b", 1), ("host-c", 2)):
        fleet.set(h, n, step=10, phase="dispatch", mb=3, age_s=1.0)
    if corr.evaluate():
        errors.append("stall selftest: verdict on a healthy fleet")
    # host-c wedges a step behind; peers park at step 11's dispatch.
    t[0] += 90.0
    fleet.set("host-a", 0, 11, "dispatch", -1, 90.0)
    fleet.set("host-b", 1, 11, "dispatch", -1, 90.0)
    fleet.set("host-c", 2, 10, "h2d_stage", 1, 95.0)
    if corr.evaluate():
        errors.append("stall selftest: conviction on a single tick")
    # Peers advanced a step before parking, so their streaks only
    # start counting now — two stale ticks convict.
    for _ in range(2):
        t[0] += 30.0
        for snap in fleet.snaps.values():
            snap.wall_ts = t[0]
            snap.beacon["age_s"] += 30.0
        verdicts = corr.evaluate()
    if (
        len(verdicts) != 1
        or verdicts[0].detector != "collective_stall"
        or verdicts[0].host != "host-c"
        or verdicts[0].node_id != 2
    ):
        errors.append(f"stall selftest: bad verdicts {verdicts}")
    inc = corr.open_incident()
    if not inc or inc["kind"] != "laggard":
        errors.append(f"stall selftest: bad incident {inc}")
    # Coordinated capture: DIAGNOSE+PROFILE to every node, once.
    if sorted({n for n, _, _ in pushes}) != [0, 1, 2]:
        errors.append(f"stall selftest: capture missed hosts {pushes}")
    if len(pushes) != 6:
        errors.append(f"stall selftest: capture count {len(pushes)}")
    # The incident trace: one root, a progress span per host.
    tl = traces.get(inc["trace_id"]) if inc else None
    names = [s["name"] for s in (tl or {}).get("spans", ())]
    if names.count("stall.incident") != 1:
        errors.append(f"stall selftest: trace roots in {names}")
    if names.count("stall.progress") != 3:
        errors.append(f"stall selftest: progress spans in {names}")
    # rc contract via the snapshot file path: 1 open, 0 resolved.
    snap = corr.snapshot()
    rendered = render_stall(snap)
    for needle in ("incident", "OPEN", "host-c", "<- culprit",
                   "STALLED"):
        if needle not in rendered:
            errors.append(
                f"stall render missing {needle!r}: {rendered!r}"
            )
    with tempfile.NamedTemporaryFile(
        "w", suffix=".json", delete=False
    ) as f:
        json.dump(snap, f)
        path = f.name
    try:
        if stall_report(path) != 1:
            errors.append("stall_report rc != 1 with open incident")
        # host-c recovers: incident resolves, rc drops to 0.
        t[0] += 30.0
        fleet.set("host-a", 0, 12, "dispatch", -1, 1.0)
        fleet.set("host-b", 1, 12, "dispatch", -1, 1.0)
        fleet.set("host-c", 2, 12, "dispatch", -1, 1.0)
        if corr.evaluate():
            errors.append("stall selftest: verdict after recovery")
        if corr.open_incident() is not None:
            errors.append("stall selftest: incident not resolved")
        snap = corr.snapshot()
        if not snap["incidents"]:
            errors.append("stall selftest: resolved incident lost")
        with open(path, "w") as f:
            json.dump(snap, f)
        if stall_report(path) != 0:
            errors.append("stall_report rc != 0 after resolution")
    finally:
        os.unlink(path)
    # A flapping beacon (stale but advancing) must never convict.
    flap = StallCorrelator(
        fleet=fleet, clock=lambda: t[0],
        config={"stall_after_s": 60.0, "stall_ticks": 2.0},
    )
    step = 12
    for _ in range(5):
        t[0] += 120.0
        step += 1
        for i, h in enumerate(("host-a", "host-b", "host-c")):
            fleet.set(h, i, step, "dispatch", -1, 200.0)
        if flap.evaluate():
            errors.append("stall selftest: flapping beacon convicted")
            break
    return errors


def main(argv=None) -> int:
    p = argparse.ArgumentParser("obs_report")
    p.add_argument("event_file", nargs="?", default="")
    p.add_argument(
        "--failure-ts", type=float, default=None,
        help="failure instant (unix time); derived from master-side "
        "node.fail/node.gone events when omitted",
    )
    p.add_argument("--top", type=int, default=15)
    p.add_argument(
        "--goodput", action="store_true",
        help="print the goodput/badput wall-time attribution",
    )
    p.add_argument(
        "--perf", action="store_true",
        help="print the step-phase / compile / MFU summary from the "
        "profiler's trace events",
    )
    p.add_argument(
        "--health", type=str, default="",
        metavar="TARGET",
        help="render the master's fleet-health verdicts from a live "
        "master (host:port) or a HealthMonitor.snapshot() JSON file; "
        "exits 1 when a critical verdict is active",
    )
    p.add_argument(
        "--serving", type=str, default="",
        metavar="TARGET",
        help="render the master's serving plane (request counters, "
        "per-replica TTFT/TPOT/queue/KV stats, unhealthy replicas) "
        "from a live master (host:port) or a ServingRouter.snapshot()"
        " JSON file; exits 1 when a replica is unhealthy",
    )
    p.add_argument(
        "--pool", type=str, default="",
        metavar="TARGET",
        help="render the multi-job pool plane (queue depth per "
        "priority band, per-tenant quota usage, slice utilization, "
        "preemption counts, wait-time percentiles) from a live pool "
        "master (host:port) or a PoolScheduler.snapshot() JSON file",
    )
    p.add_argument(
        "--capacity", type=str, default="",
        metavar="TARGET",
        help="render the pool capacity plane (per-tenant chip-second "
        "accounting, goodput-per-chip, preemption/restore overhead, "
        "SLO error budgets with burn-rate alerts) from a live pool "
        "master (host:port) or a CapacityLedger.snapshot() JSON "
        "file; exits 1 while any tenant's error budget is burning",
    )
    p.add_argument(
        "--stall", type=str, default="",
        metavar="TARGET",
        help="render the stall-localization plane (per-host progress "
        "beacons, open/recent collective_stall incidents with the "
        "localized culprit, trace id, and coordinated-capture bundle "
        "paths) from a live master (host:port) or a "
        "StallCorrelator.snapshot() JSON file; exits 1 while an "
        "incident is open",
    )
    p.add_argument(
        "--trace", type=str, default="",
        metavar="KEY",
        help="render the causal trace timeline(s) for KEY — a trace "
        "id, a serving request id, or a node (node:<id> or bare id) "
        "— from the target given as the positional argument: a live "
        "master (host:port, TraceQueryRequest RPC) or a JSON file "
        "of trace-store timelines",
    )
    p.add_argument(
        "--postmortem", type=str, default="",
        metavar="DIR",
        help="render a forensics dir (flight-recorder bundles + "
        "faulthandler stack dumps + traces) into the last-N-seconds-"
        "before-failure report",
    )
    p.add_argument(
        "--window", type=float, default=60.0,
        help="with --postmortem: seconds before the failure instant "
        "to report on",
    )
    p.add_argument(
        "--selftest", action="store_true",
        help="run the reconstruction/goodput/fleet/postmortem "
        "pipelines on synthetic inputs",
    )
    args = p.parse_args(argv)
    if args.selftest:
        return selftest()
    if args.health:
        return health_report(args.health)
    if args.serving:
        return serving_report(args.serving)
    if args.pool:
        return pool_report(args.pool)
    if args.capacity:
        return capacity_report(args.capacity)
    if args.stall:
        return stall_report(args.stall)
    if args.trace:
        if not args.event_file:
            p.error(
                "--trace needs a target: obs_report --trace KEY "
                "HOST:PORT|traces.json"
            )
        return trace_report(args.trace, args.event_file)
    if args.postmortem:
        from dlrover_tpu.obs.postmortem import render_postmortem

        rendered = render_postmortem(
            args.postmortem, window=args.window
        )
        print(rendered)
        return 1 if rendered.startswith("no forensics artifacts") else 0
    if not args.event_file:
        p.error("event_file is required (or pass --selftest/--postmortem)")
    return report(
        args.event_file, args.failure_ts, args.top,
        goodput=args.goodput, perf=args.perf,
    )


if __name__ == "__main__":
    sys.exit(main())
