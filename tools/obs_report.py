"""Render a recovery timeline + metrics table from an obs JSONL trace.

Reads the event file the tracer exports (DLROVER_TPU_TRACE_FILE) —
e.g. the per-host traces the chaos drills leave behind — and prints:

* the reconstructed recovery timeline (obs/timeline.py), when the
  canonical trainer marks are present;
* a per-event-name table: count, and for span events total/mean
  duration, sorted by total time.

Usage:
    python tools/obs_report.py TRACE.jsonl [--failure-ts T] [--top N]
    python tools/obs_report.py --selftest

``--selftest`` runs the reconstruction pipeline on a synthetic event
log and exits nonzero on any inconsistency — a fast CI smoke with no
inputs (invoked by tests/test_obs.py).
"""

from __future__ import annotations

import argparse
import sys

import _repo_path  # noqa: F401

from dlrover_tpu.obs.timeline import (
    REQUIRED_PHASES,
    load_events,
    reconstruct_recovery_timeline,
    render_timeline,
)


def metrics_table(events, top: int = 15) -> str:
    stats = {}
    for ev in events:
        name = ev.get("name", "?")
        count, total = stats.get(name, (0, 0.0))
        stats[name] = (count + 1, total + float(ev.get("dur_s", 0.0)))
    rows = sorted(
        stats.items(), key=lambda kv: (-kv[1][1], -kv[1][0])
    )[:top]
    lines = [
        f"top {len(rows)} event names (of {len(stats)}):",
        f"  {'event':<32} {'count':>7} {'total_s':>9} {'mean_s':>9}",
    ]
    for name, (count, total) in rows:
        mean = total / count if count else 0.0
        lines.append(
            f"  {name:<32} {count:>7} {total:>9.3f} {mean:>9.4f}"
        )
    return "\n".join(lines)


def report(path: str, failure_ts=None, top: int = 15) -> int:
    events = [e for e in load_events(path) if "ts" in e]
    if not events:
        print(f"no events in {path}")
        return 1
    t0 = min(e["ts"] for e in events)
    t1 = max(e["ts"] for e in events)
    print(
        f"{len(events)} events over {t1 - t0:.1f}s from {path}"
    )
    tl = reconstruct_recovery_timeline(events, t_failure=failure_ts)
    if tl is not None:
        print()
        print(render_timeline(tl))
    print()
    print(metrics_table(events, top=top))
    return 0


def selftest() -> int:
    """Hermetic check of the reconstruction pipeline on synthetic
    events shaped like a real drill trace."""
    t = 1000.0
    events = [
        {"name": "node.heartbeat_timeout", "ts": t, "node_id": 1},
        {"name": "trainer.proc_start", "ts": t + 4.0},
        {"name": "trainer.dist_ready", "ts": t + 10.0},
        {"name": "trainer.built", "ts": t + 25.0},
        {"name": "trainer.restore_done", "ts": t + 27.5},
        {"name": "trainer.first_step_done", "ts": t + 40.0},
        {"name": "trainer.step", "ts": t + 41.0, "step": 11},
        {"name": "trainer.throughput_recovered", "ts": t + 45.0},
    ]
    tl = reconstruct_recovery_timeline(events)
    errors = []
    if tl is None:
        errors.append("reconstruction returned None")
    else:
        if not tl.complete:
            errors.append(f"timeline incomplete: {tl.phases}")
        for name in REQUIRED_PHASES:
            dur = tl.phases.get(name)
            if dur is None or dur <= 0:
                errors.append(f"phase {name} not positive: {dur}")
        expect = {
            "failure-detect": 4.0,
            "rendezvous": 6.0,
            "build": 15.0,
            "restore": 2.5,
            "first-step": 12.5,
            "throughput-90": 5.0,
        }
        for name, want in expect.items():
            got = tl.phases.get(name)
            if got is None or abs(got - want) > 1e-6:
                errors.append(f"phase {name}: want {want}, got {got}")
        if abs(tl.total_s - 45.0) > 1e-6:
            errors.append(f"total_s: want 45.0, got {tl.total_s}")
        render_timeline(tl)  # must not raise
        metrics_table(events)
    if errors:
        print("obs selftest FAILED:")
        for e in errors:
            print(f"  - {e}")
        return 1
    print("obs selftest ok")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser("obs_report")
    p.add_argument("event_file", nargs="?", default="")
    p.add_argument(
        "--failure-ts", type=float, default=None,
        help="failure instant (unix time); derived from master-side "
        "node.fail/node.gone events when omitted",
    )
    p.add_argument("--top", type=int, default=15)
    p.add_argument(
        "--selftest", action="store_true",
        help="run the reconstruction pipeline on synthetic events",
    )
    args = p.parse_args(argv)
    if args.selftest:
        return selftest()
    if not args.event_file:
        p.error("event_file is required (or pass --selftest)")
    return report(args.event_file, args.failure_ts, args.top)


if __name__ == "__main__":
    sys.exit(main())
