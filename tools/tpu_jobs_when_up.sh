#!/bin/bash
# Probe-gated chain of the round's hardware jobs, ordered per the
# round-4 verdict: the moment the TPU tunnel answers, land the bench
# record FIRST (PERF_r05.json), the kernel smoke SECOND
# (KERNELS_r05.json), then the multi-run stability record, a step
# profile, the autotune+tuned re-bench (pins the headline config),
# AGD convergence on chip, long-context bench, decode bench, the
# uncapped tune retry, and a profile of the tuned winner. Each
# stage's gate is an artifact written ONLY on success, so a tunnel
# drop mid-stage retries on the next probe instead of permanently
# skipping.
#
# Run:  nohup tools/tpu_jobs_when_up.sh >> /tmp/tpu_jobs.log 2>&1 &
set -u
cd "$(dirname "$0")/.." || exit 1

probe() {
  # The matmul alone would pass on jax's CPU fallback while the TPU
  # is down — assert the backend too.
  timeout 90 python -c "
import jax
assert jax.default_backend() in ('tpu', 'axon'), jax.default_backend()
import jax.numpy as jnp
(jnp.ones((256, 256)) @ jnp.ones((256, 256))).block_until_ready()
" >/dev/null 2>&1
}

# Hard deadline: stop well before the round's driver-side bench
# capture so two clients never contend for the single chip.
#
# FAIL-CLOSED (VERDICT r5 #2): the r5 chain ran with the deadline
# opt-in and unset, and chip contention ate the capture window. The
# deadline is now mandatory — when DEADLINE_EPOCH is not given it is
# derived from the round clock (now + DEADLINE_BUDGET_S, default 4h),
# and an explicit DEADLINE_EPOCH=0 ("never") is refused outright.
# On expiry, the in-flight stage's whole process group gets
# SIGTERM -> (30s grace) -> SIGKILL, so a wedged tunnel helper
# holding the pipes cannot keep squatting on the chip.
if [ "${DEADLINE_EPOCH:-}" = "0" ]; then
  echo "[$(date +%T)] ERROR: DEADLINE_EPOCH=0 (run forever) is not" \
       "allowed — unset it to derive a deadline from the round clock," \
       "or pass an epoch" >&2
  exit 2
fi
case "${DEADLINE_EPOCH:-}" in
  ''|*[!0-9]*)
    if [ -n "${DEADLINE_EPOCH:-}" ]; then
      echo "[$(date +%T)] ERROR: DEADLINE_EPOCH='${DEADLINE_EPOCH}' is not an epoch" >&2
      exit 2
    fi
    DEADLINE_EPOCH=$(( $(date +%s) + ${DEADLINE_BUDGET_S:-14400} ))
    echo "[$(date +%T)] no DEADLINE_EPOCH given; derived $DEADLINE_EPOCH" \
         "(now + ${DEADLINE_BUDGET_S:-14400}s)"
    ;;
esac

# Chain-scoped progress beacon: bench children stamp step/phase into
# this file (their own setdefault defers to the export), so a
# deadline-killed stage leaves a readable last-known position for
# log_hang below instead of only rc=124.
export DLROVER_TPU_BEACON_FILE="${DLROVER_TPU_BEACON_FILE:-/tmp/dlrover_tpu_beacon_chain_$$.json}"

# log_hang STAGE_DESC: after a budget/deadline kill, read the dead
# child's final beacon stamp and append a kind-"hang" record to the
# bench ledger (tools/bench_ledger.py) so the timed-out stage is
# localizable in the history — prints the record id + last stamp.
log_hang() {
  python - "$1" <<'PY' 2>/dev/null || echo "[$(date +%T)] hang forensics unavailable"
import sys
sys.path.insert(0, "tools")
import _repo_path  # noqa: F401
from dlrover_tpu.obs import beacon as b
stamp = b.read_beacon() or {}
age = b.stamp_age(stamp) if stamp else None
where = (
    "last beacon stamp: step {} {}".format(
        stamp.get("step"), stamp.get("phase"))
    + (" (age {:.0f}s)".format(age) if age is not None else "")
    if stamp else "no beacon stamp (stage never stamped)"
)
rec = {
    "metric": "nanogpt_tokens_per_sec_per_chip",
    "value": 0.0, "unit": "tokens/s/chip", "vs_baseline": 0.0,
    "error": "tpu_hang", "kind": "hang",
    "detail": "tpu_jobs_when_up.sh killed stage: " + sys.argv[1][:200],
    "stage": "chain",
}
if stamp:
    rec["beacon"] = {
        k: stamp.get(k)
        for k in ("pid", "step", "microbatch", "phase", "seq")
    }
    if age is not None:
        rec["beacon"]["age_s"] = round(age, 1)
import bench_ledger
stored = bench_ledger.append_record(rec)
print("hang ledger record {}@{}; {}".format(
    stored.get("ts"), str(stored.get("git_rev", ""))[:12], where))
PY
}

# run_stage BUDGET_S CMD...: run one chain stage in its own session,
# clamped to min(budget, time to the deadline). On expiry: SIGTERM to
# the process GROUP, 30s grace, SIGKILL to the group. Returns the
# stage's rc, or 124 on a deadline/budget kill, 125 when no usable
# window remains.
run_stage() {
  local budget=$1; shift
  local now remain end
  now=$(date +%s); remain=$(( DEADLINE_EPOCH - now ))
  if [ "$remain" -le 60 ]; then
    echo "[$(date +%T)] <61s to deadline; not starting: $*"
    return 125
  fi
  [ "$budget" -gt "$remain" ] && budget=$remain
  setsid "$@" &
  local pid=$!
  end=$(( now + budget ))
  while kill -0 "$pid" 2>/dev/null; do
    if [ "$(date +%s)" -ge "$end" ]; then
      echo "[$(date +%T)] stage budget/deadline expired; SIGTERM to pgid $pid"
      kill -TERM -- "-$pid" 2>/dev/null
      local j
      for j in $(seq 1 30); do
        kill -0 "$pid" 2>/dev/null || break
        sleep 1
      done
      if kill -0 "$pid" 2>/dev/null; then
        echo "[$(date +%T)] still alive after grace; SIGKILL to pgid $pid"
        kill -KILL -- "-$pid" 2>/dev/null
      fi
      wait "$pid" 2>/dev/null
      echo "[$(date +%T)] $(log_hang "$*")"
      return 124
    fi
    sleep 2
  done
  wait "$pid"
}

for i in $(seq 1 400); do
  if [ "$(date +%s)" -ge "$DEADLINE_EPOCH" ]; then
    echo "[$(date +%T)] deadline reached; exiting to free the chip"
    exit 0
  fi
  if probe; then
    echo "[$(date +%T)] probe ok (try $i)"
    if [ ! -f PERF_r05.json ]; then
      echo "[$(date +%T)] landing the baseline bench record"
      run_stage 1800 env CAPTURE_STAGE=baseline python -u tools/capture_perf.py >> /tmp/capture_perf.log 2>&1
      echo "[$(date +%T)] baseline rc=$? (artifact: $(ls PERF_r05.json 2>/dev/null || echo none))"
    elif [ ! -f KERNELS_r05.json ]; then
      echo "[$(date +%T)] running kernel smoke"
      run_stage 1800 python -u tools/tpu_kernel_smoke.py >> /tmp/kernel_smoke.log 2>&1
      echo "[$(date +%T)] smoke rc=$? (artifact: $(ls KERNELS_r05.json 2>/dev/null || echo none))"
    elif [ ! -f STABILITY_r05.json ]; then
      echo "[$(date +%T)] bench stability (3 runs)"
      run_stage 3600 python -u tools/bench_stability.py >> /tmp/bench_stability.log 2>&1
      echo "[$(date +%T)] stability rc=$?"
    elif [ ! -f /tmp/profile_step.txt ] && [ "$(cat /tmp/profile_step.fails 2>/dev/null || echo 0)" -lt 2 ]; then
      # Moved ahead of the long stages: the per-op attribution gates
      # the round's attention-optimization work, and a tunnel drop
      # after stability must not leave the builder blind for hours.
      # Capped at 2 failures so a deterministically broken profiler
      # can't starve AGD/longctx/decode/tune of the whole window.
      echo "[$(date +%T)] profiling the tuned step"
      if run_stage 900 python -u tools/profile_step.py 'full,flash,18,1024,1024,-,nofn' > /tmp/profile_step.partial 2>&1; then
        mv /tmp/profile_step.partial /tmp/profile_step.txt
        echo "[$(date +%T)] profile ok ($(wc -l < /tmp/profile_step.txt) lines)"
      else
        rc=$?
        fails=$(( $(cat /tmp/profile_step.fails 2>/dev/null || echo 0) + 1 ))
        echo "$fails" > /tmp/profile_step.fails
        echo "[$(date +%T)] profile failed rc=$rc (failure $fails/2)"
      fi
    elif [ ! -f /tmp/capture_tune.done ] && [ "$(cat /tmp/capture_tune.fails 2>/dev/null || echo 0)" -lt 2 ]; then
      # Ahead of AGD/longctx/decode: the tune winner auto-pins into
      # bench_tuned.json, which the driver's end-of-round capture
      # loads — the single highest-leverage stage for the headline
      # if the window is short. AGD already has an acceptable labeled
      # CPU-fallback artifact, so it yields its slot to the tune.
      # The sweep covers scan-unroll, save_attn, and xent-chunk axes
      # besides the bwd blocks.
      # Capped at 2 failed attempts here (a window shorter than the
      # sweep would otherwise starve longctx/decode forever); a
      # final uncapped retry sits after the decode stage.
      echo "[$(date +%T)] autotune + tuned re-bench"
      run_stage 5400 env CAPTURE_STAGE=tune python -u tools/capture_perf.py >> /tmp/capture_perf.log 2>&1
      rc=$?
      # The tune stage appends to PERF_r05.json on success; a rc=0 with
      # no autotune results also returns 0 — either way, done once.
      if [ $rc -eq 0 ]; then
        touch /tmp/capture_tune.done
      else
        fails=$(( $(cat /tmp/capture_tune.fails 2>/dev/null || echo 0) + 1 ))
        echo "$fails" > /tmp/capture_tune.fails
      fi
      echo "[$(date +%T)] tune rc=$rc"
    elif { [ ! -f AGD_CONVERGENCE_r05.json ] || grep -q reduced-cpu AGD_CONVERGENCE_r05.json; } \
        && [ "$(cat /tmp/agd_conv.fails 2>/dev/null || echo 0)" -lt 2 ]; then
      # A labeled reduced-scale CPU fallback (written if the tunnel
      # stayed dead) is superseded by a real-chip run. Capped at 2
      # failures like profile/tune so a deterministically broken
      # study can't starve longctx/decode of the window.
      echo "[$(date +%T)] running agd convergence (200 steps x 3 runs)"
      if run_stage 2700 python -u tools/agd_convergence.py --steps 200 >> /tmp/agd_conv.log 2>&1; then
        echo "[$(date +%T)] agd ok"
      else
        rc=$?
        fails=$(( $(cat /tmp/agd_conv.fails 2>/dev/null || echo 0) + 1 ))
        echo "$fails" > /tmp/agd_conv.fails
        echo "[$(date +%T)] agd failed rc=$rc (failure $fails/2)"
      fi
    elif [ ! -f LONGCTX_r05.json ]; then
      echo "[$(date +%T)] running long-context bench"
      run_stage 1800 python -u tools/longctx_bench.py >> /tmp/longctx.log 2>&1
      echo "[$(date +%T)] longctx rc=$?"
    elif [ -f tools/decode_bench.py ] && [ ! -f DECODE_r05.json ]; then
      echo "[$(date +%T)] running decode bench"
      run_stage 1800 python -u tools/decode_bench.py >> /tmp/decode_bench.log 2>&1
      echo "[$(date +%T)] decode rc=$?"
    elif [ ! -f /tmp/capture_tune.done ]; then
      # Uncapped tune retry once everything else has landed.
      echo "[$(date +%T)] autotune retry (post-bench stages done)"
      run_stage 5400 env CAPTURE_STAGE=tune python -u tools/capture_perf.py >> /tmp/capture_perf.log 2>&1
      rc=$?
      [ $rc -eq 0 ] && touch /tmp/capture_tune.done
      echo "[$(date +%T)] tune retry rc=$rc"
    elif [ -f bench_tuned.json ] && [ ! -f /tmp/profile_tuned.txt ]; then
      # Attribution of the TUNED step (where does the winner's time
      # go) — spec comes straight from the pinned winner.
      spec=$(python -c "import json;print(json.load(open('bench_tuned.json'))['spec'])" 2>/dev/null)
      if [ -n "$spec" ]; then
        echo "[$(date +%T)] profiling the tuned winner: $spec"
        run_stage 900 python -u tools/profile_step.py "$spec" > /tmp/profile_tuned.partial 2>&1
        rc=$?
        # single attempt either way; keep the output (including the
        # failure diagnostics) rather than touching an empty file
        mv /tmp/profile_tuned.partial /tmp/profile_tuned.txt
        echo "[$(date +%T)] tuned profile rc=$rc"
      else
        echo "[$(date +%T)] bench_tuned.json has no readable spec; skipping tuned profile"
        touch /tmp/profile_tuned.txt
      fi
    else
      echo "[$(date +%T)] all jobs done"; exit 0
    fi
  else
    echo "[$(date +%T)] probe failed (try $i)"
  fi
  # PROBE_INTERVAL_S: test hook + operator knob; the deadline check
  # at the top of the loop bounds how stale a sleep can leave us.
  sleep "${PROBE_INTERVAL_S:-90}"
done
