#!/bin/bash
cd /root/repo
probe() { timeout 90 python -c "import jax.numpy as jnp; (jnp.ones((256,256))@jnp.ones((256,256))).sum()" >/dev/null 2>&1; }
for i in $(seq 1 200); do
  if probe; then
    echo "[$(date +%T)] probe ok (try $i)"
    if [ ! -f KERNELS_r04.json ]; then
      echo "[$(date +%T)] running kernel smoke"
      timeout 1800 python -u tools/tpu_kernel_smoke.py >> /tmp/kernel_smoke.log 2>&1
      echo "[$(date +%T)] smoke rc=$? (artifact: $(ls KERNELS_r04.json 2>/dev/null || echo none))"
    elif [ ! -f AGD_CONVERGENCE_r04.json ]; then
      echo "[$(date +%T)] running agd convergence (200 steps x 2)"
      timeout 2700 python -u tools/agd_convergence.py --steps 200 >> /tmp/agd_conv.log 2>&1
      echo "[$(date +%T)] agd rc=$?"
    elif [ ! -f LONGCTX_r04.json ]; then
      echo "[$(date +%T)] running long-context bench"
      timeout 1800 python -u tools/longctx_bench.py >> /tmp/longctx.log 2>&1
      echo "[$(date +%T)] longctx rc=$?"
    elif [ ! -f /tmp/final_sweep.txt ]; then
      echo "[$(date +%T)] final micro-sweep (offload/batch/xent-chunks)"
      { timeout 1200 python -u tools/perf_sweep.py \
          'offload,flash,18,1024,1024,-,nofn' \
          'full,flash,17,1024,1024,-,nofn' \
          'full,flash,19,1024,1024,-,nofn' ;
        SWEEP_XENT_CHUNKS=4 timeout 600 python -u tools/perf_sweep.py 'full,flash,18,1024,1024,-,nofn' ;
        SWEEP_XENT_CHUNKS=16 timeout 600 python -u tools/perf_sweep.py 'full,flash,18,1024,1024,-,nofn' ;
      } > /tmp/final_sweep.txt 2>&1
      echo "[$(date +%T)] final sweep done:"; cat /tmp/final_sweep.txt | grep -E "step=|FAILED"
    elif [ ! -f /tmp/profile_step.txt ]; then
      echo "[$(date +%T)] profiling the tuned step"
      timeout 900 python -u tools/profile_step.py 'full,flash,18,1024,1024,-,nofn' > /tmp/profile_step.txt 2>&1
      echo "[$(date +%T)] profile rc=$? ($(wc -l < /tmp/profile_step.txt) lines)"
    elif [ ! -f /tmp/bench_stability.json ]; then
      echo "[$(date +%T)] bench stability re-run"
      BENCH_MAX_WAIT_S=600 timeout 900 python bench.py > /tmp/bench_stability.json 2>/dev/null
      echo "[$(date +%T)] bench rc=$?: $(cat /tmp/bench_stability.json)"
    else
      echo "[$(date +%T)] all jobs done"; exit 0
    fi
  else
    echo "[$(date +%T)] probe failed (try $i)"
  fi
  sleep 90
done
