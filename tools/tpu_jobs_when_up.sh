#!/bin/bash
# Probe-gated chain of the round's hardware jobs: the moment the TPU
# tunnel answers, land (in order) the kernel smoke, the AGD
# convergence artifact, the long-context bench, a final micro-sweep,
# a step profile, and a bench stability re-run. Each stage's gate is
# an artifact written ONLY on success, so a tunnel drop mid-stage
# retries on the next probe instead of permanently skipping.
#
# Run:  nohup tools/tpu_jobs_when_up.sh >> /tmp/tpu_jobs.log 2>&1 &
set -u
cd "$(dirname "$0")/.." || exit 1

probe() {
  # The matmul alone would pass on jax's CPU fallback while the TPU
  # is down — assert the backend too.
  timeout 90 python -c "
import jax
assert jax.default_backend() in ('tpu', 'axon'), jax.default_backend()
import jax.numpy as jnp
(jnp.ones((256, 256)) @ jnp.ones((256, 256))).block_until_ready()
" >/dev/null 2>&1
}

# Hard deadline: stop well before the round's driver-side bench
# capture so two clients never contend for the single chip.
DEADLINE_EPOCH=${DEADLINE_EPOCH:-0}
for i in $(seq 1 200); do
  if [ "$DEADLINE_EPOCH" -gt 0 ] && [ "$(date +%s)" -ge "$DEADLINE_EPOCH" ]; then
    echo "[$(date +%T)] deadline reached; exiting to free the chip"
    exit 0
  fi
  if probe; then
    echo "[$(date +%T)] probe ok (try $i)"
    if [ ! -f KERNELS_r04.json ]; then
      echo "[$(date +%T)] running kernel smoke"
      timeout 1800 python -u tools/tpu_kernel_smoke.py >> /tmp/kernel_smoke.log 2>&1
      echo "[$(date +%T)] smoke rc=$? (artifact: $(ls KERNELS_r04.json 2>/dev/null || echo none))"
    elif [ ! -f AGD_CONVERGENCE_r04.json ]; then
      echo "[$(date +%T)] running agd convergence (200 steps x 2)"
      timeout 2700 python -u tools/agd_convergence.py --steps 200 >> /tmp/agd_conv.log 2>&1
      echo "[$(date +%T)] agd rc=$?"
    elif [ ! -f LONGCTX_r04.json ]; then
      echo "[$(date +%T)] running long-context bench"
      timeout 1800 python -u tools/longctx_bench.py >> /tmp/longctx.log 2>&1
      echo "[$(date +%T)] longctx rc=$?"
    elif [ ! -f /tmp/final_sweep.txt ]; then
      echo "[$(date +%T)] final micro-sweep (offload/batch/xent-chunks)"
      { timeout 1200 python -u tools/perf_sweep.py \
          'offload,flash,18,1024,1024,-,nofn' \
          'full,flash,17,1024,1024,-,nofn' \
          'full,flash,19,1024,1024,-,nofn' ;
        SWEEP_XENT_CHUNKS=4 timeout 600 python -u tools/perf_sweep.py 'full,flash,18,1024,1024,-,nofn' ;
        SWEEP_XENT_CHUNKS=16 timeout 600 python -u tools/perf_sweep.py 'full,flash,18,1024,1024,-,nofn' ;
      } > /tmp/final_sweep.partial 2>&1
      # Gate only on real results: at least one timed line.
      if grep -q "step=" /tmp/final_sweep.partial; then
        mv /tmp/final_sweep.partial /tmp/final_sweep.txt
        echo "[$(date +%T)] final sweep done:"; grep -E "step=|FAILED" /tmp/final_sweep.txt
      else
        echo "[$(date +%T)] final sweep produced no results; will retry"
      fi
    elif [ ! -f /tmp/profile_step.txt ]; then
      echo "[$(date +%T)] profiling the tuned step"
      if timeout 900 python -u tools/profile_step.py 'full,flash,18,1024,1024,-,nofn' > /tmp/profile_step.partial 2>&1; then
        mv /tmp/profile_step.partial /tmp/profile_step.txt
        echo "[$(date +%T)] profile ok ($(wc -l < /tmp/profile_step.txt) lines)"
      else
        echo "[$(date +%T)] profile failed rc=$?; will retry"
      fi
    elif [ ! -f /tmp/bench_stability.json ]; then
      echo "[$(date +%T)] bench stability re-run"
      BENCH_MAX_WAIT_S=600 timeout 900 python bench.py > /tmp/bench_stability.partial 2>>/tmp/bench_stability.err
      if grep -q '"error"' /tmp/bench_stability.partial || ! grep -q '"value"' /tmp/bench_stability.partial; then
        echo "[$(date +%T)] bench stability failed; will retry: $(cat /tmp/bench_stability.partial)"
      else
        mv /tmp/bench_stability.partial /tmp/bench_stability.json
        echo "[$(date +%T)] bench stability: $(cat /tmp/bench_stability.json)"
      fi
    else
      echo "[$(date +%T)] all jobs done"; exit 0
    fi
  else
    echo "[$(date +%T)] probe failed (try $i)"
  fi
  sleep 90
done
