"""Chaos soak runner: kill-the-master drills under injected RPC faults.

Each round spawns a REAL master subprocess (``dlrover_tpu.master.main``
with ``--state_dir``), runs a simulated agent against it through the
chaos injector (seeded drop/latency on every client RPC), SIGKILLs the
master mid-sharded-run, restarts it on the same port, and verifies the
control-plane survivability contract:

* the agent's connection supervisor rides out the outage (no RPC-path
  caller raises),
* the replacement master warm-restarts from the newest snapshot and
  emits ``master.warm_restart``,
* every dataset record is processed exactly once — the shard a worker
  held across the outage is neither lost nor re-dispatched.

Usage::

    python tools/chaos_drill.py --selftest          # seeded, <60s (CI)
    python tools/chaos_drill.py --rounds 5 --seed 7 # soak
    python tools/chaos_drill.py --json out.json

The fault schedule is deterministic from ``--seed`` (same seed -> same
injected faults), so a failing soak round is replayable.
"""

import _repo_path  # noqa: F401  (sys.path, must precede dlrover_tpu)

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

from dlrover_tpu.common import chaos
from dlrover_tpu.common.comm import RpcClient, find_free_port
from dlrover_tpu.common.config import ensure_framework_on_pythonpath
from dlrover_tpu.common import messages as msg
from dlrover_tpu.master.state_store import MasterStateStore
from dlrover_tpu.obs.timeline import load_events


class DrillError(AssertionError):
    pass


def start_master(
    port: int,
    state_dir: str,
    trace_file: str,
    extra_env=None,
    ready_timeout: float = 30.0,
) -> subprocess.Popen:
    """Spawn the real master CLI and wait until it answers RPCs."""
    env = ensure_framework_on_pythonpath(dict(os.environ))
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "DLROVER_TPU_TRACE_FILE": trace_file,
            # Journal eagerly: drills kill the master within seconds
            # of the last ledger change.
            "DLROVER_TPU_SNAPSHOT_MIN_INTERVAL": "0.05",
            "DLROVER_TPU_SNAPSHOT_SECONDS": "1",
            # The master must not inherit the drill's client-side
            # chaos env (it would fault its own loopback use).
            "DLROVER_TPU_CHAOS": "0",
        }
    )
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "dlrover_tpu.master.main",
            "--port", str(port),
            "--node_num", "1",
            "--rdzv_timeout", "2",
            "--heartbeat_timeout", "60",
            "--monitor_interval", "1",
            "--state_dir", state_dir,
        ],
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    probe = RpcClient(
        f"127.0.0.1:{port}", timeout=1.0, wait_for_ready=True
    )
    deadline = time.monotonic() + ready_timeout
    try:
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise DrillError(
                    f"master exited rc={proc.returncode} before ready"
                )
            try:
                probe.get(msg.KVStoreGetRequest(key="__ready_probe__"))
                return proc
            except Exception:  # noqa: BLE001 — not up yet
                time.sleep(0.1)
    finally:
        probe.close()
    proc.kill()
    raise DrillError(f"master not ready within {ready_timeout}s")


def wait_for_ledger(
    state_dir: str,
    dataset: str,
    doing_task_id: int,
    timeout: float = 15.0,
) -> dict:
    """Block until the newest valid snapshot records ``doing_task_id``
    as in-flight — the drill must not kill the master before the
    dispatch it asserts on is durable."""
    store = MasterStateStore(state_dir)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        doc = store.load_latest()
        if doc is not None:
            ds = (
                doc["state"]
                .get("task_manager", {})
                .get("datasets", {})
                .get(dataset)
            )
            if ds is not None and any(
                t.get("task_id") == doing_task_id
                for t in ds.get("state", {}).get("doing", [])
            ):
                return doc
        time.sleep(0.05)
    raise DrillError(
        f"ledger snapshot never recorded task {doing_task_id} as "
        f"doing within {timeout}s"
    )


def check_exactly_once(spans, total_records: int) -> None:
    """Every record in [0, total_records) covered exactly once."""
    seen = {}
    for start, end in spans:
        for r in range(start, end):
            seen[r] = seen.get(r, 0) + 1
    doubles = sorted(r for r, n in seen.items() if n > 1)
    missing = sorted(r for r in range(total_records) if r not in seen)
    if doubles:
        raise DrillError(
            f"records processed twice: {doubles[:10]} "
            f"({len(doubles)} total)"
        )
    if missing:
        raise DrillError(
            f"records never processed: {missing[:10]} "
            f"({len(missing)} total)"
        )


def run_drill(
    seed: int = 0,
    total_records: int = 64,
    batch_size: int = 4,
    kill_after_tasks: int = 3,
    drop_rate: float = 0.05,
    latency_ms: float = 2.0,
    reconnect_budget: float = 60.0,
    down_seconds: float = 0.0,
    keep_dir: bool = False,
) -> dict:
    """One kill+restart drill; returns a JSON-able report, raises
    :class:`DrillError` on any contract violation."""
    # Late imports: MasterClient pulls the agent layer.
    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.agent.sharding_client import ShardingClient

    tmpdir = tempfile.mkdtemp(prefix="chaos_drill_")
    state_dir = os.path.join(tmpdir, "state")
    trace_file = os.path.join(tmpdir, "trace.jsonl")
    port = find_free_port()
    t0 = time.monotonic()
    master = start_master(port, state_dir, trace_file)
    client = None
    try:
        client = MasterClient(f"127.0.0.1:{port}", node_id=0)
        client.supervisor.outage_budget = reconnect_budget
        client.supervisor.backoff_base = 0.1
        client.register_node()
        # Client-side chaos: every RPC from here on rides the seeded
        # fault schedule (drops surface as transient ConnectionErrors
        # the supervisor must absorb).
        chaos.install_injector(
            chaos.ChaosInjector(
                seed=seed,
                drop_rate=drop_rate,
                latency_ms=latency_ms,
                node_id=0,
            )
        )
        sharding = ShardingClient("drill", client=client)
        sharding.create_dataset(
            dataset_size=total_records,
            batch_size=batch_size,
            num_minibatches_per_shard=1,
        )
        processed = []
        killed = False
        t_kill = None
        restart_done = {}

        def restart_later():
            # Hold the outage open while the agent keeps issuing
            # RPCs (report_task_done / get_task below block in the
            # connection supervisor until we come back): with
            # down_seconds > ~6s this proves agents outlive the
            # legacy 3-fixed-retry window.
            if down_seconds > 0:
                time.sleep(down_seconds)
            restart_done["proc"] = start_master(
                port, state_dir, trace_file
            )
            restart_done["t_back"] = time.monotonic()

        restarter = None
        while True:
            task = sharding.get_task(timeout=120)
            if task is None:
                break
            if not killed and len(processed) + 1 >= kill_after_tasks:
                # This shard is DOING on the master and unreported by
                # us: the warm restart must keep it with node 0 —
                # re-queueing it would double-process, dropping it
                # would starve the epoch.
                wait_for_ledger(state_dir, "drill", task.task_id)
                master.kill()  # SIGKILL: no goodbye snapshot
                master.wait()
                t_kill = time.monotonic()
                restarter = threading.Thread(
                    target=restart_later, daemon=True
                )
                restarter.start()
                killed = True
            processed.append((task.shard.start, task.shard.end))
            sharding.report_task_done(task.task_id)
        if restarter is not None:
            restarter.join(timeout=60)
        if "proc" in restart_done:
            master = restart_done["proc"]
        t_back = restart_done.get("t_back")
        if not killed:
            raise DrillError(
                "drill finished before the kill point — dataset too "
                "small for kill_after_tasks"
            )
        check_exactly_once(processed, total_records)
        # Give the daemon-thread result reports a beat, then verify
        # the master agrees the dataset completed.
        deadline = time.monotonic() + 10
        probe = RpcClient(f"127.0.0.1:{port}", timeout=2.0)
        try:
            while time.monotonic() < deadline:
                ck = probe.get(
                    msg.ShardCheckpointRequest(dataset_name="drill")
                )
                state = json.loads(ck.content) if ck.content else {}
                if not state.get("todo") and not state.get("doing"):
                    break
                time.sleep(0.2)
            else:
                raise DrillError(
                    f"master still holds unfinished shards: {state}"
                )
        finally:
            probe.close()
        events = load_events(trace_file)
        warm = [
            e for e in events if e.get("name") == "master.warm_restart"
        ]
        if not warm:
            raise DrillError(
                "no master.warm_restart event in the trace — the "
                "replacement master cold-started"
            )
        report = {
            "seed": seed,
            "total_records": total_records,
            "shards_processed": len(processed),
            "outage_s": round((t_back or 0) - (t_kill or 0), 3),
            "reconnects": client.supervisor.reconnects,
            "outages": client.supervisor.outages,
            "warm_restart_events": len(warm),
            "warm_restart_alive_nodes": warm[0].get("alive_nodes"),
            "chaos_decisions": len(
                chaos.get_injector().decisions
                if chaos.get_injector() else ()
            ),
            "wall_s": round(time.monotonic() - t0, 3),
            "dir": tmpdir if keep_dir else None,
        }
        return report
    finally:
        chaos.install_injector(None)
        chaos.reset()
        if client is not None:
            client.close()
        if master.poll() is None:
            master.send_signal(signal.SIGTERM)
            try:
                master.wait(timeout=10)
            except subprocess.TimeoutExpired:
                master.kill()
        if not keep_dir:
            shutil.rmtree(tmpdir, ignore_errors=True)


def run_remediation_drill(seed: int = 11) -> dict:
    """Seeded slow-host remediation drill, fully in-process: a
    simulated 3-host fleet feeds a REAL master's RPC server through
    the chaos injector (seeded latency on every client call), one
    host degrades persistently and one flaps. The self-healing
    contract, asserted from the event trace:

    * the degrading host is cordoned (``node.cordon`` +
      ``remediation.cordon``) and replaced via a ScalePlan
      (``node.replace``),
    * after the replacement reports healthy, probation confirms
      recovery (``remediation.recovered``) and the sick pod is
      retired,
    * the flapping host is damped by hysteresis: ZERO remediation
      actions, zero scale plans touching it.
    """
    import dlrover_tpu.obs as obs
    from dlrover_tpu.master.job_manager import Scaler
    from dlrover_tpu.master.master import JobMaster

    tracer = obs.configure_tracer()  # in-memory ring
    cursor = 0
    scaler = Scaler()  # records ScalePlans
    master = JobMaster(
        port=0, node_num=3, min_nodes=2, rdzv_timeout=1.0,
        collect_interval=999.0, health_interval=9999.0,
        remediation_config={
            "interval_s": 9999.0,  # ticked manually, deterministically
            "hysteresis_ticks": 2,
            "recovery_ticks": 2,
            "probation_s": 300.0,
            "cooldown_s": 0.0,
            "blast_window_s": 600.0,
            "blast_max_actions": 1.0,
        },
        scaler=scaler,
    )
    master.prepare()
    client = None
    try:
        # Client-side chaos: every feed RPC rides the seeded latency
        # schedule (remediation must not depend on timely telemetry).
        chaos.install_injector(
            chaos.ChaosInjector(seed=seed, latency_ms=2.0, node_id=0)
        )
        client = RpcClient(master.addr)

        def snap(node_id, host, ts, step_time):
            return msg.MetricsSnapshotReport(
                node_id=node_id, host=host, timestamp=ts,
                registry={}, resource={"tokens_per_s": 500.0},
                step_times=[step_time], events=[],
            )

        for node_id, host in ((0, "h0"), (1, "h1"), (2, "h2")):
            client.report(
                msg.NodeAddressRequest(node_id=node_id, node_ip=host)
            )

        def feed(host_steps, span=240.0, n=25):
            now = time.time()
            for i in range(n):
                ts = now - span + i * (span / n)
                for node_id, host, fn in host_steps:
                    client.report(snap(node_id, host, ts, fn(ts, now)))

        ramp = lambda ts, now: 0.1 * (  # noqa: E731
            1.0 + max(0.0, ts - (now - 120.0)) / 40.0
        )
        flat = lambda ts, now: 0.1  # noqa: E731
        feed([(0, "h0", flat), (1, "h1", ramp), (2, "h2", ramp)])

        # Interleave: h1 stays sick every tick; h2 flaps (its history
        # clears between ticks, so its verdict resolves and re-fires)
        # — hysteresis (2 consecutive sick ticks) must damp h2 while
        # convicting h1.
        for round_ in range(3):
            master.health.evaluate_once()
            master.remediation.tick_once()
            master.timeseries.drop_label("host", "h2")  # h2 "recovers"
            master.health.evaluate_once()
            master.remediation.tick_once()
            feed([(2, "h2", ramp)])  # ...and relapses

        decisions = master.remediation.decisions()
        acted = [
            d for d in decisions
            if d.action == "cordon_replace" and d.node_id == 1
            and d.outcome in ("acted", "recovered")
        ]
        if len(acted) != 1:
            raise DrillError(
                "expected exactly one cordon_replace of node 1, got "
                f"{[(d.action, d.node_id, d.outcome) for d in decisions]}"
            )
        if any(d.node_id == 2 for d in decisions):
            raise DrillError(
                "flapping host h2 drew a remediation decision — "
                "hysteresis failed to damp it"
            )
        plans = list(scaler.executed_plans)
        launched = [n.id for p in plans for n in p.launch_nodes]
        if len(launched) != 1:
            raise DrillError(
                f"expected one replacement launch, got plans {plans}"
            )
        repl_id = acted[0].replacement_id

        # The replacement registers and reports healthy; h1 is gone
        # (its telemetry purged at cordon), so the fleet recovers.
        client.report(
            msg.NodeAddressRequest(node_id=repl_id, node_ip="h1b")
        )
        feed(
            [(0, "h0", flat), (2, "h2", flat), (repl_id, "h1b", flat)],
            span=140.0, n=15,
        )
        master.health.evaluate_once()
        for _ in range(3):
            master.remediation.tick_once()
        if acted[0].outcome != "recovered":
            raise DrillError(
                "probation did not confirm recovery: "
                f"{[(d.action, d.outcome) for d in master.remediation.decisions()]}"
            )
        if master.remediation.probation_failing():
            raise DrillError("probation_failing after recovery")
        from dlrover_tpu.common.constants import NodeStatus

        node1 = master.job_manager.get_node(1)
        if node1 is None or node1.status != NodeStatus.DELETED:
            raise DrillError(
                "cordoned node 1 was not retired after recovery: "
                f"{node1.status if node1 else None}"
            )

        events, cursor = tracer.events_since(cursor)
        names = [e.get("name") for e in events]
        for needle in (
            "node.cordon", "remediation.cordon", "node.replace",
            "remediation.decision", "remediation.recovered",
        ):
            if needle not in names:
                raise DrillError(
                    f"event {needle!r} missing from the drill trace"
                )
        cordons = [
            e for e in events if e.get("name") == "node.cordon"
        ]
        if [e.get("node_id") for e in cordons] != [1]:
            raise DrillError(
                f"cordon events not exactly [node 1]: {cordons}"
            )
        injector = chaos.get_injector()
        return {
            "seed": seed,
            "decisions": len(decisions),
            "replacement_id": repl_id,
            "chaos_decisions": len(
                injector.decisions if injector else ()
            ),
            "events": len(events),
        }
    finally:
        chaos.install_injector(None)
        chaos.reset()
        if client is not None:
            client.close()
        master.stop()


def check_schedule_reproducibility(seed: int = 1234, calls: int = 200):
    """Same seed + same call sequence -> identical fault schedule."""
    def schedule(s):
        inj = chaos.ChaosInjector(
            seed=s, drop_rate=0.2, error_rate=0.1, latency_ms=3.0,
            node_id=0,
        )
        out = []
        for i in range(calls):
            try:
                inj.before_client_call("get", object())
                out.append("pass")
            except chaos.ChaosDropError:
                out.append("fault")
        return out

    a, b = schedule(seed), schedule(seed)
    if a != b:
        raise DrillError("same seed produced different fault schedules")
    if a == schedule(seed + 1):
        raise DrillError(
            "different seeds produced identical fault schedules"
        )
    if "fault" not in a:
        raise DrillError("no faults injected at drop_rate=0.2")
    return {"calls": calls, "faults": a.count("fault")}


def selftest() -> int:
    """Seeded, hermetic, <60s: CI smoke for the chaos harness."""
    t0 = time.monotonic()
    repro = check_schedule_reproducibility()
    print(
        f"schedule reproducibility ok "
        f"({repro['faults']}/{repro['calls']} faults)"
    )
    report = run_drill(
        seed=7,
        total_records=32,
        batch_size=4,
        kill_after_tasks=2,
        drop_rate=0.05,
        latency_ms=1.0,
    )
    print(
        f"kill+restart drill ok: {report['shards_processed']} shards "
        f"exactly-once, outage {report['outage_s']}s, "
        f"{report['reconnects']} reconnect(s)"
    )
    rem = run_remediation_drill(seed=11)
    print(
        f"remediation drill ok: slow host cordoned+replaced "
        f"(replacement {rem['replacement_id']}), flapper damped, "
        f"{rem['chaos_decisions']} chaos decision(s)"
    )
    print(f"chaos drill selftest ok ({time.monotonic() - t0:.1f}s)")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("chaos_drill")
    parser.add_argument("--selftest", action="store_true",
                        help="seeded quick mode (<60s) for CI")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--rounds", type=int, default=1)
    parser.add_argument("--records", type=int, default=64)
    parser.add_argument("--batch", type=int, default=4)
    parser.add_argument("--kill_after", type=int, default=3)
    parser.add_argument("--drop_rate", type=float, default=0.05)
    parser.add_argument("--latency_ms", type=float, default=2.0)
    parser.add_argument(
        "--down_seconds", type=float, default=0.0,
        help="hold the master outage open this long before restart",
    )
    parser.add_argument("--json", type=str, default="",
                        help="write the soak report to this path")
    parser.add_argument("--keep_dir", action="store_true")
    args = parser.parse_args(argv)
    if args.selftest:
        return selftest()
    reports = []
    failures = 0
    for i in range(args.rounds):
        seed = args.seed + i
        try:
            rep = run_drill(
                seed=seed,
                total_records=args.records,
                batch_size=args.batch,
                kill_after_tasks=args.kill_after,
                drop_rate=args.drop_rate,
                latency_ms=args.latency_ms,
                down_seconds=args.down_seconds,
                keep_dir=args.keep_dir,
            )
            rep["ok"] = True
        except DrillError as e:
            failures += 1
            rep = {"seed": seed, "ok": False, "error": str(e)}
        print(json.dumps(rep))
        reports.append(rep)
    summary = {
        "rounds": args.rounds,
        "failures": failures,
        "reports": reports,
    }
    if args.json:
        with open(args.json, "w") as f:
            json.dump(summary, f, indent=2)
    print(
        f"chaos soak: {args.rounds - failures}/{args.rounds} rounds ok"
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
