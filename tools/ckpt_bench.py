"""Flash-checkpoint staging benchmark -> CKPT_r05.json (VERDICT r4
missing #5).

Measures, on a >= 1 GB state, what the reference publishes for its
async checkpoint design (/root/reference/docs/design/
async-checkpoint.md:31-40 — 2.3 s device->shm staging vs 6.5 s
blocking serialize+write for a 3 GB model):

* ``stage_s``       — save_to_memory: device->host copy + shm write,
                      the ONLY time the train loop is blocked;
* ``blocking_s``    — the alternative a trainer without the shm path
                      pays inline: device->host + pack_shard_file
                      serialize + storage write of the same state;
* ``persist_s``     — async latency from save_to_storage returning to
                      the agent's commit landing (trainer runs
                      meanwhile);
* ``restore_s``     — engine.load_flat of the committed checkpoint.

Runs on whatever backend jax has (the artifact records it): on the
TPU host the device->host copy is the real HBM transfer; on CPU it
degenerates to memcpy, which still measures the shm-vs-serialize
design point (serialization cost dominates the blocking path either
way).

Run:  python -u tools/ckpt_bench.py [--gb 1.0]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import time
import uuid

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--gb", type=float, default=1.0,
                    help="state size in GiB (default 1.0)")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--out", default=os.path.join(REPO, "CKPT_r05.json"))
    args = ap.parse_args()

    os.environ.setdefault("DLROVER_TPU_JOB_NAME",
                          f"ckptbench{uuid.uuid4().hex[:6]}")
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # The env var alone does not beat the preregistered axon TPU
        # plugin (tests/conftest.py has the same note); with the
        # tunnel down an axon init blocks for minutes.
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver
    from dlrover_tpu.trainer.flash_checkpoint.engine import (
        CheckpointEngine,
        pack_shard_file,
    )

    ckpt_dir = "/tmp/ckpt_bench/store"
    shutil.rmtree("/tmp/ckpt_bench", ignore_errors=True)
    os.makedirs(ckpt_dir, exist_ok=True)

    # A transformer-shaped state: a handful of big matmul weights plus
    # small vector leaves (biases/norms) so the pytree walk and entry
    # planning see realistic leaf-count structure, not one blob.
    leaf_mb = 64
    n_big = max(1, int(args.gb * 1024) // leaf_mb)
    rows = leaf_mb * 1024 * 1024 // (4 * 4096)
    key = jax.random.PRNGKey(0)
    state = {
        f"layer{i}": {
            "w": jax.random.normal(
                jax.random.fold_in(key, i), (rows, 4096), jnp.float32
            ),
            "b": jnp.ones((4096,), jnp.float32),
            "scale": jnp.float32(1.0),
        }
        for i in range(n_big)
    }
    jax.block_until_ready(state)
    nbytes = sum(
        x.nbytes for x in jax.tree_util.tree_leaves(state)
    )
    print(f"[ckpt] state: {nbytes / 2**30:.2f} GiB, "
          f"{len(jax.tree_util.tree_leaves(state))} leaves, "
          f"backend={jax.default_backend()}", flush=True)

    saver = AsyncCheckpointSaver(
        checkpoint_dir=ckpt_dir, local_shard_num=1, global_shard_num=1,
        commit_timeout=300.0,
    )
    saver.start()
    engine = CheckpointEngine(ckpt_dir, use_agent=True)
    rec: dict = {
        "state_gib": round(nbytes / 2**30, 3),
        "leaves": len(jax.tree_util.tree_leaves(state)),
        "backend": jax.default_backend(),
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    try:
        # -- staging (save_to_memory): first call creates/maps the shm
        # segment; steady state is the repeat. Report both.
        t0 = time.perf_counter()
        assert engine.save_to_memory(0, state)
        first = time.perf_counter() - t0
        stages = []
        for i in range(args.repeats):
            t0 = time.perf_counter()
            assert engine.save_to_memory(i + 1, state)
            stages.append(time.perf_counter() - t0)
        rec["stage_first_s"] = round(first, 3)
        rec["stage_s"] = round(min(stages), 3)
        rec["stage_all_s"] = [round(s, 3) for s in stages]
        print(f"[ckpt] save_to_memory: first={first:.2f}s "
              f"steady={min(stages):.2f}s", flush=True)

        # -- blocking baseline: device->host + serialize + write, the
        # inline cost a trainer without shm staging pays every save.
        blocking = []
        for i in range(args.repeats):
            t0 = time.perf_counter()
            arrays, total = engine._stage(state)
            payload = bytearray(total)
            for e, host in arrays:
                payload[e.offset:e.offset + e.nbytes] = (
                    host.tobytes() if not host.flags["C_CONTIGUOUS"]
                    else memoryview(host).cast("B")
                )
            data = pack_shard_file(0, [e for e, _ in arrays], {},
                                   bytes(payload))
            with open(f"/tmp/ckpt_bench/blocking_{i}.ckpt", "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            blocking.append(time.perf_counter() - t0)
            os.unlink(f"/tmp/ckpt_bench/blocking_{i}.ckpt")
        rec["blocking_s"] = round(min(blocking), 3)
        rec["blocking_all_s"] = [round(s, 3) for s in blocking]
        rec["blocking_over_stage"] = round(
            min(blocking) / max(min(stages), 1e-9), 2
        )
        print(f"[ckpt] blocking serialize+write: {min(blocking):.2f}s "
              f"({rec['blocking_over_stage']}x staging)", flush=True)

        # -- async persist latency: trainer-side call returns after
        # staging; the agent writes + commits in the background.
        step = args.repeats + 1
        t0 = time.perf_counter()
        assert engine.save_to_storage(step, state)
        returned = time.perf_counter() - t0
        assert engine.wait_persisted(step, timeout=300.0)
        persisted = time.perf_counter() - t0
        rec["save_to_storage_returns_s"] = round(returned, 3)
        rec["persist_s"] = round(persisted, 3)
        print(f"[ckpt] save_to_storage returned in {returned:.2f}s, "
              f"committed at {persisted:.2f}s", flush=True)

        # -- restore
        t0 = time.perf_counter()
        step_got, flat, _extra = engine.load_flat(step)
        restore = time.perf_counter() - t0
        assert step_got == step
        got = sum(v.nbytes for v in flat.values())
        assert got == nbytes, (got, nbytes)
        rec["restore_s"] = round(restore, 3)
        print(f"[ckpt] restore (load_flat): {restore:.2f}s", flush=True)

        # Sanity: restored bytes match a source leaf.
        import numpy as np

        np.testing.assert_array_equal(
            flat["layer0/w"], np.asarray(state["layer0"]["w"])
        )
        rec["verified"] = True
    finally:
        engine.close()
        saver.close()
        for shm in saver._shms:
            try:
                shm.unlink()
            except Exception:  # noqa: BLE001
                pass
        shutil.rmtree("/tmp/ckpt_bench", ignore_errors=True)

    json.dump(rec, open(args.out, "w"), indent=1)
    print(f"[ckpt] wrote {args.out}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
