"""Autotune the flash-attention BACKWARD block sizes on real hardware.

Sweeps (block_q_bwd, block_k_bwd) over the divisibility-chain-valid
grid at the shipped forward blocks (1024/1024 — the r4 sweep
optimum), full remat, batch 18,
fused CE without saved logits — the bench.py configuration — plus a fused-norm A/B,
and prints the ranked results with the winning bench spec.

Run (TPU):  python tools/autotune_bwd_blocks.py [--quick]
Each config costs one compile (~20-40 s cold; the persistent compile
cache makes re-runs cheap) + ~2 s of measurement.
"""

from __future__ import annotations

import argparse
import math
import sys

import _repo_path  # noqa: F401


def valid_chain(blocks) -> bool:
    return math.lcm(*blocks) <= 2 * max(blocks)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true",
                   help="only the most promising half of the grid")
    p.add_argument("--fwd", default="1024,1024",
                   help="forward block_q,block_k")
    args = p.parse_args()

    import jax

    if jax.default_backend() != "tpu":
        print("warning: not on TPU; timings are meaningless",
              file=sys.stderr)

    from perf_sweep import run_config  # noqa: E402
    from dlrover_tpu.parallel.mesh import (  # noqa: E402
        MeshConfig,
        build_mesh,
    )

    bq, bk = (int(x) for x in args.fwd.split(","))
    candidates = []
    sizes = (128, 256, 512, 1024)
    for bqb in sizes:
        for bkb in sizes:
            if args.quick and (bqb < 256 or bkb < 256):
                continue
            if valid_chain((bq, bk, bqb, bkb)):
                candidates.append((bqb, bkb))

    mesh = build_mesh(MeshConfig(data=len(jax.devices())))
    # Machine-readable device count: spec batch is GLOBAL for this
    # mesh, bench.py's BENCH_BATCH_PER_CHIP is per-chip — the capture
    # tool needs this line to convert units when pinning a winner.
    print(f"n_devices: {len(jax.devices())}")
    print(f"sweeping {len(candidates)} bwd-block configs at "
          f"fwd {bq}/{bk} (+ fused-norm A/B at defaults)")
    # Baseline A/B first: fused norms off (the r4-measured default)
    # vs forced ON — keep re-checking the A/B as kernels evolve.
    run_config(mesh, f"full,flash,18,{bq},{bk},-,nofn")
    run_config(mesh, f"full,flash,18,{bq},{bk},-,fn")
    # Layer-scan unroll sweep: the r5 step profile attributes ~16% of
    # step time to scan-carry dynamic-update-slice fusions; unrolling
    # lets XLA fuse across layers at the cost of program size. Also
    # re-check the remat choice at the unrolled optimum — the
    # full-remat win was measured rolled.
    for u in (2, 3, 4, 6, 12):
        run_config(mesh, f"full,flash,18,{bq},{bk},-,nofn,u{u}")
    run_config(mesh, f"none,flash,18,{bq},{bk},-,nofn,u4")
    run_config(mesh, f"dots,flash,18,{bq},{bk},-,nofn,u4")
    # save_attn: full recompute except the flash (o, lse) — skips the
    # second fwd-kernel run in the backward. Sweep it rolled and at
    # the unroll points since the two compose.
    run_config(mesh, f"sattn,flash,18,{bq},{bk},-,nofn")
    for u in (2, 4):
        run_config(mesh, f"sattn,flash,18,{bq},{bk},-,nofn,u{u}")
    # Fused-CE chunk count: the r5 trace prices the CE loops at
    # 35.5 ms/step with the f32 dwte accumulator re-read per chunk;
    # fewer chunks trade accumulator round-trips for logits HBM.
    for xc in (2, 4, 16):
        run_config(mesh, f"full,flash,18,{bq},{bk},-,nofn,xc{xc}")
    # Lever combinations: each pair/triple, so the winner isn't
    # hostage to one lever losing on hardware.
    run_config(mesh, f"sattn,flash,18,{bq},{bk},-,nofn,u4,xc4")
    run_config(mesh, f"sattn,flash,18,{bq},{bk},-,nofn,xc4")
    run_config(mesh, f"full,flash,18,{bq},{bk},-,nofn,u4,xc4")
    # Batch interacts with the new memory knobs (save_attn saves
    # more residuals, small xc holds bigger logits): re-check the
    # b18 optimum one notch up and down on the combined candidate.
    run_config(mesh, f"sattn,flash,20,{bq},{bk},-,nofn,u4,xc4")
    run_config(mesh, f"sattn,flash,16,{bq},{bk},-,nofn,u4,xc4")
    for bqb, bkb in candidates:
        run_config(mesh, f"full,flash,18,{bq},{bk},-,{bqb},{bkb},nofn")
    print("pick the fastest line; bench.py BENCH_* env then pins it")
    return 0


if __name__ == "__main__":
    sys.exit(main())
