"""Long-context attention benchmark on the real chip.

Long context is first-class (SURVEY §5): the flash kernel must hold
its throughput as T grows — an O(T^2)-HBM attention would OOM where
flash is merely compute-bound, and the sliding-window band should
approach T/(2W) speedup as dead kv blocks are skipped. This measures
flash fwd+bwd at long T (GPT-2-shaped heads) plus the banded variant,
and writes LONGCTX_r05.json.

Run:  python tools/longctx_bench.py [--max-t 32768]
"""

from __future__ import annotations

import json
import os
import sys
import time

import _repo_path  # noqa: F401

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax.numpy as jnp

from dlrover_tpu.ops.flash_attention import flash_attention


def bench(f, *args, n=10):
    out = f(*args)
    jax.block_until_ready(out)
    jax.device_get(jax.tree.leaves(out)[0].ravel()[0])
    t0 = time.time()
    for _ in range(n):
        out = f(*args)
    jax.block_until_ready(out)
    jax.device_get(jax.tree.leaves(out)[0].ravel()[0])
    return (time.time() - t0) / n


def main() -> int:
    max_t = 32768
    # --cpu-check: one tiny size, plumbing only (interpret-mode flash
    # at real long-context sizes is infeasible on CPU).
    cpu_check = "--cpu-check" in sys.argv
    for i, a in enumerate(sys.argv):
        if a == "--max-t":
            max_t = int(sys.argv[i + 1])
    h, d = 12, 64  # GPT-2 heads
    results = []
    t = 128 if cpu_check else 4096
    if cpu_check:
        max_t = t
    while t <= max_t:
        b = 1 if cpu_check else max(1, 32768 // t)
        q, k, v = (
            jax.random.normal(kk, (b, t, h, d), jnp.bfloat16)
            for kk in jax.random.split(jax.random.PRNGKey(0), 3)
        )

        def fwd_bwd(window=None):
            def loss(q, k, v):
                return jnp.sum(
                    flash_attention(
                        q, k, v, causal=True, window=window
                    ).astype(jnp.float32) ** 2
                )

            return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

        row = {"t": t, "batch": b, "heads": h, "head_dim": d}
        # Progress marker BEFORE the compile: the r5 tunnel died
        # mid-compile at t=8192 and the log couldn't say where.
        print(f"# t={t} b={b}: compiling + timing full...", flush=True)
        try:
            dt = bench(fwd_bwd(), q, k, v, n=1 if cpu_check else 10)
            # causal flash fwd+bwd ~ 3.5 * (T^2/2) * H * D * 2*B FLOPs
            flops = 3.5 * 0.5 * t * t * h * d * 2 * b * 2
            row["full_ms"] = round(dt * 1e3, 2)
            row["full_tflops"] = round(flops / dt / 1e12, 1)
            w = 4096
            if t > w:
                print(f"# t={t}: windowed (w={w})...", flush=True)
                dtw = bench(fwd_bwd(window=w), q, k, v)
                row["window4k_ms"] = round(dtw * 1e3, 2)
                row["window_speedup"] = round(dt / dtw, 2)
        except Exception as exc:  # noqa: BLE001
            row["error"] = f"{type(exc).__name__}: {str(exc)[:200]}"
        results.append(row)
        print(json.dumps(row), flush=True)
        # Checkpoint after every size: the r5 tunnel died mid-compile
        # at t=8192 and the all-at-the-end write lost the measured
        # t=4096 row with it. The round artifact still only lands
        # once a >=8k row has real numbers (the VERDICT bar); shorter
        # partials go to /tmp so a retry can see what happened.
        on_tpu = jax.default_backend() in ("tpu", "axon")
        out = {
            "backend": jax.default_backend(),
            "device": str(jax.devices()[0]),
            "results": results,
        }
        landed = any(
            r.get("full_ms") and r["t"] >= 8192 for r in results
        )
        path = (
            "LONGCTX_r05.json" if (on_tpu and not cpu_check and landed)
            else "/tmp/longctx_partial.json" if not cpu_check
            else "/tmp/longctx_check.json"
        )
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
        t *= 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
