"""Land the round's verified perf numbers the moment the TPU answers.

Unattended capture chain (VERDICT r4 item 1):

1. loop the health-gated bench until it succeeds -> PERF_r05.json
   gets a ``stage=baseline`` record;
2. run the backward-block autotune + fused-norm A/B
   (tools/autotune_bwd_blocks.py --quick) and pick the fastest line;
3. pin the winner via BENCH_BLOCKS / BENCH_FUSED_NORM and re-bench
   -> ``stage=tuned`` record.

Every successful measurement is appended to PERF_r05.json atomically,
so a tunnel outage mid-chain never erases landed results; the tuned
re-bench is retried a few times before giving up (the baseline record
survives regardless).

Cache-aware since the tune-cache PR: stage 1's bench record carries a
``tune_key``, and before spending the (long, chip-hogging) autotune
sweep stage 2 asks the persistent trial cache
(``dlrover_tpu/accelerate/tune_cache.py``) for the best recorded pins
under that key — a warm cache turns the whole sweep into a file read.
``--no-cache`` (or ``CAPTURE_NO_CACHE=1``) disables the cache for the
entire chain, children included, by exporting
``DLROVER_TPU_TUNE_CACHE=0``.

Run:  nohup python tools/capture_perf.py >/tmp/capture_perf.log 2>&1 &
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PERF = os.path.join(REPO, "PERF_r05.json")


def log(msg: str) -> None:
    print(f"[{time.strftime('%F %T')}] {msg}", flush=True)


def decode_output(v) -> str:
    """Normalize a ``TimeoutExpired`` capture attribute to text.

    ``exc.stdout``/``exc.stderr`` are None — or BYTES, ``text=True``
    notwithstanding — when the child is killed mid-pipe. Every
    TimeoutExpired handler under tools/ that reads them must route
    through here (regression-tested): the r5 autotune handler passed
    raw bytes to ``parse_autotune`` and the whole sweep's results
    were lost to a TypeError."""
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return v or ""


def stamp_meta(rec: dict) -> dict:
    """Provenance stamp (host/backend/jax versions) on a perf record;
    the bench child usually pre-stamps, this backfills older shapes.
    Best-effort: a record without a stamp still beats no record."""
    if "meta" not in rec:
        try:
            import _repo_path  # noqa: F401
            from dlrover_tpu.common.runmeta import run_metadata

            rec["meta"] = run_metadata(backend=rec.get("backend"))
        except Exception as exc:  # noqa: BLE001
            log(f"meta stamp failed: {exc!r}")
    return rec


def append_perf(rec: dict) -> None:
    """Append atomically. A hard-won measurement must survive even a
    corrupt history file: the record is salvaged to a side file and
    the chain continues (the corrupt original is never overwritten)."""
    rec = stamp_meta(rec)
    try:
        hist = []
        if os.path.exists(PERF):
            hist = json.load(open(PERF))
            assert isinstance(hist, list), f"{PERF} is not a list"
        hist.append(rec)
        tmp = PERF + ".tmp"
        json.dump(hist, open(tmp, "w"), indent=1)
        os.replace(tmp, PERF)
        log(f"PERF_r05.json <- {rec}")
    except Exception as exc:  # noqa: BLE001
        salvage = PERF + ".salvaged"
        with open(salvage, "a") as f:
            f.write(json.dumps(rec) + "\n")
        log(
            f"PERF history unusable ({exc!r}); record salvaged to "
            f"{salvage} — merge by hand"
        )


def final_beacon_stamp() -> dict:
    """The dead bench child's last progress stamp, read from the
    capture-scoped beacon file (main() pins DLROVER_TPU_BEACON_FILE so
    parent and children agree on the path). Empty when the child died
    before its first stamp."""
    try:
        import _repo_path  # noqa: F401
        from dlrover_tpu.obs import beacon as _beacon

        raw = _beacon.read_beacon()
        if not raw:
            return {}
        stamp = {
            k: raw.get(k)
            for k in ("pid", "step", "microbatch", "phase", "seq")
        }
        age = _beacon.stamp_age(raw)
        if age is not None:
            stamp["age_s"] = round(age, 1)
        return stamp
    except Exception as exc:  # noqa: BLE001 — forensics never kill
        # the capture chain
        log(f"beacon read failed: {exc!r}")
        return {}


def hang_record(timeout_s: float, stage: str) -> str:
    """A bench.py that WE had to kill never got to write its own
    failure record — append the kind-"hang" ledger record here, with
    the final beacon stamp, and return the one-line digest for the
    log. The blind seam this closes (ROADMAP item 1): a timed-out
    stage used to leave nothing but rc=124."""
    stamp = final_beacon_stamp()
    where = (
        f"last beacon stamp: step {stamp.get('step')} "
        f"{stamp.get('phase')} (age {stamp.get('age_s', '?')}s)"
        if stamp
        else "no beacon stamp (child died before its first stamp)"
    )
    rec = {
        "metric": "nanogpt_tokens_per_sec_per_chip",
        "value": 0.0,
        "unit": "tokens/s/chip",
        "vs_baseline": 0.0,
        "error": "tpu_hang",
        "kind": "hang",
        "detail": f"capture_perf killed bench.py at {timeout_s:.0f}s",
        "stage": stage or "adhoc",
    }
    if stamp:
        rec["beacon"] = stamp
    if os.getenv("BENCH_NO_LEDGER", "0") == "1":
        return f"{where}; ledger disabled (BENCH_NO_LEDGER=1)"
    try:
        import bench_ledger

        stored = bench_ledger.append_record(rec)
        ref = (
            f"{stored.get('ts')}@"
            f"{str(stored.get('git_rev', ''))[:12]}"
        )
        return f"hang ledger record {ref}; {where}"
    except Exception as exc:  # noqa: BLE001
        return f"hang ledger append failed: {exc!r}; {where}"


def run_bench(extra_env: dict, timeout_s: float) -> dict | None:
    """One bench.py run; returns the parsed JSON record or None.

    Failure diagnostics are logged here (stderr tail, timeout vs
    unparseable) — the unattended log must say WHY an attempt failed,
    not just that it did."""
    try:
        p = subprocess.run(
            [sys.executable, "bench.py"],
            env={**os.environ, **extra_env},
            capture_output=True,
            text=True,
            cwd=REPO,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired as exc:
        tail = (
            decode_output(exc.stderr) + decode_output(exc.output)
        )[-500:]
        log(
            f"bench.py timed out after {timeout_s:.0f}s"
            + (f"; tail: {tail}" if tail else " (no output captured)")
        )
        log(
            hang_record(
                timeout_s, extra_env.get("BENCH_LEDGER_STAGE", "")
            )
        )
        return None
    for line in p.stdout.splitlines():
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                log(f"unparseable bench JSON line: {line[:300]}")
                return None
    log(
        f"bench.py rc={p.returncode}, no JSON line; stderr tail: "
        f"{(p.stderr or '')[-500:]}"
    )
    return None


def winner_env(spec: str, n_chips: int = 1) -> dict:
    """Map a perf_sweep spec (the autotune's fastest line) onto the
    BENCH_* pins bench.py reads. Field layout: perf_sweep.build_spec —
    remat,flash,batch,bq,bk,sl[,bqb,bkb], 'nofn' strippable flag."""
    parts = spec.split(",")
    # Pin fused norms only when the winner spec forced them; an
    # unflagged spec ran the config default (off since r4), which is
    # also bench.py's default - no pin needed.
    fused = None
    if "nofn" in parts:
        fused = "0"
    elif "fn" in parts:
        fused = "1"
    from perf_sweep import is_unroll_token, is_xent_token

    unroll = xent = None
    for p in parts:
        if is_unroll_token(p):
            unroll = p[1:]
        elif is_xent_token(p):
            xent = p[2:]
    parts = [
        p for p in parts
        if p not in ("nofn", "fn")
        and not is_unroll_token(p) and not is_xent_token(p)
    ]

    def blk(i, default):
        if len(parts) <= i or parts[i] == "-":
            return default
        return int(parts[i])

    bq = blk(3, 512)
    bk = blk(4, 1024)
    bqb = blk(6, bq)
    bkb = blk(7, bk)
    env = {"BENCH_BLOCKS": f"{bq},{bk},{bqb},{bkb}"}
    # Unit conversion: the sweep spec's batch is GLOBAL across its
    # mesh; bench.py's knob is per-chip (batch = knob * n_chips).
    batch = blk(2, 18)
    per_chip = max(1, batch // max(1, n_chips))
    if per_chip != 18:  # bench.py's default batch-per-chip
        env["BENCH_BATCH_PER_CHIP"] = str(per_chip)
    if fused is not None:
        env["BENCH_FUSED_NORM"] = fused
    if unroll is not None:
        env["BENCH_UNROLL"] = unroll
    if xent is not None:
        env["BENCH_XENT_CHUNKS"] = xent
    if parts and parts[0] != "full":
        # bench.py defaults to full remat; pin any other winner.
        # Sweep tokens are build_spec's grammar ("attn" etc.); bench
        # wants remat.py policy names, so map through the same table.
        env["BENCH_REMAT"] = {
            "attn": "attention", "sattn": "save_attn"
        }.get(parts[0], parts[0])
    return env


def persist_winner(pins: dict, tuned_rec: dict, spec: str) -> None:
    """Write the tuned pins to bench_tuned.json at the repo root when
    the tuned record beats the baseline by more than measurement
    noise. bench.py loads this file as its defaults (explicit BENCH_*
    env still wins), so the driver's end-of-round capture runs the
    best measured config even if no one edits the code defaults
    before then. Threshold: +0.5% — half the 3-run stability spread
    (STABILITY_r05.json: 1.26%) so a within-noise 'winner' never
    displaces the known-good shipped defaults."""
    try:
        with open(os.path.join(REPO, "PERF_r05.json")) as f:
            base = [
                r for r in json.load(f) if r.get("stage") == "baseline"
            ]
    except Exception:  # noqa: BLE001
        base = []
    if not base:
        return
    if tuned_rec["value"] <= base[-1]["value"] * 1.005:
        log(
            f"tuned {tuned_rec['value']} within noise of baseline "
            f"{base[-1]['value']}; not pinning"
        )
        return
    # Atomic (tmp + replace): a SIGKILL mid-write must never leave a
    # truncated file for every later bench run to trip over.
    path = os.path.join(REPO, "bench_tuned.json")
    with open(path + ".tmp", "w") as f:
        json.dump(
            {"pins": pins, "spec": spec,
             "tuned_value": tuned_rec["value"],
             "baseline_value": base[-1]["value"],
             "ts": tuned_rec.get("ts")},
            f, indent=1,
        )
    os.replace(path + ".tmp", path)
    log(f"pinned winner to bench_tuned.json: {pins}")


def _load_tune_cache_mod():
    """Load accelerate/tune_cache.py WITHOUT importing the accelerate
    package (whose ``__init__`` pulls jax; this parent must stay
    jax-free so a wedged tunnel can never hang it). The module's own
    imports only touch the jax-free common/ and obs/ packages."""
    import importlib.util

    import _repo_path  # noqa: F401 — repo root onto sys.path

    path = os.path.join(
        REPO, "dlrover_tpu", "accelerate", "tune_cache.py"
    )
    spec = importlib.util.spec_from_file_location(
        "_capture_tune_cache", path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def cached_pins(tune_key: str | None) -> dict | None:
    """Best cached BENCH_* pins for ``tune_key``, or None (no key, no
    cache, cache disabled, nothing recorded). Consulted before the
    autotune sweep so a warm cache skips it entirely."""
    if not tune_key:
        return None
    try:
        tc = _load_tune_cache_mod()
        cache = tc.resolve()
        if cache is None:
            return None
        best = cache.best(tune_key)
        if best and isinstance(best.get("config"), dict):
            pins = best["config"].get("pins") or {}
            if pins:
                return {k: str(v) for k, v in pins.items()}
    except Exception as exc:  # noqa: BLE001 — a broken cache must
        # degrade to "run the sweep", never kill the chain
        log(f"tune cache consult failed: {exc!r}")
    return None


def last_recorded_tune_key() -> str | None:
    """Best-effort ``tune_key`` from the bench ledger — the tune-only
    mode's fallback when no baseline record from this process carries
    one. ``CAPTURE_TUNE_KEY`` pins it explicitly.

    Not simply "the newest record": baseline-stage records are
    preferred (they carry the shipped-defaults key of the problem the
    chain is measuring), and among equals a non-cpu backend wins — an
    ad-hoc CPU smoke bench appending the newest record must not hand
    the TPU chain a key whose cached pins were tuned for a different
    backend/model (the chain would silently skip the ~45-min sweep on
    their strength)."""
    explicit = os.getenv("CAPTURE_TUNE_KEY")
    if explicit:
        return explicit
    path = os.getenv(
        "DLROVER_TPU_BENCH_LEDGER", ""
    ) or os.path.join(REPO, "BENCH_LEDGER.jsonl")
    recs = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and rec.get("tune_key"):
                    recs.append(rec)
    except OSError:
        pass
    if not recs:
        return None
    # An absent backend field is unknown, not cpu — legacy records
    # keep their old "newest wins" rank.
    best = max(
        enumerate(recs),
        key=lambda ir: (
            ir[1].get("stage") == "baseline",
            ir[1].get("backend") != "cpu",
            ir[0],
        ),
    )[1]
    if best.get("backend") == "cpu":
        log(
            "warning: tune-key fallback found only cpu-backend ledger "
            f"records; using key {best['tune_key']} from a cpu run"
        )
    return best["tune_key"]


def run_autotune(timeout_s: float = 2700) -> str:
    """One quick autotune sweep; returns its stdout as TEXT even on
    timeout (the r5 regression: ``exc.stdout`` arrives as bytes when
    the child dies mid-pipe, and feeding bytes to ``parse_autotune``
    threw the partial sweep away)."""
    try:
        p = subprocess.run(
            [sys.executable, "tools/autotune_bwd_blocks.py", "--quick"],
            capture_output=True,
            text=True,
            cwd=REPO,
            timeout=timeout_s,
        )
        return p.stdout or ""
    except subprocess.TimeoutExpired as exc:
        log("autotune timed out; using partial results")
        return decode_output(exc.stdout)


def parse_autotune(out: str) -> tuple | None:
    """Best (spec, tok_s) from perf_sweep result lines. Ranked by
    tokens/s, NOT step time — the sweep now varies batch size, and a
    smaller batch always wins on raw step-ms while losing on
    throughput (the metric bench.py reports)."""
    best = None
    for line in out.splitlines():
        m = re.match(
            r"^(\S+)\s+step=\s*[0-9.]+ms\s+tok/s=\s*([0-9.]+)", line
        )
        if m:
            spec, tok_s = m.group(1), float(m.group(2))
            if best is None or tok_s > best[1]:
                best = (spec, tok_s)
    return best


def main() -> int:
    # Capture-scoped beacon file, inherited by every bench child (its
    # own setdefault defers to ours): when a child has to be killed,
    # final_beacon_stamp() knows where its last position landed.
    os.environ.setdefault(
        "DLROVER_TPU_BEACON_FILE",
        os.path.join(
            os.getenv("TMPDIR", "/tmp"),
            f"dlrover_tpu_beacon_capture_{os.getpid()}.json",
        ),
    )

    # CAPTURE_STAGE gates which stages run so the unattended chain can
    # land the cheap baseline record first and defer the long autotune:
    #   baseline — stage 1 only;  tune — stages 2-3 only;  all (default).
    stage_sel = os.environ.get("CAPTURE_STAGE", "all")

    # --no-cache / CAPTURE_NO_CACHE=1: the escape hatch for a clean
    # re-sweep. Exported so the bench children inherit it too (no pin
    # application, no trial recording anywhere in the chain).
    if (
        "--no-cache" in sys.argv[1:]
        or os.getenv("CAPTURE_NO_CACHE", "0") == "1"
    ):
        os.environ["DLROVER_TPU_TUNE_CACHE"] = "0"
        log("tune cache disabled for this capture chain (--no-cache)")

    baseline_rec = None

    # Stage 1: baseline, looped until the tunnel answers.
    if stage_sel in ("baseline", "all"):
        attempt = 0
        while True:
            attempt += 1
            rec = run_bench(
                {
                    "BENCH_MAX_WAIT_S": "600",
                    "BENCH_PROBE_TIMEOUT": "90",
                    # True shipped defaults: a bench_tuned.json from
                    # an earlier tune pass must not leak into the
                    # baseline this stage records (the tuned gate
                    # compares against this number).
                    "BENCH_IGNORE_TUNED": "1",
                    # Stage label for the bench ledger record the
                    # child appends (tools/bench_ledger.py).
                    "BENCH_LEDGER_STAGE": "baseline",
                },
                timeout_s=1800,
            )
            if rec and not rec.get("error"):
                rec.update(
                    stage="baseline",
                    config="shipped defaults",
                    ts=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                )
                append_perf(rec)
                baseline_rec = rec
                break
            log(f"baseline attempt {attempt}: {rec}")
            if stage_sel == "baseline" and attempt >= 2:
                log("baseline-only mode: giving the chip back after 2 tries")
                return 1
            time.sleep(90)
    if stage_sel == "baseline":
        return 0

    # Stage 2: consult the persistent trial cache BEFORE spending the
    # sweep — on TPU every avoided dry-run is tens of seconds of chip
    # time, and the sweep is ~45 min of it. The baseline record (or,
    # in tune-only mode, the newest ledger record) carries the key.
    tune_key = (baseline_rec or {}).get(
        "tune_key"
    ) or last_recorded_tune_key()
    pins = cached_pins(tune_key)
    if pins is not None:
        spec = "tune_cache"
        log(
            f"tune cache hit for key {tune_key}: skipping the "
            f"autotune sweep; pins={pins}"
        )
    else:
        # Cold cache: autotune sweep (partial output still usable on
        # timeout).
        log("autotune sweep starting")
        out = run_autotune()
        best = parse_autotune(out)
        if best is None:
            log("no autotune results; stopping after baseline")
            # In tune-only mode the job chain keys its done-marker on
            # rc=0; an empty autotune usually means the tunnel died
            # mid-sweep, so report retryable and let the next probe
            # re-enter the stage.
            return 2 if stage_sel == "tune" else 0
        spec, tok_s = best
        m = re.search(r"^n_devices:\s*(\d+)", out, re.M)
        n_chips = int(m.group(1)) if m else 1
        log(f"autotune winner: {spec} at {tok_s:.0f} tok/s "
            f"(sweep mesh: {n_chips} chip(s))")
        pins = winner_env(spec, n_chips)

    # Stage 3: tuned re-bench with the winner pinned.
    for i in range(3):
        rec = run_bench(
            {
                **pins,
                "BENCH_MAX_WAIT_S": "600",
                "BENCH_PROBE_TIMEOUT": "90",
                "BENCH_LEDGER_STAGE": "tuned",
            },
            timeout_s=1800,
        )
        if rec and not rec.get("error"):
            rec.update(
                stage="tuned",
                config=f"{spec} -> {pins}",
                ts=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            )
            append_perf(rec)
            persist_winner(pins, rec, spec)
            return 0
        log(f"tuned re-bench attempt {i + 1}: {rec}")
        time.sleep(90)
    # Distinct from the terminal rc=0 cases (tuned record landed, or
    # autotune produced nothing to pin): a tunnel drop here is
    # RETRYABLE — the job chain keys its done-marker on rc=0, so
    # returning nonzero makes the next probe re-enter this stage.
    log("tuned re-bench never landed (tunnel drop?); will retry")
    return 2


if __name__ == "__main__":
    sys.exit(main())
