"""Serving-plane chaos drill: replica kill under synthetic traffic.

The serving counterpart of ``tools/chaos_drill.py``: an in-process
master (router + health plane + remediation engine, deterministically
ticked) fronts REAL replica subprocesses
(``python -m dlrover_tpu.serving.replica``, seed-identical tiny
models). Synthetic greedy traffic streams through the fleet, one
replica is SIGKILLed mid-flight, and the drill asserts the serving
survivability contract:

* **zero dropped requests** — every submitted request completes; the
  killed replica's in-flight work is requeued (``serve.requeue``) and
  finished by the survivor, so the kill costs latency, not requests;
* **bounded p99** — end-to-end latency stays under the drill bound
  through the failover;
* **the kill is visible in the control plane** — a
  ``replica_unhealthy`` health verdict convicts the stalled replica,
  the remediation ladder's drain rung fires
  (``remediation.drain_replica``), and the node watchdog retires the
  dead node (``serve.replica_gone``);
* **failover is correct, not just complete** — requeued requests'
  greedy tokens equal an in-process reference ``generate.generate``
  on the same seed model (recompute-on-failover is exact for greedy
  decode).

Usage::

    python tools/serve_drill.py --selftest        # seeded, <90s (CI)
    python tools/serve_drill.py --requests 64 --seed 3
    python tools/serve_drill.py --json out.json
"""

import _repo_path  # noqa: F401  (sys.path, must precede dlrover_tpu)

import argparse
import json
import os
import subprocess
import sys
import time

from dlrover_tpu.common.config import ensure_framework_on_pythonpath
from dlrover_tpu.common.constants import replica_node_id


class DrillError(AssertionError):
    pass


def spawn_replica(
    master_addr: str,
    replica_id: int,
    seed: int,
    max_len: int = 48,
    role: str = "mixed",
    lanes: int = 2,
    prefill_chunk: int = 8,
    pull_batch: int = 2,
    prefill_budget: int = 0,
) -> subprocess.Popen:
    env = ensure_framework_on_pythonpath(dict(os.environ))
    env["JAX_PLATFORMS"] = "cpu"
    env["DLROVER_TPU_CHAOS"] = "0"
    return subprocess.Popen(
        [
            sys.executable,
            "-m", "dlrover_tpu.serving.replica",
            "--master", master_addr,
            "--replica_id", str(replica_id),
            "--seed", str(seed),
            "--lanes", str(lanes),
            "--block_size", "8",
            "--prefill_chunk", str(prefill_chunk),
            "--prefill_budget", str(prefill_budget),
            "--max_len", str(max_len),
            "--heartbeat_interval", "0.5",
            "--stats_interval", "0.5",
            "--pull_batch", str(pull_batch),
            "--role", role,
        ],
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )


def run_serving_drill(
    seed: int = 7,
    replicas: int = 2,
    requests: int = 20,
    max_new: int = 24,
    p99_bound_s: float = 45.0,
    deadline_s: float = 150.0,
    verify_outputs: int = 4,
) -> dict:
    """One replica-kill drill; returns a JSON-able report, raises
    :class:`DrillError` on any contract violation."""
    import numpy as np

    import dlrover_tpu.obs as obs
    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.master.master import JobMaster

    tracer = obs.configure_tracer()  # in-memory ring
    t0 = time.monotonic()
    master = JobMaster(
        port=0,
        node_num=2,
        rdzv_timeout=1.0,
        heartbeat_timeout=6.0,
        monitor_interval=0.5,
        collect_interval=999.0,
        health_interval=9999.0,  # ticked manually, deterministically
        remediation_config={
            "interval_s": 9999.0,  # ticked manually
            "hysteresis_ticks": 2,
            "cooldown_s": 0.0,
            "blast_window_s": 600.0,
            "blast_max_actions": 4.0,
            "probation_s": 300.0,
        },
        serving_config={
            "progress_timeout_s": 1.5,
            "scale_cooldown_s": 9999.0,
        },
    )
    # Critical conviction at 1x the progress timeout: the drill's
    # remediation path must outrun the 6s heartbeat watchdog so BOTH
    # failover paths (drain + node-gone) are exercised.
    master.health._config["replica_stall_crit_ratio"] = 1.0
    master.prepare()
    procs = {}
    client = None
    killed_id = None
    try:
        for rid in range(replicas):
            procs[replica_node_id(rid)] = spawn_replica(
                master.addr, rid, seed
            )
        client = MasterClient(master.addr, node_id=-1)

        def ready_count():
            snap = master.serving.snapshot()
            return sum(
                1 for r in snap["replicas"]
                if r["state"] == "ready"
            )

        deadline = time.monotonic() + 60
        while ready_count() < replicas:
            if time.monotonic() > deadline:
                raise DrillError(
                    f"only {ready_count()}/{replicas} replicas "
                    "registered within 60s"
                )
            for node_id, proc in procs.items():
                if proc.poll() is not None:
                    raise DrillError(
                        f"replica {node_id} exited rc="
                        f"{proc.returncode} before registering"
                    )
            time.sleep(0.2)

        # Seeded traffic: prompts the reference model can replay.
        rng = np.random.default_rng(seed)
        from dlrover_tpu.serving.replica import build_tiny_model

        ref_params, ref_cfg = build_tiny_model(seed, block_size=64)
        prompts = {}
        rids = []
        for i in range(requests):
            plen = int(rng.integers(3, 12))
            prompt = rng.integers(
                0, ref_cfg.vocab_size, size=plen
            ).tolist()
            resp = client.serve_submit(
                prompt, max_new_tokens=max_new, temperature=0.0,
                request_id=f"drill-{i}",
            )
            if not resp.accepted:
                raise DrillError(f"submit {i} rejected")
            prompts[resp.request_id] = prompt
            rids.append(resp.request_id)

        def states():
            out = {}
            for rid in rids:
                r = client.serve_result(rid)
                out[rid] = r
            return out

        # Let the fleet chew until the kill point: some requests
        # done, and the victim replica holds in-flight work (so the
        # kill leaves requests to rescue).
        kill_deadline = time.monotonic() + 60
        victim_node = None
        while victim_node is None:
            if time.monotonic() > kill_deadline:
                raise DrillError(
                    "no replica accumulated in-flight work to kill"
                )
            st = states()
            done_n = sum(
                1 for r in st.values() if r.state == "done"
            )
            in_flight = [
                r for r in st.values() if r.state == "dispatched"
            ]
            if done_n >= max(requests // 10, 1) and in_flight:
                victim_node = in_flight[0].replica_id
            else:
                time.sleep(0.05)
        t_kill = time.monotonic()
        procs[victim_node].kill()
        procs[victim_node].wait()
        killed_id = victim_node
        print(
            f"[drill] killed replica {victim_node} at "
            f"+{t_kill - t0:.1f}s", flush=True,
        )

        # Drive the verdict -> remediation pipeline deterministically
        # while traffic finishes on the survivor.
        end = time.monotonic() + deadline_s
        last_tick = 0.0
        while time.monotonic() < end:
            now = time.monotonic()
            if now - last_tick >= 0.4:
                last_tick = now
                master.health.evaluate_once()
                master.remediation.tick_once()
            st = states()
            if all(r.state == "done" for r in st.values()):
                break
            if any(r.state == "failed" for r in st.values()):
                bad = {
                    rid: r.error for rid, r in st.items()
                    if r.state == "failed"
                }
                raise DrillError(f"requests FAILED: {bad}")
            time.sleep(0.1)
        st = states()
        incomplete = {
            rid: r.state for rid, r in st.items()
            if r.state != "done"
        }
        if incomplete:
            raise DrillError(
                f"requests dropped/incomplete after "
                f"{deadline_s:.0f}s: {incomplete}"
            )

        # Zero drops + bounded p99 (the same nearest-rank formula
        # the router's exported gauge uses).
        from dlrover_tpu.obs.timeseries import _percentile

        latencies = sorted(r.latency_s for r in st.values())
        p99 = _percentile(latencies, 99.0)
        if p99 > p99_bound_s:
            raise DrillError(
                f"p99 {p99:.1f}s exceeds bound {p99_bound_s}s"
            )
        requeued = [rid for rid in rids if st[rid].requeues > 0]
        if not requeued:
            raise DrillError(
                "no request was requeued — the kill victim held "
                "in-flight work, so the failover path never ran"
            )

        # Failover correctness: requeued requests' greedy tokens
        # must equal the reference model's (recompute is exact).
        import jax.numpy as jnp

        from dlrover_tpu.models import generate

        mismatches = []
        for rid in requeued[:verify_outputs]:
            out = generate.generate(
                ref_params, ref_cfg,
                jnp.asarray([prompts[rid]], jnp.int32),
                max_new_tokens=max_new, temperature=0.0,
            )
            want = np.asarray(out)[0, len(prompts[rid]):].tolist()
            if st[rid].tokens != want:
                mismatches.append((rid, st[rid].tokens, want))
        if mismatches:
            raise DrillError(
                f"requeued outputs diverged from reference: "
                f"{mismatches}"
            )

        # The control plane saw the kill: verdict + drain + requeue
        # (+ the node watchdog retiring the dead node).
        events, _ = tracer.events_since(0)
        names = [e.get("name") for e in events]
        verdicts = [
            e for e in events
            if e.get("name") == "health.verdict"
            and e.get("detector") == "replica_unhealthy"
        ]
        if not any(v.get("node_id") == killed_id for v in verdicts):
            raise DrillError(
                "no replica_unhealthy verdict for the killed "
                f"replica in the trace ({len(verdicts)} verdicts)"
            )
        for needle in ("serve.requeue", "remediation.drain_replica"):
            if needle not in names:
                raise DrillError(
                    f"event {needle!r} missing from the drill trace"
                )
        drains = [
            e for e in events
            if e.get("name") == "remediation.drain_replica"
        ]
        if not any(d.get("node_id") == killed_id for d in drains):
            raise DrillError(
                f"drain decisions {drains} never targeted the "
                f"killed replica {killed_id}"
            )

        # -- distributed-trace acceptance (ISSUE 13): the surviving
        # trace of a requeued request, assembled SERVER-side and
        # fetched via query_traces, shows >=2 replica hops with
        # monotonic non-overlapping phase spans; TTFT phase
        # histograms sum to the observed TTFT; and the remediation
        # decision trace links verdict -> drain -> requeue.
        probe = requeued[0]
        trace_id = st[probe].trace_id
        if not trace_id:
            raise DrillError(f"request {probe} carries no trace_id")
        tq = client.query_traces(trace_id=trace_id)
        if not tq.enabled or not tq.traces:
            raise DrillError(
                f"query_traces({trace_id}) returned nothing "
                f"(enabled={tq.enabled})"
            )
        spans = tq.traces[0]["spans"]
        hops = [s for s in spans if s["name"] == "serve.hop"]
        hop_replicas = {
            s["tags"].get("replica_id") for s in hops
        }
        if len(hops) < 2 or len(hop_replicas) < 2:
            raise DrillError(
                f"requeued request {probe}: expected >=2 hops on "
                f">=2 replicas, got {len(hops)} hop(s) on "
                f"{sorted(hop_replicas)}"
            )

        def check_monotonic(what, group):
            ordered = sorted(group, key=lambda s: s["start_ts"])
            prev_end, prev_name = None, ""
            for s in ordered:
                if (
                    prev_end is not None
                    and s["start_ts"] < prev_end - 1e-6
                ):
                    raise DrillError(
                        f"{what} spans overlap: {s['name']} starts "
                        f"{prev_end - s['start_ts']:.6f}s before "
                        f"{prev_name} ends"
                    )
                prev_end = s["start_ts"] + s["dur_s"]
                prev_name = s["name"]

        check_monotonic(f"{probe} hop", hops)
        phase_span_names = (
            "serve.dispatch", "serve.prefill", "serve.first_token",
            "serve.decode",
        )
        phase_spans = [
            s for s in spans if s["name"] in phase_span_names
        ]
        if len(phase_spans) != 4:
            raise DrillError(
                f"expected the 4 TTFT phase spans on {probe}'s "
                f"completing hop, got "
                f"{sorted(s['name'] for s in phase_spans)}"
            )
        check_monotonic(f"{probe} phase", phase_spans)
        ph = st[probe].phases
        ttft_total = ph.get("ttft_total", 0.0)
        phase_sum = sum(
            ph.get(k, 0.0)
            for k in ("queue", "dispatch", "prefill", "first_decode")
        )
        if abs(phase_sum - ttft_total) > 1e-3:
            raise DrillError(
                f"TTFT phases {ph} sum to {phase_sum:.6f}s != "
                f"observed total {ttft_total:.6f}s"
            )
        # The replica-reported TTFT must agree with its own phase
        # split (prefill + first_decode span admit -> first token).
        replica_sum = ph.get("prefill", 0.0) + ph.get(
            "first_decode", 0.0
        )
        if abs(replica_sum - st[probe].ttft_s) > 0.05:
            raise DrillError(
                f"replica phases sum {replica_sum:.4f}s diverges "
                f"from reported ttft_s {st[probe].ttft_s:.4f}s"
            )
        # Histogram cross-check: per-phase sums across ALL completed
        # requests equal the observed totals within tolerance.
        hist = obs.get_registry().get(
            "dlrover_serve_ttft_phase_seconds"
        )
        hist_total = sum(
            hist.sum(phase=p)
            for p in ("queue", "dispatch", "prefill", "first_decode")
        )
        observed_total = sum(
            r.phases.get("ttft_total", 0.0) for r in st.values()
        )
        if abs(hist_total - observed_total) > 1e-3 * max(
            len(st), 1
        ) + 1e-6:
            raise DrillError(
                f"TTFT phase histogram sums {hist_total:.6f}s != "
                f"observed TTFT total {observed_total:.6f}s"
            )

        # The remediation decision trace for the killed replica:
        # verdict -> drain -> requeue linked by ONE trace id.
        rq = client.query_remediation()
        drain_decisions = [
            dec for dec in rq.decisions
            if dec.action == "drain_replica"
            and dec.node_id == killed_id
        ]
        if not drain_decisions or not drain_decisions[0].trace_id:
            raise DrillError(
                "no traced drain_replica decision for the killed "
                f"replica in {len(rq.decisions)} decision(s)"
            )
        dec_trace = client.query_traces(
            trace_id=drain_decisions[0].trace_id
        ).traces
        if not dec_trace:
            raise DrillError(
                "decision trace "
                f"{drain_decisions[0].trace_id} not in the store"
            )
        dec_names = {s["name"] for s in dec_trace[0]["spans"]}
        for needle in (
            "remediation.verdict", "remediation.drain_replica",
            "serve.requeue",
        ):
            if needle not in dec_names:
                raise DrillError(
                    f"decision trace missing {needle!r}: has "
                    f"{sorted(dec_names)}"
                )
        requeue_rids = {
            s["tags"].get("request_id")
            for s in dec_trace[0]["spans"]
            if s["name"] == "serve.requeue"
        }
        if not requeue_rids & set(requeued):
            raise DrillError(
                f"decision-trace requeues {sorted(requeue_rids)} "
                f"name none of the requeued requests {requeued}"
            )
        # And the killed node is a queryable SUBJECT of it.
        by_subject = client.query_traces(
            subject=f"node:{killed_id}"
        ).traces
        if drain_decisions[0].trace_id not in {
            t["trace_id"] for t in by_subject
        }:
            raise DrillError(
                f"subject query node:{killed_id} does not surface "
                "the drain decision trace"
            )

        counters = master.serving.counters()
        # Latency SLO surface for the bench ledger: end-to-end TTFT
        # (router-observed, requeue waits included) and TPOT over
        # every completed request.
        ttfts = sorted(
            r.phases.get("ttft_total", r.ttft_s)
            for r in st.values()
        )
        tpots = sorted(r.tpot_s for r in st.values())
        report = {
            "seed": seed,
            "requests": requests,
            "completed": counters["done"],
            "failed": counters["failed"],
            "requeued_requests": len(requeued),
            "requeued_total": counters["requeued_total"],
            "killed_replica": killed_id,
            "p99_s": round(p99, 3),
            "p50_s": round(_percentile(latencies, 50.0), 3),
            "ttft_p50_s": round(_percentile(ttfts, 50.0), 4),
            "ttft_p99_s": round(_percentile(ttfts, 99.0), 4),
            "tpot_p50_s": round(_percentile(tpots, 50.0), 5),
            "tpot_p99_s": round(_percentile(tpots, 99.0), 5),
            "verdicts": len(verdicts),
            "drains": len(drains),
            "outputs_verified": min(len(requeued), verify_outputs),
            "wall_s": round(time.monotonic() - t0, 1),
        }
        return report
    finally:
        if client is not None:
            client.close()
        for proc in procs.values():
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
        master.stop()


def _run_disagg_leg(
    seed: int,
    fleet,
    workload: dict,
    kill_prefill: bool = False,
    max_len: int = 64,
    deadline_s: float = 150.0,
) -> dict:
    """One fleet leg of the interference drill: warm up the whole
    pipeline, start the streaming decodes, unleash the long-prompt
    storm beside them, optionally SIGKILL a prefill replica holding
    prefilling work, and require every request to complete with
    greedy tokens bitwise equal to the reference model.

    ``fleet`` is ``[(replica_id, role, lanes), ...]``; ``workload``
    carries the prompts (identical across legs — the comparison is
    the same traffic through two fleet shapes). Returns the leg
    report: per-stream TPOTs, requeue counts, the killed node."""
    import numpy as np

    import dlrover_tpu.obs as obs
    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.master.master import JobMaster

    tracer = obs.configure_tracer()
    t0 = time.monotonic()
    master = JobMaster(
        port=0,
        node_num=2,
        rdzv_timeout=1.0,
        heartbeat_timeout=6.0,
        monitor_interval=0.5,
        collect_interval=999.0,
        health_interval=9999.0,  # ticked manually
        remediation_config={
            "interval_s": 9999.0,
            "hysteresis_ticks": 2,
            "cooldown_s": 0.0,
            "blast_window_s": 600.0,
            "blast_max_actions": 4.0,
            "probation_s": 300.0,
        },
        serving_config={
            "progress_timeout_s": 1.5,
            "scale_cooldown_s": 9999.0,
        },
    )
    master.health._config["replica_stall_crit_ratio"] = 1.0
    master.prepare()
    procs = {}
    client = None
    killed_id = None
    try:
        from dlrover_tpu.common.constants import replica_node_id

        for rid, role, lanes in fleet:
            procs[replica_node_id(rid)] = spawn_replica(
                master.addr, rid, seed,
                max_len=max_len, role=role, lanes=lanes,
                prefill_chunk=16, pull_batch=4,
                # The SAME per-step prefill budget in both legs:
                # the comparison isolates fleet SHAPE (where the
                # prefill compute lands), not scheduler tuning.
                prefill_budget=64,
            )
        client = MasterClient(master.addr, node_id=-1)

        def ready_count():
            snap = master.serving.snapshot()
            return sum(
                1 for r in snap["replicas"]
                if r["state"] == "ready"
            )

        deadline = time.monotonic() + 90
        while ready_count() < len(fleet):
            if time.monotonic() > deadline:
                raise DrillError(
                    f"only {ready_count()}/{len(fleet)} replicas "
                    "registered within 90s"
                )
            for node_id, proc in procs.items():
                if proc.poll() is not None:
                    raise DrillError(
                        f"replica {node_id} exited rc="
                        f"{proc.returncode} before registering"
                    )
            time.sleep(0.2)

        def submit(tag, prompt, max_new):
            resp = client.serve_submit(
                prompt, max_new_tokens=max_new, temperature=0.0,
                request_id=tag,
            )
            if not resp.accepted:
                raise DrillError(f"submit {tag} rejected")
            return resp.request_id

        def states(rids):
            return {rid: client.serve_result(rid) for rid in rids}

        def tick():
            master.health.evaluate_once()
            master.remediation.tick_once()

        def wait_done(rids, budget, what):
            end = time.monotonic() + budget
            last_tick = 0.0
            while time.monotonic() < end:
                now = time.monotonic()
                if now - last_tick >= 0.4:
                    last_tick = now
                    tick()
                st = states(rids)
                failed = {
                    rid: r.error for rid, r in st.items()
                    if r.state == "failed"
                }
                if failed:
                    raise DrillError(
                        f"{what} requests FAILED: {failed}"
                    )
                if all(r.state == "done" for r in st.values()):
                    return st
                time.sleep(0.05)
            st = states(rids)
            incomplete = {
                rid: r.state for rid, r in st.items()
                if r.state != "done"
            }
            raise DrillError(
                f"{what} incomplete after {budget:.0f}s: "
                f"{incomplete}"
            )

        # Warm up every compiled program in the pipeline (prefill
        # chunks, ragged decode, both handoff install buckets) so
        # compile time never lands inside a measured TPOT interval.
        warm = [
            submit("warm-s", workload["warm_stream"], 3),
            submit("warm-l", workload["warm_storm"], 2),
        ]
        wait_done(warm, 90.0, "warmup")

        # Streaming decodes: short prompts, long outputs — the
        # latency-sensitive traffic disaggregation protects.
        stream_rids = [
            submit(f"stream-{i}", p, workload["stream_max_new"])
            for i, p in enumerate(workload["streams"])
        ]
        # Wait until every stream is ON a replica decoding (mixed:
        # dispatched; disagg: decoding after its prefill handoff).
        end = time.monotonic() + 60
        while True:
            st = states(stream_rids)
            if all(
                r.state in ("dispatched", "decoding", "done")
                for r in st.values()
            ):
                break
            if time.monotonic() > end:
                raise DrillError(
                    "streams never reached the decode stage: "
                    f"{ {k: v.state for k, v in st.items()} }"
                )
            time.sleep(0.05)

        # The long-prompt storm, landing beside the live decodes.
        storm_rids = [
            submit(f"storm-{i}", p, workload["storm_max_new"])
            for i, p in enumerate(workload["storms"])
        ]

        if kill_prefill:
            # SIGKILL one prefill replica while it holds requests
            # mid-prefill (the handoff pipeline's upstream stage).
            end = time.monotonic() + 60
            victim = None
            while victim is None:
                st = states(storm_rids)
                prefilling = [
                    r for r in st.values()
                    if r.state == "prefilling"
                ]
                if prefilling:
                    victim = prefilling[0].replica_id
                elif time.monotonic() > end:
                    raise DrillError(
                        "no storm request ever showed state "
                        "'prefilling' to aim the kill at"
                    )
                else:
                    time.sleep(0.02)
            procs[victim].kill()
            procs[victim].wait()
            killed_id = victim
            print(
                f"[disagg] killed prefill replica {victim} at "
                f"+{time.monotonic() - t0:.1f}s", flush=True,
            )

        st = wait_done(
            stream_rids + storm_rids, deadline_s, "drill"
        )

        # Bitwise correctness of EVERY output (streams + storm,
        # through handoffs and any kill-requeues) vs the reference.
        import jax.numpy as jnp

        from dlrover_tpu.models import generate
        from dlrover_tpu.serving.replica import build_tiny_model

        ref_params, ref_cfg = build_tiny_model(
            seed, block_size=max(max_len, 64)
        )
        prompts = {}
        for i, p in enumerate(workload["streams"]):
            prompts[f"stream-{i}"] = (
                p, workload["stream_max_new"]
            )
        for i, p in enumerate(workload["storms"]):
            prompts[f"storm-{i}"] = (p, workload["storm_max_new"])
        mismatches = []
        for rid, (prompt, max_new) in prompts.items():
            out = generate.generate(
                ref_params, ref_cfg,
                jnp.asarray([prompt], jnp.int32),
                max_new_tokens=max_new, temperature=0.0,
            )
            want = np.asarray(out)[0, len(prompt):].tolist()
            if st[rid].tokens != want:
                mismatches.append((rid, st[rid].tokens, want))
        if mismatches:
            raise DrillError(
                "outputs diverged from the reference through the "
                f"handoff: {mismatches[:3]}"
            )

        requeued = [
            rid for rid in stream_rids + storm_rids
            if st[rid].requeues > 0
        ]
        if kill_prefill:
            if not requeued:
                raise DrillError(
                    "the prefill kill left nothing to requeue — "
                    "the failover path never ran"
                )
            events, _ = tracer.events_since(0)
            names = [e.get("name") for e in events]
            for needle in (
                "serve.requeue", "remediation.drain_replica",
            ):
                if needle not in names:
                    raise DrillError(
                        f"event {needle!r} missing from the "
                        "disagg drill trace"
                    )

        report = {
            "stream_tpots_s": [
                round(st[rid].tpot_s, 6) for rid in stream_rids
            ],
            "stream_ttfts_s": [
                round(
                    st[rid].phases.get("ttft_total", st[rid].ttft_s),
                    6,
                )
                for rid in stream_rids
            ],
            "requeued": len(requeued),
            "killed_replica": killed_id,
            "completed": len(st),
            "wall_s": round(time.monotonic() - t0, 1),
        }
        disagg = any(role != "mixed" for _, role, _ in fleet)
        if disagg:
            # The request trace must show the prefill -> handoff ->
            # decode hop chain, server-assembled.
            probe = next(
                (
                    rid for rid in storm_rids
                    if st[rid].requeues == 0
                    and "handoff" in st[rid].phases
                ),
                None,
            )
            if probe is None:
                raise DrillError(
                    "no storm request completed via a clean "
                    "handoff to probe the trace with"
                )
            tq = client.query_traces(trace_id=st[probe].trace_id)
            if not tq.enabled or not tq.traces:
                raise DrillError(
                    f"query_traces({st[probe].trace_id}) empty"
                )
            spans = tq.traces[0]["spans"]
            hops = [s for s in spans if s["name"] == "serve.hop"]
            ends = [s["tags"].get("end") for s in hops]
            handoffs = [
                s for s in spans if s["name"] == "serve.handoff"
            ]
            hop_replicas = {
                s["tags"].get("replica_id") for s in hops
            }
            if (
                "handoff" not in ends
                or not handoffs
                or len(hops) < 2
                or len(hop_replicas) < 2
            ):
                raise DrillError(
                    f"request {probe}'s trace lacks the prefill ->"
                    " handoff -> decode chain: hops end "
                    f"{ends}, {len(handoffs)} serve.handoff "
                    f"span(s), replicas {sorted(hop_replicas)}"
                )
            report["handoff_chain_probe"] = probe
            snap = master.serving.snapshot()
            report["roles"] = snap.get("roles", {})
        return report
    finally:
        if client is not None:
            client.close()
        for proc in procs.values():
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
        master.stop()


def run_disagg_drill(
    seed: int = 11,
    streams: int = 4,
    storms: int = 16,
    stream_max_new: int = 48,
    storm_prompt_len: int = 56,
    kill: bool = True,
) -> dict:
    """The prefill/decode disaggregation acceptance drill (ISSUE 15),
    two halves:

    1. **Interference A/B** (in-process, virtual per-replica clocks —
       ``tools/decode_bench.run_disagg_ab``): with the long-prompt
       storm running, disaggregated p99 stream TPOT must BEAT
       colocated on the same workload, with every output bitwise
       equal to ``generate.generate``. Per-replica virtual clocks
       charge each loop only its own measured step costs — the
       single-core-honest model of dedicated role hardware.
    2. **Failover leg** (real master + replica subprocesses): a
       2-prefill + 1-decode fleet serves the storm beside the
       streams, one prefill replica is SIGKILLed while holding
       prefilling requests, and the drill asserts zero dropped
       requests, bitwise outputs through handoff AND kill-requeue,
       and that a handed-off request's server-assembled trace shows
       the prefill -> handoff -> decode hop chain.
    """
    import numpy as np

    from dlrover_tpu.serving.replica import build_tiny_model

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from decode_bench import run_disagg_ab

    t0 = time.monotonic()
    print(
        "[disagg] interference A/B (virtual-clock, in-process)...",
        flush=True,
    )
    ab = run_disagg_ab(seed=seed)
    if not ab["disagg_p99_tpot_s"] < ab["coloc_p99_tpot_s"]:
        raise DrillError(
            f"disaggregated p99 stream TPOT "
            f"{ab['disagg_p99_tpot_s']}s did NOT beat colocated "
            f"{ab['coloc_p99_tpot_s']}s under the same prompt storm"
        )
    print(
        f"[disagg] A/B: colocated p99 TPOT "
        f"{ab['coloc_p99_tpot_s']}s vs disaggregated "
        f"{ab['disagg_p99_tpot_s']}s (x{ab['tpot_p99_ratio']}, "
        f"{ab['outputs_verified']} outputs bitwise-verified)",
        flush=True,
    )

    _, cfg = build_tiny_model(seed, block_size=64)
    rng = np.random.default_rng(seed)
    lanes = streams + 2
    workload = {
        "warm_stream": rng.integers(
            0, cfg.vocab_size, size=6
        ).tolist(),
        "warm_storm": rng.integers(
            0, cfg.vocab_size, size=storm_prompt_len
        ).tolist(),
        "streams": [
            rng.integers(0, cfg.vocab_size, size=6).tolist()
            for _ in range(streams)
        ],
        "storms": [
            rng.integers(
                0, cfg.vocab_size, size=storm_prompt_len
            ).tolist()
            for _ in range(storms)
        ],
        "stream_max_new": stream_max_new,
        "storm_max_new": 8,
    }
    print(
        "[disagg] failover leg (2 prefill + 1 decode"
        + (", SIGKILL one prefill)..." if kill else ")..."),
        flush=True,
    )
    disagg = _run_disagg_leg(
        seed,
        [(0, "prefill", 4), (1, "prefill", 4), (2, "decode", lanes)],
        workload,
        kill_prefill=kill,
        max_len=128,
    )
    print(
        f"[disagg] failover leg done in {disagg['wall_s']}s "
        f"({disagg['completed']} completed, "
        f"{disagg['requeued']} requeued)", flush=True,
    )
    report = {
        "seed": seed,
        "streams": streams,
        "storms": storms,
        "stream_max_new": stream_max_new,
        "storm_prompt_len": storm_prompt_len,
        "coloc_tpot_p99_s": ab["coloc_p99_tpot_s"],
        "disagg_tpot_p99_s": ab["disagg_p99_tpot_s"],
        "coloc_tpot_p50_s": ab["coloc_p50_tpot_s"],
        "disagg_tpot_p50_s": ab["disagg_p50_tpot_s"],
        "tpot_p99_ratio": ab["tpot_p99_ratio"],
        "ab_handoffs": ab["handoffs"],
        "killed_replica": disagg.get("killed_replica"),
        "requeued": disagg.get("requeued", 0),
        "completed": disagg.get("completed", 0),
        "handoff_chain_probe": disagg.get("handoff_chain_probe"),
        "roles": disagg.get("roles", {}),
        # Wall-clock stream TPOTs from the subprocess leg, for the
        # record (NOT gated: this container serializes every replica
        # onto one core, so subprocess wall time measures OS
        # scheduling, not fleet shape).
        "failover_leg_stream_tpots_s": disagg.get(
            "stream_tpots_s", []
        ),
        "wall_s": round(time.monotonic() - t0, 1),
    }
    return report


def selftest() -> int:
    """Seeded, hermetic CPU-mesh drill (the tier-1 acceptance:
    >=2 replicas serve synthetic traffic through one replica kill
    with zero drops and bounded p99)."""
    t0 = time.monotonic()
    report = run_serving_drill(seed=7)
    print(
        f"serving drill ok: {report['completed']}/"
        f"{report['requests']} requests completed through the kill "
        f"of replica {report['killed_replica']} "
        f"({report['requeued_requests']} requeued, "
        f"{report['outputs_verified']} outputs verified, "
        f"p99 {report['p99_s']}s)"
    )
    print(
        f"serve drill selftest ok "
        f"({time.monotonic() - t0:.1f}s)"
    )
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("serve_drill")
    parser.add_argument("--selftest", action="store_true",
                        help="seeded quick mode (<90s) for CI")
    parser.add_argument(
        "--disagg", action="store_true",
        help="prefill/decode disaggregation drill: long-prompt "
        "storm beside streaming decodes through a colocated and a "
        "disaggregated fleet (SIGKILL one prefill replica "
        "mid-handoff), asserting zero drops, bitwise outputs, the "
        "prefill->handoff->decode trace chain, and disagg p99 TPOT "
        "beating colocated",
    )
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--requests", type=int, default=18)
    parser.add_argument("--max_new", type=int, default=5)
    parser.add_argument("--p99_bound", type=float, default=45.0)
    parser.add_argument("--json", type=str, default="",
                        help="write the drill report to this path")
    args = parser.parse_args(argv)
    if args.disagg:
        t0 = time.monotonic()
        try:
            report = run_disagg_drill(seed=args.seed or 11)
        except DrillError as e:
            print(f"disagg drill FAILED: {e}", file=sys.stderr)
            return 1
        print(json.dumps(report))
        if args.json:
            with open(args.json, "w") as f:
                json.dump(report, f, indent=2)
        print(
            f"disagg drill ok: stream TPOT p99 "
            f"{report['disagg_tpot_p99_s']}s disaggregated vs "
            f"{report['coloc_tpot_p99_s']}s colocated "
            f"(x{report['tpot_p99_ratio']}), "
            f"{report['requeued']} requeued through the prefill "
            f"kill of replica {report['killed_replica']} "
            f"({time.monotonic() - t0:.1f}s)"
        )
        return 0
    if args.selftest:
        return selftest()
    try:
        report = run_serving_drill(
            seed=args.seed,
            replicas=args.replicas,
            requests=args.requests,
            max_new=args.max_new,
            p99_bound_s=args.p99_bound,
        )
        report["ok"] = True
        rc = 0
    except DrillError as e:
        report = {"ok": False, "error": str(e)}
        rc = 1
    if rc == 0 and os.environ.get("DECODE_LEDGER", "1") != "0":
        # Latency joins the regression gate: TTFT/TPOT p50/p99 ride
        # the same kind-"decode" fingerprinted ledger as throughput,
        # so `bench_ledger compare --metric serve_ttft_p99_s` trips
        # on a latency regression exactly like a tok/s one.
        # (--selftest never writes: CI must not pollute the history.)
        sys.path.insert(
            0, os.path.join(os.path.dirname(__file__))
        )
        from bench_ledger import append_record

        for metric, value, extra in (
            (
                "serve_ttft_p99_s",
                report["ttft_p99_s"],
                {"p50": report["ttft_p50_s"]},
            ),
            (
                "serve_tpot_p99_s",
                report["tpot_p99_s"],
                {"p50": report["tpot_p50_s"]},
            ),
        ):
            stored = append_record(
                {
                    "kind": "decode",
                    "metric": metric,
                    "value": value,
                    "unit": "s",
                    "requests": report["requests"],
                    "replicas": args.replicas,
                    **extra,
                },
            )
            print(
                f"[drill] ledger += {metric} "
                f"{stored.get('value')} s",
                flush=True,
            )
    print(json.dumps(report))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2)
    return rc


if __name__ == "__main__":
    sys.exit(main())
