"""Multi-job pool drill: gang scheduling + checkpoint-backed
preemption on a hermetic 4-slice fake pool.

The pool counterpart of ``serve_drill``/``chaos_drill``: an
in-process :class:`~dlrover_tpu.pool.TPUPoolMaster` owns 4 fake
slices; drill "trainers" are worker THREADS speaking the real wire
protocol (``MasterClient(job_id=...)`` over the pool's single gRPC
endpoint, routed by the ``_job`` envelope id) and consuming shards
from their job's own ledger. The drill plays one full capacity
incident and asserts the acceptance contract:

* a low-priority job is running when a high-priority gang that does
  not fit arrives;
* the low job is preempted through the GRACEFUL path: its workers
  finish the in-flight shard, flash-checkpoint through the shm
  staging format, and the checkpoint is durably staged (tracker
  file) BEFORE the pool releases a single slice — asserted on the
  trace spans;
* the high gang is placed WHOLE, never partially — asserted on the
  pool's allocation events;
* on the high job's completion the preempted job resumes
  ELASTICALLY (fewer slices than its gang, >= min_slices, via
  backfill under a capacity-blocked head) and finishes with
  exactly-once shard accounting: every ledger task completed exactly
  once across both incarnations, none lost, none double-counted;
* an over-quota submission queues with a quota verdict while other
  tenants keep placing (no starvation), and the whole
  queue -> preempt -> place -> resume story is ONE distributed trace
  via ``query_traces``; ``dlrover_pool_*`` metrics expose queue
  depth, placement latency, and preemption counts.

Usage::

    python tools/pool_drill.py --selftest     # seeded, <60s (CI)
    python tools/pool_drill.py --json out.json
"""

import _repo_path  # noqa: F401  (sys.path, must precede dlrover_tpu)

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np


class DrillError(AssertionError):
    pass


def wait_for(cond, timeout: float, what: str, poll: float = 0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(poll)
    raise DrillError(f"timeout ({timeout:.0f}s) waiting for {what}")


class JobState:
    """Drill-side per-job book: which worker processed which ledger
    task how many times (the exactly-once evidence), plus checkpoint
    facts, shared across placement incarnations."""

    def __init__(self, job_id: str, dataset: str, dataset_size: int,
                 shard_ms: float, ckpt_dir: str = ""):
        self.job_id = job_id
        self.dataset = dataset
        self.dataset_size = dataset_size
        self.shard_ms = shard_ms
        self.ckpt_dir = ckpt_dir
        self.lock = threading.Lock()
        self.processed = {}  # task_id -> completion count
        self.records = 0
        self.records_at_park = -1
        self.parked_steps = []  # steps flash-checkpointed at park
        self.restored_steps = []  # steps read back on resume
        self.threads = []


def _flash_checkpoint(state: JobState, node_id: int) -> int:
    """The graceful-park checkpoint: stage this worker's state
    through the flash-checkpoint shm format (the 16.2x staging path),
    then persist payload + tracker file durably — the same
    stage-then-persist shape the agent saver uses."""
    from dlrover_tpu.common.ckpt_shm import (
        SharedMemoryHandler,
        pack_meta,
        plan_entries,
    )
    from dlrover_tpu.common.constants import CheckpointConstant

    with state.lock:
        done = np.asarray(sorted(state.processed), np.int64)
        step = int(state.records)
    entries, _ = plan_entries(
        [(
            "worker_state", "int64", [len(done)],
            [[0, len(done)]], done.nbytes,
        )]
    )
    handler = SharedMemoryHandler(node_id, job=state.job_id)
    try:
        handler.save(step, [(entries[0], done)])
        loaded = handler.load()
        if loaded is None:
            raise DrillError(
                f"{state.job_id} worker {node_id}: shm stage lost"
            )
        l_step, l_entries, _, payload = loaded
        step_dir = os.path.join(state.ckpt_dir, f"iter_{l_step}")
        os.makedirs(step_dir, exist_ok=True)
        blob = os.path.join(step_dir, f"worker_{node_id}.ckpt")
        tmp = blob + ".tmp"
        with open(tmp, "wb") as f:
            meta = pack_meta(l_step, l_entries, {})
            f.write(len(meta).to_bytes(8, "little"))
            f.write(meta)
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, blob)
        tracker = os.path.join(
            state.ckpt_dir, CheckpointConstant.TRACKER_FILE
        )
        tmp = tracker + f".tmp{node_id}"
        with open(tmp, "w") as f:
            f.write(str(l_step))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, tracker)
    finally:
        handler.unlink()
    with state.lock:
        state.parked_steps.append(step)
        state.records_at_park = step
    return step


def _restore_checkpoint(state: JobState) -> None:
    from dlrover_tpu.common.constants import CheckpointConstant

    tracker = os.path.join(
        state.ckpt_dir, CheckpointConstant.TRACKER_FILE
    )
    try:
        with open(tracker) as f:
            step = int(f.read().strip())
    except (OSError, ValueError):
        return
    with state.lock:
        state.restored_steps.append(step)


def drill_worker(
    addr: str,
    state: JobState,
    node_id: int,
    resume: bool,
) -> None:
    """One drill trainer: real RPCs against the pool endpoint with
    the job id on the envelope. Contract on park: finish + report the
    in-flight shard, flash-checkpoint durably on ``save_checkpoint``,
    exit on ``stop_training``."""
    from dlrover_tpu.agent.master_client import (
        MasterClient,
        MasterOutageError,
    )
    from dlrover_tpu.common.constants import EventAction, TaskType

    client = MasterClient(
        addr, node_id=node_id, job_id=state.job_id
    )
    parking = False
    try:
        client.register_node("worker")
        if node_id == 0 and not resume:
            client.create_dataset(
                state.dataset,
                dataset_size=state.dataset_size,
                batch_size=1,
                num_minibatches_per_shard=1,
            )
        if resume and state.ckpt_dir and node_id == 0:
            _restore_checkpoint(state)
        while True:
            action = client.heartbeat()
            if action == EventAction.SAVE_CHECKPOINT.value:
                if state.ckpt_dir:
                    _flash_checkpoint(state, node_id)
                parking = True
                continue
            if action == EventAction.STOP_TRAINING.value:
                client.report_succeeded()
                return
            if parking:
                # Parked: no new work; wait for stop_training.
                time.sleep(0.01)
                continue
            task = client.get_task(state.dataset)
            if task.task_id < 0:
                if task.task_type == TaskType.NONE:
                    client.report_succeeded()
                    return
                time.sleep(0.02)
                continue
            n = max(task.shard.end - task.shard.start, 0)
            time.sleep(state.shard_ms / 1000.0)
            with state.lock:
                state.processed[task.task_id] = (
                    state.processed.get(task.task_id, 0) + 1
                )
                state.records += n
            client.report_task_result(
                state.dataset, task.task_id, True
            )
    except MasterOutageError:
        return
    finally:
        client.close()


def make_launcher(states):
    def launch(job_id, addr, slices, resume):
        state = states[job_id]
        for i in range(len(slices)):
            t = threading.Thread(
                target=drill_worker,
                args=(addr, state, i, resume),
                name=f"{job_id}-w{i}",
                daemon=True,
            )
            t.start()
            state.threads.append(t)

    return launch


def run_pool_drill(seed: int = 7, shard_ms: float = 20.0) -> dict:
    import dlrover_tpu.obs as obs
    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.pool import (
        PoolJobSpec,
        PoolJobState,
        TPUPoolMaster,
        tracker_ckpt_probe,
    )

    tracer = obs.configure_tracer()  # in-memory ring
    t0 = time.monotonic()
    tmp = tempfile.mkdtemp(prefix="pool_drill_")
    low_ckpt = os.path.join(tmp, "low_ckpt")
    os.makedirs(low_ckpt, exist_ok=True)

    states = {
        "low": JobState("low", "ds-low", 40, shard_ms, low_ckpt),
        "high": JobState("high", "ds-high", 12, shard_ms),
        "med": JobState("med", "ds-med", 8, shard_ms),
        "overq": JobState("overq", "ds-overq", 6, shard_ms),
    }
    job_master_defaults = dict(
        rdzv_timeout=1.0,
        heartbeat_timeout=60.0,
        monitor_interval=600.0,
        collect_interval=999.0,
        health_interval=9999.0,
        remediation_interval=9999.0,
    )
    master = TPUPoolMaster(
        slices=4,
        tenant_quotas={"research": 3},
        park_timeout_s=30.0,
        watch_interval=0.1,
        worker_launcher=make_launcher(states),
        job_master_defaults=job_master_defaults,
    )
    master.prepare()
    client = MasterClient(master.addr, node_id=-1)

    def status(job_id):
        return client.pool_job_status(job_id)

    def submit(job_id, tenant, priority, n, mins=0, probe=None):
        r = master.submit(
            PoolJobSpec(
                job_id=job_id, tenant=tenant, priority=priority,
                n_slices=n, min_slices=mins,
            ),
            ckpt_probe=probe,
        )
        if not r.get("state"):
            raise DrillError(
                f"submit {job_id} rejected: {r.get('reason')}"
            )
        return r

    try:
        # 1. Low-priority research job on 3 of 4 slices.
        r_low = submit(
            "low", "research", 1, 3, mins=1,
            probe=tracker_ckpt_probe(low_ckpt),
        )
        wait_for(
            lambda: status("low").state == PoolJobState.PLACED,
            10, "low placed",
        )
        wait_for(
            lambda: len(states["low"].processed) >= 5,
            20, "low making shard progress",
        )

        # 2. High-priority gang of 4: must preempt low gracefully.
        r_high = submit("high", "prod", 5, 4)
        wait_for(
            lambda: status("high").state == PoolJobState.PLACED,
            30, "high gang placed after preemption",
        )
        st_low = status("low")
        if st_low.state not in (
            PoolJobState.PREEMPTED, PoolJobState.PLACED
        ):
            raise DrillError(
                f"low in unexpected state {st_low.state!r} after "
                "preemption"
            )
        if not states["low"].parked_steps:
            raise DrillError(
                "low parked without writing a flash checkpoint"
            )

        # Trace: park(staged) -> release -> place, one incident trace.
        tq = client.query_traces(trace_id=r_high["trace_id"])
        if not tq.enabled or not tq.traces:
            raise DrillError("high's incident trace missing")
        spans = tq.traces[0]["spans"]
        by_name = {}
        for s in spans:
            by_name.setdefault(s["name"], []).append(s)
        for needle in (
            "pool.submit", "pool.queue_wait", "pool.park",
            "pool.release", "pool.place",
        ):
            if needle not in by_name:
                raise DrillError(
                    f"incident trace missing {needle!r}: has "
                    f"{sorted(by_name)}"
                )
        park = by_name["pool.park"][0]
        if park["tags"].get("staged") is not True:
            raise DrillError(
                f"park span not staged: {park['tags']}"
            )
        if int(park["tags"].get("ckpt_step", -1)) < 0:
            raise DrillError(
                f"park span carries no checkpoint step: "
                f"{park['tags']}"
            )
        release = by_name["pool.release"][0]
        park_end = park["start_ts"] + park["dur_s"]
        if release["start_ts"] < park_end - 1e-6:
            raise DrillError(
                "slices released BEFORE the checkpoint was staged: "
                f"release at {release['start_ts']}, park ended "
                f"{park_end}"
            )
        place_high = [
            s for s in by_name["pool.place"]
            if s["tags"].get("job_id") == "high"
        ]
        if not place_high:
            raise DrillError("no pool.place span for high")
        if place_high[0]["start_ts"] < release["start_ts"] - 1e-6:
            raise DrillError(
                "high placed before the victim's slices were "
                "released"
            )
        granted = place_high[0]["tags"].get("slices", "")
        if len(granted.split(",")) != 4:
            raise DrillError(
                f"high gang not whole: placed on {granted!r}"
            )
        # Never-partial: every pool.allocate event for high grants
        # the full gang (the allocator is all-or-nothing; this
        # asserts it stayed that way end to end).
        events, _ = tracer.events_since(0)
        high_allocs = [
            e for e in events
            if e.get("name") == "pool.allocate"
            and e.get("job_id") == "high"
        ]
        if len(high_allocs) != 1 or len(
            high_allocs[0].get("slices", "").split(",")
        ) != 4:
            raise DrillError(
                f"partial/duplicate allocation for high: "
                f"{high_allocs}"
            )
        # The checkpoint really is durable on disk.
        from dlrover_tpu.common.constants import CheckpointConstant

        tracker = os.path.join(
            low_ckpt, CheckpointConstant.TRACKER_FILE
        )
        if not os.path.exists(tracker):
            raise DrillError("no durable checkpoint tracker file")

        # 3. While high runs: med queues (no capacity, cannot
        # preempt a higher band); overq (research) queues too.
        submit("med", "prod", 3, 2)
        submit("overq", "research", 3, 3)
        for jid in ("med", "overq"):
            if status(jid).state != PoolJobState.QUEUED:
                raise DrillError(
                    f"{jid} should queue while high holds the pool "
                    f"(got {status(jid).state!r})"
                )

        # 4. High completes -> med places; low resumes ELASTICALLY
        # via backfill under the capacity-blocked overq head.
        wait_for(
            lambda: status("high").state == PoolJobState.DONE,
            30, "high completing",
        )
        wait_for(
            lambda: status("med").state in (
                PoolJobState.PLACED, PoolJobState.DONE
            ),
            10, "med placed after high",
        )
        wait_for(
            lambda: status("low").state in (
                PoolJobState.PLACED, PoolJobState.DONE
            ),
            10, "low resumed",
        )
        st_low = status("low")
        if st_low.preemptions != 1:
            raise DrillError(
                f"low preemptions {st_low.preemptions} != 1"
            )
        if st_low.state == PoolJobState.PLACED and len(
            st_low.slices
        ) >= 3:
            raise DrillError(
                f"low resume was not elastic: {st_low.slices}"
            )
        if not states["low"].restored_steps:
            raise DrillError(
                "resumed low never read its checkpoint back"
            )
        # The resume rides the SAME incident trace (pool.resume).
        tq = client.query_traces(trace_id=r_high["trace_id"])
        names = {s["name"] for s in tq.traces[0]["spans"]}
        if "pool.resume" not in names:
            raise DrillError(
                f"incident trace has no pool.resume: {sorted(names)}"
            )
        # ... and the victim is a queryable subject of it.
        subj = client.query_traces(subject="pooljob:low").traces
        if r_high["trace_id"] not in {
            t["trace_id"] for t in subj
        }:
            raise DrillError(
                "subject query pooljob:low does not surface the "
                "incident trace"
            )

        # 5. Quota: with low (research) resumed on 2 slices of the
        # 3-slice research quota, overq's 3-slice ask is quota-denied
        # and must keep queueing WITHOUT starving anyone.
        wait_for(
            lambda: (
                client.query_pool().snapshot["counters"][
                    "quota_denied"
                ].get("research", 0) >= 1
            ),
            20, "overq quota-denied verdict",
        )
        if status("overq").state != PoolJobState.QUEUED:
            raise DrillError("overq should still be queued")

        # 6. Everything drains: med, low, then overq (quota frees).
        for jid in ("med", "low", "overq"):
            wait_for(
                lambda j=jid: status(j).state == PoolJobState.DONE,
                40, f"{jid} completing",
            )

        # 7. Exactly-once shard accounting for the preempted job:
        # every ledger task completed exactly once across BOTH
        # incarnations; none lost, none double-counted.
        low = states["low"]
        with low.lock:
            processed = dict(low.processed)
            records = low.records
            records_at_park = low.records_at_park
        expect_tasks = set(range(low.dataset_size))
        got_tasks = set(processed)
        if got_tasks != expect_tasks:
            raise DrillError(
                f"lost shards: {sorted(expect_tasks - got_tasks)}; "
                f"unknown: {sorted(got_tasks - expect_tasks)}"
            )
        doubles = {t: c for t, c in processed.items() if c != 1}
        if doubles:
            raise DrillError(
                f"double-counted shards: {doubles}"
            )
        if records != low.dataset_size:
            raise DrillError(
                f"record count {records} != dataset "
                f"{low.dataset_size}"
            )
        if not 0 < records_at_park < low.dataset_size:
            raise DrillError(
                f"park did not interrupt the dataset "
                f"(records_at_park={records_at_park})"
            )
        ctx = master.context("low")
        if not ctx.master.task_manager.finished():
            raise DrillError("low's master ledger not finished")

        # 8. Metrics + snapshot exposure.
        snap = client.query_pool().snapshot
        counters = snap["counters"]
        if counters["preemptions"].get("priority", 0) != 1:
            raise DrillError(
                f"preemption counters wrong: {counters}"
            )
        if counters["backfills"] < 1:
            raise DrillError("elastic resume did not backfill")
        if not snap["wait_seconds"]:
            raise DrillError("no wait-time percentiles in snapshot")
        text = client.query_metrics()
        for needle in (
            "dlrover_pool_queue_depth",
            "dlrover_pool_preemptions_total",
            "dlrover_pool_placement_seconds",
            "dlrover_pool_quota_denied_total",
        ):
            if needle not in text:
                raise DrillError(
                    f"{needle} missing from /metrics exposition"
                )

        waits = snap["wait_seconds"]
        return {
            "seed": seed,
            "jobs": 4,
            "preemptions": counters["preemptions"],
            "quota_denied": counters["quota_denied"],
            "backfills": counters["backfills"],
            "placements": counters["placements"],
            "low_tasks": len(processed),
            "low_records_at_park": records_at_park,
            "low_parked_step": max(low.parked_steps),
            "low_resume_slices": len(st_low.slices),
            "incident_trace": r_high["trace_id"],
            "wait_p99_by_band": {
                band: w["p99"] for band, w in waits.items()
            },
            "wall_s": round(time.monotonic() - t0, 1),
        }
    finally:
        client.close()
        master.stop()
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


def selftest() -> int:
    t0 = time.monotonic()
    report = run_pool_drill(seed=7)
    print(
        f"pool drill ok: high gang placed whole after graceful "
        f"preemption (ckpt step {report['low_parked_step']} staged "
        f"before release), low resumed elastically on "
        f"{report['low_resume_slices']} slice(s) with "
        f"{report['low_tasks']} shards exactly-once, "
        f"quota_denied={report['quota_denied']}"
    )
    print(
        f"pool drill selftest ok ({time.monotonic() - t0:.1f}s)"
    )
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("pool_drill")
    parser.add_argument("--selftest", action="store_true",
                        help="seeded quick mode (<60s) for CI")
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--shard_ms", type=float, default=20.0)
    parser.add_argument("--json", type=str, default="",
                        help="write the drill report to this path")
    args = parser.parse_args(argv)
    if args.selftest:
        return selftest()
    try:
        report = run_pool_drill(
            seed=args.seed, shard_ms=args.shard_ms
        )
        report["ok"] = True
        rc = 0
    except DrillError as e:
        report = {"ok": False, "error": str(e)}
        rc = 1
    print(json.dumps(report))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2)
    return rc


if __name__ == "__main__":
    sys.exit(main())
