"""Microbenchmark: flash-attention kernel vs XLA attention, fwd+bwd.

Usage: python tools/attn_bench.py [B T H D] [--window W]

--window adds sliding-window rows (band W) to the sweep: expected
speedup over full causal approaches T/(2W) as T grows (dead kv blocks
are skipped, ops/flash_attention.py _dispatch_block).
"""

from __future__ import annotations

import functools
import sys
import time

import _repo_path  # noqa: F401

import jax
import jax.numpy as jnp

from dlrover_tpu.models.gpt import _default_attention
from dlrover_tpu.ops.flash_attention import flash_attention


def timeit(fn, *args, n=20):
    out = fn(*args)
    jax.block_until_ready(out)
    # force real sync on axon transport
    jax.device_get(jax.tree.leaves(out)[0].ravel()[0])
    t0 = time.time()
    for _ in range(n):
        out = fn(*args)
    jax.device_get(jax.tree.leaves(out)[0].ravel()[0])
    return (time.time() - t0) / n


def main():
    args = sys.argv[1:]
    window = None
    if "--window" in args:
        i = args.index("--window")
        try:
            window = int(args[i + 1])
        except (IndexError, ValueError):
            sys.exit("usage: attn_bench.py [B T H D] --window <int>")
        del args[i:i + 2]
    B, T, H, D = 16, 1024, 12, 64
    if len(args) >= 4:
        B, T, H, D = map(int, args[:4])
    key = jax.random.PRNGKey(0)
    kq, kk, kv, kg = jax.random.split(key, 4)
    q = jax.random.normal(kq, (B, T, H, D), jnp.bfloat16)
    k = jax.random.normal(kk, (B, T, H, D), jnp.bfloat16)
    v = jax.random.normal(kv, (B, T, H, D), jnp.bfloat16)
    do = jax.random.normal(kg, (B, T, H, D), jnp.bfloat16)

    # Attention matmul FLOPs: fwd 4*B*H*T*T*D, bwd 2x+recompute.
    fwd_fl = 4 * B * H * T * T * D
    causal = 0.5  # causal effectively halves useful work

    def bench(name, attn):
        f = jax.jit(attn)
        vjp_f = jax.jit(
            lambda q, k, v, do: jax.vjp(attn, q, k, v)[1](do)
        )
        tf = timeit(f, q, k, v)
        tb = timeit(vjp_f, q, k, v, do)
        print(
            f"{name:28s} fwd={tf*1e3:7.2f}ms ({fwd_fl/tf/1e12:6.1f} TF/s "
            f"dense) bwd+fwd={tb*1e3:7.2f}ms",
            flush=True,
        )

    bench("xla", functools.partial(_default_attention, causal=True))
    for bq, bk in [(128, 128), (256, 256), (512, 512), (256, 512),
                   (512, 256), (1024, 128), (128, 1024)]:
        bench(
            f"flash bq={bq} bk={bk}",
            functools.partial(
                flash_attention, causal=True, block_q=bq, block_k=bk
            ),
        )
    if window is not None:
        for bq, bk in [(128, 128), (256, 256), (512, 512)]:
            bench(
                f"flash W={window} bq={bq} bk={bk}",
                functools.partial(
                    flash_attention, causal=True, window=window,
                    block_q=bq, block_k=bk,
                ),
            )


if __name__ == "__main__":
    main()
