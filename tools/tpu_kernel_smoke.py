"""Compile + parity-check every Pallas kernel on the REAL TPU.

The round-4 lesson: interpret-mode tests (the CPU suite) do not
enforce TPU tiling constraints — the round-3 fused-norm backward
shipped three rounds of green CPU tests while being uncompilable on
hardware (its (1, E) dg partials sat below the 8-sublane tile floor).
This tool is the guard: one run lowers and executes every kernel
variant on the live chip, checks numerics against the XLA reference,
and writes KERNELS_r{N}.json for the record.

Run on a TPU host:  python tools/tpu_kernel_smoke.py
Exit code is the number of failing kernels (0 = all good).

``--small`` shrinks shapes so the harness itself can be validated on
CPU in interpret mode in seconds — that run checks the TOOL, not the
hardware lowering (which is the entire point of the full run).
"""

from __future__ import annotations

import contextlib
import functools
import json
import sys
import time

import _repo_path  # noqa: F401

import os

import jax

# The site-installed axon hook overrides JAX_PLATFORMS at import
# time; re-assert the env choice (same dance as bench.py) so
# JAX_PLATFORMS=cpu --small really validates on CPU.
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax.numpy as jnp
import numpy as np

RESULTS = []
SMALL = "--small" in sys.argv
# (seq for flash checks, rows for xent) — small keeps CPU interpret
# runs tractable; full exercises the shipped 1024x1024 tiles.
SEQ = 128 if SMALL else 1024
XENT_V = 1024 if SMALL else 50304


def check(name, fn):
    t0 = time.time()
    try:
        fn()
        RESULTS.append(
            {"kernel": name, "ok": True,
             "seconds": round(time.time() - t0, 1)}
        )
        print(f"ok   {name} ({time.time() - t0:.1f}s)")
    except Exception as exc:  # noqa: BLE001
        RESULTS.append(
            {"kernel": name, "ok": False,
             "error": f"{type(exc).__name__}: {str(exc)[:300]}"}
        )
        print(f"FAIL {name}: {type(exc).__name__}: {str(exc)[:200]}")


def _close(a, b, atol, rtol=1e-3):
    np.testing.assert_allclose(
        np.asarray(a, np.float32), np.asarray(b, np.float32),
        atol=atol, rtol=rtol,
    )


def _prec(tag):
    """True-f32 parity needs both sides pinned to exact-f32 matmuls.

    Under the TPU default (single-pass bf16 on the MXU), the
    near-cancelling rows of attention backward (dp - delta ~= 0 for
    near-deterministic softmax rows) leave ~4e-3 * |dp| rounding
    residue, and kernel vs reference round differently — the first
    r5 hardware smoke measured 0.054 max-abs spread against the 2e-2
    f32 tolerance in exactly those rows, in BOTH the causal and
    windowed variants (same early rows, same data). The f32 checks
    validate the math, so they trace (kernel AND reference) under
    HIGHEST — multi-pass, f32-exact; all three then pass on chip.

    bf16 checks must stay at the production default: Mosaic rejects
    HIGHEST with bf16 operands (r5 smoke: remote-compile crash), and
    default single-pass is what training runs anyway.
    """
    return (jax.default_matmul_precision("highest") if tag == "f32"
            else contextlib.nullcontext())


def flash_checks():
    from dlrover_tpu.ops.flash_attention import flash_attention
    from dlrover_tpu.ops.prefix_lm import (
        prefix_lm_attention,
        prefix_lm_attention_reference,
    )

    # The canonical XLA reference the flash kernel must agree with —
    # the repo's own non-flash fallback, not a local re-derivation
    # that could drift.
    from dlrover_tpu.models.gpt import _default_attention as dense

    key = jax.random.PRNGKey(0)
    q, k, v = (
        jax.random.normal(kk, (2, SEQ, 4, 64), jnp.float32)
        for kk in jax.random.split(key, 3)
    )

    def grad_check(f_kernel, f_ref, *args, atol):
        """Full parity: dq AND dk AND dv (a wrong dkv accumulation
        must not exit 0 from a 'parity-check' tool)."""
        argnums = tuple(range(len(args)))
        gk = jax.jit(jax.grad(
            lambda *a: jnp.sum(f_kernel(*a).astype(jnp.float32) ** 2),
            argnums=argnums,
        ))
        gr = jax.jit(jax.grad(
            lambda *a: jnp.sum(f_ref(*a).astype(jnp.float32) ** 2),
            argnums=argnums,
        ))
        for got, want in zip(gk(*args), gr(*args)):
            _close(got, want, atol)

    # One dtype/tolerance table for every dtype-parametrized check —
    # TPU sublane tile floors are dtype-dependent (8 for f32, 16 for
    # bf16), so the production bf16 path needs its own lowering check
    # everywhere, at its own (looser) parity tolerance.
    DTYPES = (
        (jnp.float32, "f32", 2e-2), (jnp.bfloat16, "bf16", 0.5),
    )

    # fwd+bwd (dq/dk/dv), causal and full, at the shipped 1024x1024
    # tiles, in f32 AND bf16.
    for dt, tag, atol in DTYPES:
        qd, kd, vd = q.astype(dt), k.astype(dt), v.astype(dt)
        with _prec(tag):
            check(
                f"flash_causal_fwd_bwd_{tag}",
                functools.partial(
                    grad_check,
                    lambda q_, k_, v_: flash_attention(
                        q_, k_, v_, causal=True
                    ),
                    lambda q_, k_, v_: dense(q_, k_, v_, True),
                    qd, kd, vd, atol=atol,
                ),
            )
    with _prec("f32"):
        check(
            "flash_full_fwd_bwd",
            lambda: grad_check(
                lambda q_, k_, v_: flash_attention(
                    q_, k_, v_, causal=False
                ),
                lambda q_, k_, v_: dense(q_, k_, v_, False),
                q, k, v, atol=2e-2,
            ),
        )
    # Sliding window (Mistral band) + non-1024 sequence (512 tiles),
    # gradients included (the banded bwd has its own dispatch) — in
    # bf16 too (the production decode dtype; its tile floors are 2x
    # the f32 ones).
    half = SEQ // 2
    qs, ks, vs = q[:, :half], k[:, :half], v[:, :half]
    for dt, tag, atol in DTYPES:
        with _prec(tag):
            check(
                f"flash_sliding_window_fwd_bwd_{tag}",
                functools.partial(
                    grad_check,
                    lambda q_, k_, v_: flash_attention(
                        q_, k_, v_, causal=True, window=half // 4
                    ),
                    lambda q_, k_, v_: dense(
                        q_, k_, v_, True, window=half // 4
                    ),
                    qs.astype(dt), ks.astype(dt), vs.astype(dt),
                    atol=atol,
                ),
            )
    # Odd length -> internal padding path.
    odd = SEQ // 2 + 8
    qo, ko, vo = q[:, :odd], k[:, :odd], v[:, :odd]
    with _prec("f32"):
        check(
            "flash_padded_t520",
            lambda: _close(
                flash_attention(qo, ko, vo, causal=True),
                dense(qo, ko, vo, True), 2e-3,
            ),
        )
        # GLM prefix-LM composition (square prefix + rectangular
        # causal suffix) — exercises flash_attention_rect's lowering.
        check(
            "prefix_lm_composition",
            lambda: _close(
                prefix_lm_attention(q, k, v, SEQ // 3),
                prefix_lm_attention_reference(q, k, v, SEQ // 3), 2e-3,
            ),
        )
    # Rectangular grads (chunked-prefill shape: tail queries against
    # the full key set, per-side padding).
    from dlrover_tpu.ops.flash_attention import flash_attention_rect

    def dense_rect(q_, k_, v_, win=None):
        off = k_.shape[1] - q_.shape[1]
        d_ = q_.shape[-1]
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q_, k_,
            preferred_element_type=jnp.float32,
        ) / (d_**0.5)
        qp = off + jnp.arange(q_.shape[1])[:, None]
        kp = jnp.arange(k_.shape[1])[None, :]
        keep = kp <= qp
        if win is not None:
            keep &= (qp - kp) < win
        s = jnp.where(keep[None, None], s, -jnp.inf)
        w = jax.nn.softmax(s, axis=-1)
        return jnp.einsum(
            "bhqk,bkhd->bqhd", w, v_.astype(jnp.float32)
        ).astype(q_.dtype)

    tq = SEQ // 4
    with _prec("f32"):
        check(
            "flash_rect_fwd_bwd",
            lambda: grad_check(
                lambda q_, k_, v_: flash_attention_rect(
                    q_, k_, v_, causal=True
                ),
                dense_rect, q[:, -tq:], k, v, atol=2e-2,
            ),
        )

    # Banded rectangular (q_offset + window) — the windowed ring's
    # live non-resident hop kernel (parallel/ring_attention.py
    # _ring_flash_windowed) and windowed chunked prefill; new in r5,
    # never compiled on hardware before this check.
    win_w = SEQ // 8
    for dt, tag, atol in DTYPES:
        with _prec(tag):
            check(
                f"flash_rect_windowed_fwd_bwd_{tag}",
                functools.partial(
                    grad_check,
                    lambda q_, k_, v_: flash_attention_rect(
                        q_, k_, v_, causal=True, window=win_w
                    ),
                    lambda q_, k_, v_: dense_rect(
                        q_, k_, v_, win=win_w
                    ),
                    q[:, -tq:].astype(dt), k.astype(dt),
                    v.astype(dt), atol=atol,
                ),
            )


def norm_checks():
    from dlrover_tpu.ops.layer_norm import (
        fused_add_layer_norm,
        fused_add_rms_norm,
        fused_layer_norm,
        fused_rms_norm,
    )

    x = jax.random.normal(jax.random.PRNGKey(1), (64, 768), jnp.float32)
    g = jax.random.normal(jax.random.PRNGKey(2), (768,)) + 1.0
    b = jax.random.normal(jax.random.PRNGKey(3), (768,))

    def ref_rms(x, g, eps=1e-5):
        s = jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + eps)
        return x * s * g

    def ref_ln(x, g, b, eps=1e-5):
        mu = jnp.mean(x, -1, keepdims=True)
        s = jax.lax.rsqrt(
            jnp.mean((x - mu) ** 2, -1, keepdims=True) + eps
        )
        return (x - mu) * s * g + b

    def gcheck(fk, fr, args, atol=2e-2):
        gk = jax.jit(jax.grad(lambda *a: jnp.sum(fk(*a) ** 2),
                              argnums=tuple(range(len(args)))))
        gr = jax.jit(jax.grad(lambda *a: jnp.sum(fr(*a) ** 2),
                              argnums=tuple(range(len(args)))))
        for got, want in zip(gk(*args), gr(*args)):
            _close(got, want, atol)

    check("rms_norm_fwd_bwd", lambda: gcheck(
        fused_rms_norm, ref_rms, (x, g)))
    check("layer_norm_bias_fwd_bwd", lambda: gcheck(
        fused_layer_norm, ref_ln, (x, g, b)))
    check("add_rms_norm_fwd_bwd", lambda: gcheck(
        lambda x, r, g: fused_add_rms_norm(x, r, g)[0],
        lambda x, r, g: ref_rms(x + r, g), (x, x * 0.5, g)))
    # The exact variant GPT's fused path uses (bias + residual, the
    # db/dg accumulator that carried the round-4 tiling bug) — in
    # bf16 too, the production dtype.
    check("add_layer_norm_bias_fwd_bwd", lambda: gcheck(
        lambda x, r, g, b: fused_add_layer_norm(x, r, g, b)[0],
        lambda x, r, g, b: ref_ln(x + r, g, b), (x, x * 0.5, g, b)))
    xb = x.astype(jnp.bfloat16)
    check("add_layer_norm_bias_fwd_bwd_bf16", lambda: gcheck(
        lambda x, r, g, b: fused_add_layer_norm(x, r, g, b)[0]
        .astype(jnp.float32),
        lambda x, r, g, b: ref_ln(
            x.astype(jnp.float32) + r.astype(jnp.float32), g, b
        ),
        (xb, (x * 0.5).astype(jnp.bfloat16), g, b), atol=0.3))


def quant_checks():
    from dlrover_tpu.ops.quantization import (
        dequantize_blockwise,
        dequantize_blockwise_4bit,
        quantize_blockwise,
        quantize_blockwise_4bit,
    )

    x = jax.random.normal(jax.random.PRNGKey(4), (512, 256) if SMALL else (4096, 512))

    def rt8():
        q, s, shape = quantize_blockwise(x)
        y = dequantize_blockwise(q, s, shape)
        assert float(jnp.abs(y - x).max()) < 0.05

    def rt4():
        q, s, shape = quantize_blockwise_4bit(x)
        y = dequantize_blockwise_4bit(q, s, shape)
        assert float(jnp.abs(y - x).max()) < 0.6

    check("quantize_8bit_roundtrip", rt8)
    check("quantize_4bit_roundtrip", rt4)


def xent_checks():
    from dlrover_tpu.ops.cross_entropy import fused_cross_entropy

    n, e, v = (64, 128, XENT_V) if SMALL else (512, 256, 50304)
    x = jax.random.normal(jax.random.PRNGKey(5), (n, e)) * 0.1
    w = jax.random.normal(jax.random.PRNGKey(6), (v, e)) * 0.02
    t = jax.random.randint(jax.random.PRNGKey(7), (n,), 0, v)

    def ref(x, w):
        logits = (x @ w.T).astype(jnp.float32)
        lp = jax.nn.log_softmax(logits, -1)
        return -jnp.mean(
            jnp.take_along_axis(lp, t[:, None], axis=-1)
        )

    for save in (False, True):
        def run(save=save):
            gk = jax.jit(jax.grad(
                lambda x, w: fused_cross_entropy(x, w, t, 8, save),
                argnums=(0, 1),
            ))
            gr = jax.jit(jax.grad(ref, argnums=(0, 1)))
            for got, want in zip(gk(x, w), gr(x, w)):
                _close(got, want, 2e-3)

        check(f"fused_xent_save_logits_{int(save)}", run)


def main() -> int:
    print(f"devices: {jax.devices()}")
    if jax.default_backend() not in ("tpu", "axon"):
        print("WARNING: not on TPU — this run does NOT validate "
              "hardware lowering")
    flash_checks()
    norm_checks()
    quant_checks()
    xent_checks()
    fails = [r for r in RESULTS if not r["ok"]]
    out = {
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "total": len(RESULTS),
        "failed": len(fails),
        "results": RESULTS,
    }
    # Only a full-shape run on the real chip earns the round
    # artifact; a --small/CPU run validates the harness, not the
    # hardware lowering, and must not masquerade as the record.
    on_tpu = jax.default_backend() in ("tpu", "axon")
    path = (
        "KERNELS_r05.json" if (on_tpu and not SMALL)
        else "/tmp/kernel_smoke_harness.json"
    )
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"total": len(RESULTS), "failed": len(fails)}))
    return len(fails)


if __name__ == "__main__":
    sys.exit(main())
