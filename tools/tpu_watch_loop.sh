#!/usr/bin/env bash
# Round-4 capture loop: keep trying the health-gated bench until the
# TPU tunnel answers, then land the verified number in PERF_r04.json
# and stop. Designed to run unattended in tmux for hours; every
# attempt's outcome is appended to /tmp/tpu_watch_r4b.log.
set -u
cd "$(dirname "$0")/.."

LOG=/tmp/tpu_watch_r4b.log
echo "[$(date +%F' '%T)] watch loop starting" >> "$LOG"
attempt=0
while true; do
  attempt=$((attempt + 1))
  # bench.py itself health-probes, retries with backoff inside this
  # budget, and always prints one JSON line.
  out=$(BENCH_MAX_WAIT_S=600 BENCH_PROBE_TIMEOUT=90 python bench.py \
        2>>"$LOG")
  echo "[$(date +%F' '%T)] attempt $attempt: $out" >> "$LOG"
  if [ -n "$out" ] && ! grep -q '"error"' <<<"$out"; then
    echo "$out" > /tmp/bench_success.json
    if python - <<'EOF' >> "$LOG" 2>&1
import json, os, time
line = open("/tmp/bench_success.json").read().strip().splitlines()[-1]
rec = json.loads(line)
rec.update(stage="baseline", config="shipped defaults",
           ts=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
hist = []
if os.path.exists("PERF_r04.json"):
    # A corrupt/non-list history must fail loudly, not be overwritten.
    hist = json.load(open("PERF_r04.json"))
    assert isinstance(hist, list), f"PERF_r04.json is not a list: {hist!r}"
hist.append(rec)
tmp = "PERF_r04.json.tmp"
json.dump(hist, open(tmp, "w"), indent=1)
os.replace(tmp, "PERF_r04.json")
print("PERF_r04.json <-", rec)
EOF
    then
      echo "[$(date +%F' '%T)] SUCCESS - loop exiting" >> "$LOG"
      break
    else
      echo "[$(date +%F' '%T)] bench OK but PERF_r04.json append" \
           "FAILED - fix by hand; raw line in /tmp/bench_success.json" \
           >> "$LOG"
      break
    fi
  fi
  sleep 90
done
