#!/usr/bin/env bash
# Thin wrapper: the unattended perf-capture chain lives in
# tools/capture_perf.py (baseline bench loop -> autotune -> tuned
# re-bench, each landed in PERF_r05.json atomically). Logs to
# /tmp/tpu_watch_r4b.log.
set -u
cd "$(dirname "$0")/.."
exec python tools/capture_perf.py >> /tmp/tpu_watch_r4b.log 2>&1
