"""Perf sweep harness: times the GPT-2 train step across configs.

Usage:
  python tools/perf_sweep.py 'remat,flash,batch[,bq,bk[,sl[,bqb,bkb]]]'
  remat: full | attn | none | dots | offload
  flash: flash | xla | noop (noop stubs attention to measure the
         step's non-attention cost by subtraction)
  sl: save-logits cross-entropy variant (pass "sl"; "-" to skip)
  bqb,bkb: backward-kernel block sizes (default = forward blocks)
  nofn / fn (anywhere): force the fused Pallas norms off / on
         (absent = config default, off since the r4 measurement)

Prints one line per config: config, step ms, MFU, vs_baseline.
"""

from __future__ import annotations

import dataclasses
import os
import functools
import sys
import time

import _repo_path  # noqa: F401

import jax
import jax.numpy as jnp
import optax

from dlrover_tpu.models import gpt
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.trainer.step import (
    make_sharded_init,
    make_train_step,
    shard_batch,
)

PEAK = 197e12
REF_HFU = 0.496


def is_unroll_token(p: str) -> bool:
    """"uK" scan-unroll flag token. Shared with capture_perf's
    winner_env so spec parsing and env pinning can't desynchronize."""
    return len(p) > 1 and p[0] == "u" and p[1:].isdigit()


def is_xent_token(p: str) -> bool:
    """"xcN" fused-CE chunk-count flag token (same sharing rule)."""
    return len(p) > 2 and p[:2] == "xc" and p[2:].isdigit()


def build_spec(spec: str):
    """Parse a sweep spec -> (cfg, attn_fn, batch, save_logits,
    xent_chunks). Shared with tools/profile_step.py so the profiled
    config is byte-identical to the benchmarked one. Omitted fields
    default to flash attention with the kernel's own autotuned block
    sizes and batch 16; xent_chunks resolves here (xcN token, else
    SWEEP_XENT_CHUNKS, else 8) so every caller sees one value."""
    parts = spec.split(",")
    # "nofn"/"fn" are flag tokens, not positional: strip them before
    # the positional fields so they really work anywhere in the spec.
    # nofn forces the fused Pallas norms OFF, fn forces them ON;
    # absent = the config default (off since r4 — see
    # gpt.use_fused_norm).
    fused_norm = None
    if "nofn" in parts:
        fused_norm = False
    elif "fn" in parts:
        fused_norm = True
    # "uK" (e.g. u2, u4): lax.scan unroll factor for the layer stack.
    # "xcN" (e.g. xc4): fused-CE chunk count (r5 trace: the f32 dwte
    # accumulator is re-read/written once per chunk — 144 MB x chunks
    # of pure accumulator traffic at GPT-2 vocab).
    unroll, xent_chunks = 1, None
    for p in parts:
        if is_unroll_token(p):
            unroll = int(p[1:])
        elif is_xent_token(p):
            xent_chunks = int(p[2:])
    if xent_chunks is None:  # token absent — env fallback, then 8
        xent_chunks = int(os.getenv("SWEEP_XENT_CHUNKS", "8"))
    parts = [
        p for p in parts
        if p not in ("nofn", "fn")
        and not is_unroll_token(p) and not is_xent_token(p)
    ]
    remat_s = parts[0]
    flash_s = parts[1] if len(parts) > 1 else "flash"
    batch = int(parts[2]) if len(parts) > 2 else 16
    def _blk(i):
        # "-" (or absence) = kernel default for any block field
        if len(parts) <= i or parts[i] == "-":
            return None
        return int(parts[i])

    block_q = _blk(3)
    block_k = _blk(4)
    save_logits = len(parts) > 5 and parts[5] == "sl"
    block_q_bwd = _blk(6)
    block_k_bwd = _blk(7)
    remat = {
        "full": True, "attn": "attention", "none": False,
        "dots": "dots", "offload": "offload", "sattn": "save_attn",
    }[remat_s]
    use_flash = flash_s == "flash"

    cfg = dataclasses.replace(
        gpt.GPTConfig.gpt2(), remat=remat,
        use_flash_attention=use_flash, use_fused_norm=fused_norm,
        scan_unroll=unroll,
    )
    attn_fn = None
    if flash_s == "noop":
        # Attention stubbed to identity-on-v: measures the step's
        # non-attention cost by subtraction.
        attn_fn = lambda q, k, v: v  # noqa: E731
    elif use_flash:
        from dlrover_tpu.ops.flash_attention import flash_attention

        # block_q/block_k None -> default_block_sizes autotuning
        attn_fn = functools.partial(
            flash_attention, causal=True, block_q=block_q,
            block_k=block_k, block_q_bwd=block_q_bwd,
            block_k_bwd=block_k_bwd,
        )
    return cfg, attn_fn, batch, save_logits, xent_chunks


def run_config(mesh, spec: str) -> None:
    cfg, attn_fn, batch, save_logits, spec_chunks = build_spec(spec)

    optimizer = optax.adamw(3e-4, weight_decay=0.1)
    # Fused-CE recompute granularity (bigger chunks = bigger bwd
    # matmuls and fewer dwte accumulator round-trips, more logits HBM
    # at once); fully resolved by build_spec.
    chunks = spec_chunks
    loss = functools.partial(
        gpt.loss_fn_fused, cfg=cfg, attn_fn=attn_fn,
        save_logits=save_logits, num_chunks=chunks,
    )
    init, _ = make_sharded_init(
        mesh,
        functools.partial(gpt.init_params, cfg=cfg),
        gpt.param_logical_axes(cfg),
        optimizer,
    )
    params, opt_state = init(jax.random.PRNGKey(0))
    step = make_train_step(mesh, loss, optimizer)

    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, cfg.block_size), 0, cfg.vocab_size
    )
    targets = jnp.roll(tokens, -1, axis=1)
    tokens, targets = shard_batch(mesh, tokens, targets)

    try:
        for _ in range(3):
            params, opt_state, metrics = step(
                params, opt_state, tokens, targets
            )
        float(metrics["loss"])
        n_steps = 10
        t0 = time.time()
        for _ in range(n_steps):
            params, opt_state, metrics = step(
                params, opt_state, tokens, targets
            )
        float(metrics["loss"])
        dt = (time.time() - t0) / n_steps
    except Exception as e:  # noqa: BLE001
        print(f"{spec:32s} FAILED: {type(e).__name__}: {str(e)[:120]}")
        return
    tok_s = batch * cfg.block_size / dt
    mfu = tok_s * gpt.flops_per_token(cfg) / PEAK
    print(
        f"{spec:32s} step={dt*1000:7.1f}ms tok/s={tok_s:9.0f} "
        f"mfu={mfu:.3f} vs={mfu/REF_HFU:.3f}",
        flush=True,
    )


def main():
    mesh = build_mesh(MeshConfig(data=len(jax.devices())))
    for spec in sys.argv[1:]:
        run_config(mesh, spec)


if __name__ == "__main__":
    main()
