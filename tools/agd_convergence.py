"""Measure AGD-vs-AdamW convergence on the nanoGPT task (real TPU).

The reference claims AGD converges up to 1.5x faster than AdamW on
nanoGPT pretraining (BASELINE.md; /root/reference/atorch/docs/
README-AGD.md:29). This runs both optimizers on identical data and
init for N steps of the bench model (GPT-2 124M unless --small) and
reports loss-at-step plus steps-to-target ratios, writing
AGD_CONVERGENCE_r05.json.

Run:  python tools/agd_convergence.py [--small] [--steps N]
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
import sys
import time

import _repo_path  # noqa: F401

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax.numpy as jnp
import optax

from dlrover_tpu.models import gpt
from dlrover_tpu.optim.agd import agd as agd_opt
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.trainer.step import (
    make_sharded_init,
    make_train_step,
    shard_batch,
)


def run(optimizer, cfg, mesh, steps, log_every):
    """Train from the same seed; return the loss trace."""
    loss = functools.partial(gpt.loss_fn_fused, cfg=cfg)
    init, _ = make_sharded_init(
        mesh,
        functools.partial(gpt.init_params, cfg=cfg),
        gpt.param_logical_axes(cfg),
        optimizer,
    )
    params, opt_state = init(jax.random.PRNGKey(0))
    step = make_train_step(mesh, loss, optimizer)

    # FRESH synthetic batch per step (same generative rule, stepped
    # seed): convergence on a data distribution, not single-batch
    # memorization — the regime the reference's 1.5x claim is about.
    # The rule (segment transforms of a shared base) is learnable, so
    # the loss trace separates optimizers, unlike uniform-random
    # tokens whose floor is log(V) for every optimizer.
    def batch(i):
        key = jax.random.PRNGKey(1000 + i)
        base = jax.random.randint(
            key, (8 * max(1, len(jax.devices())), cfg.block_size // 4),
            0, cfg.vocab_size // 4,
        )
        tokens = jnp.concatenate(
            [base, base * 2 % cfg.vocab_size,
             base * 3 % cfg.vocab_size, (base + 7) % cfg.vocab_size],
            axis=1,
        )
        targets = jnp.roll(tokens, -1, axis=1)
        return shard_batch(mesh, tokens, targets)

    trace = []
    for i in range(steps):
        tokens, targets = batch(i)
        params, opt_state, m = step(params, opt_state, tokens, targets)
        # The final step is ALWAYS logged — ratios and "final loss"
        # must describe step `steps`, not the last log_every multiple.
        if (i + 1) % log_every == 0 or (i + 1) == steps:
            trace.append((i + 1, float(m["loss"])))
    return trace


def best_finite_trace(runs):
    """(key, trace) with the lowest FINITE final loss; a NaN-diverged
    run must never win (NaN compares false against everything, which
    would freeze min() on whichever trace it met first). Falls back
    to the raw dict only if every run diverged."""
    import math

    finite = {
        k: tr for k, tr in runs.items() if math.isfinite(tr[-1][1])
    }
    return min((finite or runs).items(), key=lambda kv: kv[1][-1][1])


def steps_to(trace, target):
    for s, l in trace:
        if l <= target:
            return s
    return None


def main() -> int:
    small = "--small" in sys.argv
    steps = 200
    for i, a in enumerate(sys.argv):
        if a == "--steps":
            steps = int(sys.argv[i + 1])
    cfg = gpt.GPTConfig.gpt2() if not small else gpt.GPTConfig.nano()
    if small:
        # Reduced-scale dims, overridable (AGD_LAYERS/BLOCK/VOCAB env)
        # so the CPU fallback can run a mid-size study instead of the
        # 2-layer nano default when wall-clock allows.
        cfg = dataclasses.replace(
            cfg,
            n_layer=int(os.environ.get("AGD_LAYERS", 2)),
            block_size=int(os.environ.get("AGD_BLOCK", 128)),
            vocab_size=int(os.environ.get("AGD_VOCAB", 1024)),
            dtype=jnp.float32, remat=False,
        )
    mesh = build_mesh(MeshConfig(data=len(jax.devices())))
    log_every = max(1, steps // 40)

    t0 = time.time()

    # Both optimizers run the standard nanoGPT-style schedule —
    # linear warmup (10% of steps) + cosine to 10% of peak. This
    # matters most for AGD: with the reference-recommended
    # delta=1e-14 the early preconditioned steps are enormous while
    # v_t is still tiny, and a constant LR lets that noise dominate a
    # short study (measured r5: constant-LR AGD lost 0.86x even at
    # the reference's own lr/delta settings).
    def sched(peak):
        # warmup < decay_steps or optax's cosine segment is empty
        # (a --steps 1 smoke run would crash at construction).
        warmup = min(max(1, steps // 10), max(steps - 1, 0))
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=peak,
            warmup_steps=warmup,
            decay_steps=steps, end_value=peak * 0.1,
        )

    adamw = run(
        optax.adamw(sched(3e-4), b1=0.9, b2=0.95, weight_decay=0.1),
        cfg, mesh, steps, log_every,
    )
    # AGD at the reference's documented transformer settings — lr
    # 1/10 of AdamW's, delta 1e-14 (README-AGD.md:22-23; the first r5
    # run used AdamW's own lr and the 1e-5 delta default and AGD
    # unsurprisingly lost, speedup 0.6). Two LR points; ratios use
    # the better trace, both are recorded.
    agd_runs = {}
    for lr in (3e-5, 6e-5):
        agd_runs[lr] = run(
            agd_opt(sched(lr), betas=(0.9, 0.95), delta=1e-14,
                    weight_decay=0.1),
            cfg, mesh, steps, log_every,
        )
        # Completed-trace checkpoint: a tunnel drop during a later
        # run must not erase finished ones (the r5 longctx lesson).
        with open("/tmp/agd_partial.json", "w") as f:
            json.dump(
                {"adamw": adamw,
                 "agd": {str(k): v for k, v in agd_runs.items()}},
                f,
            )
    agd_lr, agd = best_finite_trace(agd_runs)
    # Ratio: AdamW steps / AGD steps to reach the loss AGD ends at
    # (and a mid target), >1 means AGD is faster.
    final_agd = agd[-1][1]
    mid = (agd[0][1] + final_agd) / 2
    ratios = {}
    for name, tgt in (("final_agd_loss", final_agd), ("mid_loss", mid)):
        sa, sb = steps_to(adamw, tgt), steps_to(agd, tgt)
        ratios[name] = {
            "target": round(tgt, 4),
            "adamw_steps": sa,
            "agd_steps": sb,
            "speedup": (round(sa / sb, 3) if sa and sb else None),
        }
        if sa is None and sb is not None:
            # AdamW never reached AGD's loss within the budget: the
            # true speedup is censored at steps/sb — report the floor
            # rather than an ambiguous null.
            ratios[name]["speedup_floor"] = round(steps / sb, 3)
            ratios[name]["agd_strictly_better"] = True
    out = {
        "model": (
            "gpt2-124M" if not small else
            f"nano-small(L{cfg.n_layer},T{cfg.block_size},"
            f"V{cfg.vocab_size})"
        ),
        "steps": steps,
        "backend": jax.default_backend(),
        "adamw_trace": adamw,
        "agd_lr": agd_lr,
        "adamw_lr": 3e-4,
        "agd_delta": 1e-14,
        "schedule": "warmup 10% + cosine to 0.1x peak (both)",
        "agd_trace": agd,
        "agd_traces_by_lr": {str(k): v for k, v in agd_runs.items()},
        "ratios": ratios,
        "reference_claim": "AGD up to 1.5x faster than AdamW "
                           "(atorch/docs/README-AGD.md:29)",
        "elapsed_s": round(time.time() - t0, 1),
    }
    # Same artifact gating as the other round tools: only a full-size
    # run on the real chip writes the round record. VERDICT r4 next #3
    # sanctioned fallback: if the tunnel stays dead all round,
    # AGD_ALLOW_CPU=1 lets a reduced-scale CPU run write the round
    # artifact — loudly labeled, never silently passed off as
    # hardware-scale.
    on_tpu = jax.default_backend() in ("tpu", "axon")
    cpu_fallback = (
        os.environ.get("AGD_ALLOW_CPU") == "1" and not on_tpu
    )
    if cpu_fallback:
        out["note"] = (
            "CPU fallback at reduced scale (TPU tunnel unavailable "
            "all round) — convergence-ratio evidence only; absolute "
            "wall-clock numbers are not hardware-representative"
        )
        out["scale"] = "reduced-cpu"
    path = (
        "AGD_CONVERGENCE_r05.json" if (on_tpu and not small) or cpu_fallback
        else "/tmp/agd_convergence_check.json"
    )
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(
        {"final_adamw": adamw[-1][1], "final_agd": final_agd,
         "ratios": ratios}
    ))
    return 0


if __name__ == "__main__":
    sys.exit(main())
