"""Append-only, fingerprinted ledger of bench results + regression gate.

Why: the r5 perf levers shipped *unmeasured* because the one capture
window crashed and the numbers evaporated (VERDICT r5 #1-#2). The
ledger makes that structurally impossible to repeat: every successful
``bench.py`` run (including the ones ``tools/capture_perf.py`` and
``tools/bench_stability.py`` drive) appends one JSON line to
``BENCH_LEDGER.jsonl`` at the repo root — fingerprinted with git rev,
config hash, backend, host, and toolchain versions — and ``compare``
turns the history into a CI-able regression gate.

Record shape (one JSON object per line)::

    {"metric": "nanogpt_tokens_per_sec_per_chip", "value": 12345.6,
     "unit": "tokens/s/chip", "vs_baseline": 0.92, "mfu": 0.457,
     "stage": "baseline" | "tuned" | "stability" | "adhoc",
     "stats": {"n": 3, "mean": ..., "stddev": ..., "spread_pct": ...},
     "git_rev": "<full sha>", "config_hash": "<12 hex>",
     "meta": {"host": ..., "backend": ..., "jax": ..., "jaxlib": ...},
     "ts": "2026-08-03T12:00:00Z"}

``stats`` is present when the record came from the 3-run stability
protocol (STABILITY_r05.json lineage); single runs carry ``value``
only. Error records (``"error": ...``, value 0.0) are kept — a dead
capture window should be visible in the history — but never picked as
a comparison endpoint.

Usage::

    python tools/bench_ledger.py append --json '{"metric": ..., ...}'
    python tools/bench_ledger.py compare --baseline <rev-prefix|last>
        [--threshold 0.03] [--metric NAME[,NAME...]]
        [--head <rev-prefix>]
    python tools/bench_ledger.py show [-n 10]

``compare`` exit codes: 0 = head within threshold of baseline (or
better), 1 = regression past the threshold, 2 = can't compare
(missing records / bad arguments) — distinct so CI can tell "slower"
from "blind". ``--metric`` accepts a comma-separated list: each
metric is gated independently (its own report + verdict line) and
the aggregated exit code takes the worst outcome, with "slower"
outranking "blind" — any regression → 1, else any not-comparable
→ 2, else 0.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional, Tuple

import _repo_path  # noqa: F401

from dlrover_tpu.common.runmeta import (
    config_fingerprint,
    git_rev,
    run_metadata,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LEDGER_ENV = "DLROVER_TPU_BENCH_LEDGER"
DEFAULT_THRESHOLD = 0.03  # ~2.4x the measured 3-run spread (1.26%)


def ledger_path(path: Optional[str] = None) -> str:
    return (
        path
        or os.getenv(LEDGER_ENV, "")
        or os.path.join(REPO, "BENCH_LEDGER.jsonl")
    )


def append_record(
    record: dict,
    path: Optional[str] = None,
    env: Optional[dict] = None,
    backend: Optional[str] = None,
) -> dict:
    """Fingerprint ``record`` and append it as one JSON line.

    Pre-set fields win (a caller that already knows its backend or
    stage keeps them); the fingerprint fills whatever is missing. The
    write is a single ``O_APPEND`` line, so concurrent writers can
    interleave records but never tear one."""
    rec = dict(record)
    rec.setdefault(
        "ts", time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    )
    rec.setdefault("git_rev", git_rev(REPO))
    rec.setdefault("config_hash", config_fingerprint(env, repo=REPO))
    rec.setdefault(
        "meta", run_metadata(backend=backend or rec.get("backend"))
    )
    rec.setdefault("stage", os.getenv("BENCH_LEDGER_STAGE", "adhoc"))
    line = json.dumps(rec, sort_keys=True)
    fd = os.open(
        ledger_path(path),
        os.O_WRONLY | os.O_CREAT | os.O_APPEND,
        0o644,
    )
    try:
        os.write(fd, (line + "\n").encode())
    finally:
        os.close(fd)
    return rec


def load_records(path: Optional[str] = None) -> List[dict]:
    """Every parseable record, in append order. Corrupt lines are
    skipped with a note on stderr — a half-written line must not make
    the whole history unreadable."""
    records = []
    try:
        with open(ledger_path(path)) as f:
            for i, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    print(
                        f"[ledger] skipping corrupt line {i}",
                        file=sys.stderr,
                    )
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
    except OSError:
        pass
    return records


def record_value(rec: dict) -> Optional[float]:
    """The comparable throughput of a record: the stability mean when
    the record carries multi-run stats, else the single-run value.
    None for error records (value 0 with an error class)."""
    if rec.get("error"):
        return None
    stats = rec.get("stats")
    if isinstance(stats, dict) and stats.get("mean"):
        return float(stats["mean"])
    value = rec.get("value")
    if isinstance(value, (int, float)) and value > 0:
        return float(value)
    return None


def lower_is_better(metric: Optional[str]) -> bool:
    """Latency metrics gate in the opposite direction from
    throughput: a HIGHER ttft/tpot/latency-seconds value is the
    regression. Keyed on the metric naming convention (duration
    metrics end in _s/_seconds/_ms or name the latency quantity)."""
    if not metric:
        return False
    m = metric.lower()
    return (
        m.endswith(("_s", "_seconds", "_ms"))
        or "latency" in m
        or "ttft" in m
        or "tpot" in m
    )


def _matches(rec: dict, selector: str) -> bool:
    if selector in ("", "last"):
        return True
    rev = str(rec.get("git_rev", ""))
    if rev.startswith(selector):
        return True
    return rec.get("stage") == selector or rec.get("label") == selector


def find_record(
    records: List[dict],
    selector: str,
    metric: Optional[str] = None,
    before: Optional[dict] = None,
) -> Optional[dict]:
    """Newest measurable record matching ``selector`` (a git-rev
    prefix, a stage/label name, or "last"), optionally strictly older
    than ``before`` (so --baseline last never compares head to
    itself)."""
    seen_before = before is None
    for rec in reversed(records):
        if not seen_before:
            if rec is before:
                seen_before = True
            continue
        if metric and rec.get("metric") != metric:
            continue
        if record_value(rec) is None:
            continue
        if _matches(rec, selector):
            return rec
    return None


def compare(
    baseline: str,
    head: str = "",
    metric: Optional[str] = None,
    threshold: float = DEFAULT_THRESHOLD,
    path: Optional[str] = None,
) -> Tuple[int, str]:
    """(exit code, human report). Regression = head more than
    ``threshold`` (fractional) WORSE than baseline: below it for
    higher-is-better metrics (throughput), above it for
    lower-is-better ones (latency — see :func:`lower_is_better`)."""
    records = load_records(path)
    if not records:
        return 2, f"no ledger records at {ledger_path(path)}"
    head_rec = find_record(records, head or "last", metric=metric)
    if head_rec is None:
        return 2, f"no measurable head record (selector {head or 'last'!r})"
    if metric is None:
        metric = head_rec.get("metric")
    base_rec = find_record(
        records, baseline, metric=metric, before=head_rec
    )
    if base_rec is None:
        return 2, (
            f"no measurable baseline record for selector {baseline!r} "
            f"(metric {metric!r}) older than head"
        )
    head_v = record_value(head_rec)
    base_v = record_value(base_rec)
    delta = (head_v - base_v) / base_v
    inverted = lower_is_better(metric)
    regressed = delta > threshold if inverted else delta < -threshold

    def _describe(tag, rec, v):
        meta = rec.get("meta", {}) or {}
        stats = rec.get("stats") or {}
        extra = (
            f" (n={stats.get('n')}, stddev={stats.get('stddev')})"
            if stats
            else ""
        )
        v_str = f"{v:.1f}" if v >= 10 else f"{v:g}"
        return (
            f"  {tag}: {v_str} {rec.get('unit', '')}{extra}\n"
            f"    rev={str(rec.get('git_rev', ''))[:12]} "
            f"stage={rec.get('stage')} config={rec.get('config_hash')} "
            f"backend={meta.get('backend')} host={meta.get('host')} "
            f"ts={rec.get('ts')}"
        )

    lines = [
        f"bench ledger compare [{metric}], threshold {threshold:.1%}"
        + (" (lower is better)" if inverted else "")
        + ":",
        _describe("head    ", head_rec, head_v),
        _describe("baseline", base_rec, base_v),
        f"  delta: {delta:+.2%} -> "
        + ("REGRESSION" if regressed else "ok"),
    ]
    if (head_rec.get("meta") or {}).get("backend") != (
        base_rec.get("meta") or {}
    ).get("backend"):
        lines.append(
            "  WARNING: head and baseline ran on different backends —"
            " this delta compares hardware, not code"
        )
    if head_rec.get("config_hash") != base_rec.get("config_hash"):
        lines.append(
            "  note: config fingerprints differ (knobs/pins changed "
            "between the runs)"
        )
        # Records carry the applied pins since the tune-cache PR, so
        # a config mismatch is debuggable here instead of by
        # re-running both sides.
        hp = head_rec.get("pins") or {}
        bp = base_rec.get("pins") or {}
        for k in sorted(set(hp) | set(bp)):
            if hp.get(k) != bp.get(k):
                lines.append(
                    f"    pin {k}: head={hp.get(k, '<unset>')} "
                    f"baseline={bp.get(k, '<unset>')}"
                )
    return (1 if regressed else 0), "\n".join(lines)


def show(n: int = 10, path: Optional[str] = None) -> str:
    records = load_records(path)[-n:]
    if not records:
        return f"no ledger records at {ledger_path(path)}"
    lines = [f"last {len(records)} ledger records:"]
    for rec in records:
        v = record_value(rec)
        meta = rec.get("meta", {}) or {}
        lines.append(
            f"  {rec.get('ts')} {str(rec.get('git_rev', ''))[:12]} "
            f"{str(rec.get('stage') or '-'):<9} "
            + (
                f"{v:10.1f}"
                if v is not None
                else f"ERROR({rec.get('error')})"
            )
            + f" {rec.get('unit', '')} backend={meta.get('backend')} "
            f"config={rec.get('config_hash')}"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser("bench_ledger")
    p.add_argument("--ledger", default="", help="ledger file override")
    sub = p.add_subparsers(dest="cmd", required=True)

    ap = sub.add_parser("append", help="append one fingerprinted record")
    ap.add_argument(
        "--json", required=True,
        help="the record as a JSON object string",
    )
    ap.add_argument("--stage", default="", help="stage label override")

    cp = sub.add_parser("compare", help="regression-gate head vs baseline")
    cp.add_argument(
        "--baseline", required=True,
        help="git-rev prefix, stage/label name, or 'last' "
        "(newest measurable record older than head)",
    )
    cp.add_argument("--head", default="", help="head selector (default: newest)")
    cp.add_argument(
        "--metric", default=None,
        help="metric name, or a comma-separated list — each metric "
        "gates independently and the worst outcome wins the exit "
        "code (any regression -> 1, else any not-comparable -> 2, "
        "else 0)",
    )
    cp.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD)

    sp = sub.add_parser("show", help="print recent records")
    sp.add_argument("-n", type=int, default=10)

    args = p.parse_args(argv)
    path = args.ledger or None
    if args.cmd == "append":
        try:
            rec = json.loads(args.json)
        except ValueError as exc:
            print(f"unparseable --json: {exc}", file=sys.stderr)
            return 2
        if not isinstance(rec, dict):
            print("--json must be a JSON object", file=sys.stderr)
            return 2
        if args.stage:
            rec["stage"] = args.stage
        stored = append_record(rec, path=path)
        print(json.dumps(stored, sort_keys=True))
        return 0
    if args.cmd == "compare":
        metrics = (
            [m.strip() for m in args.metric.split(",") if m.strip()]
            if args.metric
            else [None]
        )
        results = []
        for metric in metrics:
            rc, report = compare(
                args.baseline,
                head=args.head,
                metric=metric,
                threshold=args.threshold,
                path=path,
            )
            results.append((metric, rc))
            print(report)
        if len(results) > 1:
            # Per-metric verdicts, then one aggregated exit code:
            # any regression beats any not-comparable beats ok.
            verdict = {0: "ok", 1: "REGRESSED", 2: "not comparable"}
            print("per-metric verdicts:")
            for metric, rc in results:
                print(f"  {metric}: {verdict.get(rc, rc)}")
        codes = [rc for _, rc in results]
        if 1 in codes:
            return 1
        if 2 in codes:
            return 2
        return 0
    print(show(args.n, path=path))
    return 0


if __name__ == "__main__":
    sys.exit(main())
