"""Trace the bench train step on the real chip and print a per-op
time attribution.

Usage: python tools/profile_step.py [spec]   (spec as in perf_sweep)

Captures a jax.profiler trace of a few steps, then parses the
trace.json.gz xplane export and aggregates device-lane event
durations by op name — the flat profile the reference gets from
AProfiler's module hooks, here straight from XLA's own timeline.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import sys
import tempfile

TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))

import _repo_path  # noqa: F401


def capture(spec: str, trace_dir: str) -> None:
    import functools

    import jax
    import jax.numpy as jnp
    import optax

    from dlrover_tpu.models import gpt
    from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
    from dlrover_tpu.trainer.step import (
        make_sharded_init,
        make_train_step,
        shard_batch,
    )

    # Exactly perf_sweep's spec grammar AND config construction —
    # build_spec is shared so the profiled program is the benched one.
    from perf_sweep import build_spec

    cfg, attn_fn, batch, save_logits, xent_chunks = build_spec(spec)
    mesh = build_mesh(MeshConfig(data=len(jax.devices())))
    optimizer = optax.adamw(3e-4, weight_decay=0.1)
    loss = functools.partial(
        gpt.loss_fn_fused, cfg=cfg, attn_fn=attn_fn,
        save_logits=save_logits, num_chunks=xent_chunks,
    )
    init, _ = make_sharded_init(
        mesh,
        functools.partial(gpt.init_params, cfg=cfg),
        gpt.param_logical_axes(cfg),
        optimizer,
    )
    params, opt_state = init(jax.random.PRNGKey(0))
    step = make_train_step(mesh, loss, optimizer)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, cfg.block_size), 0,
        cfg.vocab_size,
    )
    targets = jnp.roll(tokens, -1, axis=1)
    tokens, targets = shard_batch(mesh, tokens, targets)
    for _ in range(3):  # compile + warm
        params, opt_state, m = step(params, opt_state, tokens, targets)
    float(m["loss"])
    with jax.profiler.trace(trace_dir):
        for _ in range(3):
            params, opt_state, m = step(
                params, opt_state, tokens, targets
            )
        float(m["loss"])


def report(trace_dir: str, top: int = 25) -> None:
    paths = glob.glob(
        f"{trace_dir}/**/*.trace.json.gz", recursive=True
    )
    if not paths:
        print("no trace.json.gz produced", file=sys.stderr)
        return
    with gzip.open(sorted(paths)[-1], "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    # device lanes: pid whose process_name mentions TPU/device
    name_by_pid = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            name_by_pid[e["pid"]] = e["args"].get("name", "")
    device_pids = {
        pid for pid, n in name_by_pid.items()
        if "TPU" in n or "/device" in n.lower() or "XLA" in n
    }
    per_op = collections.Counter()
    src_of = {}
    total = 0.0
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        dur = float(e.get("dur", 0.0))
        name = e.get("name", "?")
        # Purely numeric names are per-execution run-id envelopes
        # (one per profiled step) duplicating jit_step's total — they
        # drown the real per-op rows without adding information.
        if name.isdigit():
            continue
        per_op[name] += dur
        total += dur
        if name not in src_of and e.get("args"):
            a = e["args"]
            src = a.get("source") or ""
            tf_op = a.get("tf_op") or ""
            # keep the repo-relative tail of the source path and the
            # last two named-scope segments of the tf_op
            if src:
                repo = os.path.dirname(TOOLS_DIR) + os.sep
                src = src.split(repo)[-1]
            if tf_op:
                tf_op = "/".join(tf_op.rstrip(":").split("/")[-2:])
            attr = " ".join(x for x in (src, tf_op) if x)
            if attr:  # first NON-EMPTY attribution wins
                src_of[name] = attr
    if not per_op:
        print(
            f"lanes seen: {sorted(set(name_by_pid.values()))[:10]}",
            file=sys.stderr,
        )
        print("no device events matched", file=sys.stderr)
        return
    print(f"# total device time {total / 1e3:.2f} ms (3 steps)")
    for name, dur in per_op.most_common(top):
        print(
            f"{dur / total * 100:6.2f}%  {dur / 1e3 / 3:8.3f} ms/step"
            f"  {name[:46]:46s} {src_of.get(name, '')[:70]}"
        )


def main() -> int:
    spec = sys.argv[1] if len(sys.argv) > 1 else "full,flash,16"
    trace_dir = tempfile.mkdtemp(prefix="dlrover_tpu_trace_")
    capture(spec, trace_dir)
    report(trace_dir)
    print(f"# trace dir: {trace_dir}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
