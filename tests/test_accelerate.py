"""auto_accelerate: analyser, candidate pruning, dry-run search."""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.accelerate import (
    Strategy,
    analyse_model,
    auto_accelerate,
)
from dlrover_tpu.accelerate.analyser import estimate_step_memory
from dlrover_tpu.accelerate.strategy import (
    candidate_strategies,
    _factorizations,
)
from dlrover_tpu.models import gpt


CFG = gpt.GPTConfig(
    vocab_size=256,
    block_size=64,
    n_layer=2,
    n_head=2,
    n_embd=32,
    dtype=jnp.float32,
    remat=False,
)


def _model():
    init = functools.partial(gpt.init_params, cfg=CFG)
    loss = functools.partial(gpt.loss_fn, cfg=CFG)
    axes = gpt.param_logical_axes(CFG)
    return init, loss, axes


def _sample_batch(n=2):
    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(key, (n, CFG.block_size), 0, 256)
    return tokens, jnp.roll(tokens, -1, axis=1)


def test_factorizations():
    fs = _factorizations(8, 3)
    assert (8, 1, 1) in fs and (2, 2, 2) in fs and (1, 1, 8) in fs
    for f in fs:
        assert f[0] * f[1] * f[2] == 8


def test_candidate_strategies_cover_mesh_space():
    cands = candidate_strategies(8)
    names = {c.name() for c in cands}
    assert len(names) == len(cands)  # no duplicates
    shapes = {c.mesh_dict["fsdp"] for c in cands}
    assert {1, 2, 4, 8} <= shapes
    # pipe is a first-class search axis (VERDICT r3 weak #3)
    pipes = {c.mesh_dict.get("pipe", 1) for c in cands}
    assert {1, 2, 4, 8} <= pipes
    assert all(
        c.mesh_dict.get("pipe", 1) <= 4
        for c in candidate_strategies(8, max_pipe=4)
    )


def test_analyse_model_counts_params():
    init, _, _ = _model()
    a = analyse_model(init)
    real = gpt.num_params(gpt.init_params(jax.random.PRNGKey(0), CFG))
    assert a.n_params == real


def test_memory_estimate_prunes_impossible():
    init, _, _ = _model()
    a = analyse_model(init)
    # a tiny "HBM" of 1KB: nothing fits
    s = Strategy(mesh_shape=(("data", 8),))
    _, fits = estimate_step_memory(a, s, 1 << 20, hbm_bytes=1 << 10)
    assert not fits
    # sharded model on generous HBM fits
    s2 = Strategy(mesh_shape=(("data", 1), ("fsdp", 8)))
    _, fits2 = estimate_step_memory(a, s2, 1 << 10, hbm_bytes=1 << 30)
    assert fits2
    # more sharding -> strictly less memory
    e_dp, _ = estimate_step_memory(a, s, 1 << 10, 1 << 30)
    e_fsdp, _ = estimate_step_memory(a, s2, 1 << 10, 1 << 30)
    assert e_fsdp < e_dp


def test_explicit_strategy_path_trains():
    init, loss, axes = _model()
    s = Strategy(
        mesh_shape=(("data", 2), ("fsdp", 2), ("tensor", 2)),
        dtype="float32",
        micro_batch_size=4,
    )
    res = auto_accelerate(
        init, loss, axes, _sample_batch(), strategy=s,
        devices=jax.devices()[:8],
    )
    params, opt_state = res.init_fn(jax.random.PRNGKey(0))
    tokens, targets = _sample_batch(8)
    tokens, targets = res.shard_batch_fn(tokens, targets)
    losses = []
    for _ in range(5):
        params, opt_state, metrics = res.step_fn(
            params, opt_state, tokens, targets
        )
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]


def test_search_picks_a_strategy_and_logs():
    init, loss, axes = _model()
    cands = [
        Strategy(mesh_shape=(("data", 4),), micro_batch_size=4,
                 dtype="float32"),
        Strategy(mesh_shape=(("data", 2), ("fsdp", 2)),
                 micro_batch_size=4, dtype="float32"),
    ]
    res = auto_accelerate(
        init, loss, axes, _sample_batch(),
        devices=jax.devices()[:4],
        candidates=cands,
        hbm_bytes=1 << 30,
        activation_bytes_per_sample=1 << 10,
    )
    assert res.strategy in cands
    assert res.throughput is not None and res.throughput > 0
    ran = [e for e in res.search_log if "samples_per_sec" in e]
    assert len(ran) == 2


def test_candidate_strategies_seq_impl_knob():
    """seq_impls only multiplies candidates that have a real seq axis."""
    cands = candidate_strategies(8, seq_impls=("ring", "a2a"))
    with_seq = [c for c in cands if c.mesh_dict.get("seq", 1) > 1]
    without_seq = [c for c in cands if c.mesh_dict.get("seq", 1) == 1]
    assert {c.seq_impl for c in with_seq} == {"ring", "a2a"}
    assert {c.seq_impl for c in without_seq} == {"auto"}
    assert len({c.name() for c in cands}) == len(cands)
    # round-trips through json
    s = with_seq[0]
    assert Strategy.from_json(s.to_json()) == s


@pytest.mark.parametrize("seq_impl", ["ring", "a2a", "auto"])
def test_seq_strategy_trains_with_each_impl(seq_impl):
    """A seq-sharded strategy binds the chosen sequence-parallel
    attention family into the built step (CFG has 2 heads, seq=2:
    the a2a head constraint holds, auto also routes to a2a)."""
    init, loss, axes = _model()
    s = Strategy(
        mesh_shape=(("data", 2), ("seq", 2)),
        dtype="float32",
        micro_batch_size=4,
        seq_impl=seq_impl,
    )
    res = auto_accelerate(
        init, loss, axes, _sample_batch(), strategy=s,
        devices=jax.devices()[:4],
    )
    params, opt_state = res.init_fn(jax.random.PRNGKey(0))
    tokens, targets = res.shard_batch_fn(*_sample_batch(4))
    losses = []
    for _ in range(5):
        params, opt_state, metrics = res.step_fn(
            params, opt_state, tokens, targets
        )
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]


def test_windowed_model_trains_under_seq_sharding():
    """A sliding-window (Mistral-style) config now COMPOSES with a seq
    axis (VERDICT r4 weak #3): the binding forwards cfg.sliding_window
    into the windowed ring/a2a schedules instead of refusing, and the
    one-step loss matches the single-device windowed loss."""
    from dlrover_tpu.models import llama

    lcfg = dataclasses.replace(
        llama.LlamaConfig.tiny(),
        block_size=32,
        sliding_window=12,
        use_flash_attention=False,  # CPU: xla ring path
    )
    init = functools.partial(llama.init_params, cfg=lcfg)
    loss = functools.partial(llama.loss_fn, cfg=lcfg)
    axes = llama.param_logical_axes(lcfg)
    from dlrover_tpu.accelerate.api import _seq_attention_opts

    assert _seq_attention_opts(loss) == {
        "window": 12, "impl": "xla", "causal": True,
    }
    tokens = jnp.zeros((4, lcfg.block_size), jnp.int32)
    s = Strategy(
        mesh_shape=(("data", 2), ("seq", 2)),
        dtype="float32",
        micro_batch_size=4,
        seq_impl="ring",
    )
    res = auto_accelerate(
        init, loss, axes, (tokens, tokens), strategy=s,
        devices=jax.devices()[:4],
    )
    params, opt_state = res.init_fn(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(3)
    tok = jax.random.randint(key, (4, lcfg.block_size), 0,
                             lcfg.vocab_size)
    tgt = jnp.roll(tok, -1, axis=1)
    stok, stgt = res.shard_batch_fn(tok, tgt)
    # Single-device windowed reference loss from the same init —
    # computed BEFORE the step call, which donates params.
    want = float(loss(params, tok, tgt))
    _, _, metrics = res.step_fn(params, opt_state, stok, stgt)
    sharded_loss = float(metrics["loss"])
    assert abs(sharded_loss - want) < 5e-4, (sharded_loss, want)


def test_seq_binding_honors_model_attention_pin():
    """The auto-binding must not override a cfg-pinned attention
    kernel choice, and must leave models with a caller-bound attn_fn
    alone."""
    from dlrover_tpu.accelerate.api import (
        _maybe_bind_seq_attention,
        _seq_attention_opts,
    )
    from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh

    # cfg pin -> impl forwarded; causal always declared by GPTConfig
    pinned = functools.partial(
        gpt.loss_fn,
        cfg=dataclasses.replace(CFG, use_flash_attention=False),
    )
    assert _seq_attention_opts(pinned) == {
        "impl": "xla", "causal": True,
    }
    assert _seq_attention_opts(
        functools.partial(gpt.loss_fn, cfg=CFG)
    ) == {"causal": True}
    # a non-causal declaration rides through to the binding
    assert _seq_attention_opts(
        functools.partial(
            gpt.loss_fn, cfg=dataclasses.replace(CFG, causal=False)
        )
    ) == {"causal": False}

    mesh = build_mesh(
        MeshConfig(data=2, seq=2), devices=jax.devices()[:4]
    )
    s = Strategy(mesh_shape=(("data", 2), ("seq", 2)))
    # caller already bound attn_fn: binding is a no-op
    prebound = functools.partial(
        gpt.loss_fn, cfg=CFG, attn_fn=gpt._default_attention
    )
    assert _maybe_bind_seq_attention(prebound, mesh, s) is prebound
    # unbound hook gets wrapped; explicit kwargs thread through
    bound = _maybe_bind_seq_attention(
        functools.partial(gpt.loss_fn, cfg=CFG), mesh, s,
        seq_attention_kwargs={"causal": True},
    )
    assert isinstance(bound, functools.partial)
    assert "attn_fn" in bound.keywords

    # a REQUIRED attn_fn hook (no default) is still bound, not skipped
    def required_hook_loss(params, tokens, targets, *, attn_fn):
        return gpt.loss_fn(
            params, tokens, targets, cfg=CFG, attn_fn=attn_fn
        )

    bound2 = _maybe_bind_seq_attention(required_hook_loss, mesh, s)
    assert isinstance(bound2, functools.partial)
    assert "attn_fn" in bound2.keywords


def test_search_excludes_unexecutable_pipe_candidates():
    """The generic GSPMD step cannot run a pipe axis as 1F1B, so the
    dry-run search must skip pipe>1 candidates rather than measure a
    replicated impostor (they stay in the grid for plan mode and
    parallel.pipeline users)."""
    init, loss, axes = _model()
    cands = [
        Strategy(mesh_shape=(("data", 4),), micro_batch_size=4,
                 dtype="float32"),
        Strategy(mesh_shape=(("data", 2), ("pipe", 2)),
                 micro_batch_size=4, dtype="float32"),
    ]
    res = auto_accelerate(
        init, loss, axes, _sample_batch(),
        devices=jax.devices()[:4],
        candidates=cands,
        hbm_bytes=1 << 30,
        activation_bytes_per_sample=1 << 10,
    )
    assert res.strategy == cands[0]
    ran = [e for e in res.search_log if "samples_per_sec" in e]
    assert len(ran) == 1  # only the non-pipe candidate was measured


def test_llama2_7b_plan_for_v5p32_in_ci():
    """BASELINE.md tracks 'Llama-2-7B FSDP-equivalent via
    auto-accelerate'. Plan-only proof, pure eval_shape — no compile,
    no devices: the memory model must admit viable v5p-32 candidates,
    reject unsharded replication, and the ranked plan must shard the
    model at least 8 ways with predicted HBM under the 95 GB/chip
    budget (ref planning loop: atorch/auto/accelerate.py:196-227)."""
    from dlrover_tpu.accelerate import plan_strategies
    from dlrover_tpu.accelerate.analyser import HBM_BYTES
    from dlrover_tpu.models import llama

    cfg = llama.LlamaConfig.llama2_7b()
    init = functools.partial(llama.init_params, cfg=cfg)
    loss = functools.partial(llama.loss_fn, cfg=cfg)
    # Raw (no-remat) activation bytes/sample: ~10 E-wide + 3
    # intermediate-wide tensors per layer, bf16.
    act = int(
        cfg.n_layer * cfg.block_size
        * (10 * cfg.n_embd + 3 * cfg.intermediate) * 2
    )
    hbm = HBM_BYTES["v5p"]
    tokens = jnp.zeros((1, cfg.block_size), jnp.int32)
    entries = plan_strategies(
        init,
        n_devices=32,
        hbm_bytes=hbm,
        activation_bytes_per_sample=act,
        model_loss=loss,
        sample_batch=(tokens, tokens),
        chip="v5p",  # rank with the TARGET's peaks, not this host's
    )
    assert entries, "no viable 7B strategy on v5p-32"

    def shards(e):
        m = e.strategy.mesh_dict
        return (
            m.get("fsdp", 1) * m.get("tensor", 1) * m.get("pipe", 1)
        )

    top = entries[0]
    assert shards(top) >= 8, (
        f"top plan barely shards: {top.strategy.mesh_dict}"
    )
    assert top.est_bytes_per_device < hbm
    assert top.predicted_step_s is not None  # roofline ranked
    # an fsdp>=8 plan is among the viable set (the tracked config)
    assert any(
        e.strategy.mesh_dict.get("fsdp", 1) >= 8 for e in entries
    )
    # unsharded replication must NOT fit anywhere in the viable set:
    # 7B params + f32 optimizer state alone exceed 95 GB/chip
    assert all(shards(e) > 1 for e in entries)


class TestTuneCacheWarmStart:
    """Acceptance gate: a warm persistent cache reaches the same best
    strategy with STRICTLY fewer dry-run evaluations, observably."""

    CANDS = [
        Strategy(mesh_shape=(("data", 4),), micro_batch_size=4,
                 dtype="float32"),
        Strategy(mesh_shape=(("data", 2), ("fsdp", 2)),
                 micro_batch_size=4, dtype="float32"),
    ]

    def _run(self, tune_cache):
        init, loss, axes = _model()
        return auto_accelerate(
            init, loss, axes, _sample_batch(),
            devices=jax.devices()[:4],
            candidates=list(self.CANDS),
            hbm_bytes=1 << 30,
            activation_bytes_per_sample=1 << 10,
            tune_cache=tune_cache,
        )

    @staticmethod
    def _dry_runs(res):
        return [
            e for e in res.search_log
            if "samples_per_sec" in e and not e.get("cached")
        ]

    def test_warm_cache_skips_dry_runs(self, tmp_path):
        from dlrover_tpu.obs.metrics import get_registry

        cache_path = str(tmp_path / "tune.jsonl")
        r1 = self._run(cache_path)
        assert len(self._dry_runs(r1)) == 2
        # every dry-run was recorded as a trial
        import json as _json

        with open(cache_path) as f:
            trials = [_json.loads(line) for line in f]
        assert len(trials) == 2
        assert all(not t["failed"] for t in trials)

        hits = get_registry().get("dlrover_tune_cache_hits_total")
        h0 = hits.value()
        r2 = self._run(cache_path)
        assert len(self._dry_runs(r2)) == 0  # strictly fewer: zero
        cached = [e for e in r2.search_log if e.get("cached")]
        assert len(cached) == 2
        assert r2.strategy == r1.strategy
        assert hits.value() == h0 + 1

    def test_cache_false_disables_read_and_write(self, tmp_path):
        import os

        r = self._run(False)
        assert len(self._dry_runs(r)) == 2
        # nothing written anywhere under the default resolution either
        assert not os.path.exists(str(tmp_path / "tune.jsonl"))

    def test_cached_failure_replayed_as_avoided_point(self, tmp_path):
        from dlrover_tpu.accelerate import tune_cache as tc
        from dlrover_tpu.accelerate.api import _tune_cache_key
        from dlrover_tpu.accelerate.analyser import analyse_model

        init, loss, axes = _model()
        key = _tune_cache_key(
            analyse_model(init), _sample_batch(), 4
        )
        cache = tc.TuneCache(str(tmp_path / "tune.jsonl"))
        # pre-poison candidate 1 as a cached OOM
        cache.record(key, self.CANDS[1].to_json(), None, failed=True)
        r = self._run(cache)
        # only the non-poisoned candidate was dry-run; the cached
        # failure kept its twin out of the winner's seat
        assert len(self._dry_runs(r)) == 1
        assert r.strategy == self.CANDS[0]
        errs = [e for e in r.search_log if e.get("cached")]
        assert errs and errs[0]["error"] == "cached failed trial"

    def test_fully_poisoned_cache_retries_fresh(self, tmp_path):
        """A cache holding only failures for EVERY candidate must not
        pin the job to instant permanent failure: the failures may be
        a stale transient (another process holding HBM, a flaky
        compile), and without fresh dry-runs no success could ever
        land to clear them."""
        from dlrover_tpu.accelerate import tune_cache as tc
        from dlrover_tpu.accelerate.api import _tune_cache_key
        from dlrover_tpu.accelerate.analyser import analyse_model

        init, loss, axes = _model()
        key = _tune_cache_key(
            analyse_model(init), _sample_batch(), 4
        )
        cache = tc.TuneCache(str(tmp_path / "tune.jsonl"))
        for s in self.CANDS:
            cache.record(key, s.to_json(), None, failed=True)
        r = self._run(cache)
        assert len(self._dry_runs(r)) == 2  # fresh runs happened
        assert r.strategy in self.CANDS

    def test_unmatchable_records_count_as_miss(self, tmp_path):
        """Records for the key whose configs match no current
        candidate (a Strategy schema drift) replay nothing — that
        must read as a MISS, not a 100% hit rate avoiding no work."""
        from dlrover_tpu.accelerate import tune_cache as tc
        from dlrover_tpu.accelerate.api import _tune_cache_key
        from dlrover_tpu.accelerate.analyser import analyse_model
        from dlrover_tpu.obs.metrics import get_registry

        init, loss, axes = _model()
        key = _tune_cache_key(
            analyse_model(init), _sample_batch(), 4
        )
        cache = tc.TuneCache(str(tmp_path / "tune.jsonl"))
        cache.record(key, '{"schema": "from-the-future"}', 99.0)
        reg = get_registry()
        h0 = reg.get("dlrover_tune_cache_hits_total").value()
        m0 = reg.get("dlrover_tune_cache_misses_total").value()
        r = self._run(cache)
        assert len(self._dry_runs(r)) == 2  # nothing avoided
        assert reg.get("dlrover_tune_cache_hits_total").value() == h0
        assert (
            reg.get("dlrover_tune_cache_misses_total").value() == m0 + 1
        )


class TestOverlapStrategy:
    def test_grid_overlap_only_on_pure_data_factorizations(self):
        cands = candidate_strategies(
            8,
            micro_batch_sizes=(4,),
            remats=(False,),
            overlap_reduces=(False, True),
            reduce_bucket_mbs=(2.0, 8.0),
        )
        with_ov = [c for c in cands if c.overlap_reduce]
        assert with_ov, "no overlap candidates generated"
        assert all(c.pure_data_parallel for c in with_ov)
        # bucket size only multiplies overlapped candidates
        assert {c.reduce_bucket_mb for c in with_ov} == {2.0, 8.0}
        assert all(
            c.reduce_bucket_mb == 4.0
            for c in cands
            if not c.overlap_reduce
        )
        assert len({c.name() for c in cands}) == len(cands)
        s = with_ov[0]
        assert Strategy.from_json(s.to_json()) == s

    def test_explicit_overlap_strategy_trains(self):
        init, loss, axes = _model()
        s = Strategy(
            mesh_shape=(("data", 4),),
            dtype="float32",
            micro_batch_size=4,
            overlap_reduce=True,
            reduce_bucket_mb=0.5,
        )
        res = auto_accelerate(
            init, loss, axes, _sample_batch(), strategy=s,
            devices=jax.devices()[:4],
        )
        params, opt_state = res.init_fn(jax.random.PRNGKey(0))
        tokens, targets = res.shard_batch_fn(*_sample_batch(4))
        losses = []
        for _ in range(5):
            params, opt_state, metrics = res.step_fn(
                params, opt_state, tokens, targets
            )
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]

    def test_overlap_on_sharded_mesh_rejected(self):
        init, loss, axes = _model()
        s = Strategy(
            mesh_shape=(("data", 2), ("fsdp", 2)),
            dtype="float32",
            micro_batch_size=4,
            overlap_reduce=True,
        )
        with pytest.raises(ValueError, match="overlap_reduce"):
            auto_accelerate(
                init, loss, axes, _sample_batch(), strategy=s,
                devices=jax.devices()[:4],
            )


class TestPipelineStrategy:
    def test_grid_pipeline_knobs_and_json_roundtrip(self):
        cands = candidate_strategies(
            8,
            micro_batch_sizes=(4,),
            remats=(False,),
            pipeline_depths=(0, 2),
            device_prefetchs=(True, False),
        )
        with_pd = [c for c in cands if c.pipeline_depth]
        assert with_pd, "no pipelined candidates generated"
        # pipelining needs the built-in step: never on a pipe axis
        assert all(
            c.mesh_dict.get("pipe", 1) == 1 for c in with_pd
        )
        assert {c.device_prefetch for c in cands} == {True, False}
        assert len({c.name() for c in cands}) == len(cands)
        s = with_pd[0]
        assert "-pd:2" in s.name()
        assert Strategy.from_json(s.to_json()) == s
        # pre-knob Strategy JSON (older tune-cache records) decodes
        # with the defaults — warm starts stay replayable
        import dataclasses as _dc
        import json as _json

        d = _dc.asdict(s)
        d.pop("pipeline_depth")
        d.pop("device_prefetch")
        old = Strategy.from_json(_json.dumps(d))
        assert old.pipeline_depth == 0 and old.device_prefetch

    def test_encoding_covers_pipeline_knobs(self):
        from dlrover_tpu.accelerate.bayes_search import encode_strategy

        base = Strategy(mesh_shape=(("data", 8),))
        pd = Strategy(mesh_shape=(("data", 8),), pipeline_depth=2)
        pd4 = Strategy(mesh_shape=(("data", 8),), pipeline_depth=4)
        nodp = Strategy(
            mesh_shape=(("data", 8),), device_prefetch=False
        )
        encs = [
            tuple(encode_strategy(s)) for s in (base, pd, pd4, nodp)
        ]
        assert len(set(encs)) == 4

    def test_explicit_pipelined_strategy_trains(self):
        init, loss, axes = _model()
        s = Strategy(
            mesh_shape=(("data", 4),),
            dtype="float32",
            micro_batch_size=4,
            pipeline_depth=1,
        )
        res = auto_accelerate(
            init, loss, axes, _sample_batch(), strategy=s,
            devices=jax.devices()[:4],
        )
        params, opt_state = res.init_fn(jax.random.PRNGKey(0))
        tokens, targets = res.shard_batch_fn(*_sample_batch(4))
        losses = []
        for _ in range(5):
            params, opt_state, metrics = res.step_fn(
                params, opt_state, tokens, targets
            )
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]
        assert "grad_norm" in metrics  # the shared metrics contract


def test_search_raises_when_nothing_fits():
    init, loss, axes = _model()
    with pytest.raises(RuntimeError, match="no strategy fits"):
        auto_accelerate(
            init, loss, axes, _sample_batch(),
            devices=jax.devices()[:4],
            hbm_bytes=1 << 10,
        )
