"""Sequence-sharded prefix-LM attention: two-pass ring vs dense.

Removes the round-4 GLM limitation (prefix-LM was single-shard along
the sequence): pass 1 is the causal ring, pass 2 a prefix-masked
bidirectional ring whose boundary block contributes through a
static-shape rectangular call, and rows select by global position
(parallel/ring_attention.py ring_prefix_lm_attention).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.ops.prefix_lm import prefix_lm_attention_reference
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.parallel.ring_attention import (
    make_sharded_prefix_attention,
)

B, T, H, D = 2, 64, 4, 16


@pytest.fixture(scope="module")
def qkv():
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(
        jax.random.normal(k, (B, T, H, D), jnp.float32) for k in ks
    )


# p=13: boundary mid-block (b_p=0, rem=13); p=16: exact block edge
# (rem=0); p=37: later block straddle; p=64: fully bidirectional.
@pytest.mark.parametrize("prefix_len", [0, 13, 16, 37, 64])
def test_ring_prefix_matches_dense(qkv, prefix_len):
    q, k, v = qkv
    mesh = build_mesh(
        MeshConfig(seq=4), devices=jax.devices()[:4]
    )
    attn = make_sharded_prefix_attention(mesh, prefix_len)
    got = jax.jit(attn)(q, k, v)
    want = prefix_lm_attention_reference(q, k, v, prefix_len)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=5e-5, rtol=1e-3
    )


def test_ring_prefix_composes_with_tensor_and_batch(qkv):
    """seq x tensor x data composition: heads ride ``tensor``,
    batch rides ``data``, sequence blocks ride the ring."""
    q, k, v = qkv
    mesh = build_mesh(
        MeshConfig(data=2, seq=2, tensor=2),
        devices=jax.devices()[:8],
    )
    attn = make_sharded_prefix_attention(mesh, 37)
    got = jax.jit(attn)(q, k, v)
    want = prefix_lm_attention_reference(q, k, v, 37)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=5e-5, rtol=1e-3
    )


@pytest.mark.slow
def test_ring_prefix_grads_flow(qkv):
    q, k, v = qkv
    mesh = build_mesh(MeshConfig(seq=4), devices=jax.devices()[:4])
    attn = make_sharded_prefix_attention(mesh, 37)

    def loss(q, k, v):
        return jnp.sum(attn(q, k, v) ** 2)

    def dense_loss(q, k, v):
        return jnp.sum(
            prefix_lm_attention_reference(q, k, v, 37)
            .astype(jnp.float32) ** 2
        )

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.jit(jax.grad(dense_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-3
        )


def test_unsharded_mesh_delegates_to_exact_composition(qkv):
    q, k, v = qkv
    mesh = build_mesh(
        MeshConfig(data=4), devices=jax.devices()[:4]
    )
    attn = make_sharded_prefix_attention(mesh, 24)
    got = attn(q, k, v)
    want = prefix_lm_attention_reference(q, k, v, 24)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4
    )


def test_glm_routes_to_ring_via_mesh(qkv):
    """Model-level wiring: prefix_attention_for(mesh=...) with seq>1
    returns the sharded ring path; losses through the GLM backbone
    match the single-shard path."""
    from dlrover_tpu.models import glm, llama

    cfg = glm.tiny()
    params = glm.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(4), (2, cfg.block_size), 8, cfg.vocab_size
    )
    mesh = build_mesh(MeshConfig(seq=4), devices=jax.devices()[:4])
    attn = glm.prefix_attention_for(cfg, 24, mesh=mesh)
    h_ring = llama.backbone(params, tokens, cfg, attn)
    h_single = llama.backbone(
        params, tokens, cfg, glm.prefix_attention_for(cfg, 24)
    )
    np.testing.assert_allclose(
        np.asarray(h_ring), np.asarray(h_single), atol=2e-4, rtol=2e-3
    )


def test_sharded_path_validates_prefix_len(qkv):
    """Out-of-range prefix_len raises on the SHARDED path too (it
    silently meant 'fully bidirectional' before review)."""
    q, k, v = qkv
    mesh = build_mesh(MeshConfig(seq=4), devices=jax.devices()[:4])
    attn = make_sharded_prefix_attention(mesh, T + 100)
    with pytest.raises(ValueError, match="prefix_len"):
        jax.jit(attn)(q, k, v)
