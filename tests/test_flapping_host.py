"""Flapping-host chaos: a host that dies and is relaunched IMMEDIATELY
— and then dies AGAIN mid-recovery — must still converge to a steady
2-host world. The soak drill waits for stability between kills; this
test deliberately doesn't, covering the rendezvous/agent races that
rapid churn exposes (a node registering while its previous
incarnation's death is still being processed; a kill landing mid-
rendezvous). Reuses the preemption drill's real-process helpers
(master + agents + jax.distributed trainers)."""

import os
import re
import signal
import time

from examples.chaos.host_preemption_drill import (
    start_agent,
    start_master,
    wait_stepping,
)


def test_double_flap_converges(tmp_path):
    tmp = str(tmp_path)
    m0 = os.path.join(tmp, "metrics_n0.json")
    m1 = os.path.join(tmp, "metrics_n1.json")
    master, addr = start_master(tmp)
    agents = {}
    # One shared wall-clock budget (the sibling drill test bounds its
    # subprocess at 900 s): every wait below draws from it, so a hung
    # rendezvous — the regime this test provokes — fails in ~15 min,
    # not the sum of all per-wait deadlines.
    deadline = time.time() + 900

    def budget():
        return max(5.0, deadline - time.time())

    try:
        agents[0] = start_agent(0, addr, tmp, 2000)
        agents[1] = start_agent(1, addr, tmp, 2000)
        t0 = time.time()
        assert wait_stepping(m0, t0 - 1, budget(), min_step=3), tmp
        assert wait_stepping(m1, t0 - 1, budget(), min_step=3), tmp

        # Flap 1: kill and relaunch host 1 with NO stabilization wait.
        os.killpg(agents[1].pid, signal.SIGKILL)
        agents[1].wait()
        agents[1] = start_agent(1, addr, tmp, 2000)

        # Flap 2: re-kill a few seconds later — mid-recovery — and
        # relaunch again.
        time.sleep(4)
        os.killpg(agents[1].pid, signal.SIGKILL)
        agents[1].wait()
        time.sleep(2)
        agents[1] = start_agent(1, addr, tmp, 2000)

        # Both hosts stepping within the remaining shared budget
        # (capped — convergence itself should take well under this).
        t_conv = time.time()
        c0 = wait_stepping(m0, t_conv, min(240, budget()), min_step=1)
        c1 = wait_stepping(m1, t_conv, min(240, budget()), min_step=1)
        assert c0 and c1, f"flap did not converge: {c0} {c1}; see {tmp}"
        # Stepping alone is not convergence — a split brain (each host
        # alone in its own world=1) would also step. The final spawn
        # on BOTH agents must be in the re-formed 2-host world.
        for rank in (0, 1):
            with open(os.path.join(tmp, f"agent_n{rank}.log")) as f:
                spawns = re.findall(r"rank=\d+/(\d+)", f.read())
            assert spawns and spawns[-1] == "2", (
                f"agent {rank}'s final world is /{spawns[-1:]}, "
                f"not the re-formed 2-host world; see {tmp}"
            )
    finally:
        for a in agents.values():
            if a.poll() is None:
                try:
                    os.killpg(a.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
        master.terminate()
        try:
            master.wait(10)
        except Exception:  # noqa: BLE001
            master.kill()
