"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Must set the env vars before jax initializes its backends, so this
executes at conftest import time (pytest loads conftest before test
modules import jax).
"""

import os

# Force-override: the environment presets JAX_PLATFORMS=axon (the real
# TPU tunnel, registered by a sitecustomize hook at interpreter start);
# tests always run on the virtual 8-device CPU mesh. The env var alone
# does not win against the preregistered backend, so also flip the jax
# config after import.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The persistent autotune cache defaults to TUNE_CACHE.jsonl at the
# repo root; tests must never write there. Suites that exercise the
# cache pass an explicit tmp path (which bypasses this) or monkeypatch
# the env themselves.
os.environ.setdefault("DLROVER_TPU_TUNE_CACHE", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def fresh_context():
    """Reset the global Context singleton around a test."""
    from dlrover_tpu.common.config import Context

    Context.reset()
    yield Context.singleton()
    Context.reset()


@pytest.fixture(autouse=True)
def _teardown_ckpt_saver_singleton():
    """The agent-hosted AsyncCheckpointSaver is a process singleton;
    a test that started one (agent run paths) must not pin its
    checkpoint dir for later tests' standalone Checkpointers."""
    yield
    from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver

    inst = AsyncCheckpointSaver._instance
    if inst is not None:
        try:
            inst.close()
        except Exception:  # noqa: BLE001
            pass
