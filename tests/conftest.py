"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Must set the env vars before jax initializes its backends, so this
executes at conftest import time (pytest loads conftest before test
modules import jax).
"""

import os

# Force-override: the environment presets JAX_PLATFORMS=axon (the real
# TPU tunnel, registered by a sitecustomize hook at interpreter start);
# tests always run on the virtual 8-device CPU mesh. The env var alone
# does not win against the preregistered backend, so also flip the jax
# config after import.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The persistent autotune cache defaults to TUNE_CACHE.jsonl at the
# repo root; tests must never write there. Suites that exercise the
# cache pass an explicit tmp path (which bypasses this) or monkeypatch
# the env themselves.
os.environ.setdefault("DLROVER_TPU_TUNE_CACHE", "0")

# The remediation engine's background thread must never act mid-test
# on a JobMaster a suite built for something else (its first tick at
# the default 15 s cadence could cordon a deliberately-degraded drill
# host and change later assertions). Suites that exercise remediation
# pass an explicit config (which beats the env) and tick manually.
os.environ.setdefault("DLROVER_TPU_REMEDIATION_INTERVAL_S", "9999")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# -- multiprocess-CPU-jax capability gate ------------------------------------
#
# The suites that build a REAL 2-process jax.distributed world
# (test_flapping_host, test_two_host_drill, test_multiprocess_jax)
# require the CPU backend to execute cross-process computations. Some
# jaxlib builds (0.4.36 among them) rendezvous fine and then raise
# "Multiprocess computations aren't implemented on the CPU backend"
# at the first collective — and a worker wedged on that error can hang
# a drill until the harness timeout kills the whole run, taking every
# alphabetically-later suite with it. Probe the capability ONCE with a
# minimal 2-process allgather (no repo code, so a probe failure is the
# environment, never a regression) and skip those suites with the real
# reason instead of hanging.

_MULTIPROCESS_SUITES = {
    "test_flapping_host.py",
    "test_two_host_drill.py",
    "test_multiprocess_jax.py",
}

_PROBE_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import numpy as np
import jax
jax.distributed.initialize(
    coordinator_address=sys.argv[1],
    num_processes=2,
    process_id=int(sys.argv[2]),
    initialization_timeout=60,
)
from jax.experimental import multihost_utils
mine = np.array([jax.process_index() + 1.0], np.float32)
world = multihost_utils.process_allgather(mine)
assert sorted(world.ravel().tolist()) == [1.0, 2.0], world
print("PROBE_OK", flush=True)
"""


def _probe_multiprocess_cpu_jax():
    """(ok, reason): can this container run a 2-process CPU
    jax.distributed collective?"""
    import socket
    import subprocess
    import sys as _sys
    import tempfile

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    addr = f"127.0.0.1:{port}"
    with tempfile.NamedTemporaryFile(
        "w", suffix="_mp_probe.py", delete=False
    ) as f:
        f.write(_PROBE_WORKER)
        worker = f.name
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    procs = [
        subprocess.Popen(
            [_sys.executable, worker, addr, str(pid)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
        )
        for pid in range(2)
    ]
    outputs = []
    ok = True
    for p in procs:
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            out = (out or b"") + b"\n[probe timeout]"
        outputs.append(out.decode(errors="replace"))
        ok = ok and p.returncode == 0
    os.unlink(worker)
    if ok:
        return True, ""
    combined = "\n".join(outputs)
    for line in combined.splitlines():
        if "Multiprocess computations" in line:
            return False, line.strip()
    tail = combined.strip().splitlines()[-1:] or ["no output"]
    return False, f"2-process CPU jax probe failed: {tail[0]}"


_MP_PROBE_RESULT = None


def _mp_probe_cached():
    global _MP_PROBE_RESULT
    if _MP_PROBE_RESULT is None:
        _MP_PROBE_RESULT = _probe_multiprocess_cpu_jax()
    return _MP_PROBE_RESULT


def pytest_runtest_setup(item):
    # Probe lazily at the FIRST gated item's setup (cached after), not
    # at collection: `pytest -k other_suite` or --collect-only still
    # collects these files and must not pay the 2-process jax probe
    # for tests that will never run.
    if os.path.basename(str(item.fspath)) not in _MULTIPROCESS_SUITES:
        return
    ok, reason = _mp_probe_cached()
    if not ok:
        pytest.skip(
            "multiprocess CPU jax unavailable in this container: "
            f"{reason}"
        )


@pytest.fixture()
def fresh_context():
    """Reset the global Context singleton around a test."""
    from dlrover_tpu.common.config import Context

    Context.reset()
    yield Context.singleton()
    Context.reset()


@pytest.fixture(autouse=True)
def _teardown_ckpt_saver_singleton():
    """The agent-hosted AsyncCheckpointSaver is a process singleton;
    a test that started one (agent run paths) must not pin its
    checkpoint dir for later tests' standalone Checkpointers."""
    yield
    from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver

    inst = AsyncCheckpointSaver._instance
    if inst is not None:
        try:
            inst.close()
        except Exception:  # noqa: BLE001
            pass
