"""Standalone brain service: RPC server + RemoteBrain client parity
with the in-process BrainService (VERDICT r2 weak #6 — brain as a
service, not only a library). The durable sqlite file is the
cross-job datastore; restarts keep the history."""

import subprocess
import sys
import time

import pytest

from dlrover_tpu.brain.server import BrainRpcServer, RemoteBrain
from dlrover_tpu.brain.service import (
    BrainResourceOptimizer,
    BrainService,
    JobMetricsRecord,
    RuntimeSample,
)


@pytest.fixture(autouse=True)
def _no_leaked_chaos():
    """A chaos injector left installed by an earlier module would
    fault THIS module's in-process RPC clients (the injector is
    process-global); clear it both ways."""
    from dlrover_tpu.common import chaos

    chaos.install_injector(None)
    yield
    chaos.install_injector(None)


@pytest.fixture()
def remote(tmp_path):
    server = BrainRpcServer(
        BrainService(str(tmp_path / "brain.db"))
    )
    server.start()
    client = RemoteBrain(f"127.0.0.1:{server.port}")
    yield client, server
    client.close()
    server.stop()


def _metrics(sig="gpt2", workers=4, tput=100.0, mem=8192):
    return JobMetricsRecord(
        job_name="j1",
        model_signature=sig,
        workers=workers,
        memory_mb=mem,
        chips_per_worker=4,
        throughput=tput,
        peak_memory_mb=mem // 2,
    )


class TestRemoteParity:
    def test_persist_and_optimize_match_local(self, remote, tmp_path):
        client, server = remote
        local = BrainService(":memory:")
        for brain in (client, local):
            brain.persist_metrics(_metrics(workers=4, tput=100.0))
            brain.persist_metrics(_metrics(workers=8, tput=190.0))
        want = local.optimize_job_resource("gpt2")
        got = client.optimize_job_resource("gpt2")
        assert got == want
        assert got is not None

    def test_runtime_samples_and_worker_count(self, remote):
        client, _ = remote
        for i in range(4):
            client.persist_runtime_sample(
                RuntimeSample(
                    job_name="j1",
                    node_type="worker",
                    node_id=i % 2,
                    used_cpu=2.0,
                    used_memory_mb=2048,
                    config_cpu=4.0,
                    config_memory_mb=4096,
                    speed=10.0,
                )
            )
        # No crash, algorithm responds (the wire contract is what's
        # under test; RemoteBrain mirrors BrainService METHOD names).
        grown = client.optimize_worker_oom("gpt2", 4096)
        assert grown >= 4096

    def test_fleet_and_health_persist_over_rpc(self, remote):
        """The health plane's channel (fleet samples + verdict
        transitions) round-trips through the standalone brain's RPC
        kinds exactly like local persistence."""
        client, server = remote
        client.persist_fleet_sample(
            job_name="j1",
            aggregates={"step_time_s": {"mean": 0.2}},
            goodput_ratio=0.8,
            health_score=0.9,
            timestamp=1000.0,
        )
        client.persist_health_verdict(
            job_name="j1",
            detector="throughput_degradation",
            severity="critical",
            node_id=3,
            message="host h1 2.5x baseline",
            action="profile",
            evidence="[[1000.0, 0.25]]",
            timestamp=1000.0,
        )
        samples = server.brain.recent_fleet_samples("j1")
        assert samples[0]["goodput_ratio"] == 0.8
        assert samples[0]["aggregates"]["step_time_s"]["mean"] == 0.2
        verdicts = server.brain.recent_health_verdicts("j1")
        assert verdicts[0]["detector"] == "throughput_degradation"
        assert verdicts[0]["node_id"] == 3
        assert verdicts[0]["action"] == "profile"

    def test_remediation_decisions_persist_over_rpc(self, remote):
        """The remediation engine's audit channel round-trips through
        the standalone brain's ``remediation`` persist kind."""
        client, server = remote
        client.persist_remediation_decision(
            job_name="j1",
            decision_id=7,
            detector="throughput_degradation",
            node_id=1,
            host="h1",
            action="cordon_replace",
            outcome="acted",
            dry_run=0,
            governors='{"hysteresis": "ok", "cooldown": "ok"}',
            message="host h1 2.5x baseline",
            timestamp=1000.0,
        )
        client.persist_remediation_decision(
            job_name="j1",
            decision_id=7,
            detector="throughput_degradation",
            node_id=1,
            host="h1",
            action="cordon_replace",
            outcome="recovered",
            dry_run=0,
            governors="{}",
            message="host h1 2.5x baseline",
            timestamp=1200.0,
        )
        rows = server.brain.recent_remediation_decisions("j1")
        assert [r["outcome"] for r in rows] == ["recovered", "acted"]
        assert rows[1]["governors"]["hysteresis"] == "ok"
        assert rows[0]["decision_id"] == 7
        assert rows[0]["host"] == "h1"
        assert not rows[0]["dry_run"]

    def test_unknown_algorithm_raises_remotely(self, remote):
        client, _ = remote
        with pytest.raises(RuntimeError, match="failed"):
            client._call("no_such_algorithm")

    def test_resource_optimizer_over_remote_brain(self, remote):
        client, _ = remote
        client.persist_metrics(_metrics(workers=4, tput=100.0))
        client.persist_metrics(_metrics(workers=8, tput=190.0))
        opt = BrainResourceOptimizer(
            client, "gpt2", min_workers=1, max_workers=16
        )
        target = opt.target_worker_count(4, speed_monitor=None)
        assert 1 <= target <= 16


class TestDurability:
    def test_history_survives_service_restart(self, tmp_path):
        db = str(tmp_path / "brain.db")
        s1 = BrainRpcServer(BrainService(db))
        s1.start()
        c1 = RemoteBrain(f"127.0.0.1:{s1.port}")
        c1.persist_metrics(_metrics(workers=4, tput=100.0))
        c1.persist_metrics(_metrics(workers=8, tput=190.0))
        before = c1.optimize_job_resource("gpt2")
        c1.close()
        s1.stop()

        s2 = BrainRpcServer(BrainService(db))
        s2.start()
        c2 = RemoteBrain(f"127.0.0.1:{s2.port}")
        try:
            assert c2.optimize_job_resource("gpt2") == before
        finally:
            c2.close()
            s2.stop()


class TestCli:
    def test_entrypoint_serves(self, tmp_path):
        # The child's environment is scrubbed of every DLROVER_TPU_*
        # knob: when the FULL suite runs, earlier modules leave
        # process-global env state (chaos gates, trace files, snapshot
        # cadences) that a subprocess inherits — the load-flakiness
        # this test used to show came from exactly that plus a tight
        # 20s startup deadline on a busy box.
        import os

        env = {
            k: v
            for k, v in os.environ.items()
            if not k.startswith("DLROVER_TPU_")
        }
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "dlrover_tpu.brain.main",
                "--db", str(tmp_path / "b.db"),
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
        )
        try:
            import select

            port = None
            # Generous deadline: under full-suite load a cold python
            # + grpc import can take far longer than in isolation,
            # and a slow start is not the failure this test hunts.
            deadline = time.time() + 60
            while time.time() < deadline and port is None:
                if proc.poll() is not None:
                    break  # died before printing the port
                # Non-blocking read: readline() on a silent-but-alive
                # child would hang past the deadline.
                ready, _, _ = select.select(
                    [proc.stdout], [], [], 0.2
                )
                if not ready:
                    continue
                line = proc.stdout.readline()
                if line.startswith("DLROVER_TPU_BRAIN_PORT="):
                    port = int(line.strip().split("=")[1])
            if port is None and proc.poll() is None:
                # Still alive but silent past the deadline: collect
                # its stderr for the failure message instead of
                # asserting blind. A child wedged enough to also
                # ignore SIGTERM gets SIGKILL — the failure we want
                # reported is the assert below, not TimeoutExpired
                # from this cleanup.
                proc.terminate()
                try:
                    proc.wait(10)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
            assert port, (
                "brain CLI never printed its port; stderr:\n"
                + (proc.stderr.read() or "")
            )
            client = RemoteBrain(f"127.0.0.1:{port}")
            try:
                client.persist_metrics(_metrics())
            finally:
                client.close()
        finally:
            if proc.poll() is None:
                proc.terminate()
            try:
                proc.wait(10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
