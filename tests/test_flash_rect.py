"""Rectangular flash attention: Tq != Tk, causal offsets, gradients.

The square kernel generalized with a static ``q_offset`` (global
position of q row 0 in key coordinates) and per-side padding —
chunked prefill, prefix-LM suffix rows, and cross-attention at exact
cost (ops/flash_attention.py flash_attention_rect).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_rect,
)


def _qkv(key, tq, tk, b=2, h=3, d=16):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (b, tq, h, d), jnp.float32),
        jax.random.normal(kk, (b, tk, h, d), jnp.float32),
        jax.random.normal(kv, (b, tk, h, d), jnp.float32),
    )


def _dense(q, k, v, causal, q_offset):
    b, tq, h, d = q.shape
    tk = k.shape[1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / (d**0.5)
    if causal:
        qp = q_offset + jnp.arange(tq)[:, None]
        kp = jnp.arange(tk)[None, :]
        s = jnp.where((kp <= qp)[None, None], s, -jnp.inf)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", w, v.astype(jnp.float32)
    ).astype(q.dtype)


@pytest.mark.parametrize(
    "tq,tk,causal,offset",
    [
        (24, 64, False, 0),     # cross-attention, short queries
        (64, 24, False, 0),     # cross-attention, long queries
        (24, 64, True, None),   # chunked-prefill convention (tail)
        (24, 64, True, 8),      # explicit mid offset
        (40, 40, True, 0),      # square via the rect path
        (17, 51, True, None),   # odd sizes -> both sides pad
        (64, 64, True, None),   # offset defaults to 0 at equal sizes
    ],
)
def test_rect_matches_dense(tq, tk, causal, offset):
    q, k, v = _qkv(jax.random.PRNGKey(0), tq, tk)
    got = flash_attention_rect(
        q, k, v, causal=causal, q_offset=offset, interpret=True
    )
    eff = (tk - tq) if offset is None else offset
    want = _dense(q, k, v, causal, eff)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4
    )


def test_rect_grads_match_dense():
    """dq AND dk AND dv through the rectangular fused backward."""
    q, k, v = _qkv(jax.random.PRNGKey(1), 24, 56, b=1, h=2, d=8)

    def f_kernel(q, k, v):
        return jnp.sum(
            flash_attention_rect(q, k, v, causal=True, interpret=True)
            ** 2
        )

    def f_dense(q, k, v):
        return jnp.sum(_dense(q, k, v, True, 56 - 24) ** 2)

    g1 = jax.grad(f_kernel, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=1e-3
        )


def test_rect_square_equals_flash_attention():
    """Tq == Tk with offset 0 reproduces the square kernel exactly
    (same blocks, same masks)."""
    q, k, v = _qkv(jax.random.PRNGKey(2), 64, 64)
    a = flash_attention_rect(
        q, k, v, causal=True, q_offset=0, interpret=True
    )
    b = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-6
    )


def test_rect_lse_matches_dense():
    q, k, v = _qkv(jax.random.PRNGKey(3), 16, 48)
    _, lse = flash_attention_rect(
        q, k, v, causal=True, interpret=True, return_lse=True
    )
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / (16**0.5)
    qp = (48 - 16) + jnp.arange(16)[:, None]
    kp = jnp.arange(48)[None, :]
    s = jnp.where((kp <= qp)[None, None], s, -jnp.inf)
    want = jax.scipy.special.logsumexp(s, axis=-1)
    np.testing.assert_allclose(
        np.asarray(lse), np.asarray(want), atol=2e-5, rtol=1e-5
    )


def test_rect_rejects_negative_causal_offset():
    q, k, v = _qkv(jax.random.PRNGKey(4), 64, 24)
    with pytest.raises(ValueError, match="q_offset"):
        flash_attention_rect(
            q, k, v, causal=True, interpret=True
        )  # default offset 24-64 < 0


def test_chunked_prefill_equals_full_causal():
    """Processing queries in chunks against the full key set (each
    chunk at its own offset) reproduces the one-shot causal result —
    the chunked-prefill contract."""
    t = 96
    q, k, v = _qkv(jax.random.PRNGKey(5), t, t)
    full = flash_attention(q, k, v, causal=True, interpret=True)
    chunks = []
    for start in (0, 32, 64):
        chunks.append(
            flash_attention_rect(
                q[:, start:start + 32], k[:, :start + 32],
                v[:, :start + 32], causal=True, q_offset=start,
                interpret=True,
            )
        )
    got = jnp.concatenate(chunks, axis=1)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(full), atol=2e-5, rtol=1e-4
    )


def test_rect_window_matches_dense():
    """Sliding band + q_offset: the rect kernel's band compares run
    in key coordinates (Mistral chunked prefill)."""
    q, k, v = _qkv(jax.random.PRNGKey(6), 16, 48)
    got = flash_attention_rect(
        q, k, v, causal=True, window=8, interpret=True
    )
    off = 48 - 16
    b, tq, h, d = q.shape
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / (d**0.5)
    qp = off + jnp.arange(tq)[:, None]
    kp = jnp.arange(48)[None, :]
    mask = (kp <= qp) & ((qp - kp) < 8)
    s = jnp.where(mask[None, None], s, -jnp.inf)
    want = jnp.einsum(
        "bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1),
        v.astype(jnp.float32),
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4
    )
