"""Self-healing control plane: verdict-driven remediation with
safety governors.

Covers the acceptance criteria of the remediation PR:

* every governor (hysteresis, shared decorrelated cooldown, blast
  radius, min-nodes floor, probation recovery/rollback/escalation,
  dry-run) unit-tested hermetically with fake clocks;
* verdict/cooldown/remediation state journals into master state
  snapshots — a warm restart neither re-fires a sticky verdict's
  action nor forgets an in-flight cordon;
* the hermetic acceptance drill against a REAL in-process master: an
  injected degrading host is cordoned and replaced via a ScalePlan,
  goodput recovers, the flapping control host draws ZERO scale
  actions, and every decision is queryable via RPC, ``obs_report``,
  brain rows, and ``dlrover_remediation_*`` metrics; with dry_run the
  same drill records decisions but mutates nothing.
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import RpcClient
from dlrover_tpu.common.constants import (
    EventAction,
    NodeStatus,
    NodeType,
)
from dlrover_tpu.master.job_manager import JobManager, Scaler
from dlrover_tpu.master.master import JobMaster
from dlrover_tpu.master.remediation import (
    ACTION_CORDON_REPLACE,
    ACTION_RESTART_TRAINING,
    ACTION_SHRINK,
    OUTCOME_ACTED,
    OUTCOME_BLOCKED,
    OUTCOME_DRY_RUN,
    OUTCOME_ESCALATED,
    OUTCOME_FAILED,
    OUTCOME_RECOVERED,
    OUTCOME_ROLLED_BACK,
    RemediationDecision,
    RemediationEngine,
    render_remediation,
)
from dlrover_tpu.obs.health import (
    SEVERITY_CRITICAL,
    HealthMonitor,
    HealthVerdict,
)
from dlrover_tpu.obs.timeseries import TimeSeriesStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class FakeHealth:
    """The engine's contract with the health plane: active verdicts +
    the shared (detector, host, node) action-stamp map."""

    def __init__(self):
        self.verdicts = []
        self.stamps = {}

    def active_verdicts(self):
        return list(self.verdicts)

    def action_stamp(self, key):
        return self.stamps.get(key)

    def stamp_action(self, key, ts):
        self.stamps[key] = ts


class FakeServicer:
    def __init__(self):
        self.pushed = []
        self.peer_restarts = []

    def push_action(self, node_id, action, dedupe_key=None):
        self.pushed.append((node_id, action))
        return True

    def restart_peers(self, exclude_id, dedupe_prefix=None):
        self.peer_restarts.append(exclude_id)


class FakeSpeedMonitor:
    def __init__(self):
        self.running = set()

    def add_running_node(self, node_id):
        self.running.add(node_id)

    def remove_running_node(self, node_id):
        self.running.discard(node_id)


def verdict(
    detector="throughput_degradation",
    host="h1",
    node_id=1,
    severity=SEVERITY_CRITICAL,
    baseline=0.1,
):
    return HealthVerdict(
        detector=detector,
        severity=severity,
        message=f"host {host} degraded",
        host=host,
        node_id=node_id,
        metrics={"baseline_mean_s": baseline},
    )


ENGINE_CONFIG = {
    "interval_s": 9999.0,
    "hysteresis_ticks": 2.0,
    "recovery_ticks": 2.0,
    "cooldown_s": 100.0,
    "cooldown_jitter": 0.0,  # deterministic governor tests
    "blast_window_s": 600.0,
    "blast_max_actions": 1.0,
    "probation_s": 300.0,
    "recover_ratio": 1.25,
}


def make_engine(clk, workers=3, min_nodes=1, **overrides):
    jm = JobManager(scaler=Scaler())
    speed = FakeSpeedMonitor()
    for i in range(workers):
        jm.register_node(node_id=i, addr=f"h{i}")
        speed.add_running_node(i)
    health = FakeHealth()
    servicer = FakeServicer()
    config = dict(ENGINE_CONFIG)
    config.update(overrides)
    engine = RemediationEngine(
        health=health,
        job_manager=jm,
        servicer=servicer,
        speed_monitor=speed,
        min_nodes=min_nodes,
        clock=clk,
        config=config,
    )
    return engine, health, servicer, jm


class TestGovernors:
    def setup_method(self):
        self.clk = FakeClock(1000.0)

    def test_hysteresis_requires_consecutive_sick_ticks(self):
        engine, health, servicer, jm = make_engine(self.clk)
        health.verdicts = [verdict()]
        assert engine.tick_once() == []  # tick 1: warming up
        out = engine.tick_once()  # tick 2: acts
        assert [(d.action, d.outcome) for d in out] == [
            (ACTION_CORDON_REPLACE, OUTCOME_ACTED)
        ]
        assert jm.get_node(1).cordoned

    def test_flapping_subject_is_damped(self):
        """A verdict that resolves between ticks resets the sick
        streak: six flapping ticks, ZERO actions."""
        engine, health, servicer, jm = make_engine(self.clk)
        for _ in range(3):
            health.verdicts = [verdict()]
            assert engine.tick_once() == []
            health.verdicts = []  # resolved — streak resets
            assert engine.tick_once() == []
        assert engine.decisions() == []
        assert not jm.get_node(1).cordoned
        assert servicer.pushed == []
        assert jm.scaler.executed_plans == []

    def test_cooldown_shared_with_health_action_stamps(self):
        """A PROFILE the health plane queued 50s ago for the same
        subject blocks remediation (one shared stamp map); past the
        cooldown the action proceeds."""
        engine, health, servicer, jm = make_engine(self.clk)
        v = verdict()
        health.stamps[v.key()] = 950.0  # the PR-8 capture path acted
        health.verdicts = [v]
        engine.tick_once()
        out = engine.tick_once()
        assert [d.outcome for d in out] == [OUTCOME_BLOCKED]
        assert "cooldown" in out[0].governors
        assert out[0].governors["cooldown"].startswith("blocked")
        assert not jm.get_node(1).cordoned
        # Only ONE blocked record while the situation is unchanged.
        assert engine.tick_once() == []
        # Past the cooldown: acts, and stamps the shared map back.
        self.clk.t = 1100.0
        out = engine.tick_once()
        assert [d.outcome for d in out] == [OUTCOME_ACTED]
        assert health.stamps[v.key()] == 1100.0

    def test_blast_radius_caps_actions_per_window(self):
        """Two hosts sick together: ONE action per window; the second
        is vetoed and acts only after the window slides. Probation is
        kept longer than the blast window so the first cordon is still
        in flight when the second host's turn comes."""
        engine, health, servicer, jm = make_engine(
            self.clk, probation_s=10000.0
        )
        health.verdicts = [
            verdict(host="h1", node_id=1),
            verdict(host="h2", node_id=2),
        ]
        engine.tick_once()
        out = engine.tick_once()
        outcomes = {d.node_id: d.outcome for d in out}
        assert sorted(outcomes.values()) == [
            OUTCOME_ACTED, OUTCOME_BLOCKED
        ]
        blocked = next(
            d for d in out if d.outcome == OUTCOME_BLOCKED
        )
        assert blocked.governors["blast_radius"].startswith("blocked")
        assert len(engine.cordoned_nodes()) == 1
        # Window slides -> the second host's turn (its own probation
        # subject is free, cooldown untouched).
        self.clk.t = 1000.0 + 601.0
        out = engine.tick_once()
        acted = [d for d in out if d.outcome == OUTCOME_ACTED]
        assert len(acted) == 1
        assert len(engine.cordoned_nodes()) == 2

    def test_min_nodes_floor_blocks_cordon_and_shrink(self):
        engine, health, servicer, jm = make_engine(
            self.clk, workers=3, min_nodes=3
        )
        health.verdicts = [verdict()]
        engine.tick_once()
        out = engine.tick_once()
        assert [d.outcome for d in out] == [OUTCOME_BLOCKED]
        assert out[0].governors["min_nodes"].startswith("blocked")
        assert not jm.get_node(1).cordoned
        assert jm.scaler.executed_plans == []

    def test_restart_action_and_probation_recovery(self):
        engine, health, servicer, jm = make_engine(self.clk)
        health.verdicts = [
            verdict(detector="recompile_storm", host="h2", node_id=2)
        ]
        engine.tick_once()
        (d,) = engine.tick_once()
        assert (d.action, d.outcome) == (
            ACTION_RESTART_TRAINING, OUTCOME_ACTED
        )
        assert servicer.pushed == [(2, "restart_training")]
        # Verdict resolves; after recovery_ticks healthy ticks the
        # probation finalizes as recovered.
        health.verdicts = []
        engine.tick_once()
        engine.tick_once()
        assert engine.decisions()[-1].outcome == OUTCOME_RECOVERED
        assert not engine.probation_failing()

    def test_probation_failure_escalates_restart_to_cordon(self):
        engine, health, servicer, jm = make_engine(self.clk)
        health.verdicts = [
            verdict(detector="rss_growth", host="h2", node_id=2)
        ]
        engine.tick_once()
        (d,) = engine.tick_once()
        assert d.action == ACTION_RESTART_TRAINING
        # Still sick at the probation deadline -> escalate.
        self.clk.t = 1000.0 + 301.0
        out = engine.tick_once()
        assert d.outcome == OUTCOME_ESCALATED
        # The escalated rung acts as cordon_replace once the blast
        # window slides past the original restart (cooldown already
        # passed; hysteresis is already satisfied).
        self.clk.t = 1000.0 + 601.0
        out = engine.tick_once()
        assert [(x.action, x.outcome) for x in out] == [
            (ACTION_CORDON_REPLACE, OUTCOME_ACTED)
        ]
        assert jm.get_node(2).cordoned

    def test_probation_failure_rolls_back_cordon_then_shrinks(self):
        """The reversibility contract: a cordon-replace that did not
        restore health is rolled back (un-cordon, replacement
        retired), and the next conviction — the host sick past budget
        — shrinks the world instead."""
        engine, health, servicer, jm = make_engine(self.clk, workers=4)
        health.verdicts = [verdict()]
        engine.tick_once()
        (d,) = engine.tick_once()
        assert d.action == ACTION_CORDON_REPLACE
        repl_id = d.replacement_id
        assert repl_id >= 0
        assert jm.get_node(repl_id).status == NodeStatus.PENDING
        # Probation deadline passes with the verdict still active.
        self.clk.t = 1000.0 + 301.0
        engine.tick_once()
        assert d.outcome == OUTCOME_ROLLED_BACK
        assert not jm.get_node(1).cordoned  # un-cordoned
        assert jm.get_node(repl_id).status == NodeStatus.DELETED
        assert (1, "restart_training") in servicer.pushed  # rejoins
        assert engine.cordoned_nodes() == []
        # Still sick past the cooldown: the ladder says SHRINK — the
        # node is retired with NO replacement.
        self.clk.t = 1000.0 + 700.0
        out = engine.tick_once()
        shrinks = [x for x in out if x.action == ACTION_SHRINK]
        assert [x.outcome for x in shrinks] == [OUTCOME_ACTED]
        assert jm.get_node(1).status == NodeStatus.DELETED
        plans = jm.scaler.executed_plans
        assert plans[-1].remove_nodes[0].id == 1
        assert plans[-1].launch_nodes == []

    def test_shrink_probation_failure_goes_alert_only(self):
        engine, health, servicer, jm = make_engine(self.clk, workers=4)
        health.verdicts = [verdict()]
        # Walk the ladder to shrink quickly.
        engine._ladder[("h1", 1)] = 2
        engine.tick_once()
        (d,) = engine.tick_once()
        assert d.action == ACTION_SHRINK
        self.clk.t = 1000.0 + 301.0
        engine.tick_once()
        assert d.outcome == OUTCOME_ESCALATED
        assert engine.probation_failing()  # still convicted, no help
        # Alert-only: no further decisions, ever.
        self.clk.t = 1000.0 + 2000.0
        assert engine.tick_once() == []

    def test_dry_run_records_but_mutates_nothing(self):
        engine, health, servicer, jm = make_engine(
            self.clk, dry_run=1.0
        )
        health.verdicts = [verdict()]
        engine.tick_once()
        out = engine.tick_once()
        assert [d.outcome for d in out] == [OUTCOME_DRY_RUN]
        assert out[0].dry_run
        # One record per episode, not one per tick.
        assert engine.tick_once() == []
        # Nothing mutated: no cordon, no plans, no pushes, no shared
        # cooldown stamp (the real capture path must stay free to
        # act), no probation.
        assert not jm.get_node(1).cordoned
        assert jm.scaler.executed_plans == []
        assert servicer.pushed == []
        assert health.stamps == {}
        assert engine.cordoned_nodes() == []
        assert not engine.probation_failing()

    def test_disabled_engine_never_acts(self):
        engine, health, servicer, jm = make_engine(
            self.clk, enabled=0.0
        )
        health.verdicts = [verdict()]
        for _ in range(4):
            assert engine.tick_once() == []
        assert servicer.pushed == []

    def test_unmapped_detectors_stay_alert_only(self):
        engine, health, servicer, jm = make_engine(self.clk)
        health.verdicts = [
            verdict(detector="goodput_slo", host="", node_id=-1),
            verdict(detector="heartbeat_gap", host="", node_id=2),
        ]
        for _ in range(4):
            assert engine.tick_once() == []
        assert servicer.pushed == []

    def test_decision_roundtrip_and_render(self):
        d = RemediationDecision(
            decision_id=3,
            detector="throughput_degradation",
            severity="critical",
            node_id=1,
            host="h1",
            action=ACTION_CORDON_REPLACE,
            trigger="host h1 degraded",
            governors={"hysteresis": "ok"},
            outcome=OUTCOME_ACTED,
            timestamp=12.5,
            probation_deadline=312.5,
        )
        assert RemediationDecision.from_dict(d.to_dict()) == d
        rendered = render_remediation(
            {
                "enabled": True,
                "dry_run": False,
                "cordoned": [1],
                "decisions": [d.to_dict()],
            }
        )
        assert "cordon_replace" in rendered
        assert "governors ok" in rendered

    def test_cooldown_jitter_is_stable_per_subject(self):
        """The decorrelating jitter is one deterministic draw per
        subject — NOT re-rolled every tick (the min of repeated
        uniforms walks to zero, collapsing the spread back into
        lockstep at ~cooldown_s)."""
        engine, health, servicer, jm = make_engine(
            self.clk, cooldown_jitter=1.0
        )
        k1 = ("throughput_degradation", "h1", 1)
        k2 = ("throughput_degradation", "h2", 2)
        c1 = engine._cooldown_for(k1)
        assert engine._cooldown_for(k1) == c1  # stable across ticks
        assert engine._cooldown_for(k2) != c1  # spread apart
        assert 100.0 <= c1 <= 200.0  # cooldown_s * (1 + jitter)

    def test_cordon_purges_speed_monitor_and_rollback_restores(self):
        """The benched host's frozen step-time EWMA must leave the
        straggler accounting at cordon time (a stale slow window
        would pin its verdict and guarantee a wrong rollback), and a
        rollback puts the host back into step accounting."""
        engine, health, servicer, jm = make_engine(self.clk)
        health.verdicts = [verdict()]
        engine.tick_once()
        (d,) = engine.tick_once()
        assert d.outcome == OUTCOME_ACTED
        assert 1 not in engine.speed_monitor.running
        # Peers re-rendezvous via the ONE shared broadcast helper.
        assert servicer.peer_restarts == [1]
        self.clk.t = 1000.0 + 301.0
        engine.tick_once()  # probation fails -> rollback
        assert d.outcome == OUTCOME_ROLLED_BACK
        assert 1 in engine.speed_monitor.running

    def test_failed_replacement_launch_still_owns_the_cordon(self):
        """A replacement launch that raises must NOT strand the node:
        the engine still records the cordon and runs probation, so
        the benched pod is eventually rolled back (or retired), never
        parked forever outside every bookkeeping structure."""
        engine, health, servicer, jm = make_engine(self.clk)

        def boom(node, reason=""):
            raise RuntimeError("pod create failed")

        jm.launch_replacement = boom
        health.verdicts = [verdict()]
        engine.tick_once()
        (d,) = engine.tick_once()
        assert d.outcome == OUTCOME_ACTED
        assert d.replacement_id == -1
        assert engine.cordoned_nodes() == [1]
        assert jm.get_node(1).cordoned
        # Probation still governs it: the failed replace rolls back.
        self.clk.t = 1000.0 + 301.0
        engine.tick_once()
        assert d.outcome == OUTCOME_ROLLED_BACK
        assert not jm.get_node(1).cordoned
        assert engine.cordoned_nodes() == []

    def test_missing_replacement_blocks_recovery(self):
        """The cordon purged the sick host's telemetry, so its
        verdict resolves and the shrunken fleet reads healthy — but
        with NO replacement alive, probation must fail and roll the
        cordon back (restoring capacity), never declare RECOVERED and
        retire the pod, leaving the job permanently a worker short."""
        engine, health, servicer, jm = make_engine(self.clk)
        jm.launch_replacement = lambda node, reason="": None
        health.verdicts = [verdict()]
        engine.tick_once()
        (d,) = engine.tick_once()
        assert d.replacement_id == -1
        health.verdicts = []  # resolved via the telemetry purge
        for _ in range(3):
            engine.tick_once()
        assert d.outcome != OUTCOME_RECOVERED
        self.clk.t = 1000.0 + 301.0
        engine.tick_once()
        assert d.outcome == OUTCOME_ROLLED_BACK
        assert not jm.get_node(1).cordoned
        assert jm.get_node(1).is_alive()  # NOT retired

    def test_pending_replacement_blocks_recovery(self):
        """An unschedulable replacement (stuck PENDING, never
        registers) must hold probation open — recovery only counts
        once the replacement is actually RUNNING."""
        engine, health, servicer, jm = make_engine(self.clk)
        health.verdicts = [verdict()]
        engine.tick_once()
        (d,) = engine.tick_once()
        repl_id = d.replacement_id
        assert jm.get_node(repl_id).status == NodeStatus.PENDING
        health.verdicts = []  # resolved via the telemetry purge
        for _ in range(3):
            engine.tick_once()
        assert d.outcome != OUTCOME_RECOVERED
        # The replacement's agent registers (-> RUNNING): now the
        # healthy ticks count.
        jm.register_node(node_id=repl_id, addr="h1b")
        engine.tick_once()
        engine.tick_once()
        assert d.outcome == OUTCOME_RECOVERED

    def test_failed_action_backs_off_on_shared_cooldown(self):
        """A persistently-failing action (cluster API down) must not
        re-fire — and re-record a decision + brain row + metric —
        every tick: the failure stamps the shared cooldown, the next
        tick records ONE blocked decision, then the episode dedupes
        until the cooldown passes."""
        engine, health, servicer, jm = make_engine(self.clk)

        def boom(node_id):
            raise RuntimeError("cluster api down")

        jm.retire_node = boom
        engine._ladder[("h1", 1)] = 2  # sick past budget -> shrink
        health.verdicts = [verdict()]
        engine.tick_once()
        (d,) = engine.tick_once()
        assert d.outcome == OUTCOME_FAILED
        assert health.stamps  # shared cooldown stamped
        out = engine.tick_once()
        assert [x.outcome for x in out] == [OUTCOME_BLOCKED]
        assert out[0].governors["cooldown"].startswith("blocked")
        assert engine.tick_once() == []  # episode deduped


class TestCordonIntegrity:
    """The cordon must survive the two paths that historically undid
    it: the node-death peer-restart broadcast (RESTART_TRAINING
    doubles as un-cordon on the agent) and a benched agent's own
    restart re-registering from scratch."""

    def _servicer(self):
        from dlrover_tpu.master.rendezvous import (
            ElasticRendezvous,
            NetworkCheckRendezvous,
        )
        from dlrover_tpu.master.servicer import MasterServicer
        from dlrover_tpu.master.task_manager import TaskManager

        jm = JobManager(scaler=Scaler())
        servicer = MasterServicer(
            job_manager=jm,
            task_manager=TaskManager(),
            elastic_rdzv=ElasticRendezvous(),
            check_rdzv=NetworkCheckRendezvous(),
        )
        for i in range(3):
            servicer._register_node(
                msg.NodeAddressRequest(node_id=i, node_ip=f"h{i}")
            )
        return servicer, jm

    def test_restart_peers_skips_cordoned_nodes(self):
        servicer, jm = self._servicer()
        jm.cordon_node(1, reason="test")
        servicer.restart_peers(0)
        restarted = {
            n for n in (0, 1, 2)
            if EventAction.RESTART_TRAINING.value
            in servicer.pending_actions(n)
        }
        assert restarted == {2}  # not the dead node, NOT the benched

    def test_reregistering_cordoned_node_reasserts_cordon(self):
        servicer, jm = self._servicer()
        jm.cordon_node(1, reason="test")
        for mgr in servicer.rdzv_managers.values():
            mgr.remove_alive_node(1, node_rank=1)
        servicer.speed_monitor.remove_running_node(1)
        # The benched agent crashes and its supervisor restarts it:
        # the fresh agent re-registers knowing nothing of the cordon.
        servicer._register_node(
            msg.NodeAddressRequest(node_id=1, node_ip="h1")
        )
        assert servicer.pending_actions(1) == [
            EventAction.CORDON.value
        ]
        for mgr in servicer.rdzv_managers.values():
            assert 1 not in mgr._alive_nodes
        assert jm.get_node(1).cordoned

    def test_terminal_reincarnation_keeps_cordon(self):
        """An agent gone past the heartbeat timeout goes TERMINAL;
        its re-register builds a fresh Node incarnation — which must
        keep the cordon (only the remediation engine un-cordons), or
        the benched host rejoins the world next to its replacement."""
        servicer, jm = self._servicer()
        jm.cordon_node(1, reason="test")
        # The engine's cordon flow already pulled it from rendezvous.
        for mgr in servicer.rdzv_managers.values():
            mgr.remove_alive_node(1, node_rank=1)
        jm.retire_node(1)  # terminal incarnation, cordon still owned
        servicer._register_node(
            msg.NodeAddressRequest(node_id=1, node_ip="h1")
        )
        node = jm.get_node(1)
        assert node.is_alive() and node.cordoned
        assert servicer.pending_actions(1) == [
            EventAction.CORDON.value
        ]
        for mgr in servicer.rdzv_managers.values():
            assert 1 not in mgr._alive_nodes

    def test_heartbeat_drops_restart_that_raced_the_cordon(self):
        """The peer broadcast snapshots the worker list before the
        remediation thread flips the cordon flag (TOCTOU): a stale
        RESTART_TRAINING already queued when the cordon lands must be
        dropped at delivery — the agent overloads it as un-cordon."""
        servicer, jm = self._servicer()
        servicer.push_action(
            1, EventAction.RESTART_TRAINING.value
        )  # broadcast won the race
        jm.cordon_node(1, reason="test")
        servicer.push_action(1, EventAction.CORDON.value)
        resp = servicer._heartbeat(msg.HeartbeatRequest(node_id=1))
        assert resp.action == EventAction.CORDON.value  # restart gone
        # The rollback's legitimate un-park clears the flag FIRST,
        # so its restart is delivered.
        jm.uncordon_node(1)
        servicer.push_action(1, EventAction.RESTART_TRAINING.value)
        resp = servicer._heartbeat(msg.HeartbeatRequest(node_id=1))
        assert resp.action == EventAction.RESTART_TRAINING.value

    def test_probation_survives_history_eviction(self):
        """A mass-degradation storm can push the acted decision out
        of the bounded history ring while its probation is open: the
        journal carries probations FULLY, so a warm restore must not
        drop one — stranding the cordoned node forever."""
        clk = FakeClock(1000.0)
        engine, health, servicer, jm = make_engine(clk, history=4.0)
        health.verdicts = [verdict()]
        engine.tick_once()
        (d,) = engine.tick_once()
        assert d.outcome == OUTCOME_ACTED
        # Storm: the ring (4) evicts the acted decision.
        for i in range(6):
            engine._record(
                RemediationDecision(
                    decision_id=100 + i, detector="x", severity="c",
                    node_id=9, host="hx", action=ACTION_SHRINK,
                    trigger="storm", outcome=OUTCOME_BLOCKED,
                )
            )
        assert d.decision_id not in {
            x.decision_id for x in engine.decisions()
        }
        snap = engine.to_snapshot()

        engine2, health2, servicer2, jm2 = make_engine(clk, history=4.0)
        engine2.restore_snapshot(snap)
        jm2.register_node(node_id=d.replacement_id, addr="h1b")
        assert engine2.cordoned_nodes() == [1]
        # The probation is live: it still finalizes (here: recovery
        # retires the benched pod; failure would roll it back).
        health2.verdicts = []
        engine2.tick_once()
        engine2.tick_once()
        assert not engine2.cordoned_nodes()

    def test_join_rendezvous_refused_while_cordoned(self):
        """A benched agent that raced its CORDON delivery into a
        rejoin must be refused — admitting it would form a world
        around a host about to park its trainer mid-collective."""
        servicer, jm = self._servicer()
        jm.cordon_node(1, reason="test")
        resp = servicer._join_rendezvous(
            msg.JoinRendezvousRequest(node_id=1, node_rank=1)
        )
        assert resp.round == -1
        assert EventAction.CORDON.value in servicer.pending_actions(1)

    def test_adopt_node_advances_id_allocator(self):
        """launch_replacement must never mint an id colliding with an
        in-flight auto-scaler node tracked via adopt_node."""
        from dlrover_tpu.common.node import Node

        jm = JobManager(scaler=Scaler())
        for i in range(2):
            jm.register_node(node_id=i, addr=f"h{i}")
        jm.adopt_node(Node(type=NodeType.WORKER, id=2, rank=2))
        repl = jm.launch_replacement(jm.get_node(1), reason="test")
        assert repl.id == 3

    def test_relaunch_keeps_cordon(self):
        """A benched host whose pod is preempted mid-probation is
        relaunched by the failure path — the fresh incarnation must
        come back benched, not rejoin next to its replacement."""
        jm = JobManager(scaler=Scaler())
        for i in range(3):
            jm.register_node(node_id=i, addr=f"h{i}")
        jm.cordon_node(1, reason="test")
        jm.handle_node_gone(1, reason="preempted")
        assert jm.get_node(1).cordoned

    def test_replacement_inherits_criticality(self):
        jm = JobManager(scaler=Scaler())
        for i in range(3):
            jm.register_node(node_id=i, addr=f"h{i}")
        node = jm.get_node(1)
        node.critical = True
        repl = jm.launch_replacement(node, reason="test")
        assert repl.critical

    def test_rollback_keeps_replacement_when_node_died(self):
        """Rolling back a cordon whose benched pod already DIED must
        keep the live replacement — it IS the capacity now; retiring
        it too would leave the world a worker short forever."""
        clk = FakeClock(1000.0)
        engine, health, servicer, jm = make_engine(clk, workers=4)
        health.verdicts = [verdict()]
        engine.tick_once()
        (d,) = engine.tick_once()
        repl_id = d.replacement_id
        assert repl_id >= 0
        # The benched pod dies during probation (budget exhausted —
        # it stays terminal).
        node = jm.get_node(1)
        node.relaunchable = False
        node.update_status(NodeStatus.FAILED)
        # Probation fails with the verdict still active.
        clk.t = 1000.0 + 301.0
        engine.tick_once()
        assert d.outcome == OUTCOME_ROLLED_BACK
        assert jm.get_node(repl_id).is_alive()  # replacement KEPT
        assert engine.cordoned_nodes() == []
        # No undeliverable un-park push to the dead node.
        assert (1, "restart_training") not in servicer.pushed

    def test_shrink_lowers_auto_scaler_target(self):
        """Elastic shrink must stick: the worker target drops with
        the world, or an auto-scaler pass would immediately launch a
        replacement and undo the shrink."""

        class FakeAutoScaler:
            target_workers = 3

        clk = FakeClock(1000.0)
        engine, health, servicer, jm = make_engine(clk, workers=4)
        engine.auto_scaler = FakeAutoScaler()
        engine._ladder[("h1", 1)] = 2  # sick past budget -> shrink
        health.verdicts = [verdict()]
        engine.tick_once()
        (d,) = engine.tick_once()
        assert d.action == ACTION_SHRINK
        assert engine.auto_scaler.target_workers == 2


class TestStateJournaling:
    """Satellite: active verdicts + cooldown stamps + remediation
    state survive a master warm restart — a sticky critical verdict
    must NOT re-fire its action after every bounce."""

    def _convict(self, monitor, store, clk):
        for i in range(40):
            t = 900.0 + i * 5
            v = 0.1 if t < 1000 else 0.1 * (1 + (t - 1000) / 30.0)
            store.record("host.step_time", v, ts=t, host="slow")
        clk.t = 1095.0
        return monitor.evaluate_once()

    def test_sticky_verdict_does_not_refire_action_after_restore(self):
        clk = FakeClock(1000.0)
        store = TimeSeriesStore(clock=clk)
        actions = []
        config = {
            "window_s": 60.0, "min_points": 3.0,
            "goodput_grace_s": 0.0, "action_cooldown_s": 600.0,
        }
        monitor = HealthMonitor(
            store, clock=clk, config=config,
            action_sink=lambda n, a: actions.append((n, a)),
            fleet=type(
                "F", (),
                {"node_for_host": staticmethod(lambda h: 3),
                 "aggregates": staticmethod(dict)},
            )(),
        )
        verdicts = self._convict(monitor, store, clk)
        assert [v.severity for v in verdicts] == [SEVERITY_CRITICAL]
        assert actions == [(3, "profile")]
        snap = monitor.to_snapshot()
        assert snap["active"] and snap["last_action"]

        # The replacement master restores and re-evaluates the SAME
        # still-sick fleet: the verdict is already active (no
        # transition), so no second action fires.
        actions2 = []
        monitor2 = HealthMonitor(
            store, clock=clk, config=config,
            action_sink=lambda n, a: actions2.append((n, a)),
            fleet=monitor.fleet,
        )
        monitor2.restore_snapshot(snap)
        assert [
            v.key() for v in monitor2.active_verdicts()
        ] == [v.key() for v in monitor.active_verdicts()]
        verdicts2 = monitor2.evaluate_once()
        assert [v.severity for v in verdicts2] == [SEVERITY_CRITICAL]
        assert actions2 == []  # the journaled state absorbed it
        # Even a severity re-transition respects the restored stamp.
        assert monitor2.action_stamp(verdicts2[0].key()) is not None

        # WITHOUT the journal (the old bug): the same evaluation
        # re-fires the action immediately.
        actions3 = []
        monitor3 = HealthMonitor(
            store, clock=clk, config=config,
            action_sink=lambda n, a: actions3.append((n, a)),
            fleet=monitor.fleet,
        )
        monitor3.evaluate_once()
        assert actions3 == [(3, "profile")]

    def test_remediation_snapshot_roundtrip(self):
        clk = FakeClock(1000.0)
        engine, health, servicer, jm = make_engine(clk)
        health.verdicts = [verdict()]
        engine.tick_once()
        (d,) = engine.tick_once()
        assert d.outcome == OUTCOME_ACTED
        snap = engine.to_snapshot()

        engine2, health2, servicer2, jm2 = make_engine(clk)
        engine2.restore_snapshot(snap)
        # In production the journaled node table restores alongside:
        # the replacement exists in the new master's JobManager too.
        jm2.register_node(node_id=d.replacement_id, addr="h1b")
        assert engine2.cordoned_nodes() == [1]
        restored = engine2.decisions()
        assert [x.decision_id for x in restored] == [d.decision_id]
        # The wall-clock deadline keeps its meaning, but is extended
        # when needed so the new master can re-earn recovery_ticks.
        assert restored[0].probation_deadline >= d.probation_deadline
        # The restored probation still finalizes (here: recovery).
        health2.verdicts = []
        engine2.tick_once()
        engine2.tick_once()
        assert engine2.decisions()[-1].outcome == OUTCOME_RECOVERED

    def test_restore_near_deadline_still_allows_recovery(self):
        """A master bounce that consumed most of the probation window
        must not force a rollback: restore zeroes healthy_ticks, so
        the deadline is extended to fit recovery_ticks fresh
        observations — a recovered fleet finalizes as RECOVERED, not
        rolled back at the stale deadline."""
        clk = FakeClock(1000.0)
        engine, health, servicer, jm = make_engine(
            clk, interval_s=15.0
        )
        health.verdicts = [verdict()]
        engine.tick_once()
        (d,) = engine.tick_once()
        assert d.probation_deadline == 1300.0
        snap = engine.to_snapshot()

        # Warm restart lands 10s before the original deadline; the
        # fleet genuinely recovered while the master was down.
        clk.t = 1290.0
        engine2, health2, servicer2, jm2 = make_engine(
            clk, interval_s=15.0
        )
        engine2.restore_snapshot(snap)
        jm2.register_node(node_id=d.replacement_id, addr="h1b")
        health2.verdicts = []
        engine2.tick_once()  # healthy tick 1 (t=1290)
        clk.t = 1305.0  # past the ORIGINAL deadline
        engine2.tick_once()  # healthy tick 2 -> recovered
        restored = engine2.decisions()[-1]
        assert restored.outcome == OUTCOME_RECOVERED
        assert not jm2.get_node(1).is_alive()  # retired, not rolled back

    def test_master_collect_state_carries_health_and_remediation(self):
        master = JobMaster(
            port=0, node_num=2, rdzv_timeout=1.0,
            collect_interval=999.0, health_interval=9999.0,
            remediation_config={"interval_s": 9999.0},
        )
        try:
            state = master._collect_state()
            assert "health" in state and "remediation" in state
            # Round-trips through JSON (the journal's on-disk form).
            json.loads(json.dumps(state))
            master.health.restore_snapshot(state["health"])
            master.remediation.restore_snapshot(state["remediation"])
        finally:
            master.stop()


@pytest.fixture()
def drill_master():
    scaler = Scaler()
    m = JobMaster(
        port=0, node_num=3, min_nodes=2, rdzv_timeout=1.0,
        metrics_port=0, collect_interval=999.0,
        health_interval=9999.0,
        remediation_config={
            "interval_s": 9999.0,
            "hysteresis_ticks": 2.0,
            "recovery_ticks": 2.0,
            "cooldown_s": 0.0,
            "blast_window_s": 600.0,
            "blast_max_actions": 1.0,
            "probation_s": 300.0,
        },
        scaler=scaler,
    )
    m.prepare()
    yield m, scaler
    m.stop()


def snapshot_msg(node_id, host, ts, step_time):
    return msg.MetricsSnapshotReport(
        node_id=node_id,
        host=host,
        timestamp=ts,
        registry={},
        resource={"tokens_per_s": 500.0},
        step_times=[step_time],
        events=[],
    )


class TestAcceptanceDrill:
    """The hermetic drill: a simulated fleet with one injected
    degrading host heals itself — cordoned, replaced via a ScalePlan,
    goodput recovered — while a flapping host is damped by hysteresis
    and never ping-pongs the world size."""

    def feed(self, client, host_steps, span=240.0, n=25):
        now = time.time()
        for i in range(n):
            ts = now - span + i * (span / n)
            for node_id, host, fn in host_steps:
                client.report(
                    snapshot_msg(node_id, host, ts, fn(ts, now))
                )

    @staticmethod
    def ramp(ts, now):
        return 0.1 * (1.0 + max(0.0, ts - (now - 120.0)) / 40.0)

    @staticmethod
    def flat(ts, now):
        return 0.1

    def register(self, master):
        client = RpcClient(master.addr)
        for node_id, host in ((0, "h0"), (1, "h1"), (2, "h2")):
            client.report(
                msg.NodeAddressRequest(node_id=node_id, node_ip=host)
            )
        return client

    def run_flap_rounds(self, master, client, rounds=3):
        """h1 stays sick every tick; h2 relapses but its history
        clears between engine ticks (the flap)."""
        for _ in range(rounds):
            master.health.evaluate_once()
            master.remediation.tick_once()
            master.timeseries.drop_label("host", "h2")
            master.health.evaluate_once()
            master.remediation.tick_once()
            self.feed(client, [(2, "h2", self.ramp)])

    def test_drill(self, drill_master, tmp_path):
        master, scaler = drill_master
        client = self.register(master)
        self.feed(client, [
            (0, "h0", self.flat),
            (1, "h1", self.ramp),
            (2, "h2", self.ramp),
        ])
        self.run_flap_rounds(master, client)

        # --- the degrading host was cordoned and replaced ---------
        decisions = master.remediation.decisions()
        acted = [
            d for d in decisions
            if d.action == ACTION_CORDON_REPLACE and d.node_id == 1
        ]
        assert len(acted) == 1, [
            (d.action, d.node_id, d.outcome) for d in decisions
        ]
        d = acted[0]
        assert d.governors == {
            "hysteresis": "ok", "cooldown": "ok",
            "blast_radius": "ok", "min_nodes": "ok",
            "pool_grant": "ok",  # single-job master: no grant cap
        }
        assert d.trigger  # the convicting verdict's message rides it
        repl_id = d.replacement_id
        assert repl_id >= 0
        launched = [
            n.id for p in scaler.executed_plans for n in p.launch_nodes
        ]
        assert launched == [repl_id]  # exactly ONE ScalePlan launch

        # --- the flapping host drew ZERO actions ------------------
        assert not any(x.node_id == 2 for x in decisions)
        assert not master.job_manager.get_node(2).cordoned

        # --- the cordoned agent is parked, peers re-rendezvous ----
        beats = []
        while True:
            a = client.report(msg.HeartbeatRequest(node_id=1)).action
            if a == "none":
                break
            beats.append(a)
        assert EventAction.CORDON.value in beats

        # --- replacement reports healthy -> probation recovery ----
        client.report(
            msg.NodeAddressRequest(node_id=repl_id, node_ip="h1b")
        )
        self.feed(client, [
            (0, "h0", self.flat),
            (2, "h2", self.flat),
            (repl_id, "h1b", self.flat),
        ], span=140.0, n=15)
        master.health.evaluate_once()
        for _ in range(3):
            master.remediation.tick_once()
        assert d.outcome == OUTCOME_RECOVERED
        assert not master.remediation.probation_failing()
        # cordon-then-replace completed: the sick pod is retired.
        assert (
            master.job_manager.get_node(1).status == NodeStatus.DELETED
        )
        # ...and the stale flag is cleared, so a future incarnation
        # of this node id would start un-benched.
        assert not master.job_manager.get_node(1).cordoned
        assert master.remediation.cordoned_nodes() == []
        # goodput recovered: no active critical verdicts, score back.
        assert master.health.critical_count() == 0
        assert master.health.health_score() == 1.0
        # the replacement world satisfies the elastic floor
        alive = [
            n for n in master.job_manager.list_nodes(NodeType.WORKER)
            if n.is_alive() and not n.cordoned
        ]
        assert len(alive) >= 2

        # --- queryable: RPC --------------------------------------
        from dlrover_tpu.agent.master_client import MasterClient

        mc = MasterClient(master.addr, node_id=0)
        resp = mc.query_remediation()
        assert resp.enabled and not resp.dry_run
        assert not resp.probation_failing
        wire = [
            x for x in resp.decisions
            if x.action == ACTION_CORDON_REPLACE
        ]
        assert wire and wire[-1].outcome == OUTCOME_RECOVERED
        assert wire[-1].governors["blast_radius"] == "ok"
        only_h1 = mc.query_remediation(node_id=1)
        assert {x.node_id for x in only_h1.decisions} == {1}

        # --- queryable: brain rows -------------------------------
        rows = master.brain.recent_remediation_decisions("default")
        assert {
            (r["action"], r["outcome"]) for r in rows
        } >= {
            (ACTION_CORDON_REPLACE, OUTCOME_ACTED),
            (ACTION_CORDON_REPLACE, OUTCOME_RECOVERED),
        }
        assert rows[0]["governors"]  # audit trail decoded

        # --- queryable: metrics ----------------------------------
        url = f"http://127.0.0.1:{master.metrics_server.port}"
        body = urllib.request.urlopen(
            f"{url}/metrics", timeout=5
        ).read().decode()
        assert (
            'dlrover_remediation_decisions_total{'
            'detector="throughput_degradation",'
            'action="cordon_replace",outcome="recovered"}'
        ) in body
        assert "dlrover_remediation_cordoned_nodes" in body

        # --- queryable: obs_report --health against the master ---
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "tools", "obs_report.py"),
                "--health", master.addr,
            ],
            capture_output=True, text=True, timeout=120, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "remediation" in proc.stdout
        assert "cordon_replace" in proc.stdout
        assert "recovered" in proc.stdout

    def test_dry_run_records_but_mutates_nothing(self, tmp_path):
        scaler = Scaler()
        master = JobMaster(
            port=0, node_num=3, min_nodes=2, rdzv_timeout=1.0,
            collect_interval=999.0, health_interval=9999.0,
            remediation_config={
                "interval_s": 9999.0,
                "hysteresis_ticks": 2.0,
                "cooldown_s": 0.0,
                "dry_run": 1.0,
            },
            scaler=scaler,
        )
        master.prepare()
        try:
            client = self.register(master)
            self.feed(client, [
                (0, "h0", self.flat),
                (1, "h1", self.ramp),
                (2, "h2", self.flat),
            ])
            for _ in range(3):
                master.health.evaluate_once()
                master.remediation.tick_once()
            decisions = master.remediation.decisions()
            assert [
                (d.action, d.outcome, d.dry_run) for d in decisions
            ] == [(ACTION_CORDON_REPLACE, OUTCOME_DRY_RUN, True)]
            # ...and NOTHING moved: no plans, no cordon, the node
            # table untouched, no probation, decision still persisted.
            assert scaler.executed_plans == []
            assert not master.job_manager.get_node(1).cordoned
            assert master.job_manager.get_node(1).status == (
                NodeStatus.RUNNING
            )
            assert master.remediation.cordoned_nodes() == []
            rows = master.brain.recent_remediation_decisions("default")
            assert rows and rows[0]["dry_run"]
            # the drained heartbeat FIFO carries no remediation
            # actions (PROFILE from the capture path is fine)
            seen = []
            while True:
                a = client.report(
                    msg.HeartbeatRequest(node_id=1)
                ).action
                if a == "none":
                    break
                seen.append(a)
            assert EventAction.CORDON.value not in seen
            assert EventAction.RESTART_TRAINING.value not in seen
        finally:
            master.stop()
