"""Reconcile e2e against a simulated apiserver (VERDICT r2 item 3).

The operator runtime + stdlib REST client are driven over REAL HTTP:
a mini apiserver (http.server) stores pods / elasticjobs / scaleplans
/ leases as JSON and speaks the list/create/merge-patch/delete verbs
with label selectors. Watch requests return 400, exercising the
documented fallback to list-based resync. Nothing is mocked inside the
client — the HTTP wire is the seam.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from dlrover_tpu.operator.k8s_client import (
    ApiError,
    K8sApi,
    LeaderElector,
)
from dlrover_tpu.operator.runtime import OperatorRuntime

GROUP = "elastic.iml.github.io"
NS = "default"


class MiniApiServer:
    """Enough of the k8s REST API for the operator: namespaced
    collections with LIST (labelSelector), GET, POST, merge-PATCH,
    PUT (resourceVersion compare-and-swap), DELETE. Real-server
    fidelity where it bites: elasticjobs have a status SUBRESOURCE
    (root patches silently drop .status, /status patches only apply
    it — matching deploy/crd-elasticjob.yaml), and every write bumps
    metadata.resourceVersion. Watch -> 400 (resync fallback path)."""

    def __init__(self):
        # path prefix -> {name: object}
        self.store = {
            "pods": {},
            "elasticjobs": {},
            "scaleplans": {},
            "leases": {},
        }
        self._lock = threading.Lock()
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code, body=None):
                data = json.dumps(body or {}).encode()
                self.send_response(code)
                self.send_header(
                    "Content-Type", "application/json"
                )
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _route(self):
                """-> (collection, name) or (None, None)."""
                parts = urlparse(self.path).path.strip("/").split("/")
                # /api/v1/namespaces/{ns}/pods[/{name}]
                # /apis/{g}/{v}/namespaces/{ns}/{plural}[/{name}]
                if parts[:2] == ["api", "v1"]:
                    rest = parts[2:]
                else:
                    rest = parts[3:]
                if len(rest) >= 3 and rest[0] == "namespaces":
                    plural = rest[2]
                    name = rest[3] if len(rest) > 3 else None
                    sub = rest[4] if len(rest) > 4 else None
                    return plural, name, sub
                return None, None, None

            def _read_body(self):
                n = int(self.headers.get("Content-Length", 0))
                return (
                    json.loads(self.rfile.read(n)) if n else {}
                )

            def do_GET(self):
                q = parse_qs(urlparse(self.path).query)
                if q.get("watch") == ["true"]:
                    return self._send(
                        400, {"message": "watch not supported"}
                    )
                plural, name, _ = self._route()
                if plural not in server.store:
                    return self._send(404, {"message": "no route"})
                with server._lock:
                    if name:
                        obj = server.store[plural].get(name)
                        if obj is None:
                            return self._send(
                                404, {"message": f"{name} not found"}
                            )
                        return self._send(200, obj)
                    items = list(server.store[plural].values())
                sel = q.get("labelSelector", [""])[0]
                if sel:
                    key, _, val = sel.partition("=")
                    items = [
                        o
                        for o in items
                        if o.get("metadata", {})
                        .get("labels", {})
                        .get(key)
                        == val
                    ]
                return self._send(200, {"items": items})

            def do_POST(self):
                plural, _, _ = self._route()
                if plural not in server.store:
                    return self._send(404, {"message": "no route"})
                body = self._read_body()
                name = body.get("metadata", {}).get("name", "")
                with server._lock:
                    if name in server.store[plural]:
                        return self._send(
                            409, {"message": "already exists"}
                        )
                    if plural == "pods":
                        body.setdefault("status", {})[
                            "phase"
                        ] = "Running"
                    body.setdefault("metadata", {})[
                        "resourceVersion"
                    ] = "1"
                    server.store[plural][name] = body
                return self._send(201, body)

            @staticmethod
            def _bump(obj):
                meta = obj.setdefault("metadata", {})
                meta["resourceVersion"] = str(
                    int(meta.get("resourceVersion", "0")) + 1
                )

            def do_PATCH(self):
                plural, name, sub = self._route()
                if plural not in server.store or not name:
                    return self._send(404, {"message": "no route"})
                patch = self._read_body()
                # Status-subresource semantics (elasticjobs enable it
                # in deploy/crd-elasticjob.yaml): a root patch DROPS
                # .status; only /status applies it — and applies
                # nothing else.
                if plural == "elasticjobs":
                    if sub == "status":
                        patch = {"status": patch.get("status", {})}
                    else:
                        patch = {
                            k: v
                            for k, v in patch.items()
                            if k != "status"
                        }
                elif sub == "status":
                    patch = {"status": patch.get("status", {})}

                def merge(dst, src):
                    for k, v in src.items():
                        if isinstance(v, dict) and isinstance(
                            dst.get(k), dict
                        ):
                            merge(dst[k], v)
                        elif v is None:
                            dst.pop(k, None)
                        else:
                            dst[k] = v

                with server._lock:
                    obj = server.store[plural].get(name)
                    if obj is None:
                        return self._send(
                            404, {"message": f"{name} not found"}
                        )
                    merge(obj, patch)
                    self._bump(obj)
                return self._send(200, obj)

            def do_PUT(self):
                plural, name, _ = self._route()
                if plural not in server.store or not name:
                    return self._send(404, {"message": "no route"})
                body = self._read_body()
                rv = body.get("metadata", {}).get("resourceVersion")
                with server._lock:
                    obj = server.store[plural].get(name)
                    if obj is None:
                        return self._send(
                            404, {"message": f"{name} not found"}
                        )
                    cur = obj.get("metadata", {}).get(
                        "resourceVersion"
                    )
                    if rv is not None and rv != cur:
                        return self._send(
                            409,
                            {
                                "message": "the object has been "
                                "modified (resourceVersion "
                                f"{rv} != {cur})"
                            },
                        )
                    body.setdefault("metadata", {})[
                        "resourceVersion"
                    ] = cur
                    self._bump(body)
                    server.store[plural][name] = body
                return self._send(200, body)

            def do_DELETE(self):
                plural, name, _ = self._route()
                with server._lock:
                    gone = server.store.get(plural, {}).pop(
                        name, None
                    )
                if gone is None:
                    return self._send(
                        404, {"message": f"{name} not found"}
                    )
                return self._send(200, gone)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self.thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.httpd.server_port}"

    def set_pod_phase(self, name: str, phase: str) -> None:
        with self._lock:
            self.store["pods"][name]["status"]["phase"] = phase

    def close(self):
        self.httpd.shutdown()


@pytest.fixture()
def apiserver():
    s = MiniApiServer()
    yield s
    s.close()


def _job_cr(name="train1", replicas=2, master_restart_limit=2):
    return {
        "apiVersion": f"{GROUP}/v1alpha1",
        "kind": "ElasticJob",
        "metadata": {"name": name, "namespace": NS},
        "spec": {
            "replicaSpecs": {
                "worker": {"replicas": replicas, "restartCount": 3}
            }
        },
    }


def _jobs_path(name=""):
    base = f"/apis/{GROUP}/v1alpha1/namespaces/{NS}/elasticjobs"
    return f"{base}/{name}" if name else base


class TestReconcileE2E:
    def test_job_creates_master_pod_and_status(self, apiserver):
        api = K8sApi(apiserver.url)
        api.create(_jobs_path(), _job_cr())
        rt = OperatorRuntime(api, NS, resync_seconds=0.2)
        rt.resync_once()
        # Master pod exists with the job label, via real HTTP.
        pods = api.get(
            f"/api/v1/namespaces/{NS}/pods",
            params={"labelSelector": "dlrover-job=train1"},
        )["items"]
        assert [
            p["metadata"]["name"] for p in pods
        ] == ["train1-master"]
        # CR status written back.
        status = api.get(_jobs_path("train1")).get("status", {})
        assert status.get("phase") == "Running"

    def test_master_failure_restart_then_job_failed(self, apiserver):
        api = K8sApi(apiserver.url)
        api.create(
            _jobs_path(), _job_cr(name="flaky")
        )
        rt = OperatorRuntime(api, NS, resync_seconds=0.2)
        rt.resync_once()
        for i in range(3):  # limit is 2 restarts
            apiserver.set_pod_phase("flaky-master", "Failed")
            rt.resync_once()
            rt.resync_once()  # recreate happens on the next pass
        status = api.get(_jobs_path("flaky")).get("status", {})
        assert status.get("phase") == "Failed"
        assert status.get("masterRestarts", 0) >= 3

    def test_scaleplan_executed_and_job_deletion_cleans_up(
        self, apiserver
    ):
        api = K8sApi(apiserver.url)
        api.create(_jobs_path(), _job_cr(name="scaled"))
        rt = OperatorRuntime(api, NS, resync_seconds=0.2)
        rt.resync_once()
        api.create(
            f"/apis/{GROUP}/v1alpha1/namespaces/{NS}/scaleplans",
            {
                "apiVersion": f"{GROUP}/v1alpha1",
                "kind": "ScalePlan",
                "metadata": {"name": "scaled-plan-1"},
                "spec": {
                    "ownerJob": "scaled",
                    "createPods": [
                        {
                            "name": "scaled-worker-0",
                            "id": 0,
                            "type": "worker",
                            "resource": {
                                "cpu": "4",
                                "memory": "8192Mi",
                            },
                        }
                    ],
                },
            },
        )
        rt.resync_once()
        names = {
            p["metadata"]["name"]
            for p in api.get(
                f"/api/v1/namespaces/{NS}/pods"
            )["items"]
        }
        assert names == {"scaled-master", "scaled-worker-0"}
        # Deleting the CR tears the pods down on the next resync.
        api.delete(_jobs_path("scaled"))
        rt.resync_once()
        assert (
            api.get(f"/api/v1/namespaces/{NS}/pods")["items"] == []
        )

    def test_run_loop_with_watch_fallback(self, apiserver):
        """The full entrypoint loop against an apiserver with no
        watch support: the 400 falls back to resync, which reconciles
        a job created after startup."""
        api = K8sApi(apiserver.url)
        rt = OperatorRuntime(api, NS, resync_seconds=0.2)
        t = threading.Thread(target=rt.run, daemon=True)
        t.start()
        try:
            api.create(_jobs_path(), _job_cr(name="late"))
            deadline = time.time() + 10
            while time.time() < deadline:
                pods = api.get(
                    f"/api/v1/namespaces/{NS}/pods",
                    params={"labelSelector": "dlrover-job=late"},
                )["items"]
                if pods:
                    break
                time.sleep(0.1)
            assert pods, "run loop never reconciled the late job"
        finally:
            rt.stop()
            t.join(timeout=5)


class TestLeaderElection:
    def test_single_holder_and_takeover_after_expiry(self, apiserver):
        api = K8sApi(apiserver.url)
        # 3 s lease: the pre-expiry asserts must all land inside the
        # lease window even when a loaded CI box stalls this thread
        # for a second or two between calls.
        a = LeaderElector(api, NS, identity="a", lease_seconds=3)
        b = LeaderElector(api, NS, identity="b", lease_seconds=3)
        assert a.try_acquire()
        assert not b.try_acquire()  # a holds a fresh lease
        assert a.try_acquire()  # renewal succeeds
        time.sleep(3.6)  # lease expires un-renewed
        assert b.try_acquire()  # b takes over
        assert not a.try_acquire()  # and now a must stand by

    def test_expired_lease_race_has_single_winner(self, apiserver):
        """Two electors that both observed the same expired lease:
        the PUT carries the read resourceVersion, so exactly one CAS
        write wins and the loser returns False."""
        api = K8sApi(apiserver.url)
        a = LeaderElector(api, NS, identity="a", lease_seconds=1)
        b = LeaderElector(api, NS, identity="b", lease_seconds=1)
        assert a.try_acquire()
        time.sleep(1.5)  # expired for both observers
        results = {}
        barrier = threading.Barrier(2)

        def race(elector, key):
            barrier.wait()
            results[key] = elector.try_acquire()

        ta = threading.Thread(target=race, args=(a, "a"))
        tb = threading.Thread(target=race, args=(b, "b"))
        ta.start(); tb.start(); ta.join(); tb.join()
        assert sorted(results.values()) == [False, True], results


class TestClientErrors:
    def test_api_error_carries_status(self, apiserver):
        api = K8sApi(apiserver.url)
        with pytest.raises(ApiError) as err:
            api.get(_jobs_path("missing"))
        assert err.value.status == 404
