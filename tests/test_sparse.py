"""KvVariable C++ store: gather/insert, optimizers, eviction, ckpt, JAX."""

import numpy as np
import pytest

from dlrover_tpu.sparse import KvVariable, SparseOptimizer, embedding_lookup


def test_gather_inserts_deterministic_init():
    kv = KvVariable("emb", embedding_dim=8, seed=42)
    keys = np.array([1, 2, 3], np.int64)
    v1 = kv.gather(keys)
    assert v1.shape == (3, 8)
    assert len(kv) == 3
    # same key later -> same row; distinct keys -> distinct rows
    v2 = kv.gather(np.array([1], np.int64))
    np.testing.assert_array_equal(v1[0], v2[0])
    assert not np.array_equal(v1[0], v1[1])
    # deterministic across stores with same seed
    kv2 = KvVariable("emb2", embedding_dim=8, seed=42)
    np.testing.assert_array_equal(kv2.gather(keys), v1)


def test_gather_or_zeros_inference_mode():
    kv = KvVariable("emb", embedding_dim=4)
    kv.gather(np.array([7], np.int64))  # insert 7
    out = kv.gather(np.array([7, 8], np.int64), train=False)
    assert not np.array_equal(out[0], np.zeros(4))
    np.testing.assert_array_equal(out[1], np.zeros(4))
    assert len(kv) == 1  # 8 was NOT inserted


def test_assign_and_gather_roundtrip():
    kv = KvVariable("emb", embedding_dim=4)
    keys = np.array([10, 20], np.int64)
    vals = np.arange(8, dtype=np.float32).reshape(2, 4)
    kv.assign(keys, vals)
    np.testing.assert_array_equal(kv.gather(keys), vals)


def test_sparse_adam_matches_dense_adam():
    """Fused C++ sparse adam == optax dense adam on the same rows."""
    import jax.numpy as jnp
    import optax

    dim = 8
    kv = KvVariable("emb", embedding_dim=dim, seed=1)
    keys = np.array([5, 9], np.int64)
    init_vals = kv.gather(keys).copy()
    grads = np.random.default_rng(0).normal(size=(2, dim)).astype(
        np.float32
    )

    opt = optax.adam(1e-2, eps=1e-8)
    dense = jnp.asarray(init_vals)
    state = opt.init(dense)
    for step in range(1, 4):
        kv.apply_gradients("adam", keys, grads, step=step, lr=1e-2)
        updates, state = opt.update(jnp.asarray(grads), state, dense)
        dense = optax.apply_updates(dense, updates)
    np.testing.assert_allclose(
        kv.gather(keys, train=False), np.asarray(dense),
        atol=1e-5, rtol=1e-4,
    )


def test_sparse_adagrad_and_momentum_converge():
    dim = 4
    target = np.ones((1, dim), np.float32)
    for name in ("adagrad", "momentum"):
        kv = KvVariable(name, embedding_dim=dim, seed=3)
        keys = np.array([0], np.int64)
        for step in range(1, 200):
            vals = kv.gather(keys)
            grad = vals - target  # d/dv of 0.5||v - target||^2
            kv.apply_gradients(name, keys, grad, step=step, lr=0.1)
        final = kv.gather(keys, train=False)
        assert np.abs(final - target).max() < 0.05, name


def test_sparse_ftrl_l1_produces_zeros():
    dim = 4
    kv = KvVariable("ftrl", embedding_dim=dim, seed=4)
    keys = np.array([0], np.int64)
    rng = np.random.default_rng(0)
    for step in range(1, 50):
        # tiny noisy gradients + strong l1 -> weights pinned at 0
        grad = rng.normal(scale=0.01, size=(1, dim)).astype(np.float32)
        kv.apply_gradients(
            "ftrl", keys, grad, step=step, lr=0.1, l1=1.0
        )
    np.testing.assert_array_equal(
        kv.gather(keys, train=False), np.zeros((1, dim), np.float32)
    )


def test_duplicate_keys_grads_combined():
    dim = 2
    kv = KvVariable("dup", embedding_dim=dim, seed=5)
    keys = np.array([1, 1], np.int64)
    before = kv.gather(np.array([1], np.int64)).copy()
    g = np.ones((2, dim), np.float32)
    kv.apply_gradients("momentum", keys, g, step=1, lr=0.1, momentum=0.0)
    after = kv.gather(np.array([1], np.int64), train=False)
    # grads summed: p -= lr * (1 + 1)
    np.testing.assert_allclose(before - after, 0.2 * np.ones((1, dim)),
                               atol=1e-6)


def test_eviction_by_frequency_and_version():
    kv = KvVariable("ev", embedding_dim=4)
    hot = np.array([1], np.int64)
    cold = np.array([2], np.int64)
    for _ in range(10):
        kv.gather(hot)
    kv.gather(cold)
    assert len(kv) == 2
    removed = kv.evict(min_frequency=5)
    assert removed == 1 and len(kv) == 1
    np.testing.assert_array_equal(
        kv.gather(cold, train=False), np.zeros((1, 4))
    )
    # version-based: key 3 updated at step 10 survives an evict of
    # anything older than step 5; key 1 (version 0) goes
    kv.assign(np.array([3], np.int64), np.zeros((1, 4), np.float32),
              step=10)
    assert kv.evict(min_version=5) == 1
    assert len(kv) == 1


def test_export_import_roundtrip_with_slots():
    kv = KvVariable("ck", embedding_dim=4, seed=6)
    keys = np.array([1, 2, 3], np.int64)
    grads = np.ones((3, 4), np.float32)
    kv.gather(keys)
    kv.apply_gradients("adam", keys, grads, step=1)
    state = kv.state_dict()
    assert set(state["slots"]) == {"m", "v"}

    kv2 = KvVariable("ck2", embedding_dim=4, seed=999)
    kv2.load_state_dict(state)
    np.testing.assert_allclose(
        kv2.gather(keys, train=False), kv.gather(keys, train=False)
    )
    # continued training matches (slots restored)
    kv.apply_gradients("adam", keys, grads, step=2)
    kv2.apply_gradients("adam", keys, grads, step=2)
    np.testing.assert_allclose(
        kv2.gather(keys, train=False),
        kv.gather(keys, train=False),
        atol=1e-6,
    )


def test_delta_export():
    kv = KvVariable("delta", embedding_dim=4)
    kv.assign(np.array([1], np.int64), np.ones((1, 4), np.float32),
              step=1)
    kv.assign(np.array([2], np.int64), np.ones((1, 4), np.float32),
              step=5)
    keys, _, _, versions = kv.export(since_version=3)
    assert list(keys) == [2]


def test_jax_embedding_lookup_and_training():
    """End-to-end: lookup inside jit, grads out, sparse apply, loss
    drops — the CTR training loop shape."""
    import jax
    import jax.numpy as jnp

    dim = 8
    kv = KvVariable("ctr", embedding_dim=dim, seed=7)
    w = jnp.ones((dim,), jnp.float32) * 0.1
    keys = np.array([3, 11, 42, 3], np.int64)
    labels = jnp.asarray([1.0, 0.0, 1.0, 1.0])

    def loss_fn(emb_vals, w):
        logits = emb_vals @ w
        return jnp.mean(
            jnp.maximum(logits, 0) - logits * labels
            + jnp.log1p(jnp.exp(-jnp.abs(logits)))
        )

    @jax.jit
    def forward_grads(w, keys):
        vals = embedding_lookup(kv, keys)
        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            vals, w
        )
        return loss, grads[0], grads[1]

    losses = []
    for step in range(1, 60):
        loss, g_emb, g_w = forward_grads(w, jnp.asarray(keys))
        kv.apply_gradients(
            "adagrad", keys, np.asarray(g_emb), step=step, lr=0.5
        )
        w = w - 0.5 * g_w
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5
    assert len(kv) == 3  # unique keys inserted


def test_sparse_ftrl_lr_power_convention():
    """TF/tfplus convention: lr_power <= 0 (default -0.5, so the
    effective per-coordinate lr SHRINKS as the accumulator grows);
    positive values are rejected like the reference's kernel
    validation (tfplus training_ops.cc)."""
    import numpy as np

    from dlrover_tpu.sparse.kv_variable import KvVariable

    dim = 4
    kv = KvVariable("ftrl_power", embedding_dim=dim, seed=11)
    keys = np.array([1], np.int64)
    kv.gather(keys)  # materialize the row
    with pytest.raises(ValueError):
        kv.apply_gradients(
            "ftrl", keys, np.ones((1, dim), np.float32), step=1,
            lr=0.1, lr_power=0.5,
        )
    # With the default (-0.5) the update magnitude must shrink across
    # repeated identical gradients (1/sqrt(accum) schedule).
    before = kv.gather(keys).copy()
    kv.apply_gradients("ftrl", keys, np.ones((1, dim), np.float32),
                       step=1, lr=0.1)
    first_delta = np.abs(kv.gather(keys) - before).mean()
    for s in range(2, 31):
        kv.apply_gradients("ftrl", keys, np.ones((1, dim), np.float32),
                           step=s, lr=0.1)
    prev = kv.gather(keys).copy()
    kv.apply_gradients("ftrl", keys, np.ones((1, dim), np.float32),
                       step=31, lr=0.1)
    late_delta = np.abs(kv.gather(keys) - prev).mean()
    assert late_delta < first_delta
