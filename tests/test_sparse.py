"""KvVariable C++ store: gather/insert, optimizers, eviction, ckpt, JAX."""

import numpy as np
import pytest

from dlrover_tpu.sparse import KvVariable, SparseOptimizer, embedding_lookup


def test_gather_inserts_deterministic_init():
    kv = KvVariable("emb", embedding_dim=8, seed=42)
    keys = np.array([1, 2, 3], np.int64)
    v1 = kv.gather(keys)
    assert v1.shape == (3, 8)
    assert len(kv) == 3
    # same key later -> same row; distinct keys -> distinct rows
    v2 = kv.gather(np.array([1], np.int64))
    np.testing.assert_array_equal(v1[0], v2[0])
    assert not np.array_equal(v1[0], v1[1])
    # deterministic across stores with same seed
    kv2 = KvVariable("emb2", embedding_dim=8, seed=42)
    np.testing.assert_array_equal(kv2.gather(keys), v1)


def test_gather_or_zeros_inference_mode():
    kv = KvVariable("emb", embedding_dim=4)
    kv.gather(np.array([7], np.int64))  # insert 7
    out = kv.gather(np.array([7, 8], np.int64), train=False)
    assert not np.array_equal(out[0], np.zeros(4))
    np.testing.assert_array_equal(out[1], np.zeros(4))
    assert len(kv) == 1  # 8 was NOT inserted


def test_assign_and_gather_roundtrip():
    kv = KvVariable("emb", embedding_dim=4)
    keys = np.array([10, 20], np.int64)
    vals = np.arange(8, dtype=np.float32).reshape(2, 4)
    kv.assign(keys, vals)
    np.testing.assert_array_equal(kv.gather(keys), vals)


def test_sparse_adam_matches_dense_adam():
    """Fused C++ sparse adam == optax dense adam on the same rows."""
    import jax.numpy as jnp
    import optax

    dim = 8
    kv = KvVariable("emb", embedding_dim=dim, seed=1)
    keys = np.array([5, 9], np.int64)
    init_vals = kv.gather(keys).copy()
    grads = np.random.default_rng(0).normal(size=(2, dim)).astype(
        np.float32
    )

    opt = optax.adam(1e-2, eps=1e-8)
    dense = jnp.asarray(init_vals)
    state = opt.init(dense)
    for step in range(1, 4):
        kv.apply_gradients("adam", keys, grads, step=step, lr=1e-2)
        updates, state = opt.update(jnp.asarray(grads), state, dense)
        dense = optax.apply_updates(dense, updates)
    np.testing.assert_allclose(
        kv.gather(keys, train=False), np.asarray(dense),
        atol=1e-5, rtol=1e-4,
    )


def test_sparse_adagrad_and_momentum_converge():
    dim = 4
    target = np.ones((1, dim), np.float32)
    for name in ("adagrad", "momentum"):
        kv = KvVariable(name, embedding_dim=dim, seed=3)
        keys = np.array([0], np.int64)
        for step in range(1, 200):
            vals = kv.gather(keys)
            grad = vals - target  # d/dv of 0.5||v - target||^2
            kv.apply_gradients(name, keys, grad, step=step, lr=0.1)
        final = kv.gather(keys, train=False)
        assert np.abs(final - target).max() < 0.05, name


def test_sparse_ftrl_l1_produces_zeros():
    dim = 4
    kv = KvVariable("ftrl", embedding_dim=dim, seed=4)
    keys = np.array([0], np.int64)
    rng = np.random.default_rng(0)
    for step in range(1, 50):
        # tiny noisy gradients + strong l1 -> weights pinned at 0
        grad = rng.normal(scale=0.01, size=(1, dim)).astype(np.float32)
        kv.apply_gradients(
            "ftrl", keys, grad, step=step, lr=0.1, l1=1.0
        )
    np.testing.assert_array_equal(
        kv.gather(keys, train=False), np.zeros((1, dim), np.float32)
    )


def test_duplicate_keys_grads_combined():
    dim = 2
    kv = KvVariable("dup", embedding_dim=dim, seed=5)
    keys = np.array([1, 1], np.int64)
    before = kv.gather(np.array([1], np.int64)).copy()
    g = np.ones((2, dim), np.float32)
    kv.apply_gradients("momentum", keys, g, step=1, lr=0.1, momentum=0.0)
    after = kv.gather(np.array([1], np.int64), train=False)
    # grads summed: p -= lr * (1 + 1)
    np.testing.assert_allclose(before - after, 0.2 * np.ones((1, dim)),
                               atol=1e-6)


def test_eviction_by_frequency_and_version():
    kv = KvVariable("ev", embedding_dim=4)
    hot = np.array([1], np.int64)
    cold = np.array([2], np.int64)
    for _ in range(10):
        kv.gather(hot)
    kv.gather(cold)
    assert len(kv) == 2
    removed = kv.evict(min_frequency=5)
    assert removed == 1 and len(kv) == 1
    np.testing.assert_array_equal(
        kv.gather(cold, train=False), np.zeros((1, 4))
    )
    # version-based: key 3 updated at step 10 survives an evict of
    # anything older than step 5; key 1 (version 0) goes
    kv.assign(np.array([3], np.int64), np.zeros((1, 4), np.float32),
              step=10)
    assert kv.evict(min_version=5) == 1
    assert len(kv) == 1


def test_export_import_roundtrip_with_slots():
    kv = KvVariable("ck", embedding_dim=4, seed=6)
    keys = np.array([1, 2, 3], np.int64)
    grads = np.ones((3, 4), np.float32)
    kv.gather(keys)
    kv.apply_gradients("adam", keys, grads, step=1)
    state = kv.state_dict()
    assert set(state["slots"]) == {"m", "v"}

    kv2 = KvVariable("ck2", embedding_dim=4, seed=999)
    kv2.load_state_dict(state)
    np.testing.assert_allclose(
        kv2.gather(keys, train=False), kv.gather(keys, train=False)
    )
    # continued training matches (slots restored)
    kv.apply_gradients("adam", keys, grads, step=2)
    kv2.apply_gradients("adam", keys, grads, step=2)
    np.testing.assert_allclose(
        kv2.gather(keys, train=False),
        kv.gather(keys, train=False),
        atol=1e-6,
    )


def test_delta_export():
    kv = KvVariable("delta", embedding_dim=4)
    kv.assign(np.array([1], np.int64), np.ones((1, 4), np.float32),
              step=1)
    kv.assign(np.array([2], np.int64), np.ones((1, 4), np.float32),
              step=5)
    keys, _, _, versions = kv.export(since_version=3)
    assert list(keys) == [2]


def test_jax_embedding_lookup_and_training():
    """End-to-end: lookup inside jit, grads out, sparse apply, loss
    drops — the CTR training loop shape."""
    import jax
    import jax.numpy as jnp

    dim = 8
    kv = KvVariable("ctr", embedding_dim=dim, seed=7)
    w = jnp.ones((dim,), jnp.float32) * 0.1
    keys = np.array([3, 11, 42, 3], np.int64)
    labels = jnp.asarray([1.0, 0.0, 1.0, 1.0])

    def loss_fn(emb_vals, w):
        logits = emb_vals @ w
        return jnp.mean(
            jnp.maximum(logits, 0) - logits * labels
            + jnp.log1p(jnp.exp(-jnp.abs(logits)))
        )

    @jax.jit
    def forward_grads(w, keys):
        vals = embedding_lookup(kv, keys)
        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            vals, w
        )
        return loss, grads[0], grads[1]

    losses = []
    for step in range(1, 60):
        loss, g_emb, g_w = forward_grads(w, jnp.asarray(keys))
        kv.apply_gradients(
            "adagrad", keys, np.asarray(g_emb), step=step, lr=0.5
        )
        w = w - 0.5 * g_w
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5
    assert len(kv) == 3  # unique keys inserted


def test_sparse_ftrl_lr_power_convention():
    """TF/tfplus convention: lr_power <= 0 (default -0.5, so the
    effective per-coordinate lr SHRINKS as the accumulator grows);
    positive values are rejected like the reference's kernel
    validation (tfplus training_ops.cc)."""
    import numpy as np

    from dlrover_tpu.sparse.kv_variable import KvVariable

    dim = 4
    kv = KvVariable("ftrl_power", embedding_dim=dim, seed=11)
    keys = np.array([1], np.int64)
    kv.gather(keys)  # materialize the row
    with pytest.raises(ValueError):
        kv.apply_gradients(
            "ftrl", keys, np.ones((1, dim), np.float32), step=1,
            lr=0.1, lr_power=0.5,
        )
    # With the default (-0.5) the update magnitude must shrink across
    # repeated identical gradients (1/sqrt(accum) schedule).
    before = kv.gather(keys).copy()
    kv.apply_gradients("ftrl", keys, np.ones((1, dim), np.float32),
                       step=1, lr=0.1)
    first_delta = np.abs(kv.gather(keys) - before).mean()
    for s in range(2, 31):
        kv.apply_gradients("ftrl", keys, np.ones((1, dim), np.float32),
                           step=s, lr=0.1)
    prev = kv.gather(keys).copy()
    kv.apply_gradients("ftrl", keys, np.ones((1, dim), np.float32),
                       step=31, lr=0.1)
    late_delta = np.abs(kv.gather(keys) - prev).mean()
    assert late_delta < first_delta


def test_sparse_lamb_matches_optax_per_row():
    """Fused C++ sparse LAMB == optax.lamb with each embedding row as
    its own leaf (the trust ratio is per-row here, per-leaf there)."""
    import jax.numpy as jnp
    import optax

    dim = 8
    kv = KvVariable("emb", embedding_dim=dim, seed=11)
    keys = np.array([3, 17], np.int64)
    init_vals = kv.gather(keys).copy()
    grads = np.random.default_rng(1).normal(size=(2, dim)).astype(
        np.float32
    )

    opt = optax.lamb(1e-2, eps=1e-6, weight_decay=0.01)
    dense = {str(i): jnp.asarray(init_vals[i]) for i in range(2)}
    state = opt.init(dense)
    for step in range(1, 4):
        kv.apply_gradients(
            "lamb", keys, grads, step=step, lr=1e-2,
            weight_decay=0.01,
        )
        gtree = {str(i): jnp.asarray(grads[i]) for i in range(2)}
        updates, state = opt.update(gtree, state, dense)
        dense = optax.apply_updates(dense, updates)
    got = kv.gather(keys, train=False)
    want = np.stack([np.asarray(dense[str(i)]) for i in range(2)])
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-4)


def test_sparse_adabelief_matches_paper():
    """Fused C++ sparse AdaBelief == a numpy transcription of the
    paper's Algorithm 2 (Zhuang et al. 2020): the second moment
    tracks (g - m_t)^2 against the UPDATED first moment, with eps
    accumulated into s each step (the paper's footnote, which modern
    optax mirrors via eps_root added to stored nu).

    Deliberately NOT compared against the installed optax: its
    scale_by_belief computes the prediction error against the STALE
    state.mu (pre-update) — a known pre-fix variant that diverges
    from the paper (and from optax main, which uses the updated mu).
    The kernel follows the paper; pinning the test to the container's
    optax would entrench the variant."""
    dim = 8
    kv = KvVariable("emb", embedding_dim=dim, seed=12)
    keys = np.array([4, 8], np.int64)
    p = kv.gather(keys).copy().astype(np.float64)
    grads = np.random.default_rng(2).normal(size=(2, dim)).astype(
        np.float32
    )
    g = grads.astype(np.float64)
    lr, b1, b2, eps = 1e-2, 0.9, 0.999, 1e-8
    m = np.zeros_like(p)
    s = np.zeros_like(p)
    for step in range(1, 5):
        kv.apply_gradients(
            "adabelief", keys, grads, step=step, lr=lr, eps=eps,
        )
        m = b1 * m + (1.0 - b1) * g
        s = b2 * s + (1.0 - b2) * (g - m) ** 2 + eps
        bc1 = 1.0 - b1 ** step
        bc2 = 1.0 - b2 ** step
        p -= lr * (m / bc1) / (np.sqrt(s / bc2) + eps)
    np.testing.assert_allclose(
        kv.gather(keys, train=False), p.astype(np.float32),
        atol=1e-5, rtol=1e-4,
    )


def _group_adam_numpy(p, g, steps, lr, b1, b2, eps, l1, l2, l21):
    """Direct transcription of the reference's GroupAdam kernel math
    (tfplus training_ops.cc:1065 COMPUTE_ADAM)."""
    dim = p.shape[-1]
    m = np.zeros_like(p)
    v = np.zeros_like(p)
    a = np.zeros_like(p)
    lin = np.zeros_like(p)
    l21n = l21 * np.sqrt(dim)
    for t in range(1, steps + 1):
        b1p, b2p = b1**t, b2**t
        eps_adj = eps / np.sqrt(1.0 - b2p)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        new_a = v / (1.0 - b2p)
        delta = np.sqrt(new_a) - np.sqrt(a)
        if b1 <= b1p:  # only at t == 1
            delta = delta + eps_adj
        lin = lin + m / (1.0 - b1p) - delta / lr * p
        a = new_a
        adj = np.clip(lin, -l1, l1)
        l1l = adj - lin
        norm = np.sqrt((l1l**2).sum(-1, keepdims=True))
        y = (np.sqrt(a) + eps_adj) / lr + 2.0 * l2
        scale = np.where(norm > l21n, 1.0 - l21n / norm, 0.0)
        p = np.where(norm > l21n, l1l * scale / y, 0.0)
    return p.astype(np.float32)


def test_group_adam_matches_reference_formula():
    dim = 8
    kv = KvVariable("emb", embedding_dim=dim, seed=13)
    keys = np.array([1, 2], np.int64)
    init_vals = kv.gather(keys).copy()
    grads = np.random.default_rng(3).normal(size=(2, dim)).astype(
        np.float32
    )
    kwargs = dict(lr=0.05, b1=0.9, b2=0.999, eps=1e-8, l1=0.001,
                  l2=0.01, l21=0.001)
    for step in range(1, 4):
        kv.apply_gradients(
            "group_adam", keys, grads, step=step, lr=kwargs["lr"],
            beta1=kwargs["b1"], beta2=kwargs["b2"],
            eps=kwargs["eps"], l1=kwargs["l1"], l2=kwargs["l2"],
            l21=kwargs["l21"],
        )
    want = _group_adam_numpy(
        init_vals, grads, 3, kwargs["lr"], kwargs["b1"],
        kwargs["b2"], kwargs["eps"], kwargs["l1"], kwargs["l2"],
        kwargs["l21"],
    )
    np.testing.assert_allclose(
        kv.gather(keys, train=False), want, atol=1e-5, rtol=1e-4,
    )


def test_group_lasso_zeroes_weak_rows():
    """The L21 group penalty must collapse rows with weak gradients to
    EXACT zeros while strong rows keep learning (the reference
    blacklists such keys; the sparsification is the point)."""
    dim = 8
    kv = KvVariable("emb", embedding_dim=dim, seed=14)
    keys = np.array([100, 200], np.int64)
    strong = np.ones(dim, np.float32)
    weak = np.full(dim, 1e-4, np.float32)
    grads = np.stack([strong, weak])
    for step in range(1, 20):
        kv.apply_gradients(
            "group_adam", keys, grads, step=step, lr=0.1, l21=0.01,
        )
    vals = kv.gather(keys, train=False)
    assert np.abs(vals[0]).max() > 0  # strong row survives
    np.testing.assert_array_equal(vals[1], np.zeros(dim))  # exact 0


def test_group_ftrl_reduces_to_ftrl_without_group_terms():
    """l21=0 (and l1=l2=shrinkage=0): group FTRL must equal the plain
    fused FTRL kernel step for step."""
    dim = 6
    kv_a = KvVariable("emb_a", embedding_dim=dim, seed=15)
    kv_b = KvVariable("emb_b", embedding_dim=dim, seed=15)
    keys = np.array([7], np.int64)
    np.testing.assert_array_equal(kv_a.gather(keys), kv_b.gather(keys))
    grads = np.random.default_rng(4).normal(size=(1, dim)).astype(
        np.float32
    )
    for step in range(1, 6):
        kv_a.apply_gradients("ftrl", keys, grads, step=step, lr=0.1)
        kv_b.apply_gradients(
            "group_ftrl", keys, grads, step=step, lr=0.1, l21=0.0,
        )
    np.testing.assert_allclose(
        kv_a.gather(keys, train=False),
        kv_b.gather(keys, train=False),
        atol=1e-6, rtol=1e-6,
    )


def test_group_ftrl_l21_sparsifies():
    dim = 8
    kv = KvVariable("emb", embedding_dim=dim, seed=16)
    keys = np.array([1, 2], np.int64)
    grads = np.stack([
        np.ones(dim, np.float32),
        np.full(dim, 1e-4, np.float32),
    ])
    for step in range(1, 20):
        kv.apply_gradients(
            "group_ftrl", keys, grads, step=step, lr=0.1, l21=0.02,
        )
    vals = kv.gather(keys, train=False)
    assert np.abs(vals[0]).max() > 0
    np.testing.assert_array_equal(vals[1], np.zeros(dim))


class TestHybridStorageTier:
    """RAM/disk tiering (ref tfplus hybrid_embedding/storage_table.h):
    cold rows spill to disk, promote on access, survive checkpoints."""

    def test_ram_bounded_and_rows_preserved(self, tmp_path):
        dim = 8
        kv = KvVariable(
            "emb", embedding_dim=dim, seed=21,
            disk_tier_path=str(tmp_path / "tier.bin"),
            max_ram_rows=64,
        )
        keys = np.arange(512, dtype=np.int64)
        vals = kv.gather(keys).copy()
        assert len(kv) == 512  # nothing lost
        assert kv.ram_rows() <= 64 + 16  # per-shard rounding slack
        assert kv.disk_rows() >= 512 - 64 - 16
        # every row — resident or spilled — reads back identically
        np.testing.assert_array_equal(kv.gather(keys), vals)

    def test_promotion_preserves_freq_version_and_training(
        self, tmp_path
    ):
        dim = 4
        kv = KvVariable(
            "emb", embedding_dim=dim, seed=22,
            disk_tier_path=str(tmp_path / "tier.bin"),
            max_ram_rows=16,
        )
        hot = np.arange(8, dtype=np.int64)
        # train the hot rows, then flood with cold keys to force the
        # hot rows' neighbors to spill
        for step in (1, 2, 3):
            kv.apply_gradients(
                "adagrad", hot, np.full((8, dim), 0.1, np.float32),
                step=step, lr=0.1,
            )
        trained = kv.gather(hot, train=False).copy()
        kv.gather(np.arange(100, 400, dtype=np.int64))  # flood
        assert kv.disk_rows() > 0
        # spilled trained rows come back exactly
        np.testing.assert_array_equal(
            kv.gather(hot, train=False), trained
        )
        # and training continues from the promoted state
        kv.apply_gradients(
            "adagrad", hot, np.full((8, dim), 0.1, np.float32),
            step=4, lr=0.1,
        )

    def test_inference_reads_cold_rows_without_promotion(
        self, tmp_path
    ):
        kv = KvVariable(
            "emb", embedding_dim=4, seed=23,
            disk_tier_path=str(tmp_path / "tier.bin"),
            max_ram_rows=16,
        )
        keys = np.arange(200, dtype=np.int64)
        vals = kv.gather(keys).copy()
        disk_before = kv.disk_rows()
        assert disk_before > 0
        np.testing.assert_array_equal(
            kv.gather(keys, train=False), vals
        )
        assert kv.disk_rows() == disk_before  # no promotion churn

    def test_export_covers_both_tiers(self, tmp_path):
        kv = KvVariable(
            "emb", embedding_dim=4, seed=24,
            disk_tier_path=str(tmp_path / "tier.bin"),
            max_ram_rows=8,
        )
        keys = np.arange(100, dtype=np.int64)
        vals = kv.gather(keys).copy()
        ek, ev, ef, enew = kv.export()
        assert ek.size == 100
        order = np.argsort(ek)
        np.testing.assert_array_equal(ek[order], keys)
        np.testing.assert_array_equal(ev[order], vals)

    def test_evict_drops_cold_disk_rows_too(self, tmp_path):
        kv = KvVariable(
            "emb", embedding_dim=4, seed=25,
            disk_tier_path=str(tmp_path / "tier.bin"),
            max_ram_rows=8,
        )
        kv.gather(np.arange(64, dtype=np.int64))
        total = len(kv)
        removed = kv.evict(min_frequency=2)  # all rows have freq 1
        assert removed == total
        assert len(kv) == 0


def test_sparse_amsgrad_matches_torch_per_row():
    """Fused C++ sparse AMSGrad == torch.optim.Adam(amsgrad=True):
    the max accumulator runs on the RAW second moment with the bias
    correction applied in the denominator (torch convention; optax
    instead maxes the bias-corrected moment, which differs early)."""
    torch = pytest.importorskip("torch")

    dim = 8
    kv = KvVariable("emb", embedding_dim=dim, seed=13)
    keys = np.array([5, 9], np.int64)
    init_vals = kv.gather(keys).copy()
    grads = np.random.default_rng(3).normal(size=(2, dim)).astype(
        np.float32
    )

    p = torch.nn.Parameter(torch.tensor(init_vals))
    opt = torch.optim.Adam(
        [p], lr=1e-2, betas=(0.9, 0.999), eps=1e-8, amsgrad=True
    )
    for step in range(1, 5):
        kv.apply_gradients(
            "amsgrad", keys, grads, step=step, lr=1e-2, eps=1e-8,
        )
        opt.zero_grad()
        p.grad = torch.tensor(grads)
        opt.step()
    np.testing.assert_allclose(
        kv.gather(keys, train=False), p.detach().numpy(),
        atol=1e-5, rtol=1e-4,
    )


def test_sparse_radam_matches_optax():
    """Both unrectified (early steps at beta2=0.999: rho_t <= 4) and
    rectified (beta2=0.9 crosses the threshold fast) regimes."""
    import jax.numpy as jnp
    import optax

    for beta2, steps in ((0.999, 3), (0.9, 12)):
        dim = 8
        kv = KvVariable("emb", embedding_dim=dim, seed=14)
        keys = np.array([2, 6], np.int64)
        init_vals = kv.gather(keys).copy()
        grads = np.random.default_rng(4).normal(
            size=(2, dim)
        ).astype(np.float32)
        opt = optax.radam(
            1e-2, b2=beta2, eps=1e-8, threshold=4.0
        )
        dense = jnp.asarray(init_vals)
        state = opt.init(dense)
        for step in range(1, steps + 1):
            kv.apply_gradients(
                "radam", keys, grads, step=step, lr=1e-2,
                beta2=beta2, eps=1e-8,
            )
            updates, state = opt.update(
                jnp.asarray(grads), state, dense
            )
            dense = optax.apply_updates(dense, updates)
        np.testing.assert_allclose(
            kv.gather(keys, train=False), np.asarray(dense),
            atol=1e-5, rtol=1e-4,
        )


def test_sparse_adadelta_matches_optax():
    import jax.numpy as jnp
    import optax

    dim = 8
    kv = KvVariable("emb", embedding_dim=dim, seed=15)
    keys = np.array([1, 7], np.int64)
    init_vals = kv.gather(keys).copy()
    grads = np.random.default_rng(5).normal(size=(2, dim)).astype(
        np.float32
    )
    opt = optax.adadelta(0.5, rho=0.95, eps=1e-6)
    dense = jnp.asarray(init_vals)
    state = opt.init(dense)
    for step in range(1, 6):
        kv.apply_gradients(
            "adadelta", keys, grads, step=step, lr=0.5,
            rho=0.95, eps=1e-6,
        )
        updates, state = opt.update(jnp.asarray(grads), state, dense)
        dense = optax.apply_updates(dense, updates)
    np.testing.assert_allclose(
        kv.gather(keys, train=False), np.asarray(dense),
        atol=1e-5, rtol=1e-4,
    )


def test_sparse_adahessian_reduces_to_adam_when_h_equals_g():
    """With hessian rows == gradient rows and hessian_power=1, the
    AdaHessian second moment tracks g^2 — identical to Adam. A crisp
    invariant of the kernel math (Yao et al. 2021 eq. 9-10)."""
    dim = 8
    kv_h = KvVariable("emb", embedding_dim=dim, seed=16)
    kv_a = KvVariable("emb2", embedding_dim=dim, seed=16)
    keys = np.array([3, 11], np.int64)
    np.testing.assert_array_equal(
        kv_h.gather(keys), kv_a.gather(keys)
    )  # same seed -> same init
    grads = np.random.default_rng(6).normal(size=(2, dim)).astype(
        np.float32
    )
    for step in range(1, 4):
        kv_h.apply_gradients(
            "adahessian", keys, grads, step=step, lr=1e-2,
            hessian=grads, hessian_power=1.0,
        )
        kv_a.apply_gradients("adam", keys, grads, step=step, lr=1e-2)
    np.testing.assert_allclose(
        kv_h.gather(keys, train=False),
        kv_a.gather(keys, train=False),
        atol=1e-6, rtol=1e-5,
    )


def test_sparse_adahessian_requires_hessian():
    kv = KvVariable("emb", embedding_dim=4)
    keys = np.array([1], np.int64)
    grads = np.zeros((1, 4), np.float32)
    with pytest.raises(ValueError, match="hessian"):
        kv.apply_gradients("adahessian", keys, grads, step=1)


def test_sparse_adahessian_power_dampens_adaptivity():
    """hessian_power=0 collapses the denominator to 1+eps (pure
    momentum); the two extremes must differ given curvature."""
    dim = 4
    kv0 = KvVariable("emb", embedding_dim=dim, seed=17)
    kv1 = KvVariable("emb2", embedding_dim=dim, seed=17)
    keys = np.array([1], np.int64)
    grads = np.full((1, dim), 0.1, np.float32)
    hess = np.full((1, dim), 2.0, np.float32)
    for step in range(1, 3):
        kv0.apply_gradients(
            "adahessian", keys, grads, step=step, lr=1e-2,
            hessian=hess, hessian_power=0.0,
        )
        kv1.apply_gradients(
            "adahessian", keys, grads, step=step, lr=1e-2,
            hessian=hess, hessian_power=1.0,
        )
    d0 = kv0.gather(keys, train=False)
    d1 = kv1.gather(keys, train=False)
    assert not np.allclose(d0, d1)


def test_sparse_adamax_matches_optax():
    import jax.numpy as jnp
    import optax

    dim = 8
    kv = KvVariable("emb", embedding_dim=dim, seed=18)
    keys = np.array([2, 9], np.int64)
    init_vals = kv.gather(keys).copy()
    grads = np.random.default_rng(7).normal(size=(2, dim)).astype(
        np.float32
    )
    opt = optax.adamax(1e-2, eps=1e-8)
    dense = jnp.asarray(init_vals)
    state = opt.init(dense)
    for step in range(1, 5):
        kv.apply_gradients(
            "adamax", keys, grads, step=step, lr=1e-2, eps=1e-8,
        )
        updates, state = opt.update(jnp.asarray(grads), state, dense)
        dense = optax.apply_updates(dense, updates)
    np.testing.assert_allclose(
        kv.gather(keys, train=False), np.asarray(dense),
        atol=1e-5, rtol=1e-4,
    )


def test_sparse_nadam_matches_optax():
    import jax.numpy as jnp
    import optax

    dim = 8
    kv = KvVariable("emb", embedding_dim=dim, seed=19)
    keys = np.array([4, 6], np.int64)
    init_vals = kv.gather(keys).copy()
    grads = np.random.default_rng(8).normal(size=(2, dim)).astype(
        np.float32
    )
    opt = optax.nadam(1e-2, eps=1e-8)
    dense = jnp.asarray(init_vals)
    state = opt.init(dense)
    for step in range(1, 5):
        kv.apply_gradients(
            "nadam", keys, grads, step=step, lr=1e-2, eps=1e-8,
        )
        updates, state = opt.update(jnp.asarray(grads), state, dense)
        dense = optax.apply_updates(dense, updates)
    np.testing.assert_allclose(
        kv.gather(keys, train=False), np.asarray(dense),
        atol=1e-5, rtol=1e-4,
    )


def test_sparse_rmsprop_matches_torch():
    """Torch convention: eps OUTSIDE the sqrt (optax puts it inside),
    with classical momentum on the scaled step."""
    torch = pytest.importorskip("torch")

    dim = 8
    kv = KvVariable("emb", embedding_dim=dim, seed=20)
    keys = np.array([1, 5], np.int64)
    init_vals = kv.gather(keys).copy()
    grads = np.random.default_rng(9).normal(size=(2, dim)).astype(
        np.float32
    )
    p = torch.nn.Parameter(torch.tensor(init_vals))
    opt = torch.optim.RMSprop(
        [p], lr=1e-2, alpha=0.9, eps=1e-7, momentum=0.5
    )
    for step in range(1, 5):
        kv.apply_gradients(
            "rmsprop", keys, grads, step=step, lr=1e-2, rho=0.9,
            momentum=0.5, eps=1e-7,
        )
        opt.zero_grad()
        p.grad = torch.tensor(grads)
        opt.step()
    np.testing.assert_allclose(
        kv.gather(keys, train=False), p.detach().numpy(),
        atol=1e-5, rtol=1e-4,
    )


# ---- AdaDQH family (Ant's quasi-Hessian optimizer, ref tfplus
# ApplyAdaDQH / KvVariableGroupSparseApplyAdaDQHV2) ------------------


def test_sparse_adadqh_matches_dense_agd():
    """Fused C++ AdaDQH == the dense optax AGD core (optim/adadqh.py
    documents the naming: AdaDQH is the family's tfplus-era name)."""
    import jax.numpy as jnp
    import optax

    from dlrover_tpu.optim import adadqh as dense_adadqh

    dim = 8
    kv = KvVariable("emb", embedding_dim=dim, seed=21)
    keys = np.array([4, 13], np.int64)
    init_vals = kv.gather(keys).copy()
    grads = np.random.default_rng(7).normal(size=(2, dim)).astype(
        np.float32
    )

    opt = dense_adadqh(1e-2, b1=0.9, b2=0.999, eps=1e-5)
    dense = {str(i): jnp.asarray(init_vals[i]) for i in range(2)}
    state = opt.init(dense)
    for step in range(1, 5):
        kv.apply_gradients(
            "adadqh", keys, grads, step=step, lr=1e-2,
            beta1=0.9, beta2=0.999, eps=1e-5,
        )
        gtree = {str(i): jnp.asarray(grads[i]) for i in range(2)}
        updates, state = opt.update(gtree, state, dense)
        dense = optax.apply_updates(dense, updates)
    got = kv.gather(keys, train=False)
    want = np.stack([np.asarray(dense[str(i)]) for i in range(2)])
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-4)


def test_sparse_adadqh_eps_floor_is_sgd_regime():
    """With eps far above any curvature estimate, every coordinate
    takes momentum-SGD steps -lr*m_hat/eps — the auto-switch's SGD
    branch, verified against the closed form."""
    dim = 4
    lr, b1, eps = 1e-2, 0.9, 1e6
    kv = KvVariable("emb", embedding_dim=dim, seed=22)
    keys = np.array([5], np.int64)
    p = kv.gather(keys).copy().astype(np.float64)
    grads = np.random.default_rng(8).normal(size=(1, dim)).astype(
        np.float32
    )
    m = np.zeros(dim, np.float64)
    for step in range(1, 4):
        kv.apply_gradients(
            "adadqh", keys, grads, step=step, lr=lr, beta1=b1,
            eps=eps,
        )
        m = b1 * m + (1 - b1) * grads[0]
        p[0] -= lr * (m / (1 - b1**step)) / eps
    np.testing.assert_allclose(
        kv.gather(keys, train=False), p.astype(np.float32),
        atol=1e-6, rtol=1e-5,
    )


def _group_adadqh_numpy(p0, grads, steps, lr, b1, b2, eps, l1, l2,
                        l21):
    """Independent restatement of the published group AdaDQH-V2 rule."""
    n, dim = p0.shape
    p = p0.astype(np.float64).copy()
    m = np.zeros_like(p)
    v = np.zeros_like(p)
    lin = np.zeros_like(p)
    l1s, l2s, l21s = l1 * lr, l2 * lr, l21 * lr
    l21_norm = l21s * np.sqrt(dim)
    for t in range(1, steps + 1):
        bc1 = 1 - b1**t
        bc1_old = 1.0 if t == 1 else 1 - b1 ** (t - 1)
        b2p = b2**t
        alpha = lr * np.sqrt(1 - b2p) / bc1
        eps_adj = eps * np.sqrt(1 - b2p)
        last_eps_adj = eps * np.sqrt(1 - b2p / b2)
        m_old_hat = m / bc1_old
        v_prev = v.copy()
        m = b1 * m + (1 - b1) * grads
        u = m / bc1 - m_old_hat
        v = b2 * v_prev + (1 - b2) * u * u
        denom_new = np.maximum(np.sqrt(v), eps_adj)
        denom_old = np.maximum(np.sqrt(v_prev), last_eps_adj)
        lin += m * alpha - (denom_new - denom_old) * p
        adj = np.clip(lin, -l1s, l1s)
        l1l = adj - lin
        norm = np.sqrt((l1l**2).sum(axis=1, keepdims=True))
        scale = np.where(
            norm > l21_norm, 1 - l21_norm / np.maximum(norm, 1e-30),
            0.0,
        )
        y = np.maximum(np.sqrt(v), eps_adj) + 2 * l2s
        p = np.where(norm > l21_norm, l1l * scale / y, 0.0)
    return p.astype(np.float32)


def test_group_adadqh_matches_reference_formula():
    dim = 8
    kv = KvVariable("emb", embedding_dim=dim, seed=23)
    keys = np.array([1, 2], np.int64)
    init_vals = kv.gather(keys).copy()
    grads = np.random.default_rng(9).normal(size=(2, dim)).astype(
        np.float32
    )
    kw = dict(lr=0.05, b1=0.9, b2=0.999, eps=1e-5, l1=0.001,
              l2=0.01, l21=0.001)
    for step in range(1, 4):
        kv.apply_gradients(
            "group_adadqh", keys, grads, step=step, lr=kw["lr"],
            beta1=kw["b1"], beta2=kw["b2"], eps=kw["eps"],
            l1=kw["l1"], l2=kw["l2"], l21=kw["l21"],
        )
    want = _group_adadqh_numpy(
        init_vals, grads, 3, kw["lr"], kw["b1"], kw["b2"],
        kw["eps"], kw["l1"], kw["l2"], kw["l21"],
    )
    np.testing.assert_allclose(
        kv.gather(keys, train=False), want, atol=1e-5, rtol=1e-4,
    )


def test_group_adadqh_l21_sparsifies():
    dim = 8
    kv = KvVariable("emb", embedding_dim=dim, seed=24)
    keys = np.array([10, 20], np.int64)
    grads = np.stack([
        np.ones(dim, np.float32),
        np.full(dim, 1e-4, np.float32),
    ])
    for step in range(1, 20):
        kv.apply_gradients(
            "group_adadqh", keys, grads, step=step, lr=0.1, l21=0.05,
        )
    vals = kv.gather(keys, train=False)
    assert np.abs(vals[0]).max() > 0
    np.testing.assert_array_equal(vals[1], np.zeros(dim))


# ---- LambHessian (ref ApplyLambHessian /
# KvVariableGroupSparseApplyLambHessian) -----------------------------


def _lamb_hessian_numpy(p0, grads, hess, steps, lr, b1, b2, eps):
    p = p0.astype(np.float64).copy()
    m = np.zeros_like(p)
    v = np.zeros_like(p)
    for t in range(1, steps + 1):
        adjust = np.sqrt(1 - b2**t) / (1 - b1**t)
        m = b1 * m + (1 - b1) * grads
        v = b2 * v + (1 - b2) * hess * hess
        u = m * adjust / (np.sqrt(v) + eps)
        p_norm = np.sqrt((p**2).sum(axis=1, keepdims=True))
        u_norm = np.sqrt((u**2).sum(axis=1, keepdims=True))
        ratio = np.where(
            (p_norm > 0) & (u_norm > 0), p_norm / (u_norm + 1e-8),
            1.0,
        )
        p -= lr * ratio * u
    return p.astype(np.float32)


def test_sparse_lamb_hessian_matches_reference_formula():
    dim = 8
    kv = KvVariable("emb", embedding_dim=dim, seed=25)
    keys = np.array([6, 15], np.int64)
    init_vals = kv.gather(keys).copy()
    rng = np.random.default_rng(10)
    grads = rng.normal(size=(2, dim)).astype(np.float32)
    hess = np.abs(rng.normal(size=(2, dim))).astype(np.float32)
    for step in range(1, 4):
        kv.apply_gradients(
            "lamb_hessian", keys, grads, step=step, lr=1e-2,
            hessian=hess, eps=1e-6,
        )
    want = _lamb_hessian_numpy(
        init_vals, grads, hess, 3, 1e-2, 0.9, 0.999, 1e-6
    )
    np.testing.assert_allclose(
        kv.gather(keys, train=False), want, atol=1e-5, rtol=1e-4,
    )


def test_lamb_hessian_requires_hessian():
    kv = KvVariable("emb", embedding_dim=4)
    keys = np.array([1], np.int64)
    grads = np.zeros((1, 4), np.float32)
    with pytest.raises(ValueError, match="hessian"):
        kv.apply_gradients("lamb_hessian", keys, grads, step=1)
    with pytest.raises(ValueError, match="hessian"):
        kv.apply_gradients(
            "group_lamb_hessian", keys, grads, step=1
        )


def test_group_lamb_hessian_l21_sparsifies():
    dim = 8
    kv = KvVariable("emb", embedding_dim=dim, seed=26)
    keys = np.array([10, 20], np.int64)
    grads = np.stack([
        np.ones(dim, np.float32),
        np.full(dim, 1e-4, np.float32),
    ])
    hess = np.abs(grads)
    for step in range(1, 20):
        kv.apply_gradients(
            "group_lamb_hessian", keys, grads, step=step, lr=0.1,
            hessian=hess, l21=0.01,
        )
    vals = kv.gather(keys, train=False)
    assert np.abs(vals[0]).max() > 0
    np.testing.assert_array_equal(vals[1], np.zeros(dim))


def _group_lamb_hessian_numpy(p0, grads, hess, steps, lr, b1, b2,
                              eps, l1, l2, l21):
    """Independent restatement: trust-ratio-scaled curvature step into
    the FTRL-proximal linear/group-lasso machinery (group_adam-style
    1/lr convention)."""
    n, dim = p0.shape
    p = p0.astype(np.float64).copy()
    m = np.zeros_like(p)
    v = np.zeros_like(p)
    a = np.zeros_like(p)
    lin = np.zeros_like(p)
    l21_norm = l21 * np.sqrt(dim)
    for t in range(1, steps + 1):
        bc1, bc2 = 1 - b1**t, 1 - b2**t
        m = b1 * m + (1 - b1) * grads
        v = b2 * v + (1 - b2) * hess * hess
        new_a = v / bc2
        r = (m / bc1) / (np.sqrt(new_a) + eps)
        p_norm = np.sqrt((p**2).sum(axis=1, keepdims=True))
        r_norm = np.sqrt((r**2).sum(axis=1, keepdims=True))
        ratio = np.where(
            (p_norm > 0) & (r_norm > 0), p_norm / (r_norm + 1e-8),
            1.0,
        )
        lin += (m / bc1) * ratio - (np.sqrt(new_a) - np.sqrt(a)) / lr * p
        a = new_a
        adj = np.clip(lin, -l1, l1)
        l1l = adj - lin
        norm = np.sqrt((l1l**2).sum(axis=1, keepdims=True))
        scale = np.where(
            norm > l21_norm, 1 - l21_norm / np.maximum(norm, 1e-30),
            0.0,
        )
        y = (np.sqrt(a) + eps) / lr + 2 * l2
        p = np.where(norm > l21_norm, l1l * scale / y, 0.0)
    return p.astype(np.float32)


def test_group_lamb_hessian_matches_reference_formula():
    dim = 8
    kv = KvVariable("emb", embedding_dim=dim, seed=27)
    keys = np.array([1, 2], np.int64)
    init_vals = kv.gather(keys).copy()
    rng = np.random.default_rng(11)
    grads = rng.normal(size=(2, dim)).astype(np.float32)
    hess = np.abs(rng.normal(size=(2, dim))).astype(np.float32)
    kw = dict(lr=0.05, b1=0.9, b2=0.999, eps=1e-6, l1=0.001,
              l2=0.01, l21=0.001)
    for step in range(1, 4):
        kv.apply_gradients(
            "group_lamb_hessian", keys, grads, step=step,
            lr=kw["lr"], hessian=hess, beta1=kw["b1"],
            beta2=kw["b2"], eps=kw["eps"], l1=kw["l1"],
            l2=kw["l2"], l21=kw["l21"],
        )
    want = _group_lamb_hessian_numpy(
        init_vals, grads, hess, 3, kw["lr"], kw["b1"], kw["b2"],
        kw["eps"], kw["l1"], kw["l2"], kw["l21"],
    )
    np.testing.assert_allclose(
        kv.gather(keys, train=False), want, atol=1e-5, rtol=1e-4,
    )


def test_kv_adadqh_hypergradients_surface():
    """The sparse twin of ComputeAdaDQHHG: per-row (lr_hg, eps_hg)
    from the m/v slots, matching the dense (finite-diff tested)
    construction on the same rows; untouched keys give zeros."""
    from dlrover_tpu.optim import adadqh_hypergradients

    dim = 8
    kv = KvVariable("emb", embedding_dim=dim, seed=28)
    keys = np.array([7, 21], np.int64)
    grads = np.random.default_rng(12).normal(size=(2, dim)).astype(
        np.float32
    )
    lr, eps, b1, b2 = 1e-2, 1e-5, 0.9, 0.999
    for step in range(1, 4):
        kv.apply_gradients(
            "adadqh", keys, grads, step=step, lr=lr, beta1=b1,
            beta2=b2, eps=eps,
        )
    lr_hg, eps_hg = kv.adadqh_hypergradients(
        keys, lr=lr, step=4, eps=eps, beta1=b1, beta2=b2
    )
    want_lr, want_eps = adadqh_hypergradients(
        kv.gather_slot("m", keys), kv.gather_slot("v", keys),
        lr, eps, b1, b2, 4,
    )
    np.testing.assert_allclose(lr_hg, np.asarray(want_lr), rtol=1e-6)
    np.testing.assert_allclose(
        eps_hg, np.asarray(want_eps), rtol=1e-6
    )
    assert np.abs(lr_hg).max() > 0  # trained rows have direction

    # untouched keys: zero hypergradients, no accidental inserts —
    # the main store AND the slot stores must not grow
    size_before = len(kv)
    slot_sizes = {n: len(s) for n, s in kv._slots.items()}
    z_lr, z_eps = kv.adadqh_hypergradients(
        np.array([999], np.int64), lr=lr, step=4
    )
    np.testing.assert_array_equal(z_lr, np.zeros((1, dim)))
    np.testing.assert_array_equal(z_eps, np.zeros((1, dim)))
    assert len(kv) == size_before
    assert {n: len(s) for n, s in kv._slots.items()} == slot_sizes

    # slots written by a DIFFERENT optimizer are refused, not
    # silently misinterpreted
    kv2 = KvVariable("emb2", embedding_dim=dim, seed=29)
    kv2.apply_gradients("adam", keys, grads, step=1, lr=lr)
    with pytest.raises(ValueError, match="adadqh-family"):
        kv2.adadqh_hypergradients(keys, lr=lr, step=2)
    # misspelled slot names raise instead of returning silent zeros
    with pytest.raises(KeyError, match="unknown slot"):
        kv.gather_slot("M", keys)


def test_sparse_sgd_exact_and_converges():
    """Slot-free sparse gradient descent (ref tfplus
    python/training/gradient_descent.py): exact p -= lr*g semantics,
    alias 'gradient_descent' accepted, and convergence on the same
    quadratic the other families use."""
    dim = 4
    kv = KvVariable("sgd_exact", embedding_dim=dim, seed=7)
    keys = np.array([3], np.int64)
    before = kv.gather(keys).copy()
    g = np.ones((1, dim), np.float32)
    kv.apply_gradients("sgd", keys, g, step=1, lr=0.25)
    after = kv.gather(keys, train=False)
    np.testing.assert_allclose(
        before - after, 0.25 * np.ones((1, dim)), atol=1e-6
    )

    target = np.ones((1, dim), np.float32)
    kv2 = KvVariable("sgd_conv", embedding_dim=dim, seed=8)
    for step in range(1, 200):
        vals = kv2.gather(keys)
        kv2.apply_gradients(
            "gradient_descent", keys, vals - target, step=step, lr=0.1
        )
    final = kv2.gather(keys, train=False)
    assert np.abs(final - target).max() < 0.05
