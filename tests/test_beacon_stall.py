"""Cross-host stall localization: beacons, correlator, fleet drill.

Covers the acceptance criteria of the stall-localization PR:

* the trainer-side progress beacon round-trips through its mmap'd
  file, tolerates torn reads, and stays readable after the writer
  closes (or wedges) — the agent reads the FILE, not the process;
* ``StepPhaseProfiler`` stamps the beacon at boundaries trainers
  already cross, and a profiler-less train step writes nothing (the
  host-sync audits' regime);
* the correlator's decision table, hermetically with a fake clock:
  fleet-wide vs single-laggard split, no verdict on partial
  staleness, a flapping beacon never convicts, departed hosts purge
  their conviction state;
* the wedged-fleet drill: three REAL subprocesses stamp beacons, one
  wedges mid-step; a real in-process JobMaster convicts exactly that
  host (zero false convictions), pushes the coordinated
  DIAGNOSE+PROFILE capture to every host's FIFO in one window, mints
  the ``stall.incident`` trace, serves it over ``query_stall``, and
  ``obs_report --stall`` against the live socket exits 1 during the
  incident and 0 after resolution;
* the bench capture path: a timed-out measurement leaves a
  kind-``hang`` ledger record carrying the last beacon stamp.
"""

import json
import os
import subprocess
import sys
import time
import types

import pytest

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import RpcClient
from dlrover_tpu.common.constants import EventAction
from dlrover_tpu.master.master import JobMaster
from dlrover_tpu.obs.beacon import (
    ProgressBeacon,
    progress_key,
    read_beacon,
    stamp_age,
)
from dlrover_tpu.obs.health import (
    SEVERITY_CRITICAL,
    HealthMonitor,
)
from dlrover_tpu.obs.stall import StallCorrelator, render_stall
from dlrover_tpu.obs.timeseries import TimeSeriesStore
from dlrover_tpu.obs.trace_store import TraceStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class TestProgressBeacon:
    def test_roundtrip_and_cross_process_read(self, tmp_path):
        path = str(tmp_path / "beacon.json")
        b = ProgressBeacon(path=path)
        b.stamp(step=3, phase="dispatch", microbatch=2)
        # Another reader (the agent) opens the file fresh.
        stamp = read_beacon(path)
        assert stamp["step"] == 3
        assert stamp["phase"] == "dispatch"
        assert stamp["microbatch"] == 2
        assert stamp["pid"] == os.getpid()
        age = stamp_age(stamp)
        assert age is not None and 0.0 <= age < 5.0
        # Omitted fields keep their last value; seq advances.
        seq = stamp["seq"]
        b.stamp(phase="device_execute")
        stamp2 = read_beacon(path)
        assert stamp2["step"] == 3 and stamp2["seq"] > seq
        # The file survives the writer closing — a wedged (or dead)
        # trainer leaves its last position readable.
        b.close()
        assert read_beacon(path)["phase"] == "device_execute"

    def test_torn_read_is_no_stamp(self, tmp_path):
        path = str(tmp_path / "beacon.json")
        with open(path, "w") as f:
            f.write('{"step": 3, "pha')  # mid-write torn record
        assert read_beacon(path) is None
        assert read_beacon(str(tmp_path / "missing.json")) is None

    def test_progress_key_total_order(self):
        # Step outranks phase outranks microbatch; None sorts first.
        k = progress_key
        assert k({"step": 2, "phase": "init"}) > k(
            {"step": 1, "phase": "device_execute", "microbatch": 9}
        )
        assert k({"step": 1, "phase": "dispatch"}) > k(
            {"step": 1, "phase": "h2d_stage", "microbatch": 5}
        )
        assert k(
            {"step": 1, "phase": "h2d_stage", "microbatch": 2}
        ) > k({"step": 1, "phase": "h2d_stage", "microbatch": 1})
        assert k(None) < k({"step": 0, "phase": "init"})

    def test_profiler_stamps_boundaries(self, tmp_path):
        from dlrover_tpu.obs.profiling import StepPhaseProfiler

        path = str(tmp_path / "beacon.json")
        prof = StepPhaseProfiler(
            beacon=ProgressBeacon(path=path), poll_requests=False
        )
        prof.note_data_wait(0.01)
        assert read_beacon(path)["phase"] == "data_wait"
        prof.note_dispatch(0.01, compiled=False)
        s = read_beacon(path)
        assert (s["step"], s["phase"]) == (1, "dispatch")
        prof.end_step()
        s = read_beacon(path)
        assert (s["step"], s["phase"]) == (1, "device_execute")

    def test_disabled_beacon_writes_nothing(self, tmp_path, monkeypatch):
        from dlrover_tpu.obs.beacon import default_beacon

        path = str(tmp_path / "beacon.json")
        monkeypatch.setenv("DLROVER_TPU_BEACON_FILE", path)
        monkeypatch.setenv("DLROVER_TPU_BEACON", "0")
        assert default_beacon() is None
        assert not os.path.exists(path)


class FakeFleet:
    """``live_snapshots()`` provider shaped like FleetAggregator."""

    def __init__(self, clock):
        self.clock = clock
        self.snaps = {}

    def set(self, host, node_id, step, phase, mb, age_s):
        self.snaps[host] = types.SimpleNamespace(
            host=host, node_id=node_id, wall_ts=self.clock(),
            beacon={"step": step, "phase": phase,
                    "microbatch": mb, "age_s": age_s},
        )

    def drop(self, host):
        self.snaps.pop(host, None)

    def age(self, dt):
        for snap in self.snaps.values():
            snap.wall_ts = self.clock()
            snap.beacon["age_s"] += dt

    def live_snapshots(self):
        return list(self.snaps.values())


def make_correlator(clk, fleet, **kw):
    config = {
        "stall_after_s": 60.0,
        "stall_ticks": 2.0,
        "capture_cooldown_s": 0.0,
    }
    config.update(kw.pop("config", {}))
    return StallCorrelator(
        fleet=fleet, clock=clk, config=config, **kw
    )


class TestStallCorrelator:
    def setup_method(self):
        self.clk = FakeClock(5000.0)
        self.fleet = FakeFleet(self.clk)
        for host, nid in (("h0", 0), ("h1", 1), ("h2", 2)):
            self.fleet.set(host, nid, 10, "dispatch", -1, 1.0)

    def park(self, ticks, corr, dt=90.0):
        out = []
        for _ in range(ticks):
            self.clk.t += dt
            self.fleet.age(dt)
            out = corr.evaluate()
        return out

    def test_laggard_convicted_with_coordinated_capture(self):
        pushes = []
        traces = TraceStore(clock=self.clk)
        corr = make_correlator(
            self.clk, self.fleet, traces=traces,
            capture=lambda n, a, dedupe_key=None: (
                pushes.append((n, a, dedupe_key)) or True
            ),
        )
        assert corr.evaluate() == []
        # h2 wedges a full phase behind its peers.
        self.fleet.set("h2", 2, 10, "h2d_stage", 1, 1.0)
        verdicts = self.park(2, corr)
        assert [
            (v.detector, v.host, v.node_id, v.severity)
            for v in verdicts
        ] == [("collective_stall", "h2", 2, SEVERITY_CRITICAL)]
        assert verdicts[0].suggested_action == (
            EventAction.DIAGNOSE.value
        )
        inc = corr.open_incident()
        assert inc["kind"] == "laggard" and inc["culprit"] == "h2"
        # One coordinated round: DIAGNOSE+PROFILE to every node.
        assert sorted({n for n, _, _ in pushes}) == [0, 1, 2]
        assert len(pushes) == 6
        assert all(
            k == f"stall:{inc['id']}:{a}:{n}" for n, a, k in pushes
        )
        # The incident trace: root + per-host progress + captures.
        tl = traces.get(inc["trace_id"])
        names = [s["name"] for s in tl["spans"]]
        assert names.count("stall.incident") == 1
        assert names.count("stall.progress") == 3
        assert names.count("stall.capture") == 3
        root_id = f"{inc['trace_id']}:root"
        assert all(
            s["parent_span_id"] == root_id
            for s in tl["spans"]
            if s["name"] != "stall.incident"
        )
        culprit_span = next(
            s for s in tl["spans"]
            if s["name"] == "stall.progress"
            and s["tags"]["host"] == "h2"
        )
        assert culprit_span["tags"]["culprit"] is True
        # Recovery resolves the incident into history.
        self.clk.t += 30.0
        for host, nid in (("h0", 0), ("h1", 1), ("h2", 2)):
            self.fleet.set(host, nid, 12, "dispatch", -1, 1.0)
        assert corr.evaluate() == []
        assert corr.open_incident() is None
        snap = corr.snapshot()
        assert snap["incidents"][-1]["resolved_ts"] == self.clk.t
        assert "stall.resolved" in [
            s["name"] for s in traces.get(inc["trace_id"])["spans"]
        ]

    def test_fleet_wide_stall_not_localized(self):
        corr = make_correlator(
            self.clk, self.fleet,
            silent_probe=lambda: {1: 150.0},
        )
        verdicts = self.park(2, corr)
        assert [
            (v.detector, v.node_id, v.suggested_action)
            for v in verdicts
        ] == [("fleet_stall", -1, "")]
        assert "attributed to silent node 1" in verdicts[0].message
        assert corr.silent_suspects == {1}
        assert corr.open_incident()["kind"] == "fleet_wide"

    def test_partial_staleness_never_convicts(self):
        corr = make_correlator(self.clk, self.fleet)
        # Only h2 goes stale; its peers keep stamping.
        for step in (11, 12, 13):
            self.clk.t += 90.0
            self.fleet.set("h0", 0, step, "dispatch", -1, 1.0)
            self.fleet.set("h1", 1, step, "dispatch", -1, 1.0)
            self.fleet.snaps["h2"].beacon["age_s"] += 90.0
            assert corr.evaluate() == []
        assert corr.open_incident() is None

    def test_flapping_beacon_never_convicts(self):
        corr = make_correlator(self.clk, self.fleet)
        # Every host reads stale (slow agent cadence) but the keys
        # keep advancing: progress resets the streak each tick.
        for step in range(11, 17):
            self.clk.t += 120.0
            for host, nid in (("h0", 0), ("h1", 1), ("h2", 2)):
                self.fleet.set(host, nid, step, "dispatch", -1, 200.0)
            assert corr.evaluate() == []
        assert corr.open_incident() is None

    def test_departed_host_purges_conviction_state(self):
        corr = make_correlator(self.clk, self.fleet)
        self.park(1, corr)  # every host at 1 stalled tick
        assert corr._stalled_ticks["h2"] == 1
        self.fleet.drop("h2")
        self.clk.t += 90.0
        self.fleet.age(90.0)
        corr.evaluate()
        assert "h2" not in corr._stalled_ticks
        assert "h2" not in corr._progress

    def test_two_tied_behind_is_fleet_wide(self):
        corr = make_correlator(self.clk, self.fleet)
        self.fleet.set("h1", 1, 10, "h2d_stage", 1, 1.0)
        self.fleet.set("h2", 2, 10, "h2d_stage", 1, 1.0)
        verdicts = self.park(2, corr)
        assert verdicts[0].detector == "fleet_stall"

    def test_render_and_snapshot_roundtrip(self):
        corr = make_correlator(self.clk, self.fleet)
        self.fleet.set("h2", 2, 9, "data_wait", -1, 1.0)
        self.park(2, corr)
        snap = json.loads(json.dumps(corr.snapshot()))
        rendered = render_stall(snap)
        assert "OPEN" in rendered
        assert "<- culprit" in rendered
        assert "STALLED" in rendered


class TestMonitorIntegration:
    def test_heartbeat_gap_upgraded_for_silent_suspect(self):
        clk = FakeClock(5000.0)
        store = TimeSeriesStore(clock=clk)
        fleet = FakeFleet(clk)
        for host, nid in (("h0", 0), ("h1", 1)):
            fleet.set(host, nid, 10, "dispatch", -1, 200.0)
        ages = {0: 10.0, 1: 160.0}  # node 1 heartbeat-silent
        mon = HealthMonitor(
            store,
            heartbeat_timeout=180.0,
            heartbeat_ages=lambda: dict(ages),
            clock=clk,
            config={"goodput_grace_s": 0.0},
        )
        corr = make_correlator(clk, fleet, config={"stall_ticks": 1.0})
        mon.attach_stall(corr)
        assert mon.stall is corr
        assert corr.silent_probe is not None
        # Tick 1: the correlator (last detector) records the suspect;
        # tick 2: heartbeat_gap reads it and upgrades to DIAGNOSE.
        clk.t += 90.0
        fleet.age(90.0)
        mon.evaluate_once()
        assert corr.silent_suspects == {1}
        clk.t += 90.0
        fleet.age(90.0)
        verdicts = mon.evaluate_once()
        hb = next(
            v for v in verdicts if v.detector == "heartbeat_gap"
        )
        assert hb.node_id == 1
        assert hb.severity == SEVERITY_CRITICAL
        assert hb.suggested_action == EventAction.DIAGNOSE.value
        assert "attributed to this silent node" in hb.message
        fs = next(v for v in verdicts if v.detector == "fleet_stall")
        assert fs.suggested_action == ""

    def test_collective_stall_maps_to_cordon_replace(self):
        from dlrover_tpu.master.remediation import (
            ACTION_CORDON_REPLACE,
            DETECTOR_ACTIONS,
        )

        assert DETECTOR_ACTIONS["collective_stall"] == (
            ACTION_CORDON_REPLACE
        )
        # Fleet-wide stalls are deliberately alert-only: remediation
        # must never act on a verdict that convicts nobody.
        assert "fleet_stall" not in DETECTOR_ACTIONS

    def test_resource_monitor_ships_beacon_and_latches(self, tmp_path):
        from dlrover_tpu.agent.monitor import ResourceMonitor

        path = str(tmp_path / "beacon.json")
        b = ProgressBeacon(path=path)
        b.stamp(step=4, phase="dispatch")
        fired = []
        mon = ResourceMonitor(
            client=None,
            beacon_path=path,
            on_stale_beacon=fired.append,
        )
        payload = mon.beacon_payload()
        assert payload["step"] == 4 and payload["age_s"] >= 0.0
        # Below the threshold: latch armed, nothing fires.
        assert mon.check_beacon_stall(dict(payload)) is False
        stale = dict(payload)
        stale["age_s"] = mon.beacon_stall_s + 1.0
        assert mon.check_beacon_stall(stale) is True
        assert fired and fired[0]["step"] == 4
        # Same (pid, seq): fires once, not on every cadence tick.
        assert mon.check_beacon_stall(stale) is False
        # Fresh progress re-arms the latch.
        b.stamp(step=5, phase="dispatch")
        fresh = mon.beacon_payload()
        assert mon.check_beacon_stall(fresh) is False
        stale2 = dict(fresh)
        stale2["age_s"] = mon.beacon_stall_s + 1.0
        assert mon.check_beacon_stall(stale2) is True
        assert len(fired) == 2


WEDGE_SRC = """
import sys, time
from dlrover_tpu.obs.beacon import ProgressBeacon
path, step, phase = sys.argv[1], int(sys.argv[2]), sys.argv[3]
b = ProgressBeacon(path=path)
for s in range(1, step + 1):
    b.stamp(step=s, phase="dispatch")
b.stamp(step=step, phase=phase)
print("STAMPED", flush=True)
time.sleep(120)  # wedged in a "collective"
"""


class TestWedgedFleetDrill:
    """Three real subprocesses stamp beacons; one wedges a phase
    behind. A real in-process JobMaster localizes it, captures the
    whole fleet, serves the incident, and obs_report --stall holds
    the rc contract against the live socket."""

    @pytest.fixture()
    def master(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_STALL_STALL_AFTER_S", "0.4")
        monkeypatch.setenv("DLROVER_TPU_STALL_STALL_TICKS", "2")
        monkeypatch.setenv("DLROVER_TPU_STALL_CAPTURE_COOLDOWN_S", "0")
        m = JobMaster(
            port=0, node_num=3, rdzv_timeout=1.0, metrics_port=0,
            collect_interval=999.0, health_interval=9999.0,
        )
        m.prepare()
        yield m
        m.stop()

    def spawn_fleet(self, tmp_path):
        """(host -> beacon path, procs): h0/h1 park at step 5's
        dispatch; h2 wedged at step 4's h2d_stage."""
        paths, procs = {}, []
        spec = {"h0": (5, "dispatch"), "h1": (5, "dispatch"),
                "h2": (4, "h2d_stage")}
        for host, (step, phase) in spec.items():
            path = str(tmp_path / f"beacon_{host}.json")
            paths[host] = path
            procs.append(
                subprocess.Popen(
                    [sys.executable, "-c", WEDGE_SRC,
                     path, str(step), phase],
                    stdout=subprocess.PIPE, text=True, cwd=REPO,
                )
            )
        for p in procs:
            assert p.stdout.readline().strip() == "STAMPED"
        return paths, procs

    def report_round(self, master, paths):
        client = RpcClient(master.addr)
        for node_id, host in ((0, "h0"), (1, "h1"), (2, "h2")):
            stamp = read_beacon(paths[host])
            beacon = dict(stamp)
            beacon["age_s"] = round(stamp_age(stamp), 3)
            client.report(
                msg.MetricsSnapshotReport(
                    node_id=node_id, host=host,
                    timestamp=time.time(), beacon=beacon,
                )
            )
        return client

    def test_drill(self, master, tmp_path):
        paths, procs = self.spawn_fleet(tmp_path)
        try:
            client = RpcClient(master.addr)
            for node_id, host in ((0, "h0"), (1, "h1"), (2, "h2")):
                client.report(
                    msg.NodeAddressRequest(
                        node_id=node_id, node_ip=host
                    )
                )
            # Round 1, beacons fresh: no verdicts, no convictions.
            self.report_round(master, paths)
            assert not [
                v for v in master.health.evaluate_once()
                if v.detector in ("collective_stall", "fleet_stall")
            ]
            # The children wedge (stop stamping); two stale rounds.
            verdicts = []
            for _ in range(2):
                time.sleep(0.55)
                self.report_round(master, paths)
                verdicts = master.health.evaluate_once()
            stall = [
                v for v in verdicts
                if v.detector == "collective_stall"
            ]
            assert len(stall) == 1, verdicts
            # EXACTLY the wedged host — zero false convictions.
            assert (stall[0].host, stall[0].node_id) == ("h2", 2)
            assert not any(
                v.host in ("h0", "h1")
                for v in verdicts
                if v.detector == "collective_stall"
            )
            inc = master.stall.open_incident()
            assert inc["kind"] == "laggard"
            assert inc["culprit"] == "h2"

            # Coordinated capture: every node's FIFO drains both
            # actions — a simultaneous fleet snapshot, not a
            # culprit-only poke.
            for node_id in (0, 1, 2):
                drained = set()
                while True:
                    action = client.report(
                        msg.HeartbeatRequest(node_id=node_id)
                    ).action
                    if action == "none":
                        break
                    drained.add(action)
                assert {
                    EventAction.DIAGNOSE.value,
                    EventAction.PROFILE.value,
                } <= drained, (node_id, drained)

            # All bundles hang off ONE stall.incident trace.
            tl = master.traces.get(inc["trace_id"])
            names = [s["name"] for s in tl["spans"]]
            assert names.count("stall.incident") == 1
            assert names.count("stall.progress") == 3
            assert names.count("stall.capture") == 3

            # Diagnostics reports cross-link into the served snapshot.
            client.report(
                msg.DiagnosticsReport(
                    node_id=2, kind="stall",
                    bundle_path="/tmp/bundle_h2.json",
                    timestamp=time.time(),
                )
            )
            from dlrover_tpu.agent.master_client import MasterClient

            mc = MasterClient(master.addr, node_id=0)
            resp = mc.query_stall()
            assert resp.enabled
            snap = resp.snapshot
            assert snap["incident"]["culprit"] == "h2"
            assert snap["hosts"]["h2"]["stalled"] is True
            assert snap["incident"]["bundles"]["h2"][0][
                "bundle_path"
            ] == "/tmp/bundle_h2.json"

            # obs_report --stall, live socket: rc=1 while open.
            proc = subprocess.run(
                [
                    sys.executable,
                    os.path.join(REPO, "tools", "obs_report.py"),
                    "--stall", master.addr,
                ],
                capture_output=True, text=True, timeout=120, cwd=REPO,
            )
            assert proc.returncode == 1, proc.stdout + proc.stderr
            assert "h2" in proc.stdout
            assert "culprit" in proc.stdout
            assert inc["trace_id"] in proc.stdout
            assert "bundle" in proc.stdout

            # Recovery: the fleet advances, the incident resolves,
            # and the live rc contract drops to 0.
            for host in paths:
                b = ProgressBeacon(path=paths[host])
                b.stamp(step=9, phase="dispatch")
                b.close()
            self.report_round(master, paths)
            verdicts = master.health.evaluate_once()
            assert not any(
                v.detector in ("collective_stall", "fleet_stall")
                for v in verdicts
            )
            assert master.stall.open_incident() is None
            proc = subprocess.run(
                [
                    sys.executable,
                    os.path.join(REPO, "tools", "obs_report.py"),
                    "--stall", master.addr,
                ],
                capture_output=True, text=True, timeout=120, cwd=REPO,
            )
            assert proc.returncode == 0, proc.stdout + proc.stderr
            assert "resolved after" in proc.stdout
        finally:
            for p in procs:
                p.kill()
                p.wait()


class TestBenchHangLedger:
    """The capture path's blind-retry seam: a timed-out measurement
    child leaves a kind-'hang' ledger record with its last stamp."""

    def _wedged_child(self, tmp_path, monkeypatch):
        path = str(tmp_path / "beacon.json")
        ledger = str(tmp_path / "ledger.jsonl")
        monkeypatch.setenv("DLROVER_TPU_BEACON_FILE", path)
        monkeypatch.setenv("DLROVER_TPU_BENCH_LEDGER", ledger)
        # A real child process stamps then wedges; the parent kills
        # it on timeout and reads the file it left behind.
        proc = subprocess.Popen(
            [sys.executable, "-c", WEDGE_SRC, path, "7", "dispatch"],
            stdout=subprocess.PIPE, text=True, cwd=REPO,
        )
        assert proc.stdout.readline().strip() == "STAMPED"
        proc.kill()
        proc.wait()
        return ledger

    def test_bench_emit_failure_records_hang(
        self, tmp_path, monkeypatch, capsys
    ):
        import importlib.util

        ledger = self._wedged_child(tmp_path, monkeypatch)
        spec = importlib.util.spec_from_file_location(
            "bench_for_test", os.path.join(REPO, "bench.py")
        )
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        bench._emit_failure("tpu_hang", "no response within 900s", 2)
        out = capsys.readouterr().out.strip().splitlines()[-1]
        rec = json.loads(out)
        assert rec["kind"] == "hang"
        assert rec["error"] == "tpu_hang"
        assert rec["beacon"]["step"] == 7
        assert rec["beacon"]["phase"] == "dispatch"
        assert "step 7 dispatch" in rec["hang_digest"]
        # The same record landed in the ledger, and it can never be
        # picked as a comparison endpoint.
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import bench_ledger

            recs = bench_ledger.load_records(ledger)
            assert recs[-1]["kind"] == "hang"
            assert recs[-1]["beacon"]["step"] == 7
            assert bench_ledger.record_value(recs[-1]) is None
        finally:
            sys.path.remove(os.path.join(REPO, "tools"))

    def test_capture_perf_hang_record(self, tmp_path, monkeypatch):
        ledger = self._wedged_child(tmp_path, monkeypatch)
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import capture_perf

            line = capture_perf.hang_record(600.0, "baseline")
        finally:
            sys.path.remove(os.path.join(REPO, "tools"))
        assert "hang ledger record" in line
        assert "step 7 dispatch" in line
        with open(ledger) as f:
            recs = [json.loads(x) for x in f if x.strip()]
        assert recs[-1]["kind"] == "hang"
        assert recs[-1]["stage"] == "baseline"
        assert recs[-1]["beacon"]["step"] == 7
