"""Pool capacity accounting & SLO error budgets (obs/capacity.py +
the slo_burn detector in obs/health.py).

The acceptance drill (TestCapacityAcceptance) plays a hermetic,
fake-clocked two-tenant pool through a ~10-minute backdated timeline
— tenant ``a`` training through one preemption + restore, tenant
``b`` serving through an injected TTFT regression — against a LIVE
``TPUPoolMaster`` and asserts the four contract points:

(a) per-{tenant,state} chip-seconds partition ``total_chips x
    elapsed`` exactly;
(b) tenant a's goodput-per-chip dip is attributed to the
    ``preempting`` / ``restoring`` intervals;
(c) the fast-window burn verdict fires critical while the slow
    window holds warn, and both resolve after recovery;
(d) ``obs_report --capacity`` against the live master renders the
    table with rc=1 during the burn and rc=0 after — with the new
    metrics in the registry and the brain tables readable back.

The unit tests pin the ledger/budget invariants the drill only
samples one path through.
"""

import json
import os
import sys
import time

import pytest

from dlrover_tpu import obs
from dlrover_tpu.brain.service import BrainService
from dlrover_tpu.obs.capacity import (
    IDLE_TENANT,
    STATE_ALLOCATED,
    STATE_IDLE,
    STATE_PREEMPTING,
    STATE_RESTORING,
    CapacityLedger,
    render_capacity,
)
from dlrover_tpu.obs.health import (
    SEVERITY_CRITICAL,
    SEVERITY_WARN,
    HealthMonitor,
    SLOSpec,
    slos_from_env,
)
from dlrover_tpu.obs.timeseries import TimeSeriesStore
from dlrover_tpu.pool import SliceSpec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def two_slices():
    return [SliceSpec(slice_id=0), SliceSpec(slice_id=1)]  # 4 chips


# ---------------------------------------------------------------------------
# ledger invariants
# ---------------------------------------------------------------------------


class TestCapacityLedger:
    def test_partition_invariant_is_exact(self):
        """Closed cells + open accruals must sum to total_chips x
        elapsed EXACTLY at every instant — through allocation,
        preemption, idle gaps, and a restore."""
        clk = FakeClock(1000.0)
        led = CapacityLedger(two_slices(), clock=clk)

        def check(ts):
            snap = led.snapshot(ts=ts)
            assert snap["partition_ok"], snap["chip_seconds"]
            want = led.total_chips * (ts - 1000.0)
            assert snap["chip_seconds"]["accounted"] == pytest.approx(
                want, abs=1e-6
            )
            assert snap["chip_seconds"]["capacity"] == pytest.approx(
                want, abs=1e-6
            )

        led.on_allocate("ja", "a", [0, 1], ts=1010.0)
        check(1015.0)
        led.mark_preempting("ja", ts=1030.0)
        check(1035.0)
        led.on_release("ja", [0, 1], ts=1040.0)
        check(1045.0)
        led.on_allocate("ja", "a", [1], ts=1050.0)
        led.mark_restoring("ja", ts=1050.0)
        led.job_ready("ja", ts=1070.0)
        check(1100.0)

    def test_state_attribution_by_tenant(self):
        clk = FakeClock(1000.0)
        led = CapacityLedger(two_slices(), clock=clk)
        led.on_allocate("ja", "a", [0, 1], ts=1010.0)
        led.mark_preempting("ja", ts=1030.0)
        led.on_release("ja", [0, 1], ts=1040.0)
        snap = led.snapshot(ts=1050.0)
        a = snap["tenants"]["a"]
        assert a["states"][STATE_ALLOCATED] == pytest.approx(160.0)
        assert a["states"][STATE_PREEMPTING] == pytest.approx(80.0)
        assert a["overhead_chip_seconds"] == pytest.approx(80.0)
        by_state = snap["chip_seconds"]["by_state"]
        # idle: 2 slices x 10s before + 2 x 10s after, x 4 chips.
        assert by_state[STATE_IDLE] == pytest.approx(160.0)
        assert IDLE_TENANT not in snap["tenants"]

    def test_goodput_ratio_applies_forward_and_clamps(self):
        """A ratio observation settles the PREVIOUS ratio up to its
        stamp and applies forward; out-of-range ratios clamp."""
        clk = FakeClock(0.0)
        led = CapacityLedger(two_slices(), clock=clk)
        led.on_allocate("ja", "a", [0, 1], ts=0.0)
        led.observe_goodput("ja", 0.5, ts=10.0)  # 0..10 at ratio 0
        led.observe_goodput("ja", 1.5, ts=20.0)  # clamps to 1.0
        led.observe_goodput("ja", -3.0, ts=30.0)  # clamps to 0.0
        snap = led.snapshot(ts=40.0)
        a = snap["tenants"]["a"]
        # 10s x 8 chips x 0.5 + 10s x 8 x 1.0 + 10s x 8 x 0.0
        assert a["productive_chip_seconds"] == pytest.approx(120.0)
        assert a["goodput_per_chip"] == pytest.approx(120.0 / 320.0)

    def test_overhead_intervals_accrue_no_productive(self):
        """Acceptance (b): the goodput-per-chip dip during a
        preemption + restore is attributed to the overhead states —
        held keeps growing while productive is frozen."""
        clk = FakeClock(0.0)
        led = CapacityLedger(two_slices(), clock=clk)
        led.on_allocate("ja", "a", [0, 1], ts=0.0)
        led.observe_goodput("ja", 1.0, ts=0.0)
        before = led.snapshot(ts=100.0)["tenants"]["a"]
        assert before["goodput_per_chip"] == pytest.approx(1.0)
        led.mark_preempting("ja", ts=100.0)
        led.on_release("ja", [0, 1], ts=120.0)
        led.on_allocate("ja", "a", [0, 1], ts=140.0)
        led.mark_restoring("ja", ts=140.0)
        during = led.snapshot(ts=160.0)["tenants"]["a"]
        # productive frozen at 800 while held grew by the overhead.
        assert during["productive_chip_seconds"] == pytest.approx(
            before["productive_chip_seconds"]
        )
        assert during["goodput_per_chip"] < before["goodput_per_chip"]
        assert during["states"][STATE_PREEMPTING] == pytest.approx(
            160.0
        )
        assert during["states"][STATE_RESTORING] == pytest.approx(
            160.0
        )
        led.job_ready("ja", ts=160.0)
        after = led.snapshot(ts=260.0)["tenants"]["a"]
        assert (
            after["productive_chip_seconds"]
            > during["productive_chip_seconds"]
        )

    def test_retire_job_purges_series(self):
        """Satellite: retired jobs drop their per-job series; the
        tenant-level series survives until its LAST job retires
        (the PR-8 departed-host purge applied to tenants)."""
        clk = FakeClock(0.0)
        store = TimeSeriesStore(clock=clk)
        led = CapacityLedger(
            two_slices(), timeseries=store, clock=clk
        )
        led.on_allocate("j1", "a", [0], ts=0.0)
        led.on_allocate("j2", "a", [1], ts=0.0)
        led.observe_goodput("j1", 0.5, ts=10.0)
        led.observe_goodput("j2", 0.5, ts=10.0)
        labels = store.series_labels("tenant.goodput")
        assert {"tenant": "a", "job": "j1"} in labels
        assert {"tenant": "a"} in labels
        led.on_release("j1", [0], ts=20.0)
        led.retire_job("j1", retire_tenant=False, ts=20.0)
        labels = store.series_labels("tenant.goodput")
        assert {"tenant": "a", "job": "j1"} not in labels
        assert {"tenant": "a", "job": "j2"} in labels
        assert {"tenant": "a"} in labels
        led.on_release("j2", [1], ts=30.0)
        led.retire_job("j2", retire_tenant=True, ts=30.0)
        assert store.series_labels("tenant.goodput") == []
        # Retired productive history still counts in the rollup:
        # j1 accrued 10s x 4 chips x 0.5, j2 accrued 20s x 4 x 0.5.
        snap = led.snapshot(ts=40.0)
        assert snap["tenants"]["a"][
            "productive_chip_seconds"
        ] == pytest.approx(60.0)

    def test_brain_tables_roundtrip(self):
        brain = BrainService(":memory:")
        clk = FakeClock(0.0)
        led = CapacityLedger(
            two_slices(), brain=brain, job_name="pool", clock=clk
        )
        led.on_allocate("ja", "a", [0, 1], ts=10.0)
        led.observe_goodput("ja", 0.8, ts=20.0)
        led.mark_preempting("ja", ts=30.0)
        led.on_release("ja", [0, 1], ts=40.0)
        ivs = brain.recent_capacity_intervals("pool")
        assert ivs, "no capacity intervals persisted"
        states = {(iv["state"], iv["tenant"]) for iv in ivs}
        assert (STATE_ALLOCATED, "a") in states
        assert (STATE_PREEMPTING, "a") in states
        assert (STATE_IDLE, IDLE_TENANT) in states
        total = sum(iv["chip_seconds"] for iv in ivs)
        assert total == pytest.approx(40.0 * 8, abs=1e-6)
        gp = brain.recent_tenant_goodput("pool")
        assert gp and gp[0]["tenant"] == "a"
        assert gp[0]["held_chip_seconds"] > 0

    def test_render_capacity_partition_warning(self):
        payload = {
            "pool_slices": 1,
            "total_chips": 4,
            "elapsed_s": 10.0,
            "utilization": 0.5,
            "partition_ok": False,
            "chip_seconds": {
                "capacity": 40.0,
                "accounted": 30.0,
                "by_state": {"idle": 30.0},
            },
            "tenants": {},
        }
        out = render_capacity(payload)
        assert "WARNING" in out and "missed transition hook" in out
        assert "no tenants" in out


# ---------------------------------------------------------------------------
# scheduler/pool hook integration (no pool master)
# ---------------------------------------------------------------------------


class TestSchedulerLedgerHooks:
    def test_preempt_resume_complete_drive_ledger(self):
        """The PoolScheduler + SlicePool hooks drive the ledger
        through the real preemption path: allocated -> preempting ->
        idle -> restoring on the elastic resume, and complete()
        retires the job (tenant purge only when its last job
        leaves)."""
        from dlrover_tpu.pool import (
            PoolJobSpec,
            PoolScheduler,
            SlicePool,
        )
        from tests.test_pool import FakeRT

        clk = FakeClock(1000.0)
        store = TimeSeriesStore(clock=clk)
        pool = SlicePool(4)
        pool.ledger = CapacityLedger(
            pool.specs(), timeseries=store, clock=clk
        )
        led = pool.ledger
        sched = PoolScheduler(pool, park_timeout_s=5.0)
        sched.submit(
            PoolJobSpec(job_id="low", tenant="research", priority=1,
                        n_slices=4, min_slices=2),
            FakeRT(defer_park=True),
        )
        led.observe_goodput("low", 0.9, ts=1000.0)
        clk.t = 1100.0
        rt_low = sched._jobs["low"].runtime
        sched.submit(
            PoolJobSpec(job_id="high", tenant="prod", priority=5,
                        n_slices=2),
            FakeRT(),
        )
        clk.t = 1110.0
        rt_low.confirm_park()  # release at 1110: preempting 10s
        # low resumed elastically on the 2 remaining slices.
        assert sched.job_info("low")["state"] == "placed"
        snap = led.snapshot(ts=1150.0)
        research = snap["tenants"]["research"]
        assert research["states"][STATE_PREEMPTING] == pytest.approx(
            10.0 * 16
        )
        assert STATE_RESTORING in research["states"]
        assert snap["partition_ok"], snap["chip_seconds"]
        # Complete the high job: prod had no sibling -> tenant purge.
        led.observe_goodput("high", 0.5, ts=1150.0)
        assert {"tenant": "prod"} in store.series_labels(
            "tenant.goodput"
        )
        sched.complete("high")
        assert {"tenant": "prod"} not in store.series_labels(
            "tenant.goodput"
        )
        assert {"tenant": "research"} in store.series_labels(
            "tenant.goodput"
        )


# ---------------------------------------------------------------------------
# SLO error budgets & burn-rate detection
# ---------------------------------------------------------------------------


def _ttft_spec(**over):
    kw = dict(
        tenant="b", slo="ttft", series="tenant.ttft_p99_s",
        objective=0.5, direction="max", budget=0.05,
        labels={"tenant": "b"},
    )
    kw.update(over)
    return SLOSpec(**kw)


class TestSLOBudgets:
    def _monitor(self, clk, specs):
        store = TimeSeriesStore(clock=clk)
        mon = HealthMonitor(
            store=store, clock=clk, interval=9999.0, slos=specs
        )
        return store, mon

    def test_fast_critical_slow_warn_then_resolve(self):
        """Acceptance (c): the injected TTFT regression fires the
        fast pair critical while the slow pair holds warn; both
        resolve once the bad samples age out of their windows."""
        clk = FakeClock(200000.0)
        spec = _ttft_spec()
        store, mon = self._monitor(clk, [spec])
        now = clk.t
        for i in range(15):  # compliant traffic, 5m..1h old
            store.record(
                "tenant.ttft_p99_s", 0.2,
                ts=now - 3500 + i * 8, tenant="b",
            )
        for i in range(50):  # regression inside the 5m window
            store.record(
                "tenant.ttft_p99_s", 2.0,
                ts=now - 290 + i * 5.5, tenant="b",
            )
        active = mon.evaluate_once()
        sev = {v.host: v.severity for v in active
               if v.detector == "slo_burn"}
        assert sev.get("b/ttft/fast") == SEVERITY_CRITICAL, sev
        assert sev.get("b/ttft/slow") == SEVERITY_WARN, sev
        budgets = mon.slo_snapshot()
        assert len(budgets) == 1
        b = budgets[0]
        assert b["burning"] and b["severity"] == SEVERITY_CRITICAL
        assert b["burn"]["fast"] >= 14.4
        assert b["burn"]["slow"] >= 1.0
        assert b["budget_remaining"] == 0.0
        # Recovery: 8h later the bad samples are outside even the 6h
        # slow window; fresh traffic is compliant.
        clk.t = now + 8 * 3600.0
        for i in range(30):
            store.record(
                "tenant.ttft_p99_s", 0.2,
                ts=clk.t - 240 + i * 7, tenant="b",
            )
        active = mon.evaluate_once()
        assert not [v for v in active if v.detector == "slo_burn"]
        b = mon.slo_snapshot()[0]
        assert not b["burning"] and b["severity"] == ""
        # The resolution is in the history with resolved=True.
        resolved = [
            v for v in mon.history()
            if v.detector == "slo_burn" and v.resolved
        ]
        assert resolved

    def test_idle_tenant_never_pages(self):
        """No samples = no burn: an idle tenant keeps a full budget
        and no verdict."""
        clk = FakeClock(200000.0)
        _, mon = self._monitor(clk, [_ttft_spec()])
        active = mon.evaluate_once()
        assert not [v for v in active if v.detector == "slo_burn"]
        b = mon.slo_snapshot()[0]
        assert b["budget_remaining"] == 1.0 and not b["burning"]

    def test_blip_does_not_page(self):
        """A short spike burns the 5m window but not the 1h window —
        the min() of the pair keeps fast quiet."""
        clk = FakeClock(200000.0)
        store, mon = self._monitor(clk, [_ttft_spec()])
        now = clk.t
        for i in range(200):  # an hour of compliant traffic
            store.record(
                "tenant.ttft_p99_s", 0.2,
                ts=now - 3590 + i * 17, tenant="b",
            )
        for i in range(5):  # 5-sample blip in the last minute
            store.record(
                "tenant.ttft_p99_s", 2.0,
                ts=now - 50 + i * 9, tenant="b",
            )
        active = mon.evaluate_once()
        sev = {v.host: v.severity for v in active
               if v.detector == "slo_burn"}
        assert "b/ttft/fast" not in sev, sev

    def test_min_direction_objective(self):
        """Training-goodput SLOs gate in the other direction: bad
        when the ratio drops BELOW the objective."""
        clk = FakeClock(200000.0)
        spec = _ttft_spec(
            tenant="a", slo="goodput", series="tenant.goodput",
            objective=0.8, direction="min",
            labels={"tenant": "a"},
        )
        store, mon = self._monitor(clk, [spec])
        now = clk.t
        for i in range(60):
            store.record(
                "tenant.goodput", 0.3,
                ts=now - 290 + i * 4.5, tenant="a",
            )
        for i in range(10):
            store.record(
                "tenant.goodput", 0.95,
                ts=now - 3500 + i * 10, tenant="a",
            )
        active = mon.evaluate_once()
        sev = {v.host: v.severity for v in active
               if v.detector == "slo_burn"}
        assert sev.get("a/goodput/fast") == SEVERITY_CRITICAL, sev

    def test_slos_from_env(self, monkeypatch):
        monkeypatch.setenv(
            "DLROVER_TPU_HEALTH_SLOS",
            json.dumps([
                {"tenant": "a", "slo": "goodput",
                 "series": "tenant.goodput", "objective": 0.8,
                 "direction": "min", "budget": 0.1,
                 "labels": {"tenant": "a"}},
            ]),
        )
        specs = slos_from_env()
        assert len(specs) == 1
        assert specs[0].key() == "a/goodput"
        assert specs[0].budget == 0.1
        monkeypatch.setenv("DLROVER_TPU_HEALTH_SLOS", "not json")
        assert slos_from_env() == []


# ---------------------------------------------------------------------------
# exit-code contract (satellite: one table, enforced)
# ---------------------------------------------------------------------------


class TestObsReportExitCodes:
    """Every obs_report probe section follows the documented rc
    contract: 0 ok / 1 probe-failed / 2 target-unreachable."""

    def test_unreachable_target_is_rc2_everywhere(self, capsys):
        import obs_report

        missing = os.path.join("no", "such", "snapshot.json")
        assert obs_report.health_report(missing) == 2
        assert obs_report.serving_report(missing) == 2
        assert obs_report.pool_report(missing) == 2
        assert obs_report.capacity_report(missing) == 2
        assert obs_report.trace_report("tr-1", missing) == 2
        capsys.readouterr()

    def test_probe_failed_is_rc1(self, tmp_path, capsys):
        import obs_report

        # --pool: a failed job in the snapshot gates rc=1.
        snap = {
            "slices": {"total": 1, "used": 0},
            "jobs": {
                "j1": {"state": "failed", "tenant": "t",
                       "priority": 1, "n_slices": 1, "slices": [],
                       "preemptions": 0, "reason": "boom",
                       "trace_id": ""},
            },
            "queue_depth": {},
            "counters": {"preemptions": {}},
            "tenants": {},
            "wait_percentiles": {},
        }
        p = tmp_path / "pool.json"
        p.write_text(json.dumps(snap))
        assert obs_report.pool_report(str(p)) == 1
        snap["jobs"]["j1"]["state"] = "done"
        p.write_text(json.dumps(snap))
        assert obs_report.pool_report(str(p)) == 0
        # --capacity: a burning budget gates rc=1.
        cap = {
            "pool_slices": 1, "total_chips": 4, "elapsed_s": 1.0,
            "utilization": 0.0, "partition_ok": True,
            "chip_seconds": {"capacity": 4.0, "accounted": 4.0,
                             "by_state": {"idle": 4.0}},
            "tenants": {},
            "slo": {"budgets": [{"tenant": "b", "slo": "ttft",
                                 "burning": True,
                                 "severity": "critical",
                                 "budget_remaining": 0.0,
                                 "burn": {"fast": 20.0,
                                          "slow": 2.0}}]},
        }
        c = tmp_path / "cap.json"
        c.write_text(json.dumps(cap))
        assert obs_report.capacity_report(str(c)) == 1
        cap["slo"]["budgets"][0]["burning"] = False
        c.write_text(json.dumps(cap))
        assert obs_report.capacity_report(str(c)) == 0
        # --trace: reachable target, key not found -> rc=1.
        t = tmp_path / "traces.json"
        t.write_text(json.dumps({"traces": []}))
        assert obs_report.trace_report("ghost", str(t)) == 1
        capsys.readouterr()

    def test_exit_code_table_documented(self):
        import obs_report

        doc = obs_report.__doc__
        assert "Exit codes" in doc
        for needle in ("probe passed", "probe FAILED",
                       "target unreachable", "--capacity"):
            assert needle in doc


# ---------------------------------------------------------------------------
# the acceptance drill: live pool master, backdated two-tenant story
# ---------------------------------------------------------------------------


class TestCapacityAcceptance:
    def test_two_tenant_pool_drill(self):
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.pool import PoolJobSpec, TPUPoolMaster

        import obs_report

        brain = BrainService(":memory:")
        spec = _ttft_spec()
        master = TPUPoolMaster(
            slices=4, watch_interval=9999.0, slos=[spec],
            brain=brain,
        )
        # Backdate the whole plane onto one fake clock, anchored at
        # the ledger's birth instant so the partition stays exact.
        clk = FakeClock(master.capacity.start_ts)
        t0 = clk.t
        master.capacity.clock = clk
        master.timeseries.clock = clk
        master.health.clock = clk
        master.prepare()
        clients = []
        staged = {"ok": False}

        def probe():
            return {"staged": staged["ok"], "step": 7, "mtime": clk.t}

        try:
            clk.t = t0 + 30
            r = master.submit(
                PoolJobSpec(job_id="job-a", tenant="a", priority=1,
                            n_slices=2, min_slices=2),
                ckpt_probe=probe,
            )
            assert r["state"] == "placed", r
            clk.t = t0 + 40
            r = master.submit(
                PoolJobSpec(job_id="job-b", tenant="b", priority=5,
                            n_slices=1)
            )
            assert r["state"] == "placed", r
            # Training telemetry: tenant a runs at 0.9 goodput.
            master.capacity.observe_goodput("job-a", 0.9, ts=t0 + 60)
            # Preemption: a priority-9 gang that needs a's slices.
            clk.t = t0 + 240
            r = master.submit(
                PoolJobSpec(job_id="job-h", tenant="prod",
                            priority=9, n_slices=2)
            )
            clk.t = t0 + 300
            staged["ok"] = True  # checkpoint staged: park confirms
            deadline = time.time() + 15.0
            while time.time() < deadline:
                info = master.scheduler.job_info("job-a")
                if info["state"] == "preempted":
                    break
                time.sleep(0.05)
            assert master.scheduler.job_info("job-a")[
                "state"
            ] == "preempted"
            assert master.scheduler.job_info("job-h")[
                "state"
            ] == "placed"
            # The high job finishes; a resumes (restore path). The
            # resume placement may ride a scheduling pass still in
            # flight on the park thread, so poll (clk pinned at
            # t+360 keeps the restoring stamp deterministic).
            clk.t = t0 + 360
            master.scheduler.complete("job-h")
            deadline = time.time() + 15.0
            while time.time() < deadline:
                if master.scheduler.job_info("job-a")[
                    "state"
                ] == "placed":
                    break
                time.sleep(0.05)
            info = master.scheduler.job_info("job-a")
            assert info["state"] == "placed", info
            # Its workers re-register at t+420: restoring ends.
            clk.t = t0 + 420
            ca = MasterClient(
                master.addr, node_id=0, job_id="job-a"
            )
            clients.append(ca)
            ca.register_node("worker")
            # Serving telemetry: tenant b's TTFT regresses hard in
            # the last five minutes of the timeline.
            for i in range(15):
                master.timeseries.record(
                    "tenant.ttft_p99_s", 0.2,
                    ts=t0 + 180 + i * 8, tenant="b",
                )
                master.timeseries.record(
                    "tenant.ttft_p99_s", 0.2,
                    ts=t0 + 180 + i * 8, tenant="b", job="job-b",
                )
            for i in range(50):
                master.timeseries.record(
                    "tenant.ttft_p99_s", 2.0,
                    ts=t0 + 310 + i * 5.5, tenant="b",
                )
            clk.t = t0 + 600
            master.observe_capacity()  # join + one SLO evaluation

            # (a) the partition invariant, against the live ledger.
            snap = master.capacity.snapshot(ts=t0 + 600)
            assert snap["partition_ok"], snap["chip_seconds"]
            want = master.capacity.total_chips * 600.0
            assert snap["chip_seconds"]["accounted"] == pytest.approx(
                want, abs=1e-6
            )

            # (b) tenant a's dip is attributed to the overhead
            # states: preempting 60s x 8 chips, restoring 60s x 8.
            a = snap["tenants"]["a"]
            assert a["states"][STATE_PREEMPTING] == pytest.approx(
                60.0 * 8, abs=1e-6
            )
            assert a["states"][STATE_RESTORING] == pytest.approx(
                60.0 * 8, abs=1e-6
            )
            assert a["overhead_chip_seconds"] == pytest.approx(
                120.0 * 8, abs=1e-6
            )
            assert a["productive_chip_seconds"] > 0
            assert a["goodput_per_chip"] < 0.9

            # (c) fast fires critical while slow holds warn.
            sev = {
                v.severity
                for k, v in master.health._active.items()
                if k[0] == "slo_burn"
            }
            assert sev == {SEVERITY_CRITICAL, SEVERITY_WARN}, sev
            budgets = master.health.slo_snapshot()
            assert budgets[0]["burning"]
            assert budgets[0]["severity"] == SEVERITY_CRITICAL

            # (d) obs_report --capacity against the live master:
            # rc=1 during the burn...
            assert obs_report.capacity_report(master.addr) == 1
            # ...and rc=0 after recovery (bad samples age out).
            clk.t = t0 + 600 + 8 * 3600.0
            for i in range(30):
                master.timeseries.record(
                    "tenant.ttft_p99_s", 0.2,
                    ts=clk.t - 240 + i * 7, tenant="b",
                )
            master.health.evaluate_once()
            assert not master.health.slo_snapshot()[0]["burning"]
            assert obs_report.capacity_report(master.addr) == 0

            # New metrics exposed in the registry text.
            text = obs.get_registry().render()
            for name in (
                "dlrover_pool_chip_seconds_total",
                "dlrover_tenant_goodput_per_chip",
                "dlrover_slo_budget_remaining",
            ):
                assert name in text, name

            # Brain tables readable back.
            assert brain.recent_capacity_intervals("pool")
            assert brain.recent_tenant_goodput("pool")
        finally:
            for c in clients:
                c.close()
            master.stop()
