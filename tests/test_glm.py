"""GLM family: prefix-LM attention, qkv bias, partial rotary.

Parity targets: the reference's GLM module replacement + parallel GLM
blocks (/root/reference/atorch/atorch/auto/opt_lib/
module_replace_optimization.py, atorch/modules/distributed_modules/
transformer.py). Here GLM is the Llama backbone with config switches
(models/glm.py) and the prefix-LM mask decomposes onto a square
bidirectional prefix call + a rectangular causal suffix call
(ops/prefix_lm.py, ops/flash_attention.py flash_attention_rect).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models import generate, glm, llama
from dlrover_tpu.ops.prefix_lm import (
    prefix_lm_attention,
    prefix_lm_attention_reference,
)


@pytest.fixture(scope="module")
def cfg():
    return glm.tiny()


@pytest.fixture(scope="module")
def params(cfg):
    return glm.init_params(jax.random.PRNGKey(0), cfg)


def _qkv(key, b=2, t=64, h=4, d=16):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, t, h, d)
    return (
        jax.random.normal(kq, shape, jnp.float32),
        jax.random.normal(kk, shape, jnp.float32),
        jax.random.normal(kv, shape, jnp.float32),
    )


@pytest.mark.parametrize("prefix_len", [0, 1, 17, 32, 63, 64])
def test_prefix_attention_matches_dense(prefix_len):
    """The flash-composed prefix op == the dense masked softmax at
    every prefix length incl. the degenerate ends and odd splits."""
    q, k, v = _qkv(jax.random.PRNGKey(1))
    got = prefix_lm_attention(q, k, v, prefix_len, interpret=True)
    want = prefix_lm_attention_reference(q, k, v, prefix_len)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4
    )


def test_prefix_attention_grad_matches_dense():
    """The two-call composition is differentiable end to end and its
    gradients match the dense reference's."""
    q, k, v = _qkv(jax.random.PRNGKey(2), b=1, t=32, h=2, d=8)

    def f_flash(q, k, v):
        return jnp.sum(
            prefix_lm_attention(q, k, v, 13, interpret=True) ** 2
        )

    def f_dense(q, k, v):
        return jnp.sum(
            prefix_lm_attention_reference(q, k, v, 13) ** 2
        )

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=1e-3
        )


def test_prefix_is_bidirectional_suffix_is_causal(cfg, params):
    """Within the prefix, LATE tokens influence EARLY hidden states;
    suffix tokens never influence prefix hidden states."""
    p = 32
    tokens = jax.random.randint(
        jax.random.PRNGKey(3), (1, cfg.block_size), 8, cfg.vocab_size
    )
    attn = glm.prefix_attention_for(cfg, p)
    h0 = llama.backbone(params, tokens, cfg, attn)
    # Late-prefix edit reaches position 0: bidirectional prefix.
    h1 = llama.backbone(
        params, tokens.at[0, p - 1].set(4), cfg, attn
    )
    assert not np.allclose(
        np.asarray(h0[0, 0]), np.asarray(h1[0, 0]), atol=1e-6
    )
    # Suffix edit never reaches the prefix: causal boundary.
    h2 = llama.backbone(
        params, tokens.at[0, -1].set(4), cfg, attn
    )
    np.testing.assert_allclose(
        np.asarray(h0[0, :p]), np.asarray(h2[0, :p]), atol=1e-6
    )


def test_prefix_loss_scores_suffix_band_only(cfg, params):
    """Blank-infilling: the supervised band is [prefix-1, T-1) — the
    positions whose next-token logits produce suffix tokens. Targets
    strictly inside the prefix AND the wrap-around last position are
    ignored; the last-prefix position (which generates the FIRST
    suffix token at sampling time) IS supervised."""
    p = 24
    key = jax.random.PRNGKey(4)
    tokens = jax.random.randint(
        key, (2, cfg.block_size), 8, cfg.vocab_size
    )
    targets = jnp.roll(tokens, -1, axis=1)
    l0 = glm.prefix_lm_loss_fn(params, tokens, targets, cfg, p)
    scrambled = targets.at[:, : p - 1].set(7)
    l1 = glm.prefix_lm_loss_fn(params, tokens, scrambled, cfg, p)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    # Wrap-around position T-1 (target outside the sequence): ignored.
    l_wrap = glm.prefix_lm_loss_fn(
        params, tokens, targets.at[:, -1].set(7), cfg, p
    )
    np.testing.assert_allclose(float(l0), float(l_wrap), rtol=1e-6)
    # ... but in-band targets do count: a mid-suffix position, and
    # the last-prefix position that emits the first suffix token.
    for idx in (-2, p - 1):
        l2 = glm.prefix_lm_loss_fn(
            params, tokens, targets.at[:, idx].set(7), cfg, p
        )
        assert abs(float(l0) - float(l2)) > 1e-7, idx


def test_prefix_loss_rejects_empty_supervision_band(cfg, params):
    """prefix_len >= T leaves zero supervised positions; a mis-bucketed
    batch must fail loudly instead of training on aux-only ~0 loss
    (ADVICE r4)."""
    t = cfg.block_size
    tokens = jnp.zeros((1, t), jnp.int32)
    targets = jnp.zeros((1, t), jnp.int32)
    for bad_p in (t, t + 5):
        with pytest.raises(ValueError, match="no supervised positions"):
            glm.prefix_lm_loss_fn(params, tokens, targets, cfg, bad_p)
    # The largest legal prefix supervises exactly one position (T-2).
    l = glm.prefix_lm_loss_fn(params, tokens, targets, cfg, t - 1)
    assert jnp.isfinite(l)


def test_qkv_bias_params_and_grads(cfg, params):
    """The GLM config materializes q/k/v biases and they receive
    gradient (i.e. they are actually wired into the block)."""
    assert params["blocks"]["bq"].shape == (
        cfg.n_layer, cfg.n_embd,
    )
    assert params["blocks"]["bk"].shape == (
        cfg.n_layer, cfg.n_kv_head * cfg.head_dim,
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(5), (1, 16), 8, cfg.vocab_size
    )
    targets = jnp.roll(tokens, -1, axis=1)
    g = jax.grad(
        lambda p: glm.loss_fn(p, tokens, targets, cfg)
    )(params)
    for name in ("bq", "bk", "bv"):
        assert float(jnp.abs(g["blocks"][name]).sum()) > 0.0
    axes = glm.param_logical_axes(cfg)
    assert axes["blocks"]["bq"] == ("layers", "heads")


def test_partial_rotary_passthrough(cfg):
    """rotary_pct=0.5: the trailing half of each head passes through
    apply_rope unrotated; the leading half is position-dependent."""
    t, d = 8, cfg.head_dim
    cos, sin = llama.rope_table(cfg, t)
    assert cos.shape == (t, d // 4)  # tables cover rot/2 = d/4 dims
    x = jax.random.normal(
        jax.random.PRNGKey(6), (1, t, 2, d), jnp.float32
    )
    y = llama.apply_rope(x, cos, sin)
    np.testing.assert_allclose(
        np.asarray(y[..., d // 2:]), np.asarray(x[..., d // 2:])
    )
    assert not np.allclose(
        np.asarray(y[..., : d // 2]), np.asarray(x[..., : d // 2])
    )


def test_glm_generation_prefill_matches_prefix_forward(cfg, params):
    """GLM generation prefills the prompt BIDIRECTIONALLY (the prompt
    is the prefix, and every layer's prompt k/v depends on the mask
    through the hiddens — a causal prefill would build a different
    cache from layer 2 on). Verify the bidirectional prefill's logits
    equal the prefix-LM forward's last-position logits, and that a
    causal prefill does NOT — the regression a multi-layer network
    catches but a 1-layer one wouldn't."""
    t0 = 24
    prompt = jax.random.randint(
        jax.random.PRNGKey(7), (2, t0), 8, cfg.vocab_size
    )
    want = glm.prefix_lm_forward(
        params, prompt, dataclasses.replace(cfg, block_size=t0),
        prefix_len=t0,
    )[:, -1]
    cache = generate._cache_for(cfg, 2, t0, cfg.n_kv_head)
    assert cfg.prefix_lm  # generate.sample picks causal=False itself
    got, _ = generate.llama_prefill(
        params, cache, prompt, cfg, causal=False
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=1e-3
    )
    wrong, _ = generate.llama_prefill(
        params, cache, prompt, cfg, causal=True
    )
    assert not np.allclose(
        np.asarray(wrong), np.asarray(want), atol=1e-3
    )


def test_presets():
    c2, c3 = glm.chatglm2_6b(), glm.chatglm3_6b()
    assert c2.qkv_bias and c2.rotary_pct == 0.5 and c2.n_kv_head == 2
    assert c3.block_size == 8192
    # ~6.2B params at the ChatGLM2 shape.
    n = sum(
        int(np.prod(x.shape))
        for x in jax.tree.leaves(
            jax.eval_shape(
                lambda k: glm.init_params(k, c2),
                jax.random.PRNGKey(0),
            )
        )
    )
    assert 5.8e9 < n < 6.8e9


def test_rotary_permutation_equivalence():
    """ChatGLM rotates interleaved pairs; we rotate split halves.
    The converter permutes q/k head columns so the two compute the
    same function: ours(perm(x)) == perm(interleaved(x)). Since the
    same permutation lands on q and k, per-head dot products — and
    therefore attention — are unchanged."""
    from dlrover_tpu.models.hf_convert import (
        _interleaved_to_halves_perm,
    )

    t, d = 6, 16
    rot = d // 2
    cfg_like = glm.tiny(n_embd=16 * 4, n_head=4)  # head_dim 16
    cos, sin = llama.rope_table(cfg_like, t)  # [t, rot/2]
    x = np.random.default_rng(0).standard_normal((1, t, 1, d))
    x = jnp.asarray(x, jnp.float32)

    # Interleaved rotary (the ChatGLM convention) over first rot dims.
    c = np.asarray(cos)[None, :, None, :]
    s = np.asarray(sin)[None, :, None, :]
    xr = np.asarray(x[..., :rot]).reshape(1, t, 1, rot // 2, 2)
    inter = np.empty_like(xr)
    inter[..., 0] = xr[..., 0] * c - xr[..., 1] * s
    inter[..., 1] = xr[..., 1] * c + xr[..., 0] * s
    inter_full = np.concatenate(
        [inter.reshape(1, t, 1, rot), np.asarray(x[..., rot:])], -1
    )

    perm = _interleaved_to_halves_perm(rot)
    ext = np.concatenate([perm, np.arange(rot, d)])
    ours = llama.apply_rope(x[..., ext], cos, sin)
    np.testing.assert_allclose(
        np.asarray(ours), inter_full[..., ext], atol=1e-6
    )


def test_chatglm_hf_conversion_roundtrip(cfg):
    """Synthetic ChatGLM2-layout state_dict converts to a runnable
    pytree: fused qkv/biases split, fused SwiGLU split, strict
    leftover detection."""
    from dlrover_tpu.models.hf_convert import glm_params_from_hf

    rng = np.random.default_rng(1)
    E, D, I = cfg.n_embd, cfg.head_dim, cfg.intermediate
    kv = cfg.n_kv_head * D
    sd = {
        "transformer.embedding.word_embeddings.weight":
            rng.standard_normal((cfg.vocab_size, E)) * 0.02,
        "transformer.encoder.final_layernorm.weight": np.ones(E),
        "transformer.output_layer.weight":
            rng.standard_normal((cfg.vocab_size, E)) * 0.02,
    }
    for i in range(cfg.n_layer):
        p = f"transformer.encoder.layers.{i}"
        sd[f"{p}.self_attention.query_key_value.weight"] = (
            rng.standard_normal((E + 2 * kv, E)) * 0.02
        )
        sd[f"{p}.self_attention.query_key_value.bias"] = (
            rng.standard_normal(E + 2 * kv) * 0.02
        )
        sd[f"{p}.self_attention.dense.weight"] = (
            rng.standard_normal((E, E)) * 0.02
        )
        sd[f"{p}.mlp.dense_h_to_4h.weight"] = (
            rng.standard_normal((2 * I, E)) * 0.02
        )
        sd[f"{p}.mlp.dense_4h_to_h.weight"] = (
            rng.standard_normal((E, I)) * 0.02
        )
        sd[f"{p}.input_layernorm.weight"] = np.ones(E)
        sd[f"{p}.post_attention_layernorm.weight"] = np.ones(E)
    params = glm_params_from_hf(sd, cfg)
    ref = glm.init_params(jax.random.PRNGKey(0), cfg)

    def shapes(tree):
        return {
            jax.tree_util.keystr(path): leaf.shape
            for path, leaf in jax.tree_util.tree_leaves_with_path(tree)
        }

    assert shapes(params) == shapes(ref)
    tokens = jnp.zeros((1, 8), jnp.int32)
    logits = glm.forward(
        jax.tree.map(jnp.asarray, params), tokens, cfg
    )
    assert logits.shape == (1, 8, cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))
    # Unmapped tensors must refuse, not silently convert.
    sd["transformer.encoder.layers.0.mystery.weight"] = np.ones(3)
    with pytest.raises(ValueError, match="does not map"):
        glm_params_from_hf(sd, cfg)


def test_glm_hf_config_reads_rope_ratio():
    """Long-context ChatGLM checkpoints scale the rotary base via
    rope_ratio (HF: base = 10000 * rope_ratio); a converter that
    hard-codes theta=10000 would load 32k variants with wrong rotary
    frequencies (ADVICE r4). Non-standard original_rope layouts must
    refuse instead of converting wrong."""
    from types import SimpleNamespace

    from dlrover_tpu.models.hf_convert import glm_config_from_hf

    def hf_cfg(**extra):
        return SimpleNamespace(
            padded_vocab_size=64, seq_length=128, num_layers=2,
            num_attention_heads=4, multi_query_attention=True,
            multi_query_group_num=2, hidden_size=32,
            ffn_hidden_size=96, layernorm_epsilon=1e-5,
            add_qkv_bias=True, **extra,
        )

    assert glm_config_from_hf(hf_cfg()).rope_theta == 10000.0
    assert glm_config_from_hf(
        hf_cfg(rope_ratio=50.0)
    ).rope_theta == 500000.0
    with pytest.raises(ValueError, match="original_rope"):
        glm_config_from_hf(hf_cfg(original_rope=False))


@pytest.mark.slow
def test_glm_pipelines_like_llama():
    """Family completeness through the stack: a GLM-flavored config
    (qkv bias + half-dim rotary + GQA) trains through the 1F1B
    pipeline assembly with trajectory parity against its dense step —
    the bias leaves ride the same per-stage param split."""
    import functools

    import optax

    from dlrover_tpu.models.llama_pipeline import (
        make_llama_pipeline_step,
        shard_params_for_pipeline,
    )
    from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
    from dlrover_tpu.trainer.step import make_train_step, shard_batch

    cfg = glm.tiny(
        block_size=16, n_layer=4, n_embd=32, intermediate=64,
        vocab_size=64,
    )
    batches = []
    key = jax.random.PRNGKey(3)
    for _ in range(3):
        key, k = jax.random.split(key)
        tok = jax.random.randint(k, (8, 16), 0, cfg.vocab_size)
        batches.append((tok, jnp.roll(tok, -1, axis=1)))

    dense_mesh = build_mesh(
        MeshConfig(data=4), devices=jax.devices()[:4]
    )
    opt = optax.adamw(1e-2)
    params = glm.init_params(jax.random.PRNGKey(0), cfg)
    opt_state = opt.init(params)
    step = make_train_step(
        dense_mesh, functools.partial(llama.loss_fn, cfg=cfg), opt
    )
    dense_losses = []
    for tok, tgt in batches:
        tok, tgt = shard_batch(dense_mesh, tok, tgt)
        params, opt_state, m = step(params, opt_state, tok, tgt)
        dense_losses.append(float(m["loss"]))

    pipe_mesh = build_mesh(
        MeshConfig(data=2, pipe=2), devices=jax.devices()[:4]
    )
    p_params = shard_params_for_pipeline(
        pipe_mesh, glm.init_params(jax.random.PRNGKey(0), cfg)
    )
    p_opt_state = opt.init(p_params)
    p_step = make_llama_pipeline_step(pipe_mesh, cfg, opt)
    pipe_losses = []
    for tok, tgt in batches:
        p_params, p_opt_state, m = p_step(
            p_params, p_opt_state, tok, tgt
        )
        pipe_losses.append(float(m["loss"]))
    np.testing.assert_allclose(
        pipe_losses, dense_losses, rtol=2e-3, atol=2e-4
    )


@pytest.mark.parametrize("prefix_len", [17, 32])
def test_prefix_attention_with_tuned_blocks(prefix_len):
    """attn_blocks tuned at the full length: the suffix rect call
    clamps per side, the prefix square call takes the tuning only
    when its length fits cleanly (p=32 with 32-blocks) and falls
    back to defaults otherwise (p=17) — parity either way."""
    q, k, v = _qkv(jax.random.PRNGKey(9))
    got = prefix_lm_attention(
        q, k, v, prefix_len, interpret=True,
        attn_blocks=(32, 32, 32, 32),
    )
    want = prefix_lm_attention_reference(q, k, v, prefix_len)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4
    )
