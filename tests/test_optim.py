"""Optimizer suite: AGD, WSAM, 8-bit Adam + quantization kernels."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.ops.quantization import (
    dequantize_blockwise,
    dequantize_blockwise_ref,
    quantize_blockwise,
    quantize_blockwise_ref,
)
from dlrover_tpu.optim import WeightedSAM, adam_8bit, agd
from dlrover_tpu.optim.low_bit import optimizer_state_bytes


def _rosenbrock(p):
    x, y = p["x"], p["y"]
    return (1.0 - x) ** 2 + 100.0 * (y - x**2) ** 2


def _quadratic_problem(d=32, seed=0):
    rng = np.random.default_rng(seed)
    diag = jnp.asarray(rng.uniform(0.1, 10.0, size=d), jnp.float32)
    target = jnp.asarray(rng.normal(size=d), jnp.float32)

    def loss(params):
        return 0.5 * jnp.sum(diag * (params["w"] - target) ** 2)

    return loss, {"w": jnp.zeros(d)}


# -- quantization kernels ---------------------------------------------------


@pytest.mark.parametrize("shape", [(4096,), (1000,), (64, 80), (3, 7, 11)])
def test_quantize_roundtrip_matches_ref(shape):
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=shape), jnp.float32
    )
    q, s, sh = quantize_blockwise(x, block_size=256)
    qr, sr, _ = quantize_blockwise_ref(x, block_size=256)
    np.testing.assert_array_equal(q, qr)
    np.testing.assert_allclose(s, sr, rtol=1e-6)
    out = dequantize_blockwise(q, s, sh)
    ref = dequantize_blockwise_ref(qr, sr, sh)
    np.testing.assert_allclose(out, ref, rtol=1e-6)
    # quantization error bounded by scale/2 per element
    err = np.abs(np.asarray(out - x))
    bound = np.max(np.abs(np.asarray(x))) / 127.0
    assert err.max() <= bound + 1e-6


def test_quantize_under_jit():
    x = jnp.ones((2048,), jnp.float32) * 3.0

    @jax.jit
    def roundtrip(x):
        q, s, sh = quantize_blockwise(x, 512)
        return dequantize_blockwise(q, s, sh)

    np.testing.assert_allclose(roundtrip(x), x, rtol=1e-2)


# -- AGD --------------------------------------------------------------------


def test_agd_converges_rosenbrock():
    params = {"x": jnp.asarray(-1.0), "y": jnp.asarray(1.5)}
    opt = agd(learning_rate=0.05)
    state = opt.init(params)
    step = jax.jit(
        lambda p, s: _agd_step(opt, p, s)
    )
    for _ in range(1500):
        params, state = step(params, state)
    assert float(_rosenbrock(params)) < 1e-2


def _agd_step(opt, p, s):
    g = jax.grad(_rosenbrock)(p)
    u, s = opt.update(g, s, p)
    return optax.apply_updates(p, u), s


def test_agd_beats_adamw_on_quadratic():
    """BASELINE.md: AGD converges up to 1.5x faster than AdamW on
    nanoGPT; check direction on an ill-conditioned quadratic."""
    loss, p0 = _quadratic_problem()

    def run(opt, n=200):
        p, s = dict(p0), opt.init(p0)
        for _ in range(n):
            g = jax.grad(loss)(p)
            u, s = opt.update(g, s, p)
            p = optax.apply_updates(p, u)
        return float(loss(p))

    final_agd = run(agd(learning_rate=0.1))
    final_adamw = run(optax.adamw(0.1))
    assert final_agd < final_adamw


def test_agd_weight_decay_shrinks_params():
    p = {"w": jnp.ones(4)}
    opt = agd(learning_rate=0.1, weight_decay=0.5)
    s = opt.init(p)
    u, s = opt.update({"w": jnp.zeros(4)}, s, p)
    p2 = optax.apply_updates(p, u)
    assert float(jnp.max(p2["w"])) < 1.0


# -- WSAM -------------------------------------------------------------------


def test_wsam_decouple_converges():
    loss, p0 = _quadratic_problem(d=16)
    wsam = WeightedSAM(
        optax.sgd(0.05), rho=0.05, gamma=0.9, learning_rate=0.05
    )
    state = wsam.init(p0)
    step = jax.jit(wsam.make_step(jax.value_and_grad(loss)))
    p = p0
    losses = []
    for _ in range(300):
        p, state, l = step(p, state)
        losses.append(float(l))
    # SAM-family optimizers orbit the minimum at radius ~rho, so check
    # strong relative decrease rather than an absolute floor.
    assert losses[-1] < losses[0] * 0.01


def test_wsam_coupled_mode():
    loss, p0 = _quadratic_problem(d=16)
    wsam = WeightedSAM(
        optax.sgd(0.05), rho=0.05, gamma=0.9, decouple=False
    )
    state = wsam.init(p0)
    step = jax.jit(wsam.make_step(jax.value_and_grad(loss)))
    p, state, _ = step(p0, state)
    # moved toward target
    assert float(loss(p)) < float(loss(p0))


def test_wsam_decouple_requires_lr():
    with pytest.raises(ValueError):
        WeightedSAM(optax.sgd(0.1), decouple=True)


def test_wsam_rho_zero_equals_base():
    """With rho=0 the perturbation vanishes; decoupled sharpness term
    is zero, so WSAM == base optimizer exactly."""
    loss, p0 = _quadratic_problem(d=8)
    base = optax.sgd(0.1)
    wsam = WeightedSAM(base, rho=0.0, gamma=0.9, learning_rate=0.1)
    step = wsam.make_step(jax.value_and_grad(loss))
    p_wsam, _, _ = step(p0, wsam.init(p0))

    g = jax.grad(loss)(p0)
    u, _ = base.update(g, base.init(p0), p0)
    p_base = optax.apply_updates(p0, u)
    np.testing.assert_allclose(
        p_wsam["w"], p_base["w"], rtol=1e-5, atol=1e-7
    )


# -- 8-bit Adam -------------------------------------------------------------


def test_adam8bit_tracks_adam():
    loss, p0 = _quadratic_problem(d=4096)
    opt8 = adam_8bit(learning_rate=0.1, min_quantize_size=1024)
    opt32 = optax.adam(0.1)

    def run(opt):
        p, s = dict(p0), opt.init(p0)
        step = jax.jit(
            lambda p, s: _opt_step(opt, loss, p, s)
        )
        for _ in range(100):
            p, s = step(p, s)
        return float(loss(p))

    f8, f32 = run(opt8), run(opt32)
    # Both collapse the loss by >3 orders of magnitude; the 8-bit
    # variant is allowed a quantization-noise lag behind exact Adam.
    assert f8 < float(loss(p0)) * 1e-3
    assert f32 < float(loss(p0)) * 1e-3
    assert f8 < f32 * 50


def _opt_step(opt, loss, p, s):
    g = jax.grad(loss)(p)
    u, s = opt.update(g, s, p)
    return optax.apply_updates(p, u), s


def test_adam8bit_memory_savings():
    p = {"big": jnp.zeros(65536), "small": jnp.zeros(16)}
    opt = adam_8bit(min_quantize_size=1024)
    s = opt.init(p)
    actual, f32_equiv = optimizer_state_bytes(s)
    # int8 moments + scales ~ 1/4 the f32 footprint for the big leaf
    assert actual < f32_equiv * 0.35


def test_adam8bit_small_leaves_stay_f32():
    p = {"small": jnp.zeros(16)}
    opt = adam_8bit(min_quantize_size=1024)
    s = opt.init(p)
    inner = s[0]  # chain: (Adam8bitState, decay..., lr scale)
    assert inner.mu["small"].dtype == jnp.float32


# -- 4-bit Adam -------------------------------------------------------------


@pytest.mark.parametrize("signed", [True, False])
def test_quantize4_roundtrip_matches_ref(signed):
    from dlrover_tpu.ops.quantization import (
        dequantize_blockwise_4bit,
        dequantize_blockwise_4bit_ref,
        quantize_blockwise_4bit,
        quantize_blockwise_4bit_ref,
    )

    rng = np.random.default_rng(0)
    raw = rng.normal(size=(4096,))
    x = jnp.asarray(np.abs(raw) if not signed else raw, jnp.float32)
    q, s, sh = quantize_blockwise_4bit(x, 256, signed)
    qr, sr, _ = quantize_blockwise_4bit_ref(x, 256, signed)
    np.testing.assert_array_equal(q, qr)
    assert q.dtype == jnp.uint8 and q.shape == (16, 128)  # packed
    out = dequantize_blockwise_4bit(q, s, sh, signed)
    ref = dequantize_blockwise_4bit_ref(qr, sr, sh, signed)
    np.testing.assert_allclose(out, ref, rtol=1e-6)
    # error bounded by half a level per element (per-block absmax)
    levels = 7.0 if signed else 15.0
    err = np.abs(np.asarray(out - x))
    bound = np.max(np.abs(np.asarray(x))) / levels
    assert err.max() <= bound / 2 + 1e-6


def test_adam4bit_converges_and_halves_state():
    from dlrover_tpu.optim.low_bit import adam_4bit

    loss, p0 = _quadratic_problem(d=4096)
    opt4 = adam_4bit(learning_rate=0.1, min_quantize_size=1024)
    p, s = dict(p0), opt4.init(p0)
    step = jax.jit(lambda p, s: _opt_step(opt4, loss, p, s))
    for _ in range(150):
        p, s = step(p, s)
    # coarser states converge slower than 8-bit but must still
    # collapse the loss by orders of magnitude
    assert float(loss(p)) < float(loss(p0)) * 1e-2
    actual, f32_equiv = optimizer_state_bytes(s)
    # packed nibbles + scales ~ 1/7 the f32 footprint
    assert actual < f32_equiv * 0.2


def test_wsam_adaptive_perturbation_radius():
    """ASAM mode: perturbation normalized by ||abs(p)*g|| keeps
    ||e_w|| <= rho * max|p|; the unnormalized bug gave ~rho * max|p|^2."""
    loss, p0 = _quadratic_problem(d=8)
    big = {"w": p0["w"] + 100.0}  # large-magnitude params
    rho = 0.05
    wsam = WeightedSAM(
        optax.sgd(0.01), rho=rho, gamma=0.9, adaptive=True,
        learning_rate=0.01,
    )
    seen = []

    def recording_grad_fn(p):
        seen.append(p["w"])
        return jax.value_and_grad(loss)(p)

    wsam.make_step(recording_grad_fn)(big, wsam.init(big))
    assert len(seen) == 2  # original + perturbed
    e_w = seen[1] - seen[0]
    norm = float(jnp.linalg.norm(e_w))
    max_p = float(jnp.max(jnp.abs(big["w"])))
    assert 0.0 < norm <= rho * max_p * 1.01


def test_dense_adadqh_is_the_agd_core():
    """optim.adadqh is the AGD rule with delta named eps (AdaDQH is
    the family's tfplus-era name — optim/adadqh.py module doc)."""
    import jax
    import jax.numpy as jnp
    import optax

    from dlrover_tpu.optim import adadqh, agd

    params = {"w": jnp.array([0.5, -0.3, 1.2])}
    grads = {"w": jnp.array([0.1, -0.2, 0.05])}
    o1, o2 = adadqh(1e-2, eps=1e-5), agd(1e-2, delta=1e-5)
    s1, s2 = o1.init(params), o2.init(params)
    p1, p2 = params, params
    for _ in range(4):
        u1, s1 = o1.update(grads, s1, p1)
        u2, s2 = o2.update(grads, s2, p2)
        p1 = optax.apply_updates(p1, u1)
        p2 = optax.apply_updates(p2, u2)
    np.testing.assert_allclose(
        np.asarray(p1["w"]), np.asarray(p2["w"]), atol=1e-7
    )


def test_adadqh_hypergradients_branch_gating():
    """eps_hg is nonzero exactly where the eps floor is the active
    max() branch; lr_hg is the negated normalized momentum direction
    (ComputeAdaDQHHG behavior, previous-step bias corrections)."""
    import jax.numpy as jnp

    from dlrover_tpu.optim import adadqh_hypergradients

    b1, b2, lr, eps, step = 0.9, 0.999, 1e-2, 1e-2, 3
    m = jnp.array([0.5, 0.5])
    # first coord: huge curvature (adaptive branch); second: tiny
    # curvature (eps-floored branch)
    v = jnp.array([4.0, 1e-12])
    lr_hg, eps_hg = adadqh_hypergradients(
        m, v, lr, eps, b1, b2, step
    )
    t_prev = step - 1
    bc1, bc2 = 1 - b1**t_prev, 1 - b2**t_prev
    adjust = np.sqrt(bc2) / bc1
    eps_adj = eps * np.sqrt(bc2)
    assert float(eps_hg[0]) == 0.0  # adaptive branch: no eps effect
    assert float(eps_hg[1]) != 0.0
    np.testing.assert_allclose(
        float(lr_hg[0]), -adjust * 0.5 / 2.0, rtol=1e-6
    )
    np.testing.assert_allclose(
        float(lr_hg[1]), -adjust * 0.5 / eps_adj, rtol=1e-6
    )

    # both hypergradients must match central finite differences of
    # the documented update -lr * m_hat / max(sqrt(v_hat), eps)
    def update(lr_, eps_, vi):
        den = max(
            np.sqrt(float(vi)), eps_ * np.sqrt(bc2)
        )
        return -lr_ * adjust * 0.5 / den * 1.0  # m=0.5

    h = 1e-6
    for i, vi in enumerate([4.0, 1e-12]):
        np.testing.assert_allclose(
            float(lr_hg[i]),
            (update(lr + h, eps, vi) - update(lr - h, eps, vi))
            / (2 * h),
            rtol=1e-4,
        )
        np.testing.assert_allclose(
            float(eps_hg[i]),
            (update(lr, eps + h, vi) - update(lr, eps - h, vi))
            / (2 * h),
            rtol=1e-4, atol=1e-10,
        )
    # SAM term shifts lr_hg only
    delta = jnp.array([1.0, 1.0])
    lr_hg2, eps_hg2 = adadqh_hypergradients(
        m, v, lr, eps, b1, b2, step, sam_delta=delta, alpha=0.7,
    )
    np.testing.assert_allclose(
        np.asarray(lr_hg2), np.asarray(lr_hg - 0.3 * delta),
        rtol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(eps_hg2), np.asarray(eps_hg), rtol=1e-6
    )
