"""Real multi-process jax.distributed world over the agent env seam.

SURVEY §4: "use CPU jax.distributed multi-process tests for
collectives". Two actual OS processes bootstrap through
trainer/jax_env.py exactly as agent-launched trainers do (coordinator
address + process id/count from NodeEnv), then run a cross-process
collective over the global device set — the same path a multi-host
TPU pod takes over DCN.
"""

import os
import socket
import subprocess
import sys

_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
from dlrover_tpu.trainer import jax_env
jax_env.setup_distributed()
import jax
import jax.numpy as jnp
import numpy as np
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4, len(jax.devices())  # 2 local x 2 procs
from jax.experimental import multihost_utils
mine = np.array([jax.process_index() + 1.0], np.float32)
world = multihost_utils.process_allgather(mine)
np.testing.assert_array_equal(world.ravel(), [1.0, 2.0])

# A sharded computation over the GLOBAL mesh: psum of per-device ones.
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(np.array(jax.devices()).reshape(4), ("data",))
sharding = NamedSharding(mesh, P("data"))
local = jnp.ones((2,), jnp.float32) * (jax.process_index() + 1)
garr = jax.make_array_from_process_local_data(sharding, np.asarray(local), (4,))
total = jax.jit(
    lambda x: jnp.sum(x), in_shardings=sharding, out_shardings=NamedSharding(mesh, P())
)(garr)
# procs contribute [1,1] and [2,2] -> global sum 6
assert float(total) == 6.0, float(total)
jax_env.teardown_distributed()
print("WORKER_OK", jax.process_index())
"""


def test_two_process_world_collective(tmp_path):
    port = _free_port()
    env_base = {
        **os.environ,
        "DLROVER_TPU_COORDINATOR_ADDR": f"127.0.0.1:{port}",
        "DLROVER_TPU_NUM_PROCESSES": "2",
    }
    env_base.pop("JAX_PLATFORMS", None)
    procs = []
    for pid in range(2):
        env = {**env_base, "DLROVER_TPU_PROCESS_ID": str(pid)}
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _WORKER],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                cwd=os.path.dirname(os.path.dirname(__file__)),
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=150)
            outs.append(out)
    finally:
        for p in procs:  # never orphan workers on timeout/failure
            if p.poll() is None:
                p.kill()
                p.communicate()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"WORKER_OK {pid}" in out


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
