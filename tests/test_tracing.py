"""Cross-plane distributed tracing (ISSUE 13): trace-context
propagation, the master-side trace store, the serving router's trace
assembly with TTFT phase attribution, and the hot-loop overhead
tripwires.

The serving/remediation end-to-end half of this PR's acceptance lives
in ``tools/serve_drill.py`` (run by tests/test_serving.py): the
SIGKILL drill asserts a requeued request's trace shows >=2 replica
hops with monotonic non-overlapping phase spans, the phase histograms
sum to observed TTFT, and the drain decision's trace links
verdict -> drain -> requeue — all fetched via ``query_traces``.
"""

import gc
import random
import threading
import time

import pytest

from dlrover_tpu import obs
from dlrover_tpu.obs import tracer
from dlrover_tpu.obs.trace_store import TraceStore, span_tree


@pytest.fixture()
def seeded_ids():
    """Deterministic trace/span ids (the injectable RNG seam — no
    wall-clock analogue anywhere in id minting)."""
    prev = tracer.set_id_source(tracer.IdSource(random.Random(7)))
    yield
    tracer.set_id_source(prev)


@pytest.fixture()
def live_tracer():
    tr = obs.configure_tracer()
    yield tr
    obs.disable_tracer()


class TestTraceContext:
    def test_ids_are_deterministic_hex(self, seeded_ids):
        a = tracer.new_trace_context()
        assert len(a.trace_id) == 32 and len(a.span_id) == 16
        int(a.trace_id, 16), int(a.span_id, 16)  # valid hex
        tracer.set_id_source(tracer.IdSource(random.Random(7)))
        b = tracer.new_trace_context()
        assert (a.trace_id, a.span_id) == (b.trace_id, b.span_id)

    def test_inject_extract_roundtrip(self, seeded_ids):
        ctx = tracer.new_trace_context().child()
        with tracer.activate(ctx):
            carrier = tracer.inject()
        back = tracer.extract(carrier)
        assert back.trace_id == ctx.trace_id
        assert back.span_id == ctx.span_id
        assert back.parent_span_id == ctx.parent_span_id

    def test_extract_tolerates_garbage(self):
        for bad in (None, {}, [], "x", {"trace_id": ""},
                    {"span_id": "only"}, {"trace_id": "t"}):
            assert tracer.extract(bad) is None

    def test_activation_is_scoped_and_nested(self, seeded_ids):
        assert tracer.current_context() is None
        outer = tracer.new_trace_context()
        with tracer.activate(outer):
            assert tracer.current_context() is outer
            inner = outer.child()
            with tracer.activate(inner):
                assert tracer.current_context() is inner
                assert inner.parent_span_id == outer.span_id
            assert tracer.current_context() is outer
        assert tracer.current_context() is None
        assert tracer.inject() is None

    def test_spans_chain_span_ids(self, seeded_ids, live_tracer):
        root = tracer.new_trace_context()
        with tracer.activate(root):
            with obs.span("serve.hop"):
                with obs.span("serve.prefill"):
                    pass
        events = {e["name"]: e for e in live_tracer.events()}
        hop, prefill = events["serve.hop"], events["serve.prefill"]
        assert hop["trace_id"] == root.trace_id
        assert hop["parent_span_id"] == root.span_id
        assert prefill["parent_span_id"] == hop["span_id"]

    def test_point_events_tag_current_span(
        self, seeded_ids, live_tracer
    ):
        root = tracer.new_trace_context()
        with tracer.activate(root):
            obs.event("serve.requeue", request_id="r1")
        ev = live_tracer.events()[-1]
        assert ev["trace_id"] == root.trace_id
        assert ev["parent_span_id"] == root.span_id

    def test_no_context_no_trace_tags(self, live_tracer):
        with obs.span("trainer.step_phases"):
            obs.event("trainer.compile")
        for ev in live_tracer.events():
            assert "trace_id" not in ev


class TestThreadStateBounded:
    """The PR's tracer fix: per-thread stacks must not accumulate
    for dead threads — a churny replica/supervisor thread pool ran
    spans on thousands of short-lived threads and the old
    threading.local could strand state until interpreter exit."""

    def test_span_stacks_pruned_after_thread_churn(self, live_tracer):
        def work(i):
            with obs.span("serve.hop", i=i):
                pass

        for batch in range(8):
            threads = [
                threading.Thread(target=work, args=(batch * 32 + j,))
                for j in range(32)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        # Balanced span usage deletes entries eagerly: nothing may
        # remain for the 256 dead threads.
        assert len(live_tracer._stacks) == 0, live_tracer._stacks
        assert len(tracer._ctx_stacks) == 0, tracer._ctx_stacks

    @staticmethod
    def _dead_threads(n):
        ts = [threading.Thread(target=lambda: None) for _ in range(n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return ts

    def test_stacks_orphaned_mid_span_are_swept(self, live_tracer):
        """A thread dying INSIDE a span leaks its stack entry; the
        high-water-mark sweep must reclaim it."""
        from dlrover_tpu.obs.tracer import _STACKS_SWEEP_AT

        dead = self._dead_threads(_STACKS_SWEEP_AT)
        with live_tracer._stacks_lock:
            for t in dead:
                live_tracer._stacks[t] = ["serve.hop"]
        assert len(live_tracer._stacks) >= _STACKS_SWEEP_AT

        def spin_one_span():
            with obs.span("serve.queue"):
                pass

        # A fresh thread's first span creates a new stack entry,
        # which triggers the sweep past the high-water mark.
        t = threading.Thread(target=spin_one_span)
        t.start()
        t.join()
        assert not any(
            d in live_tracer._stacks for d in dead
        ), live_tracer._stacks
        assert len(live_tracer._stacks) <= 1

    def test_activation_stacks_orphans_swept_too(self):
        from dlrover_tpu.obs.tracer import _STACKS_SWEEP_AT

        ctx = tracer.new_trace_context()
        dead = self._dead_threads(_STACKS_SWEEP_AT)
        with tracer._ctx_lock:
            for t in dead:
                tracer._ctx_stacks[t] = [ctx]

        def activate_once():
            with tracer.activate(tracer.new_trace_context()):
                pass

        t = threading.Thread(target=activate_once)
        t.start()
        t.join()
        assert not any(d in tracer._ctx_stacks for d in dead)
        assert len(tracer._ctx_stacks) == 0

    def test_recycled_ident_cannot_inherit_context(self):
        """Thread-OBJECT keys close the ident-recycling hazard: a new
        thread must never see a dead thread's leftover context, even
        when the OS hands it the same ident (simulated directly —
        real ident reuse is nondeterministic)."""
        orphan = threading.Thread(target=lambda: None)
        orphan.start()
        orphan.join()
        with tracer._ctx_lock:
            tracer._ctx_stacks[orphan] = [tracer.new_trace_context()]
        try:
            seen = []

            def probe():
                seen.append(tracer.current_context())

            t = threading.Thread(target=probe)
            t.start()
            t.join()
            assert seen == [None]
        finally:
            with tracer._ctx_lock:
                tracer._ctx_stacks.pop(orphan, None)


class TestOverheadTripwire:
    """With tracing OFF (the production serving hot loop default),
    inject()/extract()/span()/event() must stay allocation-light:
    shared no-op objects and None returns, nothing minted per call."""

    def test_disabled_fast_paths_return_shared_objects(self):
        obs.disable_tracer()
        assert obs.span("serve.hop") is obs.span("serve.decode")
        assert obs.event("serve.submit") is None
        assert tracer.inject() is None
        assert tracer.extract(None) is None

    def test_disabled_hot_loop_is_allocation_light(self):
        import tracemalloc

        obs.disable_tracer()
        # Warm every lazy path first.
        for _ in range(100):
            tracer.inject()
            with obs.span("serve.decode"):
                pass
            obs.event("serve.tick")
        gc.collect()
        tracemalloc.start()
        for _ in range(5000):
            tracer.inject()
            with obs.span("serve.decode"):
                pass
            obs.event("serve.tick")
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        # 5000 iterations of the full disabled surface must not
        # allocate per-call state: peak transient footprint stays
        # far under one object per iteration (64 KiB is ~13 bytes
        # per iteration of slack, vs >100 B per minted context).
        assert peak < 65536, f"disabled tracing allocated {peak}B"


class TestTraceStore:
    def test_bounded_retention_evicts_oldest(self):
        store = TraceStore(max_traces=4)
        for i in range(10):
            store.add_span(f"t{i}", "serve.request", float(i), 1.0)
        assert len(store) == 4
        assert store.get("t5") is None
        assert store.get("t9") is not None

    def test_span_cap_counts_drops(self):
        store = TraceStore(max_spans_per_trace=3)
        for i in range(5):
            ok = store.add_span("t", "serve.hop", float(i), 0.1)
            assert ok == (i < 3)
        tl = store.get("t")
        assert len(tl["spans"]) == 3 and tl["dropped_spans"] == 2

    def test_subject_index_and_query(self):
        store = TraceStore()
        store.add_span(
            "ta", "serve.request", 1.0, 2.0, request_id="req-9"
        )
        store.add_span(
            "tb", "remediation.decision", 3.0, node_id=4000001
        )
        assert [t["trace_id"] for t in store.query(subject="req-9")] \
            == ["ta"]
        assert [
            t["trace_id"]
            for t in store.query(subject="node:4000001")
        ] == ["tb"]
        assert store.query(trace_id="tb")[0]["subjects"] == [
            "node:4000001"
        ]
        assert store.query(subject="nope") == []
        assert len(store.query(limit=1)) == 1

    def test_add_event_tracer_shape(self):
        """The snapshot channel's payload: tracer event dicts with
        trace ids become spans; untagged events are ignored; process
        tags (pid/role/rank) are stripped from span tags."""
        store = TraceStore()
        assert not store.add_event({"name": "trainer.step", "ts": 1})
        n = store.add_events(
            [
                {
                    "name": "ckpt.save", "ts": 5.0, "dur_s": 0.5,
                    "trace_id": "tc", "span_id": "s1",
                    "pid": 1, "role": "worker", "rank": 0,
                    "step": 12,
                },
                {"name": "no.trace", "ts": 6.0},
            ]
        )
        assert n == 1
        span = store.get("tc")["spans"][0]
        assert span["tags"] == {"step": 12}
        assert span["dur_s"] == 0.5

    def test_span_tree_depths_and_orphans(self):
        store = TraceStore()
        store.add_span("t", "serve.request", 0.0, 5.0, span_id="A")
        store.add_span(
            "t", "serve.hop", 1.0, 2.0, span_id="B",
            parent_span_id="A",
        )
        store.add_span(
            "t", "serve.prefill", 1.2, 0.5, parent_span_id="B"
        )
        store.add_span(
            "t", "serve.orphan", 0.5, 0.1, parent_span_id="GONE"
        )
        tree = span_tree(store.get("t"))
        depth = {s["name"]: s["depth"] for s in tree}
        assert depth == {
            "serve.request": 0, "serve.hop": 1,
            "serve.prefill": 2, "serve.orphan": 0,
        }


class TestRpcPropagation:
    def test_context_rides_the_envelope(self, seeded_ids):
        """An active client-side context is re-activated on the
        server's handler thread — the cross-process half of every
        propagation path (MasterClient -> servicer, replica ->
        router)."""
        from dlrover_tpu.common import messages as msg
        from dlrover_tpu.common.comm import (
            RpcClient,
            RpcDispatcher,
            RpcServer,
        )

        seen = []
        dispatcher = RpcDispatcher()

        def handler(req):
            ctx = tracer.current_context()
            seen.append(
                (ctx.trace_id, ctx.span_id) if ctx else None
            )
            return msg.KVStoreGetResponse(found=True, value=b"v")

        dispatcher.register_get(msg.KVStoreGetRequest, handler)
        server = RpcServer(dispatcher, port=0)
        server.start()
        client = RpcClient(server.addr)
        try:
            client.get(msg.KVStoreGetRequest(key="k"))
            assert seen[-1] is None  # no active trace -> no context
            ctx = tracer.new_trace_context()
            with tracer.activate(ctx):
                client.get(msg.KVStoreGetRequest(key="k"))
            assert seen[-1] == (ctx.trace_id, ctx.span_id)
        finally:
            client.close()
            server.stop(0)

    def test_old_decoder_drops_the_envelope_field(self):
        """Forward compatibility: a decoder without trace support
        (messages.deserialize) reads a trace-carrying payload as the
        plain message."""
        from dlrover_tpu.common import messages as msg

        data = msg.serialize(
            msg.KVStoreGetRequest(key="k"),
            trace={"trace_id": "t" * 32, "span_id": "s" * 16},
        )
        decoded = msg.deserialize(data)
        assert isinstance(decoded, msg.KVStoreGetRequest)
        assert decoded.key == "k"
        again, trace = msg.deserialize_with_trace(data)
        assert again.key == "k"
        assert trace["trace_id"] == "t" * 32


class TestRouterTraceAssembly:
    """The router's server-side assembly with a fake clock: hops,
    queue intervals, phase spans, and the TTFT phase decomposition
    that feeds dlrover_serve_ttft_phase_seconds."""

    def _router(self, store):
        from dlrover_tpu.serving.router import ServingRouter

        clk = [100.0]
        router = ServingRouter(
            clock=lambda: clk[0],
            config={"progress_timeout_s": 5.0},
            trace_sink=store,
        )
        return router, clk

    def test_requeued_request_timeline(self, seeded_ids):
        store = TraceStore()
        router, clk = self._router(store)
        router.register_replica(4000000)
        router.register_replica(4000001)
        rid = router.submit([1, 2, 3], max_new_tokens=4)
        trace_id = router.trace_of(rid)
        assert trace_id
        clk[0] += 1.0  # 1s queued
        assert router.pull(4000000, max_items=1)
        clk[0] += 2.0  # 2s on the doomed replica
        assert router.drain_replica(4000000, reason="test") == 1
        clk[0] += 0.5  # 0.5s requeue wait
        assert router.pull(4000001, max_items=1)
        clk[0] += 3.0
        router.complete(
            4000001, rid, [7, 8, 9, 10],
            ttft_s=0.35, tpot_s=0.01, finish_reason="length",
            phases={
                "dispatch": 0.05, "prefill": 0.3,
                "first_decode": 0.05, "decode": 1.0,
            },
        )
        res = router.result(rid)
        assert res["state"] == "done" and res["requeues"] == 1
        # queue = 1.0 (initial) + 0.5 (requeue wait)
        ph = res["phases"]
        assert ph["queue"] == pytest.approx(1.5)
        assert ph["ttft_total"] == pytest.approx(
            1.5 + 0.05 + 0.3 + 0.05
        )
        tl = store.get(trace_id)
        names = [s["name"] for s in tl["spans"]]
        assert names.count("serve.hop") == 2
        assert names.count("serve.queue") == 2
        hops = [s for s in tl["spans"] if s["name"] == "serve.hop"]
        assert {h["tags"]["replica_id"] for h in hops} == {
            4000000, 4000001,
        }
        assert {h["tags"]["end"] for h in hops} == {
            "requeue", "done",
        }
        # Phase spans are sequential and non-overlapping.
        phases = sorted(
            (
                s for s in tl["spans"]
                if s["name"] in (
                    "serve.dispatch", "serve.prefill",
                    "serve.first_token", "serve.decode",
                )
            ),
            key=lambda s: s["start_ts"],
        )
        assert [s["name"] for s in phases] == [
            "serve.dispatch", "serve.prefill",
            "serve.first_token", "serve.decode",
        ]
        for prev, cur in zip(phases, phases[1:]):
            assert cur["start_ts"] == pytest.approx(
                prev["start_ts"] + prev["dur_s"]
            )
        # Root covers submit -> done; request id is a query subject.
        root = next(
            s for s in tl["spans"] if s["name"] == "serve.request"
        )
        assert root["dur_s"] == pytest.approx(6.5)
        assert store.query(subject=rid)[0]["trace_id"] == trace_id
        # Worst-trace surface for obs_report --serving.
        worst = router.snapshot()["worst_ttft"]
        assert worst["request_id"] == rid
        assert worst["ttft_total_s"] == pytest.approx(1.9)

    def test_drain_link_records_requeues_in_decision_trace(
        self, seeded_ids
    ):
        store = TraceStore()
        router, clk = self._router(store)
        router.register_replica(4000000)
        rid = router.submit([1, 2], max_new_tokens=2)
        assert router.pull(4000000, max_items=1)
        link = (tracer.new_trace_id(), tracer.new_span_id())
        router.drain_replica(4000000, reason="verdict", link=link)
        tl = store.get(link[0])
        assert tl is not None
        requeue = next(
            s for s in tl["spans"] if s["name"] == "serve.requeue"
        )
        assert requeue["tags"]["request_id"] == rid
        assert requeue["tags"]["link_trace_id"] == router.trace_of(
            rid
        )
        assert requeue["parent_span_id"] == link[1]

    def test_adopted_caller_context_wins(self, seeded_ids):
        """A submit arriving inside an RPC-propagated context joins
        the CALLER's trace instead of minting a new one."""
        store = TraceStore()
        router, _ = self._router(store)
        caller = tracer.new_trace_context()
        with tracer.activate(caller):
            rid = router.submit([1], max_new_tokens=1)
        assert router.trace_of(rid) == caller.trace_id


class TestRendezvousRoundTrace:
    def test_every_round_traced_even_with_leftover_waiters(
        self, seeded_ids
    ):
        """A freeze that leaves surplus waiters seeds the next round
        with a NON-empty waiting set; that churn round must still
        mint its own trace (the original mint condition — empty
        waiting set — silently skipped it)."""
        from dlrover_tpu.master.rendezvous import ElasticRendezvous

        store = TraceStore()
        rdzv = ElasticRendezvous()
        rdzv.trace_sink = store
        rdzv.update_params(
            min_nodes=2, max_nodes=2, waiting_timeout=30.0
        )
        for rank in (0, 1, 2):  # one surplus waiter
            rdzv.join(rank, 1)
        _, _, world = rdzv.get_comm_world(0)
        assert sorted(world) == [0, 1]
        # Round 1 starts with rank 2 ALREADY waiting when member 0
        # rejoins (restart invalidates the frozen world) — the churn
        # case whose trace the empty-set mint condition missed.
        rdzv.join(0, 1)
        _, _, world2 = rdzv.get_comm_world(0)
        assert sorted(world2) == [0, 2]
        rounds = store.query(subject="rdzv:elastic-training")
        assert len(rounds) == 2
        assert len({t["trace_id"] for t in rounds}) == 2
        got = [
            t["spans"][0]["tags"]["round"] for t in rounds
        ]
        assert got == [0, 1]


class TestSchedulerPhases:
    """Replica-side TTFT decomposition on the real jitted scheduler:
    the reported phases must reconstruct ttft_s exactly (dispatch +
    prefill + first_decode spans admit -> first token)."""

    @pytest.fixture(scope="class")
    def model(self):
        from dlrover_tpu.serving.replica import build_tiny_model

        return build_tiny_model(0, block_size=64)

    def test_phases_sum_to_ttft(self, model):
        from dlrover_tpu.serving.scheduler import (
            ContinuousBatchingScheduler,
            ServeRequest,
        )

        params, cfg = model
        sched = ContinuousBatchingScheduler(
            params, cfg, lanes=2, max_len=32, block_size=8,
            prefill_chunk=8,
        )
        sched.submit(
            ServeRequest(
                request_id="r1", prompt=[3, 1, 4, 1, 5],
                max_new_tokens=4,
                trace={"trace_id": "t" * 32, "span_id": "s" * 16},
            )
        )
        done = []
        for _ in range(64):
            done.extend(sched.step())
            if done:
                break
        assert [c.request_id for c in done] == ["r1"]
        c = done[0]
        assert set(c.phases) == {
            "dispatch", "prefill", "first_decode", "decode",
        }
        for v in c.phases.values():
            assert v >= 0.0
        assert c.phases["prefill"] + c.phases["first_decode"] == \
            pytest.approx(c.ttft_s, abs=2e-6)
        assert c.phases["decode"] <= c.wall_s

    def test_wire_roundtrip_keeps_trace(self):
        from dlrover_tpu.serving.scheduler import ServeRequest

        req = ServeRequest(
            request_id="r", prompt=[1],
            trace={"trace_id": "t" * 32, "span_id": "s" * 16},
        )
        back = ServeRequest.from_dict(req.to_dict())
        assert back.trace == req.trace
