"""Fleet health plane: time-series store, detectors, SLO verdicts.

Covers the acceptance criteria of the health-plane PR:

* the bounded time-series store keeps full-resolution recent history,
  downsamples older samples into coarse buckets, and answers windowed
  mean/percentile/rate/robust-slope queries with an injectable clock;
* every detector fires on its synthetic signature and stays silent on
  a healthy control, hermetically (fake clock, no sleeps);
* the simulated-fleet drill: a real in-process JobMaster fed fake
  agent snapshots with one slow host and one data-starved host emits
  the right verdicts with evidence windows, auto-queues PROFILE on
  the slow host's heartbeat FIFO, serves them via ``query_health`` /
  ``/healthz`` / ``dlrover_job_health_score`` / ``obs_report
  --health``, persists the channel to the brain datastore — and
  convicts nothing on the healthy control host.
"""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import RpcClient
from dlrover_tpu.common.constants import EventAction
from dlrover_tpu.master.master import JobMaster
from dlrover_tpu.obs.exposition import MetricsHTTPServer
from dlrover_tpu.obs.health import (
    SEVERITY_CRITICAL,
    SEVERITY_WARN,
    HealthMonitor,
    render_health,
)
from dlrover_tpu.obs.timeseries import TimeSeriesStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class TestTimeSeriesStore:
    def test_window_stats(self):
        clk = FakeClock(100.0)
        store = TimeSeriesStore(clock=clk)
        for i in range(10):
            store.record("s", float(i), ts=10.0 * i, host="a")
        clk.t = 95.0
        stats = store.query("s", 50.0, host="a")
        # window [45, 95] -> samples 5..9
        assert stats.count == 5
        assert stats.mean == 7.0
        assert stats.minimum == 5.0 and stats.maximum == 9.0
        assert stats.p50 == 7.0
        baseline = store.query("s", 50.0, end_offset_s=50.0, host="a")
        # window [-5, 45] -> samples 0..4
        assert baseline.count == 5 and baseline.mean == 2.0

    def test_labels_separate_series(self):
        store = TimeSeriesStore(clock=FakeClock())
        store.record("s", 1.0, ts=10.0, host="a")
        store.record("s", 9.0, ts=10.0, host="b")
        assert store.query("s", host="a").mean == 1.0
        assert store.query("s", host="b").mean == 9.0
        assert store.series_labels("s") == [
            {"host": "a"}, {"host": "b"}
        ]

    def test_ring_retention_downsamples_old_points(self):
        store = TimeSeriesStore(
            raw_points=4, coarse_points=8, coarse_resolution=10.0,
            clock=FakeClock(1000.0),
        )
        # 2 samples per 10s bucket over 80s; the raw ring keeps the
        # newest 4 full-resolution, older ones fold to bucket means.
        for i in range(16):
            store.record("s", float(i), ts=5.0 * i)
        pts = store.points("s")
        raw_tail = pts[-4:]
        assert [v for _, v in raw_tail] == [12.0, 13.0, 14.0, 15.0]
        # Folded region: bucket means of consecutive pairs
        # ((0+1)/2, (2+3)/2, ...), one point per 10s bucket.
        folded = pts[:-4]
        assert [v for _, v in folded] == [0.5, 2.5, 4.5, 6.5, 8.5, 10.5]
        # Windowed query still spans both regions seamlessly.
        assert store.query("s").count == 10

    def test_rate_and_counter_reset(self):
        store = TimeSeriesStore(clock=FakeClock(100.0))
        store.record("c", 10.0, ts=0.0)
        store.record("c", 70.0, ts=60.0)
        assert store.rate("c", 200.0) == pytest.approx(1.0)
        # Counter reset (process restart) must not read negative.
        store.record("c", 5.0, ts=80.0)
        assert store.rate("c", 200.0) is None

    def test_slope_is_outlier_robust(self):
        store = TimeSeriesStore(clock=FakeClock(100.0))
        for i in range(20):
            v = 1.0 if i != 10 else 500.0  # one spike
            store.record("flat", v, ts=5.0 * i)
            store.record("ramp", 0.5 * i, ts=5.0 * i)
        assert store.slope("flat", 200.0) == pytest.approx(0.0)
        assert store.slope("ramp", 200.0) == pytest.approx(0.1)

    def test_bad_samples_ignored_and_series_bounded(self):
        store = TimeSeriesStore(max_series=2, clock=FakeClock())
        store.record("s", float("nan"), ts=1.0, host="a")
        store.record("s", "garbage", ts=1.0, host="a")
        assert store.query("s", host="a") is None
        store.record("s", 1.0, ts=1.0, host="a")
        store.record("s", 1.0, ts=1.0, host="b")
        store.record("s", 1.0, ts=1.0, host="c")  # over the bound
        assert store.size() == 2
        assert store.query("s", host="c") is None

    def test_drop_label_forgets_departed_host(self):
        store = TimeSeriesStore(clock=FakeClock())
        store.record("s1", 1.0, ts=1.0, host="a")
        store.record("s2", 2.0, ts=1.0, host="a")
        store.record("s1", 3.0, ts=1.0, host="b")
        store.drop_label("host", "a")
        assert store.query("s1", host="a") is None
        assert store.query("s2", host="a") is None
        assert store.query("s1", host="b").mean == 3.0


def make_monitor(clk, store, **kw):
    config = {
        "window_s": 60.0,
        "min_points": 3.0,
        "goodput_grace_s": 0.0,
    }
    config.update(kw.pop("config", {}))
    return HealthMonitor(store, clock=clk, config=config, **kw)


def feed_steps(store, host, fn, t0=900.0, n=40, dt=5.0):
    for i in range(n):
        t = t0 + i * dt
        store.record("host.step_time", fn(t), ts=t, host=host)


class TestDetectors:
    def setup_method(self):
        self.clk = FakeClock(1095.0)
        self.store = TimeSeriesStore(clock=self.clk)

    def test_degradation_fires_on_ramp_not_on_healthy(self):
        feed_steps(
            self.store, "slow",
            lambda t: 0.1 if t < 1000 else 0.1 * (1 + (t - 1000) / 30.0),
        )
        feed_steps(self.store, "ok", lambda t: 0.1)
        mon = make_monitor(self.clk, self.store)
        verdicts = mon.evaluate_once()
        assert [
            (v.detector, v.host, v.severity) for v in verdicts
        ] == [("throughput_degradation", "slow", SEVERITY_CRITICAL)]
        v = verdicts[0]
        assert v.suggested_action == EventAction.PROFILE.value
        assert len(v.evidence) >= 3
        assert v.metrics["ratio"] > 1.8

    def test_degradation_needs_min_points(self):
        feed_steps(
            self.store, "slow", lambda t: 0.5, t0=1080.0, n=2
        )
        mon = make_monitor(self.clk, self.store)
        assert mon.evaluate_once() == []

    def test_goodput_slo_breach_and_grace(self):
        for i in range(20):
            self.store.record(
                "goodput.ratio", 0.5, ts=1000.0 + 5 * i
            )
        mon = make_monitor(self.clk, self.store)
        (v,) = mon.evaluate_once()
        assert v.detector == "goodput_slo"
        assert v.severity == SEVERITY_WARN
        # Same data inside the startup grace period: no verdict.
        fresh = make_monitor(
            self.clk, TimeSeriesStore(clock=self.clk),
            config={"goodput_grace_s": 1e9},
        )
        fresh.store.record("goodput.ratio", 0.5, ts=1090.0)
        assert fresh.evaluate_once() == []

    def test_goodput_critical_floor(self):
        for i in range(20):
            self.store.record(
                "goodput.ratio", 0.3, ts=1000.0 + 5 * i
            )
        mon = make_monitor(self.clk, self.store)
        (v,) = mon.evaluate_once()
        assert (v.detector, v.severity) == (
            "goodput_slo", SEVERITY_CRITICAL
        )

    def test_data_starvation_from_cumulative_counter(self):
        feed_steps(self.store, "h", lambda t: 0.1)
        total = 0.0
        for i in range(20):
            t = 1000.0 + 5 * i
            total += 5 * 0.6  # blocked 60% of wall time
            self.store.record("host.data_wait_s", total, ts=t, host="h")
        mon = make_monitor(self.clk, self.store)
        verdicts = [
            v for v in mon.evaluate_once()
            if v.detector == "data_starvation"
        ]
        assert [(v.host, v.severity) for v in verdicts] == [
            ("h", SEVERITY_CRITICAL)
        ]
        assert verdicts[0].metrics["data_wait_frac"] == pytest.approx(
            0.6
        )

    def test_recompile_storm(self):
        feed_steps(self.store, "h", lambda t: 0.1)
        for i in range(20):
            # 1 compile per 5s tick = 12/min: storm-critical.
            self.store.record(
                "host.compiles", float(i), ts=1000.0 + 5 * i, host="h"
            )
        mon = make_monitor(self.clk, self.store)
        verdicts = [
            v for v in mon.evaluate_once()
            if v.detector == "recompile_storm"
        ]
        assert [(v.host, v.severity) for v in verdicts] == [
            ("h", SEVERITY_CRITICAL)
        ]

    def test_rss_growth_fires_on_ramp_not_on_step_jump(self):
        for i in range(40):
            t = 900.0 + 5 * i
            self.store.record(
                "host.memory_mb", 1000.0 + (t - 900.0) * 2.0,
                ts=t, host="leak",
            )
            # One-off allocation jump then flat: NOT a leak.
            self.store.record(
                "host.memory_mb",
                1000.0 if t < 1000.0 else 1400.0,
                ts=t, host="jump",
            )
        mon = make_monitor(self.clk, self.store)
        verdicts = [
            v for v in mon.evaluate_once()
            if v.detector == "rss_growth"
        ]
        assert [v.host for v in verdicts] == ["leak"]
        assert verdicts[0].suggested_action == (
            EventAction.DIAGNOSE.value
        )

    def test_straggler_persistence_needs_consecutive_ticks(self):
        class FakeSpeed:
            def __init__(self):
                self.slow = [7]

            def straggler_scores(self):
                return {7: 2.5}

            def stragglers(self):
                return list(self.slow)

        speed = FakeSpeed()
        mon = make_monitor(self.clk, self.store, speed_monitor=speed)
        assert mon.evaluate_once() == []  # tick 1
        assert mon.evaluate_once() == []  # tick 2
        (v,) = mon.evaluate_once()  # tick 3 = warn threshold
        assert (v.detector, v.node_id, v.severity) == (
            "straggler_persistence", 7, SEVERITY_WARN
        )
        for _ in range(3):
            verdicts = mon.evaluate_once()
        assert verdicts[0].severity == SEVERITY_CRITICAL  # tick 6
        # Recovery resets the streak.
        speed.slow = []
        assert mon.evaluate_once() == []
        speed.slow = [7]
        assert mon.evaluate_once() == []  # back to tick 1

    def test_heartbeat_gap_thresholds(self):
        ages = {"value": {}}
        mon = make_monitor(
            self.clk, self.store,
            heartbeat_timeout=180.0,
            heartbeat_ages=lambda: dict(ages["value"]),
        )
        ages["value"] = {1: 10.0}
        assert mon.evaluate_once() == []
        ages["value"] = {1: 100.0}  # 55% of timeout
        (v,) = mon.evaluate_once()
        assert (v.detector, v.node_id, v.severity) == (
            "heartbeat_gap", 1, SEVERITY_WARN
        )
        assert v.suggested_action == ""  # can't action a silent node
        ages["value"] = {1: 160.0}  # 89%
        (v,) = mon.evaluate_once()
        assert v.severity == SEVERITY_CRITICAL

    def test_lifecycle_resolution_score_and_history(self):
        feed_steps(
            self.store, "slow",
            lambda t: 0.1 if t < 1000 else 0.1 * (1 + (t - 1000) / 30.0),
        )
        mon = make_monitor(self.clk, self.store)
        mon.evaluate_once()
        assert mon.health_score() == pytest.approx(0.7)
        assert mon.critical_count() == 1
        assert not mon.healthz_payload()["ok"]
        # Same verdict again: active, but NOT a second transition.
        mon.evaluate_once()
        assert len(mon.history()) == 1
        # The host heals: verdict resolves, score recovers.
        feed_steps(self.store, "slow", lambda t: 0.1, t0=1100.0)
        self.clk.t = 1295.0
        assert mon.evaluate_once() == []
        assert mon.health_score() == 1.0
        assert mon.healthz_payload()["ok"]
        history = mon.history()
        assert len(history) == 2
        assert history[-1].resolved
        assert history[-1].severity == "info"

    def test_action_cooldown(self):
        feed_steps(
            self.store, "slow",
            lambda t: 0.1 if t < 1000 else 0.1 * (1 + (t - 1000) / 30.0),
        )
        actions = []
        mon = make_monitor(
            self.clk, self.store,
            action_sink=lambda n, a: actions.append((n, a)),
            config={"action_cooldown_s": 1000.0},
            fleet=type(
                "F", (),
                {"node_for_host": staticmethod(lambda h: 3),
                 "aggregates": staticmethod(dict)},
            )(),
        )
        mon.evaluate_once()
        assert actions == [(3, "profile")]
        # Resolve (healthy data) then immediately re-convict: inside
        # the cooldown the action must NOT be queued again.
        feed_steps(self.store, "slow", lambda t: 0.1, t0=1100.0)
        self.clk.t = 1295.0
        mon.evaluate_once()
        feed_steps(
            self.store, "slow",
            lambda t: 0.1 if t < 1400.0 else 0.1 * (1 + (t - 1400.0) / 30.0),
            t0=1300.0,
        )
        self.clk.t = 1495.0
        verdicts = mon.evaluate_once()
        assert [v.severity for v in verdicts] == [SEVERITY_CRITICAL]
        assert actions == [(3, "profile")]

    def test_broken_detector_does_not_silence_the_rest(self):
        feed_steps(
            self.store, "slow",
            lambda t: 0.1 if t < 1000 else 0.1 * (1 + (t - 1000) / 30.0),
        )
        mon = make_monitor(self.clk, self.store)

        def boom():
            raise RuntimeError("broken detector")

        mon.detectors.insert(0, boom)
        verdicts = mon.evaluate_once()
        assert [v.detector for v in verdicts] == [
            "throughput_degradation"
        ]

    def test_env_knob_override(self, monkeypatch):
        monkeypatch.setenv(
            "DLROVER_TPU_HEALTH_GOODPUT_SLO", "0.95"
        )
        mon = HealthMonitor(
            self.store, clock=self.clk,
            config={"min_points": 3.0, "goodput_grace_s": 0.0},
        )
        assert mon._cfg("goodput_slo") == 0.95
        # config beats env
        mon2 = HealthMonitor(
            self.store, clock=self.clk, config={"goodput_slo": 0.5}
        )
        assert mon2._cfg("goodput_slo") == 0.5


class TestHealthzEndpoint:
    def test_bare_server_keeps_liveness_ok(self):
        srv = MetricsHTTPServer(port=0)
        srv.start()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=5
            ).read()
            assert body == b"ok\n"
        finally:
            srv.stop()

    def test_healthz_serves_payload_and_503_on_critical(self):
        payload = {"ok": True, "health_score": 1.0}
        srv = MetricsHTTPServer(port=0, health=lambda: dict(payload))
        srv.start()
        try:
            url = f"http://127.0.0.1:{srv.port}/healthz"
            got = json.loads(urllib.request.urlopen(url, timeout=5).read())
            assert got["ok"] is True
            payload.update(ok=False, health_score=0.4)
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(url, timeout=5)
            assert e.value.code == 503
            got = json.loads(e.value.read())
            assert got["health_score"] == 0.4
        finally:
            srv.stop()

    def test_root_path_stays_pure_liveness(self):
        """A critical verdict must flip /healthz readiness, never the
        / liveness answer — a liveness probe restarting the master
        over a WORKER's verdict would be a self-inflicted outage."""
        srv = MetricsHTTPServer(
            port=0, health=lambda: {"ok": False, "health_score": 0.1}
        )
        srv.start()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/", timeout=5
            ).read()
            assert body == b"ok\n"
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/healthz", timeout=5
                )
        finally:
            srv.stop()

    def test_broken_health_provider_stays_alive(self):
        def boom():
            raise RuntimeError("provider broke")

        srv = MetricsHTTPServer(port=0, health=boom)
        srv.start()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=5
            ).read()
            assert json.loads(body)["ok"] is True
        finally:
            srv.stop()


class TestSimulatedFleetDrill:
    """The acceptance drill: one slow host + one data-starved host +
    one healthy control against a REAL in-process master."""

    @pytest.fixture()
    def master(self):
        m = JobMaster(
            port=0, node_num=3, rdzv_timeout=1.0, metrics_port=0,
            collect_interval=999.0, health_interval=9999.0,
        )
        m.prepare()
        yield m
        m.stop()

    @staticmethod
    def snapshot_msg(node_id, host, ts, step_time, data_wait_total):
        registry = {
            "dlrover_train_data_wait_seconds": {
                "type": "histogram", "help": "data wait",
                "labelnames": [], "buckets": [0.1, 1.0],
                "series": [[[], [0, 0, 1], data_wait_total, 1]],
            },
        }
        return msg.MetricsSnapshotReport(
            node_id=node_id,
            host=host,
            timestamp=ts,
            registry=registry,
            resource={"tokens_per_s": 500.0},
            step_times=[step_time],
            events=[],
        )

    def feed_fleet(self, master):
        """240s of backdated snapshot history on a 10s cadence:
        h0 healthy, h1 ramping slow over the last 120s, h2 blocked
        on input 60% of wall time throughout."""
        now = time.time()
        client = RpcClient(master.addr)
        for node_id, host in ((0, "h0"), (1, "h1"), (2, "h2")):
            client.report(
                msg.NodeAddressRequest(node_id=node_id, node_ip=host)
            )
        for i in range(25):
            ts = now - 240.0 + i * 10.0
            age = max(0.0, ts - (now - 120.0))
            slow_step = 0.1 * (1.0 + age / 40.0)  # up to 4x baseline
            client.report(
                self.snapshot_msg(0, "h0", ts, 0.1, 0.001 * i)
            )
            client.report(
                self.snapshot_msg(1, "h1", ts, slow_step, 0.001 * i)
            )
            client.report(
                self.snapshot_msg(2, "h2", ts, 0.1, 6.0 * i)
            )
        return client

    def test_drill(self, master, tmp_path):
        client = self.feed_fleet(master)
        # Drain any straggler-triggered actions the snapshot feed
        # already queued (SpeedMonitor's instantaneous path), so the
        # action assertion below is attributable to the health plane.
        for node_id in (0, 1, 2):
            while (
                client.report(
                    msg.HeartbeatRequest(node_id=node_id)
                ).action != "none"
            ):
                pass
        master.health._last_action.clear()

        verdicts = master.health.evaluate_once()
        by_detector = {}
        for v in verdicts:
            by_detector.setdefault(v.detector, []).append(v)

        # The slow host: critical degradation with evidence.
        (deg,) = by_detector["throughput_degradation"]
        assert (deg.host, deg.node_id, deg.severity) == (
            "h1", 1, SEVERITY_CRITICAL
        )
        assert deg.suggested_action == EventAction.PROFILE.value
        assert len(deg.evidence) >= 3
        assert deg.metrics["ratio"] >= 1.8

        # The starved host: critical data starvation.
        (sta,) = by_detector["data_starvation"]
        assert (sta.host, sta.node_id, sta.severity) == (
            "h2", 2, SEVERITY_CRITICAL
        )
        assert sta.metrics["data_wait_frac"] == pytest.approx(
            0.6, rel=0.05
        )

        # NO false positives on the healthy control host.
        assert not any(
            v.host == "h0" or v.node_id == 0 for v in verdicts
        )

        # The PROFILE action went onto the slow host's FIFO.
        assert (
            client.report(msg.HeartbeatRequest(node_id=1)).action
            == EventAction.PROFILE.value
        )
        assert (
            client.report(msg.HeartbeatRequest(node_id=0)).action
            == "none"
        )

        # query_health RPC: typed verdicts + score, node filter.
        from dlrover_tpu.agent.master_client import MasterClient

        mc = MasterClient(master.addr, node_id=0)
        resp = mc.query_health(include_history=True)
        assert resp.score < 1.0
        detectors = {v.detector for v in resp.verdicts}
        assert {"throughput_degradation", "data_starvation"} <= detectors
        wire_deg = next(
            v for v in resp.verdicts
            if v.detector == "throughput_degradation"
        )
        assert wire_deg.host == "h1"
        assert wire_deg.evidence and len(wire_deg.evidence[0]) == 2
        assert resp.history  # transitions recorded
        only_h1 = mc.query_health(node_id=1)
        assert {v.node_id for v in only_h1.verdicts} == {1}

        # /healthz: 503 + JSON facts while critical verdicts active.
        url = f"http://127.0.0.1:{master.metrics_server.port}"
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(f"{url}/healthz", timeout=5)
        assert e.value.code == 503
        payload = json.loads(e.value.read())
        assert payload["ok"] is False
        assert payload["critical_verdicts"] >= 2
        assert "throughput_degradation" in payload["detectors"]

        # /metrics: score gauge + verdict counter.
        body = urllib.request.urlopen(
            f"{url}/metrics", timeout=5
        ).read().decode()
        assert "dlrover_job_health_score" in body
        # (no exact count: the counter is process-global and other
        # tests in the session legitimately increment it too)
        assert (
            'dlrover_health_verdicts_total{detector='
            '"throughput_degradation",severity="critical"}' in body
        )

        # Brain persistence: the same channel the policy engine reads.
        rows = master.brain.recent_health_verdicts("default")
        assert {
            (r["detector"], r["severity"]) for r in rows
        } >= {
            ("throughput_degradation", "critical"),
            ("data_starvation", "critical"),
        }
        deg_row = next(
            r for r in rows if r["detector"] == "throughput_degradation"
        )
        assert deg_row["node_id"] == 1
        assert json.loads(deg_row["evidence"])  # decodable window
        fleet_rows = master.brain.recent_fleet_samples("default")
        assert fleet_rows and fleet_rows[0]["health_score"] < 1.0
        assert fleet_rows[0]["aggregates"].get("step_time_s")
        per_node = master.brain._recent_samples("default", "worker", 5)
        assert set(per_node) == {0, 1, 2}

        # obs_report --health against the LIVE master: renders the
        # verdicts and exits 1 (critical active).
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "tools", "obs_report.py"),
                "--health", master.addr,
            ],
            capture_output=True, text=True, timeout=120, cwd=REPO,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "throughput_degradation" in proc.stdout
        assert "data_starvation" in proc.stdout
        assert "job health score" in proc.stdout

        # render_health on the monitor's own snapshot agrees.
        rendered = render_health(master.health.snapshot())
        assert "h1" in rendered and "evidence" in rendered

    def test_speed_monitor_feeds_ewma_history(self, master):
        self.feed_fleet(master)
        stats = master.timeseries.query("host.step_ewma", node="1")
        assert stats is not None and stats.count >= 3
        # EWMA history tracked the slowdown.
        assert stats.last > stats.first

    def test_departed_host_history_dropped(self, master):
        self.feed_fleet(master)
        assert master.timeseries.query("host.step_time", host="h1")
        master.job_manager.handle_node_gone(1, "pod deleted")
        assert (
            master.timeseries.query("host.step_time", host="h1") is None
        )
        # Its verdicts resolve on the next evaluation (no series ->
        # no subject), so a dead host cannot pin the health score.
        verdicts = master.health.evaluate_once()
        assert not any(v.host == "h1" for v in verdicts)


class TestGoodputHistory:
    def test_accountant_records_ratio_history(self):
        from dlrover_tpu.obs.goodput import GoodputAccountant
        from dlrover_tpu.obs.metrics import MetricsRegistry

        # Wall clock: the accountant stamps history at the window
        # end, which is wall time.
        store = TimeSeriesStore()
        acct = GoodputAccountant(
            registry=MetricsRegistry(), timeseries=store
        )
        t = time.time()
        acct.add_events([
            {"name": "trainer.step", "ts": t - 30.0, "step": 1},
            {"name": "trainer.step", "ts": t - 1.0, "step": 2},
        ])
        report = acct.account(force=True)
        assert report is not None
        latest = store.latest("goodput.ratio")
        assert latest is not None
        assert latest[1] == pytest.approx(report.goodput_ratio)
        cats = {
            ls["category"]
            for ls in store.series_labels("goodput.seconds")
        }
        assert "productive" in cats
