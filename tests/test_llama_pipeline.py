"""Pipelined Llama vs its dense step: trajectory parity through the
shared full-LM 1F1B assembly (models/llama_pipeline.py)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.models import llama
from dlrover_tpu.models.llama_pipeline import (
    make_llama_pipeline_step,
    shard_params_for_pipeline,
)
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.trainer.step import make_train_step, shard_batch

CFG = llama.LlamaConfig(
    vocab_size=64,
    block_size=16,
    n_layer=4,
    n_head=4,
    n_kv_head=2,  # exercise grouped-query attention through a stage
    n_embd=32,
    intermediate=64,
    dtype=jnp.float32,
    remat=False,
)


def _batches(n_steps, batch=8, seed=3):
    key = jax.random.PRNGKey(seed)
    out = []
    for _ in range(n_steps):
        key, k = jax.random.split(key)
        tok = jax.random.randint(
            k, (batch, CFG.block_size), 0, CFG.vocab_size
        )
        out.append((tok, jnp.roll(tok, -1, axis=1)))
    return out


def _dense_trajectory(batches, lr=1e-2):
    mesh = build_mesh(MeshConfig(data=4), devices=jax.devices()[:4])
    opt = optax.adamw(lr)
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    opt_state = opt.init(params)
    step = make_train_step(
        mesh, functools.partial(llama.loss_fn, cfg=CFG), opt
    )
    losses = []
    for tok, tgt in batches:
        tok, tgt = shard_batch(mesh, tok, tgt)
        params, opt_state, m = step(params, opt_state, tok, tgt)
        losses.append(float(m["loss"]))
    return losses


def _pipeline_trajectory(batches, v_chunks=1, lr=1e-2):
    mesh = build_mesh(
        MeshConfig(data=2, pipe=2), devices=jax.devices()[:4]
    )
    opt = optax.adamw(lr)
    params = shard_params_for_pipeline(
        mesh, llama.init_params(jax.random.PRNGKey(0), CFG)
    )
    opt_state = opt.init(params)
    step = make_llama_pipeline_step(
        mesh, CFG, opt, v_chunks=v_chunks
    )
    losses = []
    for tok, tgt in batches:
        params, opt_state, m = step(params, opt_state, tok, tgt)
        losses.append(float(m["loss"]))
    return losses


class TestLlamaPipelineParity:
    @pytest.mark.slow
    def test_1f1b_matches_dense_trajectory(self):
        batches = _batches(4)
        dense = _dense_trajectory(batches)
        piped = _pipeline_trajectory(batches)
        np.testing.assert_allclose(piped, dense, rtol=2e-3, atol=2e-4)

    @pytest.mark.slow
    def test_interleaved_chunks_match_dense(self):
        batches = _batches(3)
        dense = _dense_trajectory(batches)
        piped = _pipeline_trajectory(batches, v_chunks=2)
        np.testing.assert_allclose(piped, dense, rtol=2e-3, atol=2e-4)

    def test_sequences_shorter_than_block_size(self):
        """The RoPE table is built at block_size; shorter sequences
        must slice it, not crash at trace time (review finding)."""
        mesh = build_mesh(
            MeshConfig(data=2, pipe=2), devices=jax.devices()[:4]
        )
        opt = optax.adamw(1e-2)
        params = shard_params_for_pipeline(
            mesh, llama.init_params(jax.random.PRNGKey(0), CFG)
        )
        opt_state = opt.init(params)
        step = make_llama_pipeline_step(mesh, CFG, opt)
        tok = jax.random.randint(
            jax.random.PRNGKey(9),
            (8, CFG.block_size // 2), 0, CFG.vocab_size,
        )
        _, _, m = step(
            params, opt_state, tok, jnp.roll(tok, -1, axis=1)
        )
        assert np.isfinite(float(m["loss"]))

    @pytest.mark.slow
    def test_moe_rides_the_stage_aux_channel(self):
        """Pipelined MoE: the router load-balancing loss flows
        through the schedule's aux channel and is differentiated.
        Parity target is the MICROBATCHED serial objective (the aux
        is nonlinear in the batch, so full-batch dense and
        microbatched training legitimately differ): mean over the
        same microbatches of llama.loss_fn."""
        moe_cfg = llama.LlamaConfig(
            vocab_size=64, block_size=16, n_layer=4, n_head=4,
            n_kv_head=2, n_embd=32, intermediate=64,
            dtype=jnp.float32, remat=False, n_experts=4,
        )
        mesh = build_mesh(
            MeshConfig(data=1, pipe=4), devices=jax.devices()[:4]
        )
        opt = optax.adamw(1e-2)
        params = shard_params_for_pipeline(
            mesh, llama.init_params(jax.random.PRNGKey(0), moe_cfg)
        )
        opt_state = opt.init(params)
        step = make_llama_pipeline_step(
            mesh, moe_cfg, opt, n_micro=4
        )
        key = jax.random.PRNGKey(7)
        tok = jax.random.randint(
            key, (8, moe_cfg.block_size), 0, moe_cfg.vocab_size
        )
        tgt = jnp.roll(tok, -1, axis=1)
        # serial microbatched reference on the SAME params
        ref_params = llama.init_params(jax.random.PRNGKey(0), moe_cfg)
        losses = [
            float(
                llama.loss_fn(
                    ref_params, tok[i : i + 2], tgt[i : i + 2],
                    cfg=moe_cfg,
                )
            )
            for i in range(0, 8, 2)
        ]
        want = float(np.mean(losses))
        _, _, m = step(params, opt_state, tok, tgt)
        np.testing.assert_allclose(
            float(m["loss"]), want, rtol=2e-4
        )
        # aux actually contributes: the model's own backbone reports
        # a nonzero summed router loss on these microbatches
        aux_terms = [
            float(
                llama.backbone_with_aux(
                    ref_params, tok[i : i + 2], cfg=moe_cfg
                )[1]
            )
            for i in range(0, 8, 2)
        ]
        assert min(aux_terms) > 0

    def test_moe_aux_single_stage_fallback(self):
        """pipe=1 exercises step_single's aux path — the router term
        must not silently vanish on an unpipelined mesh."""
        moe_cfg = llama.LlamaConfig(
            vocab_size=64, block_size=16, n_layer=4, n_head=4,
            n_kv_head=2, n_embd=32, intermediate=64,
            dtype=jnp.float32, remat=False, n_experts=4,
        )
        mesh = build_mesh(
            MeshConfig(data=4), devices=jax.devices()[:4]
        )
        opt = optax.adamw(1e-2)
        params = shard_params_for_pipeline(
            mesh, llama.init_params(jax.random.PRNGKey(0), moe_cfg)
        )
        opt_state = opt.init(params)
        step = make_llama_pipeline_step(
            mesh, moe_cfg, opt, n_micro=4
        )
        tok = jax.random.randint(
            jax.random.PRNGKey(7), (8, moe_cfg.block_size), 0,
            moe_cfg.vocab_size,
        )
        tgt = jnp.roll(tok, -1, axis=1)
        ref_params = llama.init_params(jax.random.PRNGKey(0), moe_cfg)
        # pipe=1 runs step_single (plain jit, no shard_map): the loss
        # is computed per FULL microbatch, so the serial reference is
        # the mean over the same mb=2 microbatches.
        losses = [
            float(
                llama.loss_fn(
                    ref_params, tok[i : i + 2], tgt[i : i + 2],
                    cfg=moe_cfg,
                )
            )
            for i in range(0, 8, 2)
        ]
        want = float(np.mean(losses))
        _, _, m = step(params, opt_state, tok, tgt)
        np.testing.assert_allclose(float(m["loss"]), want, rtol=2e-4)

    @pytest.mark.slow
    def test_moe_aux_interleaved_and_batch_sharded(self):
        """The aux channel's other schedule paths: interleaved chunks
        (V>1 — per-chunk aux must not double-count on wrap waves) and
        a data-sharded batch (aux rides the same pmean as the loss).
        Same microbatched-serial parity target."""
        moe_cfg = llama.LlamaConfig(
            vocab_size=64, block_size=16, n_layer=4, n_head=4,
            n_kv_head=2, n_embd=32, intermediate=64,
            dtype=jnp.float32, remat=False, n_experts=4,
        )
        mesh = build_mesh(
            MeshConfig(data=2, pipe=2), devices=jax.devices()[:4]
        )
        opt = optax.adamw(1e-2)
        params = shard_params_for_pipeline(
            mesh, llama.init_params(jax.random.PRNGKey(0), moe_cfg)
        )
        opt_state = opt.init(params)
        step = make_llama_pipeline_step(
            mesh, moe_cfg, opt, n_micro=4, v_chunks=2
        )
        tok = jax.random.randint(
            jax.random.PRNGKey(8), (8, moe_cfg.block_size), 0,
            moe_cfg.vocab_size,
        )
        tgt = jnp.roll(tok, -1, axis=1)
        # microbatched serial reference: n_micro=4 -> mb rows of 2,
        # each data shard sees rows split in half; the loss_fn is
        # evaluated per HALF-microbatch (shard-local normalization),
        # and the pmean of those equals the mean over all 8 halves
        ref_params = llama.init_params(jax.random.PRNGKey(0), moe_cfg)
        halves = [
            float(
                llama.loss_fn(
                    ref_params, tok[i : i + 1], tgt[i : i + 1],
                    cfg=moe_cfg,
                )
            )
            for i in range(8)
        ]
        want = float(np.mean(halves))
        _, _, m = step(params, opt_state, tok, tgt)
        np.testing.assert_allclose(
            float(m["loss"]), want, rtol=2e-4
        )
