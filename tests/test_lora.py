"""LoRA transform: init no-op, training moves only LoRA params."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.models import llama, lora


def _setup():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    lcfg = lora.LoraConfig(rank=4)
    lp = lora.init_lora(params, lcfg, jax.random.PRNGKey(1))
    return cfg, params, lcfg, lp


def test_init_is_identity():
    cfg, params, lcfg, lp = _setup()
    merged = lora.apply(params, lp, lcfg)
    for a, b in zip(jax.tree.leaves(merged), jax.tree.leaves(params)):
        np.testing.assert_array_equal(a, b)


def test_targets_and_count():
    cfg, params, lcfg, lp = _setup()
    blocks = lp["blocks"]
    # llama targets minus nothing: wq wk wv wo w_gate w_up w_down
    assert set(blocks) == {
        "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"
    }
    assert "rms1" not in blocks and "wte" not in lp
    assert lora.num_trainable(lp) < sum(
        x.size for x in jax.tree.leaves(params)
    ) * 0.2


def test_lora_training_decreases_loss_base_frozen():
    cfg, params, lcfg, lp = _setup()
    tokens = jax.random.randint(
        jax.random.PRNGKey(2), (2, cfg.block_size), 0, cfg.vocab_size
    )
    targets = jnp.roll(tokens, -1, axis=1)

    def loss(lora_p):
        eff = lora.apply(params, lora_p, lcfg)
        return llama.loss_fn(eff, tokens, targets, cfg)

    opt = optax.adam(1e-2)
    state = opt.init(lp)
    step = jax.jit(
        lambda lp_, st: _step(lp_, st, loss, opt)
    )
    l0 = float(loss(lp))
    for _ in range(5):
        lp, state, lval = step(lp, state)
    assert float(lval) < l0
    # merged-export parity: merge == apply
    m = lora.merge(params, lp, lcfg)
    a = lora.apply(params, lp, lcfg)
    for x, y in zip(jax.tree.leaves(m), jax.tree.leaves(a)):
        np.testing.assert_array_equal(x, y)


def _step(lp, state, loss, opt):
    lval, g = jax.value_and_grad(loss)(lp)
    updates, state = opt.update(g, state)
    return optax.apply_updates(lp, updates), state, lval
