"""CI wrapper for the two-host elasticity drill (VERDICT r2 item 8).

Runs examples/chaos/host_preemption_drill.py as a real multi-process
exercise: master + two agent processes, each trainer doing a real
jax.distributed init; SIGKILL of host 1; survivor re-rendezvous into
world=1 with flash-checkpoint resume; host 1 rejoin re-grows the
world. Slow (several cold compiles on one CPU core) but it is the
only test that drives the whole elasticity chain end to end.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_two_host_preemption_drill(tmp_path):
    out = tmp_path / "recovery.json"
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(
                REPO, "examples", "chaos", "host_preemption_drill.py"
            ),
            "--steps", "300",
            # BASELINE.md's recovery contract is 120 s — the recorded
            # budget must stay the contract's number so the artifact
            # can't quietly loosen (VERDICT r4 weak #6). Measured r4:
            # 34.0 s shrink / 22.6 s rejoin, comfortably inside.
            "--recovery-budget", "120",
            "--output", str(out),
        ],
        capture_output=True,
        text=True,
        timeout=900,
        cwd=REPO,
    )
    assert proc.returncode == 0, (
        f"drill failed:\n{proc.stdout[-3000:]}\n{proc.stderr[-2000:]}"
    )
    result = json.loads(out.read_text())
    assert result["world_shrank_to_one"]
    assert result["world_regrew"]
    assert result["within_budget"]
    assert result["recovery_budget_s"] == 120
    assert result["shrink_recovery_s"] <= 120
    # Phases must sum within the contract budget, not just their own
    # per-phase allowances.
    assert sum(result["shrink_phases"].values()) <= 120

    # Phase breakdown (VERDICT r3 weak #5): the recovery time must be
    # explainable — every segment present, non-negative, within its
    # own budget, and summing to ~the total.
    phases = result["shrink_phases"]
    assert phases is not None, "shrink phases missing"
    budgets = {
        # master watchdog (6 s heartbeat timeout + 2 s monitor tick)
        # + restart push + agent respawn
        "detect_respawn_s": 45.0,
        "rendezvous_init_s": 60.0,
        "build_s": 90.0,  # cold compile #1 on one CPU core
        "restore_s": 30.0,
        "first_step_s": 90.0,  # cold compile #2
    }
    for name, budget in budgets.items():
        assert 0.0 <= phases[name] <= budget, (
            f"phase {name}={phases[name]}s over its {budget}s budget"
        )
    # The observed total lags first_step_done by the drill's 1 s
    # metrics polling plus one extra confirming step.
    assert (
        abs(sum(phases.values()) - result["shrink_recovery_s"]) < 10.0
    ), f"phases {phases} do not explain {result['shrink_recovery_s']}s"

    # Recovery timeline reconstructed from the obs event trace
    # (dlrover_tpu/obs/timeline.py over the survivor's
    # DLROVER_TPU_TRACE_FILE): the canonical breakdown must be
    # complete, with every required phase present and positive.
    timeline = result["recovery_timeline"]
    assert timeline is not None, "recovery timeline missing"
    assert timeline["complete"]
    for phase in ("failure-detect", "rendezvous", "restore",
                  "first-step"):
        dur = timeline["phases"][phase]
        assert dur is not None and dur > 0.0, (
            f"timeline phase {phase} not positive: {dur} "
            f"(timeline={timeline})"
        )
    # The event-derived timeline and the phases-file segments measure
    # the same recovery: totals must agree (same marks, same clock).
    assert (
        abs(timeline["total_s"] - result["shrink_recovery_s"]) < 10.0
    ), f"timeline {timeline} vs shrink {result['shrink_recovery_s']}s"
