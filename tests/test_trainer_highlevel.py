"""High-level Trainer, hang detector, paral-config tuner."""

import functools
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.accelerate import Strategy
from dlrover_tpu.agent.hang_detector import HangDetector
from dlrover_tpu.agent.paral_config_tuner import (
    ParalConfigTuner,
    read_parallel_config,
)
from dlrover_tpu.common import messages as msg
from dlrover_tpu.models import gpt
from dlrover_tpu.trainer.trainer import Trainer, TrainingArguments


CFG = gpt.GPTConfig(
    vocab_size=128, block_size=32, n_layer=2, n_head=2, n_embd=32,
    dtype=jnp.float32, remat=False,
)


class TokenDataset:
    def __init__(self, n=256, seed=0):
        rng = np.random.default_rng(seed)
        self.data = rng.integers(
            0, CFG.vocab_size, size=(n, CFG.block_size + 1)
        ).astype(np.int32)

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        return self.data[i, :-1], self.data[i, 1:]


def test_trainer_pipelined_strategy_end_to_end(tmp_path, monkeypatch):
    """Strategy.pipeline_depth + device_prefetch drive the whole
    Trainer.train stack: device-resident prefetch queue, pipelined
    accumulation (accum 2 here), split data_wait/h2d phase
    accounting — and the run still trains and checkpoints."""
    monkeypatch.setenv(
        "DLROVER_TPU_METRICS_FILE", str(tmp_path / "metrics.json")
    )
    args = TrainingArguments(
        max_steps=4,
        global_batch_size=32,  # 4 shards x micro 4 -> accum 2
        micro_batch_size=4,
        checkpoint_dir=str(tmp_path / "ckpt"),
        save_steps=4,
        log_steps=2,
        strategy=Strategy(
            mesh_shape=(("data", 4),), dtype="float32",
            micro_batch_size=4, pipeline_depth=1,
            device_prefetch=True,
        ),
    )
    t = Trainer(
        functools.partial(gpt.init_params, cfg=CFG),
        functools.partial(gpt.loss_fn, cfg=CFG),
        gpt.param_logical_axes(CFG),
        TokenDataset(),
        args,
    )
    out = t.train()
    assert out["final_step"] == 4
    assert out["final_loss"] is not None

    # the low-HBM flavor: host-delivered batches, per-microbatch
    # staging inside the pipelined step
    monkeypatch.setenv("DLROVER_TPU_DEVICE_PREFETCH", "0")
    args2 = TrainingArguments(**{
        **args.__dict__,
        "checkpoint_dir": str(tmp_path / "ckpt2"),
        "strategy": Strategy(
            mesh_shape=(("data", 4),), dtype="float32",
            micro_batch_size=4, pipeline_depth=1,
            device_prefetch=False,
        ),
    })
    t2 = Trainer(
        functools.partial(gpt.init_params, cfg=CFG),
        functools.partial(gpt.loss_fn, cfg=CFG),
        gpt.param_logical_axes(CFG),
        TokenDataset(),
        args2,
    )
    out2 = t2.train()
    assert out2["final_step"] == 4
    assert out2["final_loss"] is not None


def test_trainer_end_to_end(tmp_path, monkeypatch):
    monkeypatch.setenv(
        "DLROVER_TPU_METRICS_FILE", str(tmp_path / "metrics.json")
    )
    args = TrainingArguments(
        max_steps=6,
        global_batch_size=16,
        micro_batch_size=4,
        checkpoint_dir=str(tmp_path / "ckpt"),
        save_steps=3,
        log_steps=2,
        strategy=Strategy(
            mesh_shape=(("data", 4),), dtype="float32",
            micro_batch_size=4,
        ),
    )
    t = Trainer(
        functools.partial(gpt.init_params, cfg=CFG),
        functools.partial(gpt.loss_fn, cfg=CFG),
        gpt.param_logical_axes(CFG),
        TokenDataset(),
        args,
    )
    out = t.train()
    assert out["final_step"] == 6
    assert out["final_loss"] is not None
    # metrics file written for the agent's training monitor
    with open(tmp_path / "metrics.json") as f:
        assert json.load(f)["step"] == 6

    # resume: a fresh Trainer continues from the checkpoint
    args2 = TrainingArguments(**{
        **args.__dict__, "max_steps": 8,
    })
    t2 = Trainer(
        functools.partial(gpt.init_params, cfg=CFG),
        functools.partial(gpt.loss_fn, cfg=CFG),
        gpt.param_logical_axes(CFG),
        TokenDataset(),
        args2,
    )
    out2 = t2.train()
    assert out2["final_step"] == 8


def test_trainer_periodic_and_standalone_eval(tmp_path, monkeypatch):
    """Evaluator role: periodic eval during train() and a standalone
    evaluate() that restores the latest committed checkpoint."""
    monkeypatch.setenv(
        "DLROVER_TPU_METRICS_FILE", str(tmp_path / "m.json")
    )
    args = TrainingArguments(
        max_steps=4,
        global_batch_size=8,
        micro_batch_size=4,
        checkpoint_dir=str(tmp_path / "ckpt_eval"),
        save_steps=4,
        eval_steps=2,
        eval_max_batches=2,
        strategy=Strategy(
            mesh_shape=(("data", 4),), dtype="float32",
            micro_batch_size=4,
        ),
    )
    t = Trainer(
        functools.partial(gpt.init_params, cfg=CFG),
        functools.partial(gpt.loss_fn, cfg=CFG),
        gpt.param_logical_axes(CFG),
        TokenDataset(),
        args,
        eval_dataset=TokenDataset(n=64, seed=9),
    )
    out = t.train()
    assert out["eval"] is not None
    assert np.isfinite(out["eval"]["eval_loss"])
    assert out["eval"]["perplexity"] > 1.0

    # standalone evaluator node: fresh Trainer, params from checkpoint
    t2 = Trainer(
        functools.partial(gpt.init_params, cfg=CFG),
        functools.partial(gpt.loss_fn, cfg=CFG),
        gpt.param_logical_axes(CFG),
        TokenDataset(),
        args,
        eval_dataset=TokenDataset(n=64, seed=9),
    )
    metrics = t2.evaluate()
    np.testing.assert_allclose(
        metrics["eval_loss"], out["eval"]["eval_loss"], rtol=1e-5
    )


def test_trainer_with_llama_family(tmp_path, monkeypatch):
    """The high-level Trainer is model-agnostic: drive it with the
    Llama family (RoPE/GQA/SwiGLU) end to end, including a save."""
    from dlrover_tpu.models import llama

    lcfg = llama.LlamaConfig.tiny()

    class LlamaData:
        def __init__(self, n=128, seed=1):
            rng = np.random.default_rng(seed)
            self.data = rng.integers(
                0, lcfg.vocab_size, size=(n, lcfg.block_size + 1)
            ).astype(np.int32)

        def __len__(self):
            return len(self.data)

        def __getitem__(self, i):
            return self.data[i, :-1], self.data[i, 1:]

    monkeypatch.setenv(
        "DLROVER_TPU_METRICS_FILE", str(tmp_path / "m.json")
    )
    args = TrainingArguments(
        max_steps=4,
        global_batch_size=8,
        micro_batch_size=4,
        checkpoint_dir=str(tmp_path / "ckpt_llama"),
        save_steps=4,
        strategy=Strategy(
            mesh_shape=(("data", 2), ("fsdp", 2), ("tensor", 2)),
            dtype="float32",
            micro_batch_size=4,
        ),
    )
    t = Trainer(
        functools.partial(llama.init_params, cfg=lcfg),
        functools.partial(llama.loss_fn, cfg=lcfg),
        llama.param_logical_axes(lcfg),
        LlamaData(),
        args,
    )
    out = t.train()
    assert out["final_step"] == 4
    assert np.isfinite(out["final_loss"])


def test_trainer_cosine_schedule_and_clipping(tmp_path, monkeypatch):
    """Warmup-cosine LR + grad clipping train end to end, and the
    standalone evaluator rebuilds the schedule-bearing opt skeleton."""
    monkeypatch.setenv(
        "DLROVER_TPU_METRICS_FILE", str(tmp_path / "m.json")
    )
    args = TrainingArguments(
        max_steps=4,
        global_batch_size=8,
        micro_batch_size=4,
        checkpoint_dir=str(tmp_path / "ckpt_sched"),
        save_steps=4,
        warmup_steps=2,
        lr_schedule="cosine",
        grad_clip_norm=1.0,
        strategy=Strategy(
            mesh_shape=(("data", 4),), dtype="float32",
            micro_batch_size=4,
        ),
    )

    def build():
        return Trainer(
            functools.partial(gpt.init_params, cfg=CFG),
            functools.partial(gpt.loss_fn, cfg=CFG),
            gpt.param_logical_axes(CFG),
            TokenDataset(),
            args,
            eval_dataset=TokenDataset(n=64, seed=3),
        )

    out = build().train()
    assert out["final_step"] == 4
    assert np.isfinite(out["final_loss"])
    # skeleton roundtrip: schedule state must match the checkpoint
    metrics = build().evaluate()
    assert np.isfinite(metrics["eval_loss"])


def test_make_optimizer_schedule_variants():
    from dlrover_tpu.accelerate import make_optimizer

    p = {"w": jnp.ones((8,))}
    for kw in (
        {"schedule": "constant"},
        {"schedule": "constant", "warmup_steps": 3},
        {"schedule": "cosine", "warmup_steps": 2, "decay_steps": 10},
        {"schedule": "cosine", "decay_steps": 10,
         "grad_clip_norm": 0.5},
    ):
        opt = make_optimizer("adamw", 1e-2, **kw)
        s = opt.init(p)
        g = {"w": jnp.full((8,), 10.0)}  # large grad: clipping binds
        u, s = opt.update(g, s, p)
        assert np.isfinite(float(jnp.sum(u["w"])))
    with pytest.raises(ValueError):
        make_optimizer("adamw", 1e-2, schedule="cosine")
    with pytest.raises(ValueError):
        make_optimizer("adamw", 1e-2, schedule="nope")
    with pytest.raises(ValueError):
        make_optimizer("adamw", 1e-2, grad_clip_norm=-1.0)
    # clipping actually binds: with sgd, ||update|| == lr * clip_norm
    # for a gradient far above the threshold
    lr, clip = 0.1, 0.5
    opt = make_optimizer("sgd", lr, grad_clip_norm=clip)
    p = {"w": jnp.zeros((4,))}
    s = opt.init(p)
    g = {"w": jnp.full((4,), 100.0)}
    u, _ = opt.update(g, s, p)
    np.testing.assert_allclose(
        float(jnp.linalg.norm(u["w"])), lr * clip, rtol=1e-5
    )


def test_hang_detector_startup_grace_and_progress(tmp_path):
    path = str(tmp_path / "m.json")
    det = HangDetector(
        hang_timeout=0.2, startup_grace=0.3, metrics_file=path
    )
    assert not det.check()  # within startup grace
    time.sleep(0.35)
    assert det.check()  # no step ever landed
    det.reset()
    with open(path, "w") as f:
        json.dump({"step": 1}, f)
    assert not det.check()  # progress
    time.sleep(0.25)
    assert det.check()  # stalled past hang_timeout
    with open(path, "w") as f:
        json.dump({"step": 2}, f)
    assert not det.check()  # recovered


def test_paral_config_tuner_stages_file(tmp_path):
    class FakeClient:
        def __init__(self):
            self.cfg = msg.ParallelConfig(
                micro_batch_size=8, version=1
            )

        def get_parallel_config(self):
            return self.cfg

    path = str(tmp_path / "paral.json")
    client = FakeClient()
    tuner = ParalConfigTuner(client, config_file=path, interval=999)
    assert tuner.poll_once()
    staged = read_parallel_config(path)
    assert staged["micro_batch_size"] == 8
    # same version: no rewrite
    assert not tuner.poll_once()
    client.cfg = msg.ParallelConfig(micro_batch_size=16, version=2)
    assert tuner.poll_once()
    assert read_parallel_config(path)["micro_batch_size"] == 16


def test_trainer_applies_paral_config(tmp_path, monkeypatch):
    path = str(tmp_path / "paral.json")
    with open(path, "w") as f:
        json.dump({"micro_batch_size": 2, "version": 3}, f)
    monkeypatch.setenv("DLROVER_TPU_PARAL_CONFIG_FILE", path)

    def make():
        return Trainer(
            functools.partial(gpt.init_params, cfg=CFG),
            functools.partial(gpt.loss_fn, cfg=CFG),
            gpt.param_logical_axes(CFG),
            TokenDataset(),
            TrainingArguments(micro_batch_size=4),
        )

    # standalone (no agent): the file must be ignored
    monkeypatch.delenv("DLROVER_TPU_AGENT_PRESENT", raising=False)
    assert make().args.micro_batch_size == 4
    # under the agent: applied
    monkeypatch.setenv("DLROVER_TPU_AGENT_PRESENT", "1")
    assert make().args.micro_batch_size == 2


def test_servicer_parallel_config_roundtrip():
    from dlrover_tpu.master.master import JobMaster

    master = JobMaster(node_num=1)
    master.prepare()
    try:
        master.servicer.set_parallel_config(
            msg.ParallelConfig(micro_batch_size=16)
        )
        from dlrover_tpu.common.comm import RpcClient

        client = RpcClient(master.addr)
        cfg = client.get(msg.ParallelConfigRequest(node_id=0))
        assert cfg.micro_batch_size == 16
        assert cfg.version == 1
    finally:
        master.stop()
