"""Regression tests for review findings on the control-plane core."""

import time

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import RpcClient
from dlrover_tpu.master.job_manager import JobManager
from dlrover_tpu.master.master import JobMaster
from dlrover_tpu.master.rendezvous import ElasticRendezvous


def test_reregistration_after_failure_revives_node():
    jm = JobManager(max_relaunch=3)
    jm.register_node(node_id=0)
    jm.handle_failure_report(0, "oom", "process_error", 0)
    # OOM escalates to relaunch: a pending replacement is tracked.
    assert jm.get_node(0).status == "pending"
    # Relaunched agent re-registers under the same id.
    node = jm.register_node(node_id=0, addr="h0-new")
    assert node.status == "running"
    assert node.relaunch_count == 1  # budget carried over
    assert not jm.all_workers_done()


def test_new_joiner_does_not_wipe_frozen_world():
    rdzv = ElasticRendezvous()
    rdzv.update_params(min_nodes=2, max_nodes=2, waiting_timeout=10)
    rdzv.join(0, 8)
    rdzv.join(1, 8)
    _, _, world0 = rdzv.get_comm_world(0)
    assert world0 == {0: 8, 1: 8}
    # A scale-up node joins before node 1 fetched the world.
    rdzv.join(2, 8)
    _, _, world1 = rdzv.get_comm_world(1)
    assert world1 == {0: 8, 1: 8}  # unchanged
    # But a returning member invalidates it.
    rdzv.join(0, 8)
    _, _, world2 = rdzv.get_comm_world(1)
    assert 2 in rdzv._waiting_nodes or world2 != {0: 8, 1: 8}


def test_node_unit_rounding_respects_min_nodes():
    rdzv = ElasticRendezvous()
    rdzv.update_params(
        min_nodes=3, max_nodes=4, waiting_timeout=0.1, node_unit=2
    )
    for rank in range(3):
        rdzv.join(rank, 4)
    time.sleep(0.2)
    _, _, world = rdzv.get_comm_world(0)
    # 3 rounds down to 2 < min_nodes=3: must NOT complete.
    assert world == {}


def test_network_check_verdict_over_rpc():
    m = JobMaster(port=0, node_num=2, rdzv_timeout=0.5)
    m.prepare()
    try:
        c = RpcClient(m.addr)
        for rank in range(2):
            c.get(
                msg.JoinRendezvousRequest(
                    node_id=rank,
                    node_rank=rank,
                    local_world_size=4,
                    rdzv_name="network-check",
                )
            )
        for rank in range(2):
            c.get(
                msg.CommWorldRequest(node_id=rank, rdzv_name="network-check")
            )
        c.report(
            msg.NetworkCheckResultRequest(
                node_id=0, normal=True, elapsed_time=1.0
            )
        )
        # Not all reported yet -> waiting
        q = c.get(msg.NetworkCheckQueryRequest(kind="fault"))
        assert q.reason == "waiting"
        c.report(
            msg.NetworkCheckResultRequest(
                node_id=1, normal=False, elapsed_time=9.0
            )
        )
        q = c.get(msg.NetworkCheckQueryRequest(kind="fault"))
        assert q.nodes == [1] and q.reason == "fault"
        # With only 2 nodes the 2x-median rule can never fire (t1 > t0+t1
        # is impossible) — straggler detection needs >= 3 nodes.
        q = c.get(msg.NetworkCheckQueryRequest(kind="straggler"))
        assert q.nodes == []
        c.close()
    finally:
        m.stop()


def test_kv_empty_value_found():
    m = JobMaster(port=0, node_num=1)
    m.prepare()
    try:
        c = RpcClient(m.addr)
        assert not c.get(msg.KVStoreGetRequest(key="flag")).found
        c.report(msg.KVStoreSetRequest(key="flag", value=b""))
        assert c.get(msg.KVStoreGetRequest(key="flag")).found
        c.close()
    finally:
        m.stop()


def test_huge_dataset_not_truncated():
    from dlrover_tpu.master.dataset_splitter import TableDatasetSplitter

    sp = TableDatasetSplitter("big", dataset_size=100, shard_size=10,
                              num_epochs=1, max_shard_count=4)
    covered = []
    while not sp.epoch_finished():
        sp.create_shards()
        covered.extend((s.start, s.end) for s in sp.get_shards())
    # All 10 shards produced across sub-epoch windows, none dropped.
    assert len(covered) == 10
    assert covered[0] == (0, 10) and covered[-1] == (90, 100)
    assert sp.epoch == 1


def test_lock_released_when_holder_process_dies():
    import multiprocessing as mp
    import time as _t
    from dlrover_tpu.common.multi_process import SharedLock

    lock = SharedLock("crash", server=True)
    try:
        ctx = mp.get_context("spawn")
        p = ctx.Process(target=_hold_lock_forever, args=("crash",))
        p.start()
        deadline = _t.time() + 30
        while not lock.locked() and _t.time() < deadline:
            _t.sleep(0.05)
        assert lock.locked()
        p.kill()  # holder dies without releasing
        p.join(timeout=30)
        deadline = _t.time() + 10
        acquired = False
        while _t.time() < deadline:
            if lock.acquire(blocking=False):
                acquired = True
                break
            _t.sleep(0.1)
        assert acquired, "lock not force-released after holder death"
        lock.release()
    finally:
        lock.close()


def _hold_lock_forever(name):
    import time as _t
    from dlrover_tpu.common.multi_process import SharedLock

    lock = SharedLock(name)
    lock.acquire()
    _t.sleep(300)


def test_queue_blocking_get_does_not_block_put_other_thread():
    import threading as th
    from dlrover_tpu.common.multi_process import SharedQueue

    q = SharedQueue("tdq", server=True)
    try:
        result = {}

        def getter():
            result["item"] = q.get(timeout=10)

        t = th.Thread(target=getter)
        t.start()
        import time as _t

        _t.sleep(0.3)  # getter is now blocked in its poll loop
        q.put("hello")  # same SharedQueue object, different thread
        t.join(timeout=10)
        assert result.get("item") == "hello"
    finally:
        q.close()


def test_pending_replacement_times_out_so_job_can_finish():
    """A relaunch plan nobody executes (local mode) must not hang the
    master forever: the PENDING ghost is abandoned after the timeout."""
    jm = JobManager(max_relaunch=3, pending_timeout=0.0)
    jm.register_node(node_id=0)
    action = jm.handle_failure_report(0, "oom", "process_error", 0)
    assert action == "relaunch_node"
    assert jm.get_node(0).status == "pending"
    assert not jm.all_workers_done()
    jm.check_nodes_once()
    assert jm.get_node(0).status == "failed"
    assert jm.all_workers_done()


def test_process_error_keeps_node_running_at_master():
    """Plain app crashes are retried in place by the agent; the node
    (pod) is alive so the master keeps it RUNNING."""
    jm = JobManager()
    jm.register_node(node_id=0)
    action = jm.handle_failure_report(
        0, "training process exit code 1\nValueError: x", "process_error", 0
    )
    assert action == "restart_in_place"
    node = jm.get_node(0)
    assert node.status == "running"
    assert node.process_failure_count == 1
    assert not jm.all_workers_done()


def test_oom_stderr_classifies_and_escalates():
    """RESOURCE_EXHAUSTED in the child's stderr tail (what the agent now
    reports) escalates to a master-owned node relaunch."""
    jm = JobManager()
    jm.register_node(node_id=0)
    action = jm.handle_failure_report(
        0,
        "training process exit code 1\njaxlib.xla_extension."
        "XlaRuntimeError: RESOURCE_EXHAUSTED: Out of memory",
        "process_error",
        0,
    )
    assert action == "relaunch_node"
    # The tracked node is now the pending replacement incarnation.
    assert jm.get_node(0).status == "pending"


def test_oom_classifier_word_boundary():
    """'oom_score_adj' in a procfs dump must not classify as OOM, while
    the real killer spellings must."""
    jm = JobManager()
    assert jm.classify_exit("oom_score_adj: 1000", "process_error") != "oom"
    assert jm.classify_exit("OOMKilled", "process_error") == "oom"
    assert jm.classify_exit("oom-killer invoked", "process_error") == "oom"
    assert jm.classify_exit("killed by oom", "process_error") == "oom"


def test_peer_death_abort_is_not_classified_preempted():
    """jax's coordination client aborts SURVIVORS of a peer death with
    stderr saying the leader 'was preempted/died'; the \\bpreempt
    match used to classify the healthy survivor as PREEMPTED ->
    RELAUNCH_NODE -> agent stopped supervising, so killing the
    coordinator host took the whole job down (found by the
    alternating-victim soak drill). The survivor must classify as a
    plain crash the agent restarts in place."""
    jm = JobManager()
    jax_abort = (
        "Terminating process because the JAX distributed service "
        "detected fatal errors. This most likely indicates that "
        "another task died; see the other task logs.\n"
        "absl::Status: UNAVAILABLE: Failed to send RPC to "
        "coordination service. Either the leader task was "
        "preempted/died/restarted unexpectedly or this task is "
        "experiencing network issues."
    )
    assert jm.classify_exit(jax_abort, "process_error") == "killed"
    # A REAL preemption notice (no coordination-service signature)
    # still classifies as preempted.
    assert (
        jm.classify_exit("node preempted by scheduler", "process_error")
        == "preempted"
    )


def test_stale_heartbeat_does_not_revive_pending_replacement():
    """A last-gasp heartbeat from the agent being replaced lands right
    after the relaunch; it must not flip the fresh PENDING node to
    RUNNING (that would defeat the pending timeout and the duplicate-
    report guard)."""
    jm = JobManager()
    jm.register_node(node_id=0)
    action = jm.handle_failure_report(
        0, "CUDA out of memory", "process_error", 0
    )
    assert action == "relaunch_node"
    assert jm.get_node(0).status == "pending"
    # In-flight beat from the dying agent arrives immediately.
    jm.update_heartbeat(0)
    jm.check_nodes_once()
    assert jm.get_node(0).status == "pending"
    # An agent still beating past the grace window is genuinely alive
    # (lost-response restart-in-place case) and does recover the node.
    jm.get_node(0).create_time -= jm.PENDING_HEARTBEAT_GRACE + 1
    jm.update_heartbeat(0)
    jm.check_nodes_once()
    assert jm.get_node(0).status == "running"
