"""ElasticTrainer fixed-global-batch semantics + checkpointable sampler."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.trainer.elastic_trainer import (
    ElasticDataLoader,
    ElasticDistributedSampler,
    ElasticTrainer,
    gradient_accumulation_steps,
)


def _mesh(data=4):
    return build_mesh(MeshConfig(data=data), devices=jax.devices()[:data])


def _linear_loss(params, x, y):
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


def _toy_data(n=64, d=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=(d, 1)).astype(np.float32)
    y = x @ w_true + 0.01 * rng.normal(size=(n, 1)).astype(np.float32)
    return x, y


def test_accum_steps_keeps_global_batch():
    # 8 shards x micro 4 = 32/step -> accum 4 for global 128
    assert gradient_accumulation_steps(128, 4, 8) == 4
    # losing half the shards doubles accumulation, global stays 128
    assert gradient_accumulation_steps(128, 4, 4) == 8
    # non-divisible rounds UP (effective batch never shrinks)
    assert gradient_accumulation_steps(100, 4, 8) == 4


def test_accumulated_step_equals_big_batch_step():
    """accum microbatches must produce the same update as one big
    batch (the whole point of fixed-global-batch elasticity)."""
    x, y = _toy_data(32)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.sgd(0.1)

    mesh = _mesh(2)
    tr = ElasticTrainer(
        mesh, _linear_loss, opt, global_batch_size=32, micro_batch_size=4
    )
    assert tr.accum_steps == 4
    p1, _, loss1 = tr.train_step(
        params, opt.init(params), jnp.asarray(x), jnp.asarray(y)
    )

    # one big-batch step on the same data
    loss_big, grads = jax.value_and_grad(_linear_loss)(
        params, jnp.asarray(x), jnp.asarray(y)
    )
    updates, _ = opt.update(grads, opt.init(params), params)
    p2 = optax.apply_updates(params, updates)

    np.testing.assert_allclose(loss1, loss_big, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_world_shrink_same_global_batch():
    """4-shard and 2-shard trainers apply the same global batch and
    produce the same parameters."""
    x, y = _toy_data(32)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.sgd(0.1)

    results = []
    for shards in (4, 2):
        tr = ElasticTrainer(
            _mesh(shards),
            _linear_loss,
            opt,
            global_batch_size=32,
            micro_batch_size=4,
        )
        assert tr.samples_per_step == 32
        p, _, _ = tr.train_step(
            params, opt.init(params), jnp.asarray(x), jnp.asarray(y)
        )
        results.append(p)
    for a, b in zip(jax.tree.leaves(results[0]), jax.tree.leaves(results[1])):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_training_converges():
    x, y = _toy_data(64)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.adam(0.05)
    tr = ElasticTrainer(
        _mesh(2), _linear_loss, opt, global_batch_size=64,
        micro_batch_size=8,
    )
    opt_state = opt.init(params)
    losses = []
    for _ in range(30):
        params, opt_state, loss = tr.train_step(
            params, opt_state, jnp.asarray(x), jnp.asarray(y)
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1


# -- sampler ---------------------------------------------------------------


def test_sampler_shards_partition_epoch():
    samplers = [
        ElasticDistributedSampler(100, num_shards=4, shard_rank=r, seed=7)
        for r in range(4)
    ]
    seen = []
    for s in samplers:
        seen.extend(list(s))
    assert len(seen) == 100  # padded 100->100 (divisible)
    assert sorted(seen) == sorted(set(seen))


def test_sampler_resume_after_world_change_no_replay():
    """Consume 40 samples on 4 shards, checkpoint, resume on 2 shards:
    the union of samples seen must cover the epoch exactly once."""
    first = [
        ElasticDistributedSampler(96, num_shards=4, shard_rank=r, seed=3)
        for r in range(4)
    ]
    seen = []
    iters = [iter(s) for s in first]
    for _ in range(10):  # 10 rounds x 4 shards = 40 samples
        for it in iters:
            seen.append(next(it))
    state = first[0].state_dict()
    assert state["consumed"] == 40

    resumed = []
    for r in range(2):
        s = ElasticDistributedSampler(96, num_shards=2, shard_rank=r, seed=3)
        s.load_state_dict(state)
        resumed.extend(list(s))
    total = seen + resumed
    assert sorted(total) == list(range(96))


def test_sampler_reshuffles_by_epoch():
    s = ElasticDistributedSampler(50, num_shards=1, shard_rank=0, seed=1)
    e0 = list(s)
    s.set_epoch(1)
    e1 = list(s)
    assert e0 != e1
    assert sorted(e0) == sorted(e1)


def test_dataloader_batches():
    data = np.arange(40, dtype=np.float32).reshape(20, 2)
    sampler = ElasticDistributedSampler(
        20, num_shards=2, shard_rank=0, shuffle=False
    )
    dl = ElasticDataLoader(data, batch_size=5, sampler=sampler)
    batches = list(dl)
    assert len(batches) == 2
    assert batches[0].shape == (5, 2)
    # shard 0 takes even positions when unshuffled
    np.testing.assert_array_equal(batches[0][:, 0], [0, 4, 8, 12, 16])
