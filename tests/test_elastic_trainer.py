"""ElasticTrainer fixed-global-batch semantics + checkpointable sampler."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.trainer.elastic_trainer import (
    ElasticDataLoader,
    ElasticDistributedSampler,
    ElasticTrainer,
    gradient_accumulation_steps,
)


def _mesh(data=4):
    return build_mesh(MeshConfig(data=data), devices=jax.devices()[:data])


def _linear_loss(params, x, y):
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


def _toy_data(n=64, d=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=(d, 1)).astype(np.float32)
    y = x @ w_true + 0.01 * rng.normal(size=(n, 1)).astype(np.float32)
    return x, y


def test_accum_steps_keeps_global_batch():
    # 8 shards x micro 4 = 32/step -> accum 4 for global 128
    assert gradient_accumulation_steps(128, 4, 8) == 4
    # losing half the shards doubles accumulation, global stays 128
    assert gradient_accumulation_steps(128, 4, 4) == 8
    # non-divisible rounds UP (effective batch never shrinks)
    assert gradient_accumulation_steps(100, 4, 8) == 4


def test_accumulated_step_equals_big_batch_step():
    """accum microbatches must produce the same update as one big
    batch (the whole point of fixed-global-batch elasticity)."""
    x, y = _toy_data(32)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.sgd(0.1)

    mesh = _mesh(2)
    # donate_state=False: ``params`` is aliased below to compute the
    # big-batch reference (the documented donation escape hatch).
    tr = ElasticTrainer(
        mesh, _linear_loss, opt, global_batch_size=32,
        micro_batch_size=4, donate_state=False,
    )
    assert tr.accum_steps == 4
    p1, _, loss1 = tr.train_step(params, opt.init(params), x, y)

    # one big-batch step on the same data
    loss_big, grads = jax.value_and_grad(_linear_loss)(
        params, jnp.asarray(x), jnp.asarray(y)
    )
    updates, _ = opt.update(grads, opt.init(params), params)
    p2 = optax.apply_updates(params, updates)

    np.testing.assert_allclose(loss1, loss_big, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_world_shrink_same_global_batch():
    """4-shard and 2-shard trainers apply the same global batch and
    produce the same parameters."""
    x, y = _toy_data(32)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.sgd(0.1)

    results = []
    for shards in (4, 2):
        tr = ElasticTrainer(
            _mesh(shards),
            _linear_loss,
            opt,
            global_batch_size=32,
            micro_batch_size=4,
            donate_state=False,  # params fed to BOTH trainers
        )
        assert tr.samples_per_step == 32
        p, _, _ = tr.train_step(params, opt.init(params), x, y)
        results.append(p)
    for a, b in zip(jax.tree.leaves(results[0]), jax.tree.leaves(results[1])):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_training_converges():
    x, y = _toy_data(64)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.adam(0.05)
    tr = ElasticTrainer(
        _mesh(2), _linear_loss, opt, global_batch_size=64,
        micro_batch_size=8,
    )
    opt_state = opt.init(params)
    losses = []
    for _ in range(30):
        # np host batch -> staged in train_step; state donated and
        # rebound each iteration (the intended steady-state shape).
        params, opt_state, loss = tr.train_step(
            params, opt_state, x, y
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1


# -- sampler ---------------------------------------------------------------


def test_sampler_shards_partition_epoch():
    samplers = [
        ElasticDistributedSampler(100, num_shards=4, shard_rank=r, seed=7)
        for r in range(4)
    ]
    seen = []
    for s in samplers:
        seen.extend(list(s))
    assert len(seen) == 100  # padded 100->100 (divisible)
    assert sorted(seen) == sorted(set(seen))


def test_sampler_resume_after_world_change_no_replay():
    """Consume 40 samples on 4 shards, checkpoint, resume on 2 shards:
    the union of samples seen must cover the epoch exactly once."""
    first = [
        ElasticDistributedSampler(96, num_shards=4, shard_rank=r, seed=3)
        for r in range(4)
    ]
    seen = []
    iters = [iter(s) for s in first]
    for _ in range(10):  # 10 rounds x 4 shards = 40 samples
        for it in iters:
            seen.append(next(it))
    state = first[0].state_dict()
    assert state["consumed"] == 40

    resumed = []
    for r in range(2):
        s = ElasticDistributedSampler(96, num_shards=2, shard_rank=r, seed=3)
        s.load_state_dict(state)
        resumed.extend(list(s))
    total = seen + resumed
    assert sorted(total) == list(range(96))


def test_sampler_reshuffles_by_epoch():
    s = ElasticDistributedSampler(50, num_shards=1, shard_rank=0, seed=1)
    e0 = list(s)
    s.set_epoch(1)
    e1 = list(s)
    assert e0 != e1
    assert sorted(e0) == sorted(e1)


# -- donation --------------------------------------------------------------


def _run_trajectory(donate: bool, steps: int = 6):
    x, y = _toy_data(32, seed=5)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.adam(0.05)
    tr = ElasticTrainer(
        _mesh(2), _linear_loss, opt, global_batch_size=32,
        micro_batch_size=4, donate_state=donate,
    )
    opt_state = opt.init(params)
    losses = []
    for _ in range(steps):
        params, opt_state, loss = tr.train_step(
            params, opt_state, x, y
        )
        losses.append(jax.device_get(loss))
    return np.asarray(losses), jax.device_get(params["w"])


def test_donation_numerics_parity():
    """The in-place (donated) step must be BITWISE identical to the
    copying step: donation changes buffer lifetime, not math."""
    losses_d, w_d = _run_trajectory(donate=True)
    losses_c, w_c = _run_trajectory(donate=False)
    np.testing.assert_array_equal(losses_d, losses_c)
    np.testing.assert_array_equal(w_d, w_c)


def test_donation_deletes_inputs_and_escape_hatch():
    x, y = _toy_data(32)
    opt = optax.sgd(0.1)

    def fresh():
        p = {"w": jnp.ones((8, 1)), "b": jnp.zeros((1,))}
        return p, opt.init(p)

    tr = ElasticTrainer(
        _mesh(2), _linear_loss, opt, global_batch_size=32,
        micro_batch_size=4,
    )
    assert tr.donate_state  # in-place update is the default
    params, opt_state = fresh()
    old_w = params["w"]
    tr.train_step(params, opt_state, x, y)
    assert old_w.is_deleted()  # XLA really updated in place

    tr2 = ElasticTrainer(
        _mesh(2), _linear_loss, opt, global_batch_size=32,
        micro_batch_size=4, donate_state=False,
    )
    params, opt_state = fresh()
    tr2.train_step(params, opt_state, x, y)
    assert not params["w"].is_deleted()  # escape hatch: alias freely
    _ = params["w"] + 1


# -- host-batch dispatch ---------------------------------------------------


def test_host_batch_dispatch_by_type_not_rank():
    """np.ndarray batches of ANY rank get staged; device arrays from
    shard_microbatches are fed through untouched (no re-staging)."""

    def loss3(params, x, y):  # x: [B, 4, 2] host batch (rank 3)
        flat = x.reshape((x.shape[0], -1))
        pred = flat @ params["w"] + params["b"]
        return jnp.mean((pred - y) ** 2)

    rng = np.random.default_rng(2)
    x = rng.normal(size=(16, 4, 2)).astype(np.float32)
    y = rng.normal(size=(16, 1)).astype(np.float32)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.sgd(0.1)
    tr = ElasticTrainer(
        _mesh(2), loss3, opt, global_batch_size=16,
        micro_batch_size=2, donate_state=False,
    )
    # rank-3 host batch: the old ndim==2 heuristic skipped staging
    p1, _, l1 = tr.train_step(params, opt.init(params), x, y)

    # pre-staged device arrays take the no-restage path, same result
    tok, tgt = tr.shard_microbatches(x, y)
    assert isinstance(tok, jax.Array)
    p2, _, l2 = tr.train_step(params, opt.init(params), tok, tgt)
    np.testing.assert_array_equal(
        jax.device_get(l1), jax.device_get(l2)
    )

    # a flat [N, ...] DEVICE batch (the pre-change jnp.asarray calling
    # convention) must fail loudly, pointing at shard_microbatches —
    # not error deep in lax.scan or silently mis-microbatch
    with pytest.raises(ValueError, match="pre-staged"):
        tr.train_step(
            params, opt.init(params), jnp.asarray(x), jnp.asarray(y)
        )


# -- async reporting -------------------------------------------------------


def test_async_reporter_exactly_once_in_order():
    from dlrover_tpu.trainer.async_metrics import AsyncScalarReporter

    class Lazy:
        """Device-scalar stand-in whose readiness we control."""

        def __init__(self, v):
            self.v = v
            self.ready = False

        def is_ready(self):
            return self.ready

        def __array__(self, dtype=None):  # jax.device_get fallback
            return np.asarray(self.v, dtype=dtype)

    got = []
    rep = AsyncScalarReporter(
        lambda step, v: got.append((step, v)), max_pending=3
    )
    vals = [Lazy(float(i)) for i in range(1, 7)]
    for i, v in enumerate(vals, start=1):
        rep.offer(i, v)
    # nothing ready, deque bounded at 3: the oldest were force-drained
    assert len(rep) == 3
    assert [s for s, _ in got] == [1, 2, 3]
    vals[3].ready = True  # step 4 finishes "on device"
    rep.drain_ready()
    assert [s for s, _ in got] == [1, 2, 3, 4]
    assert rep.flush() == 2  # tail delivered at checkpoint/shutdown
    assert [s for s, _ in got] == [1, 2, 3, 4, 5, 6]
    assert [v for _, v in got] == [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]
    assert rep.flush() == 0  # idempotent: nothing re-emitted


def test_trainer_reports_every_step_one_late_then_flush():
    x, y = _toy_data(32)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.sgd(0.1)
    reports = []
    tr = ElasticTrainer(
        _mesh(2), _linear_loss, opt, global_batch_size=32,
        micro_batch_size=4, report_fn=reports.append,
    )
    opt_state = opt.init(params)
    for _ in range(5):
        params, opt_state, _ = tr.train_step(params, opt_state, x, y)
    tr.flush_metrics()
    assert [r.step for r in reports] == [1, 2, 3, 4, 5]
    assert all(np.isfinite(r.loss) for r in reports)
    assert all(r.global_batch_size == 32 for r in reports)
    tr.flush_metrics()  # no duplicates on a second flush
    assert [r.step for r in reports] == [1, 2, 3, 4, 5]


# -- zero-sync hot loop ----------------------------------------------------


def test_hot_loop_no_host_sync_under_transfer_guard():
    """Steady-state tripwire: with pre-staged inputs, train_step plus
    async reporting performs NO device<->host transfer. Enforced two
    ways — jax.transfer_guard("disallow") (live on real accelerators;
    the CPU backend exempts same-memory transfers) and a patched
    Array.__float__ that turns any implicit scalar fetch into an
    error on every backend."""
    from jax._src import array as jax_array

    x, y = _toy_data(32)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.adam(0.05)
    reports = []
    tr = ElasticTrainer(
        _mesh(2), _linear_loss, opt, global_batch_size=32,
        micro_batch_size=4, report_fn=reports.append,
    )
    opt_state = opt.init(params)
    batches = [tr.shard_microbatches(x, y) for _ in range(4)]
    # step 1 pays the compile; the guard covers steady state only
    params, opt_state, _ = tr.train_step(params, opt_state, *batches[0])

    def _boom(self):
        raise AssertionError(
            "implicit device->host sync (float(arr)) in the hot loop"
        )

    orig = jax_array.ArrayImpl.__float__
    jax_array.ArrayImpl.__float__ = _boom
    try:
        with jax.transfer_guard("disallow"):
            for tok, tgt in batches[1:]:
                params, opt_state, loss = tr.train_step(
                    params, opt_state, tok, tgt
                )
                assert isinstance(loss, jax.Array)
            # the tripwire itself is live:
            with pytest.raises(AssertionError, match="hot loop"):
                float(loss)
    finally:
        jax_array.ArrayImpl.__float__ = orig
    tr.flush_metrics()
    assert [r.step for r in reports] == [1, 2, 3, 4]


# -- overlapped gradient reduction -----------------------------------------


def test_overlap_reduce_matches_serial_step():
    """The overlapped (bucketed per-microbatch reduce inside the
    scan) step must produce the same update as the serial
    accumulate-then-reduce step — the numerics-parity acceptance gate
    on the 8-device CPU mesh."""
    x, y = _toy_data(64)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.adam(0.05)
    mesh = _mesh(8)
    kw = dict(
        global_batch_size=64, micro_batch_size=4, donate_state=False
    )
    tr_serial = ElasticTrainer(mesh, _linear_loss, opt, **kw)
    tr_overlap = ElasticTrainer(
        mesh, _linear_loss, opt, overlap_reduce=True,
        reduce_bucket_mb=0.0001, **kw
    )
    assert tr_serial.accum_steps == tr_overlap.accum_steps == 2
    p_s, _, l_s = tr_serial.train_step(params, opt.init(params), x, y)
    p_o, _, l_o = tr_overlap.train_step(params, opt.init(params), x, y)
    np.testing.assert_allclose(
        float(l_s), float(l_o), rtol=1e-5
    )
    for a, b in zip(jax.tree.leaves(p_s), jax.tree.leaves(p_o)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_overlap_reduce_with_int8_buckets_converges():
    x, y = _toy_data(64)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.adam(0.05)
    tr = ElasticTrainer(
        _mesh(4), _linear_loss, opt, global_batch_size=64,
        micro_batch_size=8, overlap_reduce=True, reduce_bits=8,
        reduce_bucket_mb=1.0,
    )
    opt_state = opt.init(params)
    losses = []
    for _ in range(30):
        params, opt_state, loss = tr.train_step(
            params, opt_state, x, y
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1


def test_overlap_reduce_rejects_non_pure_data_mesh():
    from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh

    mesh = build_mesh(
        MeshConfig(data=2, fsdp=2), devices=jax.devices()[:4]
    )
    with pytest.raises(ValueError, match="pure data-parallel"):
        ElasticTrainer(
            mesh, _linear_loss, optax.sgd(0.1),
            global_batch_size=32, micro_batch_size=4,
            overlap_reduce=True,
        )


def test_overlap_reduce_rejects_external_step_fn():
    def step_fn(p, s, tok, tgt):
        return p, s, {"loss": jnp.float32(0)}

    with pytest.raises(ValueError, match="step_fn"):
        ElasticTrainer(
            _mesh(4), None, optax.sgd(0.1), global_batch_size=16,
            micro_batch_size=4, step_fn=step_fn, overlap_reduce=True,
        )


def test_overlap_env_knobs_resolve(monkeypatch):
    monkeypatch.setenv("DLROVER_TPU_OVERLAP_REDUCE", "1")
    monkeypatch.setenv("DLROVER_TPU_REDUCE_BUCKET_MB", "2")
    monkeypatch.setenv("DLROVER_TPU_REDUCE_BITS", "8")
    tr = ElasticTrainer(
        _mesh(2), _linear_loss, optax.sgd(0.1),
        global_batch_size=16, micro_batch_size=4,
    )
    assert tr.overlap_reduce and tr.reduce_bucket_mb == 2.0
    assert tr.reduce_bits == 8
    # explicit ctor args beat the env
    tr2 = ElasticTrainer(
        _mesh(2), _linear_loss, optax.sgd(0.1),
        global_batch_size=16, micro_batch_size=4,
        overlap_reduce=False,
    )
    assert not tr2.overlap_reduce


def test_overlap_env_default_downgrades_where_inapplicable(monkeypatch):
    """A fleet-wide DLROVER_TPU_OVERLAP_REDUCE=1 opt-in must not kill
    jobs the schedule can't apply to — only an EXPLICIT ctor
    overlap_reduce=True raises there (the two reject tests above)."""
    from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh

    monkeypatch.setenv("DLROVER_TPU_OVERLAP_REDUCE", "1")
    mesh = build_mesh(
        MeshConfig(data=2, fsdp=2), devices=jax.devices()[:4]
    )
    tr = ElasticTrainer(
        mesh, _linear_loss, optax.sgd(0.1),
        global_batch_size=32, micro_batch_size=4,
    )
    assert not tr.overlap_reduce  # downgraded to the serial step

    def step_fn(p, s, tok, tgt):
        return p, s, {"loss": jnp.float32(0)}

    tr2 = ElasticTrainer(
        _mesh(4), None, optax.sgd(0.1), global_batch_size=16,
        micro_batch_size=4, step_fn=step_fn,
    )
    assert not tr2.overlap_reduce


def test_overlap_metrics_noted_at_compile():
    from dlrover_tpu.obs.metrics import get_registry

    x, y = _toy_data(32)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.sgd(0.1)
    tr = ElasticTrainer(
        _mesh(2), _linear_loss, opt, global_batch_size=32,
        micro_batch_size=4, overlap_reduce=True, reduce_bits=8,
    )
    tr.train_step(params, opt.init(params), x, y)
    reg = get_registry()
    assert reg.get("dlrover_train_reduce_buckets").value() >= 1
    # accum=4 at int8: 4 * 3.0 B/el
    assert (
        reg.get("dlrover_train_sync_bytes_per_element").value()
        == 12.0
    )


def test_overlap_hot_loop_no_host_sync_under_transfer_guard():
    """The PR 2 zero-sync contract holds for the overlapped step too:
    steady state performs no device<->host transfer (the satellite
    guard that the new schedule introduced no hidden syncs)."""
    from jax._src import array as jax_array

    x, y = _toy_data(32)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.adam(0.05)
    reports = []
    tr = ElasticTrainer(
        _mesh(2), _linear_loss, opt, global_batch_size=32,
        micro_batch_size=4, report_fn=reports.append,
        overlap_reduce=True, reduce_bucket_mb=0.0001,
    )
    opt_state = opt.init(params)
    batches = [tr.shard_microbatches(x, y) for _ in range(4)]
    params, opt_state, _ = tr.train_step(params, opt_state, *batches[0])

    def _boom(self):
        raise AssertionError(
            "implicit device->host sync (float(arr)) in the "
            "overlapped hot loop"
        )

    orig = jax_array.ArrayImpl.__float__
    jax_array.ArrayImpl.__float__ = _boom
    try:
        with jax.transfer_guard("disallow"):
            for tok, tgt in batches[1:]:
                params, opt_state, loss = tr.train_step(
                    params, opt_state, tok, tgt
                )
                assert isinstance(loss, jax.Array)
    finally:
        jax_array.ArrayImpl.__float__ = orig
    tr.flush_metrics()
    assert [r.step for r in reports] == [1, 2, 3, 4]


def test_overlapped_step_sources_free_of_host_syncs():
    """AST tripwire (the CI satellite): the code that BUILDS the
    jitted overlapped step must contain no host-sync calls — float(),
    .item(), np.asarray, jax.device_get, block_until_ready. The
    runtime transfer-guard test catches dynamic syncs; this catches
    one added behind a rarely-hit branch."""
    import ast
    import inspect

    from dlrover_tpu.parallel import compression
    from dlrover_tpu.trainer.elastic_trainer import ElasticTrainer

    # int() is allowed: static shape arithmetic (int(np.prod(shape)))
    # never touches device buffers; float()/bool() on a traced value
    # are the classic implicit-sync shapes.
    FORBIDDEN_CALLS = {"float", "bool"}
    FORBIDDEN_ATTRS = {
        "item", "asarray", "device_get", "block_until_ready",
        "tolist",
    }

    def audit(fn_source, where):
        tree = ast.parse(fn_source)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name):
                assert f.id not in FORBIDDEN_CALLS, (
                    f"{where}:{node.lineno}: host sync {f.id}() in "
                    "the jitted overlapped step path"
                )
            if isinstance(f, ast.Attribute):
                assert f.attr not in FORBIDDEN_ATTRS, (
                    f"{where}:{node.lineno}: host sync .{f.attr}() "
                    "in the jitted overlapped step path"
                )

    import textwrap

    for fn, where in (
        (compression.bucketed_psum_mean, "bucketed_psum_mean"),
        (compression.make_compressed_train_step,
         "make_compressed_train_step"),
        (ElasticTrainer._build_overlapped_step,
         "_build_overlapped_step"),
    ):
        audit(textwrap.dedent(inspect.getsource(fn)), where)


# -- pipelined microbatch accumulation -------------------------------------


def test_pipelined_step_matches_serial_step_bitwise():
    """The acceptance gate: the host-driven microbatch pipeline must
    be BITWISE identical to the monolithic scan step on the 8-device
    CPU mesh — pipelining changes buffer lifetimes and dispatch
    order, never math."""
    x, y = _toy_data(64)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.adam(0.05)
    mesh = _mesh(8)
    kw = dict(
        global_batch_size=64, micro_batch_size=4, donate_state=False
    )
    tr_serial = ElasticTrainer(mesh, _linear_loss, opt, **kw)
    tr_pipe = ElasticTrainer(
        mesh, _linear_loss, opt, pipeline_depth=1, **kw
    )
    assert tr_serial.accum_steps == tr_pipe.accum_steps == 2
    p_s, _, l_s = tr_serial.train_step(params, opt.init(params), x, y)
    p_p, _, l_p = tr_pipe.train_step(params, opt.init(params), x, y)
    np.testing.assert_array_equal(
        jax.device_get(l_s), jax.device_get(l_p)
    )
    for a, b in zip(jax.tree.leaves(p_s), jax.tree.leaves(p_p)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pipelined_step_device_staged_batch_matches_host_path():
    """Pre-staged [accum, B, ...] device arrays (a device-resident
    prefetch queue) slice device-side and produce the same update as
    host staging."""
    x, y = _toy_data(64)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.adam(0.05)
    tr = ElasticTrainer(
        _mesh(8), _linear_loss, opt, global_batch_size=64,
        micro_batch_size=4, donate_state=False, pipeline_depth=2,
    )
    p_h, _, l_h = tr.train_step(params, opt.init(params), x, y)
    tok, tgt = tr.shard_microbatches(x, y)
    p_d, _, l_d = tr.train_step(params, opt.init(params), tok, tgt)
    np.testing.assert_array_equal(
        jax.device_get(l_h), jax.device_get(l_d)
    )
    for a, b in zip(jax.tree.leaves(p_h), jax.tree.leaves(p_d)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pipelined_composes_with_overlap_reduce():
    """pipeline_depth + overlap_reduce: per-microbatch bucketed
    reduce inside each pipelined micro program. Parity vs the serial
    step to float tolerance (the reduce schedule reorders sums,
    exactly like the monolithic overlapped step)."""
    x, y = _toy_data(64)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.adam(0.05)
    mesh = _mesh(8)
    kw = dict(
        global_batch_size=64, micro_batch_size=4, donate_state=False
    )
    tr_serial = ElasticTrainer(mesh, _linear_loss, opt, **kw)
    tr_po = ElasticTrainer(
        mesh, _linear_loss, opt, overlap_reduce=True,
        reduce_bucket_mb=0.0001, pipeline_depth=1, **kw
    )
    p_s, _, l_s = tr_serial.train_step(params, opt.init(params), x, y)
    p_o, _, l_o = tr_po.train_step(params, opt.init(params), x, y)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(l_s)),
        np.asarray(jax.device_get(l_o)),
        rtol=1e-5,
    )
    for a, b in zip(jax.tree.leaves(p_s), jax.tree.leaves(p_o)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )
    # the overlap observability rides along
    from dlrover_tpu.obs.metrics import get_registry

    assert (
        get_registry().get("dlrover_train_reduce_buckets").value() >= 1
    )


def test_pipelined_training_converges_with_donation():
    x, y = _toy_data(64)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.adam(0.05)
    tr = ElasticTrainer(
        _mesh(4), _linear_loss, opt, global_batch_size=64,
        micro_batch_size=4, pipeline_depth=1,
    )
    assert tr.donate_state and tr.accum_steps == 4
    opt_state = opt.init(params)
    losses = []
    for _ in range(30):
        params, opt_state, loss = tr.train_step(
            params, opt_state, x, y
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1


def test_pipelined_env_knob_and_step_fn_gates(monkeypatch):
    monkeypatch.setenv("DLROVER_TPU_PIPELINE_DEPTH", "2")
    tr = ElasticTrainer(
        _mesh(2), _linear_loss, optax.sgd(0.1),
        global_batch_size=16, micro_batch_size=4,
    )
    assert tr.pipeline_depth == 2
    # explicit ctor beats env
    tr2 = ElasticTrainer(
        _mesh(2), _linear_loss, optax.sgd(0.1),
        global_batch_size=16, micro_batch_size=4, pipeline_depth=0,
    )
    assert tr2.pipeline_depth == 0

    def step_fn(p, s, tok, tgt):
        return p, s, {"loss": jnp.float32(0)}

    # env default downgrades on an external step_fn...
    tr3 = ElasticTrainer(
        _mesh(4), None, optax.sgd(0.1), global_batch_size=16,
        micro_batch_size=4, step_fn=step_fn,
    )
    assert tr3.pipeline_depth == 0
    # ...but an explicit request raises
    monkeypatch.delenv("DLROVER_TPU_PIPELINE_DEPTH", raising=False)
    with pytest.raises(ValueError, match="microbatch schedule"):
        ElasticTrainer(
            _mesh(4), None, optax.sgd(0.1), global_batch_size=16,
            micro_batch_size=4, step_fn=step_fn, pipeline_depth=1,
        )


def test_pipelined_hot_loop_no_host_sync_under_transfer_guard():
    """The zero-sync contract holds for the pipelined step: steady
    state (pre-staged device batches) performs no implicit
    device<->host transfer and no float() fetch."""
    from jax._src import array as jax_array

    x, y = _toy_data(32)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.adam(0.05)
    reports = []
    tr = ElasticTrainer(
        _mesh(2), _linear_loss, opt, global_batch_size=32,
        micro_batch_size=4, report_fn=reports.append,
        pipeline_depth=1,
    )
    opt_state = opt.init(params)
    batches = [tr.shard_microbatches(x, y) for _ in range(4)]
    params, opt_state, _ = tr.train_step(params, opt_state, *batches[0])

    def _boom(self):
        raise AssertionError(
            "implicit device->host sync (float(arr)) in the "
            "pipelined hot loop"
        )

    orig = jax_array.ArrayImpl.__float__
    jax_array.ArrayImpl.__float__ = _boom
    try:
        with jax.transfer_guard("disallow"):
            for tok, tgt in batches[1:]:
                params, opt_state, loss = tr.train_step(
                    params, opt_state, tok, tgt
                )
                assert isinstance(loss, jax.Array)
    finally:
        jax_array.ArrayImpl.__float__ = orig
    tr.flush_metrics()
    assert [r.step for r in reports] == [1, 2, 3, 4]


def test_pipelined_host_staging_is_explicit_transfers_only():
    """Host-batch pipelining stages each microbatch via EXPLICIT
    device_put — legal under transfer_guard('disallow'), which only
    forbids implicit transfers. The tripwire float() stays armed."""
    from jax._src import array as jax_array

    x, y = _toy_data(32)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.adam(0.05)
    tr = ElasticTrainer(
        _mesh(2), _linear_loss, opt, global_batch_size=32,
        micro_batch_size=4, pipeline_depth=1,
    )
    opt_state = opt.init(params)
    params, opt_state, _ = tr.train_step(params, opt_state, x, y)

    orig = jax_array.ArrayImpl.__float__

    def _boom(self):
        raise AssertionError("implicit fetch in pipelined staging")

    jax_array.ArrayImpl.__float__ = _boom
    try:
        with jax.transfer_guard("disallow"):
            params, opt_state, loss = tr.train_step(
                params, opt_state, x, y
            )
            assert isinstance(loss, jax.Array)
    finally:
        jax_array.ArrayImpl.__float__ = orig


def test_pipelined_step_sources_free_of_host_syncs():
    """AST audit (the CI satellite, extended to the new builders):
    the code that builds and drives the pipelined step must contain
    no host-sync calls."""
    import ast
    import inspect
    import textwrap

    from dlrover_tpu.trainer import step as step_mod
    from dlrover_tpu.trainer.elastic_trainer import (
        ElasticTrainer,
        _PipelinedAdapter,
    )

    FORBIDDEN_CALLS = {"float", "bool"}
    FORBIDDEN_ATTRS = {
        "item", "asarray", "device_get", "block_until_ready",
        "tolist",
    }

    def audit(fn_source, where):
        tree = ast.parse(fn_source)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name):
                assert f.id not in FORBIDDEN_CALLS, (
                    f"{where}:{node.lineno}: host sync {f.id}() in "
                    "the pipelined step path"
                )
            if isinstance(f, ast.Attribute):
                assert f.attr not in FORBIDDEN_ATTRS, (
                    f"{where}:{node.lineno}: host sync .{f.attr}() "
                    "in the pipelined step path"
                )

    P = step_mod.PipelinedTrainStep
    for fn, where in (
        # The build path (__init__ holds the jitted micro/update
        # bodies) and everything the per-step drive touches. lower()
        # is excluded: it prices cost-analysis DICTS host-side and
        # never runs in the hot loop.
        (P.__init__, "PipelinedTrainStep.__init__"),
        (P.__call__, "PipelinedTrainStep.__call__"),
        (P._plan_input, "PipelinedTrainStep._plan_input"),
        (P._default_stage, "PipelinedTrainStep._default_stage"),
        (P.stage_batch, "PipelinedTrainStep.stage_batch"),
        (step_mod.make_pipelined_train_step,
         "make_pipelined_train_step"),
        (ElasticTrainer._build_pipelined_step,
         "_build_pipelined_step"),
        (ElasticTrainer.stage_microbatch, "stage_microbatch"),
        (_PipelinedAdapter, "_PipelinedAdapter"),
    ):
        audit(textwrap.dedent(inspect.getsource(fn)), where)


def test_make_pipelined_train_step_metrics_contract():
    """The standalone builder keeps make_train_step's metrics
    contract ({"loss","grad_norm"}) and matches it numerically on a
    flat accum=1 batch — without donating the caller's batch."""
    from dlrover_tpu.trainer.step import (
        make_pipelined_train_step,
        make_train_step,
        shard_batch,
    )

    x, y = _toy_data(32)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.sgd(0.1)
    mesh = _mesh(4)
    tok, tgt = shard_batch(mesh, jnp.asarray(x), jnp.asarray(y))

    base = make_train_step(mesh, _linear_loss, opt, donate=False)
    piped = make_pipelined_train_step(
        mesh, _linear_loss, opt, accum_steps=1, pipeline_depth=1,
        donate=False,
    )
    p_b, _, m_b = base(params, opt.init(params), tok, tgt)
    p_p, _, m_p = piped(params, opt.init(params), tok, tgt)
    assert set(m_p) == {"loss", "grad_norm"}
    assert not tok.is_deleted()  # caller's flat batch NOT donated
    np.testing.assert_allclose(
        float(m_b["loss"]), float(m_p["loss"]), rtol=1e-6
    )
    np.testing.assert_allclose(
        float(m_b["grad_norm"]), float(m_p["grad_norm"]), rtol=1e-6
    )
    for a, b in zip(jax.tree.leaves(p_b), jax.tree.leaves(p_p)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        )


def test_pipelined_flat_batch_of_one_not_misread_as_staged():
    """Review regression: a FLAT device batch whose leading dim is 1
    (global microbatch 1, the make_train_step convention api.py's
    dry-runs use) must not be misread as the [1, micro, ...] staged
    form — staged_device_inputs=False pins the flat reading and the
    caller's buffers stay undonated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dlrover_tpu.trainer.step import make_pipelined_train_step

    def loss1(params, x, y):  # works at batch size 1
        pred = x @ params["w"] + params["b"]
        return jnp.mean((pred - y) ** 2)

    mesh = _mesh(2)
    params = {"w": jnp.ones((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.sgd(0.1)
    step = make_pipelined_train_step(
        mesh, loss1, opt, accum_steps=1, pipeline_depth=1,
        donate=False, staged_device_inputs=False,
    )
    rep = NamedSharding(mesh, P())
    x = jax.device_put(jnp.ones((1, 8)), rep)  # flat [1, features]
    y = jax.device_put(jnp.ones((1, 1)), rep)
    p, _, m = step(params, opt.init(params), x, y)
    assert np.isfinite(float(m["loss"]))
    assert not x.is_deleted()  # flat passthrough never donates
    # second call reuses the same buffers — the dry-run loop shape
    step(p, opt.init(p), x, y)

    # lower() must read the batch the same way the step does: flat
    # pin -> priced with the full [1, 8] shape, no rank stripping
    lowered = step.lower(params, opt.init(params), x, y)
    assert lowered.cost_analysis()["flops"] > 0

    # and the staged pin rejects a wrong-shaped batch loudly
    staged = make_pipelined_train_step(
        mesh, loss1, opt, accum_steps=2, pipeline_depth=1,
        donate=False, staged_device_inputs=True,
    )
    with pytest.raises(ValueError, match="accum=2"):
        staged(params, opt.init(params), x, y)


def test_pipelined_donates_staged_microbatch_slots():
    """Donation-clean: the input slots the pipeline stages are
    consumed (deleted) as their microbatch executes — steady-state
    HBM beyond the in-flight slots is zero."""
    from dlrover_tpu.trainer.step import make_pipelined_train_step

    x, y = _toy_data(32)
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.sgd(0.1)
    mesh = _mesh(4)
    staged = []

    def spy_stage(tokens, targets, k):
        import jax as _jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(mesh, P("data"))
        mb = tokens.shape[0] // 2
        pair = (
            _jax.device_put(tokens[k * mb:(k + 1) * mb], sharding),
            _jax.device_put(targets[k * mb:(k + 1) * mb], sharding),
        )
        staged.append(pair)
        return pair

    step = make_pipelined_train_step(
        mesh, _linear_loss, opt, accum_steps=2, pipeline_depth=1,
        stage_fn=spy_stage,
    )
    step(params, opt.init(params), x, y)
    assert len(staged) == 2
    for tok_k, tgt_k in staged:
        assert tok_k.is_deleted() and tgt_k.is_deleted()


def test_dataloader_batches():
    data = np.arange(40, dtype=np.float32).reshape(20, 2)
    sampler = ElasticDistributedSampler(
        20, num_shards=2, shard_rank=0, shuffle=False
    )
    dl = ElasticDataLoader(data, batch_size=5, sampler=sampler)
    batches = list(dl)
    assert len(batches) == 2
    assert batches[0].shape == (5, 2)
    # shard 0 takes even positions when unshuffled
    np.testing.assert_array_equal(batches[0][:, 0], [0, 4, 8, 12, 16])
