"""PS-strategy auto-scaling: hot-PS migration + worker adjustment.

Mirrors tests/test_scaler.py style (fake cluster, synchronous
adjust_once passes). Parity targets:
dlrover/python/master/node/job_auto_scaler.py:98 (PSTrainingAutoScaler)
dlrover/python/master/resource/local_optimizer.py:66 (PSLocalOptimizer).
"""

import types

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.constants import (
    NodeStatus,
    NodeType,
    ps_node_id,
)
from dlrover_tpu.common.node import NodeResource
from dlrover_tpu.master.auto_scaler import (
    PsLocalOptimizer,
    PsTrainingAutoScaler,
)
from dlrover_tpu.master.job_manager import JobManager
from dlrover_tpu.master.scaler import FakeClusterClient, TPUPodScaler
from dlrover_tpu.master.speed_monitor import SpeedMonitor


class FakePsManager:
    """Just enough of master/ps_manager.py:PsManager for the scaler."""

    def __init__(self):
        self._stats = {}
        self.removed = []
        self.registered = set()

    def set_cpu(self, ps_id, cpu_percent):
        self._stats[ps_id] = msg.PsStatsReport(
            node_id=ps_id, cpu_percent=cpu_percent
        )
        self.registered.add(ps_id)

    def stats(self, max_age=None):
        return dict(self._stats)

    @property
    def partition_map(self):
        return types.SimpleNamespace(
            ps_addrs={i: f"addr-{i}" for i in sorted(self.registered)}
        )

    def remove_ps(self, ps_id):
        self.removed.append(ps_id)
        self.registered.discard(ps_id)
        self._stats.pop(ps_id, None)

    drain_ps = remove_ps


class TestPsLocalOptimizer:
    def test_hot_ps_detection_uses_window_average(self):
        opt = PsLocalOptimizer(ps_cpu_hot_threshold=0.9, window=3)
        for c in (95, 95, 95):
            opt.record_ps_sample(0, c)
        for c in (95, 40, 40):  # spiked once, cooled off
            opt.record_ps_sample(1, c)
        assert opt.hot_ps() == [0]

    def test_hot_ps_growth_plan_grows_never_shrinks(self):
        opt = PsLocalOptimizer(
            ps_cpu_hot_threshold=0.9, node_max_cpu=16.0
        )
        opt.record_ps_sample(0, 95.0)
        opt.record_ps_sample(1, 20.0)
        plan = opt.optimize_hot_ps({0: 4.0, 1: 4.0})
        assert 1 not in plan  # cold PS untouched
        assert plan[0] > 4.0
        assert plan[0] <= 16.0

    def test_worker_growth_from_ps_headroom(self):
        opt = PsLocalOptimizer(
            ps_cpu_overload_threshold=0.7, max_workers=64
        )
        opt.record_ps_sample(0, 35.0)  # util 0.35 -> factor 2
        for _ in range(opt.window):
            opt.record_speed_sample(4, 100.0)
        assert opt.optimize_worker_count(4) == 8

    def test_worker_growth_blocked_by_overloaded_ps(self):
        opt = PsLocalOptimizer(ps_cpu_overload_threshold=0.7)
        opt.record_ps_sample(0, 80.0)
        assert opt.optimize_worker_count(4) == 4

    def test_worker_growth_gated_on_marginal_speed_ratio(self):
        """Doubling workers only lifted speed 5% -> the marginal
        per-worker gain is way below min ratio; stop growing."""
        opt = PsLocalOptimizer(
            ps_cpu_overload_threshold=0.7, min_worker_speed_ratio=0.4
        )
        opt.record_ps_sample(0, 35.0)
        for _ in range(3):
            opt.record_speed_sample(4, 100.0)
        for _ in range(3):
            opt.record_speed_sample(8, 105.0)
        assert opt.worker_speed_ratio() < 0.4
        assert opt.optimize_worker_count(8) == 8

    def test_linear_scaling_keeps_growing(self):
        opt = PsLocalOptimizer(
            ps_cpu_overload_threshold=0.7, min_worker_speed_ratio=0.4
        )
        opt.record_ps_sample(0, 35.0)
        for _ in range(3):
            opt.record_speed_sample(4, 100.0)
        for _ in range(3):
            opt.record_speed_sample(8, 195.0)
        assert opt.worker_speed_ratio() > 0.9
        assert opt.optimize_worker_count(8) == 16


class TestPsTrainingAutoScaler:
    def _mk(self, ps_cpu, n_workers=2):
        client = FakeClusterClient()
        jm = JobManager(scaler=TPUPodScaler("job1", client))
        for i in range(n_workers):
            jm.register_node(node_id=i)
        ps = FakePsManager()
        ps_node = jm.register_node(
            node_type=NodeType.EMBEDDING,
            node_id=ps_node_id(100),
            resource=NodeResource(cpu=4.0, memory_mb=8192),
        )
        ps.set_cpu(100, ps_cpu)
        auto = PsTrainingAutoScaler(
            jm, SpeedMonitor(), ps, interval=999
        )
        return jm, ps, auto, ps_node

    def test_hot_ps_migration_launches_bigger_replacement(self):
        jm, ps, auto, old = self._mk(ps_cpu=95.0)
        plan = auto.adjust_once()
        assert plan is not None and len(plan.launch_nodes) == 1
        repl = plan.launch_nodes[0]
        assert repl.type == NodeType.EMBEDDING
        assert repl.config_resource.cpu > 4.0
        assert jm.get_node(repl.id).status == NodeStatus.PENDING
        # idempotent while the migration is pending
        assert auto._migrate_hot_ps() is None

    def test_migration_completes_on_replacement_registration(self):
        jm, ps, auto, old = self._mk(ps_cpu=95.0)
        plan = auto.adjust_once()
        repl = plan.launch_nodes[0]
        # replacement PS comes up and registers with the PsManager
        from dlrover_tpu.common.constants import node_ps_id

        ps.set_cpu(node_ps_id(repl.id), 10.0)
        auto.adjust_once()
        assert ps.removed == [100]
        assert auto._migrations == {}
        assert jm.get_node(ps_node_id(100)).status == NodeStatus.DELETED

    def test_dead_replacement_releases_migration_slot(self):
        """A replacement that dies before registering must not block
        the hot PS from being re-migrated forever."""
        from dlrover_tpu.common.constants import node_ps_id

        jm, ps, auto, old = self._mk(ps_cpu=95.0)
        plan = auto.adjust_once()
        repl = jm.get_node(plan.launch_nodes[0].id)
        repl.update_status(NodeStatus.FAILED)
        auto.adjust_once()
        assert 100 in auto._migrations  # retried with a fresh target
        assert auto._migrations[100] != node_ps_id(
            plan.launch_nodes[0].id
        )

    def test_cold_ps_triggers_worker_growth(self):
        jm, ps, auto, _ = self._mk(ps_cpu=35.0, n_workers=4)
        # gate needs real throughput evidence before growing
        for _ in range(5):
            auto.optimizer.record_speed_sample(4, 100.0)
        plan = auto.adjust_once()
        assert plan is not None
        assert all(
            n.type == NodeType.WORKER for n in plan.launch_nodes
        )
        assert len(plan.launch_nodes) == 4  # 4 -> 8 with factor 2

    def test_no_worker_growth_without_speed_evidence(self):
        jm, ps, auto, _ = self._mk(ps_cpu=35.0, n_workers=4)
        assert auto.adjust_once() is None  # gate fails closed
