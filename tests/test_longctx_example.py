"""CI wrapper for the long-context demo (examples/longctx): windowed
attention + GQA compact-KV + seq-sharded ring through auto_accelerate,
end to end as a user would run it. Same subprocess pattern as the
chaos-drill wrappers."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_windowed_longctx_example_smoke():
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(
                REPO, "examples", "longctx", "train_windowed.py"
            ),
            "--smoke",
        ],
        capture_output=True,
        text=True,
        timeout=600,
        cwd=REPO,
        env={
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        },
    )
    assert proc.returncode == 0, (
        f"example failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )
    assert "windowed seq-sharded training: loss" in proc.stdout
    # The script itself asserts the loss decreased; double-check the
    # mesh actually had a seq axis (the demo's point).
    assert "seq=2" in proc.stdout
