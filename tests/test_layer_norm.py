"""Fused Pallas LayerNorm/RMSNorm (+residual) vs plain XLA norms.

Interpreter mode on CPU exercises the exact kernels that compile on
TPU (same policy as tests/test_flash_attention.py). Parity target:
the reference's fused dropout_add_layer_norm integration
(atorch/modules/transformer/layers.py:74) at dropout 0.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.ops.layer_norm import (
    fused_add_layer_norm,
    fused_add_rms_norm,
    fused_layer_norm,
    fused_rms_norm,
)


def ref_ln(x, g, b, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, -1, keepdims=True)
    var = jnp.var(x32, -1, keepdims=True)
    out = (x32 - mu) * jax.lax.rsqrt(var + eps) * g
    if b is not None:
        out = out + b
    return out.astype(x.dtype)


def ref_rms(x, g, eps=1e-6):
    x32 = x.astype(jnp.float32)
    s = jax.lax.rsqrt(jnp.mean(x32**2, -1, keepdims=True) + eps)
    return (x32 * s * g).astype(x.dtype)


@pytest.fixture()
def data():
    k = jax.random.PRNGKey(0)
    # 210 rows: not a multiple of the row block, exercises padding.
    x = jax.random.normal(k, (3, 70, 64), jnp.float32)
    r = jax.random.normal(jax.random.PRNGKey(1), x.shape)
    g = jax.random.normal(jax.random.PRNGKey(2), (64,)) + 1.0
    b = jax.random.normal(jax.random.PRNGKey(3), (64,)) * 0.1
    return x, r, g, b


class TestForward:
    def test_layer_norm_matches(self, data):
        x, _, g, b = data
        np.testing.assert_allclose(
            fused_layer_norm(x, g, b), ref_ln(x, g, b),
            atol=1e-5, rtol=1e-5,
        )

    def test_rms_norm_matches(self, data):
        x, _, g, _ = data
        np.testing.assert_allclose(
            fused_rms_norm(x, g), ref_rms(x, g),
            atol=1e-5, rtol=1e-5,
        )

    def test_add_layer_norm_fuses_residual(self, data):
        x, r, g, b = data
        out, resid = fused_add_layer_norm(x, r, g, b)
        np.testing.assert_allclose(
            out, ref_ln(x + r, g, b), atol=1e-5, rtol=1e-5
        )
        np.testing.assert_allclose(resid, x + r, atol=1e-6)

    def test_bf16_no_bias_under_jit(self, data):
        x, _, g, _ = data
        xb = x.astype(jnp.bfloat16)
        got = jax.jit(fused_layer_norm)(xb, g, None)
        want = ref_ln(xb, g, None)
        np.testing.assert_allclose(
            got.astype(jnp.float32), want.astype(jnp.float32),
            atol=3e-2, rtol=3e-2,
        )


class TestBackward:
    def test_layer_norm_grads_match(self, data):
        x, _, g, b = data

        def f(x, g, b):
            return jnp.sum(jnp.sin(fused_layer_norm(x, g, b)))

        def ref(x, g, b):
            return jnp.sum(jnp.sin(ref_ln(x, g, b)))

        got = jax.grad(f, (0, 1, 2))(x, g, b)
        want = jax.grad(ref, (0, 1, 2))(x, g, b)
        for a, w in zip(got, want):
            np.testing.assert_allclose(a, w, atol=2e-4, rtol=2e-4)

    def test_add_norm_grads_include_residual_cotangent(self, data):
        """The (out, resid) second output feeds downstream compute:
        both cotangent paths into y = x + r must combine."""
        x, r, g, b = data

        def f(x, r, g, b):
            o, res = fused_add_layer_norm(x, r, g, b)
            return jnp.sum(jnp.sin(o)) + jnp.sum(res * 0.3)

        def ref(x, r, g, b):
            y = x + r
            return jnp.sum(jnp.sin(ref_ln(y, g, b))) + jnp.sum(
                y * 0.3
            )

        got = jax.grad(f, (0, 1, 2, 3))(x, r, g, b)
        want = jax.grad(ref, (0, 1, 2, 3))(x, r, g, b)
        for a, w in zip(got, want):
            np.testing.assert_allclose(a, w, atol=2e-4, rtol=2e-4)

    def test_add_rms_grads_match(self, data):
        x, r, g, _ = data

        def f(x, r, g):
            o, res = fused_add_rms_norm(x, r, g)
            return jnp.sum(jnp.cos(o)) + jnp.sum(res * 0.1)

        def ref(x, r, g):
            y = x + r
            return jnp.sum(jnp.cos(ref_rms(y, g))) + jnp.sum(
                y * 0.1
            )

        got = jax.grad(f, (0, 1, 2))(x, r, g)
        want = jax.grad(ref, (0, 1, 2))(x, r, g)
        for a, w in zip(got, want):
            np.testing.assert_allclose(a, w, atol=2e-4, rtol=2e-4)


class TestModelIntegration:
    def test_gpt_loss_and_grads_parity_fused_vs_plain(self):
        import dataclasses

        from dlrover_tpu.models import gpt

        base = gpt.GPTConfig(
            vocab_size=128, block_size=32, n_layer=2, n_head=2,
            n_embd=32, dtype=jnp.float32, remat=False,
        )
        tok = jax.random.randint(
            jax.random.PRNGKey(0), (2, 32), 0, 128
        )
        params = gpt.init_params(jax.random.PRNGKey(1), cfg=base)
        out = {}
        for fused in (False, True):
            cfg = dataclasses.replace(base, use_fused_norm=fused)
            loss_fn = functools.partial(gpt.loss_fn, cfg=cfg)
            loss, grads = jax.value_and_grad(loss_fn)(
                params, tok, tok
            )
            out[fused] = (float(loss), grads)
        assert out[False][0] == pytest.approx(out[True][0], rel=1e-5)
        for a, w in zip(
            jax.tree.leaves(out[True][1]),
            jax.tree.leaves(out[False][1]),
        ):
            np.testing.assert_allclose(a, w, atol=1e-4, rtol=1e-3)

    def test_llama_loss_parity_fused_vs_plain(self):
        import dataclasses

        from dlrover_tpu.models import llama

        base = llama.LlamaConfig(
            vocab_size=128, block_size=32, n_layer=2, n_head=4,
            n_kv_head=2, n_embd=32, intermediate=64,
            dtype=jnp.float32, remat=False,
        )
        tok = jax.random.randint(
            jax.random.PRNGKey(0), (2, 32), 0, 128
        )
        params = llama.init_params(jax.random.PRNGKey(1), cfg=base)
        losses = {}
        for fused in (False, True):
            cfg = dataclasses.replace(base, use_fused_norm=fused)
            losses[fused] = float(
                llama.loss_fn(params, tok, tok, cfg=cfg)
            )
        assert losses[True] == pytest.approx(
            losses[False], rel=1e-5
        )
