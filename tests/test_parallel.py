"""Parallel fabric + model tests on the virtual 8-device CPU mesh."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from dlrover_tpu.models import gpt
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.parallel.ring_attention import (
    make_sharded_attention,
    ring_attention,
)
from dlrover_tpu.parallel.sharding import (
    DEFAULT_RULES,
    prune_specs_to_mesh,
    spec_for,
    tree_specs,
)
from dlrover_tpu.trainer.step import (
    make_sharded_init,
    make_train_step,
    shard_batch,
)


class TestMesh:
    def test_resolve_wildcard(self):
        cfg = MeshConfig(data=-1, tensor=2).resolve(8)
        assert cfg.data == 4 and cfg.total == 8

    def test_build_8dev(self):
        mesh = build_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
        assert mesh.shape["data"] == 2
        assert mesh.shape["tensor"] == 2
        assert mesh.devices.size == 8

    def test_bad_product_raises(self):
        with pytest.raises(ValueError):
            build_mesh(MeshConfig(data=3, tensor=2))


class TestShardingRules:
    def test_spec_for(self):
        assert spec_for(("batch", "seq")) == P(("data", "fsdp"), "seq")
        assert spec_for((None, "embed")) == P(None, "fsdp")

    def test_prune(self):
        mesh = build_mesh(MeshConfig(data=8))
        spec = prune_specs_to_mesh(mesh, P(("data", "fsdp"), "tensor"))
        assert spec == P(("data",), None)


class TestRingAttention:
    def test_matches_plain_attention(self):
        mesh = build_mesh(MeshConfig(seq=8))
        b, t, h, d = 2, 64, 4, 16
        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (b, t, h, d), jnp.float32)
        k = jax.random.normal(kk, (b, t, h, d), jnp.float32)
        v = jax.random.normal(kv, (b, t, h, d), jnp.float32)

        for causal in (False, True):
            ring = make_sharded_attention(mesh, causal=causal)
            got = jax.jit(ring)(q, k, v)
            want = gpt._default_attention(q, k, v, causal=causal)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
            )

    def test_single_shard_fallback(self):
        mesh = build_mesh(MeshConfig(data=8))  # seq axis = 1
        b, t, h, d = 1, 16, 2, 8
        x = jax.random.normal(jax.random.PRNGKey(1), (b, t, h, d))
        attn = make_sharded_attention(mesh, causal=True)
        out = attn(x, x, x)
        want = gpt._default_attention(x, x, x, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5
        )


class TestWindowedSeqParallel:
    """Sliding-window attention composed with sequence sharding
    (VERDICT r4 weak #3): the windowed flash ring (static band-dead
    hop skipping), the masked XLA ring, and the banded a2a must all
    match single-device windowed attention, forward and backward."""

    def _qkv(self, b=2, t=64, h=4, d=16, seed=7):
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
        shape = (b, t, h, d)
        return (
            jax.random.normal(kq, shape, jnp.float32),
            jax.random.normal(kk, shape, jnp.float32),
            jax.random.normal(kv, shape, jnp.float32),
        )

    # Windows chosen against lq=16 (t=64 over seq=4): inside one ring
    # block, exactly one block, spanning two, spanning all, and wider
    # than the sequence (degenerates to plain causal).
    @pytest.mark.parametrize("window", [5, 16, 20, 40, 100])
    @pytest.mark.parametrize("impl", ["flash", "xla"])
    def test_ring_forward_matches_plain(self, window, impl):
        mesh = build_mesh(MeshConfig(seq=4, data=2))
        q, k, v = self._qkv()
        ring = make_sharded_attention(
            mesh, causal=True, impl=impl, window=window
        )
        got = jax.jit(ring)(q, k, v)
        want = gpt._default_attention(
            q, k, v, causal=True, window=window
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )

    @pytest.mark.parametrize(
        "window",
        [5, pytest.param(20, marks=pytest.mark.slow)],
    )
    def test_ring_gradients_match_plain(self, window):
        mesh = build_mesh(MeshConfig(seq=4, data=2))
        q, k, v = self._qkv(b=2, t=32, h=2, d=8)
        ring = make_sharded_attention(
            mesh, causal=True, impl="flash", window=window
        )

        def loss_ring(q, k, v):
            return jnp.sum(jnp.square(ring(q, k, v)))

        def loss_plain(q, k, v):
            return jnp.sum(jnp.square(gpt._default_attention(
                q, k, v, causal=True, window=window
            )))

        g1 = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        g2 = jax.grad(loss_plain, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), atol=5e-5, rtol=5e-4
            )

    @pytest.mark.parametrize("impl", ["flash", "xla"])
    def test_a2a_forward_matches_plain(self, impl):
        from dlrover_tpu.parallel.ulysses import make_a2a_attention

        mesh = build_mesh(MeshConfig(seq=4, data=2))
        q, k, v = self._qkv()
        a2a = make_a2a_attention(
            mesh, causal=True, impl=impl, window=20
        )
        got = jax.jit(a2a)(q, k, v)
        want = gpt._default_attention(q, k, v, causal=True, window=20)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )

    def test_seq_attention_dispatcher_passes_window(self):
        from dlrover_tpu.parallel.seq_attention import make_seq_attention

        mesh = build_mesh(MeshConfig(seq=4, data=2))
        q, k, v = self._qkv()
        for seq_impl in ("ring", "a2a", "auto"):
            attn = make_seq_attention(
                mesh, causal=True, seq_impl=seq_impl, window=12
            )
            got = jax.jit(attn)(q, k, v)
            want = gpt._default_attention(
                q, k, v, causal=True, window=12
            )
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want),
                atol=2e-5, rtol=2e-5, err_msg=seq_impl,
            )

    def test_window_requires_causal(self):
        mesh = build_mesh(MeshConfig(seq=4, data=2))
        with pytest.raises(ValueError, match="causal"):
            make_sharded_attention(
                mesh, causal=False, impl="flash", window=8
            )

    def test_windowed_ring_truncates_hops(self):
        """The band-dead ring tail must not be traced: with window <=
        lq the flash ring unrolls to exactly one ppermute pair (t=1
        reaches back lq-1 keys; t>=2 is statically dead), and a window
        reaching back two blocks to two pairs — checked on the jaxpr,
        where the static truncation is visible without compiling."""
        from dlrover_tpu.parallel.ring_attention import (
            ring_attention_flash,
        )
        from dlrover_tpu.parallel.shard_map_compat import shard_map

        mesh = build_mesh(MeshConfig(seq=4, data=2))
        spec = P(("data",), "seq", None, None)
        q, k, v = self._qkv()  # t=64 over seq=4 -> lq=16

        def count_ppermutes(window):
            fn = shard_map(
                functools.partial(
                    ring_attention_flash, causal=True, window=window
                ),
                mesh=mesh, in_specs=(spec, spec, spec),
                out_specs=spec, check_vma=False,
            )
            return str(jax.make_jaxpr(fn)(q, k, v)).count("ppermute")

        assert count_ppermutes(16) == 2   # k+v, t=1 only
        assert count_ppermutes(20) == 4   # band reaches block t=2


class TestGqaRing:
    """Grouped-query attention through the sequence-parallel families
    with COMPACT K/V: the ring rotates h_kv-head tensors (1/q_per_kv
    the ppermute bytes) and broadcasts per block; the a2a exchanges
    them compact when kv heads split over the axis. Parity against
    the pre-expanded path, plus a jaxpr-level traffic assertion."""

    H, HKV = 8, 2

    def _qkv(self, b=2, t=64, d=16, seed=9):
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
        q = jax.random.normal(kq, (b, t, self.H, d), jnp.float32)
        k = jax.random.normal(kk, (b, t, self.HKV, d), jnp.float32)
        v = jax.random.normal(kv, (b, t, self.HKV, d), jnp.float32)
        return q, k, v

    def _expanded(self, k):
        return jnp.repeat(k, self.H // self.HKV, axis=2)

    @pytest.mark.parametrize("impl", ["flash", "xla"])
    @pytest.mark.parametrize("window", [None, 20])
    def test_ring_matches_expanded(self, impl, window):
        mesh = build_mesh(MeshConfig(seq=4, data=2))
        q, k, v = self._qkv()
        ring = make_sharded_attention(
            mesh, causal=True, impl=impl, window=window
        )
        assert ring.supports_gqa
        got = jax.jit(ring)(q, k, v)
        want = gpt._default_attention(
            q, self._expanded(k), self._expanded(v),
            causal=True, window=window,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )

    @pytest.mark.slow
    def test_ring_gradients_match_expanded(self):
        mesh = build_mesh(MeshConfig(seq=4, data=2))
        q, k, v = self._qkv(t=32, d=8)
        ring = make_sharded_attention(mesh, causal=True, impl="flash")

        def loss_ring(q, k, v):
            return jnp.sum(jnp.square(ring(q, k, v)))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.square(gpt._default_attention(
                q, self._expanded(k), self._expanded(v), causal=True
            )))

        g1 = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), atol=5e-5, rtol=5e-4
            )

    @pytest.mark.parametrize("seq_n", [2, 4])
    def test_a2a_matches_expanded(self, seq_n):
        """seq=2: kv heads (2) split over the axis — compact a2a
        path; seq=4: they don't — pre-broadcast fallback."""
        from dlrover_tpu.parallel.ulysses import make_a2a_attention

        mesh = build_mesh(MeshConfig(seq=seq_n, data=8 // seq_n))
        q, k, v = self._qkv(b=8 // seq_n)
        a2a = make_a2a_attention(mesh, causal=True)
        assert a2a.supports_gqa
        got = jax.jit(a2a)(q, k, v)
        want = gpt._default_attention(
            q, self._expanded(k), self._expanded(v), causal=True
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )

    def test_single_shard_fallback_expands(self):
        mesh = build_mesh(MeshConfig(data=8))  # seq axis = 1
        q, k, v = self._qkv(b=1, t=16, d=8)
        attn = make_sharded_attention(mesh, causal=True)
        assert attn.supports_gqa
        got = attn(q, k, v)
        want = gpt._default_attention(
            q, self._expanded(k), self._expanded(v), causal=True
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )

    def test_ring_rotates_compact_kv(self):
        """The whole point: ppermute operands in the jaxpr carry the
        COMPACT kv head count, not the expanded one."""
        from dlrover_tpu.parallel.ring_attention import (
            ring_attention_flash,
        )
        from dlrover_tpu.parallel.shard_map_compat import shard_map

        mesh = build_mesh(MeshConfig(seq=4, data=2))
        spec = P(("data",), "seq", None, None)
        q, k, v = self._qkv()
        fn = shard_map(
            functools.partial(ring_attention_flash, causal=True),
            mesh=mesh, in_specs=(spec, spec, spec),
            out_specs=spec, check_vma=False,
        )
        txt = str(jax.make_jaxpr(fn)(q, k, v))
        ppermute_lines = [
            ln for ln in txt.splitlines() if "ppermute" in ln
        ]
        assert ppermute_lines, "no ppermute in jaxpr"
        # Every rotated tensor is [b, lq, HKV, d] per device — the
        # expanded head count (H=8) must NOT appear in any rotation.
        for ln in ppermute_lines:
            assert f",{self.HKV},"in ln.replace(" ", ""), ln
            assert f",{self.H},"not in ln.replace(" ", ""), ln


class TestRingFlashAttention:
    """Ring attention with the Pallas flash kernel per block
    (interpret mode on the CPU mesh) vs plain attention."""

    def _qkv(self, b=2, t=64, h=2, d=16):
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(2), 3)
        shape = (b, t, h, d)
        return (
            jax.random.normal(kq, shape, jnp.float32),
            jax.random.normal(kk, shape, jnp.float32),
            jax.random.normal(kv, shape, jnp.float32),
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_plain(self, causal):
        mesh = build_mesh(MeshConfig(seq=4, data=2))
        q, k, v = self._qkv()
        ring = make_sharded_attention(mesh, causal=causal, impl="flash")
        got = jax.jit(ring)(q, k, v)
        want = gpt._default_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )

    def test_gradients_match_plain(self):
        mesh = build_mesh(MeshConfig(seq=4, data=2))
        q, k, v = self._qkv(b=2, t=32, h=2, d=8)
        ring = make_sharded_attention(mesh, causal=True, impl="flash")

        def loss_ring(q, k, v):
            return jnp.sum(jnp.square(ring(q, k, v)))

        def loss_plain(q, k, v):
            return jnp.sum(
                jnp.square(gpt._default_attention(q, k, v, causal=True))
            )

        g1 = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        g2 = jax.grad(loss_plain, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), atol=5e-5, rtol=5e-4
            )

    def test_lse_output_and_grad(self):
        """flash_attention(return_lse=True): lse matches the naive
        logsumexp of scores and its cotangent reaches q/k."""
        from dlrover_tpu.ops.flash_attention import flash_attention

        b, t, h, d = 1, 32, 2, 8
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(3), 3)
        q = jax.random.normal(kq, (b, t, h, d), jnp.float32)
        k = jax.random.normal(kk, (b, t, h, d), jnp.float32)
        v = jax.random.normal(kv, (b, t, h, d), jnp.float32)
        _, lse = flash_attention(
            q, k, v, causal=False, interpret=True, return_lse=True
        )
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
        want = jax.scipy.special.logsumexp(s, axis=-1)
        np.testing.assert_allclose(
            np.asarray(lse), np.asarray(want), atol=2e-5, rtol=2e-5
        )

        def f(q, k, v):
            _, lse = flash_attention(
                q, k, v, causal=False, interpret=True, return_lse=True
            )
            return jnp.sum(lse * jnp.arange(t, dtype=jnp.float32))

        def f_ref(q, k, v):
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
            lse = jax.scipy.special.logsumexp(s, axis=-1)
            return jnp.sum(lse * jnp.arange(t, dtype=jnp.float32))

        g1 = jax.grad(f, argnums=(0, 1))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1))(q, k, v)
        for a, b_ in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), atol=5e-5, rtol=5e-4
            )


class TestA2AAttention:
    """Ulysses-style all-to-all sequence parallelism vs plain
    attention (the second context-parallel family next to the ring;
    ref atorch distributed_attention.py:80)."""

    def _qkv(self, b=2, t=64, h=4, d=16, seed=4):
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
        shape = (b, t, h, d)
        return (
            jax.random.normal(kq, shape, jnp.float32),
            jax.random.normal(kk, shape, jnp.float32),
            jax.random.normal(kv, shape, jnp.float32),
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_plain(self, causal):
        from dlrover_tpu.parallel.ulysses import make_a2a_attention

        mesh = build_mesh(MeshConfig(seq=4, data=2))
        q, k, v = self._qkv()  # 4 heads % 4 seq shards == 0
        a2a = make_a2a_attention(mesh, causal=causal)
        got = jax.jit(a2a)(q, k, v)
        want = gpt._default_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_with_tensor_cosharding(self, causal):
        """heads shard over tensor FIRST; the a2a then swaps each
        tensor shard's head group (4 heads / tensor 2 = 2, % seq 2
        == 0)."""
        from dlrover_tpu.parallel.ulysses import make_a2a_attention

        mesh = build_mesh(MeshConfig(data=2, seq=2, tensor=2))
        q, k, v = self._qkv()
        a2a = make_a2a_attention(mesh, causal=causal)
        got = jax.jit(a2a)(q, k, v)
        want = gpt._default_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )

    def test_flash_kernel_inner(self):
        from dlrover_tpu.parallel.ulysses import make_a2a_attention

        mesh = build_mesh(MeshConfig(seq=4, data=2))
        q, k, v = self._qkv()
        a2a = make_a2a_attention(mesh, causal=True, impl="flash")
        got = jax.jit(a2a)(q, k, v)
        want = gpt._default_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )

    def test_gradients_match_plain(self):
        from dlrover_tpu.parallel.ulysses import make_a2a_attention

        mesh = build_mesh(MeshConfig(seq=4, data=2))
        q, k, v = self._qkv(t=32, d=8)
        a2a = make_a2a_attention(mesh, causal=True)

        def loss_a2a(q, k, v):
            return jnp.sum(jnp.square(a2a(q, k, v)))

        def loss_plain(q, k, v):
            return jnp.sum(
                jnp.square(gpt._default_attention(q, k, v, causal=True))
            )

        g1 = jax.jit(jax.grad(loss_a2a, argnums=(0, 1, 2)))(q, k, v)
        g2 = jax.grad(loss_plain, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), atol=5e-5, rtol=5e-4
            )

    def test_head_divisibility_guard(self):
        from dlrover_tpu.parallel.ulysses import make_a2a_attention

        mesh = build_mesh(MeshConfig(seq=8))
        q, k, v = self._qkv(h=4)  # 4 heads, 8 seq shards
        a2a = make_a2a_attention(mesh, causal=True)
        with pytest.raises(ValueError, match="divisible"):
            jax.jit(a2a)(q, k, v)

    def test_single_shard_fallback(self):
        from dlrover_tpu.parallel.ulysses import make_a2a_attention

        mesh = build_mesh(MeshConfig(data=8))  # seq axis = 1
        q, k, v = self._qkv(b=1, t=16, h=2, d=8)
        a2a = make_a2a_attention(mesh, causal=True)
        want = gpt._default_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(a2a(q, k, v)), np.asarray(want),
            atol=2e-5, rtol=2e-5,
        )


class TestSeqImplDispatch:
    """make_seq_attention: the strategy-facing knob over ring vs a2a."""

    def test_choose_rules(self):
        from dlrover_tpu.parallel.seq_attention import choose_seq_impl

        assert choose_seq_impl(4, 1) == "ring"  # degenerate
        assert choose_seq_impl(8, 4) == "a2a"
        assert choose_seq_impl(6, 4) == "ring"  # 6 % 4 != 0
        # tensor co-sharding: 8/2=4 heads per tensor shard
        assert choose_seq_impl(8, 2, tensor_shards=2) == "a2a"
        assert choose_seq_impl(4, 4, tensor_shards=2) == "ring"
        assert choose_seq_impl(8, 3, tensor_shards=3) == "ring"

    @pytest.mark.parametrize("n_head", [2, 4])
    def test_auto_matches_plain_both_branches(self, n_head):
        """h=4 on seq=4 routes to a2a, h=2 to ring — both must be
        numerically plain attention."""
        from dlrover_tpu.parallel.seq_attention import make_seq_attention

        mesh = build_mesh(MeshConfig(seq=4, data=2))
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(7), 3)
        shape = (2, 32, n_head, 8)
        q = jax.random.normal(kq, shape, jnp.float32)
        k = jax.random.normal(kk, shape, jnp.float32)
        v = jax.random.normal(kv, shape, jnp.float32)
        attn = make_seq_attention(mesh, causal=True)
        want = gpt._default_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(jax.jit(attn)(q, k, v)), np.asarray(want),
            atol=2e-5, rtol=2e-5,
        )

    def test_llama_gqa_composes_with_a2a(self):
        """The Llama family's grouped-query attention repeats kv heads
        to full count BEFORE attn_fn, so the a2a family's head
        constraint sees full heads — the dispatcher must route and
        match the dense forward."""
        from dlrover_tpu.models import llama
        from dlrover_tpu.parallel.seq_attention import (
            choose_seq_impl,
            make_seq_attention,
        )

        cfg = llama.LlamaConfig(
            vocab_size=64, block_size=32, n_layer=2, n_head=4,
            n_kv_head=2, n_embd=32, intermediate=64,
            dtype=jnp.float32, remat=False,
        )
        mesh = build_mesh(MeshConfig(data=2, seq=4))
        assert choose_seq_impl(cfg.n_head, 4, 1) == "a2a"
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        tok = jax.random.randint(
            jax.random.PRNGKey(1), (2, cfg.block_size), 0,
            cfg.vocab_size,
        )
        tgt = jnp.roll(tok, -1, axis=1)
        dense = float(llama.loss_fn(params, tok, tgt, cfg=cfg))
        attn = make_seq_attention(mesh, causal=True)
        sharded = float(
            jax.jit(
                functools.partial(
                    llama.loss_fn, cfg=cfg, attn_fn=attn
                )
            )(params, tok, tgt)
        )
        np.testing.assert_allclose(sharded, dense, rtol=2e-5)

    def test_explicit_impls_and_validation(self):
        from dlrover_tpu.parallel.seq_attention import make_seq_attention

        mesh = build_mesh(MeshConfig(seq=2, data=4))
        q = jax.random.normal(jax.random.PRNGKey(8), (4, 16, 2, 8))
        for forced in ("ring", "a2a"):
            attn = make_seq_attention(mesh, causal=True, seq_impl=forced)
            want = gpt._default_attention(q, q, q, causal=True)
            np.testing.assert_allclose(
                np.asarray(jax.jit(attn)(q, q, q)), np.asarray(want),
                atol=2e-5, rtol=2e-5,
            )
        with pytest.raises(ValueError, match="seq_impl"):
            make_seq_attention(mesh, seq_impl="bogus")


def _tiny_cfg(**kw):
    base = dict(
        vocab_size=256,
        block_size=64,
        n_layer=2,
        n_head=2,
        n_embd=64,
        dtype=jnp.float32,
        remat=False,
    )
    base.update(kw)
    return gpt.GPTConfig(**base)


class TestGPT:
    def test_forward_shapes_and_finite(self):
        cfg = _tiny_cfg()
        params = gpt.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jnp.zeros((2, 32), jnp.int32)
        logits = gpt.forward(params, tokens, cfg)
        assert logits.shape == (2, 32, cfg.vocab_size)
        assert bool(jnp.isfinite(logits).all())

    @pytest.mark.slow
    def test_loss_decreases_single_device(self):
        cfg = _tiny_cfg()
        params = gpt.init_params(jax.random.PRNGKey(0), cfg)
        opt = optax.adamw(1e-3)
        opt_state = opt.init(params)
        loss = functools.partial(gpt.loss_fn, cfg=cfg)
        step = make_train_step(
            build_mesh(MeshConfig(data=8)), loss, opt, donate=False
        )
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size
        )
        targets = jnp.roll(tokens, -1, axis=1)
        losses = []
        for _ in range(5):
            params, opt_state, metrics = step(
                params, opt_state, tokens, targets
            )
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]

    def test_logical_axes_tree_matches_params(self):
        cfg = _tiny_cfg()
        params = gpt.init_params(jax.random.PRNGKey(0), cfg)
        axes = gpt.param_logical_axes(cfg)
        jax.tree.map(
            lambda p, a: None
            if len(p.shape) == len(a)
            else pytest.fail(f"rank mismatch {p.shape} vs {a}"),
            params,
            axes,
            is_leaf=lambda x: isinstance(x, tuple)
            and all(isinstance(e, (str, type(None))) for e in x),
        )


class TestShardedTraining:
    @pytest.mark.parametrize(
        "mesh_cfg",
        [
            MeshConfig(data=8),
            pytest.param(
                MeshConfig(data=2, fsdp=4), marks=pytest.mark.slow
            ),
            pytest.param(
                MeshConfig(fsdp=2, tensor=4), marks=pytest.mark.slow
            ),
            pytest.param(
                MeshConfig(data=2, fsdp=2, tensor=2),
                marks=pytest.mark.slow,
            ),
        ],
        ids=["dp", "dp-fsdp", "fsdp-tp", "dp-fsdp-tp"],
    )
    def test_train_step_all_strategies(self, mesh_cfg):
        mesh = build_mesh(mesh_cfg)
        cfg = _tiny_cfg()
        opt = optax.adamw(1e-3)
        loss = functools.partial(gpt.loss_fn, cfg=cfg)
        init, shardings = make_sharded_init(
            mesh,
            functools.partial(gpt.init_params, cfg=cfg),
            gpt.param_logical_axes(cfg),
            opt,
        )
        params, opt_state = init(jax.random.PRNGKey(0))
        step = make_train_step(mesh, loss, opt)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size
        )
        tokens, targets = shard_batch(
            mesh, tokens, jnp.roll(tokens, -1, axis=1)
        )
        params, opt_state, metrics = step(
            params, opt_state, tokens, targets
        )
        assert bool(jnp.isfinite(metrics["loss"]))
        # Weights actually sharded when a weight axis is in the mesh.
        wqkv = params["blocks"]["wqkv"]
        n_shards = len({s.device for s in wqkv.addressable_shards})
        weight_ways = mesh.shape.get("fsdp", 1) * mesh.shape.get(
            "tensor", 1
        )
        if weight_ways > 1:
            assert not wqkv.sharding.is_fully_replicated
        assert n_shards == 8  # placed on every device

    @pytest.mark.slow
    def test_save_attn_remat_matches_full_when_sharded(self):
        """save_attn under GSPMD: same loss as full remat on a
        sharded mesh with the flash kernel forced — the checkpoint
        policy must compose with sharded scan + the named pallas fwd
        (tests/test_remat_policies.py proves the single-device
        structure; this proves the mesh path)."""
        mesh = build_mesh(MeshConfig(data=2, fsdp=4))
        losses = {}
        for remat in (True, "save_attn"):
            cfg = _tiny_cfg(
                remat=remat,
                use_flash_attention=True,  # forces flash off-TPU too
                block_size=128,
                attn_blocks=(128, 128, 128, 128),
            )
            loss = functools.partial(gpt.loss_fn, cfg=cfg)
            opt = optax.adamw(1e-3)
            init, _ = make_sharded_init(
                mesh,
                functools.partial(gpt.init_params, cfg=cfg),
                gpt.param_logical_axes(cfg),
                opt,
            )
            params, opt_state = init(jax.random.PRNGKey(0))
            step = make_train_step(mesh, loss, opt)
            tokens = jax.random.randint(
                jax.random.PRNGKey(1), (8, 128), 0, cfg.vocab_size
            )
            tokens, targets = shard_batch(
                mesh, tokens, jnp.roll(tokens, -1, axis=1)
            )
            for _ in range(2):
                params, opt_state, metrics = step(
                    params, opt_state, tokens, targets
                )
            losses[str(remat)] = float(metrics["loss"])
        assert losses["True"] == pytest.approx(
            losses["save_attn"], rel=1e-5
        )

    @pytest.mark.slow
    def test_seq_parallel_with_ring_attention(self):
        mesh = build_mesh(MeshConfig(seq=4, data=2))
        cfg = _tiny_cfg()
        attn = make_sharded_attention(mesh, causal=True)
        loss = functools.partial(gpt.loss_fn, cfg=cfg, attn_fn=attn)
        opt = optax.adamw(1e-3)
        init, _ = make_sharded_init(
            mesh,
            functools.partial(gpt.init_params, cfg=cfg),
            gpt.param_logical_axes(cfg),
            opt,
        )
        params, opt_state = init(jax.random.PRNGKey(0))
        step = make_train_step(mesh, loss, opt)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, 64), 0, cfg.vocab_size
        )
        tokens, targets = shard_batch(
            mesh, tokens, jnp.roll(tokens, -1, axis=1)
        )
        params, opt_state, metrics = step(
            params, opt_state, tokens, targets
        )
        assert bool(jnp.isfinite(metrics["loss"]))


class TestMultiSlice:
    """Multi-slice (DCN) meshes with two virtual slices on CPU
    (ref: multi-node NCCL bootstrap, atorch distributed.py:587)."""

    def test_two_virtual_slices_outer_axis_is_slice_pure(self):
        from dlrover_tpu.parallel.mesh import (
            MeshConfig,
            build_mesh,
            mesh_slice_blocks,
        )

        devs = jax.devices()[:8]
        slice_ids = [0] * 4 + [1] * 4
        mesh = build_mesh(
            MeshConfig(data=2, fsdp=2, tensor=2, num_slices=2),
            devices=devs,
            slice_ids=slice_ids,
        )
        blocks = mesh_slice_blocks(mesh, 2)
        assert set(blocks[0]) == set(devs[:4])
        assert set(blocks[1]) == set(devs[4:])
        # the outer (data) axis blocks ARE the slices: data index 0
        # holds only slice-0 devices
        data0 = set(mesh.devices[0].flat)
        assert data0 == set(devs[:4])

    def test_interleaved_slice_ids_are_regrouped(self):
        from dlrover_tpu.parallel.mesh import group_devices_by_slice

        devs = jax.devices()[:8]
        # devices arrive interleaved across slices
        ids = [0, 1, 0, 1, 0, 1, 0, 1]
        ordered, oids = group_devices_by_slice(devs, 2, ids)
        assert oids == [0] * 4 + [1] * 4
        assert set(ordered[:4]) == {devs[0], devs[2], devs[4], devs[6]}

    def test_uneven_slices_rejected(self):
        from dlrover_tpu.parallel.mesh import group_devices_by_slice

        with pytest.raises(ValueError, match="uneven"):
            group_devices_by_slice(
                jax.devices()[:8], 2, [0, 0, 0, 0, 0, 1, 1, 1]
            )

    def test_indivisible_outer_axis_rejected(self):
        from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh

        with pytest.raises(ValueError, match="not divisible"):
            build_mesh(
                MeshConfig(data=3, tensor=2, num_slices=2),
                devices=jax.devices()[:6],
            )

    def test_train_step_runs_on_two_slice_mesh(self):
        """A real sharded computation over the 2-slice mesh: the data
        (DCN) axis psum and inner-axis collectives both execute."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh

        mesh = build_mesh(
            MeshConfig(data=2, fsdp=2, tensor=2, num_slices=2),
            devices=jax.devices()[:8],
            slice_ids=[0] * 4 + [1] * 4,
        )
        x = jnp.arange(32.0).reshape(8, 4)
        xs = jax.device_put(
            x, NamedSharding(mesh, P(("data", "fsdp"), "tensor"))
        )

        @jax.jit
        def global_mean(v):
            return jnp.mean(v)  # all-reduce across every axis incl DCN

        np.testing.assert_allclose(
            float(global_mean(xs)), float(x.mean()), rtol=1e-6
        )
