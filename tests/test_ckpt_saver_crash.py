"""Saver-crash chaos: SIGKILL the agent-side checkpoint saver MID-
PERSIST and prove the commit protocol's crash story at the process
level — the tracker never references the interrupted step, the staged
state survives in shm across the process death, and a restarted saver
(the relaunched agent) flushes and commits it intact.

This is the scenario the flash-checkpoint design exists for (ref
async-checkpoint design: the trainer is only blocked for staging
precisely BECAUSE the persist can die with the host); unit tests
cover commit idempotency in-process, this covers the real kill."""

import os
import signal
import subprocess
import sys
import time
import uuid

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = """
import os, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver
from dlrover_tpu.trainer.flash_checkpoint.engine import CheckpointEngine

ckpt_dir = sys.argv[1]
saver = AsyncCheckpointSaver(
    checkpoint_dir=ckpt_dir, local_shard_num=1, global_shard_num=1,
    commit_timeout=300.0,
)
saver.start()
engine = CheckpointEngine(ckpt_dir, use_agent=True)
# Large enough that the shard write takes seconds on this disk — the
# parent kills us inside that window.
state = {{
    "big": jnp.arange(100 * 1024 * 1024 // 4, dtype=jnp.float32),
    "small": jnp.full((8,), 7.0),
}}
assert engine.save_to_storage(42, state)
print("STAGED", flush=True)
time.sleep(600)  # parent SIGKILLs us mid-persist
"""


def test_saver_sigkill_mid_persist_recovers(tmp_path, monkeypatch):
    job = f"crash{uuid.uuid4().hex[:8]}"
    monkeypatch.setenv("DLROVER_TPU_JOB_NAME", job)
    ckpt_dir = str(tmp_path / "ckpt")
    os.makedirs(ckpt_dir)
    script = tmp_path / "child.py"
    script.write_text(CHILD.format(repo=REPO))

    proc = subprocess.Popen(
        [sys.executable, "-u", str(script), ckpt_dir],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        start_new_session=True,
    )
    try:
        # Kill the instant the writing dir appears — mid shard write,
        # before the commit rename.
        deadline = time.time() + 120
        killed_mid_write = False
        while time.time() < deadline:
            entries = (
                os.listdir(ckpt_dir) if os.path.isdir(ckpt_dir) else []
            )
            if any(e.startswith("._writing_") for e in entries):
                os.killpg(proc.pid, signal.SIGKILL)
                killed_mid_write = True
                break
            if proc.poll() is not None:
                pytest.fail(
                    "child exited early:\n"
                    + (proc.stdout.read() if proc.stdout else "")
                )
            time.sleep(0.002)
        assert killed_mid_write, "writing dir never appeared"
        proc.wait(30)

        # The interrupted step must NOT be visible as committed.
        tracker = os.path.join(ckpt_dir, "latest_checkpointed_step")
        assert not os.path.exists(tracker) or int(
            open(tracker).read().strip()
        ) < 42

        # A restarted agent adopts the SAME shm segment (it survived
        # the process death — the design's whole point) and flushes
        # the staged step to a committed checkpoint.
        from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver
        from dlrover_tpu.trainer.flash_checkpoint.engine import (
            CheckpointEngine,
        )

        saver = AsyncCheckpointSaver(
            checkpoint_dir=ckpt_dir, local_shard_num=1,
            global_shard_num=1, commit_timeout=120.0,
        )
        saver.start()
        try:
            assert saver.save_shm_to_storage(), (
                "restarted saver could not flush the staged shm "
                "(stale lock from the killed process?)"
            )
            engine = CheckpointEngine(ckpt_dir, use_agent=False)
            assert engine.latest_step() == 42
            step, flat, _extra = engine.load_flat()
            assert step == 42
            big = flat["big"]
            assert big.nbytes == 100 * 1024 * 1024
            np.testing.assert_array_equal(
                big[:5], np.arange(5, dtype=np.float32)
            )
            np.testing.assert_array_equal(
                big[-1], np.float32(big.size - 1)
            )
            np.testing.assert_array_equal(
                np.asarray(flat["small"]), np.full((8,), 7.0)
            )
        finally:
            saver.close()
    finally:
        if proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        # Unlink the child's ~110 MB shm segment on EVERY path —
        # keyed by the handler's naming, not a saver object that may
        # never have been constructed (uuid job names mean a leaked
        # segment is never reclaimed by a rerun).
        from dlrover_tpu.common.ckpt_shm import SharedMemoryHandler

        try:
            SharedMemoryHandler(0).unlink()
        except Exception:  # noqa: BLE001
            pass
