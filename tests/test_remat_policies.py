"""Named remat/offload policies (ref
selective_offloading_checkpoint.py): every policy computes identical
loss and gradients — only the memory/time tradeoff differs.
"""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.accelerate.remat import POLICY_NAMES, canonical
from dlrover_tpu.models import gpt


def _cfg(remat):
    return gpt.GPTConfig(
        vocab_size=128,
        block_size=32,
        n_layer=2,
        n_head=2,
        n_embd=32,
        dtype=jnp.float32,
        remat=remat,
    )


def _loss_and_grads(remat, **cfg_overrides):
    cfg = dataclasses.replace(_cfg(remat), **cfg_overrides)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, cfg.block_size), 0, cfg.vocab_size
    )
    targets = jnp.roll(tokens, -1, axis=1)
    loss_fn = functools.partial(gpt.loss_fn, cfg=cfg)
    return jax.jit(jax.value_and_grad(loss_fn))(
        params, tokens, targets
    )


class TestRematPolicies:
    def test_canonical_names(self):
        assert canonical(True) == "full"
        assert canonical(False) == "none"
        assert canonical(None) == "none"
        for n in POLICY_NAMES:
            assert canonical(n) == n
        with pytest.raises(ValueError, match="unknown remat"):
            canonical("bogus")

    @pytest.mark.parametrize(
        "policy",
        ["full", "attention", "dots", "offload", "save_attn", True],
    )
    def test_policy_matches_no_remat(self, policy):
        """Loss and every gradient identical to remat='none' — remat
        is a memory knob, never a numerics knob."""
        try:
            base_loss, base_grads = _loss_and_grads("none")
            loss, grads = _loss_and_grads(policy)
        except Exception as exc:  # noqa: BLE001
            if "pinned_host" in str(exc) or "memory kind" in str(exc):
                pytest.skip(f"backend lacks host offload: {exc}")
            raise
        np.testing.assert_allclose(
            float(loss), float(base_loss), rtol=1e-6
        )
        for a, b in zip(
            jax.tree.leaves(grads), jax.tree.leaves(base_grads)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5
            )

    def test_full_remat_uses_less_temp_memory_than_none(self):
        """XLA's own accounting: recompute trades memory for FLOPs."""
        def build(remat):
            cfg = _cfg(remat)
            params = gpt.init_params(jax.random.PRNGKey(0), cfg)
            tokens = jnp.zeros((4, cfg.block_size), jnp.int32)
            loss_fn = functools.partial(gpt.loss_fn, cfg=cfg)
            return (
                jax.jit(jax.grad(loss_fn))
                .lower(params, tokens, tokens)
                .compile()
                .memory_analysis()
            )

        m_none = build("none")
        m_full = build("full")
        if m_none is None or m_full is None:
            pytest.skip("backend lacks memory analysis")
        assert (
            m_full.temp_size_in_bytes < m_none.temp_size_in_bytes
        )

    def test_strategy_carries_named_policy(self):
        from dlrover_tpu.accelerate.strategy import Strategy

        s = Strategy(
            mesh_shape=(("data", 8),), remat="offload"
        )
        assert "remat:offload" in s.name()
        assert Strategy.from_json(s.to_json()).remat == "offload"
        assert "remat:full" in Strategy(
            mesh_shape=(("data", 8),), remat=True
        ).name()


def _pallas_outvar_counts(jaxpr, acc):
    """Outvar count of every pallas_call eqn, recursively — the flash
    forward has 2 outputs (o, lse), the backward 3 (dq, dk, dv)."""
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            acc.append(len(eqn.outvars))
        for key in ("jaxpr", "call_jaxpr", "fun_jaxpr", "branches"):
            v = eqn.params.get(key)
            if v is None:
                continue
            for x in v if isinstance(v, (tuple, list)) else [v]:
                if hasattr(x, "jaxpr"):
                    x = x.jaxpr
                if hasattr(x, "eqns"):
                    _pallas_outvar_counts(x, acc)
    return acc


class TestSaveAttnPolicy:
    """save_attn's whole point is structural: the flash forward kernel
    must be traced ONCE (its saved (o, lse) feed the backward), where
    full remat traces it twice. Assert that on the jaxpr — a numerics
    test alone would pass even if the policy silently stopped
    working."""

    def _grad_jaxpr(self, remat):
        cfg = dataclasses.replace(
            _cfg(remat),
            block_size=128,
            use_flash_attention=True,
            attn_blocks=(128, 128, 128, 128),
        )
        params = gpt.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jnp.zeros((1, cfg.block_size), jnp.int32)
        loss_fn = functools.partial(gpt.loss_fn, cfg=cfg)
        return jax.make_jaxpr(jax.grad(loss_fn))(
            params, tokens, tokens
        )

    def test_fwd_kernel_not_recomputed(self):
        full = _pallas_outvar_counts(self._grad_jaxpr("full").jaxpr, [])
        sa = _pallas_outvar_counts(
            self._grad_jaxpr("save_attn").jaxpr, []
        )
        # full remat: fwd (2 outs) twice + bwd (3 outs) once per
        # layer-scan trace; save_attn: fwd once + bwd once.
        assert sorted(full) == [2, 2, 3], full
        assert sorted(sa) == [2, 3], sa

    def test_grad_parity_with_flash(self):
        def grads(remat):
            cfg = dataclasses.replace(
                _cfg(remat),
                block_size=128,
                use_flash_attention=True,
                attn_blocks=(128, 128, 128, 128),
            )
            params = gpt.init_params(jax.random.PRNGKey(0), cfg)
            tokens = jax.random.randint(
                jax.random.PRNGKey(1), (1, 128), 0, cfg.vocab_size
            )
            loss_fn = functools.partial(gpt.loss_fn, cfg=cfg)
            return jax.jit(jax.grad(loss_fn))(params, tokens, tokens)

        for a, b in zip(
            jax.tree.leaves(grads("save_attn")),
            jax.tree.leaves(grads("full")),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
            )


class TestScanUnroll:
    """cfg.scan_unroll is a pure scheduling knob: loss and gradients
    must be bit-comparable across unroll factors, including a factor
    that does not divide n_layer and one larger than it."""

    @pytest.mark.parametrize("unroll", [2, 3])
    def test_unroll_parity(self, unroll):
        base_loss, base_g = _loss_and_grads(True, scan_unroll=1)
        loss, g = _loss_and_grads(True, scan_unroll=unroll)
        np.testing.assert_allclose(
            float(loss), float(base_loss), rtol=1e-6
        )
        for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(base_g)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
            )

    def test_unroll_exceeding_layers_ok(self):
        cfg = dataclasses.replace(_cfg(False), scan_unroll=8)
        params = gpt.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jnp.zeros((1, cfg.block_size), jnp.int32)
        out = gpt.forward(params, tokens, cfg)
        assert out.shape == (1, cfg.block_size, cfg.vocab_size)
