"""Fused chunked cross-entropy vs naive log-softmax path."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models import gpt
from dlrover_tpu.ops.cross_entropy import fused_cross_entropy


def _naive(x, wte, targets):
    logits = jnp.einsum(
        "ne,ve->nv", x, wte, preferred_element_type=jnp.float32
    )
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(
        jnp.take_along_axis(logp, targets[:, None], axis=-1)
    )


@pytest.mark.parametrize("num_chunks", [1, 4])
def test_fused_xent_matches_naive(num_chunks):
    key = jax.random.PRNGKey(0)
    n, e, v = 64, 16, 96
    x = jax.random.normal(key, (n, e), jnp.float32)
    wte = jax.random.normal(jax.random.PRNGKey(1), (v, e), jnp.float32)
    targets = jax.random.randint(jax.random.PRNGKey(2), (n,), 0, v)
    got = fused_cross_entropy(x, wte, targets, num_chunks)
    want = _naive(x, wte, targets)
    np.testing.assert_allclose(got, want, rtol=1e-5)


@pytest.mark.parametrize("num_chunks", [1, 4])
def test_fused_xent_save_logits_matches_naive(num_chunks):
    key = jax.random.PRNGKey(0)
    n, e, v = 64, 16, 96
    x = jax.random.normal(key, (n, e), jnp.float32)
    wte = jax.random.normal(jax.random.PRNGKey(1), (v, e), jnp.float32)
    targets = jax.random.randint(jax.random.PRNGKey(2), (n,), 0, v)
    got = fused_cross_entropy(x, wte, targets, num_chunks, True)
    want = _naive(x, wte, targets)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    g1 = jax.grad(
        lambda x, w: fused_cross_entropy(x, w, targets, num_chunks, True),
        argnums=(0, 1),
    )(x, wte)
    g2 = jax.grad(_naive, argnums=(0, 1))(x, wte, targets)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=2e-6, rtol=1e-4)


def test_fused_xent_save_logits_bf16_grads_close():
    """bf16 activations + save_logits: grads agree with the f32
    recompute path to bf16-rounding tolerance (documented caveat)."""
    n, e, v = 64, 32, 128
    x = jax.random.normal(
        jax.random.PRNGKey(0), (n, e), jnp.bfloat16
    )
    wte = jax.random.normal(
        jax.random.PRNGKey(1), (v, e), jnp.bfloat16
    )
    targets = jax.random.randint(jax.random.PRNGKey(2), (n,), 0, v)
    g_save = jax.grad(
        lambda x, w: fused_cross_entropy(x, w, targets, 4, True),
        argnums=(0, 1),
    )(x, wte)
    g_rec = jax.grad(
        lambda x, w: fused_cross_entropy(x, w, targets, 4, False),
        argnums=(0, 1),
    )(x, wte)
    for a, b in zip(g_save, g_rec):
        a32 = np.asarray(a, np.float32)
        b32 = np.asarray(b, np.float32)
        denom = np.maximum(np.abs(b32), 1e-4)
        assert np.median(np.abs(a32 - b32) / denom) < 0.05


def test_fused_xent_grads_match_naive():
    n, e, v = 32, 8, 64
    x = jax.random.normal(jax.random.PRNGKey(0), (n, e), jnp.float32)
    wte = jax.random.normal(jax.random.PRNGKey(1), (v, e), jnp.float32)
    targets = jax.random.randint(jax.random.PRNGKey(2), (n,), 0, v)
    g1 = jax.grad(
        lambda x, w: fused_cross_entropy(x, w, targets, 4),
        argnums=(0, 1),
    )(x, wte)
    g2 = jax.grad(_naive, argnums=(0, 1))(x, wte, targets)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=2e-6, rtol=1e-4)


def test_gpt_fused_loss_matches_plain():
    cfg = gpt.GPTConfig(
        vocab_size=128, block_size=32, n_layer=2, n_head=2, n_embd=32,
        dtype=jnp.float32, remat=False,
    )
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
    targets = jnp.roll(tokens, -1, axis=1)
    plain = gpt.loss_fn(params, tokens, targets, cfg)
    fused = gpt.loss_fn_fused(params, tokens, targets, cfg, num_chunks=4)
    np.testing.assert_allclose(fused, plain, rtol=1e-5)


def test_gpt_fused_loss_grads_under_remat():
    cfg = gpt.GPTConfig(
        vocab_size=128, block_size=32, n_layer=2, n_head=2, n_embd=32,
        dtype=jnp.float32, remat="attention",
    )
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
    targets = jnp.roll(tokens, -1, axis=1)
    g1 = jax.grad(
        lambda p: gpt.loss_fn_fused(p, tokens, targets, cfg, num_chunks=2)
    )(params)
    cfg2 = gpt.GPTConfig(
        vocab_size=128, block_size=32, n_layer=2, n_head=2, n_embd=32,
        dtype=jnp.float32, remat=False,
    )
    g2 = jax.grad(lambda p: gpt.loss_fn(p, tokens, targets, cfg2))(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-3)
