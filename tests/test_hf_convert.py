"""HF Llama -> native pytree conversion: logits parity vs transformers."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402

from dlrover_tpu.models import llama  # noqa: E402
from dlrover_tpu.models.hf_convert import (  # noqa: E402
    llama_config_from_hf,
    llama_params_from_hf,
)


@pytest.fixture(scope="module")
def hf_model():
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg)
    model.eval()
    return model


def test_logits_match_transformers(hf_model):
    cfg = llama_config_from_hf(hf_model.config)
    assert cfg.n_kv_head == 2 and cfg.head_dim == 16
    params = llama_params_from_hf(hf_model.state_dict(), cfg)

    import dataclasses

    cfg = dataclasses.replace(
        cfg, dtype=np.float32, remat=False, use_flash_attention=False
    )
    tokens_np = np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(2, 16)
    )
    with torch.no_grad():
        want = hf_model(
            torch.from_numpy(tokens_np)
        ).logits.float().numpy()
    got = np.asarray(
        llama.forward(
            jax.tree.map(np.asarray, params),
            tokens_np.astype(np.int32),
            cfg,
        )
    )
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)


def test_mixtral_logits_match_transformers():
    """MoE family parity: tiny HF Mixtral vs native gated-expert
    Llama-MoE (capacity pinned high so neither path drops tokens —
    HF Mixtral has no capacity concept)."""
    hf_cfg = transformers.MixtralConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=96,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_local_experts=4,
        num_experts_per_tok=2,
        max_position_embeddings=64,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        attn_implementation="eager",
        router_aux_loss_coef=0.0,
    )
    torch.manual_seed(1)
    model = transformers.MixtralForCausalLM(hf_cfg)
    model.eval()

    cfg = llama_config_from_hf(hf_cfg)
    assert cfg.n_experts == 4 and cfg.moe_top_k == 2
    params = llama_params_from_hf(model.state_dict(), cfg)

    import dataclasses

    cfg = dataclasses.replace(
        cfg,
        dtype=np.float32,
        remat=False,
        use_flash_attention=False,
        # capacity >= all tokens so routing never drops (HF parity)
        moe_capacity_factor=float(cfg.n_experts) / cfg.moe_top_k,
    )
    tokens_np = np.random.default_rng(1).integers(
        0, cfg.vocab_size, size=(2, 16)
    )
    with torch.no_grad():
        want = model(
            torch.from_numpy(tokens_np)
        ).logits.float().numpy()
    got = np.asarray(
        llama.forward(
            jax.tree.map(np.asarray, params),
            tokens_np.astype(np.int32),
            cfg,
        )
    )
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=5e-3)


def test_tied_embeddings_fallback(hf_model):
    cfg = llama_config_from_hf(hf_model.config)
    sd = {
        k: v for k, v in hf_model.state_dict().items()
        if k != "lm_head.weight"
    }
    params = llama_params_from_hf(sd, cfg)
    np.testing.assert_array_equal(params["lm_head"], params["wte"])


def test_mistral_config_carries_sliding_window():
    """A Mistral HF config (Llama arch + sliding_window) maps onto the
    native family with the band intact; sliding_window=8 is NARROWER
    than the 32-token probe, so the band actively masks and the logits
    parity vs HF eager Mistral proves both implementations agree on
    the (q - k < window) band convention."""
    hf_cfg = transformers.MistralConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        sliding_window=8,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    cfg = llama_config_from_hf(hf_cfg)
    assert cfg.sliding_window == 8
    assert cfg.n_kv_head == 2

    torch.manual_seed(1)
    model = transformers.MistralForCausalLM(hf_cfg)
    model.eval()
    params = llama_params_from_hf(model.state_dict(), cfg)

    import dataclasses

    cfg = dataclasses.replace(
        cfg, dtype=np.float32, remat=False, use_flash_attention=False
    )
    tokens_np = np.random.default_rng(1).integers(
        0, cfg.vocab_size, size=(2, 32)
    )
    with torch.no_grad():
        want = model(
            torch.from_numpy(tokens_np)
        ).logits.float().numpy()
    got = np.asarray(
        llama.forward(
            jax.tree.map(np.asarray, params),
            tokens_np.astype(np.int32),
            cfg,
        )
    )
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)
