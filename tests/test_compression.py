"""Compressed gradient sync: accuracy bounds and training parity."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.parallel.compression import (
    compressed_psum_mean,
    make_compressed_train_step,
    sync_bytes_per_element,
)
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from jax import shard_map
from jax.sharding import PartitionSpec as P


@pytest.mark.parametrize("bits", [8, 4])
def test_compressed_mean_close_to_exact(bits):
    mesh = build_mesh(MeshConfig(data=8))
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 4096), jnp.float32)

    fn = shard_map(
        functools.partial(
            compressed_psum_mean, axis_name="data", bits=bits,
            block=256, min_size=0
        ),
        mesh=mesh,
        in_specs=P("data"),
        out_specs=P("data"),
        check_vma=False,
    )
    got = jax.jit(fn)(x)
    # Every device's row equals the mean of all rows (then re-sharded
    # back along the axis: each shard holds the same mean values).
    want = jnp.broadcast_to(jnp.mean(x, axis=0, keepdims=True), x.shape)
    err = np.abs(np.asarray(got - want))
    # error bounded by half a quantization level of the per-block max
    bound = np.abs(np.asarray(want)).max() / (127.0 if bits == 8 else 7.0)
    assert err.max() <= bound + 1e-6


def test_compressed_mean_odd_sizes():
    mesh = build_mesh(MeshConfig(data=8))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 123), jnp.float32)
    fn = shard_map(
        functools.partial(
            compressed_psum_mean, axis_name="data", bits=8, block=64,
            min_size=0
        ),
        mesh=mesh,
        in_specs=P("data"),
        out_specs=P("data"),
        check_vma=False,
    )
    got = jax.jit(fn)(x)
    want = jnp.broadcast_to(jnp.mean(x, axis=0, keepdims=True), x.shape)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-2, rtol=2e-2
    )


def test_compressed_train_step_converges_like_exact():
    """Toy regression: compressed-sync training tracks exact-psum
    training to quantization tolerance."""
    mesh = build_mesh(MeshConfig(data=8))
    d = 512
    w_true = jax.random.normal(jax.random.PRNGKey(2), (d,))
    xs = jax.random.normal(jax.random.PRNGKey(3), (64, d))
    ys = xs @ w_true

    def loss_fn(params, x, y):
        pred = x @ params["w"]
        return jnp.mean((pred - y) ** 2)

    opt = optax.sgd(0.05)

    step_c = make_compressed_train_step(mesh, loss_fn, opt, bits=8)

    def run(step):
        # fresh params per run: the compressed step donates its inputs
        p = {"w": jnp.zeros((d,))}
        s = opt.init(p)
        for _ in range(40):
            p, s, m = step(p, s, xs, ys)
        return p, float(m["loss"])

    p_c, l_c = run(step_c)

    # exact reference (plain pmean data parallel)
    def exact_step(p, s, x, y):
        loss, g = jax.value_and_grad(loss_fn)(p, x, y)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, {"loss": loss}

    p_e, l_e = run(jax.jit(exact_step))
    assert l_c < 1e-2  # converged
    np.testing.assert_allclose(
        np.asarray(p_c["w"]), np.asarray(p_e["w"]), atol=5e-2
    )


def test_small_leaves_fall_back_to_exact_pmean():
    """Leaves below min_size skip quantization entirely (a bias would
    otherwise pad to n*block and lose precision for nothing)."""
    mesh = build_mesh(MeshConfig(data=8))
    x = jax.random.normal(jax.random.PRNGKey(4), (8, 64), jnp.float32)
    fn = shard_map(
        functools.partial(
            compressed_psum_mean, axis_name="data", bits=4, block=1024
        ),
        mesh=mesh,
        in_specs=P("data"),
        out_specs=P("data"),
        check_vma=False,
    )
    got = jax.jit(fn)(x)
    want = jnp.broadcast_to(jnp.mean(x, axis=0, keepdims=True), x.shape)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-6
    )


def test_int4_wire_format_is_packed():
    """The 4-bit all-gather payload must be half the int8 one (two
    nibbles per byte) — the README's 'int4 compressed sync' claim."""
    from dlrover_tpu.ops.quantization import (
        quantize_blockwise_4bit_ref,
        quantize_blockwise_ref,
    )

    x = jnp.ones((4096,), jnp.float32)
    q8, _, _ = quantize_blockwise_ref(x, 1024)
    q4, _, _ = quantize_blockwise_4bit_ref(x, 1024)
    assert q4.size * q4.dtype.itemsize == q8.size * q8.dtype.itemsize // 2


def test_compressed_sync_on_multislice_outer_axis():
    """The target topology: a 2-slice mesh whose outer data axis
    crosses DCN — compressed sync must be numerically sound there."""
    mesh = build_mesh(
        MeshConfig(data=4, fsdp=2, num_slices=2),
        slice_ids=[i // 4 for i in range(8)],
    )
    x = jax.random.normal(jax.random.PRNGKey(5), (4, 4096), jnp.float32)
    fn = shard_map(
        functools.partial(
            compressed_psum_mean, axis_name="data", bits=8,
            block=512, min_size=0,
        ),
        mesh=mesh,
        in_specs=P("data"),
        out_specs=P("data"),
        check_vma=False,
    )
    got = jax.jit(fn)(x)
    want = jnp.broadcast_to(jnp.mean(x, axis=0, keepdims=True), x.shape)
    err = np.abs(np.asarray(got - want))
    bound = np.abs(np.asarray(want)).max() / 127.0
    assert err.max() <= bound + 1e-6


def test_sync_bytes_accounting():
    assert sync_bytes_per_element(8) == 3.0  # vs 4.0 baseline
    assert sync_bytes_per_element(4) == 2.5


def test_compressed_sync_on_two_slice_mesh_converges():
    """Integration (VERDICT r2 item 9): the compressed gradient sync
    running on a mesh whose data axis spans TWO virtual slices — the
    quantized all-gather is the collective that rides DCN on real
    multi-slice hardware — converges in parity with the exact step."""
    mesh = build_mesh(
        MeshConfig(data=8, num_slices=2),
        slice_ids=[i // 4 for i in range(8)],
    )
    d = 512
    w_true = jax.random.normal(jax.random.PRNGKey(5), (d,))
    xs = jax.random.normal(jax.random.PRNGKey(6), (64, d))
    ys = xs @ w_true

    def loss_fn(params, x, y):
        return jnp.mean((x @ params["w"] - y) ** 2)

    opt = optax.sgd(0.05)
    step_c = make_compressed_train_step(mesh, loss_fn, opt, bits=8)

    def exact_step(p, s, x, y):
        loss, g = jax.value_and_grad(loss_fn)(p, x, y)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, {"loss": loss}

    def run(step):
        p = {"w": jnp.zeros((d,))}
        s = opt.init(p)
        for _ in range(40):
            p, s, m = step(p, s, xs, ys)
        return p, float(m["loss"])

    p_c, l_c = run(step_c)
    p_e, l_e = run(jax.jit(exact_step))
    assert l_c < 1e-2
    np.testing.assert_allclose(
        np.asarray(p_c["w"]), np.asarray(p_e["w"]), atol=5e-2
    )
