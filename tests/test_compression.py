"""Compressed gradient sync: accuracy bounds and training parity."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.parallel.compression import (
    bucket_plan,
    bucketed_psum_mean,
    compressed_psum_mean,
    make_compressed_train_step,
    make_overlapped_train_step,
    overlap_sync_bytes_per_element,
    sync_bytes_per_element,
)
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.parallel.shard_map_compat import shard_map
from jax.sharding import PartitionSpec as P


@pytest.mark.parametrize("bits", [8, 4])
def test_compressed_mean_close_to_exact(bits):
    mesh = build_mesh(MeshConfig(data=8))
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 4096), jnp.float32)

    fn = shard_map(
        functools.partial(
            compressed_psum_mean, axis_name="data", bits=bits,
            block=256, min_size=0
        ),
        mesh=mesh,
        in_specs=P("data"),
        out_specs=P("data"),
        check_vma=False,
    )
    got = jax.jit(fn)(x)
    # Every device's row equals the mean of all rows (then re-sharded
    # back along the axis: each shard holds the same mean values).
    want = jnp.broadcast_to(jnp.mean(x, axis=0, keepdims=True), x.shape)
    err = np.abs(np.asarray(got - want))
    # error bounded by half a quantization level of the per-block max
    bound = np.abs(np.asarray(want)).max() / (127.0 if bits == 8 else 7.0)
    assert err.max() <= bound + 1e-6


def test_compressed_mean_odd_sizes():
    mesh = build_mesh(MeshConfig(data=8))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 123), jnp.float32)
    fn = shard_map(
        functools.partial(
            compressed_psum_mean, axis_name="data", bits=8, block=64,
            min_size=0
        ),
        mesh=mesh,
        in_specs=P("data"),
        out_specs=P("data"),
        check_vma=False,
    )
    got = jax.jit(fn)(x)
    want = jnp.broadcast_to(jnp.mean(x, axis=0, keepdims=True), x.shape)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-2, rtol=2e-2
    )


def test_compressed_train_step_converges_like_exact():
    """Toy regression: compressed-sync training tracks exact-psum
    training to quantization tolerance."""
    mesh = build_mesh(MeshConfig(data=8))
    d = 512
    w_true = jax.random.normal(jax.random.PRNGKey(2), (d,))
    xs = jax.random.normal(jax.random.PRNGKey(3), (64, d))
    ys = xs @ w_true

    def loss_fn(params, x, y):
        pred = x @ params["w"]
        return jnp.mean((pred - y) ** 2)

    opt = optax.sgd(0.05)

    step_c = make_compressed_train_step(mesh, loss_fn, opt, bits=8)

    def run(step):
        # fresh params per run: the compressed step donates its inputs
        p = {"w": jnp.zeros((d,))}
        s = opt.init(p)
        for _ in range(40):
            p, s, m = step(p, s, xs, ys)
        return p, float(m["loss"])

    p_c, l_c = run(step_c)

    # exact reference (plain pmean data parallel)
    def exact_step(p, s, x, y):
        loss, g = jax.value_and_grad(loss_fn)(p, x, y)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, {"loss": loss}

    p_e, l_e = run(jax.jit(exact_step))
    assert l_c < 1e-2  # converged
    np.testing.assert_allclose(
        np.asarray(p_c["w"]), np.asarray(p_e["w"]), atol=5e-2
    )


def test_small_leaves_fall_back_to_exact_pmean():
    """Leaves below min_size skip quantization entirely (a bias would
    otherwise pad to n*block and lose precision for nothing)."""
    mesh = build_mesh(MeshConfig(data=8))
    x = jax.random.normal(jax.random.PRNGKey(4), (8, 64), jnp.float32)
    fn = shard_map(
        functools.partial(
            compressed_psum_mean, axis_name="data", bits=4, block=1024
        ),
        mesh=mesh,
        in_specs=P("data"),
        out_specs=P("data"),
        check_vma=False,
    )
    got = jax.jit(fn)(x)
    want = jnp.broadcast_to(jnp.mean(x, axis=0, keepdims=True), x.shape)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-6
    )


def test_int4_wire_format_is_packed():
    """The 4-bit all-gather payload must be half the int8 one (two
    nibbles per byte) — the README's 'int4 compressed sync' claim."""
    from dlrover_tpu.ops.quantization import (
        quantize_blockwise_4bit_ref,
        quantize_blockwise_ref,
    )

    x = jnp.ones((4096,), jnp.float32)
    q8, _, _ = quantize_blockwise_ref(x, 1024)
    q4, _, _ = quantize_blockwise_4bit_ref(x, 1024)
    assert q4.size * q4.dtype.itemsize == q8.size * q8.dtype.itemsize // 2


def test_compressed_sync_on_multislice_outer_axis():
    """The target topology: a 2-slice mesh whose outer data axis
    crosses DCN — compressed sync must be numerically sound there."""
    mesh = build_mesh(
        MeshConfig(data=4, fsdp=2, num_slices=2),
        slice_ids=[i // 4 for i in range(8)],
    )
    x = jax.random.normal(jax.random.PRNGKey(5), (4, 4096), jnp.float32)
    fn = shard_map(
        functools.partial(
            compressed_psum_mean, axis_name="data", bits=8,
            block=512, min_size=0,
        ),
        mesh=mesh,
        in_specs=P("data"),
        out_specs=P("data"),
        check_vma=False,
    )
    got = jax.jit(fn)(x)
    want = jnp.broadcast_to(jnp.mean(x, axis=0, keepdims=True), x.shape)
    err = np.abs(np.asarray(got - want))
    bound = np.abs(np.asarray(want)).max() / 127.0
    assert err.max() <= bound + 1e-6


def test_sync_bytes_accounting():
    assert sync_bytes_per_element(8) == 3.0  # vs 4.0 baseline
    assert sync_bytes_per_element(4) == 2.5
    assert sync_bytes_per_element(None) == 4.0  # exact sync


@pytest.mark.parametrize("bits", [8, 4])
def test_compressed_psum_mean_gradient_parity(bits):
    """Gradients SYNCED through compressed_psum_mean (the thing the
    train steps actually do) track the exact-pmean gradients within
    the per-bit quantization tolerance on the CPU mesh."""
    mesh = build_mesh(MeshConfig(data=8))
    d = 2048
    xs = jax.random.normal(jax.random.PRNGKey(7), (8, 16, d))
    ys = jax.random.normal(jax.random.PRNGKey(8), (8, 16))

    def loss_fn(w, x, y):
        return jnp.mean((x @ w - y) ** 2)

    def synced_grad(sync):
        def f(x, y):
            w = jnp.zeros((d,))
            g = jax.grad(loss_fn)(w, x, y)
            return sync(g)

        return shard_map(
            f, mesh=mesh, in_specs=(P("data"), P("data")),
            out_specs=P(), check_vma=False,
        )

    exact = jax.jit(
        synced_grad(lambda g: jax.lax.pmean(g, "data"))
    )(xs, ys)
    comp = jax.jit(
        synced_grad(
            functools.partial(
                compressed_psum_mean, axis_name="data", bits=bits,
                block=256, min_size=0,
            )
        )
    )(xs, ys)
    err = np.abs(np.asarray(comp - exact))
    bound = np.abs(np.asarray(exact)).max() / (
        127.0 if bits == 8 else 7.0
    )
    assert err.max() <= bound + 1e-6


# -- bucketed overlap ------------------------------------------------------


class TestBucketPlan:
    def test_covers_every_leaf_exactly_once_in_order(self):
        leaves = [jnp.zeros((n,)) for n in (10, 20, 5000, 3, 7)]
        plan = bucket_plan(leaves, bucket_bytes=1 << 10)
        flat = [i for b in plan for i in b]
        assert flat == list(range(len(leaves)))

    def test_respects_byte_bound_except_oversized_leaf(self):
        leaves = [
            jnp.zeros((100,)),  # 400 B
            jnp.zeros((100,)),  # 400 B
            jnp.zeros((1000,)),  # 4000 B > bound: own bucket
            jnp.zeros((50,)),  # 200 B
        ]
        plan = bucket_plan(leaves, bucket_bytes=1000)
        assert plan == [[0, 1], [2], [3]]

    def test_dtype_homogeneous_buckets(self):
        leaves = [
            jnp.zeros((10,), jnp.float32),
            jnp.zeros((10,), jnp.int32),
            jnp.zeros((10,), jnp.int32),
        ]
        plan = bucket_plan(leaves, bucket_bytes=1 << 20)
        assert plan == [[0], [1, 2]]

    def test_works_on_shape_dtype_structs(self):
        leaves = [
            jax.ShapeDtypeStruct((64, 64), jnp.float32),
            jax.ShapeDtypeStruct((8,), jnp.float32),
        ]
        plan = bucket_plan(leaves, bucket_bytes=1 << 20)
        assert plan == [[0, 1]]


@pytest.mark.parametrize("bits", [None, 8, 4])
def test_bucketed_psum_mean_matches_exact_tree_mean(bits):
    mesh = build_mesh(MeshConfig(data=8))
    tree = {
        "w": jax.random.normal(jax.random.PRNGKey(9), (8, 700)),
        "b": jax.random.normal(jax.random.PRNGKey(10), (8, 9)),
        "h": jax.random.normal(jax.random.PRNGKey(11), (8, 4096)),
    }
    fn = shard_map(
        functools.partial(
            bucketed_psum_mean, axis_name="data",
            bucket_bytes=2048, bits=bits, block=64, min_size=0,
        ),
        mesh=mesh,
        in_specs=P("data"),
        out_specs=P("data"),
        check_vma=False,
    )
    got = jax.jit(fn)(tree)
    for k, v in tree.items():
        want = jnp.broadcast_to(
            jnp.mean(v, axis=0, keepdims=True), v.shape
        )
        err = np.abs(np.asarray(got[k] - want)).max()
        tol = 1e-6 if bits is None else np.abs(
            np.asarray(want)
        ).max() / (127.0 if bits == 8 else 7.0) + 1e-6
        assert err <= tol, (k, bits, err)


def test_overlap_bytes_match_bucketed_plan_accounting():
    """The satellite contract: sync_bytes_per_element composes with
    the bucket plan — buckets partition the gradient elements exactly,
    so the overlapped schedule's per-step volume is
    accum * sum(bucket elements) * sync_bytes_per_element(bits)."""
    leaves = [jnp.zeros((n,)) for n in (300, 50, 8000, 12)]
    n_el = sum(int(leaf.size) for leaf in leaves)
    plan = bucket_plan(leaves, bucket_bytes=2048)
    plan_el = sum(
        int(leaves[i].size) for b in plan for i in b
    )
    assert plan_el == n_el  # partition: no element dropped/duplicated
    for bits, accum, per_el in (
        (None, 1, 4.0),   # exact serial baseline
        (8, 1, 3.0),      # compressed, no accumulation
        (8, 2, 6.0),      # overlap pays per microbatch
        (4, 3, 7.5),
    ):
        assert overlap_sync_bytes_per_element(bits, accum) == per_el
        assert (
            overlap_sync_bytes_per_element(bits, accum) * plan_el
            == per_el * n_el
        )


@pytest.mark.parametrize("bits", [None, 8])
def test_overlapped_accum_step_matches_serial_reference(bits):
    """make_overlapped_train_step with accum>1 (per-microbatch
    bucketed reduce inside the scan) produces the same update as the
    serial accumulate-then-reduce reference — exactly for bits=None,
    within quantization tolerance for int8."""
    mesh = build_mesh(MeshConfig(data=8))
    d = 256
    accum = 2
    w_true = jax.random.normal(jax.random.PRNGKey(12), (d,))
    xs = jax.random.normal(jax.random.PRNGKey(13), (accum, 32, d))
    ys = jnp.einsum("abd,d->ab", xs, w_true)

    def loss_fn(p, x, y):
        return jnp.mean((x @ p["w"] - y) ** 2)

    opt = optax.sgd(0.05)
    step = make_overlapped_train_step(
        mesh, loss_fn, opt, accum_steps=accum, bucket_mb=0.0005,
        bits=bits, min_size=0, block=64, donate=False,
    )
    p0 = {"w": jnp.zeros((d,))}
    p_o, _, m_o = step(p0, opt.init(p0), xs, ys)

    g_acc = jax.tree.map(jnp.zeros_like, p0)
    loss_sum = 0.0
    for k in range(accum):
        lk, gk = jax.value_and_grad(loss_fn)(p0, xs[k], ys[k])
        g_acc = jax.tree.map(
            lambda a, g: a + g / accum, g_acc, gk
        )
        loss_sum = loss_sum + lk
    u, _ = opt.update(g_acc, opt.init(p0), p0)
    p_r = optax.apply_updates(p0, u)

    np.testing.assert_allclose(
        float(m_o["loss"]), float(loss_sum / accum), rtol=1e-5
    )
    tol = dict(atol=1e-6, rtol=1e-5) if bits is None else dict(
        atol=5e-2, rtol=0.0
    )
    np.testing.assert_allclose(
        np.asarray(p_o["w"]), np.asarray(p_r["w"]), **tol
    )


def test_overlapped_flat_step_trains():
    """accum_steps=1 (flat batch, the auto_accelerate build path)
    still converges with bucketed int8 sync + donation."""
    mesh = build_mesh(MeshConfig(data=8))
    d = 512
    w_true = jax.random.normal(jax.random.PRNGKey(14), (d,))
    xs = jax.random.normal(jax.random.PRNGKey(15), (64, d))
    ys = xs @ w_true

    def loss_fn(p, x, y):
        return jnp.mean((x @ p["w"] - y) ** 2)

    opt = optax.sgd(0.05)
    step = make_overlapped_train_step(
        mesh, loss_fn, opt, bucket_mb=0.001, bits=8, min_size=0,
        block=256,
    )
    p = {"w": jnp.zeros((d,))}
    s = opt.init(p)
    for _ in range(40):
        p, s, m = step(p, s, xs, ys)
    assert float(m["loss"]) < 1e-2


def test_step_metrics_contract_matches_make_train_step():
    """Every strategy's step returns {"loss", "grad_norm"} — a caller
    reading metrics["grad_norm"] must not crash only when the search
    happens to pick an overlap/compressed strategy."""
    mesh = build_mesh(MeshConfig(data=8))
    xs = jax.random.normal(jax.random.PRNGKey(3), (16, 8))
    ys = xs @ jax.random.normal(jax.random.PRNGKey(4), (8,))

    def loss_fn(p, x, y):
        return jnp.mean((x @ p["w"] - y) ** 2)

    opt = optax.sgd(0.1)
    p = {"w": jnp.zeros((8,))}
    for step in (
        make_compressed_train_step(mesh, loss_fn, opt, bits=8,
                                   min_size=0, block=256,
                                   donate=False),
        make_overlapped_train_step(mesh, loss_fn, opt,
                                   bucket_mb=0.001, donate=False),
    ):
        _, _, m = step(p, opt.init(p), xs, ys)
        assert set(m) == {"loss", "grad_norm"}
        assert float(m["grad_norm"]) > 0.0


def test_accum_without_overlap_rejected():
    mesh = build_mesh(MeshConfig(data=8))
    with pytest.raises(ValueError, match="overlap"):
        make_compressed_train_step(
            mesh, lambda p, x, y: 0.0, optax.sgd(0.1), accum_steps=2
        )


def test_compressed_sync_on_two_slice_mesh_converges():
    """Integration (VERDICT r2 item 9): the compressed gradient sync
    running on a mesh whose data axis spans TWO virtual slices — the
    quantized all-gather is the collective that rides DCN on real
    multi-slice hardware — converges in parity with the exact step."""
    mesh = build_mesh(
        MeshConfig(data=8, num_slices=2),
        slice_ids=[i // 4 for i in range(8)],
    )
    d = 512
    w_true = jax.random.normal(jax.random.PRNGKey(5), (d,))
    xs = jax.random.normal(jax.random.PRNGKey(6), (64, d))
    ys = xs @ w_true

    def loss_fn(params, x, y):
        return jnp.mean((x @ params["w"] - y) ** 2)

    opt = optax.sgd(0.05)
    step_c = make_compressed_train_step(mesh, loss_fn, opt, bits=8)

    def exact_step(p, s, x, y):
        loss, g = jax.value_and_grad(loss_fn)(p, x, y)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, {"loss": loss}

    def run(step):
        p = {"w": jnp.zeros((d,))}
        s = opt.init(p)
        for _ in range(40):
            p, s, m = step(p, s, xs, ys)
        return p, float(m["loss"])

    p_c, l_c = run(step_c)
    p_e, l_e = run(jax.jit(exact_step))
    assert l_c < 1e-2
    np.testing.assert_allclose(
        np.asarray(p_c["w"]), np.asarray(p_e["w"]), atol=5e-2
    )
