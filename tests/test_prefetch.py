"""Prefetch pipeline: ordering, backpressure, shutdown drain, sampler
state with batches in flight, device staging (H2D in the worker under
the step's sharding), and the compute/staging overlap bench."""

import threading
import time

import numpy as np
import pytest

from dlrover_tpu.data.prefetch import (
    Prefetcher,
    SyncPipeline,
    device_prefetch_enabled,
    free_device_buffers,
    make_input_pipeline,
    prefetch_depth,
    prefetch_enabled,
)
from dlrover_tpu.trainer.elastic_trainer import (
    ElasticDataLoader,
    ElasticDistributedSampler,
)


class CountingSource:
    """Re-iterable source that records how many items were pulled."""

    def __init__(self, n, gate: threading.Event = None):
        self.n = n
        self.pulled = 0
        self.gate = gate

    def __iter__(self):
        for i in range(self.n):
            if self.gate is not None:
                self.gate.wait(5.0)
            self.pulled += 1
            yield i


def test_delivers_in_order_through_stage_fn():
    with Prefetcher(
        CountingSource(10), stage_fn=lambda x: x * 2, depth=3
    ) as pf:
        got = list(pf)
    assert got == [2 * i for i in range(10)]
    assert pf.delivered == 10


def test_end_of_stream_raises_stopiteration_repeatedly():
    pf = Prefetcher(CountingSource(2), depth=2)
    assert next(pf) == 0 and next(pf) == 1
    with pytest.raises(StopIteration):
        next(pf)
    with pytest.raises(StopIteration):
        next(pf)  # stays exhausted, no hang
    pf.close()


def test_backpressure_bounds_readahead():
    """The worker may run at most ``depth`` staged batches + 1 being
    staged ahead of the consumer — never the whole dataset."""
    src = CountingSource(100)
    pf = Prefetcher(src, depth=2)
    time.sleep(0.3)  # worker free-runs against the bounded queue
    assert src.pulled <= 2 + 1
    for _ in range(10):
        next(pf)
    time.sleep(0.2)
    assert src.pulled <= 10 + 2 + 1
    pf.close()


def test_close_drains_and_stops_worker():
    src = CountingSource(1000)
    pf = Prefetcher(src, depth=2)
    next(pf)
    pf.close()
    assert not pf._thread.is_alive()
    assert pf.dropped >= 1  # staged-but-undelivered were discarded
    assert pf.delivered == 1
    pulled_at_close = src.pulled
    time.sleep(0.15)
    assert src.pulled == pulled_at_close  # nothing pulled after close
    with pytest.raises(RuntimeError):
        next(pf)
    pf.close()  # idempotent


def test_close_from_another_thread_unblocks_consumer():
    """A restart/watchdog thread closing the pipeline must wake a
    consumer blocked on an empty queue, not strand it forever."""
    gate = threading.Event()

    def slow_source():
        gate.wait(2.0)
        yield 1

    pf = Prefetcher(slow_source(), depth=1)
    caught = []

    def consume():
        try:
            next(pf)
        except BaseException as exc:  # noqa: BLE001
            caught.append(exc)

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.2)  # consumer is now blocked in __next__
    pf.close()  # worker still parked in the source: nothing queued
    gate.set()
    t.join(timeout=3.0)
    assert not t.is_alive()
    assert caught and isinstance(caught[0], RuntimeError)
    assert "closed" in str(caught[0])
    # staged == delivered + dropped even under the close race
    assert pf.staged == pf.delivered + pf.dropped


def test_worker_exception_propagates_to_consumer():
    def bad_stage(x):
        if x == 3:
            raise ValueError("boom at 3")
        return x

    pf = Prefetcher(CountingSource(10), stage_fn=bad_stage, depth=2)
    got = []
    with pytest.raises(ValueError, match="boom at 3"):
        for item in pf:
            got.append(item)
    assert got == [0, 1, 2]
    pf.close()


# -- sampler state with batches in flight ----------------------------------


def _loader(n=40, batch=5, shuffle=False):
    data = np.arange(n, dtype=np.int64)
    sampler = ElasticDistributedSampler(
        n, num_shards=1, shard_rank=0, shuffle=shuffle, seed=3
    )
    return (
        ElasticDataLoader(data, batch_size=batch, sampler=sampler),
        sampler,
    )


def test_sampler_state_counts_only_delivered_batches():
    loader, sampler = _loader(n=40, batch=5)
    pf = Prefetcher(loader, depth=3, sampler=sampler)
    assert pf.sampler_state_dict()["consumed"] == 0  # nothing trained
    first = next(pf)
    np.testing.assert_array_equal(first, np.arange(5))
    next(pf)
    # let the worker stage ahead: the RAW sampler now over-counts
    deadline = time.time() + 2.0
    while sampler.state_dict()["consumed"] <= 10 and time.time() < deadline:
        time.sleep(0.01)
    assert sampler.state_dict()["consumed"] > 10  # in-flight counted
    assert pf.sampler_state_dict()["consumed"] == 10  # delivered only
    pf.close()

    # an elastic restart from the checkpointed state replays the
    # staged-but-untrained samples instead of skipping them
    fresh = ElasticDistributedSampler(
        40, num_shards=1, shard_rank=0, shuffle=False, seed=3
    )
    fresh.load_state_dict(pf.sampler_state_dict())
    assert next(iter(fresh)) == 10


def test_auto_epoch_restarts_source_and_bumps_epoch():
    loader, sampler = _loader(n=10, batch=5, shuffle=True)
    pf = Prefetcher(loader, depth=2, sampler=sampler, auto_epoch=True)
    batches = [next(pf) for _ in range(6)]  # 3 epochs of 2 batches
    pf.close()
    assert sampler.epoch >= 2
    e0 = np.concatenate(batches[0:2])
    e1 = np.concatenate(batches[2:4])
    assert sorted(e0.tolist()) == sorted(e1.tolist()) == list(range(10))
    assert e0.tolist() != e1.tolist()  # reshuffled per epoch


def test_auto_epoch_requires_sampler():
    with pytest.raises(ValueError, match="auto_epoch"):
        Prefetcher(CountingSource(3), auto_epoch=True)
    with pytest.raises(ValueError, match="auto_epoch"):
        SyncPipeline(CountingSource(3), auto_epoch=True)


def test_zero_batch_epoch_fails_loudly_not_hangs():
    """A dataset smaller than one batch (drop_last) yields zero-batch
    epochs; auto_epoch must raise, not busy-spin the worker while the
    consumer blocks forever."""
    loader, sampler = _loader(n=3, batch=5)  # 3 < 5: no batch, ever
    pf = Prefetcher(loader, depth=2, sampler=sampler, auto_epoch=True)
    with pytest.raises(RuntimeError, match="no batches"):
        next(pf)
    pf.close()
    sync = SyncPipeline(loader, sampler=sampler, auto_epoch=True)
    with pytest.raises(RuntimeError, match="no batches"):
        next(sync)


def test_resume_at_epoch_boundary_rolls_not_raises():
    """A checkpoint taken at the end of an epoch restores a sampler
    whose FIRST pass yields nothing — the pipeline must roll into the
    next epoch, not fire the zero-batch guard (only two consecutive
    empty passes are a real error)."""
    loader, sampler = _loader(n=20, batch=5)
    sampler.load_state_dict({"epoch": 0, "consumed": 20, "seed": 3})
    pf = Prefetcher(loader, depth=2, sampler=sampler, auto_epoch=True)
    first = next(pf)  # epoch rolled to 1, fresh pass
    assert first.shape == (5,)
    assert pf.sampler_state_dict()["epoch"] == 1
    pf.close()

    sampler2 = ElasticDistributedSampler(
        20, num_shards=1, shard_rank=0, shuffle=False, seed=3
    )
    sampler2.load_state_dict({"epoch": 0, "consumed": 20, "seed": 3})
    loader2 = ElasticDataLoader(
        np.arange(20, dtype=np.int64), batch_size=5, sampler=sampler2
    )
    sync = SyncPipeline(loader2, sampler=sampler2, auto_epoch=True)
    assert next(sync).shape == (5,)
    assert sampler2.epoch == 1


def test_make_input_pipeline_switches_on_env(monkeypatch):
    monkeypatch.delenv("DLROVER_TPU_PREFETCH", raising=False)
    pipe = make_input_pipeline(
        CountingSource(3), stage_fn=lambda x: x + 1
    )
    assert isinstance(pipe, Prefetcher)
    assert list(pipe) == [1, 2, 3]
    pipe.close()

    monkeypatch.setenv("DLROVER_TPU_PREFETCH", "0")
    loader, sampler = _loader(n=20, batch=5)
    sync = make_input_pipeline(
        loader, stage_fn=lambda b: b * 2, sampler=sampler,
        auto_epoch=True,
    )
    assert isinstance(sync, SyncPipeline)
    np.testing.assert_array_equal(next(sync), np.arange(5) * 2)
    # nothing in flight in sync mode: state tracks delivery exactly
    assert sync.sampler_state_dict()["consumed"] == 5
    batches = [next(sync) for _ in range(5)]  # rolls into epoch 1
    assert sampler.epoch == 1 and len(batches) == 5
    assert sync.wait_s_total >= 0.0 and sync.delivered == 6
    sync.close()  # no-op, idempotent
    sync.close()


# -- knobs -----------------------------------------------------------------


def test_env_knobs(monkeypatch):
    monkeypatch.delenv("DLROVER_TPU_PREFETCH", raising=False)
    assert prefetch_enabled()
    monkeypatch.setenv("DLROVER_TPU_PREFETCH", "0")
    assert not prefetch_enabled()
    monkeypatch.setenv("DLROVER_TPU_PREFETCH_DEPTH", "5")
    assert prefetch_depth() == 5
    monkeypatch.setenv("DLROVER_TPU_PREFETCH_DEPTH", "junk")
    assert prefetch_depth() == 2
    monkeypatch.setenv("DLROVER_TPU_PREFETCH_DEPTH", "0")
    assert prefetch_depth() == 1  # clamped
    with pytest.raises(ValueError):
        Prefetcher(CountingSource(1), depth=0)


# -- observability ---------------------------------------------------------


def test_prefetch_emits_trace_events_and_data_wait_metric():
    from dlrover_tpu import obs
    from dlrover_tpu.obs import tracer as tracer_mod

    tracer = tracer_mod.configure_tracer()
    try:
        with Prefetcher(
            CountingSource(3), stage_fn=lambda x: x, depth=2,
            name="obs-test",
        ) as pf:
            assert list(pf) == [0, 1, 2]
        names = [e["name"] for e in tracer.events()]
        assert "trainer.prefetch_start" in names
        assert names.count("trainer.prefetch_stage") == 3
        # exactly one wait per REAL batch: the terminal sentinel
        # fetch must not add a phantom sample
        assert names.count("trainer.prefetch_wait") == 3
        stop = [
            e for e in tracer.events()
            if e["name"] == "trainer.prefetch_stop"
        ][-1]
        assert stop["delivered"] == 3 and stop["dropped"] == 0
    finally:
        tracer_mod.disable_tracer()
    hist = obs.histogram("dlrover_train_data_wait_seconds")
    assert hist.count() >= 3  # every consumer wait was observed


# -- device staging (the device-resident input pipeline) -------------------


def _mesh8():
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:8]), ("data",))


def _batch_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P("data"))


def test_worker_h2d_delivers_committed_sharded_device_arrays():
    """The tentpole: the worker finishes with jax.device_put under
    the step's NamedSharding — the queue hands the consumer committed
    device arrays, correctly laid out on the multi-device mesh."""
    import jax

    mesh = _mesh8()
    sharding = _batch_sharding(mesh)

    def source():
        for i in range(4):
            yield np.full((16, 2), i, dtype=np.float32)

    with Prefetcher(
        source(),
        h2d_fn=lambda b: jax.device_put(b, sharding),
        device_prefetch=True,
        depth=2,
    ) as pf:
        got = list(pf)
    assert len(got) == 4
    for i, arr in enumerate(got):
        assert isinstance(arr, jax.Array)
        assert arr.sharding == sharding
        assert arr.committed
        np.testing.assert_array_equal(
            np.asarray(arr), np.full((16, 2), i, dtype=np.float32)
        )
    # worker-side H2D was timed and attributed
    assert pf.h2d_stage_s_total > 0.0


def test_consumer_h2d_when_device_prefetch_off():
    """DLROVER_TPU_DEVICE_PREFETCH=0 semantics: the worker stays
    host-side, the consumer pays the H2D inline and the wait split
    reports it as the h2d slice."""
    import jax

    mesh = _mesh8()
    sharding = _batch_sharding(mesh)
    h2d_threads = []

    def h2d(b):
        h2d_threads.append(threading.current_thread().name)
        return jax.device_put(b, sharding)

    def source():
        for _ in range(3):
            yield np.zeros((8, 2), dtype=np.float32)

    pf = Prefetcher(
        source(), h2d_fn=h2d, device_prefetch=False, depth=2
    )
    arr = next(pf)
    assert isinstance(arr, jax.Array) and arr.sharding == sharding
    # the h2d ran on THIS thread, not the prefetch worker
    assert h2d_threads[0] == threading.current_thread().name
    host_w, h2d_w = pf.wait_breakdown()
    assert h2d_w > 0.0
    assert pf.h2d_wait_s_total == pytest.approx(h2d_w)
    pf.close()


def test_sampler_state_excludes_in_flight_device_batches():
    """Delivered-only sampler snapshots hold when the in-flight
    batches are DEVICE arrays: a checkpoint must replay staged
    device-resident batches too."""
    import jax

    mesh = _mesh8()
    sharding = _batch_sharding(mesh)
    loader, sampler = _loader(n=40, batch=8)
    pf = Prefetcher(
        loader,
        h2d_fn=lambda b: jax.device_put(
            b.astype(np.float32), sharding
        ),
        depth=3,
        sampler=sampler,
    )
    first = next(pf)
    assert isinstance(first, jax.Array)
    deadline = time.time() + 2.0
    while (
        sampler.state_dict()["consumed"] <= 8
        and time.time() < deadline
    ):
        time.sleep(0.01)
    assert sampler.state_dict()["consumed"] > 8  # in-flight on device
    assert pf.sampler_state_dict()["consumed"] == 8  # delivered only
    pf.close()


def test_close_frees_dropped_device_slots():
    """Drain-on-close must hand dropped batches' HBM back eagerly:
    staged-but-undelivered device arrays are delete()d."""
    import jax

    mesh = _mesh8()
    sharding = _batch_sharding(mesh)
    staged_arrays = []

    def h2d(b):
        arr = jax.device_put(b, sharding)
        staged_arrays.append(arr)
        return arr

    def source():
        for _ in range(10):
            yield np.zeros((8, 2), dtype=np.float32)

    pf = Prefetcher(source(), h2d_fn=h2d, depth=3)
    delivered = next(pf)
    # let the worker fill the queue
    deadline = time.time() + 2.0
    while pf.staged < 3 and time.time() < deadline:
        time.sleep(0.01)
    pf.close()
    assert pf.dropped >= 1
    assert not delivered.is_deleted()  # the consumer's batch is HIS
    dropped = [a for a in staged_arrays if a is not delivered]
    assert dropped and all(a.is_deleted() for a in dropped)
    pf.close()  # idempotent under the device-staging path too


def test_free_device_buffers_walks_containers():
    import jax

    a = jax.numpy.zeros((4,))
    b = jax.numpy.zeros((2,))
    free_device_buffers(({"x": a}, [b], "not-an-array", None))
    assert a.is_deleted() and b.is_deleted()
    free_device_buffers(({"x": a}, [b]))  # already-deleted: no raise


def test_worker_h2d_failure_is_loud_not_a_hang():
    """A device_put failure in the worker must surface as a step
    error at the consumer (the _Error relay), never leave the
    consumer blocked on the bounded queue."""

    def bad_h2d(b):
        raise RuntimeError("device_put exploded")

    pf = Prefetcher(
        CountingSource(5), h2d_fn=bad_h2d, device_prefetch=True,
        depth=2,
    )
    with pytest.raises(RuntimeError, match="device_put exploded"):
        next(pf)
    pf.close()


def test_zero_batch_epoch_guard_under_device_staging():
    """The loud zero-batch-epoch failure still fires when the worker
    ends with a device stage (the guard lives upstream of h2d_fn)."""
    loader, sampler = _loader(n=3, batch=5)  # never fills a batch
    pf = Prefetcher(
        loader,
        h2d_fn=lambda b: b,
        depth=2,
        sampler=sampler,
        auto_epoch=True,
    )
    with pytest.raises(RuntimeError, match="no batches"):
        next(pf)
    pf.close()
    pf.close()  # idempotent


def test_wait_split_attribution_proportional():
    """With device prefetch, a consumer wait is split by the worker's
    host vs h2d staging proportion for that batch."""
    gate = threading.Event()

    def slow_source():
        for i in range(3):
            gate.wait(2.0)
            yield i

    def h2d(b):
        time.sleep(0.03)
        return b

    pf = Prefetcher(
        slow_source(), h2d_fn=h2d, device_prefetch=True, depth=1
    )
    time.sleep(0.05)
    gate.set()
    next(pf)
    host_w, h2d_w = pf.wait_breakdown()
    assert host_w > 0.0 and h2d_w > 0.0
    assert pf.wait_s_total == pytest.approx(host_w + h2d_w, rel=1e-6)
    pf.close()


def test_sync_pipeline_reports_same_split_metrics_as_async():
    """Satellite: SyncPipeline emits the SAME split host/h2d staging
    events and counters as the async path so obs_report summaries
    stay comparable across modes."""
    from dlrover_tpu.obs import tracer as tracer_mod
    from dlrover_tpu.obs.metrics import get_registry

    counter = get_registry().get("dlrover_prefetch_stage_seconds_total")
    host_before = counter.value(phase="host")
    h2d_before = counter.value(phase="h2d")
    tracer = tracer_mod.configure_tracer()
    try:
        sync = SyncPipeline(
            CountingSource(2),
            stage_fn=lambda x: x,
            h2d_fn=lambda x: x * 10,
        )
        assert list(sync) == [0, 10]
        sync.close()
        names = [e["name"] for e in tracer.events()]
        assert "trainer.prefetch_start" in names
        assert names.count("trainer.prefetch_stage") == 2
        assert names.count("trainer.prefetch_h2d") == 2
        waits = [
            e for e in tracer.events()
            if e["name"] == "trainer.prefetch_wait"
        ]
        assert len(waits) == 2
        assert all("host_s" in e and "h2d_s" in e for e in waits)
        stop = [
            e for e in tracer.events()
            if e["name"] == "trainer.prefetch_stop"
        ][-1]
        assert stop["delivered"] == 2
        assert "h2d_stage_s_total" in stop
    finally:
        tracer_mod.disable_tracer()
    assert counter.value(phase="host") > host_before
    assert counter.value(phase="h2d") >= h2d_before
    host_w, h2d_w = sync.wait_breakdown()
    assert host_w >= 0.0 and h2d_w >= 0.0


def test_consumer_h2d_failure_keeps_batch_accounting_invariant():
    """Review regression: an inline (device_prefetch=0) h2d_fn
    failure must count the popped batch as dropped so
    staged == delivered + dropped still holds at prefetch_stop."""
    calls = []

    def flaky_h2d(b):
        calls.append(b)
        if len(calls) == 2:
            raise RuntimeError("transient device OOM")
        return b

    pf = Prefetcher(
        CountingSource(3), h2d_fn=flaky_h2d, device_prefetch=False,
        depth=2,
    )
    assert next(pf) == 0
    with pytest.raises(RuntimeError, match="transient device OOM"):
        next(pf)
    pf.close()
    assert pf.staged == pf.delivered + pf.dropped
    assert pf.delivered == 1 and pf.dropped >= 1


def test_sync_pipeline_close_idempotent_single_stop_event():
    """Review regression: a defensive double close (context manager +
    finally) must emit exactly ONE prefetch_stop event, like the
    async pipeline's guarded close."""
    from dlrover_tpu.obs import tracer as tracer_mod

    tracer = tracer_mod.configure_tracer()
    try:
        with SyncPipeline(CountingSource(2)) as sync:
            list(sync)
            sync.close()
        sync.close()
        stops = [
            e for e in tracer.events()
            if e["name"] == "trainer.prefetch_stop"
        ]
        assert len(stops) == 1
        assert stops[0]["delivered"] == 2
    finally:
        tracer_mod.disable_tracer()


def test_device_prefetch_env_knob(monkeypatch):
    monkeypatch.delenv("DLROVER_TPU_DEVICE_PREFETCH", raising=False)
    assert device_prefetch_enabled()
    assert not device_prefetch_enabled(default=False)
    monkeypatch.setenv("DLROVER_TPU_DEVICE_PREFETCH", "0")
    assert not device_prefetch_enabled()
    assert not device_prefetch_enabled(default=True)
    monkeypatch.setenv("DLROVER_TPU_DEVICE_PREFETCH", "1")
    assert device_prefetch_enabled(default=False)

    monkeypatch.setenv("DLROVER_TPU_DEVICE_PREFETCH", "0")
    calls = []
    pf = make_input_pipeline(
        CountingSource(2), h2d_fn=lambda b: calls.append(b) or b
    )
    assert isinstance(pf, Prefetcher)
    assert not pf.device_prefetch
    assert list(pf) == [0, 1]
    assert len(calls) == 2  # consumer-side h2d still applied
    pf.close()


# -- the point of it all: overlap ------------------------------------------


def test_device_prefetch_hides_h2d_behind_compute():
    """The acceptance fallback for CPU-only containers: with H2D cost
    H per batch and compute cost C >= H per step, worker-side device
    staging (device_prefetch on) must hide H2D almost entirely, while
    the consumer-side flavor pays ~N*H on the critical path — and the
    split attribution shows exactly that difference."""
    h2d_s = 0.02
    compute_s = 0.03
    n_steps = 8

    def slow_h2d(x):
        time.sleep(h2d_s)
        return x

    def run(device_prefetch):
        pf = Prefetcher(
            CountingSource(n_steps + 2),
            h2d_fn=slow_h2d,
            device_prefetch=device_prefetch,
            depth=2,
        )
        next(pf)  # warmup: pays the initial pipeline fill
        pf.wait_s_total = 0.0
        pf.h2d_wait_s_total = 0.0
        for _ in range(n_steps):
            time.sleep(compute_s)  # "the XLA step"
            next(pf)
        wait, h2d_wait = pf.wait_s_total, pf.h2d_wait_s_total
        pf.close()
        return wait, h2d_wait

    hidden_wait, _ = run(device_prefetch=True)
    inline_wait, inline_h2d = run(device_prefetch=False)
    sequential = n_steps * h2d_s
    # worker-side H2D: nearly all of it hides behind compute
    assert hidden_wait < 0.5 * sequential, (
        f"device prefetch hid only "
        f"{sequential - hidden_wait:.3f}s of {sequential:.3f}s H2D"
    )
    # consumer-side H2D: the cost is ON the critical path and the
    # split attributes it to the h2d slice specifically
    assert inline_h2d >= 0.9 * sequential
    assert inline_wait >= inline_h2d


def test_prefetch_overlaps_staging_with_compute():
    """CPU microbench for the acceptance bar: with staging cost S per
    batch and compute cost C >= S per step, the steady-state data
    wait must be far below sequential staging (N * S) — the pipeline
    hides staging behind compute."""
    stage_s = 0.02
    compute_s = 0.03
    n_steps = 8

    def slow_stage(x):
        time.sleep(stage_s)
        return x

    pf = Prefetcher(
        CountingSource(n_steps + 2), stage_fn=slow_stage, depth=2
    )
    next(pf)  # warmup: pays the initial pipeline fill
    pf.wait_s_total = 0.0
    for _ in range(n_steps):
        time.sleep(compute_s)  # "the XLA step"
        next(pf)
    data_wait = pf.wait_s_total
    pf.close()
    sequential = n_steps * stage_s
    # generous margin for CI jitter; in practice data_wait is ~0
    assert data_wait < 0.5 * sequential, (
        f"prefetch hid only {sequential - data_wait:.3f}s of "
        f"{sequential:.3f}s staging (waited {data_wait:.3f}s)"
    )
