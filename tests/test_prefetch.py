"""Prefetch pipeline: ordering, backpressure, shutdown drain, sampler
state with batches in flight, and the compute/staging overlap bench."""

import threading
import time

import numpy as np
import pytest

from dlrover_tpu.data.prefetch import (
    Prefetcher,
    SyncPipeline,
    make_input_pipeline,
    prefetch_depth,
    prefetch_enabled,
)
from dlrover_tpu.trainer.elastic_trainer import (
    ElasticDataLoader,
    ElasticDistributedSampler,
)


class CountingSource:
    """Re-iterable source that records how many items were pulled."""

    def __init__(self, n, gate: threading.Event = None):
        self.n = n
        self.pulled = 0
        self.gate = gate

    def __iter__(self):
        for i in range(self.n):
            if self.gate is not None:
                self.gate.wait(5.0)
            self.pulled += 1
            yield i


def test_delivers_in_order_through_stage_fn():
    with Prefetcher(
        CountingSource(10), stage_fn=lambda x: x * 2, depth=3
    ) as pf:
        got = list(pf)
    assert got == [2 * i for i in range(10)]
    assert pf.delivered == 10


def test_end_of_stream_raises_stopiteration_repeatedly():
    pf = Prefetcher(CountingSource(2), depth=2)
    assert next(pf) == 0 and next(pf) == 1
    with pytest.raises(StopIteration):
        next(pf)
    with pytest.raises(StopIteration):
        next(pf)  # stays exhausted, no hang
    pf.close()


def test_backpressure_bounds_readahead():
    """The worker may run at most ``depth`` staged batches + 1 being
    staged ahead of the consumer — never the whole dataset."""
    src = CountingSource(100)
    pf = Prefetcher(src, depth=2)
    time.sleep(0.3)  # worker free-runs against the bounded queue
    assert src.pulled <= 2 + 1
    for _ in range(10):
        next(pf)
    time.sleep(0.2)
    assert src.pulled <= 10 + 2 + 1
    pf.close()


def test_close_drains_and_stops_worker():
    src = CountingSource(1000)
    pf = Prefetcher(src, depth=2)
    next(pf)
    pf.close()
    assert not pf._thread.is_alive()
    assert pf.dropped >= 1  # staged-but-undelivered were discarded
    assert pf.delivered == 1
    pulled_at_close = src.pulled
    time.sleep(0.15)
    assert src.pulled == pulled_at_close  # nothing pulled after close
    with pytest.raises(RuntimeError):
        next(pf)
    pf.close()  # idempotent


def test_close_from_another_thread_unblocks_consumer():
    """A restart/watchdog thread closing the pipeline must wake a
    consumer blocked on an empty queue, not strand it forever."""
    gate = threading.Event()

    def slow_source():
        gate.wait(2.0)
        yield 1

    pf = Prefetcher(slow_source(), depth=1)
    caught = []

    def consume():
        try:
            next(pf)
        except BaseException as exc:  # noqa: BLE001
            caught.append(exc)

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.2)  # consumer is now blocked in __next__
    pf.close()  # worker still parked in the source: nothing queued
    gate.set()
    t.join(timeout=3.0)
    assert not t.is_alive()
    assert caught and isinstance(caught[0], RuntimeError)
    assert "closed" in str(caught[0])
    # staged == delivered + dropped even under the close race
    assert pf.staged == pf.delivered + pf.dropped


def test_worker_exception_propagates_to_consumer():
    def bad_stage(x):
        if x == 3:
            raise ValueError("boom at 3")
        return x

    pf = Prefetcher(CountingSource(10), stage_fn=bad_stage, depth=2)
    got = []
    with pytest.raises(ValueError, match="boom at 3"):
        for item in pf:
            got.append(item)
    assert got == [0, 1, 2]
    pf.close()


# -- sampler state with batches in flight ----------------------------------


def _loader(n=40, batch=5, shuffle=False):
    data = np.arange(n, dtype=np.int64)
    sampler = ElasticDistributedSampler(
        n, num_shards=1, shard_rank=0, shuffle=shuffle, seed=3
    )
    return (
        ElasticDataLoader(data, batch_size=batch, sampler=sampler),
        sampler,
    )


def test_sampler_state_counts_only_delivered_batches():
    loader, sampler = _loader(n=40, batch=5)
    pf = Prefetcher(loader, depth=3, sampler=sampler)
    assert pf.sampler_state_dict()["consumed"] == 0  # nothing trained
    first = next(pf)
    np.testing.assert_array_equal(first, np.arange(5))
    next(pf)
    # let the worker stage ahead: the RAW sampler now over-counts
    deadline = time.time() + 2.0
    while sampler.state_dict()["consumed"] <= 10 and time.time() < deadline:
        time.sleep(0.01)
    assert sampler.state_dict()["consumed"] > 10  # in-flight counted
    assert pf.sampler_state_dict()["consumed"] == 10  # delivered only
    pf.close()

    # an elastic restart from the checkpointed state replays the
    # staged-but-untrained samples instead of skipping them
    fresh = ElasticDistributedSampler(
        40, num_shards=1, shard_rank=0, shuffle=False, seed=3
    )
    fresh.load_state_dict(pf.sampler_state_dict())
    assert next(iter(fresh)) == 10


def test_auto_epoch_restarts_source_and_bumps_epoch():
    loader, sampler = _loader(n=10, batch=5, shuffle=True)
    pf = Prefetcher(loader, depth=2, sampler=sampler, auto_epoch=True)
    batches = [next(pf) for _ in range(6)]  # 3 epochs of 2 batches
    pf.close()
    assert sampler.epoch >= 2
    e0 = np.concatenate(batches[0:2])
    e1 = np.concatenate(batches[2:4])
    assert sorted(e0.tolist()) == sorted(e1.tolist()) == list(range(10))
    assert e0.tolist() != e1.tolist()  # reshuffled per epoch


def test_auto_epoch_requires_sampler():
    with pytest.raises(ValueError, match="auto_epoch"):
        Prefetcher(CountingSource(3), auto_epoch=True)
    with pytest.raises(ValueError, match="auto_epoch"):
        SyncPipeline(CountingSource(3), auto_epoch=True)


def test_zero_batch_epoch_fails_loudly_not_hangs():
    """A dataset smaller than one batch (drop_last) yields zero-batch
    epochs; auto_epoch must raise, not busy-spin the worker while the
    consumer blocks forever."""
    loader, sampler = _loader(n=3, batch=5)  # 3 < 5: no batch, ever
    pf = Prefetcher(loader, depth=2, sampler=sampler, auto_epoch=True)
    with pytest.raises(RuntimeError, match="no batches"):
        next(pf)
    pf.close()
    sync = SyncPipeline(loader, sampler=sampler, auto_epoch=True)
    with pytest.raises(RuntimeError, match="no batches"):
        next(sync)


def test_resume_at_epoch_boundary_rolls_not_raises():
    """A checkpoint taken at the end of an epoch restores a sampler
    whose FIRST pass yields nothing — the pipeline must roll into the
    next epoch, not fire the zero-batch guard (only two consecutive
    empty passes are a real error)."""
    loader, sampler = _loader(n=20, batch=5)
    sampler.load_state_dict({"epoch": 0, "consumed": 20, "seed": 3})
    pf = Prefetcher(loader, depth=2, sampler=sampler, auto_epoch=True)
    first = next(pf)  # epoch rolled to 1, fresh pass
    assert first.shape == (5,)
    assert pf.sampler_state_dict()["epoch"] == 1
    pf.close()

    sampler2 = ElasticDistributedSampler(
        20, num_shards=1, shard_rank=0, shuffle=False, seed=3
    )
    sampler2.load_state_dict({"epoch": 0, "consumed": 20, "seed": 3})
    loader2 = ElasticDataLoader(
        np.arange(20, dtype=np.int64), batch_size=5, sampler=sampler2
    )
    sync = SyncPipeline(loader2, sampler=sampler2, auto_epoch=True)
    assert next(sync).shape == (5,)
    assert sampler2.epoch == 1


def test_make_input_pipeline_switches_on_env(monkeypatch):
    monkeypatch.delenv("DLROVER_TPU_PREFETCH", raising=False)
    pipe = make_input_pipeline(
        CountingSource(3), stage_fn=lambda x: x + 1
    )
    assert isinstance(pipe, Prefetcher)
    assert list(pipe) == [1, 2, 3]
    pipe.close()

    monkeypatch.setenv("DLROVER_TPU_PREFETCH", "0")
    loader, sampler = _loader(n=20, batch=5)
    sync = make_input_pipeline(
        loader, stage_fn=lambda b: b * 2, sampler=sampler,
        auto_epoch=True,
    )
    assert isinstance(sync, SyncPipeline)
    np.testing.assert_array_equal(next(sync), np.arange(5) * 2)
    # nothing in flight in sync mode: state tracks delivery exactly
    assert sync.sampler_state_dict()["consumed"] == 5
    batches = [next(sync) for _ in range(5)]  # rolls into epoch 1
    assert sampler.epoch == 1 and len(batches) == 5
    assert sync.wait_s_total >= 0.0 and sync.delivered == 6
    sync.close()  # no-op, idempotent
    sync.close()


# -- knobs -----------------------------------------------------------------


def test_env_knobs(monkeypatch):
    monkeypatch.delenv("DLROVER_TPU_PREFETCH", raising=False)
    assert prefetch_enabled()
    monkeypatch.setenv("DLROVER_TPU_PREFETCH", "0")
    assert not prefetch_enabled()
    monkeypatch.setenv("DLROVER_TPU_PREFETCH_DEPTH", "5")
    assert prefetch_depth() == 5
    monkeypatch.setenv("DLROVER_TPU_PREFETCH_DEPTH", "junk")
    assert prefetch_depth() == 2
    monkeypatch.setenv("DLROVER_TPU_PREFETCH_DEPTH", "0")
    assert prefetch_depth() == 1  # clamped
    with pytest.raises(ValueError):
        Prefetcher(CountingSource(1), depth=0)


# -- observability ---------------------------------------------------------


def test_prefetch_emits_trace_events_and_data_wait_metric():
    from dlrover_tpu import obs
    from dlrover_tpu.obs import tracer as tracer_mod

    tracer = tracer_mod.configure_tracer()
    try:
        with Prefetcher(
            CountingSource(3), stage_fn=lambda x: x, depth=2,
            name="obs-test",
        ) as pf:
            assert list(pf) == [0, 1, 2]
        names = [e["name"] for e in tracer.events()]
        assert "trainer.prefetch_start" in names
        assert names.count("trainer.prefetch_stage") == 3
        # exactly one wait per REAL batch: the terminal sentinel
        # fetch must not add a phantom sample
        assert names.count("trainer.prefetch_wait") == 3
        stop = [
            e for e in tracer.events()
            if e["name"] == "trainer.prefetch_stop"
        ][-1]
        assert stop["delivered"] == 3 and stop["dropped"] == 0
    finally:
        tracer_mod.disable_tracer()
    hist = obs.histogram("dlrover_train_data_wait_seconds")
    assert hist.count() >= 3  # every consumer wait was observed


# -- the point of it all: overlap ------------------------------------------


def test_prefetch_overlaps_staging_with_compute():
    """CPU microbench for the acceptance bar: with staging cost S per
    batch and compute cost C >= S per step, the steady-state data
    wait must be far below sequential staging (N * S) — the pipeline
    hides staging behind compute."""
    stage_s = 0.02
    compute_s = 0.03
    n_steps = 8

    def slow_stage(x):
        time.sleep(stage_s)
        return x

    pf = Prefetcher(
        CountingSource(n_steps + 2), stage_fn=slow_stage, depth=2
    )
    next(pf)  # warmup: pays the initial pipeline fill
    pf.wait_s_total = 0.0
    for _ in range(n_steps):
        time.sleep(compute_s)  # "the XLA step"
        next(pf)
    data_wait = pf.wait_s_total
    pf.close()
    sequential = n_steps * stage_s
    # generous margin for CI jitter; in practice data_wait is ~0
    assert data_wait < 0.5 * sequential, (
        f"prefetch hid only {sequential - data_wait:.3f}s of "
        f"{sequential:.3f}s staging (waited {data_wait:.3f}s)"
    )
