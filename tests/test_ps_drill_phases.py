"""CI wrapper for the PS abrupt-kill drill with phase-timed recovery
(VERDICT r3 item 7): runs examples/ctr/train.py as a real
multi-process exercise (PS servers as separate processes behind RPC),
kills one PS mid-training, and asserts the recovery breaks into
explainable, budget-checked segments.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_ps_abrupt_kill_drill_phase_budgets(tmp_path):
    out = tmp_path / "recovery_ps.json"
    trace = tmp_path / "trace.jsonl"
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "examples", "ctr", "train.py"),
            "--steps", "60",
            "--drill", "abrupt",
            "--flush-every", "10",
            "--drill-json", str(out),
        ],
        capture_output=True,
        text=True,
        timeout=600,
        cwd=REPO,
        env={
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "DLROVER_TPU_TRACE_FILE": str(trace),
        },
    )
    assert proc.returncode == 0, (
        f"drill failed:\n{proc.stdout[-3000:]}\n{proc.stderr[-2000:]}"
    )
    result = json.loads(out.read_text())
    assert result["rows_after_recovery"] > 0
    assert result["map_version_after"] > result["map_version_before"]

    phases = result["phases"]
    # Drill liveness knobs: 0.5 s ticks, 2 strikes, 2 s ping timeout
    # -> worst-case detection (2 ticks + 2 timeouts + slack) ~5 s.
    budgets = {
        "detect_s": 8.0,
        "rebalance_restore_s": 5.0,  # delta import of ~half the rows
        "client_resume_s": 10.0,  # stale-map refetch + blocked step
    }
    for name, budget in budgets.items():
        assert 0.0 <= phases[name] <= budget, (
            f"phase {name}={phases[name]}s over its {budget}s budget"
        )
    assert (
        abs(sum(phases.values()) - result["recovery_s"]) < 1.0
    ), f"phases {phases} do not explain {result['recovery_s']}s"

    # The failover also lands in the obs event stream: the kill and
    # the recovered event (with the same phase breakdown) must both be
    # there, ordered, so obs_report can explain PS recoveries too.
    from dlrover_tpu.obs.timeline import load_events

    events = {e["name"]: e for e in load_events(str(trace))}
    assert "ps.kill" in events, "ps.kill event missing from trace"
    recovered = events.get("ps.failover_recovered")
    assert recovered is not None
    assert recovered["ts"] > events["ps.kill"]["ts"]
    assert recovered["recovery_s"] == result["recovery_s"]
    for name in ("detect_s", "rebalance_restore_s", "client_resume_s"):
        assert recovered[name] == phases[name]
