"""Per-module jaxpr profiler + roofline search prior.

Parity target: AProfiler's per-module FLOPs/latency attribution
feeding the strategy engine (atorch/utils/prof.py:39,490). The "done"
criterion from the round brief: the strategy search finds the
known-best config in fewer dry-runs when seeded by the profiler's
roofline prior than by the memory prior.
"""

import functools

import jax
import jax.numpy as jnp
import pytest

from dlrover_tpu.utils.module_profiler import (
    ModuleCost,
    predict_step_time,
    profile_modules,
    total_cost,
)


def _toy(p, x):
    with jax.named_scope("proj"):
        h = x @ p["w1"]
    with jax.named_scope("act"):
        h = jax.nn.relu(h)
    return h.sum()


class TestAttribution:
    def test_matmul_flops_exact(self):
        p = {"w1": jnp.ones((64, 32))}
        x = jnp.ones((8, 64))
        costs = profile_modules(_toy, p, x)
        # 2 * M * N * K = 2 * 8 * 32 * 64
        assert costs["proj"].flops == pytest.approx(2 * 8 * 32 * 64)

    def test_grad_roughly_doubles_matmul_flops(self):
        p = {"w1": jnp.ones((64, 32))}
        x = jnp.ones((8, 64))
        fwd = profile_modules(_toy, p, x)["proj"].flops
        both = profile_modules(_toy, p, x, grad=True)["proj"].flops
        # value_and_grad differentiates wrt params only: fwd + dW
        # (no dX matmul for a leaf input), plus small elementwise.
        assert both == pytest.approx(2 * fwd, rel=0.1)

    def test_scan_multiplies_by_length(self):
        def f(p, x):
            def body(c, _):
                with jax.named_scope("cell"):
                    return c @ p["w"], None
            h, _ = jax.lax.scan(body, x, None, length=5)
            return h.sum()

        p = {"w": jnp.ones((16, 16))}
        x = jnp.ones((4, 16))
        costs = profile_modules(f, p, x)
        assert costs["cell"].flops == pytest.approx(
            5 * 2 * 4 * 16 * 16
        )

    def test_abstract_inputs_no_execution(self):
        p = {"w1": jax.ShapeDtypeStruct((64, 32), jnp.float32)}
        x = jax.ShapeDtypeStruct((8, 64), jnp.float32)
        costs = profile_modules(_toy, p, x)
        assert costs["proj"].flops > 0

    def test_gpt_scopes_and_ordering(self):
        from dlrover_tpu.models import gpt

        cfg = gpt.GPTConfig.nano()
        params = jax.eval_shape(
            functools.partial(gpt.init_params, cfg=cfg),
            jax.random.PRNGKey(0),
        )
        tok = jax.ShapeDtypeStruct((2, cfg.block_size), jnp.int32)
        loss = functools.partial(gpt.loss_fn, cfg=cfg)
        costs = profile_modules(
            loss, params, tok, tok, grad=True, top_level_only=True
        )
        for scope in ("head", "mlp", "attn", "embed"):
            assert scope in costs, costs.keys()
        # nano GPT: the vocab head dominates, mlp has 2x the matmul
        # volume of attention projections.
        assert costs["head"].flops > costs["mlp"].flops
        assert costs["mlp"].flops > costs["attn"].flops
        # Nothing substantial left unattributed.
        total = total_cost(costs)
        assert costs.get("<root>", ModuleCost()).flops < (
            0.05 * total.flops
        )


class TestRooflinePrior:
    def _strategies(self):
        from dlrover_tpu.accelerate.strategy import Strategy

        mesh = (("data", 1),)
        return (
            Strategy(mesh, remat="none", micro_batch_size=2),
            Strategy(mesh, remat="full", micro_batch_size=2),
        )

    def test_remat_costs_flops(self):
        per_sample = ModuleCost(flops=1e12, bytes=1e9)
        none_s, full_s = self._strategies()
        t_none = predict_step_time(per_sample, none_s, 1)
        t_full = predict_step_time(per_sample, full_s, 1)
        assert t_full > t_none  # recompute is not free

    def test_dtype_costs_bandwidth(self):
        import dataclasses

        # Bandwidth-bound regime: few FLOPs, lots of traffic.
        per_sample = ModuleCost(flops=1e9, bytes=1e12)
        none_s, _ = self._strategies()
        f32_s = dataclasses.replace(none_s, dtype="float32")
        assert predict_step_time(
            per_sample, f32_s, 1
        ) > predict_step_time(per_sample, none_s, 1)

    def test_pipe_bubble_costs_time(self):
        """Without a comm model, a pipe mesh must rank BELOW the
        equivalent fsdp mesh — the 1F1B bubble is pure overhead."""
        from dlrover_tpu.accelerate.strategy import Strategy

        per_sample = ModuleCost(flops=1e12, bytes=1e9)
        fsdp = Strategy((("fsdp", 8),), remat="none",
                        micro_batch_size=4)
        pipe = Strategy((("pipe", 8),), remat="none",
                        micro_batch_size=4)
        assert predict_step_time(
            per_sample, pipe, 8
        ) > predict_step_time(per_sample, fsdp, 8)

    def test_deep_model_ranks_pipe_above_fsdp(self):
        """The reason pipeline is in the search space at all (ref
        optimization_library.py:38-56): a DEEP model's per-step fsdp
        param re-sync traffic dwarfs the 1F1B bubble, so with the ICI
        term the ranking flips — pipe above pure FSDP — while a small
        model keeps fsdp on top."""
        from dlrover_tpu.accelerate.strategy import Strategy

        per_sample = ModuleCost(flops=1e12, bytes=1e9)
        fsdp = Strategy((("fsdp", 8),), remat="none",
                        micro_batch_size=4)
        pipe = Strategy((("pipe", 8),), remat="none",
                        micro_batch_size=4)
        deep_params = 40 << 30  # 10B params f32 basis
        small_params = 40 << 20
        t_fsdp_deep = predict_step_time(
            per_sample, fsdp, 8, param_bytes=deep_params
        )
        t_pipe_deep = predict_step_time(
            per_sample, pipe, 8, param_bytes=deep_params
        )
        assert t_pipe_deep < t_fsdp_deep
        t_fsdp_small = predict_step_time(
            per_sample, fsdp, 8, param_bytes=small_params
        )
        t_pipe_small = predict_step_time(
            per_sample, pipe, 8, param_bytes=small_params
        )
        assert t_fsdp_small < t_pipe_small

    def test_search_finds_known_best_in_fewer_dry_runs(self):
        """The round's done-criterion, measured: when both remat
        variants fit in memory, the known-best GPT config (no remat —
        fewer FLOPs) is dry-run FIRST under the roofline prior, while
        the memory prior (lower resident bytes = better) tries the
        remat candidate first and needs one more dry-run."""
        import dataclasses as dc

        from dlrover_tpu.accelerate.analyser import (
            analyse_model,
            estimate_step_memory,
        )
        from dlrover_tpu.accelerate.api import _roofline_prior
        from dlrover_tpu.accelerate.bayes_search import (
            BayesStrategySearch,
        )
        from dlrover_tpu.models import gpt

        cfg = dc.replace(
            gpt.GPTConfig.nano(), n_layer=2, block_size=64
        )
        model_init = functools.partial(gpt.init_params, cfg=cfg)
        model_loss = functools.partial(gpt.loss_fn, cfg=cfg)
        tok = jnp.zeros((2, cfg.block_size), jnp.int32)
        candidates = list(self._strategies())
        best = candidates[0]  # no-remat: fewer FLOPs, fits easily

        roof = _roofline_prior(
            model_init, model_loss, (tok, tok), candidates, 1
        )
        assert roof is not None

        analysis = analyse_model(model_init)
        mem = [
            estimate_step_memory(analysis, s, 1 << 20, 16 << 30)[0]
            for s in candidates
        ]

        def dry_runs_until_best(prior):
            search = BayesStrategySearch(
                candidates, cost_prior=prior
            )
            for n in range(1, len(candidates) + 1):
                cand = search.suggest()
                if cand == best:
                    return n
                search.observe(cand, 1.0)  # any finite throughput
            return len(candidates) + 1

        n_roofline = dry_runs_until_best(roof)
        n_memory = dry_runs_until_best(mem)
        assert n_roofline == 1
        assert n_roofline < n_memory


class TestCompileCache:
    def test_enable_sets_config_and_creates_dir(self, tmp_path):
        from dlrover_tpu.accelerate.api import (
            enable_persistent_compile_cache,
        )

        old = jax.config.jax_compilation_cache_dir
        try:
            jax.config.update("jax_compilation_cache_dir", None)
            d = enable_persistent_compile_cache(
                str(tmp_path / "xla")
            )
            assert (tmp_path / "xla").is_dir()
            assert jax.config.jax_compilation_cache_dir == d
            # A configured cache is never clobbered.
            d2 = enable_persistent_compile_cache(
                str(tmp_path / "other")
            )
            assert d2 == d
            assert jax.config.jax_compilation_cache_dir == d
        finally:
            jax.config.update("jax_compilation_cache_dir", old)


class TestTpPlannerPerEdgeBytes:
    def test_profiled_edge_bytes_change_the_plan(self):
        from dlrover_tpu.accelerate.tp_planner import Op, plan_chain

        # A 2-matmul chain ending in a reduce (must be R). With a huge
        # SECOND activation, ending m2 sharded and gathering is
        # expensive, so m2 should go "row" (psum) when its true output
        # bytes are known; with the uniform default both matmuls look
        # alike.
        ops = [
            Op("m1", "matmul", (256, 256)),
            Op("m2", "matmul", (256, 256),
               activation_bytes=64_000_000.0),
            Op("loss", "reduce"),
        ]
        plan = plan_chain(
            ops, tensor_size=4, activation_bytes=1000.0,
            mem_weight=8.0,
        )
        by_name = {p.name: p for p in plan}
        # m2's output is enormous: the planner must not leave it
        # sharded-then-gathered NOR psum it (comm is priced in its
        # own bytes); the cheap path is column m1 (R->S) then row m2
        # paying psum... which costs 64MB — worse than replicating
        # m2's weight (256*256*2 bytes * 8 weight) — so m2 ends
        # replicated on the S path is impossible (needs R in). The
        # exact optimum: m1 column (R->S), m2 row (S->R) would pay
        # 64e6; m1+m2 replicated pays 2*8*128KB ~ 2e6. Assert the
        # planner avoids the 64 MB move.
        assert by_name["m2"].strategy != "row"
        # And with uniform small bytes the classic megatron pairing
        # IS chosen — the override is what changed the plan.
        plan_uniform = plan_chain(
            [
                Op("m1", "matmul", (256, 256)),
                Op("m2", "matmul", (256, 256)),
                Op("loss", "reduce"),
            ],
            tensor_size=4,
            activation_bytes=1000.0,
            mem_weight=8.0,
        )
        by_name_u = {p.name: p for p in plan_uniform}
        assert by_name_u["m1"].strategy == "column"
        assert by_name_u["m2"].strategy == "row"
