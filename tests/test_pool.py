"""Multi-job pool control plane: slice allocator, gang scheduler,
preemption engine, job-routed RPC envelope, and the hermetic drill.

The drill (tools/pool_drill.py --selftest) is the acceptance test:
a 4-slice fake pool runs a low-priority job, a high-priority gang
that doesn't fit preempts it through the graceful checkpoint path,
and the preempted job resumes elastically with exactly-once shard
accounting. The unit tests here pin the scheduler invariants the
drill only samples one path through.
"""

import os
import subprocess
import sys
import threading
import time

import pytest

from dlrover_tpu.pool import (
    JobRuntime,
    PoolJobSpec,
    PoolJobState,
    PoolScheduler,
    SlicePool,
    SliceSpec,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeRT(JobRuntime):
    """Synchronous runtime: parks confirm immediately (staged unless
    told otherwise)."""

    def __init__(self, staged: bool = True, defer_park: bool = False):
        self.placements = []
        self.parks = 0
        self.stops = 0
        self.staged = staged
        self.defer_park = defer_park
        self._pending_park = None

    def place(self, slices, resume):
        self.placements.append((list(slices), resume))

    def park(self, on_parked):
        self.parks += 1
        if self.defer_park:
            self._pending_park = on_parked
        else:
            on_parked({"staged": self.staged, "path": "/ck",
                       "step": 1})

    def confirm_park(self):
        cb, self._pending_park = self._pending_park, None
        cb({"staged": self.staged, "path": "/ck", "step": 1})

    def stop(self):
        self.stops += 1


class TestSlicePool:
    def test_gang_allocation_is_atomic(self):
        pool = SlicePool(4)
        assert pool.allocate("a", "t", 3) == [0, 1, 2]
        # 2 > 1 free: nothing granted, free set untouched.
        assert pool.allocate("b", "t", 2) is None
        assert pool.n_free() == 1
        assert pool.release("a") == [0, 1, 2]
        assert pool.n_free() == 4
        # Idempotent release.
        assert pool.release("a") == []

    def test_double_allocation_refused(self):
        pool = SlicePool(4)
        assert pool.allocate("a", "t", 1) == [0]
        assert pool.allocate("a", "t", 1) is None

    def test_quota_enforced_at_allocation(self):
        pool = SlicePool(4, tenant_quotas={"research": 2})
        assert pool.allocate("a", "research", 2) is not None
        assert pool.allocate("b", "research", 1) is None
        assert pool.allocate("c", "prod", 2) is not None
        pool.release("a")
        assert pool.allocate("b", "research", 1) is not None

    def test_inventory_specs(self):
        pool = SlicePool(
            [SliceSpec(slice_id=7, hosts=2, chips_per_host=4)]
        )
        assert pool.spec(7).chips == 8
        with pytest.raises(ValueError):
            SlicePool([SliceSpec(0), SliceSpec(0)])

    def test_snapshot_shape(self):
        pool = SlicePool(2, tenant_quotas={"t": 1})
        pool.allocate("a", "t", 1)
        snap = pool.snapshot()
        assert snap["total_slices"] == 2
        assert snap["free_slices"] == [1]
        assert snap["tenants"]["t"] == {"used": 1, "quota": 1}


class TestGangScheduler:
    def _sched(self, n=4, quotas=None):
        return PoolScheduler(
            SlicePool(n, tenant_quotas=quotas), park_timeout_s=5.0
        )

    def test_fifo_within_band(self):
        sched = self._sched(4)
        a, b = FakeRT(), FakeRT()
        sched.submit(PoolJobSpec(job_id="a", priority=2,
                                 n_slices=3), a)
        sched.submit(PoolJobSpec(job_id="b", priority=2,
                                 n_slices=3), b)
        # c would fit in the free slice RIGHT NOW, but it must not
        # jump the same-band head b (FIFO within a band).
        c = FakeRT()
        sched.submit(PoolJobSpec(job_id="c", priority=2,
                                 n_slices=1), c)
        assert sched.job_info("a")["state"] == PoolJobState.PLACED
        assert sched.job_info("b")["state"] == PoolJobState.QUEUED
        assert sched.job_info("c")["state"] == PoolJobState.QUEUED
        sched.complete("a")
        # Head first; c then takes the remaining capacity in order.
        assert sched.job_info("b")["state"] == PoolJobState.PLACED
        assert sched.job_info("c")["state"] == PoolJobState.PLACED

    def test_backfill_lower_priority_into_holes(self):
        sched = self._sched(4)
        big, small = FakeRT(), FakeRT()
        sched.submit(PoolJobSpec(job_id="running", priority=3,
                                 n_slices=2), FakeRT())
        sched.submit(PoolJobSpec(job_id="big", priority=3,
                                 n_slices=4), big)
        # big blocked (head, cannot preempt same band); a STRICTLY
        # lower-priority small job takes the hole.
        sched.submit(PoolJobSpec(job_id="small", priority=1,
                                 n_slices=2), small)
        assert sched.job_info("big")["state"] == PoolJobState.QUEUED
        assert (
            sched.job_info("small")["state"] == PoolJobState.PLACED
        )
        assert sched.snapshot()["counters"]["backfills"] == 1

    def test_preemption_youngest_lowest_band_first(self):
        sched = self._sched(4)
        old, young, mid = FakeRT(), FakeRT(), FakeRT()
        sched.submit(PoolJobSpec(job_id="old", priority=1,
                                 n_slices=1), old)
        time.sleep(0.01)
        sched.submit(PoolJobSpec(job_id="young", priority=1,
                                 n_slices=1), young)
        sched.submit(PoolJobSpec(job_id="mid", priority=3,
                                 n_slices=2), mid)
        # Needs 2, 0 free: evict from band 1 only, youngest first.
        sched.submit(
            PoolJobSpec(job_id="hi", priority=5, n_slices=2),
            FakeRT(),
        )
        assert sched.job_info("hi")["state"] == PoolJobState.PLACED
        assert (
            sched.job_info("young")["state"]
            == PoolJobState.PREEMPTED
        )
        assert (
            sched.job_info("old")["state"] == PoolJobState.PREEMPTED
        )
        # Band 3 was never touched: lower bands covered the need.
        assert sched.job_info("mid")["state"] == PoolJobState.PLACED
        assert mid.parks == 0

    def test_no_partial_hold_while_preempting(self):
        """The demanding gang holds ZERO slices until the whole gang
        fits — freed capacity stays in the pool, not half-granted."""
        sched = self._sched(4)
        v1, v2 = FakeRT(defer_park=True), FakeRT(defer_park=True)
        sched.submit(PoolJobSpec(job_id="v1", priority=1,
                                 n_slices=2), v1)
        sched.submit(PoolJobSpec(job_id="v2", priority=1,
                                 n_slices=2), v2)
        hi = FakeRT()
        sched.submit(PoolJobSpec(job_id="hi", priority=5,
                                 n_slices=4), hi)
        # Both victims parking; one confirms — hi must STILL hold
        # nothing (2 free < 4).
        v1.confirm_park()
        assert sched.job_info("hi")["state"] == PoolJobState.QUEUED
        assert sched.job_info("hi")["slices"] == []
        assert sched.pool.n_free() == 2
        v2.confirm_park()
        assert sched.job_info("hi")["state"] == PoolJobState.PLACED
        assert len(sched.job_info("hi")["slices"]) == 4

    def test_checkpoint_staged_before_release(self):
        """Release strictly follows the park confirmation: while the
        victim's checkpoint is in flight its slices stay owned."""
        sched = self._sched(2)
        victim = FakeRT(defer_park=True)
        sched.submit(PoolJobSpec(job_id="victim", priority=1,
                                 n_slices=2), victim)
        sched.submit(
            PoolJobSpec(job_id="hi", priority=5, n_slices=2),
            FakeRT(),
        )
        assert victim.parks == 1
        assert sched.pool.slices_of("victim") == [0, 1]
        assert sched.pool.n_free() == 0
        victim.confirm_park()
        assert sched.pool.slices_of("victim") == []
        assert sched.job_info("hi")["state"] == PoolJobState.PLACED
        snap = sched.snapshot()
        assert snap["counters"]["preemptions"] == {"priority": 1}

    def test_unstaged_release_counts_separately_and_stops(self):
        """Workers parked cleanly but the checkpoint never confirmed
        staging: distinct reason, and the runtime gets a (no-op on a
        parked job) stop so a half-parked one can't linger."""
        sched = self._sched(2)
        victim = FakeRT(staged=False)
        sched.submit(PoolJobSpec(job_id="victim", priority=1,
                                 n_slices=2), victim)
        sched.submit(
            PoolJobSpec(job_id="hi", priority=5, n_slices=2),
            FakeRT(),
        )
        snap = sched.snapshot()
        assert snap["counters"]["preemptions"] == {"unstaged": 1}
        assert victim.stops == 1

    def test_park_timeout_watchdog_reclaims(self):
        sched = PoolScheduler(SlicePool(2), park_timeout_s=0.2)

        class NeverParks(JobRuntime):
            def place(self, slices, resume):
                pass

            def park(self, on_parked):
                pass  # never confirms

            def stop(self):
                pass

        sched.submit(PoolJobSpec(job_id="wedge", priority=1,
                                 n_slices=2), NeverParks())
        sched.submit(
            PoolJobSpec(job_id="hi", priority=5, n_slices=2),
            FakeRT(),
        )
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if sched.job_info("hi")["state"] == PoolJobState.PLACED:
                break
            time.sleep(0.05)
        assert sched.job_info("hi")["state"] == PoolJobState.PLACED
        assert (
            sched.snapshot()["counters"]["preemptions"]
            == {"forced": 1}
        )

    def test_forced_reclaim_orders_runtime_stop(self):
        """A park-timeout reclaim must hard-stop the wedged victim
        before its slices are reused — no double occupancy."""
        sched = PoolScheduler(SlicePool(2), park_timeout_s=0.2)
        stops = []

        class Wedged(JobRuntime):
            def place(self, slices, resume):
                pass

            def park(self, on_parked):
                pass  # never confirms

            def stop(self):
                stops.append(1)

        sched.submit(PoolJobSpec(job_id="wedge", priority=1,
                                 n_slices=2), Wedged())
        sched.submit(
            PoolJobSpec(job_id="hi", priority=5, n_slices=2),
            FakeRT(),
        )
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not stops:
            time.sleep(0.05)
        assert stops, "forced reclaim never stopped the runtime"

    def test_over_quota_over_capacity_head_does_not_starve(self):
        """An over-quota job whose gang ALSO exceeds free capacity
        must not become the blocked head: same-band jobs of other
        tenants keep placing (the quota non-starvation invariant)."""
        sched = self._sched(4, quotas={"research": 2})
        sched.submit(
            PoolJobSpec(job_id="p0", tenant="prod", priority=1,
                        n_slices=2),
            FakeRT(),
        )
        # Over quota (3 > 2) AND over the 2 free slices, higher band.
        sched.submit(
            PoolJobSpec(job_id="rx", tenant="research", priority=3,
                        n_slices=3),
            FakeRT(),
        )
        p1 = FakeRT()
        sched.submit(
            PoolJobSpec(job_id="p1", tenant="prod", priority=3,
                        n_slices=2),
            p1,
        )
        assert sched.job_info("p1")["state"] == PoolJobState.PLACED
        assert sched.job_info("rx")["state"] == PoolJobState.QUEUED
        assert "quota" in sched.job_info("rx")["reason"]

    def test_terminal_records_ring_bounded(self):
        from dlrover_tpu.pool import scheduler as sched_mod

        sched = self._sched(4)
        evicted = []
        sched.on_job_evicted = evicted.append
        old_cap = sched_mod.MAX_TERMINAL_JOBS
        sched_mod.MAX_TERMINAL_JOBS = 3
        try:
            for i in range(6):
                jid = f"j{i}"
                sched.submit(
                    PoolJobSpec(job_id=jid, n_slices=1), FakeRT()
                )
                sched.complete(jid)
            assert evicted == ["j0", "j1", "j2"]
            assert sched.job_info("j0") is None
            assert sched.job_info("j5") is not None
        finally:
            sched_mod.MAX_TERMINAL_JOBS = old_cap

    def test_elastic_resume_with_fewer_slices(self):
        sched = self._sched(4)
        low = FakeRT()
        sched.submit(
            PoolJobSpec(job_id="low", priority=1, n_slices=3,
                        min_slices=1),
            low,
        )
        sched.submit(
            PoolJobSpec(job_id="hi", priority=5, n_slices=4),
            FakeRT(),
        )
        assert (
            sched.job_info("low")["state"] == PoolJobState.PREEMPTED
        )
        # Capacity returns only partially: a band-3 job takes 2.
        sched.complete("hi")
        # low resumed (elastically or fully depending on ordering);
        # with 4 free it gets its full gang back first...
        assert sched.job_info("low")["state"] == PoolJobState.PLACED
        assert len(sched.job_info("low")["slices"]) == 3
        # ...but after a second preemption with only 2 free, the
        # resume is elastic.
        sched.submit(
            PoolJobSpec(job_id="hi2", priority=5, n_slices=4),
            FakeRT(),
        )
        sched.submit(
            PoolJobSpec(job_id="mid", priority=3, n_slices=2),
            FakeRT(),
        )
        sched.complete("hi2")
        info = sched.job_info("low")
        assert info["state"] == PoolJobState.PLACED
        assert len(info["slices"]) == 2  # < gang of 3, >= min 1
        assert low.placements[-1][1] is True  # resume flag

    def test_quota_denied_head_never_starves_others(self):
        sched = self._sched(4, quotas={"research": 2})
        sched.submit(
            PoolJobSpec(job_id="r1", tenant="research",
                        priority=3, n_slices=2),
            FakeRT(),
        )
        # Same tenant over quota, HIGHER priority than everything
        # else waiting — still must not block other tenants.
        sched.submit(
            PoolJobSpec(job_id="r2", tenant="research",
                        priority=5, n_slices=2),
            FakeRT(),
        )
        other = FakeRT()
        sched.submit(
            PoolJobSpec(job_id="p1", tenant="prod", priority=1,
                        n_slices=2),
            other,
        )
        assert (
            sched.job_info("r2")["state"] == PoolJobState.QUEUED
        )
        assert "quota" in sched.job_info("r2")["reason"]
        assert sched.job_info("p1")["state"] == PoolJobState.PLACED
        snap = sched.snapshot()
        assert snap["counters"]["quota_denied"] == {"research": 1}
        # Quota frees -> the queued job places without resubmission.
        sched.complete("r1")
        sched.complete("p1")
        assert sched.job_info("r2")["state"] == PoolJobState.PLACED

    def test_submit_idempotent_and_validated(self):
        sched = self._sched(2)
        r1 = sched.submit(
            PoolJobSpec(job_id="a", n_slices=1), FakeRT()
        )
        r2 = sched.submit(
            PoolJobSpec(job_id="a", n_slices=1), FakeRT()
        )
        assert r2["reason"] == "already submitted"
        assert r2["trace_id"] == r1["trace_id"]
        bad = sched.submit(
            PoolJobSpec(job_id="b", n_slices=99), FakeRT()
        )
        assert bad["state"] == ""
        assert "capacity" in bad["reason"]
        bad = sched.submit(
            PoolJobSpec(job_id="c", priority=42, n_slices=1),
            FakeRT(),
        )
        assert bad["state"] == ""


class TestJobRoutedEnvelope:
    """The per-job refactor: one server, many masters, state
    isolation keyed by the `_job` envelope id."""

    def test_envelope_roundtrip(self):
        from dlrover_tpu.common import messages as msg

        data = msg.serialize(
            msg.KVStoreSetRequest(key="k", value=b"v"),
            trace={"t": "1"},
            job_id="job-a",
        )
        m, trace, job = msg.deserialize_envelope(data)
        assert isinstance(m, msg.KVStoreSetRequest)
        assert trace == {"t": "1"}
        assert job == "job-a"
        # Old-style decode drops the envelope fields cleanly.
        m2, trace2 = msg.deserialize_with_trace(data)
        assert m2.key == "k" and trace2 == {"t": "1"}

    def test_routing_dispatcher_isolates_and_falls_through(self):
        from dlrover_tpu.common import messages as msg
        from dlrover_tpu.common.comm import (
            JobRoutingDispatcher,
            RpcDispatcher,
        )

        router = JobRoutingDispatcher()
        seen = []
        router.register_get(
            msg.PoolQueryRequest,
            lambda req: seen.append("pool") or "pool-level",
        )
        d_a = RpcDispatcher()
        d_a.register_get(
            msg.KVStoreGetRequest, lambda req: f"a:{req.key}"
        )
        router.register_job("a", d_a)
        assert (
            router.handle_get(
                msg.KVStoreGetRequest(key="x"), job_id="a"
            )
            == "a:x"
        )
        # Unhandled type on the job dispatcher falls through to the
        # pool level (e.g. TraceQueryRequest on the shared store).
        assert (
            router.handle_get(
                msg.PoolQueryRequest(), job_id="a"
            )
            == "pool-level"
        )
        with pytest.raises(KeyError, match="unknown job"):
            router.handle_get(
                msg.KVStoreGetRequest(key="x"), job_id="ghost"
            )

    def test_two_jobs_state_isolated_over_real_rpc(self):
        """Two embedded JobMasters behind one pool server: kv store,
        shard ledger, and node tables never bleed across job ids."""
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.pool import PoolJobSpec, TPUPoolMaster

        master = TPUPoolMaster(slices=4, watch_interval=9999.0)
        master.prepare()
        clients = []
        try:
            for jid in ("job-a", "job-b"):
                r = master.submit(
                    PoolJobSpec(job_id=jid, n_slices=2)
                )
                assert r["state"] == PoolJobState.PLACED, r
            ca = MasterClient(
                master.addr, node_id=0, job_id="job-a"
            )
            cb = MasterClient(
                master.addr, node_id=0, job_id="job-b"
            )
            clients += [ca, cb]
            ca.register_node("worker")
            cb.register_node("worker")
            ca.kv_set("shared-key", b"from-a")
            assert cb.kv_get("shared-key") is None
            assert ca.kv_get("shared-key") == b"from-a"
            ca.create_dataset(
                "ds", dataset_size=4, batch_size=1,
                num_minibatches_per_shard=1,
            )
            task = ca.get_task("ds")
            assert task.task_id >= 0
            # job-b has no such dataset: wait task, not job-a's.
            tb = cb.get_task("ds")
            assert tb.task_id < 0
            ctx_a = master.context("job-a")
            ctx_b = master.context("job-b")
            assert ctx_a.master.task_manager.has_dataset("ds")
            assert not ctx_b.master.task_manager.has_dataset("ds")
            assert len(ctx_a.master.job_manager.list_nodes()) == 1
            assert len(ctx_b.master.job_manager.list_nodes()) == 1
        finally:
            for c in clients:
                c.close()
            master.stop()


class TestPoolGrantConsumers:
    """Per-job planes consume pool grants instead of assuming an
    infinite cluster."""

    def test_ensure_role_capped_by_grant(self):
        from dlrover_tpu.master.job_manager import JobManager

        jm = JobManager()
        jm.pool_grant = 2
        launched = jm.ensure_role("worker", 5)
        assert len(launched) == 2
        assert jm.grant_headroom() == 0
        # Without a grant: unconstrained (single-job behavior).
        jm2 = JobManager()
        assert jm2.grant_headroom() is None
        assert len(jm2.ensure_role("worker", 5)) == 5

    def test_remediation_pool_grant_governor(self):
        from dlrover_tpu.master.job_manager import JobManager
        from dlrover_tpu.master.remediation import (
            ACTION_CORDON_REPLACE,
            GOVERNOR_OK,
            RemediationEngine,
        )
        from dlrover_tpu.obs.health import HealthMonitor
        from dlrover_tpu.obs.timeseries import TimeSeriesStore

        jm = JobManager()
        for _ in range(2):
            jm.register_node("worker")
        store = TimeSeriesStore()
        health = HealthMonitor(
            store=store, job_manager=jm, interval=9999.0
        )
        eng = RemediationEngine(
            health=health,
            job_manager=jm,
            servicer=None,
            config={"hysteresis_ticks": 1, "cooldown_s": 0.0},
            interval=9999.0,
            min_nodes=1,
        )
        from dlrover_tpu.obs.health import HealthVerdict

        v = HealthVerdict(
            detector="node_stalled", severity="critical",
            message="m", node_id=0, host="h",
        )
        eng._sick[v.key()] = 99
        g_free = eng._check_governors(
            v, ACTION_CORDON_REPLACE, time.time()
        )
        assert g_free["pool_grant"] == GOVERNOR_OK  # no grant
        jm.pool_grant = 2  # both slots alive -> zero headroom
        g_full = eng._check_governors(
            v, ACTION_CORDON_REPLACE, time.time()
        )
        assert g_full["pool_grant"].startswith("blocked")
        jm.pool_grant = 3
        g_room = eng._check_governors(
            v, ACTION_CORDON_REPLACE, time.time()
        )
        assert g_room["pool_grant"] == GOVERNOR_OK


class TestPoolDrill:
    def test_pool_drill_selftest(self):
        """The hermetic acceptance drill: gang placement, graceful
        checkpoint-backed preemption (staged before release),
        whole-gang placement, elastic resume with exactly-once shard
        accounting, quota non-starvation, and the one-trace incident
        story."""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("DLROVER_TPU_CHAOS", None)
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "tools", "pool_drill.py"),
                "--selftest",
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=180,
        )
        assert proc.returncode == 0, (
            f"pool drill failed\nstdout:\n{proc.stdout}\n"
            f"stderr:\n{proc.stderr}"
        )
        assert "pool drill selftest ok" in proc.stdout
