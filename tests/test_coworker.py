"""Coworker shm data pipeline: real producer processes, real shm.

Mirrors the reference's shm-context test approach (producer/consumer
processes over preallocated slots): batches cross process boundaries
through POSIX shm, crashes respawn, end-of-data terminates cleanly.
"""

import functools
import os
import uuid

import numpy as np
import pytest

from dlrover_tpu.data.shm_ring import (
    ShmBatchRing,
    pack_batch,
    unpack_batch,
)


@pytest.fixture(autouse=True)
def _isolated_job(monkeypatch):
    monkeypatch.setenv(
        "DLROVER_TPU_JOB_NAME", f"cw{uuid.uuid4().hex[:8]}"
    )
    yield


def _mk_batches(worker_id, n_batches=5, dim=8):
    for i in range(n_batches):
        yield {
            "x": np.full((4, dim), worker_id * 100 + i, np.float32),
            "ids": np.arange(4, dtype=np.int64) + worker_id * 1000,
        }


def _crashy_batches(worker_id, flag_dir, n_batches=4):
    flag = os.path.join(flag_dir, f"crashed_{worker_id}")
    if worker_id == 1 and not os.path.exists(flag):
        open(flag, "w").close()
        yield {"x": np.zeros((2, 2), np.float32)}
        raise RuntimeError("synthetic preprocessing crash")
    yield from _mk_batches(worker_id, n_batches)


class TestShmRing:
    def test_pack_unpack_roundtrip(self):
        buf = memoryview(bytearray(1 << 20))
        batch = {
            "a": np.arange(12, dtype=np.float32).reshape(3, 4),
            "b": np.array([1, 2, 3], np.int64),
        }
        pack_batch(buf, batch, {"step": 7})
        got, extra = unpack_batch(buf)
        assert extra == {"step": 7}
        np.testing.assert_array_equal(got["a"], batch["a"])
        np.testing.assert_array_equal(got["b"], batch["b"])

    def test_oversized_batch_raises(self):
        buf = memoryview(bytearray(256))
        with pytest.raises(ValueError, match="slot holds"):
            pack_batch(
                buf, {"x": np.zeros(1024, np.float32)}, None
            )

    def test_put_get_through_slots(self):
        ring = ShmBatchRing(
            "t1", num_slots=2, slot_bytes=1 << 16, server=True
        )
        try:
            for i in range(5):  # > num_slots: slots recycle
                assert ring.put(
                    {"x": np.full((4,), i, np.float32)},
                    extra={"i": i},
                )
                batch, extra = ring.get(timeout=5)
                assert extra["i"] == i
                np.testing.assert_array_equal(
                    batch["x"], np.full((4,), i, np.float32)
                )
        finally:
            ring.close(unlink=True)

    def test_control_message_consumes_no_slot(self):
        ring = ShmBatchRing(
            "t2", num_slots=1, slot_bytes=1 << 12, server=True
        )
        try:
            ring.put_control({"end": 0})
            batch, info = ring.get(timeout=5)
            assert batch is None and info == {"end": 0}
            # the single slot is still free
            assert ring.put({"x": np.zeros(2, np.float32)})
        finally:
            ring.close(unlink=True)


class TestCoworkerLoader:
    def test_all_batches_arrive_from_two_workers(self):
        from dlrover_tpu.data.coworker import CoworkerDataLoader

        loader = CoworkerDataLoader(
            functools.partial(_mk_batches, n_batches=5),
            num_workers=2,
            num_slots=4,
            slot_bytes=1 << 16,
            name=f"ld{uuid.uuid4().hex[:6]}",
        ).start()
        try:
            seen = []
            for batch in loader:
                seen.append(int(batch["x"][0, 0]))
            assert sorted(seen) == sorted(
                w * 100 + i for w in (0, 1) for i in range(5)
            )
        finally:
            loader.close()

    def test_crashed_worker_respawns_and_finishes(self, tmp_path):
        from dlrover_tpu.data.coworker import CoworkerDataLoader

        loader = CoworkerDataLoader(
            functools.partial(
                _crashy_batches, flag_dir=str(tmp_path), n_batches=4
            ),
            num_workers=2,
            num_slots=4,
            slot_bytes=1 << 16,
            name=f"ld{uuid.uuid4().hex[:6]}",
            max_restarts=2,
        ).start()
        try:
            vals = [int(b["x"][0, 0]) for b in loader]
            # worker 0's 4 batches + worker 1's post-respawn 4 (plus
            # the pre-crash zero batch)
            for want in [0, 1, 2, 3, 100, 101, 102, 103]:
                assert want in vals, (want, vals)
            assert loader._restarts.get(1, 0) >= 1
        finally:
            loader.close()

    def test_worker_exhausting_restarts_ends_stream(self, tmp_path):
        from dlrover_tpu.data.coworker import CoworkerDataLoader

        loader = CoworkerDataLoader(
            functools.partial(_always_crash, n_batches=2),
            num_workers=1,
            num_slots=2,
            slot_bytes=1 << 14,
            name=f"ld{uuid.uuid4().hex[:6]}",
            max_restarts=1,
        ).start()
        try:
            batches = list(loader.batches(max_batches=50))
            # crashed every incarnation: iteration still terminates
            assert len(batches) <= 4
        finally:
            loader.close()


def _always_crash(worker_id, n_batches=2):
    yield {"x": np.zeros((1,), np.float32)}
    raise RuntimeError("always crashes")


def _fetch(indices):
    return {"idx": indices, "x": indices.astype(np.float32) * 0.5}


class TestElasticCoworkers:
    def test_coworkers_drain_master_dataset(self):
        """Coworker producers pull index shards from a real master's
        dynamic sharding service; every sample arrives exactly once
        (no failures) through the shm ring."""
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.agent.sharding_client import (
            IndexShardingClient,
        )
        from dlrover_tpu.data.coworker import (
            CoworkerDataLoader,
            make_sharded_batches,
        )
        from dlrover_tpu.master.master import JobMaster

        master = JobMaster(port=0, node_num=1, rdzv_timeout=2.0)
        master.prepare()
        try:
            setup = IndexShardingClient(
                "ds", batch_size=4,
                client=MasterClient(master.addr, node_id=0),
            )
            setup.create_dataset(
                dataset_size=40, batch_size=4,
                num_minibatches_per_shard=2,
            )
            loader = CoworkerDataLoader(
                make_sharded_batches(
                    master.addr, "ds", batch_size=4, fetch_fn=_fetch
                ),
                num_workers=2,
                num_slots=4,
                slot_bytes=1 << 14,
                name=f"ld{uuid.uuid4().hex[:6]}",
            ).start()
            try:
                seen = []
                for batch in loader:
                    seen.extend(batch["idx"].tolist())
                    np.testing.assert_array_equal(
                        batch["x"],
                        batch["idx"].astype(np.float32) * 0.5,
                    )
                assert sorted(seen) == list(range(40))
            finally:
                loader.close()
        finally:
            master.stop()
