"""Golden-file tests: k8s manifest shapes vs the reference operator's
CRDs (go/operator/api/v1alpha1/{elasticjob,scaleplan}_types.go and
config/samples/). The GKE client itself can't run here (no cluster),
but the manifests it would submit are pinned byte-for-byte in shape.
"""

import os

import yaml

from dlrover_tpu.common.constants import NodeStatus, NodeType
from dlrover_tpu.common.node import Node, NodeResource
from dlrover_tpu.master.job_manager import ScalePlan
from dlrover_tpu.scheduler.factory import (
    _pod_manifest,
    elasticjob_manifest,
    scaleplan_manifest,
)

GOLDEN = os.path.join(os.path.dirname(__file__), "golden")


def _load(name):
    with open(os.path.join(GOLDEN, name)) as f:
        return yaml.safe_load(f)


def _worker(node_id, rank=None):
    return Node(
        type=NodeType.WORKER,
        id=node_id,
        rank=rank if rank is not None else node_id,
        status=NodeStatus.PENDING,
        config_resource=NodeResource(cpu=8.0, memory_mb=16384),
    )


def test_elasticjob_manifest_matches_golden():
    got = elasticjob_manifest(
        "ctr-train",
        distribution_strategy="ParameterServerStrategy",
        resource_limits={"cpu": 64, "memory": "131072Mi"},
        optimize_mode="cluster",
        brain_service="dlrover-brain:50001",
        replica_specs={
            "ps": {"replicas": 2, "restartCount": 3},
            "worker": {
                "replicas": 8,
                "restartCount": 3,
                "autoScale": True,
            },
        },
    )
    assert got == _load("elasticjob.yaml")


def test_scaleplan_manifest_matches_golden():
    plan = ScalePlan()
    plan.launch_nodes = [_worker(3)]
    plan.remove_nodes = [_worker(0)]
    got = scaleplan_manifest(
        "ctr-train-plan-1",
        "ctr-train",
        plan,
        replica_resource_specs={
            "worker": {
                "replicas": 4,
                "resource": {"cpu": "8", "memory": "16384Mi"},
            }
        },
        ps_hosts=["ctr-train-ps-0:2222", "ctr-train-ps-1:2222"],
    )
    assert got == _load("scaleplan.yaml")


def test_tpu_pod_manifest_matches_golden():
    spec = {
        "name": "ctr-train-worker-3",
        "job": "ctr-train",
        "type": "worker",
        "node_id": 3,
        "rank": 3,
        "cpu": 8,
        "memory_mb": 16384,
        "tpu_accelerator": "v5p",
        "tpu_chips": 4,
        "tpu_slice": 1,
    }
    assert _pod_manifest(spec, "default") == _load("tpu_pod.yaml")


def test_pod_manifest_omits_slice_pin_when_absent():
    spec = {
        "name": "j-worker-0",
        "job": "j",
        "node_id": 0,
        "tpu_accelerator": "v5e",
        "tpu_chips": 8,
    }
    m = _pod_manifest(spec, "ns")
    assert "dlrover-tpu/slice" not in m["spec"]["nodeSelector"]
    assert m["metadata"]["namespace"] == "ns"


def test_scaler_pod_spec_feeds_golden_pod_shape():
    """The spec TPUPodScaler emits contains every key _pod_manifest
    consumes — the two halves of the GKE path stay in sync."""
    from dlrover_tpu.master.scaler import FakeClusterClient, TPUPodScaler

    client = FakeClusterClient()
    scaler = TPUPodScaler("ctr-train", client)
    node = _worker(3)
    node.config_resource.tpu_type = "v5p"
    node.config_resource.chips = 4
    node.config_resource.slice_id = 1
    plan = ScalePlan()
    plan.launch_nodes = [node]
    scaler.scale(plan)
    (pod,) = client.list_pods("ctr-train")
    manifest = _pod_manifest(pod, "default")
    assert manifest == _load("tpu_pod.yaml")
