"""KV-cache decoding: teacher-forcing parity with training forward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models import generate, gpt, llama


@pytest.fixture(scope="module")
def gpt_setup():
    cfg = gpt.GPTConfig(
        vocab_size=128, block_size=32, n_layer=2, n_head=2, n_embd=32,
        dtype=jnp.float32, remat=False, use_flash_attention=False,
    )
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def llama_setup():
    cfg = llama.LlamaConfig.tiny()  # GQA on
    import dataclasses

    cfg = dataclasses.replace(cfg, use_flash_attention=False)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _tokens(cfg, b=2, t=16):
    return jax.random.randint(
        jax.random.PRNGKey(1), (b, t), 0, cfg.vocab_size
    )


def test_gpt_cached_decode_matches_forward(gpt_setup):
    cfg, params = gpt_setup
    tokens = _tokens(cfg)
    got = generate.decode_logits_sequential(params, cfg, tokens)
    want = gpt.forward(params, tokens, cfg)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-3
    )


def test_llama_cached_decode_matches_forward(llama_setup):
    cfg, params = llama_setup
    tokens = _tokens(cfg)
    got = generate.decode_logits_sequential(params, cfg, tokens)
    want = llama.forward(params, tokens, cfg)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-3
    )


def test_greedy_generate_matches_argmax_rollout(gpt_setup):
    """Greedy cached generation equals the naive full-forward argmax
    rollout (the nanoGPT sample loop)."""
    cfg, params = gpt_setup
    prompt = _tokens(cfg, b=1, t=4)
    out = generate.generate(
        params, cfg, prompt, max_new_tokens=6, temperature=0.0
    )
    assert out.shape == (1, 10)
    seq = prompt
    for _ in range(6):
        logits = gpt.forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1)[:, None]
        seq = jnp.concatenate([seq, nxt.astype(seq.dtype)], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


def test_llama_greedy_generate_matches_argmax_rollout(llama_setup):
    """Covers llama_prefill (batched prompt pass, GQA) + decode."""
    cfg, params = llama_setup
    prompt = _tokens(cfg, b=1, t=4)
    out = generate.generate(
        params, cfg, prompt, max_new_tokens=5, temperature=0.0
    )
    seq = prompt
    for _ in range(5):
        logits = llama.forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1)[:, None]
        seq = jnp.concatenate([seq, nxt.astype(seq.dtype)], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


def test_generate_jits_and_is_deterministic(llama_setup):
    cfg, params = llama_setup
    prompt = _tokens(cfg, b=2, t=3)
    fn = jax.jit(
        lambda p, t, k: generate.generate(
            p, cfg, t, max_new_tokens=5, temperature=1.0, top_k=8,
            key=k,
        )
    )
    k = jax.random.PRNGKey(7)
    a = fn(params, prompt, k)
    b = fn(params, prompt, k)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (2, 8)
    assert int(a.max()) < cfg.vocab_size


def test_generate_rejects_overflow(gpt_setup):
    cfg, params = gpt_setup
    prompt = _tokens(cfg, b=1, t=30)
    with pytest.raises(ValueError):
        generate.generate(params, cfg, prompt, max_new_tokens=10)


def test_mistral_windowed_decode_matches_forward():
    """Sliding-window decode parity: with a prompt LONGER than the
    window, the cached decode path must apply the same band mask as
    the training forward — an unwindowed cache attention diverges at
    every position past the window (the r4 regression this guards)."""
    import dataclasses

    cfg = dataclasses.replace(
        llama.LlamaConfig.tiny(), sliding_window=8, block_size=32
    )
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(8), (2, 25), 8, cfg.vocab_size
    )
    got = generate.decode_logits_sequential(params, cfg, tokens)
    want = llama.forward(params, tokens, cfg)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-3
    )
    # Sanity that the window binds here: the unwindowed forward
    # must NOT match at the last position (else this test is vacuous).
    full = llama.forward(
        params, tokens, dataclasses.replace(cfg, sliding_window=None)
    )
    assert not np.allclose(
        np.asarray(full[:, -1]), np.asarray(want[:, -1]), atol=1e-4
    )


def test_chunked_prefill_matches_one_shot(llama_setup):
    """Bounded-memory chunked prefill (rectangular flash against the
    growing cache) == the one-shot prefill: same last-position
    logits, same cache contents — including a final partial chunk."""
    cfg, params = llama_setup
    t0 = 40  # chunks of 16 -> 16, 16, 8 (partial tail)
    prompt = jax.random.randint(
        jax.random.PRNGKey(11), (2, t0), 0, cfg.vocab_size
    )
    cache = generate._cache_for(cfg, 2, t0, cfg.n_kv_head)
    want_logits, want_cache = generate.llama_prefill(
        params, cache, prompt, cfg
    )
    got_logits, got_cache = generate.llama_prefill_chunked(
        params, cache, prompt, cfg, chunk_size=16
    )
    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(want_logits),
        atol=2e-4, rtol=1e-3,
    )
    np.testing.assert_allclose(
        np.asarray(got_cache.k), np.asarray(want_cache.k),
        atol=2e-5, rtol=1e-4,
    )
    np.testing.assert_allclose(
        np.asarray(got_cache.v), np.asarray(want_cache.v),
        atol=2e-5, rtol=1e-4,
    )


def test_chunked_prefill_rejects_empty_prompt(llama_setup):
    """A zero-length prompt would previously crash deep inside
    _rms_norm with x_last=None (ADVICE r4); it must fail at the API
    boundary with a clear message."""
    cfg, params = llama_setup
    empty = jnp.zeros((2, 0), jnp.int32)
    cache = generate._cache_for(cfg, 2, 8, cfg.n_kv_head)
    with pytest.raises(ValueError, match="at least one prompt token"):
        generate.llama_prefill_chunked(params, cache, empty, cfg)


def test_mistral_chunked_prefill_matches_one_shot():
    """Windowed chunked prefill == one-shot windowed prefill, with
    the prompt long enough that the band binds across chunks."""
    import dataclasses

    cfg = dataclasses.replace(
        llama.LlamaConfig.tiny(), sliding_window=8, block_size=64
    )
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(
        jax.random.PRNGKey(12), (2, 40), 0, cfg.vocab_size
    )
    cache = generate._cache_for(cfg, 2, 40, cfg.n_kv_head)
    want, want_cache = generate.llama_prefill(
        params, cache, prompt, cfg
    )
    got, got_cache = generate.llama_prefill_chunked(
        params, cache, prompt, cfg, chunk_size=16
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(got_cache.k), np.asarray(want_cache.k),
        atol=2e-5, rtol=1e-4,
    )
    np.testing.assert_allclose(
        np.asarray(got_cache.v), np.asarray(want_cache.v),
        atol=2e-5, rtol=1e-4,
    )
