"""Master tests: rendezvous, sharding, speed monitor, servicer over RPC.

Follows the reference's test technique (SURVEY.md §4): a real in-process
master + N simulated nodes calling the client API.
"""

import time

import pytest

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import RpcClient
from dlrover_tpu.common.constants import RendezvousName, TaskType
from dlrover_tpu.master.job_manager import JobManager
from dlrover_tpu.master.master import JobMaster
from dlrover_tpu.master.rendezvous import (
    ElasticRendezvous,
    NetworkCheckRendezvous,
)
from dlrover_tpu.master.task_manager import TaskManager
from dlrover_tpu.master.speed_monitor import SpeedMonitor


class TestElasticRendezvous:
    def test_completes_at_max_nodes(self):
        rdzv = ElasticRendezvous()
        rdzv.update_params(min_nodes=2, max_nodes=4, waiting_timeout=30)
        for rank in range(4):
            assert rdzv.join(rank, 8) == 0
        round_, group, world = rdzv.get_comm_world(0)
        assert round_ == 1
        assert world == {0: 8, 1: 8, 2: 8, 3: 8}

    def test_completes_after_timeout_with_min_nodes(self):
        rdzv = ElasticRendezvous()
        rdzv.update_params(min_nodes=2, max_nodes=4, waiting_timeout=0.2)
        rdzv.join(0, 8)
        rdzv.join(1, 8)
        _, _, world = rdzv.get_comm_world(0)
        assert world == {}  # not yet: timeout hasn't elapsed
        time.sleep(0.3)
        _, _, world = rdzv.get_comm_world(0)
        assert world == {0: 8, 1: 8}

    def test_node_unit_rounding(self):
        rdzv = ElasticRendezvous()
        rdzv.update_params(
            min_nodes=2, max_nodes=8, waiting_timeout=0.1, node_unit=2
        )
        for rank in range(3):
            rdzv.join(rank, 4)
        time.sleep(0.2)
        _, _, world = rdzv.get_comm_world(0)
        # 3 nodes rounded down to 2 (one full node_unit)
        assert world == {0: 4, 1: 4}

    def test_restart_triggers_waiting_signal(self):
        rdzv = ElasticRendezvous()
        rdzv.update_params(min_nodes=2, max_nodes=2, waiting_timeout=10)
        rdzv.join(0, 8)
        rdzv.join(1, 8)
        rdzv.get_comm_world(0)
        assert rdzv.num_nodes_waiting() == 0
        # A previous member re-joins (process restart) -> immediate signal.
        rdzv.join(0, 8)
        assert rdzv.num_nodes_waiting() == 1


class TestNetworkCheckRendezvous:
    def _run_round(self, rdzv, statuses, times):
        """Simulate all nodes joining, getting groups, and reporting."""
        for rank in statuses:
            rdzv.join(rank, 8)
        groups = {}
        for rank in statuses:
            _, group, world = rdzv.get_comm_world(rank)
            groups[rank] = world
        for rank in statuses:
            rdzv.report_result(rank, statuses[rank], times[rank])
        return groups

    def test_pairwise_grouping(self):
        rdzv = NetworkCheckRendezvous()
        rdzv.update_params(min_nodes=4, max_nodes=4, waiting_timeout=1)
        statuses = {0: True, 1: True, 2: True, 3: True}
        times = {0: 1.0, 1: 1.1, 2: 0.9, 3: 1.0}
        groups = self._run_round(rdzv, statuses, times)
        assert groups[0] == {0: 8, 1: 8}
        assert groups[2] == {2: 8, 3: 8}
        faults, reason = rdzv.check_fault_nodes()
        assert faults == []

    def test_fault_localization_two_rounds(self):
        rdzv = NetworkCheckRendezvous()
        rdzv.update_params(min_nodes=4, max_nodes=4, waiting_timeout=1)
        # Round 0: node 1 is broken, so pair (0,1) both fail.
        statuses = {0: False, 1: False, 2: True, 3: True}
        times = {0: 10.0, 1: 10.0, 2: 1.0, 3: 1.0}
        self._run_round(rdzv, statuses, times)
        faults, reason = rdzv.check_fault_nodes()
        assert set(faults) == {0, 1}
        # Round 1: re-paired with good partners, node 0 passes, 1 fails.
        statuses = {0: True, 1: False, 2: True, 3: False}
        times = {0: 1.0, 1: 10.0, 2: 1.0, 3: 10.0}
        self._run_round(rdzv, statuses, times)
        faults, reason = rdzv.check_fault_nodes()
        assert faults == [1]

    def test_straggler_detection(self):
        rdzv = NetworkCheckRendezvous()
        rdzv.update_params(min_nodes=4, max_nodes=4, waiting_timeout=1)
        statuses = {0: True, 1: True, 2: True, 3: True}
        times = {0: 1.0, 1: 1.0, 2: 1.0, 3: 5.0}
        self._run_round(rdzv, statuses, times)
        stragglers, _ = rdzv.get_stragglers()
        assert stragglers == [3]


class TestTaskManager:
    def test_shard_lifecycle(self):
        tm = TaskManager()
        tm.create_dataset("ds", dataset_size=100, shard_size=10)
        tasks = []
        while True:
            task = tm.get_task(0, "ds")
            if task.task_type != TaskType.TRAINING:
                break
            tasks.append(task)
            tm.report_task_result("ds", task.task_id, True)
        assert len(tasks) == 10
        assert tasks[0].shard.start == 0 and tasks[0].shard.end == 10
        assert tm.finished()

    def test_dead_node_tasks_requeued(self):
        tm = TaskManager()
        tm.create_dataset("ds", dataset_size=40, shard_size=10)
        t1 = tm.get_task(0, "ds")
        t2 = tm.get_task(1, "ds")
        tm.recover_node_tasks(0)  # node 0 dies holding t1
        remaining = []
        while True:
            t = tm.get_task(1, "ds")
            if t.task_type != TaskType.TRAINING:
                break
            remaining.append(t)
            tm.report_task_result("ds", t.task_id, True)
        tm.report_task_result("ds", t2.task_id, True)
        # t1's shard was reassigned: all 4 shards processed
        starts = sorted([t1.shard.start] + [t.shard.start for t in remaining])
        assert starts == [0, 0, 20, 30]  # shard 0 appears twice (requeued)

    def test_checkpoint_restore(self):
        tm = TaskManager()
        tm.create_dataset("ds", dataset_size=40, shard_size=10)
        t = tm.get_task(0, "ds")
        ckpt = tm.get_shard_checkpoint("ds")
        assert ckpt
        tm2 = TaskManager()
        tm2.create_dataset("ds", dataset_size=40, shard_size=10)
        assert tm2.restore_shard_checkpoint("ds", ckpt)
        # All 4 shards (incl. the in-flight one) are in todo again.
        seen = 0
        while True:
            task = tm2.get_task(0, "ds")
            if task.task_type != TaskType.TRAINING:
                break
            seen += 1
            tm2.report_task_result("ds", task.task_id, True)
        assert seen == 4

    def test_multi_epoch(self):
        tm = TaskManager()
        tm.create_dataset("ds", dataset_size=20, shard_size=10, num_epochs=2)
        count = 0
        while True:
            task = tm.get_task(0, "ds")
            if task.task_type != TaskType.TRAINING:
                break
            count += 1
            tm.report_task_result("ds", task.task_id, True)
        assert count == 4  # 2 shards x 2 epochs


class TestSpeedMonitor:
    def test_throughput_and_recovery(self):
        sm = SpeedMonitor(window=10)
        sm.add_running_node(0)
        sm.add_running_node(1)
        t = 1000.0
        for step in range(5):
            sm.collect_global_step(step, t + step, tokens=100)
        assert sm.running_speed() == pytest.approx(1.0)
        assert sm.token_throughput() == pytest.approx(100.0)
        sm.remove_running_node(1)  # failure event
        assert sm.recovery_seconds() is not None  # already >= 90%


class TestJobManager:
    def test_relaunch_on_failure(self):
        jm = JobManager(max_relaunch=2)
        node = jm.register_node(node_id=0)
        action = jm.handle_failure_report(
            0, "oom killed", "process_error", 0
        )
        assert action == "relaunch_node"
        assert node.exit_reason == "oom"
        assert len(jm._scaler.executed_plans) == 1

    def test_fatal_error_no_relaunch(self):
        jm = JobManager()
        jm.register_node(node_id=0)
        node = jm.get_node(0)
        node.exit_reason = "fatal_error"
        node.relaunchable = False
        assert jm.handle_failure_report(0, "x", "rdzv_error", 0) == "stop"


class TestMasterEndToEnd:
    """Real master over gRPC, simulated agents (ref test technique)."""

    @pytest.fixture()
    def master(self):
        m = JobMaster(port=0, node_num=2, rdzv_timeout=1.0)
        m.prepare()
        yield m
        m.stop()

    def test_full_flow(self, master):
        client0 = RpcClient(master.addr)
        client1 = RpcClient(master.addr)
        # register both nodes
        client0.report(msg.NodeAddressRequest(node_id=0, node_ip="h0"))
        client1.report(msg.NodeAddressRequest(node_id=1, node_ip="h1"))
        # rendezvous
        for nid, c in ((0, client0), (1, client1)):
            resp = c.get(
                msg.JoinRendezvousRequest(
                    node_id=nid,
                    node_rank=nid,
                    local_world_size=4,
                    rdzv_name=RendezvousName.TRAINING,
                )
            )
            assert resp.round == 0
        world = client0.get(
            msg.CommWorldRequest(
                node_id=0, rdzv_name=RendezvousName.TRAINING
            )
        )
        assert world.world == {0: 4, 1: 4}
        # kv store bootstrap
        client0.report(
            msg.KVStoreSetRequest(key="coordinator", value=b"h0:9999")
        )
        got = client1.get(msg.KVStoreGetRequest(key="coordinator"))
        assert got.value == b"h0:9999"
        # dataset + tasks
        client0.report(
            msg.DatasetShardParams(
                batch_size=4,
                num_minibatches_per_shard=2,
                dataset_size=32,
                dataset_name="train",
                task_type=TaskType.TRAINING,
            )
        )
        task = client0.get(msg.TaskRequest(node_id=0, dataset_name="train"))
        assert task.task_type == TaskType.TRAINING
        assert task.shard.end - task.shard.start == 8
        client0.report(
            msg.TaskResultRequest(
                node_id=0, dataset_name="train", task_id=task.task_id
            )
        )
        # step + heartbeat
        client0.report(msg.StepReport(node_id=0, step=1, tokens=512))
        hb = client0.report(msg.HeartbeatRequest(node_id=0))
        assert hb.action == "none"
        # failure report relaunches + requeues
        client1.report(
            msg.NodeFailureReport(
                node_id=1, error_data="oom", level="process_error"
            )
        )
        nodes = client0.get(msg.JobNodesRequest())
        statuses = {n.node_id: n.status for n in nodes.nodes}
        # OOM escalates to a node relaunch: the replacement incarnation
        # is pending (the job is NOT done).
        assert statuses[1] == "pending"
        assert not master.job_manager.all_workers_done()
        client0.close()
        client1.close()


def test_node_gone_requeues_shards_via_listener():
    """A preempted node (agent never reports) must release its data
    shards through the master's DELETED listener — the cleanup the
    servicer only does on explicit failure reports."""
    from dlrover_tpu.master.master import JobMaster

    master = JobMaster(node_num=2, rdzv_timeout=1)
    master.prepare()
    try:
        jm = master.job_manager
        jm.register_node(node_id=0)
        jm.register_node(node_id=1)
        # one shard total: node 0 takes it, then dies silently
        master.task_manager.create_dataset(
            "ds", dataset_size=4, shard_size=4
        )
        task = master.task_manager.get_task(0, "ds")
        assert task.shard is not None
        jm.handle_node_gone(0, reason="Preempted")
        assert jm.get_node(0).status == "pending"
        # the shard is back on the todo queue for the survivor
        task2 = master.task_manager.get_task(1, "ds")
        assert task2.shard is not None
        assert task2.shard.start == task.shard.start
    finally:
        master.stop()
