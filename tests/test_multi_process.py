"""Tests for cross-process IPC primitives (single-process + subprocess)."""

import multiprocessing as mp
import queue

import pytest

from dlrover_tpu.common.multi_process import (
    SharedDict,
    SharedLock,
    SharedMemoryHandle,
    SharedQueue,
)


class TestSharedLock:
    def test_acquire_release(self):
        lock = SharedLock("t1", server=True)
        try:
            assert lock.acquire()
            assert lock.locked()
            client = SharedLock("t1")
            assert not client.acquire(blocking=False)
            lock.release()
            assert client.acquire(blocking=False)
            client.release()
            client.close()
        finally:
            lock.close()

    def test_context_manager(self):
        lock = SharedLock("t2", server=True)
        try:
            with lock:
                assert lock.locked()
            assert not lock.locked()
        finally:
            lock.close()


class TestSharedQueue:
    def test_fifo(self):
        q = SharedQueue("q1", server=True)
        try:
            q.put({"step": 1})
            q.put({"step": 2})
            assert q.qsize() == 2
            assert q.get()["step"] == 1
            assert q.get()["step"] == 2
            assert q.empty()
            with pytest.raises(queue.Empty):
                q.get(block=False)
        finally:
            q.close()

    def test_cross_client(self):
        q = SharedQueue("q2", server=True)
        client = SharedQueue("q2")
        try:
            client.put([1, 2, 3])
            assert q.get() == [1, 2, 3]
        finally:
            client.close()
            q.close()


class TestSharedDict:
    def test_ops(self):
        d = SharedDict("d1", server=True)
        client = SharedDict("d1")
        try:
            client.set("a", 1)
            d.update({"b": [2], "c": "x"})
            assert d.get("a") == 1
            assert client.get("b") == [2]
            assert client.get("missing", 9) == 9
            assert set(d.all()) == {"a", "b", "c"}
            assert d.pop("a") == 1
            assert d.get("a") is None
        finally:
            client.close()
            d.close()


def _subprocess_writer(qname):
    q = SharedQueue(qname)
    q.put({"from": "child"})
    q.close()


class TestCrossProcess:
    def test_queue_across_processes(self):
        q = SharedQueue("qx", server=True)
        try:
            ctx = mp.get_context("spawn")
            p = ctx.Process(target=_subprocess_writer, args=("qx",))
            p.start()
            item = q.get(timeout=30)
            p.join(timeout=30)
            assert item == {"from": "child"}
        finally:
            q.close()


class TestSharedMemory:
    def test_create_attach_unlink(self):
        shm = SharedMemoryHandle("seg1", create=True, size=1024)
        try:
            shm.buf[:4] = b"abcd"
            reader = SharedMemoryHandle("seg1")
            assert bytes(reader.buf[:4]) == b"abcd"
            reader.close()
            # Re-create with smaller size re-attaches the same segment.
            again = SharedMemoryHandle("seg1", create=True, size=512)
            assert bytes(again.buf[:4]) == b"abcd"
            again.close()
        finally:
            shm.unlink()
            shm.close()

    def test_exists(self):
        assert not SharedMemoryHandle.exists("nope")
        shm = SharedMemoryHandle("seg2", create=True, size=64)
        try:
            assert SharedMemoryHandle.exists("seg2")
        finally:
            shm.unlink()
            shm.close()
