"""Regression tests for the perf measurement tooling: the sweep-spec
grammar and the unattended capture chain's winner selection/pinning
(tools/perf_sweep.py, tools/capture_perf.py).

Every case here is a bug class the round-5 reviews actually caught:
step-ms ranking that lets a smaller batch beat a higher-throughput
config, global-vs-per-chip batch unit confusion, env vars silently
overriding explicit spec tokens, and NaN traces winning best-of
selection in the AGD study.
"""

import importlib
import json
import os
import sys

import pytest

TOOLS = os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

perf_sweep = importlib.import_module("perf_sweep")
capture_perf = importlib.import_module("capture_perf")


class TestBuildSpec:
    def test_positional_and_flag_tokens(self):
        cfg, attn_fn, batch, sl, xc = perf_sweep.build_spec(
            "sattn,flash,20,1024,512,-,nofn,u4,xc2"
        )
        assert cfg.remat == "save_attn"
        assert cfg.scan_unroll == 4
        assert cfg.use_fused_norm is False
        assert batch == 20
        assert sl is False
        assert xc == 2

    def test_flag_tokens_position_independent(self):
        a = perf_sweep.build_spec("full,flash,18,1024,1024,-,nofn,u2")
        b = perf_sweep.build_spec("full,flash,18,u2,1024,1024,-,nofn")
        assert a[0].scan_unroll == b[0].scan_unroll == 2
        assert a[2] == b[2] == 18

    def test_explicit_xc8_beats_env(self, monkeypatch):
        """xc8 must mean 8 even when SWEEP_XENT_CHUNKS says otherwise
        — the printed result line is labeled with the spec, so the
        measured program must match it."""
        monkeypatch.setenv("SWEEP_XENT_CHUNKS", "4")
        assert perf_sweep.build_spec("full,flash,18,-,-,-,xc8")[4] == 8
        # absent token -> env fallback applies
        assert perf_sweep.build_spec("full,flash,18")[4] == 4

    def test_remat_token_table(self):
        for tok, name in (
            ("full", True), ("none", False), ("attn", "attention"),
            ("sattn", "save_attn"), ("dots", "dots"),
            ("offload", "offload"),
        ):
            assert perf_sweep.build_spec(f"{tok},flash,18")[0].remat == name


class TestParseAutotune:
    OUT = (
        "n_devices: 1\n"
        "full,flash,18,1024,1024,-,nofn      step=  166.0ms "
        "tok/s=   111037 mfu=0.458 vs=0.924\n"
        "sattn,flash,16,1024,1024,-,nofn,u4,xc4 step=  140.1ms "
        "tok/s=   109900 mfu=0.470 vs=0.950\n"
        "sattn,flash,20,1024,1024,-,nofn,u4,xc4 step=  172.0ms "
        "tok/s=   119069 mfu=0.480 vs=0.960\n"
        "bogus,flash,18 FAILED: ValueError: nope\n"
    )

    def test_ranks_by_tokens_per_second_not_step_ms(self):
        spec, tok_s = capture_perf.parse_autotune(self.OUT)
        # b16 has the best step time; b20 has the best throughput —
        # throughput is what bench.py reports, so b20 must win.
        assert spec.startswith("sattn,flash,20")
        assert tok_s == 119069.0

    def test_failed_lines_skipped_and_empty_is_none(self):
        assert capture_perf.parse_autotune("x FAILED: boom") is None
        assert capture_perf.parse_autotune("") is None


class TestWinnerEnv:
    def test_full_pin_set(self):
        env = capture_perf.winner_env(
            "sattn,flash,20,1024,1024,-,nofn,u4,xc4", n_chips=1
        )
        assert env == {
            "BENCH_BLOCKS": "1024,1024,1024,1024",
            "BENCH_BATCH_PER_CHIP": "20",
            "BENCH_FUSED_NORM": "0",
            "BENCH_UNROLL": "4",
            "BENCH_XENT_CHUNKS": "4",
            "BENCH_REMAT": "save_attn",
        }

    def test_batch_is_global_converted_per_chip(self):
        """Sweep batch is global across its mesh; bench.py's knob is
        per-chip. A 2-chip sweep at global 40 must pin 20/chip."""
        env = capture_perf.winner_env(
            "sattn,flash,40,1024,1024,-,nofn", n_chips=2
        )
        assert env["BENCH_BATCH_PER_CHIP"] == "20"

    def test_default_batch_not_pinned(self):
        env = capture_perf.winner_env("full,flash,18,512,1024,-,nofn")
        assert "BENCH_BATCH_PER_CHIP" not in env

    def test_attn_token_maps_to_policy_name(self):
        env = capture_perf.winner_env("attn,flash,18,512,1024,-,nofn")
        assert env["BENCH_REMAT"] == "attention"


class TestPersistWinner:
    def test_pins_only_beyond_noise_and_atomically(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.setattr(capture_perf, "REPO", str(tmp_path))
        perf = tmp_path / "PERF_r05.json"
        perf.write_text(json.dumps(
            [{"stage": "baseline", "value": 100000.0}]
        ))
        pins = {"BENCH_UNROLL": "4"}
        # within noise: no file
        capture_perf.persist_winner(pins, {"value": 100300.0}, "s")
        assert not (tmp_path / "bench_tuned.json").exists()
        # beyond noise: pinned, valid JSON, no tmp litter
        capture_perf.persist_winner(pins, {"value": 101000.0}, "s")
        data = json.loads((tmp_path / "bench_tuned.json").read_text())
        assert data["pins"] == pins
        assert not (tmp_path / "bench_tuned.json.tmp").exists()

    def test_no_baseline_no_pin(self, tmp_path, monkeypatch):
        monkeypatch.setattr(capture_perf, "REPO", str(tmp_path))
        capture_perf.persist_winner({}, {"value": 1.0}, "s")
        assert not (tmp_path / "bench_tuned.json").exists()


class TestTuneCacheConsult:
    """capture_perf consults the persistent trial cache before
    spending the autotune sweep — and stays jax-free doing it."""

    def test_cached_pins_best_trial_wins(self, tmp_path, monkeypatch):
        monkeypatch.setenv(
            "DLROVER_TPU_TUNE_CACHE", str(tmp_path / "tc.jsonl")
        )
        tc = capture_perf._load_tune_cache_mod()
        cache = tc.resolve()
        cache.record("k1", {"pins": {"BENCH_UNROLL": 4}}, 100.0)
        cache.record("k1", {"pins": {"BENCH_UNROLL": 2}}, 120.0)
        cache.record("k1", {"pins": {"BENCH_UNROLL": 8}}, None,
                     failed=True)
        assert capture_perf.cached_pins("k1") == {"BENCH_UNROLL": "2"}
        assert capture_perf.cached_pins("other") is None
        assert capture_perf.cached_pins(None) is None

    def test_no_cache_hatch_and_empty_pins(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_TUNE_CACHE", "0")
        assert capture_perf.cached_pins("k1") is None
        # a best trial with no pins (shipped defaults) is not a hit
        monkeypatch.setenv(
            "DLROVER_TPU_TUNE_CACHE", str(tmp_path / "tc2.jsonl")
        )
        tc = capture_perf._load_tune_cache_mod()
        tc.resolve().record("k1", {"pins": {}}, 50.0)
        assert capture_perf.cached_pins("k1") is None

    def test_last_recorded_tune_key_newest_wins(
        self, tmp_path, monkeypatch
    ):
        ledger = tmp_path / "ledger.jsonl"
        ledger.write_text(
            json.dumps({"metric": "m", "tune_key": "old"}) + "\n"
            + "corrupt{\n"
            + json.dumps({"metric": "m"}) + "\n"
            + json.dumps({"metric": "m", "tune_key": "new"}) + "\n"
        )
        monkeypatch.setenv("DLROVER_TPU_BENCH_LEDGER", str(ledger))
        assert capture_perf.last_recorded_tune_key() == "new"
        monkeypatch.setenv(
            "DLROVER_TPU_BENCH_LEDGER", str(tmp_path / "absent")
        )
        assert capture_perf.last_recorded_tune_key() is None

    def test_last_recorded_tune_key_prefers_tpu_baseline(
        self, tmp_path, monkeypatch
    ):
        """An ad-hoc CPU smoke bench appending the newest record must
        not hand the TPU chain its key (the chain would skip the sweep
        on pins tuned for another backend/model); CAPTURE_TUNE_KEY
        pins the choice outright."""
        ledger = tmp_path / "ledger.jsonl"
        ledger.write_text(
            json.dumps({"metric": "m", "tune_key": "tpu-base",
                        "stage": "baseline", "backend": "axon"}) + "\n"
            + json.dumps({"metric": "m", "tune_key": "cpu-base",
                          "stage": "baseline", "backend": "cpu"}) + "\n"
            + json.dumps({"metric": "m", "tune_key": "adhoc"}) + "\n"
        )
        monkeypatch.setenv("DLROVER_TPU_BENCH_LEDGER", str(ledger))
        # newest overall is "adhoc", newest baseline is the cpu smoke
        # — the TPU baseline wins both tiebreaks
        assert capture_perf.last_recorded_tune_key() == "tpu-base"
        monkeypatch.setenv("CAPTURE_TUNE_KEY", "pinned")
        assert capture_perf.last_recorded_tune_key() == "pinned"

    def test_parent_stays_jax_free(self, tmp_path):
        """Loading + consulting the tune cache must not pull jax into
        the capture parent (a wedged tunnel could then hang it).
        Subprocess: this test process itself imports jax via
        conftest."""
        import subprocess

        src = (
            "import os, sys\n"
            f"os.environ['DLROVER_TPU_TUNE_CACHE'] = {str(tmp_path / 'tc.jsonl')!r}\n"
            f"sys.path.insert(0, {TOOLS!r})\n"
            "import capture_perf\n"
            "tc = capture_perf._load_tune_cache_mod()\n"
            "tc.resolve().record('k', {'pins': {'A': 1}}, 1.0)\n"
            "assert capture_perf.cached_pins('k') == {'A': '1'}\n"
            "assert 'jax' not in sys.modules, 'jax leaked into parent'\n"
        )
        subprocess.run(
            [sys.executable, "-c", src], check=True, timeout=60
        )


class TestLedgerPinDiff:
    def test_compare_prints_pin_diff_on_config_mismatch(
        self, tmp_path, monkeypatch
    ):
        bench_ledger = importlib.import_module("bench_ledger")
        path = str(tmp_path / "ledger.jsonl")
        base = {
            "metric": "m", "value": 100.0, "unit": "u",
            "config_hash": "aaa",
            "pins": {"BENCH_UNROLL": "1", "BENCH_REMAT": "full"},
        }
        head = {
            "metric": "m", "value": 100.0, "unit": "u",
            "config_hash": "bbb",
            "pins": {"BENCH_UNROLL": "4", "BENCH_REMAT": "full",
                     "BENCH_OVERLAP_REDUCE": "1"},
        }
        bench_ledger.append_record(base, path=path)
        bench_ledger.append_record(head, path=path)
        rc, report = bench_ledger.compare("last", path=path)
        assert rc == 0
        assert "pin BENCH_UNROLL: head=4 baseline=1" in report
        assert (
            "pin BENCH_OVERLAP_REDUCE: head=1 baseline=<unset>"
            in report
        )
        assert "BENCH_REMAT" not in report  # unchanged pins silent

    def test_compare_prints_device_prefetch_and_pipeline_pins(
        self, tmp_path
    ):
        """The device-resident input-pipeline knobs ride the same
        pin-diff surface: a tuned run pinning BENCH_DEVICE_PREFETCH /
        BENCH_PIPELINE_DEPTH against a baseline without them must
        name both in the compare output."""
        bench_ledger = importlib.import_module("bench_ledger")
        path = str(tmp_path / "ledger.jsonl")
        base = {
            "metric": "m", "value": 100.0, "unit": "u",
            "config_hash": "aaa", "pins": {},
        }
        head = {
            "metric": "m", "value": 110.0, "unit": "u",
            "config_hash": "bbb",
            "pins": {
                "BENCH_DEVICE_PREFETCH": "1",
                "BENCH_PIPELINE_DEPTH": "2",
                "BENCH_ACCUM_STEPS": "2",
            },
        }
        bench_ledger.append_record(base, path=path)
        bench_ledger.append_record(head, path=path)
        rc, report = bench_ledger.compare("last", path=path)
        assert rc == 0
        assert (
            "pin BENCH_DEVICE_PREFETCH: head=1 baseline=<unset>"
            in report
        )
        assert (
            "pin BENCH_PIPELINE_DEPTH: head=2 baseline=<unset>"
            in report
        )
        assert (
            "pin BENCH_ACCUM_STEPS: head=2 baseline=<unset>"
            in report
        )

    def test_compare_same_config_no_pin_section(
        self, tmp_path
    ):
        bench_ledger = importlib.import_module("bench_ledger")
        path = str(tmp_path / "ledger.jsonl")
        rec = {
            "metric": "m", "value": 100.0, "unit": "u",
            "config_hash": "aaa", "pins": {"BENCH_UNROLL": "1"},
        }
        bench_ledger.append_record(dict(rec), path=path)
        bench_ledger.append_record(dict(rec), path=path)
        rc, report = bench_ledger.compare("last", path=path)
        assert rc == 0 and "pin " not in report


class TestBenchPinsEmission:
    def test_smoke_child_emits_pins_overlap_and_records_trial(
        self, tmp_path
    ):
        """bench.py's measurement child (BENCH_SMOKE tiny model, CPU)
        must emit the applied pins, the overlap config, and the
        tune-cache key in its JSON record — the fields the ledger
        carries so compare mismatches are debuggable — and record the
        run as a cached trial."""
        import subprocess

        repo = os.path.dirname(TOOLS)
        cache = tmp_path / "tc.jsonl"
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "BENCH_SMOKE": "1",
            "BENCH_STEPS": "2",
            "BENCH_NO_LEDGER": "1",
            "BENCH_OVERLAP_REDUCE": "1",
            "BENCH_REDUCE_BUCKET_MB": "1",
            "DLROVER_TPU_TUNE_CACHE": str(cache),
        }
        p = subprocess.run(
            [sys.executable, os.path.join(repo, "bench.py"),
             "--child"],
            env=env, capture_output=True, text=True, timeout=300,
            cwd=repo,
        )
        assert p.returncode == 0, p.stderr[-2000:]
        rec = next(
            json.loads(line)
            for line in p.stdout.splitlines()
            if line.startswith("{")
        )
        assert rec["pins"]["BENCH_OVERLAP_REDUCE"] == "1"
        assert rec["overlap"] == {"bucket_mb": 1.0, "bits": None}
        assert rec["tune_key"]
        assert rec["value"] > 0
        trials = [
            json.loads(line) for line in cache.read_text().splitlines()
        ]
        assert len(trials) == 1
        assert trials[0]["key"] == rec["tune_key"]
        assert trials[0]["config"]["pins"] == rec["pins"]
        assert not trials[0]["failed"]


class TestBenchPipelinedSmoke:
    def test_smoke_child_pipelined_device_prefetch_record(
        self, tmp_path
    ):
        """The device-resident configuration end-to-end through
        bench.py's child: prefetch + worker-side H2D + pipelined
        accumulation. The record must carry the pipeline config, the
        new pins, and a data_wait_s figure (the attributable input
        wait)."""
        import subprocess

        repo = os.path.dirname(TOOLS)
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "BENCH_SMOKE": "1",
            "BENCH_STEPS": "2",
            "BENCH_NO_LEDGER": "1",
            "BENCH_PREFETCH": "1",
            "BENCH_DEVICE_PREFETCH": "1",
            "BENCH_PIPELINE_DEPTH": "1",
            "BENCH_ACCUM_STEPS": "2",
            "DLROVER_TPU_TUNE_CACHE": "0",
        }
        p = subprocess.run(
            [sys.executable, os.path.join(repo, "bench.py"),
             "--child"],
            env=env, capture_output=True, text=True, timeout=300,
            cwd=repo,
        )
        assert p.returncode == 0, p.stderr[-2000:]
        rec = next(
            json.loads(line)
            for line in p.stdout.splitlines()
            if line.startswith("{")
        )
        assert rec["value"] > 0
        assert rec["pins"]["BENCH_PIPELINE_DEPTH"] == "1"
        assert rec["pins"]["BENCH_DEVICE_PREFETCH"] == "1"
        assert rec["pipeline"] == {
            "depth": 1, "accum_steps": 2, "device_prefetch": 1,
        }
        assert "data_wait_s" in rec


class TestAGDTraceSelection:
    def test_nan_trace_never_wins(self):
        """agd_convergence's best-trace guard: a diverged (NaN) final
        loss must not beat a finite one via NaN-compare semantics."""
        agd = importlib.import_module("agd_convergence")
        runs = {
            3e-5: [(5, 10.0), (10, float("nan"))],
            6e-5: [(5, 10.5), (10, 9.8)],
        }
        lr, tr = agd.best_finite_trace(runs)
        assert lr == 6e-5 and tr[-1][1] == 9.8

    def test_all_diverged_still_returns(self):
        agd = importlib.import_module("agd_convergence")
        runs = {1e-3: [(5, float("nan"))]}
        assert agd.best_finite_trace(runs)[0] == 1e-3
