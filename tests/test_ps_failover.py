"""Trainer-level PS failover: an abrupt PS death mid-run is survived
by the training loop with no orderly handoff.

Parity target: the reference's version-checked PS failover in the
estimator executor (trainer/tensorflow/failover/
tensorflow_failover.py:33, executor/estimator_executor.py:52) — here
the failure detection is the PsManager liveness monitor, the blocking
is the sparse client's stale-map retry, and the recovery is a
partition rebalance restored from the last delta flush.
"""

import threading
import time

import numpy as np
import pytest

from dlrover_tpu.master.ps_manager import PsManager
from dlrover_tpu.sparse.ps_client import DistributedKvClient
from dlrover_tpu.sparse.ps_server import PsServer

DIMS = {"emb": 4}


def _start_ps(node_id, tmp_path, num_partitions=8):
    ps = PsServer(
        node_id=node_id,
        checkpoint_dir=str(tmp_path / "sparse_ckpt"),
        embedding_dims=DIMS,
        num_partitions=num_partitions,
        seed=node_id * 100,
    )
    ps.start()
    return ps


@pytest.fixture()
def cluster(tmp_path):
    mgr = PsManager(num_partitions=8)
    servers = {}
    for i in (0, 1):
        ps = _start_ps(i, tmp_path)
        servers[i] = ps
        mgr.register_ps(i, ps.addr)
    yield mgr, servers
    mgr.stop_liveness_monitor()
    for ps in servers.values():
        ps.stop()


def _client(mgr):
    return DistributedKvClient(
        lambda: mgr.partition_map, DIMS, retry_interval=0.05
    )


KEYS = np.arange(512, dtype=np.int64)


class TestLivenessMonitor:
    def test_check_liveness_fails_over_dead_ps(self, cluster):
        mgr, servers = cluster
        client = _client(mgr)
        client.lookup("emb", KEYS)  # materialize rows
        mgr.flush_all(step=1)
        v_before = mgr.partition_map.version

        victim = servers.pop(1)
        victim.stop()  # abrupt: no flush, no remove_ps
        # Strike accumulation: below the threshold nothing happens.
        assert mgr.check_liveness(failure_threshold=2) == []
        assert 1 in mgr.partition_map.ps_addrs
        # Second strike crosses the threshold: failover runs.
        assert mgr.check_liveness(failure_threshold=2) == [1]
        pmap = mgr.partition_map
        assert 1 not in pmap.ps_addrs
        assert pmap.version > v_before
        # All partitions now live on the survivor, restored from the
        # flush — every pre-kill row is readable.
        vals = client.lookup("emb", KEYS, train=False)
        assert np.isfinite(vals).all()
        client.close()

    def test_healthy_ps_resets_strikes(self, cluster):
        mgr, servers = cluster
        servers[1].stop()
        assert mgr.check_liveness(failure_threshold=3) == []
        # Node 1 comes back (restart in place) before the third strike.
        servers[1] = ps = PsServer(
            node_id=1,
            checkpoint_dir=servers[0].checkpoint_dir,
            embedding_dims=DIMS,
            num_partitions=8,
            seed=100,
        )
        ps.start()
        mgr.register_ps(1, ps.addr)
        assert mgr.check_liveness(failure_threshold=3) == []
        assert mgr.check_liveness(failure_threshold=3) == []
        assert 1 in mgr.partition_map.ps_addrs


class TestTrainingLoopSurvivesAbruptKill:
    def test_blocked_sparse_op_resumes_after_failover(self, cluster):
        """The trainer-level contract: the training loop's sparse op
        blocks on the dead PS (stale-map retries) and resumes once the
        liveness monitor rebalances — no exception reaches the loop."""
        mgr, servers = cluster
        client = _client(mgr)
        client.lookup("emb", KEYS)
        mgr.flush_all(step=1)

        victim = servers.pop(1)
        victim.stop()

        # Fail over ~0.3s from now, while the lookup below is already
        # blocking in its retry loop.
        def failover():
            time.sleep(0.3)
            mgr.check_liveness(failure_threshold=1)

        t = threading.Thread(target=failover)
        t.start()
        start = time.time()
        vals = client.lookup("emb", KEYS, train=False)
        elapsed = time.time() - start
        t.join()
        assert vals.shape == (KEYS.size, DIMS["emb"])
        assert np.isfinite(vals).all()
        # It actually blocked across the failover rather than failing.
        assert elapsed >= 0.25
        # And gradient application works against the new map too.
        client.apply_gradients(
            "emb",
            KEYS,
            np.zeros((KEYS.size, DIMS["emb"]), np.float32),
            step=2,
            optimizer="adagrad",
            lr=0.1,
        )
        client.close()

    def test_monitor_thread_end_to_end(self, cluster):
        mgr, servers = cluster
        client = _client(mgr)
        client.lookup("emb", KEYS)
        mgr.flush_all(step=1)
        mgr.start_liveness_monitor(
            interval=0.1, failure_threshold=2, ping_timeout=2.0
        )
        victim = servers.pop(1)
        victim.stop()
        vals = client.lookup("emb", KEYS, train=False)  # blocks+resumes
        assert np.isfinite(vals).all()
        assert 1 not in mgr.partition_map.ps_addrs
        client.close()


class TestMasterWiring:
    def test_embedding_node_death_triggers_ps_failover(
        self, cluster, tmp_path
    ):
        """The master's node-event path: a dead EMBEDDING node
        (heartbeat timeout / cluster event) must fail its PS over
        without any drill-side help."""
        from dlrover_tpu.common.constants import NodeType, ps_node_id
        from dlrover_tpu.master.master import JobMaster

        mgr, servers = cluster
        master = JobMaster(node_num=1)
        master.ps_manager = mgr  # wire the live manager in
        master.job_manager.register_node(
            node_type=NodeType.EMBEDDING, node_id=ps_node_id(1)
        )
        master.job_manager.handle_node_gone(
            ps_node_id(1), reason="pod deleted"
        )
        # handle_node_gone relaunches within budget; the DELETED event
        # still fires and must remove the PS from the partition map.
        assert 1 not in mgr.partition_map.ps_addrs


class TestSparseTrainer:
    def test_high_level_loop_trains_and_survives_ps_kill(
        self, cluster
    ):
        """D21 closure: the high-level PS training loop
        (trainer/sparse_trainer.py) trains the dense+sparse split and
        survives an abrupt PS death mid-run without the loop seeing
        the failure."""
        import jax
        import jax.numpy as jnp
        import optax

        from dlrover_tpu.trainer.sparse_trainer import (
            SparseTrainer,
            make_ctr_loss_and_grads,
        )

        mgr, servers = cluster
        client = _client(mgr)
        dim = DIMS["emb"]

        def loss_fn(dense, emb, labels):
            logits = (emb @ dense["w"]).reshape(-1, 8).sum(-1)
            return jnp.mean(
                optax.sigmoid_binary_cross_entropy(logits, labels)
            )

        trainer = SparseTrainer(
            client,
            make_ctr_loss_and_grads(loss_fn),
            optax.adam(1e-2),
            {"w": jnp.ones((dim,)) * 0.1},
            table="emb",
            embedding_dim=dim,
            sparse_optimizer="adagrad",
            sparse_lr=0.3,
            flush_manager=mgr,
            flush_every=5,
        )
        rng = np.random.default_rng(0)

        def batch():
            keys = rng.integers(0, 256, size=(16, 8)).astype("int64")
            labels = (keys.sum(1) % 2).astype("float32")
            return keys.ravel(), jnp.asarray(labels)

        losses = [trainer.train_step(*batch()) for _ in range(12)]
        assert trainer.last_flush_rows > 0  # periodic flush ran

        # Abrupt PS death; fail over concurrently while the next
        # train_step blocks in its sparse ops.
        victim = servers.pop(1)
        victim.stop()

        def failover():
            time.sleep(0.3)
            mgr.check_liveness(failure_threshold=1)

        t = threading.Thread(target=failover)
        t.start()
        post = [trainer.train_step(*batch()) for _ in range(8)]
        t.join()
        assert all(np.isfinite(post))
        assert trainer.step_num == 20
        # dense state round-trips for flash checkpoints
        state = trainer.state_dict()
        trainer.load_state_dict(state)
        assert trainer.step_num == 20
        client.close()


class TestNoDuplicateApplyOnRetry:
    """A retried fan-out round must not re-send shards that already
    committed: apply_gradients is not idempotent, so a mid-round PS
    death followed by a map refresh has to replay ONLY the failed
    keys (advisor finding, round 3)."""

    def _map(self, assignment, addrs, version=1):
        from dlrover_tpu.sparse.partition import PartitionMap

        return PartitionMap(
            version=version, assignment=assignment, ps_addrs=addrs
        )

    def test_committed_shard_not_resent_within_same_map(self):
        pmap = self._map(
            [0, 1] * 4, {0: "a:1", 1: "b:1"}
        )
        client = DistributedKvClient(
            lambda: pmap, DIMS, retry_interval=0.01
        )
        calls = []
        fail_once = {"b:1": True}

        def call(addr, version, sub_keys, idx):
            calls.append((addr, np.sort(sub_keys).tolist()))
            if fail_once.get(addr):
                fail_once[addr] = False
                raise RuntimeError("mid-round shard failure")

        keys = np.arange(64, dtype=np.int64)
        client._fan_out(keys, call)

        a_calls = [c for c in calls if c[0] == "a:1"]
        b_calls = [c for c in calls if c[0] == "b:1"]
        assert len(a_calls) == 1, "committed shard was re-sent"
        assert len(b_calls) == 2  # failed once, then replayed
        assert b_calls[0][1] == b_calls[1][1]
        # Every key applied exactly once across successful calls.
        applied = sorted(a_calls[0][1] + b_calls[1][1])
        assert applied == keys.tolist()
        client.close()

    def test_failover_replays_only_pending_keys_on_new_map(self):
        maps = {
            "cur": self._map([0, 1] * 4, {0: "a:1", 1: "b:1"}),
        }
        client = DistributedKvClient(
            lambda: maps["cur"], DIMS, retry_interval=0.01
        )
        calls = []

        def call(addr, version, sub_keys, idx):
            calls.append((addr, version, np.sort(sub_keys).tolist()))
            if addr == "b:1":
                # PS 1 is dead; next refresh reveals the rebalanced
                # map with everything on PS 0.
                maps["cur"] = self._map(
                    [0] * 8, {0: "a:1"}, version=2
                )
                raise RuntimeError("connection refused")

        keys = np.arange(64, dtype=np.int64)
        client._fan_out(keys, call)

        a_calls = [c for c in calls if c[0] == "a:1"]
        assert len(a_calls) == 2
        first_keys, replayed = a_calls[0][2], a_calls[1][2]
        # The replay under map v2 carries ONLY the dead shard's keys.
        assert a_calls[1][1] == 2
        assert not set(first_keys) & set(replayed)
        assert sorted(first_keys + replayed) == keys.tolist()
        client.close()
