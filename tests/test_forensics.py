"""Crash & hang forensics: flight recorder, postmortem, diagnostics.

Crash capture runs in REAL subprocesses (an excepthook or a C-level
faulthandler dump can only be proven by actually dying); the agent's
hang-forensics assembly and the master's diagnostics channel are
exercised in-process. Everything is hermetic — no JAX distributed, no
cluster.
"""

import collections
import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from dlrover_tpu import obs
from dlrover_tpu.agent.agent import AgentConfig, ElasticAgent
from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.constants import EventAction
from dlrover_tpu.master.servicer import (
    DIAGNOSTICS_HISTORY,
    MAX_PENDING_ACTIONS,
)
from dlrover_tpu.obs import flight_recorder as fr
from dlrover_tpu.obs.postmortem import (
    collect_events,
    last_fault_dump,
    load_bundles,
    load_stack_dumps,
    render_postmortem,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OBS_REPORT = os.path.join(REPO, "tools", "obs_report.py")


def _run_child(code: str, forensics_dir: str, timeout: float = 60.0):
    env = dict(os.environ)
    env["DLROVER_TPU_FORENSICS_DIR"] = forensics_dir
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-c", code],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


class TestCrashBundles:
    def test_unhandled_exception_writes_parseable_bundle(self, tmp_path):
        d = str(tmp_path)
        result = _run_child(
            "from dlrover_tpu import obs\n"
            "obs.install_flight_recorder('trainer', rank=3)\n"
            "obs.recorder_note(step=17, loss=0.5)\n"
            "def explode():\n"
            "    raise RuntimeError('forensics boom')\n"
            "explode()\n",
            d,
        )
        assert result.returncode != 0
        # The original traceback still reaches stderr (chained hook).
        assert "forensics boom" in result.stderr
        bundles = load_bundles(d)
        assert len(bundles) == 1
        bundle = bundles[0]
        assert bundle["kind"] == "exception"
        assert "RuntimeError" in bundle["reason"]
        assert bundle["role"] == "trainer" and bundle["rank"] == 3
        assert bundle["notes"] == {"step": 17, "loss": 0.5}
        assert "explode" in bundle["traceback"]
        # Non-empty all-thread Python stacks.
        assert bundle["stacks"]
        assert any(s["frames"] for s in bundle["stacks"])
        assert any(s["thread"] == "MainThread" for s in bundle["stacks"])
        # --postmortem renders it.
        report = render_postmortem(d)
        assert "forensics boom" in report
        assert "notes: loss=0.5, step=17" in report

    def test_fatal_signal_dumps_stacks_via_faulthandler(self, tmp_path):
        d = str(tmp_path)
        result = _run_child(
            "import faulthandler\n"
            "from dlrover_tpu import obs\n"
            "obs.install_flight_recorder('trainer', rank=0)\n"
            "def die_hard():\n"
            "    faulthandler._sigsegv()\n"
            "die_hard()\n",
            d,
        )
        assert result.returncode == -signal.SIGSEGV
        dumps = load_stack_dumps(d)
        assert len(dumps) == 1
        last = dumps[0]["last_dump"]
        assert "Fatal Python error" in last
        assert "die_hard" in last
        report = render_postmortem(d)
        assert "die_hard" in report
        assert "Fatal Python error" in report

    def test_bundle_retention_bounded(self, tmp_path):
        d = str(tmp_path)
        rec = fr.FlightRecorder("agent", rank=0, dir_=d, keep=3)
        for i in range(7):
            assert rec.dump("manual", reason=f"n{i}") is not None
        assert len(load_bundles(d)) == 3
        # Oldest gone, newest kept.
        reasons = {b["reason"] for b in load_bundles(d)}
        assert reasons == {"n4", "n5", "n6"}


class _HungChild:
    """Context manager: a subprocess with an installed flight
    recorder, wedged inside ``stuck_collective`` (the single-process
    hang drill)."""

    CODE = (
        "import os, time\n"
        "from dlrover_tpu import obs\n"
        "obs.install_flight_recorder('trainer', rank=0)\n"
        "open(os.environ['READY_FILE'], 'w').write('1')\n"
        "def stuck_collective():\n"
        "    time.sleep(120)\n"
        "stuck_collective()\n"
    )

    def __init__(self, forensics_dir: str):
        self.dir = forensics_dir
        self.proc = None

    def __enter__(self):
        ready = os.path.join(self.dir, "ready")
        env = dict(os.environ)
        env["DLROVER_TPU_FORENSICS_DIR"] = self.dir
        env["READY_FILE"] = ready
        env["PYTHONPATH"] = (
            REPO + os.pathsep + env.get("PYTHONPATH", "")
        )
        self.proc = subprocess.Popen(
            [sys.executable, "-c", self.CODE], env=env
        )
        deadline = time.monotonic() + 30
        while not os.path.exists(ready):
            assert self.proc.poll() is None, "hung child died early"
            assert time.monotonic() < deadline, "child never ready"
            time.sleep(0.05)
        return self.proc

    def __exit__(self, *exc):
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()


class TestHangForensics:
    """Acceptance: an induced hang produces a forensics bundle with
    the hung thread's Python stack, and --postmortem renders it."""

    def test_agent_collects_hung_trainer_stack(
        self, tmp_path, monkeypatch
    ):
        d = str(tmp_path)
        monkeypatch.setenv("DLROVER_TPU_FORENSICS_DIR", d)
        agent = ElasticAgent(
            AgentConfig(node_id=0), ["true"], client=object()
        )
        try:
            fr.install_flight_recorder("agent", rank=0, dir_=d)
            with _HungChild(d) as proc:
                agent._proc = proc
                digest, bundle_path = agent._collect_forensics(
                    "hang", hang_seconds=5.0, last_step=9
                )
            # Digest carries the hung thread's stack and the bundle
            # pointer — exactly what the failure report attaches.
            assert "stuck_collective" in digest
            assert digest.startswith(f"bundle: {bundle_path}")
            assert len(digest) <= 4096 + len(f"bundle: {bundle_path}\n")
            assert os.path.exists(bundle_path)
            bundle = json.load(open(bundle_path))
            assert bundle["kind"] == "hang"
            assert "stuck_collective" in bundle["trainer_stacks"]
            assert bundle["notes"]["last_step"] == 9
        finally:
            fr.uninstall_flight_recorder()
        # The postmortem CLI renders the hung stack from the dir.
        result = subprocess.run(
            [sys.executable, OBS_REPORT, "--postmortem", d],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert result.returncode == 0, result.stderr
        assert "stuck_collective" in result.stdout
        assert "[hang]" in result.stdout

    def test_never_signals_a_trainer_without_a_handler(
        self, tmp_path, monkeypatch
    ):
        """Default SIGUSR1 disposition KILLS the process: the agent
        must not signal a trainer whose recorder never registered the
        handler (disabled via env, still importing, or failed)."""
        d = str(tmp_path)
        monkeypatch.setenv("DLROVER_TPU_FORENSICS_DIR", d)
        proc = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(60)"]
        )
        try:
            agent = ElasticAgent(
                AgentConfig(node_id=0), ["true"], client=object()
            )
            agent._proc = proc
            assert not fr.sigusr1_ready(proc.pid)
            stacks = agent._snapshot_trainer_stacks(timeout=0.5)
            assert stacks == ""
            time.sleep(0.2)
            # The recorder-less child is still alive — not killed by
            # a blind SIGUSR1.
            assert proc.poll() is None
        finally:
            proc.kill()
            proc.wait()

    def test_incident_notes_do_not_stick_to_recorder(self, tmp_path):
        """A hang's facts (hang_seconds, last_step) must not replay
        in later diagnose/crash bundles from the same agent."""
        d = str(tmp_path)
        agent = ElasticAgent(
            AgentConfig(node_id=0), ["true"], client=object()
        )
        try:
            fr.install_flight_recorder("agent", rank=0, dir_=d)
            digest, bundle_path = agent._collect_forensics(
                "hang", hang_seconds=62.0, last_step=41
            )
            assert "last_step" in digest
            assert json.load(open(bundle_path))["notes"][
                "last_step"
            ] == 41
            digest2, bundle2 = agent._collect_forensics("diagnose")
            assert "last_step" not in digest2
            assert "last_step" not in json.load(open(bundle2))[
                "notes"
            ]
        finally:
            fr.uninstall_flight_recorder()

    def test_snapshot_of_dead_trainer_reads_crash_tail(self, tmp_path):
        d = str(tmp_path)
        result = _run_child(
            "import faulthandler\n"
            "from dlrover_tpu import obs\n"
            "obs.install_flight_recorder('trainer', rank=0)\n"
            "faulthandler._sigsegv()\n",
            d,
        )
        assert result.returncode == -signal.SIGSEGV

        class DeadProc:
            def __init__(self, pid):
                self.pid = pid

            def poll(self):
                return -11

        agent = ElasticAgent(
            AgentConfig(node_id=0), ["true"], client=object()
        )
        # The pid whose stacks file the crash left behind.
        agent._proc = DeadProc(load_stack_dumps(d)[0]["pid"])
        with pytest.MonkeyPatch.context() as mp:
            mp.setenv("DLROVER_TPU_FORENSICS_DIR", d)
            stacks = agent._snapshot_trainer_stacks()
        assert "Fatal Python error" in stacks


class TestDiagnosticsChannel:
    def _servicer(self):
        from dlrover_tpu.master.job_manager import JobManager
        from dlrover_tpu.master.rendezvous import (
            ElasticRendezvous,
            NetworkCheckRendezvous,
        )
        from dlrover_tpu.master.servicer import MasterServicer
        from dlrover_tpu.master.task_manager import TaskManager

        return MasterServicer(
            job_manager=JobManager(),
            task_manager=TaskManager(),
            elastic_rdzv=ElasticRendezvous(),
            check_rdzv=NetworkCheckRendezvous(),
        )

    def test_history_bounded_and_queryable(self):
        servicer = self._servicer()
        counter = obs.get_registry().get(
            "dlrover_forensics_bundles_total"
        )
        before = counter.value(node="5", kind="hang")
        for i in range(DIAGNOSTICS_HISTORY + 4):
            servicer._report_diagnostics(
                msg.DiagnosticsReport(
                    node_id=5,
                    kind="hang",
                    bundle_path=f"/f/b{i}.json",
                    digest=f"digest {i}",
                )
            )
        assert (
            counter.value(node="5", kind="hang")
            == before + DIAGNOSTICS_HISTORY + 4
        )
        resp = servicer._query_diagnostics(
            msg.DiagnosticsQueryRequest(node_id=5)
        )
        assert len(resp.reports) == DIAGNOSTICS_HISTORY
        # Bounded history keeps the NEWEST reports.
        assert resp.reports[-1].digest == (
            f"digest {DIAGNOSTICS_HISTORY + 3}"
        )
        assert all(r.timestamp > 0 for r in resp.reports)
        # node_id=-1 returns everything.
        servicer._report_diagnostics(
            msg.DiagnosticsReport(node_id=2, kind="diagnose")
        )
        all_resp = servicer._query_diagnostics(
            msg.DiagnosticsQueryRequest(node_id=-1)
        )
        assert len(all_resp.reports) == DIAGNOSTICS_HISTORY + 1

    def test_digest_storage_size_capped(self):
        servicer = self._servicer()
        servicer._report_diagnostics(
            msg.DiagnosticsReport(
                node_id=1, kind="crash", digest="x" * 100_000
            )
        )
        resp = servicer._query_diagnostics(
            msg.DiagnosticsQueryRequest(node_id=1)
        )
        assert len(resp.reports[0].digest) == 16384

    def test_pending_actions_fifo_drained_one_per_heartbeat(self):
        """Regression: push_action used to be last-write-wins — a
        restart_training queued before a diagnose silently vanished."""
        servicer = self._servicer()
        servicer.push_action(7, EventAction.RESTART_TRAINING.value)
        servicer.diagnose_node(7)
        # Idempotent dedupe: a second push of an already-queued
        # action collapses (two node deaths in one tick = one
        # restart per survivor), but never displaces a different one.
        servicer.push_action(7, EventAction.RESTART_TRAINING.value)
        assert servicer.pending_actions(7) == [
            "restart_training", "diagnose",
        ]
        beats = [
            servicer._heartbeat(msg.HeartbeatRequest(node_id=7)).action
            for _ in range(3)
        ]
        # FIFO order, one per heartbeat, then drained.
        assert beats == ["restart_training", "diagnose", "none"]
        assert servicer.pending_actions(7) == []
        # Other nodes see nothing.
        assert (
            servicer._heartbeat(
                msg.HeartbeatRequest(node_id=8)
            ).action
            == "none"
        )

    def test_replayed_push_with_dedupe_key_is_idempotent(self):
        """A replayed remediation push (RPC retry, engine re-fire)
        carrying the same dedupe key must be a no-op even AFTER the
        original action was delivered — the in-queue dedupe alone
        cannot stop a replayed restart_training from double-bouncing
        a trainer."""
        servicer = self._servicer()
        assert servicer.push_action(
            7, EventAction.RESTART_TRAINING.value, dedupe_key="rem:1"
        )
        # Replay while still queued: absorbed.
        assert not servicer.push_action(
            7, EventAction.RESTART_TRAINING.value, dedupe_key="rem:1"
        )
        assert (
            servicer._heartbeat(msg.HeartbeatRequest(node_id=7)).action
            == "restart_training"
        )
        # Replay AFTER delivery: the key was consumed — no second
        # bounce.
        assert not servicer.push_action(
            7, EventAction.RESTART_TRAINING.value, dedupe_key="rem:1"
        )
        assert (
            servicer._heartbeat(msg.HeartbeatRequest(node_id=7)).action
            == "none"
        )
        # A genuinely new decision (fresh key) still delivers, and
        # ordering with other actions stays FIFO.
        assert servicer.push_action(
            7, EventAction.RESTART_TRAINING.value, dedupe_key="rem:2"
        )
        servicer.diagnose_node(7)
        beats = [
            servicer._heartbeat(msg.HeartbeatRequest(node_id=7)).action
            for _ in range(3)
        ]
        assert beats == ["restart_training", "diagnose", "none"]
        # Keyless pushes keep the legacy semantics (in-queue dedupe
        # only).
        assert servicer.push_action(7, EventAction.PROFILE.value)
        assert servicer.push_action(7, EventAction.PROFILE.value) is False

    def test_pending_actions_bounded_drops_oldest(self):
        servicer = self._servicer()
        for i in range(MAX_PENDING_ACTIONS + 3):
            servicer.push_action(1, f"a{i}")
        queued = servicer.pending_actions(1)
        assert len(queued) == MAX_PENDING_ACTIONS
        assert queued[0] == "a3"  # oldest three dropped
        assert queued[-1] == f"a{MAX_PENDING_ACTIONS + 2}"

    def test_diagnostics_roundtrip_msgpack(self):
        report = msg.DiagnosticsReport(
            node_id=3, kind="hang", bundle_path="/p",
            digest="top frames", timestamp=12.5,
        )
        resp = msg.DiagnosticsQueryResponse(reports=[report])
        decoded = msg.deserialize(msg.serialize(resp))
        assert decoded.reports[0] == report


class TestStragglerDiagnose:
    def test_fresh_straggler_queues_diagnose_and_profile(self):
        """The SpeedMonitor's straggler verdict triggers a fleet
        `diagnose` AND a `profile` through the master wiring —
        delivered on the slow node's next heartbeats (one per beat)."""
        from dlrover_tpu.master.master import JobMaster

        master = JobMaster(port=0, node_num=3, rdzv_timeout=1.0)
        try:
            sm = master.speed_monitor
            assert sm.on_straggler is not None
            for node_id in range(3):
                for _ in range(3):
                    sm.observe_host_step_time(
                        node_id, 10.0 if node_id == 2 else 0.1
                    )
            assert sm.stragglers() == [2]
            assert servicer_actions(master, 2) == [
                "diagnose", "profile",
            ]
            # Re-scoring the same straggler does not re-queue.
            sm.observe_host_step_time(2, 10.0)
            assert servicer_actions(master, 2) == [
                "diagnose", "profile",
            ]
            beat = master.servicer._heartbeat(
                msg.HeartbeatRequest(node_id=2)
            )
            assert beat.action == EventAction.DIAGNOSE.value
            beat = master.servicer._heartbeat(
                msg.HeartbeatRequest(node_id=2)
            )
            assert beat.action == EventAction.PROFILE.value
        finally:
            master.stop()


def servicer_actions(master, node_id):
    return master.servicer.pending_actions(node_id)


class _DiagnoseClient:
    """Heartbeat returns a scripted action sequence; records
    diagnostics reports."""

    def __init__(self, actions):
        self.actions = collections.deque(actions)
        self.diagnostics = []

    def heartbeat(self):
        if not self.actions:
            return "none"
        action = self.actions.popleft()
        if isinstance(action, Exception):
            raise action
        return action

    def report_diagnostics(self, kind, bundle_path="", digest=""):
        self.diagnostics.append((kind, bundle_path, digest))


class TestAgentHeartbeat:
    def _run_loop(self, client, ticks: int):
        config = AgentConfig(node_id=0, heartbeat_interval=0.005)
        agent = ElasticAgent(config, ["true"], client=client)
        thread = threading.Thread(
            target=agent._heartbeat_loop, daemon=True
        )
        thread.start()
        deadline = time.monotonic() + 10
        while (
            len(client.actions) > 0 and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        agent._stop.set()
        thread.join(timeout=5)
        return agent

    def test_diagnose_action_ships_report(self):
        client = _DiagnoseClient(["none", "diagnose", "none"])
        self._run_loop(client, ticks=3)
        assert len(client.diagnostics) == 1
        kind, bundle_path, digest = client.diagnostics[0]
        assert kind == "diagnose"
        # No recorder installed in this process: digest still renders
        # (header only), bundle absent.
        assert "forensics digest (diagnose)" in digest

    def test_heartbeat_failures_counted_not_spammed(self, monkeypatch):
        failures = [RuntimeError("down")] * 20
        client = _DiagnoseClient(failures + ["none"])
        counter = obs.get_registry().get(
            "dlrover_agent_heartbeat_failures_total"
        )
        before = counter.value()
        warnings = []
        from dlrover_tpu.agent import agent as agent_mod

        real_warning = agent_mod.logger.warning
        monkeypatch.setattr(
            agent_mod.logger,
            "warning",
            lambda *a, **k: warnings.append(a),
        )
        infos = []
        monkeypatch.setattr(
            agent_mod.logger,
            "info",
            lambda *a, **k: infos.append(a),
        )
        del real_warning
        self._run_loop(client, ticks=21)
        assert counter.value() == before + 20
        # Escalating warn-once-per-streak: 1,2,4,8,16 -> 5 warnings
        # for 20 consecutive failures, not 20.
        assert 1 <= len(warnings) <= 6
        # Recovery logged once.
        assert any("recovered" in str(a[0]) for a in infos)


class TestHangDetectorClock:
    """Satellite regression: hang detection must measure ELAPSED time
    (monotonic), so an NTP wall-clock step can neither fake nor mask
    a hang."""

    def _detector(self, tmp_path, hang_timeout=50.0):
        from dlrover_tpu.agent.hang_detector import HangDetector
        from dlrover_tpu.agent.monitor import TrainingMonitor

        path = str(tmp_path / "metrics.json")
        TrainingMonitor.write_metrics(1, path=path)
        det = HangDetector(
            hang_timeout=hang_timeout,
            startup_grace=999.0,
            metrics_file=path,
        )
        assert det.check() is False  # step 1 lands as progress
        return det

    def test_wall_clock_jump_does_not_fake_hang(
        self, tmp_path, monkeypatch
    ):
        det = self._detector(tmp_path)
        real_time = time.time
        # NTP steps the wall clock forward a day: no step progressed,
        # but no *elapsed* time passed either.
        monkeypatch.setattr(
            time, "time", lambda: real_time() + 86400.0
        )
        assert det.check() is False
        assert det.seconds_since_progress() < 1.0

    def test_wall_clock_jump_back_does_not_mask_hang(
        self, tmp_path, monkeypatch
    ):
        det = self._detector(tmp_path)
        real_time = time.time
        real_mono = time.monotonic
        # Wall clock steps BACK a day while real (monotonic) time
        # exceeds the hang timeout: still a hang.
        monkeypatch.setattr(
            time, "time", lambda: real_time() - 86400.0
        )
        monkeypatch.setattr(
            time,
            "monotonic",
            lambda: real_mono() + det.hang_timeout + 1.0,
        )
        assert det.check() is True

    def test_progress_rearms_under_monotonic(self, tmp_path, monkeypatch):
        from dlrover_tpu.agent.monitor import TrainingMonitor

        det = self._detector(tmp_path)
        real_mono = time.monotonic
        monkeypatch.setattr(
            time,
            "monotonic",
            lambda: real_mono() + det.hang_timeout + 1.0,
        )
        assert det.check() is True
        # A new step re-arms even with the skewed clock.
        TrainingMonitor.write_metrics(2, path=det.metrics_file)
        assert det.check() is False


class TestPostmortemHelpers:
    def test_collect_events_merges_and_dedupes(self, tmp_path):
        d = str(tmp_path)
        bundle = {
            "kind": "hang",
            "ts": 100.0,
            "events": [
                {"name": "trainer.step", "ts": 99.0, "pid": 1},
                {"name": "trainer.step", "ts": 99.5, "pid": 1},
            ],
        }
        with open(os.path.join(d, "bundle_a_r0_1_001_hang.json"), "w") as f:
            json.dump(bundle, f)
        with open(os.path.join(d, "trace.jsonl"), "w") as f:
            # One duplicate of a bundle event + one new event.
            f.write(
                json.dumps(
                    {"name": "trainer.step", "ts": 99.5, "pid": 1}
                )
                + "\n"
            )
            f.write(
                json.dumps({"name": "node.fail", "ts": 100.0}) + "\n"
            )
        events = collect_events(d, load_bundles(d))
        assert [e["name"] for e in events] == [
            "trainer.step", "trainer.step", "node.fail",
        ]

    def test_last_fault_dump_picks_final_section(self):
        text = (
            "# header\n"
            "Current thread 0x01 (most recent call first):\n"
            '  File "a.py", line 1 in old\n'
            "\n"
            "Fatal Python error: Segmentation fault\n"
            "\n"
            "Current thread 0x01 (most recent call first):\n"
            '  File "a.py", line 2 in fresh\n'
        )
        dump = last_fault_dump(text)
        assert dump.startswith("Fatal Python error")
        assert "fresh" in dump and "old" not in dump
