"""Per-role node lifecycle in the master: chief/evaluator/worker
policies, critical-node semantics, and evaluator scheduling.

Parity target: the reference's per-role managers and critical-node
marking (dlrover/python/master/node/worker.py:32-150,
training_node.py:40-81) — recast as a RolePolicy table the JobManager
applies at registration instead of one manager class per role.
"""

import pytest

from dlrover_tpu.common.constants import (
    EVALUATOR_NODE_ID_BASE,
    JobExitReason,
    NodeAction,
    NodeStatus,
    NodeType,
    TrainingExceptionLevel,
)
from dlrover_tpu.master.job_manager import (
    JobManager,
    parse_critical_workers,
)


def _fail(jm, node_id, fatal=False):
    return jm.handle_failure_report(
        node_id,
        "boom",
        TrainingExceptionLevel.NODE_ERROR,
        restart_count=0,
        fatal=fatal,
    )


class TestRolePolicies:
    def test_chief_and_evaluator_register_critical(self):
        jm = JobManager()
        chief = jm.register_node(node_type=NodeType.CHIEF, node_id=0)
        ev = jm.register_node(node_type=NodeType.EVALUATOR, node_id=1)
        worker = jm.register_node(node_type=NodeType.WORKER, node_id=2)
        assert chief.critical and ev.critical
        assert not worker.critical

    def test_parse_critical_workers_specs(self):
        assert parse_critical_workers("") == {}
        assert parse_critical_workers("none") == {}
        assert parse_critical_workers("all") == {-1: None}
        assert parse_critical_workers("0:3,5:1") == {0: 3, 5: 1}
        assert parse_critical_workers("0:3,") == {0: 3}  # trailing comma

    @pytest.mark.parametrize("bad", ["0-3", "x:2", "-1:2", "0:-2"])
    def test_parse_critical_workers_rejects_malformed(self, bad):
        with pytest.raises(ValueError):
            parse_critical_workers(bad)

    def test_critical_worker_spec_applies(self):
        jm = JobManager(critical_workers="0:1")
        w0 = jm.register_node(node_type=NodeType.WORKER, node_id=0)
        w1 = jm.register_node(node_type=NodeType.WORKER, node_id=1)
        assert w0.critical and w0.max_relaunch_count == 1
        assert not w1.critical


class TestRoleRecovery:
    def test_evaluator_killed_is_relaunched(self):
        jm = JobManager(max_relaunch=2)
        jm.register_node(node_type=NodeType.EVALUATOR, node_id=0)
        action = _fail(jm, 0)
        assert action == NodeAction.RELAUNCH_NODE
        replacement = jm.get_node(0)
        assert replacement.status == NodeStatus.PENDING
        assert replacement.type == NodeType.EVALUATOR
        assert replacement.critical  # carried to the new incarnation
        assert not jm.job_failed()
        # The relaunched evaluator comes back and re-registers.
        back = jm.register_node(node_type=NodeType.EVALUATOR, node_id=0)
        assert back.status == NodeStatus.RUNNING

    def test_chief_loss_beyond_budget_fails_job(self):
        jm = JobManager(max_relaunch=1)
        jm.register_node(node_type=NodeType.CHIEF, node_id=0)
        assert _fail(jm, 0) == NodeAction.RELAUNCH_NODE
        assert not jm.job_failed()
        # Replacement registers, then dies again — budget exhausted.
        jm.register_node(node_type=NodeType.CHIEF, node_id=0)
        assert _fail(jm, 0) == NodeAction.STOP
        assert jm.job_failed()
        reason, detail = jm.job_failure
        assert reason == JobExitReason.CRITICAL_NODE_FAILED
        assert "chief" in detail

    def test_noncritical_worker_loss_keeps_job_alive(self):
        jm = JobManager(max_relaunch=0)
        jm.register_node(node_type=NodeType.WORKER, node_id=0)
        assert _fail(jm, 0) == NodeAction.STOP
        assert not jm.job_failed()  # elastic shrink, not job failure

    def test_critical_worker_loss_fails_job(self):
        jm = JobManager(critical_workers="all", max_relaunch=0)
        jm.register_node(node_type=NodeType.WORKER, node_id=0)
        assert _fail(jm, 0) == NodeAction.STOP
        assert jm.job_failed()

    def test_fatal_error_on_critical_node_fails_job_immediately(self):
        jm = JobManager()
        jm.register_node(node_type=NodeType.EVALUATOR, node_id=0)
        assert _fail(jm, 0, fatal=True) == NodeAction.STOP
        assert jm.job_failed()


class TestRoleQueriesAndScheduling:
    def test_is_chief_running(self):
        jm = JobManager()
        assert not jm.is_chief_running()
        jm.register_node(node_type=NodeType.CHIEF, node_id=0)
        assert jm.is_chief_running()
        jm.handle_node_succeeded(0)
        assert not jm.is_chief_running()

    def test_ensure_role_schedules_missing_evaluator(self):
        jm = JobManager()
        launched = jm.ensure_role(NodeType.EVALUATOR, 1)
        assert len(launched) == 1
        node = launched[0]
        assert node.type == NodeType.EVALUATOR
        assert node.status == NodeStatus.PENDING
        assert node.critical  # role policy applied at scheduling
        # Namespaced id: evaluator 0 never collides with worker 0, and
        # the arriving evaluator agent (which keys its RPCs by
        # evaluator_node_id(rank)) claims this exact node.
        assert node.id == EVALUATOR_NODE_ID_BASE
        # The platform launched it; its agent attaches under the id.
        attached = jm.register_node(
            node_type=NodeType.EVALUATOR, node_id=node.id
        )
        assert attached.status == NodeStatus.RUNNING
        # Idempotent: enough evaluators alive, nothing new launched.
        assert jm.ensure_role(NodeType.EVALUATOR, 1) == []
        # The launch went through the scaler as a ScalePlan.
        assert any(
            p.launch_nodes for p in jm.scaler.executed_plans
        )

    def test_retire_role_removes_alive_evaluators(self):
        jm = JobManager()
        jm.register_node(node_type=NodeType.EVALUATOR, node_id=0)
        jm.retire_role(NodeType.EVALUATOR)
        assert jm.get_node(0).status == NodeStatus.DELETED

    def test_scheduled_evaluator_never_claimed_does_not_fail_job(self):
        """A pre-scheduled evaluator the platform cannot launch times
        out PENDING and is abandoned — the job was healthy without it
        and must stay healthy (only a lost *replacement* of a
        previously-running critical node fails the job)."""
        jm = JobManager(pending_timeout=0.0)
        jm.register_node(node_type=NodeType.WORKER, node_id=0)
        jm.ensure_role(NodeType.EVALUATOR, 1)
        jm.check_nodes_once()
        ev = jm.get_node(EVALUATOR_NODE_ID_BASE)
        assert ev.status == NodeStatus.FAILED
        assert not jm.job_failed()

    def test_worker_and_evaluator_same_rank_are_distinct_nodes(self):
        jm = JobManager()
        w = jm.register_node(node_type=NodeType.WORKER, node_id=0)
        ev = jm.register_node(
            node_type=NodeType.EVALUATOR,
            node_id=EVALUATOR_NODE_ID_BASE,
        )
        assert w.id != ev.id
        assert w.type == NodeType.WORKER
        assert ev.type == NodeType.EVALUATOR

    def test_terminate_job_reclaims_fleet(self):
        jm = JobManager()
        jm.register_node(node_type=NodeType.WORKER, node_id=0)
        jm.register_node(node_type=NodeType.WORKER, node_id=1)
        jm.register_node(
            node_type=NodeType.EVALUATOR,
            node_id=EVALUATOR_NODE_ID_BASE,
        )
        jm.terminate_job()
        assert all(
            n.status == NodeStatus.DELETED for n in jm.list_nodes()
        )
        removed = [
            n.id
            for p in jm.scaler.executed_plans
            for n in p.remove_nodes
        ]
        assert sorted(removed) == [0, 1, EVALUATOR_NODE_ID_BASE]

    def test_completion_counts_chief_not_evaluator(self):
        jm = JobManager()
        jm.register_node(node_type=NodeType.WORKER, node_id=0)
        jm.register_node(node_type=NodeType.CHIEF, node_id=1)
        jm.register_node(node_type=NodeType.EVALUATOR, node_id=2)
        jm.handle_node_succeeded(0)
        assert not jm.all_workers_done()  # chief still running
        jm.handle_node_succeeded(1)
        assert jm.all_workers_done()  # evaluator does not gate
