"""Full-model pipelined GPT training vs the dense step: same math,
different schedule — trajectories must match (models/gpt_pipeline.py;
ref: PiPPy stage split with edge embed/head,
distributed_pippy_compiler.py).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.models import gpt
from dlrover_tpu.models.gpt_pipeline import (
    make_gpt_pipeline_step,
    shard_params_for_pipeline,
)
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.trainer.step import make_train_step, shard_batch

CFG = gpt.GPTConfig(
    vocab_size=64,
    block_size=16,
    n_layer=4,
    n_head=2,
    n_embd=32,
    dtype=jnp.float32,
    remat=False,
)


def _batches(n_steps, batch=8, seed=0):
    key = jax.random.PRNGKey(seed)
    out = []
    for i in range(n_steps):
        key, k = jax.random.split(key)
        tok = jax.random.randint(
            k, (batch, CFG.block_size), 0, CFG.vocab_size
        )
        out.append((tok, jnp.roll(tok, -1, axis=1)))
    return out


def _dense_trajectory(batches, lr=1e-2, return_state=False):
    mesh = build_mesh(MeshConfig(data=4), devices=jax.devices()[:4])
    opt = optax.adamw(lr)
    params = gpt.init_params(jax.random.PRNGKey(0), CFG)
    opt_state = opt.init(params)
    loss_fn = functools.partial(gpt.loss_fn, cfg=CFG)
    step = make_train_step(mesh, loss_fn, opt)
    losses = []
    for tok, tgt in batches:
        tok, tgt = shard_batch(mesh, tok, tgt)
        params, opt_state, m = step(params, opt_state, tok, tgt)
        losses.append(float(m["loss"]))
    if return_state:
        return losses, params, opt_state
    return losses


def _pipeline_trajectory(batches, mesh_cfg, v_chunks=1, lr=1e-2):
    mesh = build_mesh(mesh_cfg, devices=jax.devices()[:4])
    opt = optax.adamw(lr)
    params = shard_params_for_pipeline(
        mesh, gpt.init_params(jax.random.PRNGKey(0), CFG)
    )
    opt_state = opt.init(params)
    step = make_gpt_pipeline_step(
        mesh, CFG, opt, v_chunks=v_chunks
    )
    losses = []
    for tok, tgt in batches:
        params, opt_state, m = step(params, opt_state, tok, tgt)
        losses.append(float(m["loss"]))
    return losses


class TestGptPipelineParity:
    @pytest.mark.slow
    def test_1f1b_matches_dense_trajectory(self):
        batches = _batches(4)
        dense = _dense_trajectory(batches)
        piped = _pipeline_trajectory(
            batches, MeshConfig(data=2, pipe=2)
        )
        np.testing.assert_allclose(piped, dense, rtol=2e-3, atol=2e-4)

    def test_1f1b_actually_trains(self):
        # One FIXED batch repeated: the loss must drop (fresh random
        # tokens every step would not reliably decrease).
        batches = _batches(1) * 6
        piped = _pipeline_trajectory(
            batches, MeshConfig(data=2, pipe=2)
        )
        assert piped[-1] < piped[0] - 0.1

    @pytest.mark.slow
    def test_interleaved_chunks_match_dense(self):
        batches = _batches(3)
        dense = _dense_trajectory(batches)[:3]
        piped = _pipeline_trajectory(
            batches, MeshConfig(data=2, pipe=2), v_chunks=2
        )
        np.testing.assert_allclose(piped, dense, rtol=2e-3, atol=2e-4)

    @pytest.mark.slow
    def test_seq_sharded_pipeline_matches_dense(self):
        """seq_axis shards the token dimension inside the 1F1B
        schedule (pipeline_lm seq_axis; VERDICT r4 weak #5): with
        ring attention called directly in the manual stage body, the
        pipe x seq x data trajectory must match the dense one."""
        from dlrover_tpu.parallel.ring_attention import ring_attention

        batches = _batches(3)
        dense = _dense_trajectory(batches)[:3]
        mesh = build_mesh(
            MeshConfig(data=2, pipe=2, seq=2),
            devices=jax.devices()[:8],
        )
        opt = optax.adamw(1e-2)
        params = shard_params_for_pipeline(
            mesh, gpt.init_params(jax.random.PRNGKey(0), CFG)
        )
        opt_state = opt.init(params)
        step = make_gpt_pipeline_step(
            mesh, CFG, opt,
            attn_fn=functools.partial(
                ring_attention, axis_name="seq", causal=True
            ),
            seq_axis="seq",
        )
        losses = []
        for tok, tgt in batches:
            params, opt_state, m = step(params, opt_state, tok, tgt)
            losses.append(float(m["loss"]))
        np.testing.assert_allclose(losses, dense, rtol=2e-3, atol=2e-4)

    def test_seq_axis_requires_collective_attention(self):
        mesh = build_mesh(
            MeshConfig(data=2, pipe=2, seq=2),
            devices=jax.devices()[:8],
        )
        with pytest.raises(ValueError, match="collective attn_fn"):
            make_gpt_pipeline_step(
                mesh, CFG, optax.adamw(1e-2), seq_axis="seq"
            )

    def test_single_stage_fallback_matches_dense(self):
        batches = _batches(2)
        dense = _dense_trajectory(batches)[:2]
        piped = _pipeline_trajectory(batches, MeshConfig(data=4))
        np.testing.assert_allclose(piped, dense, rtol=2e-3, atol=2e-4)

    def test_auto_accelerate_executes_pipe_strategy(self):
        """With a pipeline_builder, a pipe>1 strategy is EXECUTABLE
        through auto_accelerate — the gap that previously excluded
        pipe candidates from the search."""
        from dlrover_tpu.accelerate import Strategy, auto_accelerate
        from dlrover_tpu.models.gpt_pipeline import GptPipelineBuilder

        init = functools.partial(gpt.init_params, cfg=CFG)
        loss = functools.partial(gpt.loss_fn, cfg=CFG)
        axes = gpt.param_logical_axes(CFG)
        s = Strategy(
            mesh_shape=(("data", 2), ("pipe", 2)),
            dtype="float32",
            micro_batch_size=4,
        )
        tok = jnp.zeros((2, CFG.block_size), jnp.int32)
        res = auto_accelerate(
            init, loss, axes, (tok, tok), strategy=s,
            learning_rate=1e-2,
            devices=jax.devices()[:4],
            pipeline_builder=GptPipelineBuilder(CFG),
        )
        params, opt_state = res.init_fn(jax.random.PRNGKey(0))
        tokb, tgtb = _batches(1, batch=8)[0]
        losses = []
        for _ in range(5):
            params, opt_state, m = res.step_fn(
                params, opt_state, tokb, tgtb
            )
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0] - 0.1

    @pytest.mark.slow
    def test_search_dry_runs_pipe_candidates_with_builder(self):
        """The search path end to end: with a pipeline_builder, pipe
        candidates are kept, BUILT, and measured alongside dense ones
        (previously they were excluded wholesale)."""
        from dlrover_tpu.accelerate import Strategy, auto_accelerate
        from dlrover_tpu.models.gpt_pipeline import GptPipelineBuilder

        init = functools.partial(gpt.init_params, cfg=CFG)
        loss = functools.partial(gpt.loss_fn, cfg=CFG)
        axes = gpt.param_logical_axes(CFG)
        cands = [
            Strategy(mesh_shape=(("data", 4),), micro_batch_size=4,
                     dtype="float32"),
            Strategy(mesh_shape=(("data", 2), ("pipe", 2)),
                     micro_batch_size=4, dtype="float32"),
        ]
        tok = jnp.zeros((2, CFG.block_size), jnp.int32)
        res = auto_accelerate(
            init, loss, axes, (tok, tok),
            devices=jax.devices()[:4],
            candidates=cands,
            hbm_bytes=1 << 30,
            activation_bytes_per_sample=1 << 10,
            pipeline_builder=GptPipelineBuilder(CFG),
        )
        ran = [e for e in res.search_log if "samples_per_sec" in e]
        assert len(ran) == 2, res.search_log  # BOTH were measured
        assert res.strategy in cands

    def test_pipe_without_builder_raises_on_explicit_strategy(self):
        from dlrover_tpu.accelerate import Strategy, auto_accelerate

        init = functools.partial(gpt.init_params, cfg=CFG)
        loss = functools.partial(gpt.loss_fn, cfg=CFG)
        axes = gpt.param_logical_axes(CFG)
        s = Strategy(
            mesh_shape=(("data", 2), ("pipe", 2)), dtype="float32",
        )
        tok = jnp.zeros((2, CFG.block_size), jnp.int32)
        with pytest.raises(ValueError, match="pipeline_builder"):
            auto_accelerate(
                init, loss, axes, (tok, tok), strategy=s,
                devices=jax.devices()[:4],
            )

    @pytest.mark.slow
    def test_dense_checkpoint_resumes_on_pipeline_mesh(self):
        """The elastic reshard story: params/opt_state stay in the
        model's NATIVE layout, so a flash checkpoint written by the
        dense step restores onto a pipeline mesh (and back) with the
        training trajectory unchanged — restarts may change the
        parallelism, never the math."""
        batches = _batches(6, seed=5)
        # full dense trajectory as the reference
        dense = _dense_trajectory(batches)

        # dense for 3 steps -> "checkpoint" (native trees) ->
        # pipeline mesh resumes steps 4-6
        _, params, opt_state = _dense_trajectory(
            batches[:3], return_state=True
        )
        opt = optax.adamw(1e-2)
        saved = jax.tree.map(np.asarray, (params, opt_state))

        mesh_p = build_mesh(
            MeshConfig(data=2, pipe=2), devices=jax.devices()[:4]
        )
        params_p = shard_params_for_pipeline(
            mesh_p, jax.tree.map(jnp.asarray, saved[0])
        )
        opt_state_p = jax.tree.map(jnp.asarray, saved[1])
        pipe_step = make_gpt_pipeline_step(mesh_p, CFG, opt)
        resumed = []
        for tok, tgt in batches[3:]:
            params_p, opt_state_p, m = pipe_step(
                params_p, opt_state_p, tok, tgt
            )
            resumed.append(float(m["loss"]))
        np.testing.assert_allclose(
            resumed, dense[3:], rtol=2e-3, atol=2e-4
        )

    def test_elastic_trainer_drives_pipeline_step(self):
        """Elastic pipelined training: the ElasticTrainer's fixed
        global batch + per-process assembly with a 1F1B step plugged
        in as step_fn (the schedule's microbatching takes over grad
        accumulation). Trajectory must match the dense ElasticTrainer
        on the same global batch."""
        import optax

        from dlrover_tpu.trainer.elastic_trainer import ElasticTrainer

        batches = _batches(4, batch=8, seed=11)

        mesh_d = build_mesh(
            MeshConfig(data=4), devices=jax.devices()[:4]
        )
        opt = optax.adamw(1e-2)
        dense_tr = ElasticTrainer(
            mesh_d,
            functools.partial(gpt.loss_fn, cfg=CFG),
            opt,
            global_batch_size=8,
            micro_batch_size=2,
        )
        params = gpt.init_params(jax.random.PRNGKey(0), CFG)
        opt_state = opt.init(params)
        dense_losses = []
        for tok, tgt in batches:
            params, opt_state, loss = dense_tr.train_step(
                params, opt_state, np.asarray(tok), np.asarray(tgt)
            )
            dense_losses.append(float(loss))

        mesh_p = build_mesh(
            MeshConfig(data=2, pipe=2), devices=jax.devices()[:4]
        )
        pipe_step = make_gpt_pipeline_step(mesh_p, CFG, opt, n_micro=4)
        pipe_tr = ElasticTrainer(
            mesh_p,
            None,
            opt,
            global_batch_size=8,
            micro_batch_size=4,
            step_fn=pipe_step,
        )
        params_p = shard_params_for_pipeline(
            mesh_p, gpt.init_params(jax.random.PRNGKey(0), CFG)
        )
        opt_state_p = opt.init(params_p)
        pipe_losses = []
        for tok, tgt in batches:
            params_p, opt_state_p, loss = pipe_tr.train_step(
                params_p, opt_state_p, np.asarray(tok),
                np.asarray(tgt),
            )
            pipe_losses.append(float(loss))
        np.testing.assert_allclose(
            pipe_losses, dense_losses, rtol=2e-3, atol=2e-4
        )
        assert pipe_tr.step_num == dense_tr.step_num == 4

    def test_elastic_trainer_requires_loss_or_step(self):
        import optax

        from dlrover_tpu.trainer.elastic_trainer import ElasticTrainer

        mesh = build_mesh(
            MeshConfig(data=4), devices=jax.devices()[:4]
        )
        with pytest.raises(ValueError, match="loss_fn"):
            ElasticTrainer(
                mesh, None, optax.adamw(1e-2),
                global_batch_size=8, micro_batch_size=2,
            )

    def test_layer_count_must_divide_stages(self):
        mesh = build_mesh(
            MeshConfig(data=1, pipe=4), devices=jax.devices()[:4]
        )
        bad = functools.partial(
            make_gpt_pipeline_step, mesh,
            gpt.GPTConfig(
                vocab_size=64, block_size=16, n_layer=6, n_head=2,
                n_embd=32, dtype=jnp.float32, remat=False,
            ),
            optax.adamw(1e-2),
        )
        with pytest.raises(ValueError, match="divide"):
            bad(v_chunks=4)
